"""Fleet routing benchmark: the reference's 4-arm strategy comparison.

Reproduces the reference's headline experiment shape
(/root/reference/benchmarking/37-capacity, BASELINE.md) at simulation scale:
an 8-pod vLLM-TPU fleet serving multi-turn conversations with large shared
system prompts. Everything in the control plane is REAL — engines run real
block managers (prefix caching, LRU eviction) emitting real msgpack KVEvents
through the real sharded event pool into the real index; routing calls the
real `Indexer.get_pod_scores` read path (tokenization included). Only device
compute is modeled: TTFT = queue wait + alpha * uncached_prefill_tokens +
beta, with pods busy for prefill + output decode.

Routing arms, mirroring the reference's comparison table
(/root/reference/benchmarking/37-capacity/README.md:230-253):
- "precise":   cache_tracking scoring — the product. Real index fed by real
               engine events; ties broken least-loaded.
- "estimated": scheduler-side estimation — an affinity table of which pod
               each block-key chain was ROUTED to before, never corrected
               by engine events, so it drifts under eviction (the
               reference's prefix-cache-scorer default/estimate mode).
- "load":      least pending work (pod_free_at), cache-oblivious.
- "random":    uniform random.
- "round_robin": strict rotation — kept as the historical headline
               baseline (BASELINE.json's >=2x TTFT target).

Target (BASELINE.json): >=80% prefix-cache hit rate and >=2x TTFT speedup vs
round-robin on an 8-replica fleet; the reference's own table shows precise
~3x load/random on TTFT — the same ordering must hold here.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

`--workload sharegpt` swaps the synthetic conversations for the
trace-driven ShareGPT replay (workloads/ subsystem; docs/workloads.md):
distribution-faithful lengths/turns, open-loop arrivals, JSONL
record/replay via --record/--trace. It validates the trace against the
committed tables, runs all five arms over it, and writes
benchmarking/FLEET_BENCH_SHAREGPT.json — the synthetic default and its
artifact series stay untouched for round-over-round comparability.

`--faults` replays the chat workload under a scripted FaultPlan
(fleethealth/: pod crash/restart, event-stream stall, batch
drop/duplication/reordering) and writes
benchmarking/FLEET_BENCH_FAULTS.json: stale-routing rate before vs after
detection, detection latency vs the configured windows, and hit-rate
retention vs the no-fault run (whose numbers must stay bit-identical to
FLEET_BENCH.json with the subsystem enabled).
"""

from __future__ import annotations

import collections
import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def _ensure_native() -> None:
    """Build the C hash core if missing (pure-Python fallback works, but the
    bench should measure the shipped fast path)."""
    import glob
    import subprocess

    if glob.glob(os.path.join(REPO, "llm_d_kv_cache_manager_tpu", "_kvtpu_native*.so")):
        return
    try:
        subprocess.run(
            [sys.executable, "setup.py", "build_ext"],
            cwd=os.path.join(REPO, "native"),
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception as e:  # noqa: BLE001 - fall back to pure Python
        print(f"native build skipped: {e}", file=sys.stderr)


_ensure_native()

from llm_d_kv_cache_manager_tpu.engine.block_manager import OutOfPagesError
from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
    ScoreRequest,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig, Message
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

MODEL = "test-model"
FIXTURE = os.path.join(REPO, "tests", "fixtures", "test-model", "tokenizer.json")

# Fleet / engine shape.
N_PODS = 8
PAGE_SIZE = 16
PAGES_PER_POD = 2048  # 32k tokens of KV per pod -> eviction pressure is real

# Workload: groups share a system prompt; each user runs a multi-turn chat.
N_GROUPS = 12
USERS_PER_GROUP = 5
TURNS_PER_USER = 5
SYSTEM_PROMPT_WORDS = 900  # ~8x question size, like the 8k-shared-prefix runs
QUESTION_WORDS = 110
RESPONSE_WORDS = 120
QPS = 20.0

# TTFT model (v5e-class serving constants). Pods continuously batch decode,
# so the serialized per-pod resource is prefill compute; queue wait is time
# until the pod's prefill slot frees up.
ALPHA_PREFILL_S_PER_TOKEN = 0.00035
BETA_OVERHEAD_S = 0.02
# Decode holds its KV pages for the response duration (reference ITL mean
# 0.020s, 37-capacity/README.md:235-238). Concurrent decodes are what put
# real pressure on the page pool: when an admission cannot allocate, the
# engine preempts its youngest running sequence (vLLM recompute-preemption),
# whose pages get reclaimed — emitting the BlockRemoved events only PRECISE
# tracking sees. This models the 73-capacity regime where estimated
# scheduling collapses (TTFT p90 31.08s vs 0.54s precise,
# /root/reference/benchmarking/73-capacity/README.md:238-246): routing
# history keeps pointing at caches that pressure already destroyed.
ITL_S_PER_TOKEN = 0.02
# Two-tier restore costs: re-landing a KV block from the host staging store
# (DMA) or a peer pod (DCN) is bandwidth-bound vs 350us/token to recompute
# on the MXU. The defaults below are assumptions; when the device bench has
# measured the data plane (benchmarking/DEVICE_BENCH.json "data_plane"
# section: _DevicePageCodec insert + connector fetch+insert per token,
# VERDICT r2 #7), the measured values replace them.
GAMMA_HOST_RESTORE_S_PER_TOKEN = 1e-5
DELTA_DCN_ONBOARD_S_PER_TOKEN = 2e-5
# Per-constant provenance: a data_plane section may carry only one of the
# two measurements (the connector legs skip when libkvtransfer.so isn't
# built) and each label must track its own constant.
_GAMMA_SOURCE = "assumed"
_DELTA_SOURCE = "assumed"


def _load_measured_data_plane() -> None:
    global GAMMA_HOST_RESTORE_S_PER_TOKEN, DELTA_DCN_ONBOARD_S_PER_TOKEN
    global _GAMMA_SOURCE, _DELTA_SOURCE
    path = os.path.join(REPO, "benchmarking", "DEVICE_BENCH.json")
    try:
        with open(path) as f:
            dp = json.load(f).get("data_plane", {})
    except (OSError, ValueError):
        return
    # Prefer the batched-leg rates: the serving path restores/onboards
    # chains through one insert_many dispatch (engine/tiering.load_chain),
    # so the per-page single-dispatch rates overstate its cost ~2x.
    if "host_restore_batch_s_per_token" in dp:
        GAMMA_HOST_RESTORE_S_PER_TOKEN = dp["host_restore_batch_s_per_token"]
        _GAMMA_SOURCE = "measured (DEVICE_BENCH.json data_plane, batched)"
    elif "host_restore_s_per_token" in dp:
        GAMMA_HOST_RESTORE_S_PER_TOKEN = dp["host_restore_s_per_token"]
        _GAMMA_SOURCE = "measured (DEVICE_BENCH.json data_plane)"
    if "dcn_onboard_chain_s_per_token" in dp:
        DELTA_DCN_ONBOARD_S_PER_TOKEN = dp["dcn_onboard_chain_s_per_token"]
        _DELTA_SOURCE = "measured (DEVICE_BENCH.json data_plane, batched)"
    elif "dcn_onboard_s_per_token" in dp:
        DELTA_DCN_ONBOARD_S_PER_TOKEN = dp["dcn_onboard_s_per_token"]
        _DELTA_SOURCE = "measured (DEVICE_BENCH.json data_plane)"


_load_measured_data_plane()

# Two-tier scenario shape: small HBM pools -> heavy eviction pressure, so
# the host tier's value (restore instead of recompute) is visible.
TWO_TIER_PAGES_PER_POD = 512
TWO_TIER_HOST_CAPACITY = 4096


def _sim_cost_model(alpha: float, gamma: float, delta: float):
    """The gate the sim's pods apply, built from the SAME constants the
    simulated clock charges — the pods' economics and the measurement's
    physics can never disagree. On the tunneled rig the measured gamma
    (812us/token) exceeds alpha (350us/token), so the gate refuses
    transfers for the benched dense model — which is exactly what round 3
    measured the hard way (rr_data_plane_speedup 0.252 with the gate off,
    VERDICT r3 weak #3)."""
    from llm_d_kv_cache_manager_tpu.engine.costs import TransferCostModel

    return TransferCostModel(
        recompute_s=alpha, staged_restore_s=gamma, onboard_s=delta,
        insert_s=gamma, source="sim-physics (measured-seeded)",
    )

from llm_d_kv_cache_manager_tpu.workloads.synthetic import (
    shared_prefix_conversations,
    text as _text,
)


def build_workload(seed: int = 42, qps: float = QPS):
    """Returns (requests, conversations, rng): time-ordered (arrival, conv_id)
    pairs plus per-conversation history seeded with group system prompts."""
    rng = random.Random(seed)
    conversations = shared_prefix_conversations(
        rng, N_GROUPS, USERS_PER_GROUP, SYSTEM_PROMPT_WORDS
    )
    turns = []
    for conv_id in conversations:
        for t in range(TURNS_PER_USER):
            turns.append((conv_id, t, None, None))
    rng.shuffle(turns)

    arrival = 0.0
    requests = []
    for conv_id, _t, _g, _u in turns:
        arrival += rng.expovariate(qps)
        requests.append((arrival, conv_id))
    return requests, conversations, rng


# Capacity-regime workload (the reference's 73-capacity shape,
# /root/reference/benchmarking/73-capacity/README.md:8-23): SINGLE-TURN
# requests drawn uniformly from many groups sharing long system prompts,
# with the groups' aggregate prefix footprint near the fleet's KV capacity
# — so LRU/preemption churn constantly rotates which prefixes are
# resident. Multi-turn chat makes routing history self-fulfilling (the
# conversation re-warms whatever pod it lands on); single-turn fan-in is
# where an estimator that never sees engine evictions goes stale.
CAPACITY_GROUPS = 48
CAPACITY_PAGES_PER_POD = 512
CAPACITY_REQUESTS = 300


def build_capacity_workload(seed: int = 42, qps: float = QPS):
    """(requests, group_prompts, rng): time-ordered (arrival, group_id)
    single-turn draws over CAPACITY_GROUPS shared-prefix groups."""
    rng = random.Random(seed)
    groups = shared_prefix_conversations(rng, CAPACITY_GROUPS, 1, SYSTEM_PROMPT_WORDS)
    group_ids = list(groups)
    arrival = 0.0
    requests = []
    for _ in range(CAPACITY_REQUESTS):
        arrival += rng.expovariate(qps)
        requests.append((arrival, rng.choice(group_ids)))
    return requests, groups, rng


class FleetSim:
    def __init__(
        self,
        strategy: str,
        seed: int = 42,
        pages_per_pod: int = PAGES_PER_POD,
        host_tier: bool = False,
        host_capacity: int = TWO_TIER_HOST_CAPACITY,
        alpha: float = ALPHA_PREFILL_S_PER_TOKEN,
        gamma: float = GAMMA_HOST_RESTORE_S_PER_TOKEN,
        delta: float = DELTA_DCN_ONBOARD_S_PER_TOKEN,
        gated: bool = True,
        health_config=None,
        fault_plan=None,
        snapshot_restore: bool = False,
        snapshot_path=None,
        snapshot_every_s: float = 0.0,
        tail_journal_len: int = 0,
        placement=None,
        prediction=None,
        cluster_replicas: int = 1,
        batch_window: int = 0,
        n_pods: int = N_PODS,
        routing_policy=None,
        membership=None,
        verify_cluster_scores: bool = False,
        transfer_faults=None,
        antientropy=None,
        measure_fetch_misses: bool = False,
    ):
        self.strategy = strategy
        # Fleet size is a RUNTIME quantity now (--autoscale grows it with
        # add_pod); N_PODS stays the historical default so every committed
        # arm is untouched.
        self.n_pods = n_pods
        # Router batching (--batch-window; the score_many read path):
        # serve_batch() scores a whole arrival window in one bulk call
        # and queues the per-item score maps here; route() consumes them
        # in arrival order instead of making a per-request scoring call.
        # Empty deque (the default path) leaves route() untouched.
        self.batch_window = batch_window
        self._prescored = collections.deque()
        self.host_tier = host_tier
        self.alpha = alpha
        self.gamma = gamma
        self.delta = delta
        self.gated = gated
        self.pages_per_pod = pages_per_pod
        self.host_capacity = host_capacity
        # When set, every route() call defers to this (phase-scripted
        # scenarios like the scale-out warm-up leg).
        self.route_override = None
        # Simulated wall clock (advanced by serve()); the fleet-health
        # tracker and the fault injector both read it, so detection
        # latency and fault windows are deterministic sim-time quantities.
        self.now = 0.0
        self.health = None
        if health_config is not None:
            from llm_d_kv_cache_manager_tpu.fleethealth import FleetHealthTracker

            self.health = FleetHealthTracker(
                health_config, clock=lambda: self.now
            )
        self.injector = None
        if fault_plan is not None:
            from llm_d_kv_cache_manager_tpu.fleethealth import FaultInjector

            self.injector = FaultInjector(fault_plan, clock=lambda: self.now)
        self.fault_plan = fault_plan
        # Load-aware routing policy (--autoscale; kvcache/routing.py):
        # the sim's own bookkeeping IS the pod-load reporter — pod_free_at
        # is the committed busy horizon, pod_active the inflight decodes —
        # reported to a sim-clocked PodLoadTracker before every routing
        # decision; preemptions feed the decayed pressure signal both
        # directly and through the BlockRemoved volume the event pool
        # observes. None (the default) leaves the read path byte-for-byte.
        self.load_tracker = None
        self.routing_policy = None
        if routing_policy is not None:
            from llm_d_kv_cache_manager_tpu.fleethealth import PodLoadTracker
            from llm_d_kv_cache_manager_tpu.kvcache.routing import (
                RoutingPolicy,
                RoutingPolicyConfig,
            )

            policy_cfg = routing_policy if isinstance(
                routing_policy, RoutingPolicyConfig
            ) else RoutingPolicyConfig(**routing_policy)
            self.load_tracker = PodLoadTracker(clock=lambda: self.now)
            self.routing_policy = RoutingPolicy(
                policy_cfg, load_tracker=self.load_tracker
            )
        # The sim's router uses the policy's `select` form (it knows the
        # candidate fleet); the indexer-side `adjust` seam stays None here
        # so load is blended exactly once. The service wiring
        # (api/http_service.py) attaches `adjust` instead — the score-map
        # surface is all an API response can carry.
        self.indexer = Indexer(
            config=IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=PAGE_SIZE),
            ),
            tokenization_pool=TokenizationPool(
                TokenizersPoolConfig(workers=2, local_tokenizer_files={MODEL: FIXTURE}),
            ),
            fleet_health=self.health,
        )
        self.indexer.run()
        self.event_pool = EventPool(
            EventPoolConfig(concurrency=2),
            self.indexer.kv_block_index,
            self.indexer.token_processor,
            health_tracker=self.health,
            load_tracker=self.load_tracker,
        )
        self.event_pool.start(with_subscriber=False)

        # Per-pod publisher sequence counters (the wire seq the tracker's
        # gap detection watches). A restarted pod's publisher restarts at 0.
        import itertools as _it

        self._it = _it
        self._seq = {f"pod-{i}": _it.count() for i in range(self.n_pods)}
        self._crashed = set()
        # Indexer (control-plane) lifecycle: --replication. While the
        # index service is down nothing digests events and scoring calls
        # go unanswered (routing falls back least-loaded). The tail
        # journal is the replay source a real deployment retains at the
        # delivery seam (bounded ring); _applied_seq mirrors the
        # per-(pod, topic) watermarks fleethealth tracks in the service
        # wiring, captured here by the sim's own sink.
        self._indexer_down = False
        self._indexer_restarted = False
        self.snapshot_restore = snapshot_restore
        self.snapshot_path = snapshot_path
        self.snapshot_every_s = snapshot_every_s
        self._last_snapshot_at = None
        self.tail_journal = (
            collections.deque(maxlen=tail_journal_len)
            if tail_journal_len else None
        )
        self._applied_seq = {}
        self.indexer_down_requests = 0
        self.scores_empty_after_restart = 0
        self.replication_stats = {}
        # (sim_time, pod_idx) of every routing decision that picked a
        # crashed pod — phantom-placement routing the subsystem exists to
        # stop. The router's retry lands the request on a live pod.
        self.stale_routes = []
        # sim_times where GetPodScores OFFERED a crashed pod at all (it
        # scored, whether or not it won the argmax): the raw staleness
        # exposure. A conversation takes the phantom route at most once
        # (the retried serve re-homes its prefix), but the index keeps
        # offering the dead pod until it is purged — or, without the
        # subsystem, forever.
        self.phantom_scores = []

        # Replicated control plane (--cluster-replicas; cluster/): the
        # precise arm scores through a ClusterScorer scatter-gather over N
        # partition-gated replicas instead of the monolithic indexer. Each
        # replica owns the event streams of the pods FNV-striped to it
        # (every published message is offered to every replica pool; the
        # ownership gate drops foreign streams), so the merged answer is
        # bit-identical to the monolithic run on full answers.
        self.cluster_scorer = None
        self.replica_pools = []
        self.replica_indexers = []
        self.partition_table = None
        # (cluster ∘ membership) composition: with a membership service
        # the static hash partitioner is replaced by a shared
        # PartitionTable — same FNV default, but ownership is LIVE state
        # the two-phase handoff can move — and every request's merged
        # cluster answer can be verified against the monolithic indexer
        # (verify_cluster_scores), which digests every stream in this sim:
        # a mismatch on a reassigned pod IS a stale-partition score.
        self.verify_cluster_scores = verify_cluster_scores
        self.stale_partition_scores = 0
        self.cluster_verified_requests = 0
        if cluster_replicas > 1:
            from llm_d_kv_cache_manager_tpu.cluster import (
                ClusterConfig,
                ClusterScorer,
                LocalReplicaTransport,
                PartitionTable,
                ReplicaPartitioner,
            )

            if membership is not None:
                self.partition_table = PartitionTable(cluster_replicas)
            transports = []
            for rid in range(cluster_replicas):
                gate = (
                    self.partition_table.gate(rid)
                    if self.partition_table is not None
                    else ReplicaPartitioner(
                        cluster_replicas, replica_id=rid
                    ).accepts
                )
                ridx = Indexer(
                    config=IndexerConfig(
                        token_processor_config=TokenProcessorConfig(
                            block_size=PAGE_SIZE
                        ),
                    ),
                    # Share the main tokenization pool (already running):
                    # replicas differ only in which event streams they
                    # digest, never in derivation.
                    tokenization_pool=self.indexer.tokenizers_pool,
                )
                rpool = EventPool(
                    EventPoolConfig(concurrency=2),
                    ridx.kv_block_index,
                    ridx.token_processor,
                    message_filter=gate,
                )
                rpool.start(with_subscriber=False)
                self.replica_indexers.append(ridx)
                self.replica_pools.append(rpool)
                transports.append(LocalReplicaTransport(ridx))
            self.cluster_scorer = ClusterScorer(
                transports,
                partitioner=(
                    self.partition_table
                    if self.partition_table is not None
                    else ReplicaPartitioner(cluster_replicas)
                ),
                config=ClusterConfig(num_replicas=cluster_replicas),
            )

        # Predictive placement (--placement; placement/): the popularity
        # tracker rides the read path, the replicator ticks under the sim
        # clock, and replication jobs flow through the real RoutePrefetcher
        # into prefetch_hashes + warm_chain on the target pods.
        self.popularity = None
        self.replicator = None
        self.route_prefetcher = None
        self.replicated_blocks = 0
        self.replication_charged_s = 0.0
        if placement is not None:
            from llm_d_kv_cache_manager_tpu.kv_connectors.prefetch import (
                RoutePrefetcher,
            )
            from llm_d_kv_cache_manager_tpu.placement import (
                ChainPopularityTracker,
                HotPrefixReplicator,
                PopularityConfig,
                ReplicationConfig,
            )

            rep_cfg = placement if isinstance(
                placement, ReplicationConfig
            ) else ReplicationConfig(**placement)
            self.popularity = ChainPopularityTracker(
                PopularityConfig(
                    half_life_s=PLACEMENT_HALF_LIFE_S,
                    max_prefix_blocks=rep_cfg.max_prefix_blocks,
                ),
                clock=lambda: self.now,
            )
            self.indexer.popularity = self.popularity
            self.route_prefetcher = RoutePrefetcher(
                self._replication_prefetch,
                queue_bound=PLACEMENT_QUEUE_BOUND,
            )
            self.replicator = HotPrefixReplicator(
                self.popularity,
                submit_fn=lambda pod, hashes, chain: (
                    self.route_prefetcher.submit(
                        pod, hashes, source="replication"
                    )
                ),
                pods_fn=lambda: [f"pod-{i}" for i in self._alive_pods()],
                config=rep_cfg,
                fleet_health=self.health,
                index=self.indexer.kv_block_index,
                clock=lambda: self.now,
            )

        # Anticipatory prefetch (--anticipate; prediction/): the session
        # table rides the read path's observation seam, the scheduler
        # ticks under the sim clock between requests, and prefetch jobs
        # flow through a bounded RoutePrefetcher (source="prediction")
        # into prefetch_hashes + warm_chain on the pod the ROUTER would
        # pick — resolved through Indexer.score_hashes with the sim's own
        # tie-break, so predictions and routing can never disagree.
        self.session_table = None
        self.prefetch_scheduler = None
        self.prediction_prefetcher = None
        self.predicted_landed_blocks = 0
        self.prediction_charged_s = 0.0
        # Optional audit seam (the anticipate bench): called after routing
        # and tokenization, BEFORE admission — the only moment "was the
        # prefix resident before arrival?" is answerable.
        self.pre_admit_hook = None
        if prediction is not None:
            from llm_d_kv_cache_manager_tpu.kv_connectors.prefetch import (
                RoutePrefetcher,
            )
            from llm_d_kv_cache_manager_tpu.prediction import (
                PredictionConfig,
                PrefetchScheduler,
                SchedulerConfig,
                SessionTable,
            )

            pred_kwargs = dict(prediction) if isinstance(
                prediction, dict
            ) else {}
            sched_kwargs = {
                k: pred_kwargs.pop(k)
                for k in (
                    "max_jobs_per_tick", "session_cooldown_s", "start_frac",
                )
                if k in pred_kwargs
            }
            self.session_table = SessionTable(
                PredictionConfig(**pred_kwargs), clock=lambda: self.now
            )
            self.indexer.prediction = self.session_table
            self.prediction_prefetcher = RoutePrefetcher(
                self._prediction_prefetch,
                queue_bound=PREDICTION_QUEUE_BOUND,
            )
            self.prefetch_scheduler = PrefetchScheduler(
                self.session_table,
                score_fn=self.indexer.score_hashes,
                submit_fn=lambda pod, hashes: (
                    self.prediction_prefetcher.submit(
                        pod, hashes, source="prediction"
                    )
                ),
                config=SchedulerConfig(**sched_kwargs),
                select_fn=self._prediction_select,
                clock=lambda: self.now,
            )

        # Elastic fleet membership (--autoscale; cluster/membership.py):
        # pods join mid-run (warm-before-serve through the data plane /
        # idle-compute warm-up) and leave (drain + quarantine). The
        # membership popularity tracker is the warm source: route
        # observation on the live read path keeps a top-K hot-chain table
        # the joining pod replays before it takes traffic.
        self.membership = None
        self.mem_popularity = None
        self.warm_stats = {"jobs": 0, "blocks_landed": 0,
                           "tokens_recomputed": 0, "charged_s": 0.0}
        if membership is not None:
            from llm_d_kv_cache_manager_tpu.cluster import (
                FleetMembership,
                MembershipConfig,
                ReplicaBinding,
            )
            from llm_d_kv_cache_manager_tpu.placement import (
                ChainPopularityTracker,
                PopularityConfig,
            )

            mem_cfg = dict(membership) if isinstance(membership, dict) else {}
            if self.popularity is None:
                self.mem_popularity = ChainPopularityTracker(
                    PopularityConfig(
                        half_life_s=float(
                            mem_cfg.get("popularity_half_life_s", 60.0)
                        ),
                        top_k=int(mem_cfg.get("warm_top_k", 8)) * 4,
                        max_prefix_blocks=int(
                            mem_cfg.get("max_prefix_blocks", 192)
                        ),
                    ),
                    clock=lambda: self.now,
                )
                self.indexer.popularity = self.mem_popularity
            else:
                self.mem_popularity = self.popularity
            bindings = [
                ReplicaBinding(
                    replica_id=rid,
                    event_pool=rpool,
                    index=ridx.kv_block_index,
                )
                for rid, (rpool, ridx) in enumerate(
                    zip(self.replica_pools, self.replica_indexers)
                )
            ]
            self.membership = FleetMembership(
                MembershipConfig(
                    warm_top_k=int(mem_cfg.get("warm_top_k", 8)),
                    warm_hotness_threshold=float(
                        mem_cfg.get("warm_hotness", 0.0)
                    ),
                ),
                table=self.partition_table,
                replicas=bindings,
                fleet_health=self.health,
                load_tracker=self.load_tracker,
                popularity=self.mem_popularity,
                warm_submit=self._membership_warm,
                watermark_fn=self._pod_watermark,
                journal_fn=(
                    (lambda: list(self.tail_journal))
                    if self.tail_journal is not None else None
                ),
                clock=lambda: self.now,
            )
            self.membership.bootstrap(
                [f"pod-{i}" for i in range(self.n_pods)]
            )

        self.pods = []
        for i in range(self.n_pods):
            self.pods.append(self._make_pod(i))
        self._addrs = None
        if host_tier:
            from llm_d_kv_cache_manager_tpu.engine.tiering import (
                IndexBackedPeerResolver,
            )

            # ONE shared address map: add_pod mutates it in place, so
            # every existing pod's resolver immediately sees new peers.
            addrs = {
                f"pod-{i}": pod.transfer_address
                for i, pod in enumerate(self.pods)
            }
            self._addrs = addrs
            for i, pod in enumerate(self.pods):
                pod.set_peer_resolver(IndexBackedPeerResolver(
                    self.indexer.kv_block_index, MODEL, addrs, f"pod-{i}",
                ))
        # Transfer-plane chaos (--chaos; kv_connectors/faults.py): every
        # pod's pooled TransferClient is re-clocked onto the sim clock and
        # wrapped in a FaultyTransport applying the per-peer plan
        # (corrupt / stall / blackhole / flap). Synthetic fetch latencies
        # (the timeout ladders a real flaky peer would cost) accumulate in
        # the wrappers and are drained into each request's prefill clock
        # by serve() — so breaker-capped tail latency is a sim-time
        # quantity, deterministic and replayable.
        self.faulty = {}
        self.transfer_fault_plan = None
        self.breaker_transitions = []  # (sim_t, peer, old, new)
        if transfer_faults is not None:
            from llm_d_kv_cache_manager_tpu.kv_connectors import faults as tf

            assert host_tier, "--chaos needs the transfer plane (host_tier)"
            cfg = dict(transfer_faults)
            pod_faults = cfg.get("pods", {})
            plan = tf.TransferFaultPlan(
                seed=int(cfg.get("seed", seed)),
                peers={
                    self._addrs[pod_id]: f for pod_id, f in pod_faults.items()
                },
            )
            self.transfer_fault_plan = plan
            verify = bool(cfg.get("verify_integrity", True))
            breaker = cfg.get("breaker")  # None -> breakers disabled

            def make_on_transition(observer: str):
                # Each pod's client keeps its OWN per-peer breakers (a
                # client-side failure memory); the observer identity makes
                # the fleet's transition log readable.
                def on_transition(peer, old, new):
                    self.breaker_transitions.append(
                        (self.now, observer, peer, old, new)
                    )
                    if self.health is not None:
                        self.health.observe_transfer_breaker(peer, old, new)

                return on_transition

            for i, pod in enumerate(self.pods):
                client = pod.connector.client
                client.clock = lambda: self.now
                client.on_breaker_transition = make_on_transition(f"pod-{i}")
                # Short, sim-scaled timeout ladder: what one fetch to a
                # dark peer costs before the client gives up.
                client.config.io_timeout_ms = int(
                    cfg.get("io_timeout_ms", 1000)
                )
                client.config.connect_timeout_ms = int(
                    cfg.get("connect_timeout_ms", 500)
                )
                client.config.retries = int(cfg.get("retries", 0))
                if breaker:
                    client.config.breaker_failure_threshold = int(
                        breaker.get("failure_threshold", 3)
                    )
                    client.config.breaker_cooldown_s = float(
                        breaker.get("cooldown_s", 4.0)
                    )
                else:
                    client.config.breaker_failure_threshold = 0  # disabled
                wrapper = tf.FaultyTransport(
                    client, plan, clock=lambda: self.now,
                    self_addr=self._addrs[f"pod-{i}"],
                    verify_integrity=verify,
                )
                pod.connector.client = wrapper
                self.faulty[i] = wrapper
        # Index anti-entropy (--divergence; antientropy/): the trust
        # tracker rides the indexer's score-filter seam and the event
        # pool's orphan probe; the residency auditor ticks under the sim
        # clock between requests (challenging the REAL pods' block
        # managers / host stores through resident_block_digest); on
        # two-tier fleets the fetch-miss feedback + resolver negative
        # caches ride every pod's TransferClient. None (the default)
        # leaves every seam untouched — the committed arms are
        # byte-identical. `measure_fetch_misses` wires the counting
        # callback WITHOUT any repair (the control arm's honest
        # wasted-fetch meter).
        self.antientropy = None
        self.auditor = None
        self.fetch_feedback = None
        self.silent_wipes = []  # (sim_t, pod_idx)
        self._next_wipe = {}
        # (sim_t, observer_pod_idx, peer_pod_id, n_missing): every
        # explicit per-block "missing" answer a fetch got from a PEER —
        # the wasted-fetch evidence stream, recorded in measurement and
        # reconciliation arms alike.
        self.fetch_miss_log = []
        if antientropy is not None:
            from llm_d_kv_cache_manager_tpu.antientropy import (
                AntiEntropyConfig,
                AntiEntropyTracker,
                AuditorConfig,
                FetchMissFeedback,
                ResidencyAuditor,
            )

            ae_cfg = dict(antientropy)
            self.antientropy = AntiEntropyTracker(
                AntiEntropyConfig(
                    accuracy_alpha=float(ae_cfg.get("accuracy_alpha", 0.3)),
                    distrust_threshold=float(
                        ae_cfg.get("distrust_threshold", 0.9)
                    ),
                    min_factor=float(ae_cfg.get("min_factor", 0.25)),
                ),
                clock=lambda: self.now,
            )
            self.indexer.antientropy = self.antientropy
            self.event_pool.divergence = self.antientropy

            def digest_fn(pod_identifier, device_hashes, host_hashes,
                          max_extra):
                try:
                    i = int(pod_identifier.split("@")[0].split("-")[1])
                except (IndexError, ValueError):
                    return None
                if i in self._crashed or i >= len(self.pods):
                    return None
                return self.pods[i].resident_block_digest(
                    device_hashes, host_hashes, max_extra
                )

            self.auditor = ResidencyAuditor(
                self.indexer.kv_block_index,
                MODEL,
                digest_fn,
                tracker=self.antientropy,
                config=AuditorConfig(
                    interval_s=float(ae_cfg.get("audit_interval_s", 2.0)),
                    sample_per_pod=int(ae_cfg.get("audit_sample", 24)),
                    readmit_sample=int(ae_cfg.get("readmit_sample", 32)),
                    seed=int(ae_cfg.get("seed", seed)),
                ),
                clock=lambda: self.now,
            )
            if self.host_tier:
                self.fetch_feedback = FetchMissFeedback(
                    self.indexer.kv_block_index,
                    MODEL,
                    self._pod_for_addr,
                    tracker=self.antientropy,
                )
                for i, pod in enumerate(self.pods):
                    resolver = pod.tier_store.peer_resolver
                    resolver.clock = lambda: self.now
                    resolver.negative_ttl_s = float(
                        ae_cfg.get("negative_ttl_s", 3.0)
                    )
                    pod.connector.client.on_fetch_misses = (
                        self._make_fetch_miss_cb(i)
                    )
        elif measure_fetch_misses and self.host_tier:
            for i, pod in enumerate(self.pods):
                pod.connector.client.on_fetch_misses = (
                    self._make_fetch_miss_cb(i)
                )
        self.pod_free_at = [0.0] * self.n_pods
        self.rr_counter = 0
        self.last_pod_idx = 0
        self.route_rng = random.Random(1234)  # "random" arm; workload rng untouched
        # "estimated" arm state: block-key -> pod the chain was last ROUTED
        # to. Never sees engine events (eviction silently invalidates it),
        # and is LRU-bounded to the fleet's nominal capacity — the
        # estimator can size its table but cannot know the engines' real
        # eviction order (reference: prefix-cache-scorer estimate mode's
        # bounded LRU).
        from collections import OrderedDict

        self.affinity = OrderedDict()
        self.affinity_cap = self.n_pods * pages_per_pod
        self.read_latencies = []
        self.hit_tokens = 0
        self.total_tokens = 0
        self.restored_blocks = 0
        self.onboarded_blocks = 0
        # Per-pod running decodes: (decode_finish_time, state, n_tokens).
        # Their pages stay referenced until release, so admission pressure
        # and preemption are real block-manager dynamics, not bookkeeping.
        self.pod_active = [[] for _ in range(self.n_pods)]
        self.preemptions = 0

    def _make_pod(self, i: int):
        pod_id = f"pod-{i}"
        return EnginePod(
            EnginePodConfig(
                pod_id=pod_id,
                model_name=MODEL,
                n_pages=self.pages_per_pod,
                page_size=PAGE_SIZE,
                max_pages_per_seq=4096,
                device_tier="hbm",
                enable_host_tier=self.host_tier,
                host_capacity_blocks=self.host_capacity,
                # Accounting pods gate with the sim's own physics (the
                # clock charges alpha/gamma/delta; the gate compares
                # the same numbers). gated=False reproduces the
                # ungated round-3 behavior for comparison arms.
                transfer_cost_model=(
                    _sim_cost_model(self.alpha, self.gamma, self.delta)
                    if (self.host_tier and self.gated) else None
                ),
            ),
            event_sink=self._sink_for(pod_id),
        )

    def _sink_for(self, pod_id: str):
        def deliver(msg):
            # Journal BEFORE the indexer-down gate: published events exist
            # whether or not the index service is up to hear them — that
            # persistence is exactly what the seq-tail replay consumes.
            if self.tail_journal is not None:
                self.tail_journal.append(msg)
            if self._indexer_down:
                return  # index service dead: nothing digests
            self._applied_seq[(msg.pod_identifier, msg.topic)] = msg.seq
            self.event_pool.add_task(msg)
            for rpool in self.replica_pools:
                # Every replica is offered every message; the partition
                # ownership gate (message_filter) keeps exactly one.
                rpool.add_task(msg)

        if self.injector is not None:
            deliver = self.injector.wrap(pod_id, deliver)

        def sink(batch):
            deliver(
                Message(
                    topic=f"kv@{pod_id}@{MODEL}",
                    payload=batch.to_msgpack(),
                    seq=next(self._seq[pod_id]),
                    pod_identifier=pod_id,
                    model_name=MODEL,
                )
            )

        return sink

    # -- elastic fleet (--autoscale) ------------------------------------

    def add_pod(self) -> int:
        """Grow the fleet by one COLD pod (scale-out). The pod exists and
        publishes events from its first store, but with a membership
        service wired it is not routable until the join choreography
        lands it in SERVING — the warm-before-serve gate."""
        i = self.n_pods
        self.n_pods += 1
        pod_id = f"pod-{i}"
        self._seq[pod_id] = self._it.count()
        self.pods.append(self._make_pod(i))
        self.pod_free_at.append(self.now)
        self.pod_active.append([])
        if self._addrs is not None:
            from llm_d_kv_cache_manager_tpu.engine.tiering import (
                IndexBackedPeerResolver,
            )

            # Mutating the SHARED map teaches every existing resolver the
            # new peer; the new pod gets its own resolver over the same map.
            self._addrs[pod_id] = self.pods[i].transfer_address
            self.pods[i].set_peer_resolver(IndexBackedPeerResolver(
                self.indexer.kv_block_index, MODEL, self._addrs, pod_id,
            ))
        return i

    def scale_out(self, k: int) -> dict:
        """Join `k` fresh pods through the full membership choreography:
        add → begin_join (hot-prefix warm jobs run through
        `_membership_warm`: data plane first, idle-compute fallback) →
        drain the landed events → finish_join (SERVING). Returns the
        per-pod join stats."""
        assert self.membership is not None, "scale_out needs membership"
        joins = {}
        for _ in range(k):
            i = self.add_pod()
            pod_id = f"pod-{i}"
            stats = self.membership.begin_join(pod_id)
            # Warm jobs ran synchronously in warm_submit; land their
            # BlockStored events before the pod takes traffic, so its
            # first routed request already scores against the warm set.
            self.event_pool.drain()
            for rpool in self.replica_pools:
                rpool.drain()
            stats.update(self.membership.finish_join(pod_id))
            joins[pod_id] = stats
        return joins

    def scale_in(self, pod_idx: int) -> dict:
        """Drained departure through membership.leave: unroutable
        immediately, stream drained, index entries quarantined."""
        assert self.membership is not None, "scale_in needs membership"
        return self.membership.leave(f"pod-{pod_idx}")

    def _membership_warm(self, pod_identifier: str, chain) -> bool:
        """Warm-before-serve executor for one hot chain on a joining pod.

        Economics-aware: first the data plane (`warm_chain` — longest
        restorable prefix through ready buffer/host/DCN peers, never
        compute; the transfer-vs-recompute gate applies), then an
        idle-compute fallback — the joining pod is NOT serving yet, so
        prefilling the hot prefix on its own clock burns capacity nobody
        is using (charged to pod_free_at: warm-up delays availability,
        honestly). Every landed block emits BlockStored, so the fleet
        index learns the warm replica before the router can choose it."""
        i = int(pod_identifier.split("-")[1])
        pod = self.pods[i]
        tokens = list(chain.prefix_tokens)
        if not tokens:
            return False
        lora = chain.extra[0] if chain.extra else None
        landed = 0
        if pod.tier_store is not None:
            landed = pod.warm_chain(tokens, lora_id=lora)
            if landed:
                cost = self.delta * landed * PAGE_SIZE
                self.pod_free_at[i] = (
                    max(self.pod_free_at[i], self.now) + cost
                )
                self.warm_stats["charged_s"] += cost
        try:
            state, cached = pod.prefill(tokens, lora_id=lora)
        except OutOfPagesError:
            self.warm_stats["jobs"] += 1
            self.warm_stats["blocks_landed"] += landed
            return landed > 0
        uncached = max(len(tokens) - cached, 0)
        if uncached:
            cost = BETA_OVERHEAD_S + self.alpha * uncached
            self.pod_free_at[i] = max(self.pod_free_at[i], self.now) + cost
            self.warm_stats["charged_s"] += cost
            self.warm_stats["tokens_recomputed"] += uncached
        pod.free(state)  # pages to the evictable prefix cache, indexed
        self.warm_stats["jobs"] += 1
        self.warm_stats["blocks_landed"] += landed + (
            uncached // PAGE_SIZE
        )
        return True

    def _pod_watermark(self, pod_identifier: str) -> dict:
        """Membership watermark_fn: the delivery seam's last-applied seq
        for ONE pod's topics (valid at handoff time because the old owner
        has drained — applied == delivered for its streams)."""
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import (
            base_pod_identifier,
        )

        base = base_pod_identifier(pod_identifier)
        return {
            key: seq for key, seq in self._applied_seq.items()
            if base_pod_identifier(key[0]) == base
        }

    # -- anti-entropy seams (--divergence) ------------------------------

    def _pod_for_addr(self, addr):
        if self._addrs is None:
            return None
        for pod_id, a in self._addrs.items():
            if a == addr:
                return pod_id
        return None

    def _make_fetch_miss_cb(self, observer_idx: int):
        """Per-pod TransferClient on_fetch_misses callback: logs the
        wasted-fetch evidence (peers only — a local staged-membership
        probe is not a peer lying) and, when the reconciliation stack is
        wired, runs the feedback purge + the observer's negative cache."""

        def cb(host, port, hashes, missing):
            addr = (host, port)
            peer = self._pod_for_addr(addr)
            if peer is not None and peer != f"pod-{observer_idx}":
                self.fetch_miss_log.append(
                    (self.now, observer_idx, peer, len(missing))
                )
            if self.fetch_feedback is not None:
                self.fetch_feedback.on_fetch_misses(
                    host, port, hashes, missing
                )
                resolver = self.pods[observer_idx].tier_store.peer_resolver
                if hasattr(resolver, "note_miss"):
                    resolver.note_miss(addr, missing, now=self.now)

        return cb

    def _apply_silent_wipes(self, now: float) -> None:
        """Silent-evictor fault mode (antientropy/): the pod loses its
        cache — engine AND host store replaced cold — but keeps its seq
        counter and keeps serving, so the event stream never betrays the
        loss. Every pre-wipe index entry for it is now phantom; only the
        anti-entropy loop (or traffic paying the misses) can find out."""
        if self.fault_plan is None:
            return
        for i in range(self.n_pods):
            faults = self.fault_plan.for_pod(f"pod-{i}")
            if faults is None or faults.silent_wipe_at_s is None:
                continue
            due = self._next_wipe.get(i, faults.silent_wipe_at_s)
            if due is None or now < due:
                continue
            pod_id = f"pod-{i}"
            old = self.pods[i]
            self.pod_active[i] = []  # in-flight decodes die with the cache
            self.pods[i] = self._make_pod(i)
            if self._addrs is not None:
                from llm_d_kv_cache_manager_tpu.engine.tiering import (
                    IndexBackedPeerResolver,
                )

                self._addrs[pod_id] = self.pods[i].transfer_address
                resolver = IndexBackedPeerResolver(
                    self.indexer.kv_block_index, MODEL, self._addrs, pod_id,
                )
                prev = old.tier_store.peer_resolver
                if isinstance(prev, IndexBackedPeerResolver):
                    # The replacement inherits the arm's resolver policy
                    # (rendezvous determinism, negative cache, sim clock).
                    resolver.rendezvous_primary = prev.rendezvous_primary
                    resolver.negative_ttl_s = prev.negative_ttl_s
                    resolver.clock = prev.clock
                self.pods[i].set_peer_resolver(resolver)
                if self.fetch_feedback is not None or (
                    old.connector.client.on_fetch_misses is not None
                ):
                    self.pods[i].connector.client.on_fetch_misses = (
                        self._make_fetch_miss_cb(i)
                    )
            old.close()
            self.silent_wipes.append((now, i))
            nxt = None
            if faults.silent_wipe_every_s > 0:
                candidate = due + faults.silent_wipe_every_s
                if (
                    faults.silent_wipe_until_s is None
                    or candidate <= faults.silent_wipe_until_s
                ):
                    nxt = candidate
            self._next_wipe[i] = nxt

    # -- pod lifecycle (fault scenarios) --------------------------------

    def _apply_lifecycle(self, now: float) -> None:
        """Crash/restart pods per the fault plan, at sim time `now`.

        A crash kills the pod's cache AND its event stream (the injector
        swallows in-window messages independently); restart brings up a
        COLD replacement — the old instance's placements are exactly the
        phantom state the tracker must detect and purge.
        """
        if self.fault_plan is None:
            return
        self._apply_silent_wipes(now)
        for i in range(self.n_pods):
            faults = self.fault_plan.for_pod(f"pod-{i}")
            if faults is None or faults.crash_at_s is None:
                continue
            crashed_now = faults.crashed(now)
            if crashed_now and i not in self._crashed:
                self._crashed.add(i)
                # In-flight decodes die with the pod; their page state is
                # unreachable (the engine instance is discarded at restart).
                self.pod_active[i] = []
            elif not crashed_now and i in self._crashed and (
                faults.restart_at_s is not None and now >= faults.restart_at_s
            ):
                self._crashed.discard(i)
                old = self.pods[i]
                self._seq[f"pod-{i}"] = self._it.count()  # publisher resets
                self.pods[i] = self._make_pod(i)
                self.pod_free_at[i] = now
                self.pod_active[i] = []
                old.close()

    def _alive_pods(self):
        if not self._crashed:
            alive = range(self.n_pods)
        else:
            alive = [i for i in range(self.n_pods) if i not in self._crashed]
        if self.membership is None:
            return alive
        # Elastic-membership routability gate: only SERVING members take
        # traffic (a warming joiner or a draining leaver is index-visible
        # but not routable). An empty intersection falls back to the
        # alive set -- the fleet must never have zero routable pods.
        serving = {
            int(p.split("-")[1]) for p in self.membership.serving_pods()
        }
        gated = [i for i in alive if i in serving]
        return gated or list(alive)

    # -- indexer lifecycle (--replication) ------------------------------

    def _apply_indexer_lifecycle(self, now: float) -> None:
        """Kill/restart the index SERVICE per the fault plan. A crash
        discards the in-memory index (the process died); restart brings up
        a replacement that starts either cold (empty) or from the last
        snapshot + seq-tail replay (cluster/snapshot.py)."""
        plan = self.fault_plan
        if plan is None or plan.indexer_crash_at_s is None:
            return
        if (
            not self._indexer_down
            and not self._indexer_restarted
            and now >= plan.indexer_crash_at_s
        ):
            self._indexer_down = True
        if (
            self._indexer_down
            and plan.indexer_restart_at_s is not None
            and now >= plan.indexer_restart_at_s
        ):
            self._restart_indexer()

    def _restart_indexer(self) -> None:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
            IndexConfig,
            new_index,
        )

        fresh = new_index(IndexConfig.default())
        self.indexer.kv_block_index = fresh
        self.event_pool.index = fresh
        self._indexer_down = False
        self._indexer_restarted = True
        restart = {"mode": "cold"}
        if (
            self.snapshot_restore
            and self.snapshot_path
            and os.path.exists(self.snapshot_path)
        ):
            from llm_d_kv_cache_manager_tpu.cluster import (
                read_snapshot,
                restore_index,
            )

            snap = read_snapshot(self.snapshot_path)
            imported = restore_index(fresh, snap)
            # Replay only the seq tail: the snapshot's watermarks make
            # re-delivery of already-applied events a no-op, so the whole
            # retained journal can be fed back blindly.
            self.event_pool.set_seq_floors(snap.seq_floors())
            skipped_before = self.event_pool.replay_skipped
            replayed = 0
            if self.tail_journal is not None:
                for msg in list(self.tail_journal):
                    self._applied_seq[(msg.pod_identifier, msg.topic)] = msg.seq
                    self.event_pool.add_task(msg)
                    replayed += 1
            self.event_pool.drain()
            self.event_pool.clear_seq_floors()
            restart = {
                "mode": "snapshot_restore",
                "imported_pod_entries": imported,
                "snapshot_created_at_s": round(snap.created_ts, 3),
                "seq_floors": len(snap.seq_counters),
                "tail_replayed": replayed,
                "replay_skipped": (
                    self.event_pool.replay_skipped - skipped_before
                ),
            }
        self.replication_stats["restart"] = restart

    def _maybe_snapshot(self, now: float) -> None:
        """Periodic snapshot cadence (pre-crash only; a real replica
        snapshots on a timer — the LAST one before the crash is what the
        restart restores, so snapshot age bounds the replay tail)."""
        if (
            not self.snapshot_every_s
            or not self.snapshot_path
            or self._indexer_down
            or self._indexer_restarted
        ):
            return
        if (
            self._last_snapshot_at is not None
            and now - self._last_snapshot_at < self.snapshot_every_s
        ):
            return
        from llm_d_kv_cache_manager_tpu.cluster import write_snapshot

        stats = write_snapshot(
            self.snapshot_path,
            self.indexer.kv_block_index,
            dict(self._applied_seq),
            created_ts=now,
        )
        self._last_snapshot_at = now
        self.replication_stats["last_snapshot"] = {
            "at_s": round(now, 3),
            "bytes": stats["bytes"],
            "keys": stats["keys"],
            "pod_entries": stats["pod_entries"],
            "seq_counters": stats["seq_counters"],
        }

    def route(self, prompt: str, lora_id=None) -> int:
        if self.route_override is not None:
            return self.route_override(prompt)
        if self.strategy == "round_robin":
            pod = self.rr_counter % self.n_pods
            self.rr_counter += 1
            return pod
        if self.strategy == "random":
            return self.route_rng.randrange(self.n_pods)
        if self.strategy == "load":
            return min(self._alive_pods(), key=lambda i: self.pod_free_at[i])
        if self.strategy == "estimated":
            return self._route_estimated(prompt)
        if self._indexer_down:
            # The index service is dead: the router's scoring call times
            # out and it falls back to least-loaded — degraded, not stuck.
            self.indexer_down_requests += 1
            return min(self._alive_pods(), key=lambda i: self.pod_free_at[i])
        if self._prescored:
            # Batched router window (serve_batch): this request's scores
            # were produced by ONE score_many call over the whole window;
            # its amortized read latency was recorded at prescore time.
            scores = self._prescored.popleft()
        else:
            t0 = time.perf_counter()
            if self.cluster_scorer is not None:
                scores = self.cluster_scorer.get_pod_scores(
                    prompt, MODEL, [], lora_id=lora_id
                )
            else:
                scores = self.indexer.get_pod_scores(
                    prompt, MODEL, [], lora_id=lora_id
                )
            self.read_latencies.append(time.perf_counter() - t0)
            if self.verify_cluster_scores and self.cluster_scorer is not None:
                # Stale-partition audit: the sim's monolithic indexer
                # digests EVERY stream, so the ownership-merged cluster
                # answer must equal it request-for-request — including
                # across live reassignments. Any divergence is a stale
                # (or lost) partition score. Untimed: the audit is not
                # part of the serving path.
                mono = self.indexer.get_pod_scores(
                    prompt, MODEL, [], lora_id=lora_id
                )
                self.cluster_verified_requests += 1
                if scores != mono:
                    self.stale_partition_scores += 1
        if self.membership is not None and scores:
            # Warm-before-serve / drain gate: the index may already know a
            # warming joiner's blocks (its warm-up emitted BlockStored) or
            # a draining leaver's remnants — the router only follows
            # SERVING members.
            serving = set(self.membership.serving_pods())
            scores = {
                p: s for p, s in scores.items()
                if p.split("@")[0] in serving
            }
        if self._indexer_restarted and not scores:
            self.scores_empty_after_restart += 1
        if self._crashed and scores and any(
            int(p.split("-")[1]) in self._crashed for p in scores
        ):
            self.phantom_scores.append(self.now)
        if self.routing_policy is not None and not self.routing_policy.is_noop:
            # Load-blend routing (--autoscale): the full candidate-set
            # decision — prefix_frac minus normalized load over every
            # routable pod, so a saturated perfect-prefix pod loses to a
            # warm-enough (or idle) alternative. prefix_only never
            # reaches here (select returns None → pure argmax below).
            choice = self.routing_policy.select(
                scores,
                [f"pod-{i}" for i in self._alive_pods()],
                now=self.now,
            )
            if choice is not None:
                return int(choice.split("-")[1])
        if not scores:
            # No cache anywhere (or every scored pod excluded as stale —
            # the explicit no-cache-signal answer): least-loaded pod.
            return min(self._alive_pods(), key=lambda i: self.pod_free_at[i])
        best = max(scores.values())
        candidates = [int(p.split("-")[1]) for p, s in scores.items() if s == best]
        return min(candidates, key=lambda i: self.pod_free_at[i])

    def _route_estimated(self, prompt: str) -> int:
        """Scheduler-side estimation: score each pod by the longest
        consecutive run of this prompt's block keys whose affinity entry
        points at it — routing history standing in for cache state. The
        estimate is never corrected by engine events: an evicted prefix
        still attracts traffic, and a never-routed-but-cached one repels
        it — exactly the failure mode precise tracking removes (reference
        37-capacity: 'default (estimated scheduling)' arm)."""
        tokens = self.indexer.tokenizers_pool.tokenize(None, prompt, MODEL)
        keys = self.indexer.token_processor.tokens_to_kv_block_keys(
            None, tokens, MODEL
        )
        run_len = [0] * self.n_pods
        for i in range(self.n_pods):
            for key in keys:
                if self.affinity.get(key.chunk_hash) != i:
                    break
                run_len[i] += 1
        best = max(run_len)
        pod = min(
            (i for i in range(self.n_pods) if run_len[i] == best),
            key=lambda i: self.pod_free_at[i],
        )
        for key in keys:
            self.affinity[key.chunk_hash] = pod
            self.affinity.move_to_end(key.chunk_hash)
        while len(self.affinity) > self.affinity_cap:
            self.affinity.popitem(last=False)
        return pod

    def _release_finished(self, now: float) -> None:
        """Free sequences whose decode completed before `now`: their pages
        move to the evictable prefix cache (still indexed until the block
        manager actually reclaims them for a later allocation)."""
        for idx, active in enumerate(self.pod_active):
            if not active:
                continue
            keep = []
            for finish, state, n_tokens in active:
                if finish <= now:
                    self.pods[idx].free(state)
                else:
                    keep.append((finish, state, n_tokens))
            self.pod_active[idx] = keep

    def _preempt_youngest(self, pod_idx: int) -> float:
        """vLLM recompute-preemption: evict the running sequence with the
        most decode left (the youngest), freeing its pages for the incoming
        admission. Returns the preempted sequence's re-prefill compute cost
        — work the pod must redo when the victim resumes, charged to the
        pod's clock so saturation compounds the way the reference's
        73-capacity run shows. The victim's page reclaim emits BlockRemoved
        through the block manager, which only precise tracking observes."""
        active = self.pod_active[pod_idx]
        k = max(range(len(active)), key=lambda j: active[j][0])
        _finish, victim, n_tokens = active.pop(k)
        self.pods[pod_idx].free(victim)
        self.preemptions += 1
        if self.load_tracker is not None:
            # Direct preemption signal (the sim's pod-load reporter knows
            # its own preemptions); the BlockRemoved volume the event pool
            # credits independently is the wire-visible trace a deployment
            # without a reporter falls back on.
            self.load_tracker.observe_preemption(
                f"pod-{pod_idx}", now=self.now
            )
        return self.alpha * n_tokens

    def serve_batch(self, items) -> list:
        """Serve one router arrival window: ONE `score_many` call over
        the whole window (against the index state at the window's head —
        what a real batching router sees), then the requests are served
        in arrival order consuming the prescored decisions. `items` is a
        list of `(arrival_s, prompt)` pairs. At window=1 the prescore IS
        a single-item bulk call over exactly the state the per-request
        path would score, so routing (and therefore the whole TTFT
        stream) is bit-identical to the flag-off run — pinned by
        `--batch-window 1`. Wired for the plain precise arm (no faults /
        replication / placement composition)."""
        if not items:
            return []
        first = items[0][0]
        # The same prelude serve() runs before routing, so the window is
        # scored against exactly the state the head request would see.
        # serve() re-runs these at the same sim time as a no-op.
        self.now = first
        self._apply_lifecycle(first)
        self._apply_indexer_lifecycle(first)
        self._maybe_snapshot(first)
        self._release_finished(first)
        if not self._indexer_down:
            reqs = [
                ScoreRequest(prompt=prompt, model_name=MODEL)
                for _, prompt in items
            ]
            t0 = time.perf_counter()
            if self.cluster_scorer is not None:
                results = self.cluster_scorer.score_many(reqs)
            else:
                results = self.indexer.score_many(reqs)
            amortized = (time.perf_counter() - t0) / len(items)
            for r in results:
                self._prescored.append(r.scores)
                self.read_latencies.append(amortized)
        return [self.serve(arrival, prompt) for arrival, prompt in items]

    def serve(
        self,
        arrival: float,
        prompt: str,
        response_words: int = RESPONSE_WORDS,
        lora_id=None,
    ) -> float:
        """Returns TTFT for this request under the simulated clock.
        `response_words` sizes the decode that holds this request's pages
        (trace-driven workloads carry per-turn output lengths; the
        synthetic workload uses the fixed RESPONSE_WORDS). `lora_id`
        scopes the request to that tenant's keyspace end-to-end: scoring,
        allocation, and the engine events all carry it."""
        self.now = arrival
        self._apply_lifecycle(arrival)
        self._apply_indexer_lifecycle(arrival)
        self._maybe_snapshot(arrival)
        self._release_finished(arrival)
        if self.replicator is not None:
            # Placement policy tick, between requests: detect hot chains,
            # push replication jobs through the prefetch plane, and drain
            # both the plane and the event pool so the landed replicas'
            # BlockStored events are index-visible before routing — the
            # same effects a real deployment gets asynchronously, made
            # deterministic under the simulated clock.
            if self.replicator.tick(arrival):
                self.route_prefetcher.drain(timeout_s=30.0)
                self.event_pool.drain()
        if self.prefetch_scheduler is not None:
            # Anticipatory-prefetch tick, between requests: sessions in
            # their predicted idle window get their continuation prefix
            # pre-landed on the router's pick. Drained like the
            # replication plane so the pre-landed blocks' BlockStored
            # events are index-visible before this arrival routes.
            if self.prefetch_scheduler.tick(arrival):
                self.prediction_prefetcher.drain(timeout_s=30.0)
                self.event_pool.drain()
        if self.auditor is not None:
            # Residency-audit tick, between requests: sampled challenges
            # of each pod's advertised entries against its REAL block
            # manager / host store, with purges + re-admissions applied
            # before this arrival routes — the asynchronous repair loop a
            # real deployment runs, made deterministic under the sim
            # clock.
            self.auditor.tick(arrival)
        if self.load_tracker is not None:
            # The sim IS the pod-load reporter: pod_free_at is each pod's
            # committed busy horizon, pod_active its inflight decode
            # depth. Reported at routing time, exactly what a sidecar
            # scraping the engines would push.
            for i in self._alive_pods():
                depth = len(self.pod_active[i])
                self.load_tracker.report(
                    f"pod-{i}",
                    queue_depth=depth,
                    inflight=depth,
                    busy_until=self.pod_free_at[i],
                    now=arrival,
                )
        pod_idx = self.route(prompt, lora_id=lora_id)
        self.last_pod_idx = pod_idx
        if pod_idx in self._crashed:
            # Phantom placement: the index still credits a dead pod. The
            # router's connection fails and it retries least-loaded — the
            # request survives, but only because of a timeout+retry the
            # health subsystem exists to make unnecessary.
            self.stale_routes.append((arrival, pod_idx))
            pod_idx = min(self._alive_pods(), key=lambda i: self.pod_free_at[i])
        pod = self.pods[pod_idx]

        tokens = self.indexer.tokenizers_pool.tokenize(None, prompt, MODEL)
        self.total_tokens += len(tokens)
        if self.pre_admit_hook is not None:
            # Residency audit (the anticipate bench): the routed pod is
            # known, the request is not yet admitted — prefill would make
            # its blocks resident and erase the before-arrival evidence.
            self.pre_admit_hook(self, pod_idx, pod, tokens, arrival)
        stats_before = dict(pod.tier_store.stats) if pod.tier_store else None

        def tier_delta():
            # Blocks re-landed through the data plane are cache hits, but
            # not free ones: charge them at DMA/DCN bandwidth instead of
            # recompute — including loads done by an allocate that then
            # failed, or the high-pressure regime under-reports itself.
            if stats_before is None:
                return 0, 0
            r = pod.tier_store.stats["restores"] - stats_before["restores"]
            o = pod.tier_store.stats["onboards"] - stats_before["onboards"]
            self.restored_blocks += r
            self.onboarded_blocks += o
            return r, o

        state = None
        requeue_s = 0.0
        while state is None:
            try:
                state, cached = pod.prefill(tokens, lora_id=lora_id)
            except OutOfPagesError:
                if self.pod_active[pod_idx]:
                    requeue_s += self._preempt_youngest(pod_idx)
                    continue
                # Sequence larger than the pod's whole free pool even with
                # every decode preempted: serve uncached (count the full
                # prefill). Any tier traffic the failed allocate already
                # performed is still charged and counted.
                restored, onboarded = tier_delta()
                start = max(arrival, self.pod_free_at[pod_idx])
                prefill_s = (
                    BETA_OVERHEAD_S
                    + self.alpha * len(tokens)
                    + self.gamma * restored * PAGE_SIZE
                    + self.delta * onboarded * PAGE_SIZE
                    + self._take_fault_charge(pod_idx)
                )
                self.pod_free_at[pod_idx] = start + prefill_s + requeue_s
                return (start - arrival) + prefill_s
        self.hit_tokens += min(cached, len(tokens))
        restored, onboarded = tier_delta()

        uncached = max(len(tokens) - cached, 0)
        prefill_s = (
            BETA_OVERHEAD_S
            + self.alpha * uncached
            + self.gamma * restored * PAGE_SIZE
            + self.delta * onboarded * PAGE_SIZE
            + self._take_fault_charge(pod_idx)
        )
        start = max(arrival, self.pod_free_at[pod_idx])
        ttft = (start - arrival) + prefill_s
        # Preempted victims resume behind this admission: their re-prefill
        # compute occupies the pod before its next free slot.
        self.pod_free_at[pod_idx] = start + prefill_s + requeue_s

        if self.host_tier:
            # Publish the committed pages to this pod's transfer server so
            # peers can onboard them over DCN (dedup'd; pages stay in HBM).
            pod.export_sequence(state)
        # The sequence decodes its response before releasing pages — the
        # concurrent-occupancy dynamic that makes KV pressure (and hence
        # preemption) real. Released lazily by _release_finished.
        decode_finish = start + prefill_s + ITL_S_PER_TOKEN * response_words
        self.pod_active[pod_idx].append((decode_finish, state, len(tokens)))
        self.event_pool.drain()
        for rpool in self.replica_pools:
            rpool.drain()
        return ttft

    def _take_fault_charge(self, pod_idx: int) -> float:
        """Drain the synthetic fetch latency the chaos injector charged
        this pod since the last request (timeout ladders paid to dark
        peers; 0.0 outside --chaos runs — the healthy path adds nothing)."""
        if not self.faulty:
            return 0.0
        wrapper = self.faulty.get(pod_idx)
        return wrapper.take_charge() if wrapper is not None else 0.0

    # -- proactive replication executor (--placement) --------------------

    def _replication_prefetch(self, pod_identifier: str, hashes) -> int:
        """The RoutePrefetcher's prefetch_fn for replication jobs: fill the
        target pod's ready buffer over the real transfer plane, then warm
        the chain through the normal allocate/restore path (commits the
        blocks + emits BlockStored, so the index learns the replica). The
        transfer time is charged to the target pod's clock — replication
        is background work, but it is not free work."""
        i = int(pod_identifier.split("-")[1])
        if i in self._crashed:
            return 0
        pod = self.pods[i]
        pod.prefetch_hashes(list(hashes))
        chain = self.popularity.chain(hashes[0])
        if chain is None or not chain.prefix_tokens:
            return 0
        lora = chain.extra[0] if chain.extra else None
        landed = pod.warm_chain(chain.prefix_tokens, lora_id=lora)
        if landed:
            self.replicated_blocks += landed
            cost_s = self.delta * landed * PAGE_SIZE
            self.pod_free_at[i] = max(self.pod_free_at[i], self.now) + cost_s
            self.replication_charged_s += cost_s
        return landed

    # -- anticipatory prefetch executor (--anticipate) --------------------

    def _prediction_select(self, scores) -> str:
        """The sim router's exact decision rule over a score map (best
        score, least-loaded tie-break; least-loaded alive pod when there
        is no cache signal anywhere) — handed to the PrefetchScheduler so
        a prediction targets precisely the pod route() would pick."""
        if not scores:
            i = min(self._alive_pods(), key=lambda i: self.pod_free_at[i])
            return f"pod-{i}"
        best = max(scores.values())
        candidates = [
            int(p.split("-")[1]) for p, s in scores.items() if s == best
        ]
        return f"pod-{min(candidates, key=lambda i: self.pod_free_at[i])}"

    def _prediction_prefetch(self, pod_identifier: str, hashes) -> int:
        """The prediction RoutePrefetcher's prefetch_fn: fill the target
        pod's ready buffer over the real transfer plane, then warm the
        session's chain through the normal allocate/restore path (commits
        blocks + emits BlockStored, so the index — and therefore the
        router — learns the pre-landed prefix). Transfer time is charged
        to the target pod's clock: anticipation is background work, not
        free work. Serving wins by construction — warm_chain aborts on
        OutOfPagesError and never computes."""
        i = int(pod_identifier.split("-")[1])
        if i in self._crashed:
            return 0
        pod = self.pods[i]
        pod.prefetch_hashes(list(hashes))
        # The job's hashes are the chain's missing tail; its last element
        # is the session's tail hash — the table key.
        rec = self.session_table.record_by_tail(hashes[-1])
        if rec is None or not rec.tokens:
            return 0
        landed = pod.warm_chain(rec.tokens, lora_id=rec.lora_id)
        if landed:
            self.predicted_landed_blocks += landed
            # Misprediction accounting counts MOVED bytes: tell the table
            # how much this prefetch actually transferred.
            self.session_table.note_landed(hashes[-1], landed)
            cost_s = self.delta * landed * PAGE_SIZE
            self.pod_free_at[i] = max(self.pod_free_at[i], self.now) + cost_s
            self.prediction_charged_s += cost_s
        return landed

    def prediction_stats(self) -> dict:
        if self.prefetch_scheduler is None:
            return {}
        return {
            "scheduler": dict(self.prefetch_scheduler.stats),
            "table": self.session_table.stats(),
            "prefetcher": self.prediction_prefetcher.status(),
            "predicted_landed_blocks": self.predicted_landed_blocks,
            "prediction_charged_s": round(self.prediction_charged_s, 4),
        }

    def placement_stats(self) -> dict:
        if self.replicator is None:
            return {}
        return {
            "replicator": dict(self.replicator.stats),
            "tracker": self.popularity.stats(),
            "prefetcher": dict(self.route_prefetcher.stats),
            "replicated_blocks": self.replicated_blocks,
            "replication_charged_s": round(self.replication_charged_s, 4),
        }

    def shutdown(self):
        if self.route_prefetcher is not None:
            self.route_prefetcher.close()
        if self.prediction_prefetcher is not None:
            self.prediction_prefetcher.close()
        if self.cluster_scorer is not None:
            self.cluster_scorer.close()
        for rpool in self.replica_pools:
            rpool.shutdown()
        self.event_pool.shutdown()
        self.indexer.shutdown()
        for pod in self.pods:
            pod.close()


def run_strategy(
    strategy: str, qps: float = QPS, workload: str = "chat", **sim_kwargs
):
    if workload == "capacity":
        requests, conversations, rng = build_capacity_workload(qps=qps)
    else:
        requests, conversations, rng = build_workload(qps=qps)
    sim = FleetSim(strategy, **sim_kwargs)
    ttfts = []
    try:
        for arrival, conv_id in requests:
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            ttfts.append(sim.serve(arrival, prompt))
            if workload != "capacity":
                # Assistant response extends the conversation (next turn's
                # prefix); capacity-regime requests are single-turn.
                conversations[conv_id] = (
                    prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
                )
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        lat = sorted(sim.read_latencies)
        read_p50 = lat[len(lat) // 2] if lat else 0.0
        extras = {
            "restored_blocks": sim.restored_blocks,
            "onboarded_blocks": sim.onboarded_blocks,
            "preemptions": sim.preemptions,
            "gated_blocks": sum(
                pod.tier_store.stats["gated_blocks"]
                for pod in sim.pods if pod.tier_store is not None
            ),
        }
        return ttfts, hit_rate, read_p50, extras
    finally:
        sim.shutdown()


# ShareGPT-shaped workload (workloads/ subsystem): the BASELINE metric is
# defined over a ShareGPT replay, so this mode serves a trace whose
# prompt-length / output-length / turns-per-session distributions match the
# committed tables (workloads/tables.py) instead of the fixed-shape
# synthetic chat above. Sessions=48 at the default table-faithful lengths
# puts the fleet's aggregate working set right at the 8x2048-page nominal
# capacity (fixture BPE ≈1.8 tokens/word), so eviction pressure — the
# regime where tracking precision matters — is real. max_turns caps the
# pmf's 20/24/32-turn tail so one marathon session can't dominate the run;
# stats.validate_trace folds the capped mass before checking fidelity.
SHAREGPT_SESSIONS = 48
SHAREGPT_MAX_TURNS = 12
SHAREGPT_SESSION_RATE = 1.5


def build_sharegpt_trace(seed: int = 42, arrival: str = "poisson"):
    from llm_d_kv_cache_manager_tpu.workloads import ShareGPTConfig, generate

    return generate(ShareGPTConfig(
        n_sessions=SHAREGPT_SESSIONS,
        seed=seed,
        arrival=arrival,
        session_rate_per_s=SHAREGPT_SESSION_RATE,
        max_turns=SHAREGPT_MAX_TURNS,
        prefix_groups=N_PODS,
    ))


def run_sharegpt_strategy(strategy: str, requests, **sim_kwargs):
    """Serve a materialized trace (workloads.spec.MaterializedRequest
    stream) through the same FleetSim as the synthetic arms. Returns the
    same (ttfts, hit_rate, read_p50, extras) tuple as run_strategy."""
    sim = FleetSim(strategy, **sim_kwargs)
    ttfts = []
    try:
        for req in requests:
            ttfts.append(
                sim.serve(req.arrival_s, req.prompt,
                          response_words=req.output_len)
            )
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        lat = sorted(sim.read_latencies)
        read_p50 = lat[len(lat) // 2] if lat else 0.0
        extras = {
            "restored_blocks": sim.restored_blocks,
            "onboarded_blocks": sim.onboarded_blocks,
            "preemptions": sim.preemptions,
        }
        return ttfts, hit_rate, read_p50, extras
    finally:
        sim.shutdown()


def main_sharegpt(args):
    """--workload sharegpt: the 5-arm comparison over ShareGPT-shaped
    traffic. Writes benchmarking/FLEET_BENCH_SHAREGPT.json — a separate
    artifact from FLEET_BENCH.json, so the synthetic headline and its
    README tables stay comparable across rounds."""
    from llm_d_kv_cache_manager_tpu.workloads import (
        read_trace,
        write_trace,
    )
    from llm_d_kv_cache_manager_tpu.workloads import stats as workload_stats

    t_start = time.time()
    if args.trace:
        trace = read_trace(args.trace)
    else:
        trace = build_sharegpt_trace(seed=args.seed, arrival=args.arrival)
    if args.record:
        write_trace(trace, args.record)
        print(f"trace recorded: {args.record}", file=sys.stderr)

    # Library self-check: the trace we are about to headline must match the
    # committed distribution tables (replayed traces included).
    fidelity = None
    if trace.workload == "sharegpt":
        fidelity = workload_stats.validate_trace(trace)
        fidelity.raise_if_failed()

    requests = trace.requests()
    arms = ("precise", "estimated", "load", "random", "round_robin")
    results = {}
    for arm in arms:
        ttfts, hit, _, ex = run_sharegpt_strategy(arm, requests)
        results[arm] = {
            "ttft_p50_s": round(p50(ttfts), 4),
            "ttft_p90_s": round(p90(ttfts), 4),
            "ttft_mean_s": round(sum(ttfts) / len(ttfts), 4),
            "prefix_hit_rate": round(hit, 4),
            "preemptions": ex["preemptions"],
        }
    speedup = (
        results["round_robin"]["ttft_p50_s"]
        / max(results["precise"]["ttft_p50_s"], 1e-9)
    )
    stats = {
        "workload": trace.workload,
        "trace": {
            "seed": trace.seed,
            "config": trace.config,
            "tables_version": trace.tables_version,
            "sessions": len(trace.sessions),
            "requests": len(requests),
            "source": args.trace or "generated",
        },
        "fleet": {
            "n_pods": N_PODS,
            "page_size": PAGE_SIZE,
            "pages_per_pod": PAGES_PER_POD,
        },
        "distribution_fidelity": fidelity.as_dict() if fidelity else None,
        "arms": results,
        "sharegpt_ttft_p50_speedup": round(speedup, 3),
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_SHAREGPT.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "sharegpt_ttft_p50_speedup_vs_round_robin",
        "value": round(speedup, 3),
        "unit": "x",
        # BASELINE.json target: >=2x TTFT speedup vs round-robin, now
        # measured on the ShareGPT replay the metric sentence names.
        "vs_baseline": round(speedup / 2.0, 3),
        "prefix_hit_rate": results["precise"]["prefix_hit_rate"],
        "source": "benchmarking/FLEET_BENCH_SHAREGPT.json",
    }))


# Fault-injection scenario (--faults; fleethealth/ subsystem): replay the
# synthetic chat workload while a scripted FaultPlan kills a pod mid-run,
# stalls another's event stream, and makes a third/fourth pod's stream
# lossy/reordering — then measure what the liveness tracker buys: how long
# phantom placements keep attracting traffic (detection latency), that
# NOTHING routes to the dead pod after detection, and how much hit rate the
# degraded modes retain vs the no-fault run. Three arms, same workload:
#   no_fault          subsystem enabled (production windows — provably
#                     inert on a run shorter than the suspect window), no
#                     faults: MUST be bit-identical to FLEET_BENCH.json's
#                     headline precise arm (cross-checked in the artifact).
#   faults_with_health the product: tight windows, demotion, quarantine.
#   faults_no_health   control: same faults, tracker off — stale routing
#                     never stops and the restarted pod's phantom entries
#                     keep lying until overwritten.
FAULT_SUSPECT_S = 1.0
FAULT_STALE_S = 2.5
FAULT_DEMOTION = 0.5
FAULT_CRASH_POD = "pod-2"
FAULT_CRASH_AT_S = 4.0
FAULT_RESTART_AT_S = 9.0
FAULT_STALL_POD = "pod-5"
FAULT_STALL_FROM_S = 3.0
FAULT_STALL_UNTIL_S = 7.0
FAULT_LOSSY_POD = "pod-6"
FAULT_DROP_RATE = 0.10
FAULT_DUP_RATE = 0.05
FAULT_REORDER_POD = "pod-7"
FAULT_REORDER_RATE = 0.10
# Post-recovery window: restart + one stale window of settling.
FAULT_RECOVERY_FROM_S = 12.0


def build_fault_plan(seed: int = 42):
    from llm_d_kv_cache_manager_tpu.fleethealth import FaultPlan, PodFaults

    return FaultPlan(seed=seed, pods={
        FAULT_CRASH_POD: PodFaults(
            crash_at_s=FAULT_CRASH_AT_S, restart_at_s=FAULT_RESTART_AT_S
        ),
        FAULT_STALL_POD: PodFaults(
            stall_from_s=FAULT_STALL_FROM_S, stall_until_s=FAULT_STALL_UNTIL_S
        ),
        FAULT_LOSSY_POD: PodFaults(
            drop_rate=FAULT_DROP_RATE, duplicate_rate=FAULT_DUP_RATE
        ),
        FAULT_REORDER_POD: PodFaults(reorder_rate=FAULT_REORDER_RATE),
    })


def run_fault_arm(health_config, fault_plan, qps: float = QPS):
    """One precise-arm replay of the chat workload under (health, faults).

    Returns per-request records plus the health/injection bookkeeping the
    artifact reports. Detection times are observed the way a router would:
    by polling the tracker's state after each request."""
    requests, conversations, rng = build_workload(qps=qps)
    sim = FleetSim(
        "precise", health_config=health_config, fault_plan=fault_plan
    )
    records = []  # (arrival, ttft, hit_tokens_delta, total_tokens_delta)
    detection = {}
    watch = []
    if fault_plan is not None and sim.health is not None:
        watch = [
            (FAULT_CRASH_POD, "crash", FAULT_CRASH_AT_S),
            (FAULT_STALL_POD, "stall", FAULT_STALL_FROM_S),
        ]
    try:
        for arrival, conv_id in requests:
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            h0, t0 = sim.hit_tokens, sim.total_tokens
            ttft = sim.serve(arrival, prompt)
            records.append(
                (arrival, ttft, sim.hit_tokens - h0, sim.total_tokens - t0)
            )
            conversations[conv_id] = (
                prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
            )
            for pod, kind, fault_at in watch:
                if pod not in detection and sim.health.state_of(pod) == "stale":
                    detection[pod] = {
                        "kind": kind,
                        "fault_at_s": fault_at,
                        "detected_at_s": round(arrival, 3),
                        "latency_s": round(arrival - fault_at, 3),
                    }
        if sim.injector is not None:
            sim.injector.flush()
        sim.event_pool.drain()
        return {
            "records": records,
            "stale_routes": list(sim.stale_routes),
            "phantom_scores": list(sim.phantom_scores),
            "detection": detection,
            "health_summary": (
                sim.health.summary(now=records[-1][0]) if sim.health else None
            ),
            "anomalies": sim.health.anomaly_totals() if sim.health else None,
            "injected": dict(sim.injector.injected) if sim.injector else None,
        }
    finally:
        sim.shutdown()


def _window_hit_rate(records, t_from=None, t_until=None):
    hit = tot = 0
    for arrival, _ttft, h, t in records:
        if t_from is not None and arrival < t_from:
            continue
        if t_until is not None and arrival >= t_until:
            continue
        hit += h
        tot += t
    return hit / max(tot, 1)


def _fault_arm_stats(arm, detection_at=None):
    records = arm["records"]
    ttfts = [r[1] for r in records]
    stale = arm["stale_routes"]
    phantom = arm.get("phantom_scores", [])
    out = {
        "ttft_p50_s": round(p50(ttfts), 4),
        "ttft_p90_s": round(p90(ttfts), 4),
        "prefix_hit_rate": round(_window_hit_rate(records), 4),
        "post_recovery_hit_rate": round(
            _window_hit_rate(records, t_from=FAULT_RECOVERY_FROM_S), 4
        ),
        "stale_routes": len(stale),
        "phantom_score_requests": len(phantom),
    }
    if detection_at is not None:
        out["stale_routes_after_detection"] = sum(
            1 for t, _pod in stale if t > detection_at
        )
        out["phantom_scores_after_detection"] = sum(
            1 for t in phantom if t > detection_at
        )
    return out


def main_faults(args):
    from llm_d_kv_cache_manager_tpu.fleethealth import FleetHealthConfig

    t_start = time.time()
    tight = FleetHealthConfig(
        suspect_after_s=FAULT_SUSPECT_S,
        stale_after_s=FAULT_STALE_S,
        suspect_demotion_factor=FAULT_DEMOTION,
    )
    production = FleetHealthConfig()  # 30s/120s: inert on a ~17s replay
    plan = build_fault_plan(seed=args.seed)

    no_fault = run_fault_arm(production, None)
    with_health = run_fault_arm(tight, plan)
    no_health = run_fault_arm(None, plan)

    crash_detected_at = (
        with_health["detection"].get(FAULT_CRASH_POD, {}).get("detected_at_s")
    )
    arms = {
        "no_fault": _fault_arm_stats(no_fault),
        "faults_with_health": _fault_arm_stats(
            with_health, detection_at=crash_detected_at
        ),
        # The control arm gets the SAME cutoff (the time at which the
        # health-enabled run had detected the crash) so its
        # *_after_detection counts read as "what the subsystem would have
        # prevented": with health they are zero, without they keep growing.
        "faults_no_health": _fault_arm_stats(
            no_health, detection_at=crash_detected_at
        ),
    }
    wh = arms["faults_with_health"]
    wh["detection"] = with_health["detection"]
    wh["anomalies"] = with_health["anomalies"]
    wh["injected"] = with_health["injected"]
    hs = with_health["health_summary"]
    wh["purged_entries"] = sum(
        p["purged_entries"] for p in hs["pods"].values()
    )
    wh["recoveries"] = sum(p["recoveries"] for p in hs["pods"].values())
    arms["faults_no_health"]["injected"] = no_health["injected"]

    nf, fh = arms["no_fault"], arms["faults_with_health"]
    stats = {
        "config": {
            "workload": "synthetic chat (build_workload), precise arm",
            "requests": len(no_fault["records"]),
            "qps": QPS,
            "n_pods": N_PODS,
            "pages_per_pod": PAGES_PER_POD,
            "seed": args.seed,
            "health": {
                "suspect_after_s": FAULT_SUSPECT_S,
                "stale_after_s": FAULT_STALE_S,
                "suspect_demotion_factor": FAULT_DEMOTION,
            },
            "no_fault_arm_health": {
                "suspect_after_s": production.suspect_after_s,
                "stale_after_s": production.stale_after_s,
            },
            "fault_plan": plan.as_dict(),
            "recovery_window_from_s": FAULT_RECOVERY_FROM_S,
        },
        "arms": arms,
        "hit_rate_retention": round(
            fh["prefix_hit_rate"] / max(nf["prefix_hit_rate"], 1e-9), 4
        ),
        "post_recovery_hit_rate_delta": round(
            nf["post_recovery_hit_rate"] - fh["post_recovery_hit_rate"], 4
        ),
        "wall_s": round(time.time() - t_start, 1),
    }
    # Acceptance cross-check: the subsystem-enabled no-fault run must match
    # the committed headline precise arm bit-for-bit (hit rate + TTFT).
    fleet_bench = os.path.join(REPO, "benchmarking", "FLEET_BENCH.json")
    if os.path.exists(fleet_bench):
        with open(fleet_bench) as f:
            fb = json.load(f)
        stats["no_fault_vs_fleet_bench"] = {
            "fleet_bench_prefix_hit_rate": fb.get("prefix_hit_rate"),
            "no_fault_prefix_hit_rate": nf["prefix_hit_rate"],
            "fleet_bench_ttft_p50_s": fb.get("ttft_p50_precise_s"),
            "no_fault_ttft_p50_s": nf["ttft_p50_s"],
            "bit_identical": (
                fb.get("prefix_hit_rate") == nf["prefix_hit_rate"]
                and fb.get("ttft_p50_precise_s") == nf["ttft_p50_s"]
            ),
        }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_FAULTS.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "stale_routes_after_detection",
        "value": wh.get("stale_routes_after_detection"),
        "unit": "requests",
        "stale_routes_with_health": wh["stale_routes"],
        "stale_routes_no_health": arms["faults_no_health"]["stale_routes"],
        "phantom_scores_after_detection_with_health": wh.get(
            "phantom_scores_after_detection"
        ),
        "phantom_scores_after_detection_no_health": arms[
            "faults_no_health"
        ].get("phantom_scores_after_detection"),
        "detection_latency_s": with_health["detection"]
        .get(FAULT_CRASH_POD, {})
        .get("latency_s"),
        "hit_rate_retention": stats["hit_rate_retention"],
        "source": "benchmarking/FLEET_BENCH_FAULTS.json",
    }))


# Transfer-plane chaos scenario (--chaos; kv_connectors/faults.py +
# connector.py hardening): the highest-DCN-traffic committed configuration
# (cache-oblivious round-robin routing over the two-tier fleet, where pods
# constantly onboard prefixes they never computed from peers) replayed
# under per-peer transfer faults:
#   no_fault              integrity + breakers ON, zero faults — must stay
#                         bit-identical to the committed FLEET_BENCH.json
#                         two-tier round-robin row (the healthy-fleet
#                         bit-identity acceptance, checked in-artifact).
#   corrupt_integrity_on  one peer ships corrupt blocks; every corruption
#                         is DETECTED (checksum seam), degrades to a
#                         fallback/recompute, ZERO corrupted blocks land.
#   corrupt_integrity_off the v1-wire control: same damage sails through
#                         and LANDS — the silent wrong-model-output
#                         failure mode the end-to-end checksum kills.
#   stall_no_breaker      one peer stalls mid-run; every fetch to it pays
#                         the full timeout ladder for the whole window.
#   stall_breaker         same stall with per-peer breakers: after
#                         `failure_threshold` consecutive timeouts the
#                         breaker opens and fetches skip instantly;
#                         half-open probes re-close it once the stall
#                         clears (recovery is part of the arm's evidence).
CHAOS_CORRUPT_POD = "pod-3"
CHAOS_CORRUPT_RATE = 0.5
CHAOS_STALL_POD = "pod-2"
CHAOS_STALL_FROM_S = 4.0
CHAOS_STALL_UNTIL_S = 12.0
CHAOS_IO_TIMEOUT_MS = 1000
CHAOS_CONNECT_TIMEOUT_MS = 500
CHAOS_RETRIES = 0
CHAOS_BREAKER_THRESHOLD = 3
# Longer than the stall's remainder past detection: the half-open probes
# (which pay a full ladder against a still-dark peer) land after the
# stall clears, so the first probe SUCCEEDS and re-closes the breaker —
# the recovery leg of the arm's evidence.
CHAOS_BREAKER_COOLDOWN_S = 8.0


def run_chaos_arm(pod_faults, breaker: bool, verify_integrity: bool,
                  qps: float = QPS, chaos_stack: bool = True):
    """One round-robin two-tier replay of the chat workload under a
    per-peer transfer fault plan, in the winning-regime model class (the
    wide-MQA int8-KV constants where the transfer-vs-recompute gate
    ADMITS peer onboards — the dense-model constants gate the data plane
    shut, which would hide every fault). Returns TTFTs, hit rate, and the
    chaos bookkeeping (injector counters, fetch log, breaker
    transitions). `chaos_stack=False` runs the identical configuration
    with NO wrapper/breaker/injector at all — the bit-identity control."""
    alpha_w, gamma_w, delta_w, _src = _winning_regime_constants()
    requests, conversations, rng = build_workload(qps=qps)
    sim = FleetSim(
        "round_robin",
        pages_per_pod=TWO_TIER_PAGES_PER_POD,
        host_tier=True,
        alpha=alpha_w, gamma=gamma_w, delta=delta_w,
        transfer_faults=(
            {
                "pods": pod_faults,
                "verify_integrity": verify_integrity,
                "breaker": (
                    {
                        "failure_threshold": CHAOS_BREAKER_THRESHOLD,
                        "cooldown_s": CHAOS_BREAKER_COOLDOWN_S,
                    }
                    if breaker else None
                ),
                "io_timeout_ms": CHAOS_IO_TIMEOUT_MS,
                "connect_timeout_ms": CHAOS_CONNECT_TIMEOUT_MS,
                "retries": CHAOS_RETRIES,
            }
            if chaos_stack else None
        ),
    )
    # Order-independent peer choice for EVERY chaos arm (baseline
    # included): per-key index entry order races with the event pool's
    # concurrent workers, and the default first-entry primary would make
    # "which peer serves this block" — and therefore which blocks meet
    # the corrupt peer — run-to-run noise. Rendezvous-ranked holders are
    # a pure function of (chunk, pod), so the whole scenario replays
    # bit-for-bit.
    for pod in sim.pods:
        pod.tier_store.peer_resolver.rendezvous_primary = True
    ttfts = []
    try:
        for arrival, conv_id in requests:
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            ttfts.append(sim.serve(arrival, prompt))
            conversations[conv_id] = (
                prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
            )
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        injected = {}
        client_stats = {}
        fetch_log = []
        # Address -> pod name, for readable logs/windows.
        addr_names = {
            f"{h}:{p}": pod for pod, (h, p) in (sim._addrs or {}).items()
        }
        for pod_idx, wrapper in sim.faulty.items():
            for k, v in wrapper.counters.items():
                injected[k] = injected.get(k, 0) + v
            for k, v in wrapper.stats.items():
                client_stats[k] = client_stats.get(k, 0) + v
            fetch_log.extend(
                (t, f"pod-{pod_idx}", addr_names.get(peer, peer), lat, kind)
                for t, peer, lat, kind in wrapper.fetch_log
            )
        fetch_log.sort()
        return {
            "ttfts": ttfts,
            "hit_rate": hit_rate,
            "restored_blocks": sim.restored_blocks,
            "onboarded_blocks": sim.onboarded_blocks,
            "injected": injected,
            "client_stats": client_stats,
            "fetch_log": fetch_log,
            # Unrounded: the stall-window arithmetic compares these against
            # full-precision fetch timestamps; main_chaos rounds for the
            # artifact only.
            "breaker_transitions": [
                (t, observer, addr_names.get(peer, peer), old, new)
                for t, observer, peer, old, new in sim.breaker_transitions
            ],
            "health": (
                sim.health.transfer_breaker_summary()
                if sim.health is not None else None
            ),
        }
    finally:
        sim.shutdown()


def _chaos_fetch_p99(arm, pod: str, open_times, t_until: float):
    """p99 of per-fetch latencies charged against `pod`, taken per
    OBSERVER: each fetching pod's fetches count from the moment ITS
    breaker for `pod` opened (`open_times`: observer -> open_t) until
    `t_until`. Breakers are client-side failure memory — "after the
    breaker opens" is only meaningful per observer; a fleet-wide window
    would keep counting other pods' bounded detection ladders as tail
    latency the breaker never promised to remove."""
    # Strictly after the open: sim time is frozen within one request, so
    # the detection ladders that OPENED the breaker share its timestamp —
    # they are the (separately reported) detection cost, not post-open
    # tail. The control arm gets the same strict cutoffs, symmetrically.
    lats = sorted(
        lat for t, observer, peer, lat, _kind in arm["fetch_log"]
        if peer == pod
        and observer in open_times
        and open_times[observer] < t < t_until
    )
    if not lats:
        return None, 0
    return lats[min(int(len(lats) * 0.99), len(lats) - 1)], len(lats)


def _chaos_arm_stats(arm):
    return {
        "ttft_p50_s": round(p50(arm["ttfts"]), 4),
        "ttft_p90_s": round(p90(arm["ttfts"]), 4),
        "prefix_hit_rate": round(arm["hit_rate"], 4),
        "restored_blocks": arm["restored_blocks"],
        "onboarded_blocks": arm["onboarded_blocks"],
        "injected": arm["injected"],
        "hedges": arm["client_stats"].get("hedges", 0),
        "hedge_wins": arm["client_stats"].get("hedge_wins", 0),
        "corrupt_blocks_detected": arm["client_stats"].get(
            "corrupt_blocks", 0
        ),
        "breaker_skipped_blocks": arm["client_stats"].get(
            "breaker_skipped_blocks", 0
        ),
        "transfer_failures": arm["client_stats"].get("failures", 0),
    }


def main_chaos(args):
    from llm_d_kv_cache_manager_tpu.kv_connectors.faults import (
        PeerTransferFaults,
    )

    t_start = time.time()
    corrupt_faults = {
        CHAOS_CORRUPT_POD: PeerTransferFaults(
            corrupt_rate=CHAOS_CORRUPT_RATE
        ),
    }
    stall_faults = {
        CHAOS_STALL_POD: PeerTransferFaults(
            stall_from_s=CHAOS_STALL_FROM_S,
            stall_until_s=CHAOS_STALL_UNTIL_S,
        ),
    }

    baseline_plain = run_chaos_arm(
        {}, breaker=True, verify_integrity=True, chaos_stack=False
    )
    no_fault = run_chaos_arm({}, breaker=True, verify_integrity=True)
    corrupt_on = run_chaos_arm(
        corrupt_faults, breaker=True, verify_integrity=True
    )
    corrupt_off = run_chaos_arm(
        corrupt_faults, breaker=True, verify_integrity=False
    )
    stall_nb = run_chaos_arm(
        stall_faults, breaker=False, verify_integrity=True
    )
    stall_b = run_chaos_arm(
        stall_faults, breaker=True, verify_integrity=True
    )

    # Stall tail latency AFTER the breaker opened. Breakers are
    # CLIENT-side failure memory — every fetching pod keeps its own for
    # the stalled peer and pays its own bounded detection cost
    # (threshold x timeout ladder) before opening — so the measurement is
    # per OBSERVER: each pod's fetches to the stalled peer count from the
    # moment its own breaker opened. The no-breaker control arm gets the
    # SAME per-observer cutoffs (the faults-bench precedent), so its p99
    # reads "what those same fetches would have cost without breakers".
    # The detection cost the breaker arm DID pay is reported alongside
    # (detection_fetches = full-ladder fetches before each open).
    open_times = {}
    for t, observer, peer, old, new in stall_b["breaker_transitions"]:
        if (
            peer == CHAOS_STALL_POD and new == "open" and old == "closed"
            and observer not in open_times
        ):
            open_times[observer] = t
    stall_window = {}
    if open_times:
        p99_b, n_b = _chaos_fetch_p99(
            stall_b, CHAOS_STALL_POD, open_times, CHAOS_STALL_UNTIL_S
        )
        p99_nb, n_nb = _chaos_fetch_p99(
            stall_nb, CHAOS_STALL_POD, open_times, CHAOS_STALL_UNTIL_S
        )
        stall_window = {
            "first_open_at_s": round(min(open_times.values()), 3),
            "last_open_at_s": round(max(open_times.values()), 3),
            "observers_opened": len(open_times),
            "detection_fetches": stall_b["injected"].get(
                "stalled_fetches", 0
            ),
            "window_until_s": CHAOS_STALL_UNTIL_S,
            "fetches_with_breaker": n_b,
            "fetches_no_breaker": n_nb,
            "p99_fetch_s_with_breaker": (
                round(p99_b, 4) if p99_b is not None else None
            ),
            "p99_fetch_s_no_breaker": (
                round(p99_nb, 4) if p99_nb is not None else None
            ),
            "p99_ratio": (
                round(p99_b / p99_nb, 4)
                if p99_b is not None and p99_nb else None
            ),
        }
    # Half-open recovery after the stall clears: the breaker must have
    # re-closed (a probe succeeded against the recovered peer).
    reclosed = any(
        peer == CHAOS_STALL_POD and new == "closed"
        and t > CHAOS_STALL_UNTIL_S
        for t, _obs, peer, _old, new in stall_b["breaker_transitions"]
    )

    arms = {
        "no_fault": _chaos_arm_stats(no_fault),
        "corrupt_integrity_on": _chaos_arm_stats(corrupt_on),
        "corrupt_integrity_off": _chaos_arm_stats(corrupt_off),
        "stall_no_breaker": _chaos_arm_stats(stall_nb),
        "stall_breaker": _chaos_arm_stats(stall_b),
    }
    arms["stall_breaker"]["breaker_transitions"] = [
        (round(t, 3), observer, peer, old, new)
        for t, observer, peer, old, new in stall_b["breaker_transitions"]
    ]
    arms["stall_breaker"]["transfer_breaker_recovered"] = reclosed

    nf, con = arms["no_fault"], arms["corrupt_integrity_on"]
    stats = {
        "config": {
            "workload": (
                "synthetic chat (build_workload), round-robin routing over "
                "the two-tier fleet in the winning-regime model class "
                "(wide-MQA int8-KV constants — the gate ADMITS peer "
                "onboards; the dense-model constants gate the data plane "
                "shut and would hide every fault). Cache-oblivious routing "
                "maximizes peer-onboard traffic, the plane under test."
            ),
            "requests": len(no_fault["ttfts"]),
            "qps": QPS,
            "n_pods": N_PODS,
            "pages_per_pod": TWO_TIER_PAGES_PER_POD,
            "seed": args.seed,
            "corrupt_pod": CHAOS_CORRUPT_POD,
            "corrupt_rate": CHAOS_CORRUPT_RATE,
            "stall_pod": CHAOS_STALL_POD,
            "stall_window_s": [CHAOS_STALL_FROM_S, CHAOS_STALL_UNTIL_S],
            "io_timeout_ms": CHAOS_IO_TIMEOUT_MS,
            "retries": CHAOS_RETRIES,
            "breaker": {
                "failure_threshold": CHAOS_BREAKER_THRESHOLD,
                "cooldown_s": CHAOS_BREAKER_COOLDOWN_S,
            },
        },
        "arms": arms,
        # The headline robustness verdicts.
        "corrupt_blocks_admitted_with_integrity": corrupt_on["injected"].get(
            "corrupt_admitted", 0
        ),
        "corrupt_blocks_detected": corrupt_on["injected"].get(
            "corrupt_detected", 0
        ),
        "corrupt_blocks_admitted_without_integrity": corrupt_off[
            "injected"
        ].get("corrupt_admitted", 0),
        "hit_rate_retention_corrupt": round(
            con["prefix_hit_rate"] / max(nf["prefix_hit_rate"], 1e-9), 4
        ),
        "stall_tail_latency": stall_window,
        "wall_s": round(time.time() - t_start, 1),
    }
    # Healthy-fleet bit-identity: the no-fault arm (integrity verification,
    # breakers, and the fault wrapper all ACTIVE — just zero faults) must
    # reproduce the IDENTICAL run with no chaos stack at all, TTFT stream
    # and hit rate bit-for-bit — hardening a healthy fleet costs nothing.
    stats["healthy_bit_identity"] = {
        "ttft_stream_identical": (
            no_fault["ttfts"] == baseline_plain["ttfts"]
        ),
        "hit_rate_identical": (
            no_fault["hit_rate"] == baseline_plain["hit_rate"]
        ),
        "onboards_identical": (
            no_fault["onboarded_blocks"] == baseline_plain["onboarded_blocks"]
            and no_fault["restored_blocks"]
            == baseline_plain["restored_blocks"]
        ),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_CHAOS.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "chaos_corrupt_blocks_admitted",
        "value": stats["corrupt_blocks_admitted_with_integrity"],
        "unit": "blocks",
        "corrupt_detected": stats["corrupt_blocks_detected"],
        "corrupt_admitted_without_integrity": stats[
            "corrupt_blocks_admitted_without_integrity"
        ],
        "hit_rate_retention_corrupt": stats["hit_rate_retention_corrupt"],
        "stall_p99_ratio": stall_window.get("p99_ratio"),
        "breaker_recovered_after_stall": reclosed,
        "source": "benchmarking/FLEET_BENCH_CHAOS.json",
    }))


# Index anti-entropy divergence scenario (--divergence; antientropy/ +
# Index.remove_entries): the index silently diverging from reality inside
# HEALTHY-looking pods — the failure family neither fleethealth (streams
# stay perfect) nor the chaos stack (the wire stays honest) can see. Two
# fault shapes, each with a reconciled arm (trust tracker + residency
# audits + fetch-miss feedback + resolver negative cache) and an
# unreconciled control:
#
#   silent evictor  precise-routed chat fleet; one pod's cache is wiped
#                   repeatedly (engine + host store replaced cold) while
#                   its event stream continues seamlessly — every
#                   pre-wipe index entry is phantom. Control: the router
#                   keeps sending conversations to their phantom
#                   full-chain scores (full recompute instead of the
#                   group-prefix hit a REAL holder would give). With
#                   anti-entropy: the next audit round catches the pod
#                   lying on its sample, purges the sampled phantoms, and
#                   the trust EWMA demotes the REST of its phantom scores
#                   below the real holders'; clean audits after the wipes
#                   stop recover the pod (trust timeline committed).
#   phantom advertiser  two-tier round-robin fleet (the chaos bench's
#                   data-plane configuration); one pod re-advertises
#                   other pods' stored chains as its own for a window.
#                   Control: rendezvous keeps electing the phantom as
#                   primary holder, and every such fetch buys an explicit
#                   per-block "missing" answer — wasted round trips for
#                   the whole replay. With anti-entropy: the first miss
#                   purges the (pod, block) entry and its advertised
#                   chain suffix, the negative cache stops the immediate
#                   re-pick, and audits sweep the rest — wasted fetches
#                   driven to ~0 after detection.
#
# Both families carry a no-fault pair (full stack attached, zero faults)
# pinned bit-identical to the stack-free run — reconciliation on a
# truthful fleet costs nothing.
DIVERGENCE_WIPE_POD = "pod-3"
DIVERGENCE_WIPE_AT_S = 4.5
DIVERGENCE_WIPE_EVERY_S = 1.5
# Wipes stop here so the tail of the replay carries enough clean audit
# rounds for the trust EWMA to recover to factor 1.0 — the recovery leg
# is part of the arm's evidence, not an afterthought.
DIVERGENCE_WIPE_UNTIL_S = 10.5
DIVERGENCE_PHANTOM_POD = "pod-3"
DIVERGENCE_PHANTOM_RATE = 0.5
# A burst advertiser (a restarted engine re-announcing a stale manifest):
# the lying window closes at 6s, so "wasted fetches after detection" is a
# well-posed number — the control keeps paying for the advertised-once
# phantoms for the rest of the replay, the reconciled arm purges them.
DIVERGENCE_PHANTOM_FROM_S = 2.0
DIVERGENCE_PHANTOM_UNTIL_S = 6.0
# Late-window wasted-fetch meter: from here (well past both the lying
# window and the reconciled arm's first repair) to the end of the replay.
DIVERGENCE_LATE_FROM_S = 8.0
DIVERGENCE_AE_CFG = {
    "audit_interval_s": 1.0,
    "audit_sample": 24,
    "readmit_sample": 32,
    "negative_ttl_s": 3.0,
    # Faster EWMA than the production default: the replay is ~15s of sim
    # time, so both the distrust drop and the clean-audit recovery must
    # land inside it.
    "accuracy_alpha": 0.4,
}


def _divergence_wipe_plan(seed: int):
    from llm_d_kv_cache_manager_tpu.fleethealth import FaultPlan, PodFaults

    return FaultPlan(seed=seed, pods={
        DIVERGENCE_WIPE_POD: PodFaults(
            silent_wipe_at_s=DIVERGENCE_WIPE_AT_S,
            silent_wipe_every_s=DIVERGENCE_WIPE_EVERY_S,
            silent_wipe_until_s=DIVERGENCE_WIPE_UNTIL_S,
        ),
    })


def _divergence_phantom_plan(seed: int):
    from llm_d_kv_cache_manager_tpu.fleethealth import FaultPlan, PodFaults

    return FaultPlan(seed=seed, pods={
        DIVERGENCE_PHANTOM_POD: PodFaults(
            phantom_advertise_rate=DIVERGENCE_PHANTOM_RATE,
            phantom_from_s=DIVERGENCE_PHANTOM_FROM_S,
            phantom_until_s=DIVERGENCE_PHANTOM_UNTIL_S,
        ),
    })


def run_divergence_scoring_arm(fault_plan, antientropy: bool,
                               qps: float = QPS):
    """One precise-arm chat replay under a silent-wipe plan (or none),
    with or without the anti-entropy stack. Returns per-request records
    plus the repair bookkeeping (trust timeline of the wiped pod,
    auditor/tracker stats).

    Every group's shared system prefix is primed on TWO pods before the
    replay (deterministic route_override warm-up, identical in every
    arm). Precise routing otherwise concentrates each group on exactly
    one pod — and a wiped pod whose chains have NO other holder hurts
    the reconciled and control arms identically (the recompute is
    unavoidable; routing can't improve on it). With a second holder the
    failure becomes the one the subsystem exists for: the control keeps
    chasing the wiped pod's phantom full-chain scores into full
    recomputes, while a reconciled router — phantoms purged, trust
    demoted — falls back to the real holder's group prefix."""
    requests, conversations, rng = build_workload(qps=qps)
    sim = FleetSim(
        "precise",
        fault_plan=fault_plan,
        antientropy=dict(DIVERGENCE_AE_CFG) if antientropy else None,
    )
    records = []
    trust_timeline = []  # (arrival, wiped pod's demotion factor)
    first_repair_at = None
    try:
        # Two-holder warm-up: group g's system prefix lands on pods
        # (g mod N) and (g+3 mod N). Primer requests are not recorded —
        # the replay's records are the measured population.
        groups = {}
        for conv_id in conversations:
            groups.setdefault(conv_id.split("-")[0], conversations[conv_id])
        t = 0.0
        for gi, group in enumerate(sorted(groups)):
            for target in (gi % sim.n_pods, (gi + 3) % sim.n_pods):
                sim.route_override = lambda p, pod=target: pod
                sim.serve(t, groups[group])
                t += 0.02
        sim.route_override = None
        for arrival, conv_id in requests:
            # Replay shifted past the warm-up phase (sim time must not go
            # backwards); the fault plan's windows are absolute sim time.
            arrival += 1.0
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            h0, t0 = sim.hit_tokens, sim.total_tokens
            ttft = sim.serve(arrival, prompt)
            records.append(
                (arrival, ttft, sim.hit_tokens - h0, sim.total_tokens - t0)
            )
            conversations[conv_id] = (
                prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
            )
            if sim.antientropy is not None:
                factor = sim.antientropy.factor_for(DIVERGENCE_WIPE_POD)
                if not trust_timeline or trust_timeline[-1][1] != factor:
                    trust_timeline.append((round(arrival, 3), round(factor, 4)))
                if (
                    first_repair_at is None
                    and sim.auditor.stats["phantoms_purged"] > 0
                ):
                    first_repair_at = round(arrival, 3)
        sim.event_pool.drain()
        return {
            "records": records,
            "ttfts": [r[1] for r in records],
            "silent_wipes": [
                (round(t, 3), i) for t, i in sim.silent_wipes
            ],
            "trust_timeline": trust_timeline,
            "first_repair_at_s": first_repair_at,
            "tracker": (
                sim.antientropy.status() if sim.antientropy else None
            ),
            "auditor": sim.auditor.status() if sim.auditor else None,
        }
    finally:
        sim.shutdown()


def run_divergence_dataplane_arm(fault_plan, antientropy: bool,
                                 qps: float = QPS):
    """One two-tier round-robin chat replay (the chaos bench's winning-
    regime data-plane configuration) under a phantom-advertiser plan (or
    none), with or without the anti-entropy stack. The wasted-fetch meter
    (explicit per-block "missing" answers from peers) runs in EVERY arm —
    measurement only, no repair — so control and reconciled arms report
    the same evidence stream."""
    alpha_w, gamma_w, delta_w, _src = _winning_regime_constants()
    requests, conversations, rng = build_workload(qps=qps)
    sim = FleetSim(
        "round_robin",
        pages_per_pod=TWO_TIER_PAGES_PER_POD,
        host_tier=True,
        alpha=alpha_w, gamma=gamma_w, delta=delta_w,
        fault_plan=fault_plan,
        antientropy=dict(DIVERGENCE_AE_CFG) if antientropy else None,
        measure_fetch_misses=True,
    )
    # Order-independent peer choice (the chaos bench precedent): per-key
    # index entry order races with the event pool's workers; rendezvous
    # holders make "which peer serves this block" — and therefore which
    # fetches meet the phantom — a pure function of (chunk, pod).
    for pod in sim.pods:
        pod.tier_store.peer_resolver.rendezvous_primary = True
    ttfts = []
    first_repair_at = None
    try:
        for arrival, conv_id in requests:
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            ttfts.append(sim.serve(arrival, prompt))
            conversations[conv_id] = (
                prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
            )
            if (
                first_repair_at is None
                and sim.fetch_feedback is not None
                and sim.fetch_feedback.stats["purged_entries"] > 0
            ):
                first_repair_at = round(arrival, 3)
        sim.event_pool.drain()
        negative_skips = sum(
            pod.tier_store.peer_resolver.negative_skips for pod in sim.pods
        )
        return {
            "ttfts": ttfts,
            "hit_rate": sim.hit_tokens / max(sim.total_tokens, 1),
            "restored_blocks": sim.restored_blocks,
            "onboarded_blocks": sim.onboarded_blocks,
            "fetch_miss_log": list(sim.fetch_miss_log),
            "first_repair_at_s": first_repair_at,
            "negative_skips": negative_skips,
            "feedback": (
                sim.fetch_feedback.status() if sim.fetch_feedback else None
            ),
            "tracker": (
                sim.antientropy.status() if sim.antientropy else None
            ),
            "auditor": sim.auditor.status() if sim.auditor else None,
            "injected": (
                dict(sim.injector.injected) if sim.injector else None
            ),
        }
    finally:
        sim.shutdown()


def _wasted_fetches(arm, peer: str, t_from=None, t_until=None) -> int:
    """Explicit per-block "missing" answers peers got from `peer` in the
    window — round trips the index's phantom advertisements bought."""
    total = 0
    for t, _observer, p, n in arm["fetch_miss_log"]:
        if p != peer:
            continue
        if t_from is not None and t < t_from:
            continue
        if t_until is not None and t >= t_until:
            continue
        total += n
    return total


def main_divergence(args):
    t_start = time.time()
    wipe_plan = _divergence_wipe_plan(args.seed)
    phantom_plan = _divergence_phantom_plan(args.seed)

    # Scoring plane (silent evictor), precise arm.
    nf_plain = run_divergence_scoring_arm(None, antientropy=False)
    nf_ae = run_divergence_scoring_arm(None, antientropy=True)
    se_ae = run_divergence_scoring_arm(wipe_plan, antientropy=True)
    se_ctl = run_divergence_scoring_arm(wipe_plan, antientropy=False)

    # Data plane (phantom advertiser), two-tier round-robin arm.
    ph_nf_plain = run_divergence_dataplane_arm(None, antientropy=False)
    ph_nf_ae = run_divergence_dataplane_arm(None, antientropy=True)
    ph_ae = run_divergence_dataplane_arm(phantom_plan, antientropy=True)
    ph_ctl = run_divergence_dataplane_arm(phantom_plan, antientropy=False)

    def scoring_stats(arm):
        records = arm["records"]
        out = {
            "ttft_p50_s": round(p50(arm["ttfts"]), 4),
            "ttft_p90_s": round(p90(arm["ttfts"]), 4),
            "prefix_hit_rate": round(_window_hit_rate(records), 4),
            "post_fault_hit_rate": round(
                _window_hit_rate(records, t_from=DIVERGENCE_WIPE_AT_S), 4
            ),
        }
        if arm["silent_wipes"]:
            out["silent_wipes"] = arm["silent_wipes"]
        if arm["tracker"] is not None:
            totals = arm["tracker"]["totals"]
            out["phantoms_purged"] = totals["purged_entries"]
            out["blocks_readmitted"] = totals["readmitted_blocks"]
            out["audit_rounds"] = arm["auditor"]["rounds"]
            out["first_repair_at_s"] = arm["first_repair_at_s"]
        return out

    def dataplane_stats(arm):
        out = {
            "ttft_p50_s": round(p50(arm["ttfts"]), 4),
            "ttft_p90_s": round(p90(arm["ttfts"]), 4),
            "prefix_hit_rate": round(arm["hit_rate"], 4),
            "restored_blocks": arm["restored_blocks"],
            "onboarded_blocks": arm["onboarded_blocks"],
            "wasted_fetch_blocks": _wasted_fetches(
                arm, DIVERGENCE_PHANTOM_POD
            ),
            "wasted_fetch_blocks_late_window": _wasted_fetches(
                arm, DIVERGENCE_PHANTOM_POD,
                t_from=DIVERGENCE_LATE_FROM_S,
            ),
        }
        if arm["injected"] is not None:
            out["phantom_advertised"] = arm["injected"].get(
                "phantom_advertised", 0
            )
        if arm["tracker"] is not None:
            out["first_repair_at_s"] = arm["first_repair_at_s"]
            out["purged_entries"] = arm["tracker"]["totals"]["purged_entries"]
            out["negative_skips"] = arm["negative_skips"]
            out["feedback"] = arm["feedback"]
        return out

    arms = {
        "scoring_no_fault_plain": scoring_stats(nf_plain),
        "scoring_no_fault_antientropy": scoring_stats(nf_ae),
        "silent_evict_antientropy": scoring_stats(se_ae),
        "silent_evict_control": scoring_stats(se_ctl),
        "dataplane_no_fault_plain": dataplane_stats(ph_nf_plain),
        "dataplane_no_fault_antientropy": dataplane_stats(ph_nf_ae),
        "phantom_antientropy": dataplane_stats(ph_ae),
        "phantom_control": dataplane_stats(ph_ctl),
    }
    arms["silent_evict_antientropy"]["trust_timeline"] = se_ae[
        "trust_timeline"
    ]

    nf_post = arms["scoring_no_fault_plain"]["post_fault_hit_rate"]
    retention_ae = arms["silent_evict_antientropy"][
        "post_fault_hit_rate"
    ] / max(nf_post, 1e-9)
    retention_ctl = arms["silent_evict_control"][
        "post_fault_hit_rate"
    ] / max(nf_post, 1e-9)
    # Trust recovered = the wiped pod's demotion factor back at 1.0 by the
    # end of the replay (clean audits after the wipes stopped).
    trust_recovered = (
        bool(se_ae["trust_timeline"])
        and se_ae["trust_timeline"][-1][1] == 1.0
        and any(f < 1.0 for _t, f in se_ae["trust_timeline"])
    )

    stats = {
        "config": {
            "workload": (
                "synthetic chat (build_workload). Scoring family: precise "
                "routing, single-tier (the headline arm's configuration). "
                "Data-plane family: round-robin two-tier in the "
                "winning-regime model class (the chaos bench's "
                "configuration — cache-oblivious routing maximizes peer "
                "traffic, the plane under test)."
            ),
            "requests": len(nf_plain["records"]),
            "qps": QPS,
            "n_pods": N_PODS,
            "seed": args.seed,
            "wipe_plan": wipe_plan.as_dict(),
            "phantom_plan": phantom_plan.as_dict(),
            "antientropy": dict(DIVERGENCE_AE_CFG),
            "late_window_from_s": DIVERGENCE_LATE_FROM_S,
        },
        "arms": arms,
        # Headline verdicts.
        "silent_evict_hit_retention_antientropy": round(retention_ae, 4),
        "silent_evict_hit_retention_control": round(retention_ctl, 4),
        "silent_evict_trust_recovered": trust_recovered,
        "phantom_wasted_fetches_late_window_antientropy": arms[
            "phantom_antientropy"
        ]["wasted_fetch_blocks_late_window"],
        "phantom_wasted_fetches_late_window_control": arms[
            "phantom_control"
        ]["wasted_fetch_blocks_late_window"],
        # Healthy-fleet bit-identity: the full anti-entropy stack attached
        # (tracker at the score seam, auditor ticking every second,
        # fetch-miss callbacks wired) with zero faults must reproduce the
        # stack-free run bit-for-bit in BOTH families.
        "healthy_bit_identity": {
            "scoring_ttft_stream_identical": (
                nf_ae["ttfts"] == nf_plain["ttfts"]
            ),
            "scoring_hit_identical": (
                arms["scoring_no_fault_antientropy"]["prefix_hit_rate"]
                == arms["scoring_no_fault_plain"]["prefix_hit_rate"]
            ),
            "dataplane_ttft_stream_identical": (
                ph_nf_ae["ttfts"] == ph_nf_plain["ttfts"]
            ),
            "dataplane_hit_identical": (
                ph_nf_ae["hit_rate"] == ph_nf_plain["hit_rate"]
            ),
            "dataplane_tier_traffic_identical": (
                ph_nf_ae["onboarded_blocks"] == ph_nf_plain["onboarded_blocks"]
                and ph_nf_ae["restored_blocks"]
                == ph_nf_plain["restored_blocks"]
            ),
        },
        "wall_s": round(time.time() - t_start, 1),
    }
    # (The scoring family's no-fault arm is NOT the FLEET_BENCH precise
    # row: the two-holder warm-up phase precedes the replay in every
    # scoring arm, identically. The baseline it must — and does — match
    # bit-for-bit is its own stack-free twin; FLEET_BENCH.json
    # byte-identity with the feature off is verified by rerunning the
    # default bench, which never constructs the anti-entropy stack.)
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_DIVERGENCE.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "divergence_hit_retention_with_antientropy",
        "value": stats["silent_evict_hit_retention_antientropy"],
        "unit": "fraction",
        "control_retention": stats["silent_evict_hit_retention_control"],
        "trust_recovered": trust_recovered,
        "phantom_wasted_fetches_late_window": stats[
            "phantom_wasted_fetches_late_window_antientropy"
        ],
        "phantom_wasted_fetches_late_window_control": stats[
            "phantom_wasted_fetches_late_window_control"
        ],
        "source": "benchmarking/FLEET_BENCH_DIVERGENCE.json",
    }))


# SLO autopilot scenario (--autopilot; autopilot/ subsystem): one
# diurnal-load + fault-mix replay, served four ways. The fleet is the
# winning-regime two-tier configuration (precise routing, placement
# replication, residency audits, per-peer breakers + hedged fetches) and
# the scenario stacks three stressors the static configs trade off
# against each other:
#   qps swing        low -> peak -> low (the diurnal shape; queueing at
#                    the peak is where background replication charges
#                    show up in p50 TTFT),
#   stalling peer    AUTOPILOT_STALL_POD's transfer port hangs fetches
#                    for a window inside the peak (breaker evidence),
#   silent evictor   AUTOPILOT_WIPE_POD's cache is wiped on a cadence
#                    inside the peak, stream seamless (hit-rate burn the
#                    audit cadence exists to repair).
# Arms:
#   static_conservative  the baseline knob positions (K=1, small job
#                        budget, slow audits): cheapest background work,
#                        slowest divergence repair.
#   static_aggressive    K=3, doubled job budget, 8x audit cadence:
#                        fastest repair, but the replication charges ride
#                        the read path at the peak — p50 pays all day for
#                        resilience it needs for one window.
#   autopilot            starts bit-identical to static_conservative and
#                        lets the controller (autopilot/) move the SAME
#                        knobs the aggressive arm pins, only while the
#                        burn evidence says to, decaying back after.
#   healthy pair         the same replay with NO faults, controller
#                        attached vs absent — the bit-identity pin: on
#                        healthy signals the autopilot arm's TTFT stream,
#                        hit rate, and knob positions must be identical
#                        to not having the subsystem at all.
# SLO objectives are sim-backed (injected counts_fn closures over the
# arm's own counters — the seam obs/slo.py exposes for exactly this):
#   read_latency_p99  requests slower than AUTOPILOT_TTFT_SLO_S,
#   hit_rate          requests whose cached-token fraction fell under
#                     AUTOPILOT_HIT_FRAC_FLOOR.
# Burn-minutes = sim-time spent with ANY objective breaching (both
# windows over threshold), sampled on the same AUTOPILOT_EVAL_DT_S grid
# in every arm. The headline verdict: the controller arm's burn-minutes
# are <= every static arm's AND its p50 TTFT is within 1.05x the best
# static arm's — adaptivity buys the aggressive arm's compliance at the
# conservative arm's price.
AUTOPILOT_QPS_LOW = 12.0
AUTOPILOT_QPS_PEAK = 30.0
AUTOPILOT_PEAK_FROM_S = 10.0
AUTOPILOT_PEAK_UNTIL_S = 24.0
AUTOPILOT_USERS_PER_GROUP = 6
AUTOPILOT_TURNS_PER_USER = 8
# The wipe window is LONG relative to the controller's reaction time
# (~2-3s from first badness to knobs landed): a reactive repair covers
# most of the window, a scheduled-audit repair covers none of it.
AUTOPILOT_WIPE_PODS = ("pod-3", "pod-5")
AUTOPILOT_WIPE_AT_S = 11.0
AUTOPILOT_WIPE_EVERY_S = 1.0
AUTOPILOT_WIPE_UNTIL_S = 22.0
AUTOPILOT_STALL_POD = "pod-2"
# The stall covers the morning ramp — it opens BEFORE the chains cross
# the hotness threshold and closes before the wipes bite. An
# always-aggressive replicator spends the whole ramp retrying
# single-holder (unhedgeable) fetches against the hung port and carries
# those timeout charges into the peak; a conservative replicator never
# touches the stalled peer; the controller is still at its conservative
# baseline (nothing is burning yet), so by the time burn evidence makes
# it raise K the port is healthy again.
AUTOPILOT_STALL_FROM_S = 2.0
AUTOPILOT_STALL_UNTIL_S = 12.0
# Sim-scaled SLO/controller clocks (the replay is ~33s of sim time; the
# production defaults are 300s/3600s windows).
AUTOPILOT_EVAL_DT_S = 0.25
AUTOPILOT_SLO_FAST_S = 1.5
AUTOPILOT_SLO_SLOW_S = 4.0
AUTOPILOT_BURN_THRESHOLD = 2.0
# TTFT SLO sits ABOVE the cost of the biggest honest recompute (~1.4s
# for a late-turn wiped conversation) and BELOW one stalled-fetch
# timeout ladder (3.0s): the read-latency objective counts requests the
# transfer plane hung, not requests the hit-rate objective already
# counts as recompute badness.
AUTOPILOT_TTFT_SLO_S = 2.5
AUTOPILOT_TTFT_BUDGET = 0.01
# The healthy replay's worst per-request cached fraction is ~0.70 (a
# turn-1 request re-reading a primed group prefix); a wiped conversation
# that recovered only its group prefix re-serves at ~0.48. The floor
# sits between them.
AUTOPILOT_HIT_FRAC_FLOOR = 0.6
AUTOPILOT_HIT_BUDGET = 0.06
AUTOPILOT_CTRL_CFG = dict(
    min_interval_s=0.2, warmup_s=6.0, cooldown_s=1.0, decay_after_s=3.0,
)
# Knob baselines (the conservative operator config) and the aggressive
# arm's static pins. The autopilot arm's knob bounds derive from the
# BASELINES via the owners' register_knobs(): K ceiling 3, jobs ceiling
# 4, audit floor 1.0s — the aggressive positions are exactly reachable.
AUTOPILOT_PLACEMENT_BASE = dict(
    k_replicas=1, hotness_threshold=6.0, cooldown_s=2.0,
    max_jobs_per_tick=2, max_prefix_blocks=64,
)
AUTOPILOT_PLACEMENT_AGGR = dict(
    k_replicas=3, hotness_threshold=6.0, cooldown_s=2.0,
    max_jobs_per_tick=4, max_prefix_blocks=64,
)
AUTOPILOT_AUDIT_BASE_S = 8.0
AUTOPILOT_AUDIT_AGGR_S = 1.0
AUTOPILOT_HEDGE_FLOOR_BASE_S = 0.2
AUTOPILOT_HEDGE_FLOOR_AGGR_S = 0.05
AUTOPILOT_AE_CFG = {
    "audit_sample": 24,
    "readmit_sample": 32,
    "negative_ttl_s": 3.0,
    "accuracy_alpha": 0.4,
}
AUTOPILOT_BREAKER_THRESHOLD = 3
AUTOPILOT_BREAKER_COOLDOWN_S = 6.0
AUTOPILOT_IO_TIMEOUT_MS = 3000
AUTOPILOT_CONNECT_TIMEOUT_MS = 1500


def build_autopilot_workload(seed: int = 42):
    """(requests, conversations, rng): the synthetic chat shape with a
    diurnal arrival rate — Poisson at AUTOPILOT_QPS_LOW outside the
    [PEAK_FROM, PEAK_UNTIL) window, AUTOPILOT_QPS_PEAK inside it."""
    rng = random.Random(seed)
    conversations = shared_prefix_conversations(
        rng, N_GROUPS, AUTOPILOT_USERS_PER_GROUP, SYSTEM_PROMPT_WORDS
    )
    turns = []
    for conv_id in conversations:
        for t in range(AUTOPILOT_TURNS_PER_USER):
            turns.append((conv_id, t))
    rng.shuffle(turns)
    arrival = 0.0
    requests = []
    for conv_id, _t in turns:
        qps = (
            AUTOPILOT_QPS_PEAK
            if AUTOPILOT_PEAK_FROM_S <= arrival < AUTOPILOT_PEAK_UNTIL_S
            else AUTOPILOT_QPS_LOW
        )
        arrival += rng.expovariate(qps)
        requests.append((arrival, conv_id))
    return requests, conversations, rng


def _autopilot_fault_plans(seed: int, healthy: bool):
    """(wipe FaultPlan or None, transfer-stall peer dict): the fault mix,
    or the empty pair for the healthy bit-identity arms."""
    if healthy:
        return None, {}
    from llm_d_kv_cache_manager_tpu.fleethealth import FaultPlan, PodFaults
    from llm_d_kv_cache_manager_tpu.kv_connectors import faults as tf

    wipe_plan = FaultPlan(seed=seed, pods={
        pod: PodFaults(
            silent_wipe_at_s=AUTOPILOT_WIPE_AT_S,
            silent_wipe_every_s=AUTOPILOT_WIPE_EVERY_S,
            silent_wipe_until_s=AUTOPILOT_WIPE_UNTIL_S,
        )
        for pod in AUTOPILOT_WIPE_PODS
    })
    stall_faults = {
        AUTOPILOT_STALL_POD: tf.PeerTransferFaults(
            stall_from_s=AUTOPILOT_STALL_FROM_S,
            stall_until_s=AUTOPILOT_STALL_UNTIL_S,
        ),
    }
    return wipe_plan, stall_faults


def run_autopilot_arm(mode: str, healthy: bool = False, seed: int = 42):
    """One diurnal fault-mix replay. `mode`:
      'off'        conservative baseline knobs, no controller,
      'aggressive' the static aggressive knob pins, no controller,
      'autopilot'  conservative baselines + the closed-loop controller.
    Every arm runs the SAME subsystems (placement, anti-entropy,
    breakers/hedges, SLO monitor on the same evaluation grid); only the
    knob positions — static vs controlled — differ."""
    from llm_d_kv_cache_manager_tpu.autopilot import (
        AutopilotConfig,
        AutopilotController,
        KNOB_TRANSFER_HEDGE_FLOOR,
        KnobRegistry,
        KnobSpec,
        SignalAssembler,
    )
    from llm_d_kv_cache_manager_tpu.obs.slo import (
        OBJECTIVE_HIT_RATE,
        OBJECTIVE_READ_LATENCY,
        SLOConfig,
        SLOMonitor,
        SLOObjective,
    )

    aggressive = mode == "aggressive"
    alpha_w, gamma_w, delta_w, _src = _winning_regime_constants()
    requests, conversations, rng = build_autopilot_workload(seed)
    wipe_plan, stall_faults = _autopilot_fault_plans(seed, healthy)
    sim = FleetSim(
        "precise",
        # Oversized pods (2x the headline arm's 2048 pages, not the
        # two-tier capacity squeeze): the healthy diurnal peak must be
        # SLO-clean and free of device-eviction noise — burn in the
        # fault arms has to come from the faults, and the aggressive
        # arm's replication must not pay a hidden capacity tax.
        pages_per_pod=2 * PAGES_PER_POD,
        host_tier=True,
        alpha=alpha_w, gamma=gamma_w, delta=delta_w,
        fault_plan=wipe_plan,
        placement=dict(
            AUTOPILOT_PLACEMENT_AGGR if aggressive
            else AUTOPILOT_PLACEMENT_BASE
        ),
        antientropy=dict(
            AUTOPILOT_AE_CFG,
            audit_interval_s=(
                AUTOPILOT_AUDIT_AGGR_S if aggressive
                else AUTOPILOT_AUDIT_BASE_S
            ),
            seed=seed,
        ),
        transfer_faults={
            "pods": stall_faults,
            "verify_integrity": True,
            "breaker": {
                "failure_threshold": AUTOPILOT_BREAKER_THRESHOLD,
                "cooldown_s": AUTOPILOT_BREAKER_COOLDOWN_S,
            },
            "io_timeout_ms": AUTOPILOT_IO_TIMEOUT_MS,
            "connect_timeout_ms": AUTOPILOT_CONNECT_TIMEOUT_MS,
            "retries": 0,
        },
    )
    # Deterministic peer choice (the chaos/divergence precedent) + the
    # arm's hedge-floor position on every pod's client.
    hedge_floor = (
        AUTOPILOT_HEDGE_FLOOR_AGGR_S if aggressive
        else AUTOPILOT_HEDGE_FLOOR_BASE_S
    )
    for pod in sim.pods:
        pod.tier_store.peer_resolver.rendezvous_primary = True
        pod.connector.client.config.hedge_delay_floor_s = hedge_floor

    ttfts = []
    records = []  # (arrival, ttft, hit_tokens, total_tokens)
    slow_reqs = [0]
    bad_hit_reqs = [0]
    total_reqs = [0]
    try:
        # Sole-holder warm-up (identical in every arm; primer requests
        # are not part of the measured population): group g's system
        # prefix lands on pod (g mod N) and NOWHERE else — a wiped pod's
        # groups have no free fallback. Second holders exist only where
        # a replication policy (static pin or controller nudge) pays to
        # create them.
        groups = {}
        for conv_id in conversations:
            groups.setdefault(conv_id.split("-")[0], conversations[conv_id])
        t = 0.0
        for gi, group in enumerate(sorted(groups)):
            sim.route_override = lambda p, pod=gi % sim.n_pods: pod
            sim.serve(t, groups[group])
            t += 0.02
        sim.route_override = None

        # Sim-backed SLO monitor (constructed after the warm-up so its
        # baseline sample excludes priming spend); identical config and
        # evaluation grid in every arm.
        objectives = [
            SLOObjective(
                name=OBJECTIVE_READ_LATENCY,
                description=(
                    f"requests with TTFT > {AUTOPILOT_TTFT_SLO_S}s"
                ),
                budget=AUTOPILOT_TTFT_BUDGET,
                counts_fn=lambda: (slow_reqs[0], total_reqs[0]),
            ),
            SLOObjective(
                name=OBJECTIVE_HIT_RATE,
                description=(
                    "requests whose cached-token fraction fell under "
                    f"{AUTOPILOT_HIT_FRAC_FLOOR}"
                ),
                budget=AUTOPILOT_HIT_BUDGET,
                counts_fn=lambda: (bad_hit_reqs[0], total_reqs[0]),
            ),
        ]
        monitor = SLOMonitor(
            objectives,
            SLOConfig(
                fast_window_s=AUTOPILOT_SLO_FAST_S,
                slow_window_s=AUTOPILOT_SLO_SLOW_S,
                burn_threshold=AUTOPILOT_BURN_THRESHOLD,
            ),
            clock=lambda: sim.now,
        )

        controller = None
        registry = None
        if mode == "autopilot":
            registry = KnobRegistry()
            sim.replicator.register_knobs(registry)
            sim.auditor.register_knobs(registry)
            # Fleet-wide hedge-floor knob: the sim owns ALL pods' clients,
            # so it publishes one knob whose setter fans out (the service
            # wiring registers the single default client's instead).
            cfg0 = sim.pods[0].connector.client.config

            def _set_hedge_floor(v):
                for p in sim.pods:
                    p.connector.client.config.hedge_delay_floor_s = float(v)

            registry.register(
                KnobSpec(
                    name=KNOB_TRANSFER_HEDGE_FLOOR,
                    floor=min(0.001, cfg0.hedge_delay_floor_s),
                    ceiling=cfg0.hedge_delay_cap_s,
                    max_step=max(cfg0.hedge_delay_floor_s / 2.0, 0.001),
                    description=(
                        "minimum delay before a hedged fetch launches "
                        "(fleet-wide)"
                    ),
                ),
                get=lambda: cfg0.hedge_delay_floor_s,
                set_=_set_hedge_floor,
            )

            def _agg_transfer_status():
                peers: dict = {}
                for p in sim.pods:
                    for key, doc in (
                    p.connector.client.status().get("peers", {}).items()
                ):
                        agg = peers.setdefault(
                            key, {"state": "closed", "opens": 0}
                        )
                        if doc.get("state") == "open":
                            agg["state"] = "open"
                        agg["opens"] += int(doc.get("opens", 0))
                return {"peers": peers}

            class _FleetTransferStatus:
                def status(self):
                    return _agg_transfer_status()

            assembler = SignalAssembler(
                slo_monitor=monitor,
                transfer_client=_FleetTransferStatus(),
                antientropy=sim.antientropy,
                prefetchers={"route": sim.route_prefetcher},
                clock=lambda: sim.now,
            )
            controller = AutopilotController(
                registry, assembler,
                config=AutopilotConfig(**AUTOPILOT_CTRL_CFG),
                clock=lambda: sim.now,
            )

        # The replay, shifted past the warm-up (fault windows are
        # absolute sim time). One evaluation grid drives the monitor in
        # every arm — and the controller in the autopilot arm.
        shift = 1.0
        burn_timeline = []  # (t, breaching objective names)
        knob_timeline = []  # (t, {knob: position}) — autopilot arm only
        next_eval = shift

        def _evaluate(now):
            if controller is not None:
                controller.tick(now)
                snap = controller.last_snapshot
                breaching = list(snap.breaching) if snap else []
            else:
                breaching = list(monitor.evaluate(now)["breaching"])
            burn_timeline.append((round(now, 3), breaching))
            if registry is not None and not registry.at_baseline():
                knob_timeline.append((
                    round(now, 3),
                    {
                        name: doc["position"]
                        for name, doc in registry.positions().items()
                    },
                ))

        for arrival, conv_id in requests:
            arrival += shift
            while next_eval <= arrival:
                _evaluate(next_eval)
                next_eval += AUTOPILOT_EVAL_DT_S
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            h0, t0 = sim.hit_tokens, sim.total_tokens
            ttft = sim.serve(arrival, prompt)
            ttfts.append(ttft)
            d_hit = sim.hit_tokens - h0
            d_total = sim.total_tokens - t0
            records.append((arrival, ttft, d_hit, d_total))
            total_reqs[0] += 1
            if ttft > AUTOPILOT_TTFT_SLO_S:
                slow_reqs[0] += 1
            if d_total > 0 and d_hit / d_total < AUTOPILOT_HIT_FRAC_FLOOR:
                bad_hit_reqs[0] += 1
            conversations[conv_id] = (
                prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
            )
        # Cool-down tail: keep evaluating past the last arrival so the
        # decay path (knobs walking home) is part of the record.
        tail_until = requests[-1][0] + shift + 4.0
        while next_eval <= tail_until:
            _evaluate(next_eval)
            next_eval += AUTOPILOT_EVAL_DT_S
        sim.event_pool.drain()

        breaker_opens = sum(
            1 for _t, _obs, _peer, _old, new in sim.breaker_transitions
            if new == "open"
        )
        return {
            "ttfts": ttfts,
            "records": records,
            "hit_rate": sim.hit_tokens / max(sim.total_tokens, 1),
            "burn_timeline": burn_timeline,
            "knob_timeline": knob_timeline,
            "slow_requests": slow_reqs[0],
            "bad_hit_requests": bad_hit_reqs[0],
            "silent_wipes": [(round(t, 3), i) for t, i in sim.silent_wipes],
            "breaker_opens": breaker_opens,
            "preemptions": sim.preemptions,
            "replication": sim.placement_stats(),
            "auditor": sim.auditor.status() if sim.auditor else None,
            "knob_positions": (
                {
                    name: doc["position"]
                    for name, doc in registry.positions().items()
                }
                if registry is not None else None
            ),
            "controller": (
                controller.status() if controller is not None else None
            ),
        }
    finally:
        sim.shutdown()


def _burn_minutes(timeline) -> float:
    """Sim-minutes with ANY objective breaching, on the shared grid."""
    return round(
        sum(AUTOPILOT_EVAL_DT_S for _t, breaching in timeline if breaching)
        / 60.0,
        4,
    )


def main_autopilot(args):
    """--autopilot: the closed-loop controller comparison. Writes
    benchmarking/FLEET_BENCH_AUTOPILOT.json."""
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
        native_available,
    )

    if not native_available():
        print(json.dumps({
            "metric": "autopilot_burn_minutes",
            "value": None,
            "skipped": "libkvtransfer.so not built (make kvtransfer)",
        }))
        return

    t_start = time.time()
    arms_raw = {
        "static_conservative": run_autopilot_arm("off", seed=args.seed),
        "static_aggressive": run_autopilot_arm(
            "aggressive", seed=args.seed
        ),
        "autopilot": run_autopilot_arm("autopilot", seed=args.seed),
        "healthy_off": run_autopilot_arm("off", healthy=True, seed=args.seed),
        "healthy_autopilot": run_autopilot_arm(
            "autopilot", healthy=True, seed=args.seed
        ),
    }

    def arm_stats(arm, with_knobs=False):
        out = {
            "ttft_p50_s": round(p50(arm["ttfts"]), 4),
            "ttft_p90_s": round(p90(arm["ttfts"]), 4),
            "prefix_hit_rate": round(arm["hit_rate"], 4),
            "burn_minutes": _burn_minutes(arm["burn_timeline"]),
            "slow_requests": arm["slow_requests"],
            "bad_hit_requests": arm["bad_hit_requests"],
            "breaker_opens": arm["breaker_opens"],
            "preemptions": arm["preemptions"],
            "replicated_blocks": arm["replication"].get(
                "replicated_blocks", 0
            ),
            "replication_charged_s": arm["replication"].get(
                "replication_charged_s", 0.0
            ),
            "audit_rounds": (
                arm["auditor"]["rounds"] if arm["auditor"] else 0
            ),
        }
        if arm["silent_wipes"]:
            out["silent_wipes"] = arm["silent_wipes"]
        if with_knobs and arm["controller"] is not None:
            ctrl = arm["controller"]
            out["actuations"] = ctrl["stats"]["actuations"]
            out["reverts"] = ctrl["stats"]["reverts"]
            out["rules_fired"] = {
                name: doc["fired"]
                for name, doc in ctrl["rules"].items() if doc["fired"]
            }
            out["final_at_baseline"] = ctrl["at_baseline"]
            out["recent_actuations"] = ctrl["recent_actuations"]
            out["knob_timeline"] = arm["knob_timeline"]
        return out

    arms = {
        "static_conservative": arm_stats(arms_raw["static_conservative"]),
        "static_aggressive": arm_stats(arms_raw["static_aggressive"]),
        "autopilot": arm_stats(arms_raw["autopilot"], with_knobs=True),
        "healthy_off": arm_stats(arms_raw["healthy_off"]),
        "healthy_autopilot": arm_stats(
            arms_raw["healthy_autopilot"], with_knobs=True
        ),
    }

    ap_burn = arms["autopilot"]["burn_minutes"]
    static_burns = {
        name: arms[name]["burn_minutes"]
        for name in ("static_conservative", "static_aggressive")
    }
    best_static_p50 = min(
        arms[name]["ttft_p50_s"]
        for name in ("static_conservative", "static_aggressive")
    )
    p50_ratio = round(
        arms["autopilot"]["ttft_p50_s"] / max(best_static_p50, 1e-9), 4
    )

    h_off = arms_raw["healthy_off"]
    h_on = arms_raw["healthy_autopilot"]
    healthy_bit_identity = {
        "ttft_stream_identical": h_on["ttfts"] == h_off["ttfts"],
        "hit_identical": h_on["hit_rate"] == h_off["hit_rate"],
        "knobs_at_baseline": bool(
            h_on["controller"] and h_on["controller"]["at_baseline"]
        ),
        "actuations": (
            h_on["controller"]["stats"]["actuations"]
            if h_on["controller"] else None
        ),
        "burn_timeline_identical": (
            h_on["burn_timeline"] == h_off["burn_timeline"]
        ),
    }

    stats = {
        "config": {
            "workload": (
                "synthetic chat with a diurnal arrival rate "
                f"({AUTOPILOT_QPS_LOW} qps -> {AUTOPILOT_QPS_PEAK} qps in "
                f"[{AUTOPILOT_PEAK_FROM_S}, {AUTOPILOT_PEAK_UNTIL_S})s -> "
                f"{AUTOPILOT_QPS_LOW} qps), sole-holder warm-up, precise "
                "routing, two-tier winning-regime data plane"
            ),
            "requests": len(arms_raw["autopilot"]["ttfts"]),
            "n_pods": N_PODS,
            "seed": args.seed,
            "faults": {
                "wipe_pods": list(AUTOPILOT_WIPE_PODS),
                "wipe_window_s": [
                    AUTOPILOT_WIPE_AT_S, AUTOPILOT_WIPE_UNTIL_S,
                ],
                "wipe_every_s": AUTOPILOT_WIPE_EVERY_S,
                "stall_pod": AUTOPILOT_STALL_POD,
                "stall_window_s": [
                    AUTOPILOT_STALL_FROM_S, AUTOPILOT_STALL_UNTIL_S,
                ],
            },
            "slo": {
                "eval_dt_s": AUTOPILOT_EVAL_DT_S,
                "fast_window_s": AUTOPILOT_SLO_FAST_S,
                "slow_window_s": AUTOPILOT_SLO_SLOW_S,
                "burn_threshold": AUTOPILOT_BURN_THRESHOLD,
                "ttft_slo_s": AUTOPILOT_TTFT_SLO_S,
                "ttft_budget": AUTOPILOT_TTFT_BUDGET,
                "hit_frac_floor": AUTOPILOT_HIT_FRAC_FLOOR,
                "hit_budget": AUTOPILOT_HIT_BUDGET,
            },
            "controller": dict(AUTOPILOT_CTRL_CFG),
            "knobs": {
                "placement_base": dict(AUTOPILOT_PLACEMENT_BASE),
                "placement_aggressive": dict(AUTOPILOT_PLACEMENT_AGGR),
                "audit_interval_base_s": AUTOPILOT_AUDIT_BASE_S,
                "audit_interval_aggressive_s": AUTOPILOT_AUDIT_AGGR_S,
                "hedge_floor_base_s": AUTOPILOT_HEDGE_FLOOR_BASE_S,
                "hedge_floor_aggressive_s": AUTOPILOT_HEDGE_FLOOR_AGGR_S,
            },
        },
        "arms": arms,
        # Headline verdicts.
        "autopilot_burn_minutes": ap_burn,
        "static_burn_minutes": static_burns,
        "autopilot_beats_every_static_on_burn": all(
            ap_burn <= b for b in static_burns.values()
        ),
        "autopilot_p50_vs_best_static": p50_ratio,
        "autopilot_p50_within_1p05x": p50_ratio <= 1.05,
        "healthy_bit_identity": healthy_bit_identity,
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_AUTOPILOT.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "autopilot_burn_minutes",
        "value": ap_burn,
        "unit": "sim-minutes breaching",
        "static_burn_minutes": static_burns,
        "beats_every_static": stats["autopilot_beats_every_static_on_burn"],
        "p50_vs_best_static": p50_ratio,
        "healthy_bit_identical": (
            healthy_bit_identity["ttft_stream_identical"]
            and healthy_bit_identity["knobs_at_baseline"]
        ),
        "source": "benchmarking/FLEET_BENCH_AUTOPILOT.json",
    }))


# Indexer kill-and-restart scenario (--replication; cluster/ subsystem):
# replay the ShareGPT trace while the INDEX SERVICE itself crashes mid-run,
# and compare what the restarted instance starts from:
#   no_fault          same trace, no indexer fault — the hit-rate yardstick.
#   cold_restart      restart with an empty index: routing is blind until
#                     the fleet re-stores its chains (the pre-cluster/
#                     production posture, ROADMAP "Scale out the indexer").
#   snapshot_restore  restart from the last periodic snapshot + seq-tail
#                     replay of the retained event journal
#                     (cluster/snapshot.py): warm in seconds.
# Time-to-warm is sim-time from restart until the CUMULATIVE post-restart
# token hit rate reaches REPLICATION_WARM_FRACTION of the pre-crash
# baseline — cumulative, not windowed, so one lucky window can't call a
# blind index warm. The dip is quantified over a fixed post-restart window.
REPLICATION_CRASH_AT_S = 25.0
REPLICATION_RESTART_AT_S = 30.0
REPLICATION_SNAPSHOT_EVERY_S = 5.0
REPLICATION_TAIL_JOURNAL = 8192
REPLICATION_WARM_FRACTION = 0.9
REPLICATION_DIP_WINDOW_S = 15.0


def run_replication_arm(requests, mode: str, snapshot_path=None):
    """One precise-arm ShareGPT replay under an indexer fault (or none)."""
    sim_kwargs = {}
    if mode != "no_fault":
        from llm_d_kv_cache_manager_tpu.fleethealth import FaultPlan

        sim_kwargs = dict(
            fault_plan=FaultPlan(
                indexer_crash_at_s=REPLICATION_CRASH_AT_S,
                indexer_restart_at_s=REPLICATION_RESTART_AT_S,
            ),
            snapshot_restore=(mode == "snapshot_restore"),
            snapshot_path=snapshot_path,
            snapshot_every_s=(
                REPLICATION_SNAPSHOT_EVERY_S
                if mode == "snapshot_restore" else 0.0
            ),
            tail_journal_len=(
                REPLICATION_TAIL_JOURNAL
                if mode == "snapshot_restore" else 0
            ),
        )
    sim = FleetSim("precise", **sim_kwargs)
    records = []
    try:
        for req in requests:
            h0, t0 = sim.hit_tokens, sim.total_tokens
            ttft = sim.serve(
                req.arrival_s, req.prompt, response_words=req.output_len
            )
            records.append(
                (req.arrival_s, ttft, sim.hit_tokens - h0,
                 sim.total_tokens - t0)
            )
        return {
            "records": records,
            "replication": dict(sim.replication_stats),
            "indexer_down_requests": sim.indexer_down_requests,
            "scores_empty_after_restart": sim.scores_empty_after_restart,
        }
    finally:
        sim.shutdown()


def _replication_warm_stats(records, crash_at, restart_at):
    """Time-to-warm + dip quantification for one arm's request records."""
    baseline = _window_hit_rate(records, t_until=crash_at)
    post = [r for r in records if r[0] >= restart_at]
    # Warm = the cumulative post-restart token hit rate reaches
    # warm_fraction x baseline AND NEVER drops below it again: the
    # threshold time is the first request after the LAST sub-threshold
    # point, so one lucky early request can't call a blind index warm.
    target = REPLICATION_WARM_FRACTION * baseline
    hit = tot = 0
    last_below = -1
    rows = []
    for i, (arrival, _ttft, h, t) in enumerate(post):
        hit += h
        tot += t
        rows.append(arrival)
        if not tot or (hit / tot) < target:
            last_below = i
    time_to_warm = None
    if post and last_below < len(post) - 1:
        time_to_warm = rows[last_below + 1] - restart_at
    last_post_arrival = post[-1][0] if post else restart_at
    return {
        "pre_crash_hit_rate": round(baseline, 4),
        "post_restart_hit_rate": round(
            _window_hit_rate(records, t_from=restart_at), 4
        ),
        "dip_window_hit_rate": round(
            _window_hit_rate(
                records, t_from=restart_at,
                t_until=restart_at + REPLICATION_DIP_WINDOW_S,
            ), 4,
        ),
        "hit_rate_dip": round(
            baseline - _window_hit_rate(
                records, t_from=restart_at,
                t_until=restart_at + REPLICATION_DIP_WINDOW_S,
            ), 4,
        ),
        "time_to_warm_s": (
            None if time_to_warm is None else round(time_to_warm, 3)
        ),
        # Never warmed before the trace ended: lower-bound for ratios.
        "warm_censored_at_s": (
            round(last_post_arrival - restart_at, 3)
            if time_to_warm is None else None
        ),
    }


def main_replication(args):
    import tempfile

    from llm_d_kv_cache_manager_tpu.workloads import read_trace

    t_start = time.time()
    if args.trace:
        trace = read_trace(args.trace)
    else:
        trace = build_sharegpt_trace(seed=args.seed, arrival=args.arrival)
    requests = trace.requests()

    snapshot_path = os.path.join(
        tempfile.gettempdir(), f"kvtpu_bench_snapshot_{os.getpid()}.cbor"
    )
    arms = {}
    for mode in ("no_fault", "cold_restart", "snapshot_restore"):
        arm = run_replication_arm(requests, mode, snapshot_path=snapshot_path)
        records = arm["records"]
        ttfts = [r[1] for r in records]
        stats = {
            "ttft_p50_s": round(p50(ttfts), 4),
            "ttft_p90_s": round(p90(ttfts), 4),
            "prefix_hit_rate": round(_window_hit_rate(records), 4),
        }
        if mode != "no_fault":
            stats.update(_replication_warm_stats(
                records, REPLICATION_CRASH_AT_S, REPLICATION_RESTART_AT_S
            ))
            stats["indexer_down_requests"] = arm["indexer_down_requests"]
            stats["scores_empty_after_restart"] = (
                arm["scores_empty_after_restart"]
            )
            stats["replication"] = arm["replication"]
        arms[mode] = stats
    try:
        os.unlink(snapshot_path)
    except OSError:
        pass

    cold = arms["cold_restart"]
    warm = arms["snapshot_restore"]
    cold_ttw = cold["time_to_warm_s"]
    if cold_ttw is None:
        cold_ttw = cold["warm_censored_at_s"]
    warm_ttw = warm["time_to_warm_s"]
    speedup = (
        round(cold_ttw / max(warm_ttw, 1e-9), 2)
        if (cold_ttw is not None and warm_ttw is not None) else None
    )
    stats = {
        "config": {
            "workload": "sharegpt replay (workloads/), precise arm",
            "trace": {
                "seed": trace.seed,
                "sessions": len(trace.sessions),
                "requests": len(requests),
                "tables_version": trace.tables_version,
            },
            "n_pods": N_PODS,
            "pages_per_pod": PAGES_PER_POD,
            "indexer_crash_at_s": REPLICATION_CRASH_AT_S,
            "indexer_restart_at_s": REPLICATION_RESTART_AT_S,
            "snapshot_every_s": REPLICATION_SNAPSHOT_EVERY_S,
            "tail_journal_messages": REPLICATION_TAIL_JOURNAL,
            "warm_fraction": REPLICATION_WARM_FRACTION,
            "dip_window_s": REPLICATION_DIP_WINDOW_S,
        },
        "arms": arms,
        "time_to_warm_cold_s": cold_ttw,
        "time_to_warm_snapshot_s": warm_ttw,
        "snapshot_restore_time_to_warm_speedup": speedup,
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_REPLICATION.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "snapshot_restore_time_to_warm_speedup",
        "value": speedup,
        "unit": "x",
        # Acceptance: snapshot restore warms >=5x faster than cold restart.
        "vs_baseline": None if speedup is None else round(speedup / 5.0, 3),
        "time_to_warm_cold_s": cold_ttw,
        "time_to_warm_snapshot_s": warm_ttw,
        "hit_rate_dip_cold": cold["hit_rate_dip"],
        "hit_rate_dip_snapshot": warm["hit_rate_dip"],
        "source": "benchmarking/FLEET_BENCH_REPLICATION.json",
    }))


# Multi-tenant placement scenario (--placement; placement/ subsystem):
# T tenants share the fleet, each with its own system prefix served under
# its own LoRA keyspace; tenant popularity is Zipf. Three precise-routing
# arms over matched traces:
#   uniform_precise    zipf_s=0 control mix (tenants spread evenly — the
#                      "single-tenant" hit-rate yardstick: no hotspot, so
#                      precise routing is at its best).
#   hotspot_precise    Zipf hotspot mix, placement OFF: the hot tenants'
#                      traffic concentrates on whichever pod owns each hot
#                      prefix — that pod saturates and churns while the
#                      rest of the fleet idles.
#   hotspot_placement  same hotspot mix, placement ON: the popularity
#                      tracker detects the hot chains and the replicator
#                      K-way-replicates their prefixes through the
#                      prefetch/transfer plane, so new sessions tie across
#                      replicas and least-loaded tie-breaking spreads them.
# All arms run the data plane (host tier + DCN peers) in the winning-regime
# model class (wide-MQA int8-KV — same derivation as the scale-out warm-up
# scenario), so the placement-off arm already has every REACTIVE remedy;
# what the artifact isolates is the value of PROACTIVE placement.
PLACEMENT_TENANTS = 12
PLACEMENT_SESSIONS = 200
PLACEMENT_ZIPF_S = 1.8
PLACEMENT_SESSION_RATE = 6.0
PLACEMENT_MAX_TURNS = 3
# Every tenant's system prompt is the same length (the mix is the variable
# under test, not the prefix-length lottery): 900 words ≈ 1.6k fixture
# tokens ≈ 102 blocks.
PLACEMENT_PREFIX_WORDS = 1500
PLACEMENT_PAGES_PER_POD = 1024
PLACEMENT_HOST_CAPACITY = 512
PLACEMENT_K_REPLICAS = 3
PLACEMENT_HOTNESS = 30.0
PLACEMENT_COOLDOWN_S = 6.0
PLACEMENT_HALF_LIFE_S = 60.0
PLACEMENT_QUEUE_BOUND = 64
# Retained/replicated prefix bound: must cover the whole shared prefix —
# a partial replica never ties with the full-prefix owner, so routing
# would keep concentrating (128 blocks = 2048 tokens > the 102-block
# prefix above).
PLACEMENT_MAX_PREFIX_BLOCKS = 192


def _winning_regime_constants():
    """(alpha, gamma, delta, source): per-token recompute/restore/onboard
    seconds for the wide-MQA int8-KV model class, derived from the SAME
    measured rig rates as everything else (DEVICE_BENCH.json when present;
    assumed v5e rates otherwise). Shared by run_winning_regime and the
    placement scenario so 'the regime where transfer wins' means one
    thing."""
    from llm_d_kv_cache_manager_tpu.engine import costs as costs_mod
    from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

    rates = costs_mod.measured_rates() or costs_mod.ASSUMED_RATES
    wide = LlamaConfig(
        vocab_size=32768, d_model=8192, n_layers=4, n_q_heads=64,
        n_kv_heads=1, head_dim=128, d_ff=28672,
    )
    kv_bytes = costs_mod.kv_bytes_per_token(wide, quantized=True)
    alpha = costs_mod.flops_per_token(wide) / rates["compute_flops_per_s"]
    gamma = kv_bytes / rates["staged_bytes_per_s"]
    delta = kv_bytes / rates["peer_bytes_per_s"]
    return alpha, gamma, delta, rates["source"]


def build_placement_trace(seed: int = 42, zipf_s: float = PLACEMENT_ZIPF_S):
    from llm_d_kv_cache_manager_tpu.workloads import (
        MultiTenantConfig,
        generate_multitenant,
    )

    return generate_multitenant(MultiTenantConfig(
        n_tenants=PLACEMENT_TENANTS,
        n_sessions=PLACEMENT_SESSIONS,
        seed=seed,
        zipf_s=zipf_s,
        session_rate_per_s=PLACEMENT_SESSION_RATE,
        max_turns=PLACEMENT_MAX_TURNS,
        prefix_words=PLACEMENT_PREFIX_WORDS,
    ))


def run_placement_arm(requests, placement=None):
    """One precise-arm replay of a multi-tenant trace, data plane on, in
    the winning-regime model class. `placement` (a ReplicationConfig or
    kwargs dict) enables the placement subsystem; None pins today's
    reactive-only read path."""
    from llm_d_kv_cache_manager_tpu.workloads import tenant_of

    alpha, gamma, delta, _src = _winning_regime_constants()
    sim = FleetSim(
        "precise",
        pages_per_pod=PLACEMENT_PAGES_PER_POD,
        host_tier=True,
        host_capacity=PLACEMENT_HOST_CAPACITY,
        alpha=alpha, gamma=gamma, delta=delta,
        placement=placement,
    )
    ttfts = []
    per_tenant: dict = {}
    hot_pod_counts = [0] * N_PODS
    try:
        for req in requests:
            tenant = tenant_of(req.session)
            h0, t0 = sim.hit_tokens, sim.total_tokens
            ttfts.append(sim.serve(
                req.arrival_s, req.prompt,
                response_words=req.output_len, lora_id=tenant,
            ))
            rec = per_tenant.setdefault(tenant, [0, 0, 0])
            rec[0] += sim.hit_tokens - h0
            rec[1] += sim.total_tokens - t0
            rec[2] += 1
            if tenant == 0:
                hot_pod_counts[sim.last_pod_idx] += 1
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        extras = {
            "restored_blocks": sim.restored_blocks,
            "onboarded_blocks": sim.onboarded_blocks,
            "preemptions": sim.preemptions,
            "placement": sim.placement_stats(),
            "per_tenant": per_tenant,
            "hot_tenant_pod_counts": hot_pod_counts,
        }
        return ttfts, hit_rate, extras
    finally:
        sim.shutdown()


def main_placement(args):
    """--placement: the multi-tenant hotspot comparison. Writes
    benchmarking/FLEET_BENCH_PLACEMENT.json."""
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
        native_available,
    )

    if not native_available():
        print(json.dumps({
            "metric": "placement_hit_rate_retention",
            "value": None,
            "skipped": "libkvtransfer.so not built (make kvtransfer)",
        }))
        return

    t_start = time.time()
    uniform_trace = build_placement_trace(seed=args.seed, zipf_s=0.0)
    hotspot_trace = build_placement_trace(
        seed=args.seed, zipf_s=PLACEMENT_ZIPF_S
    )
    uniform_requests = uniform_trace.requests()
    hotspot_requests = hotspot_trace.requests()

    placement_cfg = dict(
        k_replicas=PLACEMENT_K_REPLICAS,
        hotness_threshold=PLACEMENT_HOTNESS,
        cooldown_s=PLACEMENT_COOLDOWN_S,
        max_prefix_blocks=PLACEMENT_MAX_PREFIX_BLOCKS,
    )
    arms = {}
    for name, requests, placement in (
        ("uniform_precise", uniform_requests, None),
        ("hotspot_precise", hotspot_requests, None),
        ("hotspot_placement", hotspot_requests, placement_cfg),
    ):
        ttfts, hit, ex = run_placement_arm(requests, placement=placement)
        hot_tenant = ex["per_tenant"].get(0, [0, 0, 0])
        arms[name] = {
            "ttft_p50_s": round(p50(ttfts), 4),
            "ttft_p90_s": round(p90(ttfts), 4),
            "ttft_mean_s": round(sum(ttfts) / len(ttfts), 4),
            "prefix_hit_rate": round(hit, 4),
            "preemptions": ex["preemptions"],
            "onboarded_blocks": ex["onboarded_blocks"],
            "restored_blocks": ex["restored_blocks"],
            "hot_tenant_hit_rate": round(
                hot_tenant[0] / max(hot_tenant[1], 1), 4
            ),
            "hot_tenant_requests": hot_tenant[2],
            # Where the hot tenant's requests actually landed — the
            # concentration-vs-spread mechanism, measured: precise-only
            # piles them onto the prefix owner; replication spreads them
            # across the K-replica set via the least-loaded tie-break.
            "hot_tenant_pod_counts": ex["hot_tenant_pod_counts"],
            "hot_tenant_pods_used": sum(
                1 for c in ex["hot_tenant_pod_counts"] if c > 0
            ),
        }
        if ex["placement"]:
            arms[name]["placement"] = ex["placement"]

    alpha, gamma, delta, rates_source = _winning_regime_constants()
    baseline_hit = arms["uniform_precise"]["prefix_hit_rate"]
    retention = arms["hotspot_placement"]["prefix_hit_rate"] / max(
        baseline_hit, 1e-9
    )
    degraded = arms["hotspot_precise"]["prefix_hit_rate"] / max(
        baseline_hit, 1e-9
    )
    from llm_d_kv_cache_manager_tpu.workloads import tenant_weights

    stats = {
        "config": {
            "workload": "multitenant-sharegpt (workloads/multitenant.py), "
                        "precise arm, data plane on",
            "n_tenants": PLACEMENT_TENANTS,
            "n_sessions": PLACEMENT_SESSIONS,
            "zipf_s": PLACEMENT_ZIPF_S,
            "prefix_words": PLACEMENT_PREFIX_WORDS,
            "hot_tenant_session_share": round(
                tenant_weights(PLACEMENT_TENANTS, PLACEMENT_ZIPF_S)[0], 4
            ),
            "session_rate_per_s": PLACEMENT_SESSION_RATE,
            "max_turns": PLACEMENT_MAX_TURNS,
            "requests_uniform": len(uniform_requests),
            "requests_hotspot": len(hotspot_requests),
            "n_pods": N_PODS,
            "pages_per_pod": PLACEMENT_PAGES_PER_POD,
            "host_capacity_blocks": PLACEMENT_HOST_CAPACITY,
            "seed": args.seed,
            "model_class": "wide MQA + int8 KV (winning regime, shared "
                           "with data_plane_winning_regime)",
            "rates_source": rates_source,
            "alpha_recompute_s_per_token": round(alpha, 8),
            "gamma_staged_s_per_token": round(gamma, 8),
            "delta_dcn_s_per_token": round(delta, 8),
            "placement": {
                "k_replicas": PLACEMENT_K_REPLICAS,
                "hotness_threshold": PLACEMENT_HOTNESS,
                "cooldown_s": PLACEMENT_COOLDOWN_S,
                "half_life_s": PLACEMENT_HALF_LIFE_S,
                "queue_bound": PLACEMENT_QUEUE_BOUND,
                "max_prefix_blocks": PLACEMENT_MAX_PREFIX_BLOCKS,
            },
        },
        "arms": arms,
        # Acceptance: the replication arm retains >=90% of the uniform-mix
        # ("single-tenant") hit rate at the hotspot mix where the
        # precise-only arm measurably degrades.
        "hit_rate_retention_placement": round(retention, 4),
        "hit_rate_retention_precise_only": round(degraded, 4),
        "ttft_p50_speedup_vs_precise_only": round(
            arms["hotspot_precise"]["ttft_p50_s"]
            / max(arms["hotspot_placement"]["ttft_p50_s"], 1e-9), 3
        ),
        # How many times worse than the uniform-mix baseline each hotspot
        # arm's mean TTFT is — the degradation the hotspot causes, and
        # what replication buys back.
        "ttft_mean_degradation_precise_only_x": round(
            arms["hotspot_precise"]["ttft_mean_s"]
            / max(arms["uniform_precise"]["ttft_mean_s"], 1e-9), 2
        ),
        "ttft_mean_degradation_placement_x": round(
            arms["hotspot_placement"]["ttft_mean_s"]
            / max(arms["uniform_precise"]["ttft_mean_s"], 1e-9), 2
        ),
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_PLACEMENT.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "placement_hit_rate_retention",
        "value": round(retention, 4),
        # Target: >=0.9 of the uniform-mix hit rate under the hotspot mix.
        "vs_baseline": round(retention / 0.9, 3),
        "unit": "fraction",
        "precise_only_retention": round(degraded, 4),
        "ttft_p50_speedup_vs_precise_only": stats[
            "ttft_p50_speedup_vs_precise_only"
        ],
        "source": "benchmarking/FLEET_BENCH_PLACEMENT.json",
    }))


# -- anticipatory-prefetch scenario (--anticipate; prediction/) ---------------
# Multi-turn sessions spend most of their wall-clock in think time, and the
# fleet's eviction churn uses exactly that window to destroy the session's
# resident prefix — so the next turn pays restore/recompute ON its TTFT.
# The session predictor turns think time into warm time: it learns each
# session's next-turn ETA from the read path alone, and pre-lands the
# continuation prefix on the pod the router would pick, through the same
# bounded prefetch + warm_chain admission seams replication uses.
#
# Two replays (the committed ShareGPT shape, and the new agentic trace —
# fan-out/fan-in tool loops with short regular gaps, the predictor's best
# case), two arms each over the SAME requests:
#
# - "reactive": today's read path, data plane on — missing blocks are
#   restored/onboarded at admission time, charged to the request's TTFT
#   (the reactive route-driven prefetcher's behavior: in the sim, routing
#   and admission are the same instant, so a route-time prefetch hint has
#   zero think-window to act in).
# - "anticipate": the predictor pre-lands during the idle window;
#   transfer time is charged to the target pod's clock (background, not
#   free), and every pre-landed block that the predicted turn never
#   consumed — or that landed on a pod the router then didn't pick — is
#   counted as mispredicted bytes, the honest cost column.
#
# Headline: fraction of turn-N>=2 requests whose FULL previous-turn prefix
# is resident on the routed pod BEFORE arrival (audited at the pre-admit
# seam), plus the TTFT delta.
ANTICIPATE_PAGES_PER_POD = 1536    # tight HBM: think-window eviction is real
ANTICIPATE_HOST_CAPACITY = 16384   # ...but evicted blocks stay restorable
ANTICIPATE_MAX_SESSIONS = 512
ANTICIPATE_MAX_CHAIN_BLOCKS = 512
ANTICIPATE_MAX_JOBS_PER_TICK = 4
ANTICIPATE_COOLDOWN_S = 2.0
ANTICIPATE_START_FRAC = 0.4
PREDICTION_QUEUE_BOUND = 64
AGENTIC_TASKS = 16
AGENTIC_TASK_RATE = 0.8


def build_agentic_trace(seed: int = 42):
    from llm_d_kv_cache_manager_tpu.workloads import (
        AgenticConfig,
        generate_agentic,
    )

    return generate_agentic(AgenticConfig(
        n_tasks=AGENTIC_TASKS,
        seed=seed,
        task_rate_per_s=AGENTIC_TASK_RATE,
    ))


def run_anticipate_arm(requests, predict: bool):
    """One precise-arm replay, data plane on, winning-regime constants.
    `predict=True` wires the session predictor; either way the pre-admit
    audit measures, for every turn-N>=2 request, how much of the previous
    turn's full prompt chain is resident on the routed pod at arrival."""
    from llm_d_kv_cache_manager_tpu.prediction import fleet_prior_from_tables
    from llm_d_kv_cache_manager_tpu.workloads import ShareGPTConfig

    alpha, gamma, delta, _src = _winning_regime_constants()
    prediction = None
    if predict:
        # Cold-start ETA prior from the committed workload tables (the
        # ShareGPT think-time shape); the online fleet reservoir takes
        # over after the first observed continuations.
        sg = ShareGPTConfig()
        prediction = dict(
            max_sessions=ANTICIPATE_MAX_SESSIONS,
            max_chain_blocks=ANTICIPATE_MAX_CHAIN_BLOCKS,
            block_bytes=_geo_kv_block_bytes(),
            default_eta_s=fleet_prior_from_tables(
                sg.think_time_mean_s, sg.read_s_per_unit
            ),
            max_jobs_per_tick=ANTICIPATE_MAX_JOBS_PER_TICK,
            session_cooldown_s=ANTICIPATE_COOLDOWN_S,
            start_frac=ANTICIPATE_START_FRAC,
        )
    sim = FleetSim(
        "precise",
        pages_per_pod=ANTICIPATE_PAGES_PER_POD,
        host_tier=True,
        host_capacity=ANTICIPATE_HOST_CAPACITY,
        alpha=alpha, gamma=gamma, delta=delta,
        prediction=prediction,
    )
    prev_chain = {}
    current = {}
    audit = {
        "turn2_requests": 0,
        "full_resident": 0,
        "resident_blocks": 0,
        "prefix_blocks": 0,
        "wrong_pod_blocks": 0,
    }

    def hook(sim, pod_idx, pod, tokens, arrival):
        sess, turn = current["session"], current["turn"]
        keys = sim.indexer.token_processor.tokens_to_kv_block_keys(
            None, tokens, MODEL
        )
        chain = [k.chunk_hash for k in keys]
        if turn >= 1:
            prev = prev_chain.get(sess)
            if prev:
                audit["turn2_requests"] += 1
                resident = pod.resident_prefix_blocks(prev)
                audit["resident_blocks"] += resident
                audit["prefix_blocks"] += len(prev)
                if resident >= len(prev):
                    audit["full_resident"] += 1
            if sim.session_table is not None and chain:
                # Wrong-pod audit: the prefetch this turn consumed (the
                # table resolved it during route-time observation) landed
                # on `consumed.pod`; if the router picked elsewhere, those
                # blocks were mispredicted cost.
                rec = sim.session_table.record_by_tail(chain[-1])
                if rec is not None and rec.consumed is not None:
                    if rec.consumed.pod != f"pod-{pod_idx}":
                        audit["wrong_pod_blocks"] += rec.consumed.blocks
                        sim.session_table.count_wrong_pod(
                            rec.consumed.blocks
                        )
                    rec.consumed = None
        prev_chain[sess] = chain

    sim.pre_admit_hook = hook
    ttfts = []
    ttfts_turn2 = []
    try:
        for req in requests:
            current = {"session": req.session, "turn": req.turn}
            ttft = sim.serve(
                req.arrival_s, req.prompt, response_words=req.output_len
            )
            ttfts.append(ttft)
            if req.turn >= 1:
                ttfts_turn2.append(ttft)
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        extras = {
            "restored_blocks": sim.restored_blocks,
            "onboarded_blocks": sim.onboarded_blocks,
            "preemptions": sim.preemptions,
            "audit": audit,
            "prediction": sim.prediction_stats(),
        }
        return ttfts, ttfts_turn2, hit_rate, extras
    finally:
        sim.shutdown()


def _anticipate_arm_stats(ttfts, ttfts_turn2, hit, ex):
    audit = ex["audit"]
    row = {
        "ttft_p50_s": round(p50(ttfts), 4),
        "ttft_p90_s": round(p90(ttfts), 4),
        "ttft_mean_s": round(sum(ttfts) / len(ttfts), 4),
        "ttft_turn2plus_p50_s": round(p50(ttfts_turn2), 4),
        "ttft_turn2plus_p90_s": round(p90(ttfts_turn2), 4),
        "prefix_hit_rate": round(hit, 4),
        "preemptions": ex["preemptions"],
        "restored_blocks": ex["restored_blocks"],
        "onboarded_blocks": ex["onboarded_blocks"],
        "turn2plus_requests": audit["turn2_requests"],
        # The headline: the request arrived and its entire previous-turn
        # prompt chain was already device-resident on the routed pod.
        "prefix_resident_before_arrival_frac": round(
            audit["full_resident"] / max(audit["turn2_requests"], 1), 4
        ),
        # Partial credit view: resident blocks over predicted-prefix
        # blocks, aggregated.
        "prefix_blocks_resident_frac": round(
            audit["resident_blocks"] / max(audit["prefix_blocks"], 1), 4
        ),
    }
    if ex["prediction"]:
        pred = ex["prediction"]
        table = pred["table"]
        row["prediction"] = pred
        row["mispredicted_blocks"] = table["mispredicted_blocks"]
        row["mispredicted_bytes"] = table["mispredicted_bytes"]
        row["predicted_landed_blocks"] = pred["predicted_landed_blocks"]
        row["prediction_charged_s"] = pred["prediction_charged_s"]
    return row


def main_anticipate(args):
    """--anticipate: the session-predictor comparison over the ShareGPT
    and agentic replays. Writes benchmarking/FLEET_BENCH_ANTICIPATE.json."""
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
        native_available,
    )

    if not native_available():
        print(json.dumps({
            "metric": "anticipate_prefix_resident_frac",
            "value": None,
            "skipped": "libkvtransfer.so not built (make kvtransfer)",
        }))
        return

    t_start = time.time()
    traces = {
        "sharegpt": build_sharegpt_trace(seed=args.seed).requests(),
        "agentic": build_agentic_trace(seed=args.seed).requests(),
    }
    arms = {}
    for trace_name, requests in traces.items():
        for arm_name, predict in (("reactive", False), ("anticipate", True)):
            ttfts, t2, hit, ex = run_anticipate_arm(requests, predict)
            arms[f"{trace_name}_{arm_name}"] = _anticipate_arm_stats(
                ttfts, t2, hit, ex
            )

    alpha, gamma, delta, rates_source = _winning_regime_constants()

    def speedup(trace_name, key):
        return round(
            arms[f"{trace_name}_reactive"][key]
            / max(arms[f"{trace_name}_anticipate"][key], 1e-9), 3
        )

    stats = {
        "config": {
            "workloads": {
                "sharegpt": "build_sharegpt_trace (the --workload sharegpt "
                            "replay shape)",
                "agentic": "workloads/agentic.py fan-out/fan-in trace "
                           f"({AGENTIC_TASKS} tasks)",
            },
            "requests": {k: len(v) for k, v in traces.items()},
            "n_pods": N_PODS,
            "pages_per_pod": ANTICIPATE_PAGES_PER_POD,
            "host_capacity_blocks": ANTICIPATE_HOST_CAPACITY,
            "seed": args.seed,
            "model_class": "wide MQA + int8 KV (winning regime, shared "
                           "with placement/data_plane_winning_regime)",
            "rates_source": rates_source,
            "alpha_recompute_s_per_token": round(alpha, 8),
            "gamma_staged_s_per_token": round(gamma, 8),
            "delta_dcn_s_per_token": round(delta, 8),
            "kv_block_bytes": _geo_kv_block_bytes(),
            "prediction": {
                "max_sessions": ANTICIPATE_MAX_SESSIONS,
                "max_chain_blocks": ANTICIPATE_MAX_CHAIN_BLOCKS,
                "max_jobs_per_tick": ANTICIPATE_MAX_JOBS_PER_TICK,
                "session_cooldown_s": ANTICIPATE_COOLDOWN_S,
                "start_frac": ANTICIPATE_START_FRAC,
                "queue_bound": PREDICTION_QUEUE_BOUND,
            },
        },
        "arms": arms,
        # Acceptance: >=50% of turn-N>=2 ShareGPT requests arrive with the
        # full continuation prefix already resident (higher on agentic),
        # and the anticipate arm's TTFT beats the reactive arm's.
        "sharegpt_prefix_resident_frac": arms["sharegpt_anticipate"][
            "prefix_resident_before_arrival_frac"
        ],
        "agentic_prefix_resident_frac": arms["agentic_anticipate"][
            "prefix_resident_before_arrival_frac"
        ],
        "sharegpt_ttft_p50_speedup": speedup("sharegpt", "ttft_p50_s"),
        "sharegpt_ttft_turn2plus_p50_speedup": speedup(
            "sharegpt", "ttft_turn2plus_p50_s"
        ),
        "agentic_ttft_p50_speedup": speedup("agentic", "ttft_p50_s"),
        "agentic_ttft_turn2plus_p50_speedup": speedup(
            "agentic", "ttft_turn2plus_p50_s"
        ),
        "sharegpt_mispredicted_bytes": arms["sharegpt_anticipate"].get(
            "mispredicted_bytes", 0
        ),
        "agentic_mispredicted_bytes": arms["agentic_anticipate"].get(
            "mispredicted_bytes", 0
        ),
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_ANTICIPATE.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "anticipate_prefix_resident_frac",
        "value": stats["sharegpt_prefix_resident_frac"],
        # Target: >=50% of turn-N>=2 ShareGPT requests fully pre-landed.
        "vs_baseline": round(
            stats["sharegpt_prefix_resident_frac"] / 0.5, 3
        ),
        "unit": "fraction",
        "agentic_prefix_resident_frac": stats[
            "agentic_prefix_resident_frac"
        ],
        "sharegpt_ttft_p50_speedup": stats["sharegpt_ttft_p50_speedup"],
        "agentic_ttft_p50_speedup": stats["agentic_ttft_p50_speedup"],
        "source": "benchmarking/FLEET_BENCH_ANTICIPATE.json",
    }))


# -- hierarchical federation scenario (--geo; federation/) --------------------
# A geo-distributed fleet: GEO_REGIONS regions × GEO_PODS_PER_REGION pods,
# sessions home-pinned with diurnal skew (workloads/geo.py), one region
# lost mid-replay. Two arms over the SAME trace:
#
# - "flat_global": one fleet of all pods behind one precise index — the
#   deployment today's control plane would run. Routing ignores geography,
#   so session prefixes migrate between regions and every peer onboard
#   that crosses a region boundary is WAN traffic (attributed at the peer-
#   resolver seam); region loss leaves phantom placements the router must
#   discover by retry.
# - "federation": region-local precise fleets under a GlobalRouter
#   (federation/): region pick by sketch affinity over shipped digests,
#   precise scoring inside the region, hot prefixes replicated cross-
#   region through the warm_chain admission seam, digest staleness
#   detecting the loss and rendezvous failover re-homing its sessions.
#
# Cross-region bytes are the honest comparison: the flat arm pays per-
# onboard KV bytes; federation pays digest bytes + proactive warm bytes.
GEO_REGIONS = 3
GEO_PODS_PER_REGION = 4
GEO_SESSIONS = 220
GEO_SESSION_RATE = 2.4
GEO_DAY_PERIOD_S = 120.0
GEO_AMPLITUDE = 0.85
GEO_PREFIX_WORDS = 900
GEO_PREFIXES_PER_REGION = 2
GEO_MAX_TURNS = 5
GEO_PAGES_PER_POD = 384
GEO_HOST_CAPACITY = 512
# Region lost mid-replay, at this fraction of the trace span.
GEO_LOST_REGION = "region-1"
GEO_LOSS_AT_FRAC = 0.55
# Pre-loss hit-rate window length (seconds of sim time before the loss).
GEO_PRELOSS_WINDOW_S = 60.0
# Digest cadence + staleness windows (sim time). Detection time is
# bounded by stale_after + one interval; the bench reports the measured
# value next to the configured windows.
GEO_DIGEST_INTERVAL_S = 4.0
GEO_DIGEST_SUSPECT_S = 8.0
GEO_DIGEST_STALE_S = 12.0
# Cross-region hot-chain admission: decayed-score threshold + cooldown
# (federation/region.py knobs), and how much slower the WAN is than the
# intra-region DCN the delta constant models.
GEO_WARM_THRESHOLD = 8.0
GEO_WARM_COOLDOWN_S = 120.0
GEO_CROSS_DELTA_MULT = 4.0
GEO_DIGEST_HOT_K = 6
GEO_MAX_PREFIX_BLOCKS = 24
GEO_SKETCH_WIDTH = 1024
GEO_HALF_LIFE_S = 60.0
GEO_LOAD_NORM = 4.0


def _geo_kv_block_bytes() -> int:
    """KV bytes of one PAGE_SIZE-token block in the winning-regime model
    class (the same wide-MQA int8 shape every placement/transfer number
    uses) — the unit every cross-region byte column is priced in."""
    from llm_d_kv_cache_manager_tpu.engine import costs as costs_mod
    from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

    wide = LlamaConfig(
        vocab_size=32768, d_model=8192, n_layers=4, n_q_heads=64,
        n_kv_heads=1, head_dim=128, d_ff=28672,
    )
    return costs_mod.kv_bytes_per_token(wide, quantized=True) * PAGE_SIZE


def build_geo_trace(seed: int = 42):
    from llm_d_kv_cache_manager_tpu.workloads import GeoConfig, generate_geo

    return generate_geo(GeoConfig(
        n_regions=GEO_REGIONS,
        n_sessions=GEO_SESSIONS,
        seed=seed,
        day_period_s=GEO_DAY_PERIOD_S,
        diurnal_amplitude=GEO_AMPLITUDE,
        session_rate_per_s=GEO_SESSION_RATE,
        prefixes_per_region=GEO_PREFIXES_PER_REGION,
        prefix_words=GEO_PREFIX_WORDS,
        max_turns=GEO_MAX_TURNS,
    ))


def _geo_region_of_pod(pod_idx: int) -> str:
    return f"region-{pod_idx // GEO_PODS_PER_REGION}"


class _RegionAccountingResolver:
    """Peer-resolver wrapper attributing peer fetches to intra- vs
    cross-region pairs (flat arm). The tiering store resolves a block
    more than once per fetch (source gating + run batching), so each
    (destination pod, block) pair is counted ONCE — an undercount when
    eviction forces the same block to re-onboard later, which is the
    conservative direction for the flat arm's cross-region column."""

    def __init__(self, inner, addr_to_pod, self_pod_idx, counters):
        self.inner = inner
        self.addr_to_pod = addr_to_pod
        self.self_region = _geo_region_of_pod(self_pod_idx)
        self.counters = counters
        self._seen = set()

    def __call__(self, chunk_hash):
        addr = self.inner(chunk_hash)
        if addr is not None and chunk_hash not in self._seen:
            self._seen.add(chunk_hash)
            src = self.addr_to_pod.get(tuple(addr))
            if src is not None:
                if _geo_region_of_pod(src) == self.self_region:
                    self.counters["intra_region_blocks"] += 1
                else:
                    self.counters["cross_region_blocks"] += 1
        return addr


def _geo_spread_router(sim):
    """Precise routing with an UNBIASED tie-break, for both geo arms.

    FleetSim.route's historical tie-breaks resolve equal scores (and the
    no-signal fallback) to the lowest pod index — invisible in the
    committed single-fleet arms, but in a geography-labeled fleet it
    plants every consolidation point in "region-0" by construction. A
    real fleet's balancer has no favorite pod: ties break by
    (least-loaded, per-(request, pod) rendezvous hash), so placement is
    deterministic yet spread. Same argmax, same precision — only exact
    ties differ."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import fnv64a

    def route(prompt):
        head = prompt[:80].encode("utf-8", "ignore")

        def spread_key(i):
            return (sim.pod_free_at[i], fnv64a(b"%d:" % i + head))

        scores = sim.indexer.get_pod_scores(prompt, MODEL, [])
        if sim._crashed and scores and any(
            int(p.split("-")[1]) in sim._crashed for p in scores
        ):
            sim.phantom_scores.append(sim.now)
        if scores:
            best = max(scores.values())
            cands = [
                int(p.split("-")[1]) for p, s in scores.items()
                if s == best
            ]
            return min(cands, key=spread_key)
        return min(sim._alive_pods(), key=spread_key)

    return route


def _geo_hit_windows(records, loss_at_s, post_from_s):
    """(pre_loss_hit, post_failover_hit) token-weighted hit rates: pre =
    [loss - GEO_PRELOSS_WINDOW_S, loss), post = [post_from_s, end]."""
    def rate(lo, hi):
        hit = tot = 0
        for arrival, _ttft, h, t in records:
            if lo <= arrival < hi:
                hit += h
                tot += t
        return hit / max(tot, 1)

    return (
        rate(loss_at_s - GEO_PRELOSS_WINDOW_S, loss_at_s),
        rate(post_from_s, float("inf")),
    )


def run_geo_flat(requests, loss_at_s):
    """Flat-global arm: one precise fleet of every pod, data plane on.
    Region loss = the region's pods crash (phantom placements stay in the
    global index; the router discovers them by retry)."""
    from llm_d_kv_cache_manager_tpu.engine.tiering import (
        IndexBackedPeerResolver,
    )

    alpha, gamma, delta, _src = _winning_regime_constants()
    n_pods = GEO_REGIONS * GEO_PODS_PER_REGION
    sim = FleetSim(
        "precise",
        n_pods=n_pods,
        pages_per_pod=GEO_PAGES_PER_POD,
        host_tier=True,
        host_capacity=GEO_HOST_CAPACITY,
        alpha=alpha, gamma=gamma, delta=delta,
    )
    sim.route_override = _geo_spread_router(sim)
    counters = {"intra_region_blocks": 0, "cross_region_blocks": 0}
    addr_to_pod = {
        tuple(pod.transfer_address): i for i, pod in enumerate(sim.pods)
    }
    for i, pod in enumerate(sim.pods):
        pod.set_peer_resolver(_RegionAccountingResolver(
            IndexBackedPeerResolver(
                sim.indexer.kv_block_index, MODEL, sim._addrs, f"pod-{i}",
            ),
            addr_to_pod, i, counters,
        ))
    records = []
    out_of_home = 0
    lost = False
    lost_idx = int(GEO_LOST_REGION.split("-")[1])
    try:
        for req in requests:
            if not lost and req.arrival_s >= loss_at_s:
                for i in range(
                    lost_idx * GEO_PODS_PER_REGION,
                    (lost_idx + 1) * GEO_PODS_PER_REGION,
                ):
                    sim._crashed.add(i)
                    sim.pod_active[i] = []
                    # A lost region is UNREACHABLE, not just unroutable:
                    # its transfer servers are gone with it, so the data
                    # plane cannot onboard from its pods (the index's
                    # phantom entries resolve to nothing). Mutating the
                    # shared addr map severs every resolver at once.
                    sim._addrs.pop(f"pod-{i}", None)
                lost = True
            h0, t0 = sim.hit_tokens, sim.total_tokens
            ttft = sim.serve(
                req.arrival_s, req.prompt, response_words=req.output_len
            )
            records.append((
                req.arrival_s, ttft,
                sim.hit_tokens - h0, sim.total_tokens - t0,
            ))
            if (
                req.region is not None
                and _geo_region_of_pod(sim.last_pod_idx) != req.region
            ):
                out_of_home += 1
        pre_hit, post_hit = _geo_hit_windows(records, loss_at_s, loss_at_s)
        block_bytes = _geo_kv_block_bytes()
        return records, {
            "prefix_hit_rate": round(
                sim.hit_tokens / max(sim.total_tokens, 1), 4
            ),
            "pre_loss_hit_rate": round(pre_hit, 4),
            "post_loss_hit_rate": round(post_hit, 4),
            "cross_region_fetch_blocks": counters["cross_region_blocks"],
            "cross_region_fetch_bytes": (
                counters["cross_region_blocks"] * block_bytes
            ),
            "intra_region_fetch_blocks": counters["intra_region_blocks"],
            "onboarded_blocks": sim.onboarded_blocks,
            "restored_blocks": sim.restored_blocks,
            "preemptions": sim.preemptions,
            "out_of_home_requests": out_of_home,
            "stale_routes_after_loss": len(sim.stale_routes),
            "phantom_score_offers": len(sim.phantom_scores),
        }
    finally:
        sim.shutdown()


def run_geo_federation(requests, loss_at_s):
    """Federation arm: GEO_REGIONS region-local fleets under one
    GlobalRouter. Digests ship every GEO_DIGEST_INTERVAL_S of sim time;
    hot chains replicate cross-region through warm_chain-style admission
    (prefill + free on the target, charged at WAN rate); losing a region
    silences its digests — staleness detection + rendezvous failover."""
    from llm_d_kv_cache_manager_tpu.federation import (
        FederationConfig,
        GlobalRouter,
        Region,
        encode_digest,
    )
    from llm_d_kv_cache_manager_tpu.placement import (
        ChainPopularityTracker,
        PopularityConfig,
    )

    alpha, gamma, delta, _src = _winning_regime_constants()
    block_bytes = _geo_kv_block_bytes()
    region_names = [f"region-{r}" for r in range(GEO_REGIONS)]

    class _GeoClock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = _GeoClock()
    sims = {}
    trackers = {}
    for name in region_names:
        sim = FleetSim(
            "precise",
            n_pods=GEO_PODS_PER_REGION,
            pages_per_pod=GEO_PAGES_PER_POD,
            host_tier=True,
            host_capacity=GEO_HOST_CAPACITY,
            alpha=alpha, gamma=gamma, delta=delta,
        )
        tracker = ChainPopularityTracker(
            PopularityConfig(
                half_life_s=GEO_HALF_LIFE_S,
                top_k=GEO_DIGEST_HOT_K * 4,
                max_prefix_blocks=GEO_MAX_PREFIX_BLOCKS,
                # Digest economy: the shipped sketch is the digest's bulk
                # (rows x width cells). 1024x4 keeps collision rates
                # negligible at this fleet's chain count while a digest
                # stays sketch-sized on the WAN — the honest digest-
                # bytes/s column prices exactly this choice.
                sketch_width=GEO_SKETCH_WIDTH,
            ),
            clock=clock,
        )
        sim.indexer.popularity = tracker
        sim.route_override = _geo_spread_router(sim)
        sims[name] = sim
        trackers[name] = tracker

    warm_stats = {"jobs": 0, "blocks": 0, "bytes": 0, "charged_s": 0.0}
    lost_state = {"lost": False}

    def make_warm_fn(region_name):
        sim = sims[region_name]

        def warm_fn(chain):
            # Cross-region admission: the chain's prefix KV ships over
            # the WAN and lands through the target's normal allocate
            # path (BlockStored emitted -> the region's index learns the
            # replica), charged to the target pod at WAN rate. Serving
            # always wins: OutOfPagesError = no replication this tick.
            if lost_state["lost"] and region_name == GEO_LOST_REGION:
                return 0
            tokens = list(chain.prefix_tokens)
            if not tokens:
                return 0
            # Rendezvous target inside the region (same ranking the
            # placement replicator uses fleet-wide).
            from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import (
                fnv64a,
            )

            i = max(
                range(sim.n_pods),
                key=lambda j: fnv64a(
                    b"%d:pod-%d" % (chain.head, j)
                ),
            )
            pod = sim.pods[i]
            lora = chain.extra[0] if chain.extra else None
            try:
                state, cached = pod.prefill(tokens, lora_id=lora)
            except OutOfPagesError:
                return 0
            uncached = max(len(tokens) - cached, 0)
            blocks = uncached // PAGE_SIZE
            pod.free(state)
            if blocks:
                cost = delta * GEO_CROSS_DELTA_MULT * uncached
                sim.pod_free_at[i] = max(
                    sim.pod_free_at[i], clock.t
                ) + cost
                warm_stats["jobs"] += 1
                warm_stats["blocks"] += blocks
                warm_stats["bytes"] += blocks * block_bytes
                warm_stats["charged_s"] += cost
            sim.event_pool.drain()
            return blocks

        return warm_fn

    fed_config = FederationConfig(
        region_id=region_names[0],
        regions=region_names,
        digest_interval_s=GEO_DIGEST_INTERVAL_S,
        digest_suspect_after_s=GEO_DIGEST_SUSPECT_S,
        digest_stale_after_s=GEO_DIGEST_STALE_S,
        digest_hot_k=GEO_DIGEST_HOT_K,
        digest_max_prefix_blocks=GEO_MAX_PREFIX_BLOCKS,
        replicate_score_threshold=GEO_WARM_THRESHOLD,
        replicate_cooldown_s=GEO_WARM_COOLDOWN_S,
    )
    regions = {
        name: Region(
            name,
            sims[name].indexer,
            tracker=trackers[name],
            pods_fn=(
                lambda name=name: [
                    f"pod-{i}" for i in sims[name]._alive_pods()
                ]
            ),
            load_fn=(
                lambda name=name: sum(
                    len(a) for a in sims[name].pod_active
                ) / (sims[name].n_pods * GEO_LOAD_NORM)
            ),
            warm_fn=make_warm_fn(name),
        )
        for name in region_names
    }
    router = GlobalRouter(
        fed_config, regions, clock=clock,
    )

    def derive(prompt):
        # Derivation is region-independent (same model/config everywhere);
        # use any live region's pipeline.
        name = region_names[0]
        if lost_state["lost"] and name == GEO_LOST_REGION:
            name = region_names[-1]
        indexer = sims[name].indexer
        tokens = indexer.tokenizers_pool.tokenize(None, prompt, MODEL)
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            None, tokens, MODEL
        )
        return [k.chunk_hash for k in keys]

    records = []
    digest_bytes = 0
    digest_ships = 0
    lost_region_retries = 0
    detection_at = None
    next_digest = 0.0
    picked_by_region = {name: 0 for name in region_names}
    try:
        for req in requests:
            now = req.arrival_s
            clock.t = now
            if not lost_state["lost"] and now >= loss_at_s:
                lost_state["lost"] = True
            if now >= next_digest:
                for name in region_names:
                    if lost_state["lost"] and name == GEO_LOST_REGION:
                        continue  # a lost region ships nothing
                    sims[name].now = now
                    data = encode_digest(
                        regions[name].build_digest(fed_config, now=now)
                    )
                    digest_bytes += len(data)
                    digest_ships += 1
                    router.ingest_digest(data, now=now)
                next_digest = now + GEO_DIGEST_INTERVAL_S
            if (
                detection_at is None
                and lost_state["lost"]
                and router.failover.state_of(GEO_LOST_REGION) == "stale"
            ):
                detection_at = now
            picked, _detail = router.pick_region(
                derive(req.prompt), home_region=req.region, now=now
            )
            if lost_state["lost"] and picked == GEO_LOST_REGION:
                # Pre-detection window: the router still trusts the lost
                # region's last digest; the scoring call fails and the
                # request retries its rendezvous failover — the timeout+
                # retry staleness detection exists to remove.
                lost_region_retries += 1
                picked = router.failover.failover_region(
                    picked, exclude=[GEO_LOST_REGION]
                ) or region_names[0]
            picked_by_region[picked] += 1
            sim = sims[picked]
            h0, t0 = sim.hit_tokens, sim.total_tokens
            ttft = sim.serve(
                now, req.prompt, response_words=req.output_len
            )
            records.append((
                now, ttft, sim.hit_tokens - h0, sim.total_tokens - t0,
            ))
        total_hit = sum(s.hit_tokens for s in sims.values())
        total_tokens = sum(s.total_tokens for s in sims.values())
        post_from = detection_at if detection_at is not None else loss_at_s
        pre_hit, post_hit = _geo_hit_windows(records, loss_at_s, post_from)
        _, post_loss_hit = _geo_hit_windows(records, loss_at_s, loss_at_s)
        duration = max(records[-1][0], 1e-9)
        status = router.status(now=clock.t)
        return records, {
            "prefix_hit_rate": round(total_hit / max(total_tokens, 1), 4),
            "pre_loss_hit_rate": round(pre_hit, 4),
            "post_failover_hit_rate": round(post_hit, 4),
            "post_loss_hit_rate": round(post_loss_hit, 4),
            "cross_region_fetch_bytes": digest_bytes + warm_stats["bytes"],
            "digest_bytes_shipped": digest_bytes,
            "digest_bytes_per_s": round(digest_bytes / duration, 1),
            "digests_shipped": digest_ships,
            "warm_jobs": warm_stats["jobs"],
            "warm_blocks": warm_stats["blocks"],
            "warm_bytes": warm_stats["bytes"],
            "warm_charged_s": round(warm_stats["charged_s"], 4),
            "detection_s": (
                round(detection_at - loss_at_s, 3)
                if detection_at is not None else None
            ),
            "lost_region_retries": lost_region_retries,
            "mispicked_regions": router.stats_counters[
                "mispicked_regions"
            ],
            "routed_by_region": picked_by_region,
            "failovers": router.failover.failovers,
            "preemptions": sum(s.preemptions for s in sims.values()),
            "onboarded_blocks": sum(
                s.onboarded_blocks for s in sims.values()
            ),
            "lost_region_state": status["regions"][GEO_LOST_REGION][
                "state"
            ],
        }
    finally:
        for sim in sims.values():
            sim.shutdown()


def main_geo(args):
    """--geo: the hierarchical-federation comparison. Writes
    benchmarking/FLEET_BENCH_GEO.json."""
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
        native_available,
    )

    if not native_available():
        print(json.dumps({
            "metric": "geo_cross_region_bytes_ratio",
            "value": None,
            "skipped": "libkvtransfer.so not built (make kvtransfer)",
        }))
        return

    t_start = time.time()
    trace = build_geo_trace(seed=args.seed)
    requests = trace.requests()
    span = requests[-1].arrival_s
    loss_at_s = round(span * GEO_LOSS_AT_FRAC, 3)
    region_sessions = {}
    for region in trace.session_regions.values():
        region_sessions[region] = region_sessions.get(region, 0) + 1

    flat_records, flat = run_geo_flat(requests, loss_at_s)
    fed_records, fed = run_geo_federation(requests, loss_at_s)

    flat_ttfts = [r[1] for r in flat_records]
    fed_ttfts = [r[1] for r in fed_records]
    flat["ttft_p50_s"] = round(p50(flat_ttfts), 4)
    flat["ttft_p90_s"] = round(p90(flat_ttfts), 4)
    fed["ttft_p50_s"] = round(p50(fed_ttfts), 4)
    fed["ttft_p90_s"] = round(p90(fed_ttfts), 4)

    retention = fed["post_failover_hit_rate"] / max(
        fed["pre_loss_hit_rate"], 1e-9
    )
    bytes_ratio = fed["cross_region_fetch_bytes"] / max(
        flat["cross_region_fetch_bytes"], 1
    )
    stats = {
        "config": {
            "workload": "geo-sharegpt (workloads/geo.py): home-pinned "
                        "sessions, diurnal skew, one region lost "
                        "mid-replay",
            "n_regions": GEO_REGIONS,
            "pods_per_region": GEO_PODS_PER_REGION,
            "n_sessions": GEO_SESSIONS,
            "requests": len(requests),
            "sessions_per_region": region_sessions,
            "day_period_s": GEO_DAY_PERIOD_S,
            "diurnal_amplitude": GEO_AMPLITUDE,
            "prefix_words": GEO_PREFIX_WORDS,
            "prefixes_per_region": GEO_PREFIXES_PER_REGION,
            "pages_per_pod": GEO_PAGES_PER_POD,
            "host_capacity_blocks": GEO_HOST_CAPACITY,
            "seed": args.seed,
            "lost_region": GEO_LOST_REGION,
            "loss_at_s": loss_at_s,
            "trace_span_s": round(span, 3),
            "kv_block_bytes": _geo_kv_block_bytes(),
            "digest_interval_s": GEO_DIGEST_INTERVAL_S,
            "digest_suspect_after_s": GEO_DIGEST_SUSPECT_S,
            "digest_stale_after_s": GEO_DIGEST_STALE_S,
            "warm_threshold": GEO_WARM_THRESHOLD,
            "warm_cooldown_s": GEO_WARM_COOLDOWN_S,
            "cross_delta_mult": GEO_CROSS_DELTA_MULT,
            "model_class": "wide MQA + int8 KV (winning regime, shared "
                           "with the placement scenario)",
        },
        "arms": {"flat_global": flat, "federation": fed},
        # Acceptance: federation ships fewer cross-region bytes than the
        # flat fleet's peer onboards AND retains >=80% of the pre-loss
        # hit rate after failover, with detection time reported.
        "cross_region_bytes_ratio": round(bytes_ratio, 4),
        "hit_rate_retention_after_failover": round(retention, 4),
        "detection_s": fed["detection_s"],
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_GEO.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "geo_hit_rate_retention_after_failover",
        "value": round(retention, 4),
        # Target: >=0.8 of the pre-loss hit rate after failover.
        "vs_baseline": round(retention / 0.8, 3),
        "unit": "fraction",
        "cross_region_bytes_ratio_vs_flat": round(bytes_ratio, 4),
        "detection_s": fed["detection_s"],
        "source": "benchmarking/FLEET_BENCH_GEO.json",
    }))


def main_cluster_check(args):
    """--cluster-replicas N: route the synthetic headline precise arm
    through a ClusterScorer scatter-gather over N partition-gated local
    replicas and pin it bit-identical to the monolithic run (full answers
    => identical merged scores => identical routing => identical TTFT
    stream). Prints the verdict; commits nothing — the monolithic
    artifacts stay the single source of truth."""
    n = args.cluster_replicas
    t_start = time.time()
    ttft_mono, hit_mono, _, _ = run_strategy("precise")
    ttft_clu, hit_clu, _, _ = run_strategy("precise", cluster_replicas=n)
    identical = ttft_mono == ttft_clu and hit_mono == hit_clu
    print(json.dumps({
        "metric": "cluster_precise_bit_identical",
        "value": bool(identical),
        "replicas": n,
        "prefix_hit_rate_monolithic": round(hit_mono, 4),
        "prefix_hit_rate_cluster": round(hit_clu, 4),
        "ttft_p50_monolithic_s": round(p50(ttft_mono), 4),
        "ttft_p50_cluster_s": round(p50(ttft_clu), 4),
        "requests": len(ttft_mono),
        "wall_s": round(time.time() - t_start, 1),
    }))
    if not identical:
        sys.exit(1)


# Saturation-resilience scenario (--autoscale; ROADMAP item 4): the
# committed qps ladder's saturation row (capacity-regime workload at
# qps 40, where the precise arm degrades to multi-second TTFT p50 with
# hundreds of recompute-preemptions) under three treatments:
#   precise_saturated     the ladder's qps_40 precise row, re-run — must
#                         match the committed FLEET_BENCH.json bit-for-bit
#                         (the no-treatment control).
#   load_blend            + the load-aware routing policy
#                         (kvcache/routing.py select): prefix_frac minus
#                         normalized load over every routable pod.
#   load_blend_autoscale  + elastic membership: AUTOSCALE_SCALE_OUT_PODS
#                         pods join mid-run (warm-before-serve: top-K hot
#                         prefixes land via the data plane / the joiner's
#                         own idle compute BEFORE it takes traffic) and
#                         one pod leaves late (drain + quarantine).
# The yardstick is the UNSATURATED operating point: the ladder's qps_20
# precise row (queues still clear there; qps_40 is past the cliff).
# Targets: autoscale TTFT p50 <= 3x the unsaturated baseline, hit-rate
# retention >= 80% of precise-at-qps_40, zero stale-partition scores in
# the reassignment audit, and zero silent drops (every offered request
# returns a TTFT — the sim has no place to lose one; the service-surface
# sheds are explicit 429/RESOURCE_EXHAUSTED, tested in tests/).
AUTOSCALE_QPS = 40.0
AUTOSCALE_BASELINE_QPS = QPS  # 20.0 — the committed unsaturated row
AUTOSCALE_SCALE_OUT_AT_S = 1.0
AUTOSCALE_SCALE_OUT_PODS = 8
AUTOSCALE_SCALE_IN_AT_S = 7.0
AUTOSCALE_WARM_TOP_K = 6       # ~6 shared prefixes fit a 512-page joiner
AUTOSCALE_WARM_HOTNESS = 0.5
AUTOSCALE_POLICY = {
    "policy": "load_blend",
    # One full prefix hit is worth ~2 units of normalized load: the
    # policy diverts when the queue cost clearly exceeds the cache win.
    "load_weight": 0.25,
    "queue_depth_norm": 4.0,
    "busy_norm_s": 1.0,
    "preemption_norm": 8.0,
}
# Live-reassignment audit leg: a 2-replica partition-gated cluster serves
# the capacity replay while one pod's stream is handed off mid-run; EVERY
# request's ownership-merged answer is compared against the monolithic
# index (which digests all streams) — any divergence is a stale-partition
# score.
REASSIGN_CHECK_REPLICAS = 2
REASSIGN_CHECK_AT_S = 4.0
REASSIGN_CHECK_POD = "pod-3"
REASSIGN_CHECK_REQUESTS = 150


def run_autoscale_arm(
    qps: float, routing_policy=None, autoscale: bool = False, seed: int = 42
):
    """One capacity-regime replay under (policy, elasticity). Returns
    (ttfts, hit_rate, extras)."""
    requests, conversations, rng = build_capacity_workload(seed=seed, qps=qps)
    membership = None
    health = None
    if autoscale:
        from llm_d_kv_cache_manager_tpu.fleethealth import FleetHealthConfig

        membership = {
            "warm_top_k": AUTOSCALE_WARM_TOP_K,
            "warm_hotness": AUTOSCALE_WARM_HOTNESS,
        }
        # Production windows (30s/120s): inert on a ~10s replay — the
        # tracker is here as the leave path's quarantine target, not as a
        # fault detector.
        health = FleetHealthConfig()
    sim = FleetSim(
        "precise",
        pages_per_pod=CAPACITY_PAGES_PER_POD,
        routing_policy=routing_policy,
        membership=membership,
        health_config=health,
    )
    ttfts = []
    events = {}
    scaled_out = scaled_in = False
    try:
        for arrival, conv_id in requests:
            if (
                autoscale and not scaled_out
                and arrival >= AUTOSCALE_SCALE_OUT_AT_S
            ):
                sim.now = max(sim.now, AUTOSCALE_SCALE_OUT_AT_S)
                events["scale_out"] = {
                    "at_s": AUTOSCALE_SCALE_OUT_AT_S,
                    "pods": AUTOSCALE_SCALE_OUT_PODS,
                    "joins": sim.scale_out(AUTOSCALE_SCALE_OUT_PODS),
                }
                scaled_out = True
            if (
                autoscale and not scaled_in
                and arrival >= AUTOSCALE_SCALE_IN_AT_S
            ):
                sim.now = max(sim.now, AUTOSCALE_SCALE_IN_AT_S)
                events["scale_in"] = {
                    "at_s": AUTOSCALE_SCALE_IN_AT_S,
                    "leave": sim.scale_in(0),
                }
                scaled_in = True
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            ttfts.append(sim.serve(arrival, prompt))
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        policy_stats = None
        if sim.routing_policy is not None:
            st = sim.routing_policy.status()
            policy_stats = {
                "policy": st["policy"],
                "decisions": st["stats"]["adjusted_requests"],
                "overrides": st["stats"]["overrides"],
            }
        extras = {
            "preemptions": sim.preemptions,
            "final_n_pods": sim.n_pods,
            "events": events,
            "warm": dict(sim.warm_stats),
            "routing_policy": policy_stats,
            "membership": (
                sim.membership.status()["stats"]
                if sim.membership is not None else None
            ),
        }
        return ttfts, hit_rate, extras
    finally:
        sim.shutdown()


def run_reassignment_check(seed: int = 42):
    """Live partition handoff under traffic, audited request-by-request.

    A 2-replica partition-gated cluster (PartitionTable gates, shared
    with the scatter-gather merge) serves the capacity replay; at
    REASSIGN_CHECK_AT_S the membership service hands REASSIGN_CHECK_POD's
    stream to the other replica (two-phase: pause → drain → watermark →
    entry move → seq-floor journal replay → resume). Every request's
    merged cluster answer is compared with the monolithic indexer's —
    stale_partition_scores MUST be zero."""
    requests, conversations, rng = build_capacity_workload(seed=seed, qps=QPS)
    requests = requests[:REASSIGN_CHECK_REQUESTS]
    sim = FleetSim(
        "precise",
        pages_per_pod=CAPACITY_PAGES_PER_POD,
        cluster_replicas=REASSIGN_CHECK_REPLICAS,
        membership={},
        tail_journal_len=REPLICATION_TAIL_JOURNAL,
        verify_cluster_scores=True,
    )
    handoff = None
    try:
        for arrival, conv_id in requests:
            if handoff is None and arrival >= REASSIGN_CHECK_AT_S:
                sim.now = max(sim.now, arrival)
                old = sim.partition_table.replica_for(REASSIGN_CHECK_POD)
                handoff = sim.membership.reassign_pod(
                    REASSIGN_CHECK_POD,
                    (old + 1) % REASSIGN_CHECK_REPLICAS,
                )
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            sim.serve(arrival, prompt)
        return {
            "replicas": REASSIGN_CHECK_REPLICAS,
            "moved_pod": REASSIGN_CHECK_POD,
            "reassign_at_s": REASSIGN_CHECK_AT_S,
            "requests": len(requests),
            "verified_requests": sim.cluster_verified_requests,
            "stale_partition_scores": sim.stale_partition_scores,
            "handoff": handoff,
            "prefix_hit_rate": round(
                sim.hit_tokens / max(sim.total_tokens, 1), 4
            ),
        }
    finally:
        sim.shutdown()


def main_autoscale(args):
    """--autoscale: the saturation-resilience comparison. Writes
    benchmarking/FLEET_BENCH_AUTOSCALE.json."""
    t_start = time.time()
    # The two no-treatment arms ride run_strategy (the EXACT ladder code
    # path) so they must reproduce the committed qps_20/qps_40 rows.
    ttft_base, hit_base, _, ex_base = run_strategy(
        "precise", qps=AUTOSCALE_BASELINE_QPS, workload="capacity",
        pages_per_pod=CAPACITY_PAGES_PER_POD,
    )
    ttft_sat, hit_sat, _, ex_sat = run_strategy(
        "precise", qps=AUTOSCALE_QPS, workload="capacity",
        pages_per_pod=CAPACITY_PAGES_PER_POD,
    )
    ttft_blend, hit_blend, ex_blend = run_autoscale_arm(
        AUTOSCALE_QPS, routing_policy=AUTOSCALE_POLICY, seed=args.seed
    )
    # Control: scale-out WITHOUT the policy — separates what new capacity
    # buys from what routing it well buys.
    ttft_scale, hit_scale, ex_scale = run_autoscale_arm(
        AUTOSCALE_QPS, routing_policy=None, autoscale=True, seed=args.seed
    )
    ttft_auto, hit_auto, ex_auto = run_autoscale_arm(
        AUTOSCALE_QPS, routing_policy=AUTOSCALE_POLICY, autoscale=True,
        seed=args.seed,
    )
    reassignment = run_reassignment_check(seed=args.seed)

    def arm_stats(ttfts, hit, extra=None):
        out = {
            "ttft_p50_s": round(p50(ttfts), 4),
            "ttft_p90_s": round(p90(ttfts), 4),
            "ttft_mean_s": round(sum(ttfts) / len(ttfts), 4),
            "prefix_hit_rate": round(hit, 4),
            "requests_offered": len(ttfts),
            "requests_served": len(ttfts),  # every TTFT returned: no
            # silent drops exist in this serving model; service-surface
            # sheds are explicit 429/RESOURCE_EXHAUSTED (tests/)
        }
        if extra:
            out.update(extra)
        return out

    arms = {
        "unsaturated_baseline": arm_stats(
            ttft_base, hit_base,
            {"qps": AUTOSCALE_BASELINE_QPS,
             "preemptions": ex_base["preemptions"]},
        ),
        "precise_saturated": arm_stats(
            ttft_sat, hit_sat,
            {"qps": AUTOSCALE_QPS, "preemptions": ex_sat["preemptions"]},
        ),
        "load_blend": arm_stats(
            ttft_blend, hit_blend,
            {"qps": AUTOSCALE_QPS, **ex_blend},
        ),
        "precise_autoscale": arm_stats(
            ttft_scale, hit_scale,
            {"qps": AUTOSCALE_QPS, **ex_scale},
        ),
        "load_blend_autoscale": arm_stats(
            ttft_auto, hit_auto,
            {"qps": AUTOSCALE_QPS, **ex_auto},
        ),
    }
    base_p50 = arms["unsaturated_baseline"]["ttft_p50_s"]
    auto = arms["load_blend_autoscale"]
    ratio_vs_unsaturated = round(auto["ttft_p50_s"] / max(base_p50, 1e-9), 3)
    hit_retention = round(
        auto["prefix_hit_rate"] / max(arms["precise_saturated"]
                                      ["prefix_hit_rate"], 1e-9), 4
    )
    stats = {
        "config": {
            "workload": (
                f"capacity regime (single-turn fan-in over "
                f"{CAPACITY_GROUPS} shared-prefix groups), the committed "
                "qps ladder's saturation row"
            ),
            "qps_saturated": AUTOSCALE_QPS,
            "qps_unsaturated_baseline": AUTOSCALE_BASELINE_QPS,
            "n_pods": N_PODS,
            "pages_per_pod": CAPACITY_PAGES_PER_POD,
            "requests": CAPACITY_REQUESTS,
            "seed": args.seed,
            "scale_out": {
                "at_s": AUTOSCALE_SCALE_OUT_AT_S,
                "pods": AUTOSCALE_SCALE_OUT_PODS,
                "warm_top_k": AUTOSCALE_WARM_TOP_K,
                "warm_hotness_threshold": AUTOSCALE_WARM_HOTNESS,
            },
            "scale_in": {"at_s": AUTOSCALE_SCALE_IN_AT_S, "pod": "pod-0"},
            "routing_policy": AUTOSCALE_POLICY,
        },
        "arms": arms,
        "reassignment": reassignment,
        "ttft_p50_vs_unsaturated_baseline": ratio_vs_unsaturated,
        "hit_rate_retention_vs_precise_saturated": hit_retention,
        "targets": {
            "ttft_p50_within_3x_unsaturated": ratio_vs_unsaturated <= 3.0,
            "hit_retention_ge_80pct": hit_retention >= 0.8,
            "zero_stale_partition_scores": (
                reassignment["stale_partition_scores"] == 0
            ),
            "no_silent_drops": all(
                a["requests_served"] == a["requests_offered"]
                for a in arms.values()
            ),
        },
        "wall_s": round(time.time() - t_start, 1),
    }
    # Acceptance cross-check: the no-treatment arms must reproduce the
    # committed ladder rows bit-for-bit (same code path, same seed).
    fleet_bench = os.path.join(REPO, "benchmarking", "FLEET_BENCH.json")
    if os.path.exists(fleet_bench):
        with open(fleet_bench) as f:
            ladder = json.load(f).get("qps_ladder", {})
        committed_sat = ladder.get(f"qps_{AUTOSCALE_QPS:g}", {}).get(
            "precise", {}
        )
        stats["ladder_cross_check"] = {
            "committed_qps40_precise_ttft_p50_s": committed_sat.get(
                "ttft_p50_s"
            ),
            "rerun_qps40_precise_ttft_p50_s": arms["precise_saturated"][
                "ttft_p50_s"
            ],
            "bit_identical": (
                committed_sat.get("ttft_p50_s")
                == arms["precise_saturated"]["ttft_p50_s"]
                and committed_sat.get("prefix_hit_rate")
                == arms["precise_saturated"]["prefix_hit_rate"]
            ),
        }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_AUTOSCALE.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "autoscale_ttft_p50_vs_unsaturated",
        "value": ratio_vs_unsaturated,
        "unit": "x (target <= 3)",
        "saturated_precise_p50_s": arms["precise_saturated"]["ttft_p50_s"],
        "load_blend_p50_s": arms["load_blend"]["ttft_p50_s"],
        "autoscale_p50_s": auto["ttft_p50_s"],
        "hit_rate_retention": hit_retention,
        "stale_partition_scores": reassignment["stale_partition_scores"],
        "policy_overrides": (ex_auto.get("routing_policy") or {}).get(
            "overrides"
        ),
        "targets_met": all(stats["targets"].values()),
        "source": "benchmarking/FLEET_BENCH_AUTOSCALE.json",
    }))


# Resource-governor pressure scenario (--pressure; resourcegov/): the
# adversarial workloads (workloads/adversarial.py) replayed through the
# sim's REAL control-plane structures — chain memo, prefix store, session
# table, popularity tracker, KV-block index — with a ResourceAccountant
# metering them on the same evaluation-grid idiom as the autopilot arm:
#   ungoverned   flood + session storm, no governor: the accounted-bytes
#                column must grow monotonically PAST 2x the budget (the
#                leak the governor exists to cap).
#   governed     the SAME replay with the governor ticking on the grid:
#                accounted bytes must hold <= budget for the whole run
#                while retaining >= 80% of the ungoverned hit rate (the
#                ladder sheds re-derivable support state before index
#                capacity, and index sheds take the LRU tail — flood
#                garbage — first).
#   churn storm  the cache-friendly churn trace over an elastic fleet
#                whose roster follows churn_schedule(): with the
#                DepartureReaper wired to membership leave, per-pod map
#                cardinality (fleet health / load / anti-entropy rows)
#                tracks the LIVE pods; the unreaped arm accumulates one
#                row per pod that EVER existed.
#   no_pressure  the headline precise arm rerun with resourcegov
#                importable but OFF — its committed FLEET_BENCH.json
#                fields must reproduce byte-identically (md5 over the
#                canonical serialization), the feature-off bit-identity
#                pin every control-plane PR carries.
# Oversized pods (PRESSURE_PAGES_PER_POD) keep device eviction from
# masking control-plane growth: the index must grow with every unique
# flood prompt, not plateau at device capacity.
PRESSURE_BUDGET_MB = 8.0
PRESSURE_EVAL_DT_S = 1.0
PRESSURE_COOLDOWN_S = 1.0
PRESSURE_PAGES_PER_POD = 8192
# Per-entry byte estimates, mirrored from the service wiring
# (api/http_service.py): estimates by design — the budget is a policy
# ceiling over the accounted sum, deterministic under the sim clock.
PRESSURE_BYTES_PER_ENTRY = {
    "sessions": 512.0,
    "popularity": 256.0,
    "chain_memo": 256.0,
    "prefix_store": 4096.0,
    "index": 1024.0,
}


def build_pressure_requests():
    """Flood + session storm merged into one arrival-ordered stream.
    Session namespaces are disjoint (f* vs x*), so the merge is a pure
    interleave: per-session turn order survives the sort."""
    from llm_d_kv_cache_manager_tpu.workloads import (
        generate_flood,
        generate_session_explosion,
    )

    flood = generate_flood()
    storm = generate_session_explosion()
    requests = flood.requests() + storm.requests()
    requests.sort(key=lambda r: (r.arrival_s, r.session, r.turn))
    return flood, storm, requests


def _pressure_accountant(sim):
    """Meter the sim's live control-plane structures — the same opt-in
    hooks (`entries()` + `shed(fraction)`) the service wiring registers,
    pointed at the sim's instances."""
    from llm_d_kv_cache_manager_tpu.resourcegov import (
        Meter,
        ResourceAccountant,
    )

    acc = ResourceAccountant()
    acc.register(Meter(
        "sessions",
        entries=sim.session_table.sessions,
        bytes_per_entry=PRESSURE_BYTES_PER_ENTRY["sessions"],
        shed=sim.session_table.shed,
    ))
    acc.register(Meter(
        "popularity",
        entries=sim.popularity.entries,
        bytes_per_entry=PRESSURE_BYTES_PER_ENTRY["popularity"],
        shed=sim.popularity.shed,
    ))
    memo = sim.indexer.token_processor.chain_memo
    if memo is not None:
        acc.register(Meter(
            "chain_memo",
            entries=memo.entries,
            bytes_per_entry=PRESSURE_BYTES_PER_ENTRY["chain_memo"],
            shed=memo.shed,
        ))
    store = sim.indexer.prefix_store
    if hasattr(store, "entries") and hasattr(store, "shed"):
        acc.register(Meter(
            "prefix_store",
            entries=store.entries,
            bytes_per_entry=PRESSURE_BYTES_PER_ENTRY["prefix_store"],
            shed=store.shed,
        ))
    index = sim.indexer.kv_block_index
    inner = getattr(index, "inner", index)

    def _index_entries():
        sizes = getattr(inner, "segment_sizes", None)
        if sizes is not None:
            return sum(sizes())
        data = getattr(inner, "_data", None)
        return len(data) if data is not None else 0

    acc.register(Meter(
        "index",
        entries=_index_entries,
        bytes_per_entry=PRESSURE_BYTES_PER_ENTRY["index"],
        shed=getattr(inner, "shed", None),
    ))
    return acc


def run_pressure_arm(governed: bool):
    """One adversarial replay (flood + session storm). `governed` wires
    a ResourceGovernor over the accountant and ticks it on the grid;
    the ungoverned arm samples the same accountant without actuating."""
    from llm_d_kv_cache_manager_tpu.resourcegov import (
        ResourceGovConfig,
        ResourceGovernor,
    )

    _flood, _storm, requests = build_pressure_requests()
    sim = FleetSim(
        "precise",
        pages_per_pod=PRESSURE_PAGES_PER_POD,
        placement=dict(AUTOPILOT_PLACEMENT_BASE),
        prediction={},
    )
    accountant = _pressure_accountant(sim)
    governor = None
    if governed:
        governor = ResourceGovernor(
            accountant,
            ResourceGovConfig(
                budget_mb=PRESSURE_BUDGET_MB,
                cooldown_s=PRESSURE_COOLDOWN_S,
                min_interval_s=PRESSURE_EVAL_DT_S,
            ),
            clock=lambda: sim.now,
        )
    timeline = []  # (t, accounted_bytes, level) on the evaluation grid
    next_eval = [PRESSURE_EVAL_DT_S]

    def _evaluate(now):
        # Governed samples are taken AFTER the tick: the acceptance is
        # on what the governor leaves behind, not on the instant before
        # it acts.
        if governor is not None:
            governor.tick(now)
        timeline.append((
            round(now, 3),
            int(accountant.total_bytes()),
            governor.level if governor is not None else "off",
        ))

    try:
        for req in requests:
            while next_eval[0] <= req.arrival_s:
                _evaluate(next_eval[0])
                next_eval[0] += PRESSURE_EVAL_DT_S
            sim.serve(req.arrival_s, req.prompt,
                      response_words=req.output_len)
        _evaluate(next_eval[0])  # final sample past the last arrival
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        peak = max(b for _t, b, _lvl in timeline)
        return {
            "requests": len(requests),
            "hit_rate": round(hit_rate, 4),
            "timeline": timeline,
            "peak_accounted_bytes": peak,
            "final_accounted_bytes": timeline[-1][1],
            "meters": {
                name: doc["entries"]
                for name, doc in sorted(accountant.snapshot().items())
            },
            "governor": governor.status() if governor is not None else None,
        }
    finally:
        sim.shutdown()


def run_pressure_churn(reaped: bool):
    """The churn-storm leg: the cache-friendly trace served while the
    roster follows churn_schedule() through the full membership
    choreography. `reaped` wires a DepartureReaper's forget_pod fan-out
    to every leave — the treatment whose per-pod map cardinality must
    track LIVE pods; the unreaped arm shows the cumulative leak."""
    from llm_d_kv_cache_manager_tpu.fleethealth import FleetHealthConfig
    from llm_d_kv_cache_manager_tpu.resourcegov import DepartureReaper
    from llm_d_kv_cache_manager_tpu.workloads import (
        ChurnStormConfig,
        churn_schedule,
        generate_churn_storm,
    )

    cfg = ChurnStormConfig()
    requests = generate_churn_storm(cfg).requests()
    schedule = churn_schedule(cfg)
    sim = FleetSim(
        "precise",
        n_pods=cfg.base_pods,
        routing_policy=dict(AUTOSCALE_POLICY),
        membership={},
        health_config=FleetHealthConfig(),
        antientropy=dict(AUTOPILOT_AE_CFG, seed=42),
    )
    reaper = None
    if reaped:
        reaper = DepartureReaper()
        reaper.register("fleethealth", sim.health.forget_pod)
        reaper.register("load", sim.load_tracker.forget_pod)
        reaper.register("antientropy", sim.antientropy.forget_pod)
    # schedule name ("churn-i") -> sim pod id ("pod-j"); join order.
    roster = {}
    live = {f"pod-{i}" for i in range(cfg.base_pods)}
    ever = set(live)
    cardinality = []  # (t, live, ever, fleethealth, load, antientropy)

    def _record(now):
        cardinality.append((
            round(now, 3),
            len(live),
            len(ever),
            sim.health.entries(),
            sim.load_tracker.entries(),
            sim.antientropy.entries(),
        ))

    def _apply(event):
        at, action, name = event
        sim.now = max(sim.now, at)
        if action == "join":
            joins = sim.scale_out(1)
            pod_id = next(iter(joins))
            roster[name] = pod_id
            live.add(pod_id)
            ever.add(pod_id)
        else:
            pod_id = roster[name]
            sim.scale_in(int(pod_id.split("-")[1]))
            live.discard(pod_id)
            if reaper is not None:
                reaper.reap(pod_id)
        _record(sim.now)

    pending = list(schedule)
    try:
        for req in requests:
            while pending and pending[0][0] <= req.arrival_s:
                _apply(pending.pop(0))
            sim.serve(req.arrival_s, req.prompt,
                      response_words=req.output_len)
        # The roster script outlives the short trace on purpose: the
        # leak (or its absence) keeps accumulating with zero traffic.
        while pending:
            _apply(pending.pop(0))
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        final = cardinality[-1]
        return {
            "requests": len(requests),
            "churn_events": len(schedule),
            "hit_rate": round(hit_rate, 4),
            "cardinality": cardinality,
            "final": {
                "live_pods": final[1],
                "ever_pods": final[2],
                "fleethealth_rows": final[3],
                "load_rows": final[4],
                "antientropy_rows": final[5],
            },
            "reaper": reaper.status() if reaper is not None else None,
        }
    finally:
        sim.shutdown()


# The committed-headline fields the no-pressure arm must reproduce.
PRESSURE_PIN_FIELDS = (
    "prefix_hit_rate", "ttft_p50_precise_s", "ttft_mean_precise_s",
)


def run_pressure_baseline():
    """Feature-off bit-identity pin: rerun the headline precise +
    round-robin arms with resourcegov imported (the code is resident,
    the governor simply never constructed — exactly the RESOURCEGOV=0
    service) and md5-compare the canonical serialization of the
    headline fields against the committed FLEET_BENCH.json."""
    import hashlib

    import llm_d_kv_cache_manager_tpu.resourcegov  # noqa: F401

    ttft_precise, hit_rate, _read_p50, _ = run_strategy("precise")
    ttft_rr, _, _, _ = run_strategy("round_robin")
    rerun = {
        "prefix_hit_rate": round(hit_rate, 4),
        "ttft_p50_precise_s": round(p50(ttft_precise), 4),
        "ttft_mean_precise_s": round(
            sum(ttft_precise) / len(ttft_precise), 4
        ),
    }
    doc = {
        "rerun": rerun,
        "rerun_ttft_p50_round_robin_s": round(p50(ttft_rr), 4),
    }
    fleet_bench = os.path.join(REPO, "benchmarking", "FLEET_BENCH.json")
    if os.path.exists(fleet_bench):
        with open(fleet_bench, "rb") as f:
            raw = f.read()
        committed = {
            k: json.loads(raw).get(k) for k in PRESSURE_PIN_FIELDS
        }
        canon = lambda d: json.dumps(  # noqa: E731
            d, sort_keys=True, separators=(",", ":")
        ).encode()
        doc.update({
            "committed": committed,
            "fleet_bench_md5": hashlib.md5(raw).hexdigest(),
            "rerun_md5": hashlib.md5(canon(rerun)).hexdigest(),
            "committed_md5": hashlib.md5(canon(committed)).hexdigest(),
            "byte_identical": canon(rerun) == canon(committed),
        })
    else:
        doc["byte_identical"] = None
    return doc


def main_pressure(args):
    """--pressure: the resource-governor acceptance run. Writes
    benchmarking/FLEET_BENCH_PRESSURE.json."""
    t_start = time.time()
    ungoverned = run_pressure_arm(governed=False)
    governed = run_pressure_arm(governed=True)
    churn_reaped = run_pressure_churn(reaped=True)
    churn_unreaped = run_pressure_churn(reaped=False)
    baseline = run_pressure_baseline()

    budget_bytes = int(PRESSURE_BUDGET_MB * 1024 * 1024)
    un_bytes = [b for _t, b, _lvl in ungoverned["timeline"]]
    monotonic = all(b2 >= b1 for b1, b2 in zip(un_bytes, un_bytes[1:]))
    retention = round(
        governed["hit_rate"] / max(ungoverned["hit_rate"], 1e-9), 4
    )
    reaped_rows = [
        max(fh, ld) for _t, _lv, _ev, fh, ld, _ae
        in churn_reaped["cardinality"]
    ]
    reaped_live = [
        lv for _t, lv, _ev, _fh, _ld, _ae in churn_reaped["cardinality"]
    ]
    verdicts = {
        "governed_held_budget": (
            governed["peak_accounted_bytes"] <= budget_bytes
        ),
        "hit_retention_ge_80pct": retention >= 0.8,
        "ungoverned_monotonic": monotonic,
        "ungoverned_past_2x_budget": (
            ungoverned["peak_accounted_bytes"] > 2 * budget_bytes
        ),
        # Tracking live means bounded BY live at every churn sample;
        # the unreaped control must end with the cumulative roster.
        "churn_rows_track_live": all(
            rows <= lv for rows, lv in zip(reaped_rows, reaped_live)
        ),
        "churn_unreaped_cumulative": (
            churn_unreaped["final"]["fleethealth_rows"]
            >= churn_unreaped["final"]["ever_pods"] - 1
            > churn_unreaped["final"]["live_pods"]
        ),
        "no_pressure_bit_identical": baseline.get("byte_identical"),
    }
    stats = {
        "scenario": {
            "budget_mb": PRESSURE_BUDGET_MB,
            "eval_dt_s": PRESSURE_EVAL_DT_S,
            "cooldown_s": PRESSURE_COOLDOWN_S,
            "pages_per_pod": PRESSURE_PAGES_PER_POD,
            "bytes_per_entry": PRESSURE_BYTES_PER_ENTRY,
        },
        "arms": {
            "ungoverned": ungoverned,
            "governed": governed,
            "churn_reaped": churn_reaped,
            "churn_unreaped": churn_unreaped,
        },
        "no_pressure": baseline,
        "hit_retention": retention,
        "verdicts": verdicts,
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)
    artifact = {k: v for k, v in stats.items() if k != "wall_s"}
    out = os.path.join(REPO, "benchmarking", "FLEET_BENCH_PRESSURE.json")
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "metric": "pressure_hit_retention_governed_vs_ungoverned",
        "value": retention,
        "unit": "fraction (target >= 0.8)",
        "governed_peak_mb": round(
            governed["peak_accounted_bytes"] / 1024 / 1024, 2
        ),
        "ungoverned_peak_mb": round(
            ungoverned["peak_accounted_bytes"] / 1024 / 1024, 2
        ),
        "budget_mb": PRESSURE_BUDGET_MB,
        "churn_final_rows_reaped": churn_reaped["final"],
        "churn_final_rows_unreaped": churn_unreaped["final"],
        "verdicts_met": all(bool(v) for v in verdicts.values()),
        "source": "benchmarking/FLEET_BENCH_PRESSURE.json",
    }))


def run_batch_window_arm(window: int, qps: float = QPS):
    """The synthetic chat workload served through router arrival windows:
    requests are grouped into windows of `window` arrivals, each window
    scored by ONE `Indexer.score_many` call, then served in order. The
    prompt stream is built with the exact RNG call sequence of
    run_strategy (question then response per request), so the served
    prompts are identical to the flag-off run and any TTFT difference is
    purely a routing-decision difference."""
    requests, conversations, rng = build_workload(qps=qps)
    sim = FleetSim("precise", batch_window=window)
    ttfts = []
    window_buf = []
    try:
        for arrival, conv_id in requests:
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            conversations[conv_id] = (
                prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
            )
            window_buf.append((arrival, prompt))
            if len(window_buf) == window:
                ttfts.extend(sim.serve_batch(window_buf))
                window_buf = []
        if window_buf:
            ttfts.extend(sim.serve_batch(window_buf))
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        lat = sorted(sim.read_latencies)
        read_p50 = lat[len(lat) // 2] if lat else 0.0
        return ttfts, hit_rate, read_p50
    finally:
        sim.shutdown()


def main_batch_window(args):
    """--batch-window W: serve the synthetic headline precise arm through
    router arrival windows scored by `score_many`. Always runs the W=1
    pin first — one-item windows must route bit-identically to the
    per-request path (identical TTFT stream + hit rate) — then reports
    the requested window. Prints the verdict; commits nothing — the
    per-request artifacts stay the single source of truth."""
    w = args.batch_window
    t_start = time.time()
    ttft_single, hit_single, _, _ = run_strategy("precise")
    ttft_w1, hit_w1, _ = run_batch_window_arm(1)
    identical = ttft_single == ttft_w1 and hit_single == hit_w1
    out = {
        "metric": "batch_window_w1_bit_identical",
        "value": bool(identical),
        "window": w,
        "prefix_hit_rate_per_request": round(hit_single, 4),
        "prefix_hit_rate_w1": round(hit_w1, 4),
        "ttft_p50_per_request_s": round(p50(ttft_single), 4),
        "ttft_p50_w1_s": round(p50(ttft_w1), 4),
        "requests": len(ttft_single),
    }
    if w > 1:
        ttft_w, hit_w, read_w = run_batch_window_arm(w)
        out.update({
            "prefix_hit_rate_at_window": round(hit_w, 4),
            "ttft_p50_at_window_s": round(p50(ttft_w), 4),
            "read_path_p50_ms_at_window": round(read_w * 1e3, 3),
        })
    out["wall_s"] = round(time.time() - t_start, 1)
    print(json.dumps(out))
    if not identical:
        sys.exit(1)


def p50(xs):
    return sorted(xs)[len(xs) // 2]


def p90(xs):
    return sorted(xs)[min(int(len(xs) * 0.9), len(xs) - 1)]


def run_two_tier_comparison(baseline_precise=None, baseline_rr=None):
    """Same fleet under heavy HBM pressure, host tier off vs on: evicted
    blocks restore at DMA/DCN bandwidth instead of recomputing on the MXU.
    This is the serving behavior kv_connectors enables (VERDICT r1 #2).

    The host-tier-OFF baselines are identical deterministic configurations
    to the pressured strategy-arms runs; callers that already ran those
    pass them in as (ttfts, hit_rate) instead of paying two duplicate
    300-request simulations."""
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import native_available

    if not native_available():
        return {"skipped": "libkvtransfer.so not built"}

    if baseline_precise is None:
        ttfts, hit, _, _ = run_strategy(
            "precise", pages_per_pod=TWO_TIER_PAGES_PER_POD, host_tier=False
        )
        baseline_precise = (ttfts, hit)
    ttft_off, hit_off = baseline_precise
    ttft_on, hit_on, _, extras = run_strategy(
        "precise", pages_per_pod=TWO_TIER_PAGES_PER_POD, host_tier=True
    )
    # DCN leg: cache-oblivious (round-robin) routing lands requests on pods
    # that never computed the prefix — the data plane onboards the blocks
    # from peers instead of recomputing. Pods export committed pages on
    # free() via the sim's host tier, so peers can fetch them.
    ttft_rr_dp, hit_rr_dp, _, extras_rr = run_strategy(
        "round_robin", pages_per_pod=TWO_TIER_PAGES_PER_POD, host_tier=True
    )
    if baseline_rr is None:
        ttfts, hit, _, _ = run_strategy(
            "round_robin", pages_per_pod=TWO_TIER_PAGES_PER_POD, host_tier=False
        )
        baseline_rr = (ttfts, hit)
    ttft_rr, hit_rr = baseline_rr
    return {
        "hbm_pages_per_pod": TWO_TIER_PAGES_PER_POD,
        "gamma_s_per_token": GAMMA_HOST_RESTORE_S_PER_TOKEN,
        "gamma_source": _GAMMA_SOURCE,
        "delta_s_per_token": DELTA_DCN_ONBOARD_S_PER_TOKEN,
        "delta_source": _DELTA_SOURCE,
        "ttft_p50_hbm_only_s": round(p50(ttft_off), 4),
        "ttft_p50_two_tier_s": round(p50(ttft_on), 4),
        "ttft_p50_two_tier_speedup": round(
            p50(ttft_off) / max(p50(ttft_on), 1e-9), 3
        ),
        "hit_rate_hbm_only": round(hit_off, 4),
        "hit_rate_two_tier": round(hit_on, 4),
        "restored_blocks": extras["restored_blocks"],
        "onboarded_blocks": extras["onboarded_blocks"],
        "rr_ttft_p50_no_data_plane_s": round(p50(ttft_rr), 4),
        "rr_ttft_p50_with_data_plane_s": round(p50(ttft_rr_dp), 4),
        "rr_data_plane_speedup": round(
            p50(ttft_rr) / max(p50(ttft_rr_dp), 1e-9), 3
        ),
        "rr_hit_rate_no_data_plane": round(hit_rr, 4),
        "rr_hit_rate_with_data_plane": round(hit_rr_dp, 4),
        "rr_onboarded_blocks": extras_rr["onboarded_blocks"],
        "gated_blocks": extras["gated_blocks"] + extras_rr["gated_blocks"],
        "gate": "transfer-vs-recompute (engine/costs.py), sim-physics seeded",
    }


def run_qps_ladder(pressured_raw=None):
    """TTFT vs arrival rate, per routing arm — the shape of the reference's
    QPS ladders (/root/reference/benchmarking/37-capacity/README.md:342-347:
    precise holds 0.29s TTFT p90 at 20 QPS while load/random explode past
    170s). TTFT is the one metric this sim's clock models soundly (queue
    wait + prefill compute), so the ladder reports TTFT only; throughput
    claims stay with the measured benches. Arms run under the pressured
    pool size where routing quality decides whether prefill queues clear.

    `pressured_raw` ({arm: (ttfts, hit)}) lets the qps=20 row reuse
    main()'s already-run pressured arms (identical deterministic configs)
    instead of paying three duplicate 300-request simulations — the same
    reuse contract as run_two_tier_comparison."""
    arms = ("precise", "estimated", "load", "round_robin")
    ladder = {}
    for qps in (10.0, 20.0, 40.0):
        row = {}
        for arm in arms:
            if qps == QPS and pressured_raw and arm in pressured_raw:
                ttfts, hit, ex = pressured_raw[arm]
            else:
                ttfts, hit, _, ex = run_strategy(
                    arm, qps=qps, workload="capacity",
                    pages_per_pod=CAPACITY_PAGES_PER_POD,
                )
            row[arm] = {
                "ttft_p50_s": round(p50(ttfts), 4),
                "ttft_p90_s": round(p90(ttfts), 4),
                "prefix_hit_rate": round(hit, 4),
                "preemptions": ex["preemptions"],
            }
        row["precise_vs_round_robin_p90"] = round(
            row["round_robin"]["ttft_p90_s"]
            / max(row["precise"]["ttft_p90_s"], 1e-9), 1
        )
        ladder[f"qps_{qps:g}"] = row
    return ladder


def run_winning_regime():
    """Scale-out warm-up, in the regime where the data plane WINS.

    Transfer beats recompute when a model carries few KV bytes per token of
    compute (engine/costs.py): here a wide-MQA int8-KV model class —
    ~6.7 GFLOP/token of recompute against ~1 KB/token of KV — whose
    per-token alpha/gamma/delta are derived from the SAME measured rig
    rates as everything else (DEVICE_BENCH.json; assumed v5e rates only if
    the artifact is missing). Scenario: a fleet serves multi-turn
    conversations; a fresh pod joins (scale-up / failover replacement) and
    the next wave of every conversation is rebalanced onto it. With the
    data plane the new pod onboards each conversation's prefix from its
    home pod over DCN (real connector, real index lookups, gate admits);
    without, it recomputes every prefix from scratch."""
    from llm_d_kv_cache_manager_tpu.kv_connectors.connector import native_available

    if not native_available():
        return {"skipped": "libkvtransfer.so not built"}

    alpha_w, gamma_w, delta_w, rates_source = _winning_regime_constants()

    def run(data_plane: bool):
        rng = random.Random(7)
        conversations = shared_prefix_conversations(rng, 6, 3, SYSTEM_PROMPT_WORDS)
        conv_ids = list(conversations)
        sim = FleetSim(
            "precise", pages_per_pod=TWO_TIER_PAGES_PER_POD,
            host_tier=data_plane, alpha=alpha_w, gamma=gamma_w, delta=delta_w,
        )
        new_pod = N_PODS - 1
        try:
            # Phase 1: one turn per conversation on home pods 0..N-2.
            arrival = 0.0
            for i, c in enumerate(conv_ids):
                sim.route_override = lambda p, i=i: i % (N_PODS - 1)
                prompt = conversations[c] + " [user] " + _text(rng, QUESTION_WORDS)
                arrival += rng.expovariate(QPS)
                sim.serve(arrival, prompt)
                conversations[c] = (
                    prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
                )
            # Phase 2: the next turn of EVERY conversation lands on the new
            # pod, closed-loop (one request in flight — the TTFT gap is
            # pure warm-up cost, transfer vs recompute, the same
            # methodology as the device fleet bench's closed-loop note).
            arrival += 5.0
            sim.route_override = lambda p: new_pod
            cold_ttfts = []  # group-first requests: the warm-up cost itself
            warm_ttfts = []  # later users hit the now-warm HBM in BOTH arms
            seen_groups = set()
            for c in conv_ids:
                prompt = conversations[c] + " [user] " + _text(rng, QUESTION_WORDS)
                arrival = max(arrival, sim.pod_free_at[new_pod]) + 0.01
                ttft = sim.serve(arrival, prompt)
                group = c.split("-")[0]
                if group in seen_groups:
                    warm_ttfts.append(ttft)
                else:
                    seen_groups.add(group)
                    cold_ttfts.append(ttft)
            return cold_ttfts, warm_ttfts, (
                sim.onboarded_blocks + sim.restored_blocks
            )
        finally:
            sim.shutdown()

    cold_dp, warm_dp, moved = run(True)
    cold_nodp, warm_nodp, _ = run(False)
    return {
        "scenario": "scale-out warm-up: fresh pod onboards rebalanced "
                    "conversations' prefixes from home pods over DCN; "
                    "cold = each group's first request on the new pod "
                    "(the warm-up cost itself), warm = later users, whose "
                    "restorable prefix is already resident and whose "
                    "never-computed suffix recomputes in BOTH arms (the "
                    "warm p50s should therefore be ~equal — an in-artifact "
                    "control)",
        "model_class": "wide MQA + int8 KV (d_model 8192, n_layers 4, "
                       "n_kv_heads 1): ~6.7 GF/token vs ~1.06 KB/token",
        "rates_source": rates_source,
        "alpha_recompute_s_per_token": round(alpha_w, 8),
        "gamma_staged_s_per_token": round(gamma_w, 8),
        "delta_dcn_s_per_token": round(delta_w, 8),
        "requests": len(cold_dp) + len(warm_dp),
        "cold_requests": len(cold_dp),
        "blocks_moved": moved,
        "cold_ttft_p50_recompute_s": round(p50(cold_nodp), 4),
        "cold_ttft_p50_data_plane_s": round(p50(cold_dp), 4),
        "cold_ttft_p50_speedup": round(
            p50(cold_nodp) / max(p50(cold_dp), 1e-9), 3
        ),
        "warm_ttft_p50_recompute_s": round(p50(warm_nodp), 4),
        "warm_ttft_p50_data_plane_s": round(p50(warm_dp), 4),
    }


def main():
    t_start = time.time()
    # Headline arms at the default pool size (BASELINE.json continuity).
    # The precise arm runs with the tracing spine ON so the round's stats
    # carry a per-stage attribution of the real read path + write plane
    # (obs/); tracing is wall-clock-only, so the deterministic sim outputs
    # (TTFT, hit rate, routing) are bit-identical either way. Like
    # read_path_p50_ms, the attribution is stderr-stats only — wall-clock
    # numbers would dirty the committed artifact's deterministic reruns.
    from llm_d_kv_cache_manager_tpu import obs as _obs

    _obs.configure(_obs.ObsConfig(enabled=True, ring_capacity=4096))
    _obs.get_recorder().clear()
    ttft_precise, hit_rate, read_p50, _ = run_strategy("precise")
    _traces = _obs.get_recorder().recent()
    stage_attribution = {
        "read": _obs.aggregate_stages(
            [t for t in _traces if t.name == "read.get_pod_scores"]
        ),
        "write": _obs.aggregate_stages(
            [t for t in _traces if t.name == "write.digest"]
        ),
        "transfer": _obs.aggregate_stages(
            [t for t in _traces if t.name.startswith("transfer.")]
        ),
    }
    _obs.configure(_obs.ObsConfig(enabled=False))
    ttft_rr, _, _, _ = run_strategy("round_robin")

    # The reference's 4-arm comparison (precise / estimated / load / random,
    # 37-capacity/README.md:230-253) plus round_robin — run on the
    # capacity-regime workload (single-turn shared-prefix fan-in at ~70%
    # nominal resident fill, the 73-capacity shape) because that's where
    # the arms genuinely separate: estimation is only wrong once
    # eviction/preemption invalidates routing history, and multi-turn chat
    # re-warms whatever pod the conversation lands on.
    arms = ("precise", "estimated", "load", "random", "round_robin")
    results = {}
    raw = {}
    for arm in arms:
        ttfts, hit, _, ex = run_strategy(
            arm, workload="capacity", pages_per_pod=CAPACITY_PAGES_PER_POD
        )
        raw[arm] = (ttfts, hit, ex)
        results[arm] = {
            "ttft_p50_s": round(p50(ttfts), 4),
            "ttft_p90_s": round(p90(ttfts), 4),
            "ttft_mean_s": round(sum(ttfts) / len(ttfts), 4),
            "prefix_hit_rate": round(hit, 4),
            "preemptions": ex["preemptions"],
        }
    two_tier = run_two_tier_comparison()
    winning = run_winning_regime()
    ladder = run_qps_ladder(pressured_raw=raw)

    speedup = p50(ttft_rr) / max(p50(ttft_precise), 1e-9)
    stats = {
        "config": {
            "n_pods": N_PODS,
            "page_size": PAGE_SIZE,
            "pages_per_pod": PAGES_PER_POD,
            "pressured_pages_per_pod": TWO_TIER_PAGES_PER_POD,
            "n_groups": N_GROUPS,
            "users_per_group": USERS_PER_GROUP,
            "turns_per_user": TURNS_PER_USER,
            "qps": QPS,
            "itl_s_per_token": ITL_S_PER_TOKEN,
            "capacity_groups": CAPACITY_GROUPS,
            "capacity_pages_per_pod": CAPACITY_PAGES_PER_POD,
            "capacity_requests": CAPACITY_REQUESTS,
        },
        "sim_ttft_p50_speedup": round(speedup, 3),
        "ttft_p50_precise_s": round(p50(ttft_precise), 4),
        "ttft_p50_round_robin_s": round(p50(ttft_rr), 4),
        "ttft_mean_precise_s": round(sum(ttft_precise) / len(ttft_precise), 4),
        "ttft_mean_round_robin_s": round(sum(ttft_rr) / len(ttft_rr), 4),
        "prefix_hit_rate": round(hit_rate, 4),
        "read_path_p50_ms": round(read_p50 * 1e3, 3),
        "stage_attribution": stage_attribution,
        "strategies_under_pressure": {
            "hbm_pages_per_pod": CAPACITY_PAGES_PER_POD,
            "workload": (
                f"capacity regime: single-turn fan-in over "
                f"{CAPACITY_GROUPS} shared-prefix groups (~70% nominal "
                "resident fill) with decode page-holds and "
                "recompute-preemption — the 73-capacity shape"
            ),
            "arms": results,
        },
        "two_tier": two_tier,
        "data_plane_winning_regime": winning,
        "qps_ladder": ladder,
        "requests": len(ttft_precise),
        "wall_s": round(time.time() - t_start, 1),
    }
    # Device-measured mini-fleet (VERDICT r2 #3): fleet_device_bench.py runs
    # 2-4 real-compute EnginePods on the chip and measures wall-clock TTFT
    # through the full stack. Carry its committed result alongside the
    # simulated numbers so the round artifact holds both.
    fleet_dev = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarking", "FLEET_DEVICE_BENCH.json",
    )
    if os.path.exists(fleet_dev):
        with open(fleet_dev) as f:
            fd = json.load(f)
        stats["device_measured_fleet"] = {
            "ttft_p50_speedup": fd.get("ttft_p50_speedup"),
            "precise": fd.get("precise"),
            "round_robin": fd.get("round_robin"),
            "device": fd.get("device"),
            "full_mode_version": fd.get("config", {}).get(
                "full_mode_version", "v1"
            ),
        }
        # v2 artifacts carry the random arm (ADVICE r3) — don't drop it.
        if "random" in fd:
            stats["device_measured_fleet"]["random"] = fd["random"]
    print(json.dumps(stats), file=sys.stderr)
    # Machine-readable stats artifact (VERDICT r4 #1): gen_readme renders the
    # fleet section from THIS file, never from the driver's stderr tail —
    # BENCH_r04.json's tail was truncated mid-JSON and degraded the README.
    # Excluded from the committed artifact: wall_s, read_path_p50_ms and
    # stage_attribution (all wall-clock measured — they dirty the diff on
    # every otherwise identical deterministic rerun; the read path's
    # measured latencies and the committed per-stage attribution live in
    # MICRO_BENCH.json) and device_measured_fleet (a copy of
    # FLEET_DEVICE_BENCH.json; one source of truth, read directly by
    # gen_readme's fleet-device section).
    artifact = {
        k: v
        for k, v in stats.items()
        if k not in (
            "wall_s", "read_path_p50_ms", "stage_attribution",
            "device_measured_fleet",
        )
    }
    fleet_bench = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarking", "FLEET_BENCH.json",
    )
    with open(fleet_bench, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")

    # Final parsed line (VERDICT r4 #5): lead with the DEVICE-measured fleet
    # speedup when a chip-measured artifact exists — the simulated arm
    # saturated at 6.698x in r02 and stopped measuring progress. The sim
    # number rides along as a secondary field.
    dev = stats.get("device_measured_fleet", {})
    if dev.get("ttft_p50_speedup"):
        print(
            json.dumps(
                {
                    "metric": "device_fleet_ttft_p50_speedup_vs_round_robin",
                    "value": round(float(dev["ttft_p50_speedup"]), 3),
                    "unit": "x",
                    # BASELINE.json target: >=2x TTFT speedup vs round-robin.
                    "vs_baseline": round(
                        float(dev["ttft_p50_speedup"]) / 2.0, 3
                    ),
                    "sim_ttft_p50_speedup": round(speedup, 3),
                    "device": dev.get("device"),
                    "source": "benchmarking/FLEET_DEVICE_BENCH.json",
                }
            )
        )
    else:
        print(
            json.dumps(
                {
                    "metric": "ttft_p50_speedup_vs_round_robin",
                    "value": round(speedup, 3),
                    "unit": "x",
                    # BASELINE.json target: >=2x TTFT speedup vs round-robin.
                    "vs_baseline": round(speedup / 2.0, 3),
                }
            )
        )


def parse_args(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--workload", choices=("synthetic", "sharegpt"), default="synthetic",
        help="synthetic (default; the historical artifact-comparable "
             "workload) or sharegpt (trace-driven, distribution-faithful "
             "ShareGPT replay — the BASELINE metric's workload)",
    )
    ap.add_argument(
        "--trace", default=None, metavar="PATH",
        help="replay a recorded JSONL trace (workloads/trace.py schema) "
             "instead of generating one (sharegpt mode only)",
    )
    ap.add_argument(
        "--record", default=None, metavar="PATH",
        help="write the served trace to PATH as JSONL before running "
             "(sharegpt mode only)",
    )
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--arrival", choices=("poisson", "bursty"), default="poisson",
        help="session-arrival process for a generated sharegpt trace",
    )
    ap.add_argument(
        "--faults", action="store_true",
        help="run the fault-injection scenario (pod crash/restart, event "
             "stall, batch drop/dup/reorder) over the synthetic chat "
             "workload and write benchmarking/FLEET_BENCH_FAULTS.json",
    )
    ap.add_argument(
        "--chaos", action="store_true",
        help="run the transfer-plane chaos scenario (kv_connectors/faults."
             "py): per-peer corrupt/stall faults over the two-tier "
             "round-robin replay — end-to-end integrity vs the v1 wire, "
             "breakers vs bare timeouts — writing "
             "benchmarking/FLEET_BENCH_CHAOS.json",
    )
    ap.add_argument(
        "--placement", action="store_true",
        help="run the multi-tenant hotspot scenario (placement/ "
             "subsystem): Zipf tenant mix over per-tenant LoRA-isolated "
             "system prefixes; precise-only vs proactive K-way "
             "replication, writing benchmarking/FLEET_BENCH_PLACEMENT.json",
    )
    ap.add_argument(
        "--cluster-replicas", type=int, default=0, metavar="N",
        help="route the synthetic headline precise arm through N "
             "partitioned ClusterScorer replicas (cluster/) and verify the "
             "result is bit-identical to the monolithic arm; prints the "
             "verdict, writes no artifact",
    )
    ap.add_argument(
        "--batch-window", type=int, default=0, metavar="W",
        help="serve the synthetic headline precise arm through router "
             "arrival windows of W requests, each window scored by one "
             "Indexer.score_many call; always pins W=1 bit-identical to "
             "the per-request path first. Prints the verdict, writes no "
             "artifact",
    )
    ap.add_argument(
        "--autoscale", action="store_true",
        help="run the saturation-resilience scenario (load-aware routing "
             "policy + elastic membership: pods join warm-before-serve "
             "and leave drained mid-run at the qps ladder's saturation "
             "point, plus a live partition-reassignment audit), writing "
             "benchmarking/FLEET_BENCH_AUTOSCALE.json",
    )
    ap.add_argument(
        "--geo", action="store_true",
        help="run the hierarchical-federation scenario (federation/): "
             "home-pinned sessions with diurnal skew across regions, one "
             "region lost mid-replay; flat global fleet vs two-level "
             "federated routing, writing benchmarking/FLEET_BENCH_GEO.json",
    )
    ap.add_argument(
        "--anticipate", action="store_true",
        help="run the anticipatory-prefetch scenario (prediction/ "
             "subsystem): session predictor pre-lands each session's next "
             "turn during its think window; reactive vs anticipate arms "
             "over the ShareGPT and agentic replays, writing "
             "benchmarking/FLEET_BENCH_ANTICIPATE.json",
    )
    ap.add_argument(
        "--divergence", action="store_true",
        help="run the index anti-entropy scenario (antientropy/): a "
             "silent-evictor pod (cache wiped, stream seamless) under "
             "precise routing and a phantom-advertiser pod on the "
             "two-tier data plane, each with reconciliation vs an "
             "unreconciled control, writing "
             "benchmarking/FLEET_BENCH_DIVERGENCE.json",
    )
    ap.add_argument(
        "--autopilot", action="store_true",
        help="run the SLO-autopilot scenario (autopilot/ subsystem): one "
             "diurnal-load + fault-mix replay (qps swing, stalling "
             "transfer peer, silent evictor) served by static "
             "conservative/aggressive knob configs vs the closed-loop "
             "controller, plus a healthy bit-identity pair, writing "
             "benchmarking/FLEET_BENCH_AUTOPILOT.json",
    )
    ap.add_argument(
        "--pressure", action="store_true",
        help="run the resource-governor scenario (resourcegov/ "
             "subsystem): adversarial flood + session-storm replay "
             "governed vs ungoverned (byte budget, shed ladder), a "
             "churn-storm leg with departed-pod reaping, and the "
             "feature-off headline bit-identity pin, writing "
             "benchmarking/FLEET_BENCH_PRESSURE.json",
    )
    ap.add_argument(
        "--replication", action="store_true",
        help="run the indexer kill-and-restart scenario (FaultPlan "
             "indexer_crash) over the ShareGPT replay: cold restart vs "
             "snapshot+seq-tail-replay restore (cluster/), writing "
             "benchmarking/FLEET_BENCH_REPLICATION.json",
    )
    return ap.parse_args(argv)


if __name__ == "__main__":
    _args = parse_args()
    if _args.anticipate:
        main_anticipate(_args)
    elif _args.placement:
        main_placement(_args)
    elif _args.geo:
        main_geo(_args)
    elif _args.autoscale:
        main_autoscale(_args)
    elif _args.batch_window > 0:
        main_batch_window(_args)
    elif _args.cluster_replicas > 1:
        main_cluster_check(_args)
    elif _args.autopilot:
        main_autopilot(_args)
    elif _args.pressure:
        main_pressure(_args)
    elif _args.replication:
        main_replication(_args)
    elif _args.divergence:
        main_divergence(_args)
    elif _args.chaos:
        main_chaos(_args)
    elif _args.faults:
        main_faults(_args)
    elif _args.workload == "sharegpt":
        main_sharegpt(_args)
    else:
        main()

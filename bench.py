"""Fleet routing benchmark: prefix-aware scoring vs round-robin.

Reproduces the reference's headline experiment shape
(/root/reference/benchmarking/37-capacity, BASELINE.md) at simulation scale:
an 8-pod vLLM-TPU fleet serving multi-turn conversations with large shared
system prompts. Everything in the control plane is REAL — engines run real
block managers (prefix caching, LRU eviction) emitting real msgpack KVEvents
through the real sharded event pool into the real index; routing calls the
real `Indexer.get_pod_scores` read path (tokenization included). Only device
compute is modeled: TTFT = queue wait + alpha * uncached_prefill_tokens +
beta, with pods busy for prefill + output decode.

Target (BASELINE.json): >=80% prefix-cache hit rate and >=2x TTFT speedup vs
round-robin on an 8-replica fleet.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def _ensure_native() -> None:
    """Build the C hash core if missing (pure-Python fallback works, but the
    bench should measure the shipped fast path)."""
    import glob
    import subprocess

    if glob.glob(os.path.join(REPO, "llm_d_kv_cache_manager_tpu", "_kvtpu_native*.so")):
        return
    try:
        subprocess.run(
            [sys.executable, "setup.py", "build_ext"],
            cwd=os.path.join(REPO, "native"),
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception as e:  # noqa: BLE001 - fall back to pure Python
        print(f"native build skipped: {e}", file=sys.stderr)


_ensure_native()

from llm_d_kv_cache_manager_tpu.engine.block_manager import OutOfPagesError
from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig, Message
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

MODEL = "test-model"
FIXTURE = os.path.join(REPO, "tests", "fixtures", "test-model", "tokenizer.json")

# Fleet / engine shape.
N_PODS = 8
PAGE_SIZE = 16
PAGES_PER_POD = 2048  # 32k tokens of KV per pod -> eviction pressure is real

# Workload: groups share a system prompt; each user runs a multi-turn chat.
N_GROUPS = 12
USERS_PER_GROUP = 5
TURNS_PER_USER = 5
SYSTEM_PROMPT_WORDS = 900  # ~8x question size, like the 8k-shared-prefix runs
QUESTION_WORDS = 110
RESPONSE_WORDS = 120
QPS = 20.0

# TTFT model (v5e-class serving constants). Pods continuously batch decode,
# so the serialized per-pod resource is prefill compute; queue wait is time
# until the pod's prefill slot frees up.
ALPHA_PREFILL_S_PER_TOKEN = 0.00035
BETA_OVERHEAD_S = 0.02

_WORDS = (
    "the quick brown fox jumps over lazy dog system user assistant tool "
    "response message conversation template routing cache block prefix "
    "token mesh shard kernel attention page table fleet score index event"
).split()


def _text(rng: random.Random, n_words: int) -> str:
    return " ".join(rng.choice(_WORDS) for _ in range(n_words))


def build_workload(seed: int = 42):
    """Returns (requests, conversations, rng): time-ordered (arrival, conv_id)
    pairs plus per-conversation history seeded with group system prompts."""
    rng = random.Random(seed)
    system_prompts = [
        f"[group {g}] " + _text(rng, SYSTEM_PROMPT_WORDS) for g in range(N_GROUPS)
    ]
    conversations = {}  # conv_id -> history text
    turns = []
    for g in range(N_GROUPS):
        for u in range(USERS_PER_GROUP):
            conv_id = f"g{g}-u{u}"
            conversations[conv_id] = system_prompts[g]
            for t in range(TURNS_PER_USER):
                turns.append((conv_id, t, g, u))
    rng.shuffle(turns)

    arrival = 0.0
    requests = []
    for conv_id, _t, _g, _u in turns:
        arrival += rng.expovariate(QPS)
        requests.append((arrival, conv_id))
    return requests, conversations, rng


class FleetSim:
    def __init__(self, strategy: str, seed: int = 42):
        self.strategy = strategy
        self.indexer = Indexer(
            config=IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=PAGE_SIZE),
            ),
            tokenization_pool=TokenizationPool(
                TokenizersPoolConfig(workers=2, local_tokenizer_files={MODEL: FIXTURE}),
            ),
        )
        self.indexer.run()
        self.event_pool = EventPool(
            EventPoolConfig(concurrency=2),
            self.indexer.kv_block_index,
            self.indexer.token_processor,
        )
        self.event_pool.start(with_subscriber=False)

        self.pods = []
        for i in range(N_PODS):
            pod_id = f"pod-{i}"
            pod = EnginePod(
                EnginePodConfig(
                    pod_id=pod_id,
                    model_name=MODEL,
                    n_pages=PAGES_PER_POD,
                    page_size=PAGE_SIZE,
                    max_pages_per_seq=4096,
                ),
                event_sink=self._sink_for(pod_id),
            )
            self.pods.append(pod)
        self.pod_free_at = [0.0] * N_PODS
        self.rr_counter = 0
        self.read_latencies = []
        self.hit_tokens = 0
        self.total_tokens = 0

    def _sink_for(self, pod_id: str):
        def sink(batch):
            self.event_pool.add_task(
                Message(
                    topic=f"kv@{pod_id}@{MODEL}",
                    payload=batch.to_msgpack(),
                    seq=0,
                    pod_identifier=pod_id,
                    model_name=MODEL,
                )
            )

        return sink

    def route(self, prompt: str) -> int:
        if self.strategy == "round_robin":
            pod = self.rr_counter % N_PODS
            self.rr_counter += 1
            return pod
        t0 = time.perf_counter()
        scores = self.indexer.get_pod_scores(prompt, MODEL, [])
        self.read_latencies.append(time.perf_counter() - t0)
        if not scores:
            # No cache anywhere: least-loaded pod.
            return min(range(N_PODS), key=lambda i: self.pod_free_at[i])
        best = max(scores.values())
        candidates = [int(p.split("-")[1]) for p, s in scores.items() if s == best]
        return min(candidates, key=lambda i: self.pod_free_at[i])

    def serve(self, arrival: float, prompt: str) -> float:
        """Returns TTFT for this request under the simulated clock."""
        pod_idx = self.route(prompt)
        pod = self.pods[pod_idx]

        tokens = self.indexer.tokenizers_pool.tokenize(None, prompt, MODEL)
        self.total_tokens += len(tokens)
        try:
            state, cached = pod.prefill(tokens)
        except OutOfPagesError:
            # Sequence larger than the pod's whole free pool: serve uncached
            # (count the full prefill) without touching the cache.
            return BETA_OVERHEAD_S + ALPHA_PREFILL_S_PER_TOKEN * len(tokens)
        self.hit_tokens += min(cached, len(tokens))

        uncached = max(len(tokens) - cached, 0)
        prefill_s = BETA_OVERHEAD_S + ALPHA_PREFILL_S_PER_TOKEN * uncached
        start = max(arrival, self.pod_free_at[pod_idx])
        ttft = (start - arrival) + prefill_s
        self.pod_free_at[pod_idx] = start + prefill_s

        pod.free(state)  # pages stay cached for future turns
        self.event_pool.drain()
        return ttft

    def shutdown(self):
        self.event_pool.shutdown()
        self.indexer.shutdown()
        for pod in self.pods:
            pod.close()


def run_strategy(strategy: str):
    requests, conversations, rng = build_workload()
    sim = FleetSim(strategy)
    ttfts = []
    try:
        for arrival, conv_id in requests:
            question = _text(rng, QUESTION_WORDS)
            prompt = conversations[conv_id] + " [user] " + question
            ttfts.append(sim.serve(arrival, prompt))
            # Assistant response extends the conversation (next turn's prefix).
            conversations[conv_id] = prompt + " [assistant] " + _text(rng, RESPONSE_WORDS)
        hit_rate = sim.hit_tokens / max(sim.total_tokens, 1)
        lat = sorted(sim.read_latencies)
        read_p50 = lat[len(lat) // 2] if lat else 0.0
        return ttfts, hit_rate, read_p50
    finally:
        sim.shutdown()


def p50(xs):
    return sorted(xs)[len(xs) // 2]


def main():
    t_start = time.time()
    ttft_precise, hit_rate, read_p50 = run_strategy("precise")
    ttft_rr, _, _ = run_strategy("round_robin")

    speedup = p50(ttft_rr) / max(p50(ttft_precise), 1e-9)
    stats = {
        "ttft_p50_precise_s": round(p50(ttft_precise), 4),
        "ttft_p50_round_robin_s": round(p50(ttft_rr), 4),
        "ttft_mean_precise_s": round(sum(ttft_precise) / len(ttft_precise), 4),
        "ttft_mean_round_robin_s": round(sum(ttft_rr) / len(ttft_rr), 4),
        "prefix_hit_rate": round(hit_rate, 4),
        "read_path_p50_ms": round(read_p50 * 1e3, 3),
        "requests": len(ttft_precise),
        "wall_s": round(time.time() - t_start, 1),
    }
    print(json.dumps(stats), file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "ttft_p50_speedup_vs_round_robin",
                "value": round(speedup, 3),
                "unit": "x",
                # BASELINE.json target: >=2x TTFT speedup vs round-robin.
                "vs_baseline": round(speedup / 2.0, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

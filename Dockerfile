# Container image for the KV-cache-manager scoring service.
#
# Parity target: /root/reference/Dockerfile (Go builder + UBI runtime with
# libtokenizers/libzmq baked in; entrypoint = the online scoring service).
# This build: Python runtime + the two native components compiled in-image
# (hash core, kv_connectors transfer engine); entrypoint = the HTTP scoring
# service (api/http_service.py), which wires the indexer read path, the ZMQ
# KVEvents plane and /metrics.

FROM python:3.12-slim AS builder

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make libzmq3-dev && rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY pyproject.toml ./
COPY llm_d_kv_cache_manager_tpu ./llm_d_kv_cache_manager_tpu
COPY native ./native
COPY kv_connectors ./kv_connectors
COPY services ./services

RUN pip install --no-cache-dir \
        msgpack xxhash pyzmq tokenizers prometheus-client aiohttp \
        "transformers>=4.40" grpcio protobuf gunicorn uvloop \
    && cd native && python setup.py build_ext \
    && cd ../kv_connectors/cpp && make

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        libzmq5 && rm -rf /var/lib/apt/lists/* \
    && useradd --uid 10001 --create-home kvtpu

COPY --from=builder /usr/local/lib/python3.12/site-packages /usr/local/lib/python3.12/site-packages
COPY --from=builder /src/llm_d_kv_cache_manager_tpu /app/llm_d_kv_cache_manager_tpu
COPY --from=builder /src/kv_connectors/cpp/libkvtransfer.so /app/kv_connectors/cpp/libkvtransfer.so
COPY --from=builder /src/services /app/services

WORKDIR /app
USER 10001

# Env contract (see api/http_service.py): ZMQ_ENDPOINT, ZMQ_TOPIC,
# POOL_CONCURRENCY, PYTHONHASHSEED, BLOCK_SIZE, BLOCK_HASH_ALGO, HTTP_PORT,
# HF_TOKEN,
# LOCAL_TOKENIZER_DIR, ENABLE_HF_TOKENIZER, ENABLE_METRICS.
EXPOSE 8080 5557
ENTRYPOINT ["python", "-m", "llm_d_kv_cache_manager_tpu.api.http_service"]

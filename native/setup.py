"""Build the native hash core: python native/setup.py build_ext (or `make native`).

Installs _kvtpu_native.so into the llm_d_kv_cache_manager_tpu package dir,
where kvcache/kvblock/hashing.py picks it up (pure-Python fallback otherwise).
"""

import os
import shutil
import sys
from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext

HERE = os.path.dirname(os.path.abspath(__file__))
PKG_DIR = os.path.join(HERE, "..", "llm_d_kv_cache_manager_tpu")


class BuildInPackage(build_ext):
    def run(self):
        super().run()
        for output in self.get_outputs():
            target = os.path.join(PKG_DIR, os.path.basename(output))
            shutil.copy2(output, target)
            print(f"installed {target}")


setup(
    name="kvtpu-native",
    version="0.1.0",
    ext_modules=[
        Extension(
            "_kvtpu_native",
            sources=[os.path.join(HERE, "fnvcbor.c")],
            include_dirs=[HERE],
            depends=[os.path.join(HERE, "kvhash.h")],
            extra_compile_args=["-O3"],
        ),
        Extension(
            "_kvtpu_kvscore",
            sources=[os.path.join(HERE, "kvscore.c")],
            include_dirs=[HERE],
            depends=[os.path.join(HERE, "kvhash.h")],
            extra_compile_args=["-O3"],
        ),
    ],
    cmdclass={"build_ext": BuildInPackage},
    script_args=sys.argv[1:] or ["build_ext"],
)

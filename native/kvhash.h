/* Shared chained-CBOR+FNV-64a hashing helpers for the native modules.
 *
 * Extracted from fnvcbor.c so the scoring/index arena (kvscore.c) can derive
 * request keys with the exact same byte layout and folding as the hash core.
 * Everything here is static inline: each including translation unit gets its
 * own copy, no cross-.so symbol coupling.
 *
 * The canonical form hashed per block is the CBOR array
 *   [parent_u64, [token_u32...], extra|null]
 * folded with FNV-64a from the standard offset basis — bit-identical to the
 * pure-Python implementation in kvcache/kvblock/hashing.py (the test oracle).
 */

#ifndef KVTPU_KVHASH_H
#define KVTPU_KVHASH_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stddef.h>

#define FNV64_OFFSET 0xcbf29ce484222325ULL
#define FNV64_PRIME 0x100000001b3ULL

static inline uint64_t kv_fnv1a64(const uint8_t *data, size_t n, uint64_t h) {
    for (size_t i = 0; i < n; i++) {
        h ^= (uint64_t)data[i];
        h *= FNV64_PRIME;
    }
    return h;
}

/* Shortest-form CBOR head (RFC 8949 canonical). Returns bytes written. */
static inline size_t kv_cbor_head(uint8_t *out, uint8_t major, uint64_t value) {
    uint8_t mt = (uint8_t)(major << 5);
    if (value < 24) {
        out[0] = mt | (uint8_t)value;
        return 1;
    } else if (value <= 0xff) {
        out[0] = mt | 24;
        out[1] = (uint8_t)value;
        return 2;
    } else if (value <= 0xffff) {
        out[0] = mt | 25;
        out[1] = (uint8_t)(value >> 8);
        out[2] = (uint8_t)value;
        return 3;
    } else if (value <= 0xffffffffULL) {
        out[0] = mt | 26;
        out[1] = (uint8_t)(value >> 24);
        out[2] = (uint8_t)(value >> 16);
        out[3] = (uint8_t)(value >> 8);
        out[4] = (uint8_t)value;
        return 5;
    }
    out[0] = mt | 27;
    for (int i = 0; i < 8; i++) out[1 + i] = (uint8_t)(value >> (56 - 8 * i));
    return 9;
}

/* One chain link over a pre-converted block: FNV-64a of the canonical CBOR
 * [parent, [tokens...], extra|null]. `buf` must hold the worst case:
 * 20 + 9*n_toks + 9*(n_extra+1) bytes. */
static inline uint64_t kv_hash_block(uint8_t *buf, uint64_t parent,
                                     const uint64_t *toks, Py_ssize_t n_toks,
                                     const uint64_t *extra, Py_ssize_t n_extra) {
    size_t pos = 0;
    buf[pos++] = 0x83; /* array(3) */
    pos += kv_cbor_head(buf + pos, 0, parent);
    pos += kv_cbor_head(buf + pos, 4, (uint64_t)n_toks);
    for (Py_ssize_t i = 0; i < n_toks; i++)
        pos += kv_cbor_head(buf + pos, 0, toks[i]);
    if (extra == NULL) {
        buf[pos++] = 0xf6; /* null */
    } else {
        pos += kv_cbor_head(buf + pos, 4, (uint64_t)n_extra);
        for (Py_ssize_t i = 0; i < n_extra; i++)
            pos += kv_cbor_head(buf + pos, 0, extra[i]);
    }
    return kv_fnv1a64(buf, pos, FNV64_OFFSET);
}

/* Token -> uint64, accepting anything with __index__ (plain ints, numpy and
 * jax integer scalars) so callers never pay a Python-side [int(t) ...] copy.
 * Returns -1 with an exception set on failure. */
static inline int kv_as_u64(PyObject *o, uint64_t *out) {
    unsigned long long v = PyLong_AsUnsignedLongLong(o);
    if (v == (unsigned long long)-1 && PyErr_Occurred()) {
        if (!PyErr_ExceptionMatches(PyExc_TypeError)) return -1;
        PyErr_Clear();
        PyObject *ix = PyNumber_Index(o);
        if (!ix) return -1;
        v = PyLong_AsUnsignedLongLong(ix);
        Py_DECREF(ix);
        if (v == (unsigned long long)-1 && PyErr_Occurred()) return -1;
    }
    *out = v;
    return 0;
}

/* Convert a Python sequence of token-likes into a fresh uint64_t array.
 * On success *out_n holds the element count; caller PyMem_Free()s. */
static inline uint64_t *kv_tokens_to_array(PyObject *tokens_obj,
                                           Py_ssize_t *out_n) {
    PyObject *seq = PySequence_Fast(tokens_obj, "tokens must be a sequence");
    if (!seq) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    uint64_t *arr = (uint64_t *)PyMem_Malloc(n ? n * sizeof(uint64_t) : 1);
    if (!arr) {
        Py_DECREF(seq);
        PyErr_NoMemory();
        return NULL;
    }
    PyObject **items = PySequence_Fast_ITEMS(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (kv_as_u64(items[i], &arr[i]) < 0) {
            PyMem_Free(arr);
            Py_DECREF(seq);
            return NULL;
        }
    }
    Py_DECREF(seq);
    *out_n = n;
    return arr;
}

/* Optional extra-key tuple (e.g. [lora_id]): NULL-able uint64 array. */
static inline int kv_extra_to_array(PyObject *extra_obj, uint64_t **out,
                                    Py_ssize_t *out_n) {
    if (extra_obj == NULL || extra_obj == Py_None) {
        *out = NULL;
        *out_n = 0;
        return 0;
    }
    *out = kv_tokens_to_array(extra_obj, out_n);
    return *out ? 0 : -1;
}

#endif /* KVTPU_KVHASH_H */

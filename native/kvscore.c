/* kvscore.c — native GIL-free index arena + fused batch scorer.
 *
 * Second native module beside fnvcbor.c: a C arena holding the sharded
 * index's published read view (per-key pod-entry slots keyed by
 * (model_id, chunk_hash)), with the whole router read path — lookup +
 * longest-prefix score + per-pod scalar adjustments (fleet-health
 * demotion, anti-entropy accuracy factors, routing load demotion) —
 * fused into ONE GIL-released crossing (`score_batch`), and event
 * digestion (`apply_batch`) applying decoded BlockStored/BlockRemoved
 * batches against the same arena while readers stay lock-free.
 *
 * Concurrency design (mirrors sharded.py's GIL-atomic published-view
 * trick, in C):
 *
 * - One writer mutex serializes all mutation (add/evict/remove/apply).
 *   Writers NEVER touch the Python C-API while holding it, and release
 *   the GIL before taking it, so a digest thread can apply events while
 *   router threads score.
 * - Readers never lock. Each key node carries a seqlock (Boehm pattern:
 *   odd version = write in progress); a reader copies the entry slots,
 *   then revalidates the version. Structural changes (node unlink /
 *   free / reuse) bump a global epoch BEFORE the structure changes, so
 *   a chain walk that ends in a miss is only trusted if the epoch is
 *   unchanged across the walk. Torn reads retry a bounded number of
 *   times, then fall back to taking the writer mutex (counted in
 *   stats() as `locked_lookups`).
 * - Nodes live in type-stable slabs that are never freed while the
 *   arena lives: a stale reader can always dereference a node pointer;
 *   the seqlock + epoch protocol rejects whatever it reads there.
 *
 * The Python-facing surface speaks ONLY integer ids: the wrapper
 * (kvcache/kvblock/native_index.py) interns pod/tier/model strings to
 * small ints and owns every string comparison (pod_matches, filters),
 * pushing them down as bitmaps and factor tables. Entry slots pack
 * (pod_id << 16) | tier_id into one atomic uint64 (0 = empty slot);
 * slot order is the per-key LRU's oldest-first published order,
 * exactly what `LRUCache.keys()` yields in the Python backends.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stdatomic.h>
#include <stdint.h>
#include <string.h>

#include "kvhash.h"

#define KVS_SLAB_NODES 1024
#define KVS_MAX_WALK 65536      /* chain-walk bound before declaring torn */
#define KVS_FIND_RETRIES 64     /* lock-free retries before mutex fallback */

/* ---------------------------------------------------------------------- */
/* Node types                                                             */
/* ---------------------------------------------------------------------- */

/* Request-key node: seqlock-protected so readers can snapshot the entry
 * slots without the writer mutex. `entries[i]` packs (pod_id<<16)|tier_id,
 * 0 = empty; live slots are entries[0..n_entries) in oldest-first order. */
typedef struct KeyNode {
    _Atomic uint64_t version;        /* seqlock; odd = write in progress */
    _Atomic uint64_t hash;
    _Atomic uint32_t model_id;
    _Atomic uint32_t n_entries;
    _Atomic(struct KeyNode *) next;  /* bucket chain (readers walk this) */
    /* Writer-only fields (mutex-protected): */
    struct KeyNode *free_next;
    struct KeyNode *lru_prev, *lru_next; /* recency list, head = oldest */
    size_t bucket;
    _Atomic uint64_t entries[];      /* cap slots */
} KeyNode;

/* Engine-key → request-key mapping. Only ever touched under the writer
 * mutex (even "reads" move recency, mirroring LRUCache.get), so no
 * atomics needed. */
typedef struct EngNode {
    uint64_t hash;
    uint32_t model_id;
    uint32_t req_model;
    uint64_t req_hash;
    struct EngNode *next;            /* bucket chain */
    struct EngNode *free_next;
    struct EngNode *lru_prev, *lru_next;
    size_t bucket;
} EngNode;

typedef struct {
    PyObject_HEAD
    pthread_mutex_t mu;

    uint32_t cap;                    /* pods_per_key: entry slots per node */
    Py_ssize_t max_keys;             /* capacity of key map AND engine map */
    size_t key_stride;               /* slab stride for KeyNode + slots */

    /* Request-key map */
    size_t n_buckets, mask;
    _Atomic(KeyNode *) *buckets;
    KeyNode *key_lru_head, *key_lru_tail;
    Py_ssize_t n_keys;
    KeyNode *key_free;

    /* Engine map */
    EngNode **e_buckets;             /* same n_buckets/mask */
    EngNode *eng_lru_head, *eng_lru_tail;
    Py_ssize_t n_eng;
    EngNode *eng_free;

    /* Type-stable slabs (never freed while the arena lives) */
    void **slabs;
    size_t n_slabs, slabs_cap;
    size_t bytes_allocated;

    _Atomic uint64_t epoch;          /* bumped BEFORE structural changes */
    uint64_t locked_lookups;         /* bounded-retry mutex fallbacks */
    uint64_t total_adds;             /* entry-slot insertions */
    uint64_t total_evictions;        /* capacity evictions of key nodes */
    uint64_t blocks_applied;         /* apply_batch blocks processed */
} ArenaObject;

/* ---------------------------------------------------------------------- */
/* Allocation                                                             */
/* ---------------------------------------------------------------------- */

static void *arena_slab(ArenaObject *a, size_t sz) {
    if (a->n_slabs == a->slabs_cap) {
        size_t ncap = a->slabs_cap ? a->slabs_cap * 2 : 16;
        void **ns = (void **)realloc(a->slabs, ncap * sizeof(void *));
        if (!ns) return NULL;
        a->slabs = ns;
        a->slabs_cap = ncap;
    }
    void *p = calloc(1, sz);
    if (!p) return NULL;
    a->slabs[a->n_slabs++] = p;
    a->bytes_allocated += sz;
    return p;
}

/* Writer mutex held. */
static KeyNode *key_node_alloc(ArenaObject *a) {
    if (a->key_free) {
        KeyNode *n = a->key_free;
        a->key_free = n->free_next;
        return n;
    }
    char *slab = (char *)arena_slab(a, KVS_SLAB_NODES * a->key_stride);
    if (!slab) return NULL;
    for (size_t i = 1; i < KVS_SLAB_NODES; i++) {
        KeyNode *n = (KeyNode *)(slab + i * a->key_stride);
        n->free_next = a->key_free;
        a->key_free = n;
    }
    return (KeyNode *)slab;
}

static EngNode *eng_node_alloc(ArenaObject *a) {
    if (a->eng_free) {
        EngNode *n = a->eng_free;
        a->eng_free = n->free_next;
        return n;
    }
    EngNode *slab = (EngNode *)arena_slab(a, KVS_SLAB_NODES * sizeof(EngNode));
    if (!slab) return NULL;
    for (size_t i = 1; i < KVS_SLAB_NODES; i++) {
        slab[i].free_next = a->eng_free;
        a->eng_free = &slab[i];
    }
    return &slab[0];
}

/* ---------------------------------------------------------------------- */
/* Hashing / buckets                                                      */
/* ---------------------------------------------------------------------- */

static inline size_t bucket_of(const ArenaObject *a, uint32_t model,
                               uint64_t hash) {
    uint64_t x = hash ^ ((uint64_t)model * 0x9E3779B97F4A7C15ULL);
    x ^= x >> 33;
    x *= 0xFF51AFD7ED558CCDULL;
    x ^= x >> 33;
    return (size_t)(x & a->mask);
}

/* ---------------------------------------------------------------------- */
/* Seqlock (Boehm pattern)                                                */
/* ---------------------------------------------------------------------- */

static inline void node_write_begin(KeyNode *n) {
    uint64_t v = atomic_load_explicit(&n->version, memory_order_relaxed);
    atomic_store_explicit(&n->version, v + 1, memory_order_relaxed);
    atomic_thread_fence(memory_order_release);
}

static inline void node_write_end(KeyNode *n) {
    uint64_t v = atomic_load_explicit(&n->version, memory_order_relaxed);
    atomic_store_explicit(&n->version, v + 1, memory_order_release);
}

/* Snapshot a node's identity + entry slots. Returns:
 *   >= 0  consistent snapshot, hash/model matched; value = entry count
 *   -1    torn read (writer active / version moved): retry the walk
 *   -2    consistent snapshot but identity mismatch: not our key, walk on
 */
static inline int node_read(const KeyNode *n, uint32_t want_model,
                            uint64_t want_hash, uint64_t *out, uint32_t cap) {
    uint64_t v1 = atomic_load_explicit(&n->version, memory_order_acquire);
    if (v1 & 1) return -1;
    uint64_t h = atomic_load_explicit(&n->hash, memory_order_relaxed);
    uint32_t m = atomic_load_explicit(&n->model_id, memory_order_relaxed);
    uint32_t ne = atomic_load_explicit(&n->n_entries, memory_order_relaxed);
    if (ne > cap) ne = cap;
    for (uint32_t i = 0; i < ne; i++)
        out[i] = atomic_load_explicit(&n->entries[i], memory_order_relaxed);
    atomic_thread_fence(memory_order_acquire);
    uint64_t v2 = atomic_load_explicit(&n->version, memory_order_relaxed);
    if (v1 != v2) return -1;
    if (h != want_hash || m != want_model) return -2;
    return (int)ne;
}

/* Lock-free point lookup. Returns:
 *   1  hit: entries copied into out[], *n_out set
 *   0  definite miss (epoch stable across the walk)
 *  -1  unstable (torn node / epoch moved / walk bound hit): caller retries
 */
static int arena_find_lockfree(ArenaObject *a, uint32_t model, uint64_t hash,
                               uint64_t *out, int *n_out) {
    uint64_t e1 = atomic_load_explicit(&a->epoch, memory_order_acquire);
    KeyNode *n = atomic_load_explicit(&a->buckets[bucket_of(a, model, hash)],
                                      memory_order_acquire);
    int steps = 0;
    while (n) {
        if (++steps > KVS_MAX_WALK) return -1;
        int r = node_read(n, model, hash, out, a->cap);
        if (r >= 0) {
            *n_out = r;
            return 1;
        }
        if (r == -1) return -1;
        n = atomic_load_explicit(&n->next, memory_order_acquire);
    }
    atomic_thread_fence(memory_order_acquire);
    if (atomic_load_explicit(&a->epoch, memory_order_relaxed) != e1) return -1;
    *n_out = 0;
    return 0;
}

/* Writer-side (mutex held) exact find; no seqlock dance needed. */
static KeyNode *key_find_locked(ArenaObject *a, uint32_t model, uint64_t hash) {
    KeyNode *n = atomic_load_explicit(&a->buckets[bucket_of(a, model, hash)],
                                      memory_order_relaxed);
    while (n) {
        if (atomic_load_explicit(&n->hash, memory_order_relaxed) == hash &&
            atomic_load_explicit(&n->model_id, memory_order_relaxed) == model)
            return n;
        n = atomic_load_explicit(&n->next, memory_order_relaxed);
    }
    return NULL;
}

/* Point lookup with bounded lock-free retries, then mutex fallback.
 * Call WITHOUT the mutex held (and, on hot paths, without the GIL). */
static int arena_find(ArenaObject *a, uint32_t model, uint64_t hash,
                      uint64_t *out) {
    int n_out = 0;
    for (int attempt = 0; attempt < KVS_FIND_RETRIES; attempt++) {
        int r = arena_find_lockfree(a, model, hash, out, &n_out);
        if (r == 1) return n_out;
        if (r == 0) return 0;
    }
    pthread_mutex_lock(&a->mu);
    a->locked_lookups++;
    KeyNode *n = key_find_locked(a, model, hash);
    n_out = 0;
    if (n) {
        uint32_t ne = atomic_load_explicit(&n->n_entries, memory_order_relaxed);
        if (ne > a->cap) ne = a->cap;
        for (uint32_t i = 0; i < ne; i++)
            out[i] = atomic_load_explicit(&n->entries[i], memory_order_relaxed);
        n_out = (int)ne;
    }
    pthread_mutex_unlock(&a->mu);
    return n_out;
}

/* ---------------------------------------------------------------------- */
/* Writer primitives (mutex held throughout; no Python API)               */
/* ---------------------------------------------------------------------- */

static void key_lru_unlink(ArenaObject *a, KeyNode *n) {
    if (n->lru_prev) n->lru_prev->lru_next = n->lru_next;
    else a->key_lru_head = n->lru_next;
    if (n->lru_next) n->lru_next->lru_prev = n->lru_prev;
    else a->key_lru_tail = n->lru_prev;
    n->lru_prev = n->lru_next = NULL;
}

static void key_lru_push_tail(ArenaObject *a, KeyNode *n) {
    n->lru_prev = a->key_lru_tail;
    n->lru_next = NULL;
    if (a->key_lru_tail) a->key_lru_tail->lru_next = n;
    else a->key_lru_head = n;
    a->key_lru_tail = n;
}

static void key_lru_touch(ArenaObject *a, KeyNode *n) {
    if (a->key_lru_tail == n) return;
    key_lru_unlink(a, n);
    key_lru_push_tail(a, n);
}

/* Unlink a key node from its bucket chain + LRU and put it on the free
 * list, wiped so stale readers see a non-matching identity. The epoch is
 * bumped BEFORE any structural store so a concurrent lock-free miss that
 * raced this unlink gets invalidated and retried. */
static void key_node_remove(ArenaObject *a, KeyNode *victim) {
    atomic_fetch_add_explicit(&a->epoch, 1, memory_order_seq_cst);
    /* Unlink from bucket chain (release stores: readers chase `next`). */
    _Atomic(KeyNode *) *slot = &a->buckets[victim->bucket];
    KeyNode *cur = atomic_load_explicit(slot, memory_order_relaxed);
    if (cur == victim) {
        atomic_store_explicit(
            slot, atomic_load_explicit(&victim->next, memory_order_relaxed),
            memory_order_release);
    } else {
        while (cur) {
            KeyNode *nxt = atomic_load_explicit(&cur->next,
                                                memory_order_relaxed);
            if (nxt == victim) {
                atomic_store_explicit(
                    &cur->next,
                    atomic_load_explicit(&victim->next, memory_order_relaxed),
                    memory_order_release);
                break;
            }
            cur = nxt;
        }
    }
    key_lru_unlink(a, victim);
    /* Wipe identity under the seqlock so a reader mid-snapshot rejects. */
    node_write_begin(victim);
    atomic_store_explicit(&victim->hash, 0, memory_order_relaxed);
    atomic_store_explicit(&victim->model_id, 0, memory_order_relaxed);
    atomic_store_explicit(&victim->n_entries, 0, memory_order_relaxed);
    node_write_end(victim);
    atomic_store_explicit(&victim->next, NULL, memory_order_relaxed);
    victim->free_next = a->key_free;
    a->key_free = victim;
    a->n_keys--;
}

/* Find-or-create + recency touch (mirrors LRUCache.add for the key map:
 * present -> move to end; absent -> append, evicting the oldest at
 * capacity — capacity eviction does NOT sweep the engine map, exactly
 * like the Python backends). Returns NULL only on allocation failure. */
static KeyNode *key_get_or_create(ArenaObject *a, uint32_t model,
                                  uint64_t hash, int *created) {
    KeyNode *n = key_find_locked(a, model, hash);
    if (n) {
        key_lru_touch(a, n);
        if (created) *created = 0;
        return n;
    }
    if (a->n_keys >= a->max_keys && a->key_lru_head) {
        key_node_remove(a, a->key_lru_head);
        a->total_evictions++;
    }
    n = key_node_alloc(a);
    if (!n) return NULL;
    /* Reuse of a node a stale reader may still point at: bump the epoch
     * BEFORE re-initializing so any walk through the old linkage retries. */
    atomic_fetch_add_explicit(&a->epoch, 1, memory_order_seq_cst);
    node_write_begin(n);
    atomic_store_explicit(&n->hash, hash, memory_order_relaxed);
    atomic_store_explicit(&n->model_id, model, memory_order_relaxed);
    atomic_store_explicit(&n->n_entries, 0, memory_order_relaxed);
    for (uint32_t i = 0; i < a->cap; i++)
        atomic_store_explicit(&n->entries[i], 0, memory_order_relaxed);
    node_write_end(n);
    size_t b = bucket_of(a, model, hash);
    n->bucket = b;
    atomic_store_explicit(
        &n->next, atomic_load_explicit(&a->buckets[b], memory_order_relaxed),
        memory_order_relaxed);
    atomic_store_explicit(&a->buckets[b], n, memory_order_release);
    key_lru_push_tail(a, n);
    a->n_keys++;
    if (created) *created = 1;
    return n;
}

/* Per-key entry-slot add with LRUCache.add semantics over the packed
 * slots: present -> move to the end (shift the tail down); absent ->
 * append, dropping slot 0 (the oldest) at capacity. One seqlock write
 * section per call. */
static void node_entry_add(ArenaObject *a, KeyNode *n, uint64_t packed) {
    uint32_t ne = atomic_load_explicit(&n->n_entries, memory_order_relaxed);
    uint32_t i;
    for (i = 0; i < ne; i++) {
        if (atomic_load_explicit(&n->entries[i], memory_order_relaxed) ==
            packed)
            break;
    }
    node_write_begin(n);
    if (i < ne) {
        /* Move to end: shift everything after i down one slot. */
        for (uint32_t j = i; j + 1 < ne; j++)
            atomic_store_explicit(
                &n->entries[j],
                atomic_load_explicit(&n->entries[j + 1], memory_order_relaxed),
                memory_order_relaxed);
        atomic_store_explicit(&n->entries[ne - 1], packed,
                              memory_order_relaxed);
    } else if (ne < a->cap) {
        atomic_store_explicit(&n->entries[ne], packed, memory_order_relaxed);
        atomic_store_explicit(&n->n_entries, ne + 1, memory_order_relaxed);
    } else {
        /* At capacity: drop the oldest (slot 0), append at the end. */
        for (uint32_t j = 0; j + 1 < ne; j++)
            atomic_store_explicit(
                &n->entries[j],
                atomic_load_explicit(&n->entries[j + 1], memory_order_relaxed),
                memory_order_relaxed);
        atomic_store_explicit(&n->entries[ne - 1], packed,
                              memory_order_relaxed);
    }
    node_write_end(n);
    a->total_adds++;
}

/* Remove one exact packed entry. Returns 1 if removed. */
static int node_entry_remove(ArenaObject *a, KeyNode *n, uint64_t packed) {
    uint32_t ne = atomic_load_explicit(&n->n_entries, memory_order_relaxed);
    for (uint32_t i = 0; i < ne; i++) {
        if (atomic_load_explicit(&n->entries[i], memory_order_relaxed) !=
            packed)
            continue;
        node_write_begin(n);
        for (uint32_t j = i; j + 1 < ne; j++)
            atomic_store_explicit(
                &n->entries[j],
                atomic_load_explicit(&n->entries[j + 1], memory_order_relaxed),
                memory_order_relaxed);
        atomic_store_explicit(&n->entries[ne - 1], 0, memory_order_relaxed);
        atomic_store_explicit(&n->n_entries, ne - 1, memory_order_relaxed);
        node_write_end(n);
        return 1;
    }
    return 0;
}

/* -- engine map (writer mutex held; plain memory) ----------------------- */

static EngNode *eng_find(ArenaObject *a, uint32_t model, uint64_t hash) {
    EngNode *n = a->e_buckets[bucket_of(a, model, hash)];
    while (n) {
        if (n->hash == hash && n->model_id == model) return n;
        n = n->next;
    }
    return NULL;
}

static void eng_lru_unlink(ArenaObject *a, EngNode *n) {
    if (n->lru_prev) n->lru_prev->lru_next = n->lru_next;
    else a->eng_lru_head = n->lru_next;
    if (n->lru_next) n->lru_next->lru_prev = n->lru_prev;
    else a->eng_lru_tail = n->lru_prev;
    n->lru_prev = n->lru_next = NULL;
}

static void eng_lru_push_tail(ArenaObject *a, EngNode *n) {
    n->lru_prev = a->eng_lru_tail;
    n->lru_next = NULL;
    if (a->eng_lru_tail) a->eng_lru_tail->lru_next = n;
    else a->eng_lru_head = n;
    a->eng_lru_tail = n;
}

static void eng_remove(ArenaObject *a, EngNode *victim) {
    EngNode **slot = &a->e_buckets[victim->bucket];
    while (*slot && *slot != victim) slot = &(*slot)->next;
    if (*slot) *slot = victim->next;
    eng_lru_unlink(a, victim);
    victim->next = NULL;
    victim->free_next = a->eng_free;
    a->eng_free = victim;
    a->n_eng--;
}

/* LRUCache.add semantics: present -> touch + replace value; absent ->
 * append, evicting the oldest mapping at capacity. Returns 0 on alloc
 * failure (mapping silently dropped — matches a full LRU more than an
 * error, and the Python fallback path still exists). */
static int eng_add(ArenaObject *a, uint32_t model, uint64_t hash,
                   uint32_t req_model, uint64_t req_hash) {
    EngNode *n = eng_find(a, model, hash);
    if (n) {
        n->req_model = req_model;
        n->req_hash = req_hash;
        eng_lru_unlink(a, n);
        eng_lru_push_tail(a, n);
        return 1;
    }
    if (a->n_eng >= a->max_keys && a->eng_lru_head)
        eng_remove(a, a->eng_lru_head);
    n = eng_node_alloc(a);
    if (!n) return 0;
    n->hash = hash;
    n->model_id = model;
    n->req_model = req_model;
    n->req_hash = req_hash;
    size_t b = bucket_of(a, model, hash);
    n->bucket = b;
    n->next = a->e_buckets[b];
    a->e_buckets[b] = n;
    eng_lru_push_tail(a, n);
    a->n_eng++;
    return 1;
}

/* LRUCache.get semantics: hit touches recency. */
static EngNode *eng_get(ArenaObject *a, uint32_t model, uint64_t hash) {
    EngNode *n = eng_find(a, model, hash);
    if (n) {
        eng_lru_unlink(a, n);
        eng_lru_push_tail(a, n);
    }
    return n;
}

/* ---------------------------------------------------------------------- */
/* Argument conversion helpers (GIL held)                                 */
/* ---------------------------------------------------------------------- */

/* Sequence of (model_id, hash) pairs -> parallel C arrays. */
static int parse_pairs(PyObject *obj, uint32_t **models, uint64_t **hashes,
                       Py_ssize_t *n) {
    PyObject *seq = PySequence_Fast(obj, "expected a sequence of key pairs");
    if (!seq) return -1;
    Py_ssize_t len = PySequence_Fast_GET_SIZE(seq);
    uint32_t *ms = (uint32_t *)PyMem_Malloc(len ? len * sizeof(uint32_t) : 1);
    uint64_t *hs = (uint64_t *)PyMem_Malloc(len ? len * sizeof(uint64_t) : 1);
    if (!ms || !hs) {
        PyMem_Free(ms);
        PyMem_Free(hs);
        Py_DECREF(seq);
        PyErr_NoMemory();
        return -1;
    }
    PyObject **items = PySequence_Fast_ITEMS(seq);
    for (Py_ssize_t i = 0; i < len; i++) {
        PyObject *pair = items[i];
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            PyErr_SetString(PyExc_TypeError,
                            "key pair must be a (model_id, hash) tuple");
            goto fail;
        }
        uint64_t m, h;
        if (kv_as_u64(PyTuple_GET_ITEM(pair, 0), &m) < 0) goto fail;
        if (kv_as_u64(PyTuple_GET_ITEM(pair, 1), &h) < 0) goto fail;
        ms[i] = (uint32_t)m;
        hs[i] = h;
    }
    Py_DECREF(seq);
    *models = ms;
    *hashes = hs;
    *n = len;
    return 0;
fail:
    PyMem_Free(ms);
    PyMem_Free(hs);
    Py_DECREF(seq);
    return -1;
}

/* Sequence of packed entry ints -> uint64 array. */
static int parse_packed(PyObject *obj, uint64_t **out, Py_ssize_t *n) {
    return (*out = kv_tokens_to_array(obj, n)) ? 0 : -1;
}

/* Optional bytes-like bitmap: borrowed pointer + length (no copy; caller
 * must keep `obj` alive across use). Py_None -> NULL. */
static int parse_bitmap(PyObject *obj, const uint8_t **buf, Py_ssize_t *len) {
    if (obj == NULL || obj == Py_None) {
        *buf = NULL;
        *len = 0;
        return 0;
    }
    char *b;
    Py_ssize_t l;
    if (PyBytes_AsStringAndSize(obj, &b, &l) < 0) return -1;
    *buf = (const uint8_t *)b;
    *len = l;
    return 0;
}

static inline int bitmap_test(const uint8_t *buf, Py_ssize_t len, uint32_t id) {
    Py_ssize_t byte = (Py_ssize_t)(id >> 3);
    if (byte >= len) return 0;
    return (buf[byte] >> (id & 7)) & 1;
}

/* Optional sequence of doubles -> malloc'd array (Py_None -> NULL). */
static int parse_f64_table(PyObject *obj, double **out, Py_ssize_t *n) {
    if (obj == NULL || obj == Py_None) {
        *out = NULL;
        *n = 0;
        return 0;
    }
    PyObject *seq = PySequence_Fast(obj, "expected a float sequence");
    if (!seq) return -1;
    Py_ssize_t len = PySequence_Fast_GET_SIZE(seq);
    double *arr = (double *)PyMem_Malloc(len ? len * sizeof(double) : 1);
    if (!arr) {
        Py_DECREF(seq);
        PyErr_NoMemory();
        return -1;
    }
    PyObject **items = PySequence_Fast_ITEMS(seq);
    for (Py_ssize_t i = 0; i < len; i++) {
        arr[i] = PyFloat_AsDouble(items[i]);
        if (arr[i] == -1.0 && PyErr_Occurred()) {
            PyMem_Free(arr);
            Py_DECREF(seq);
            return -1;
        }
    }
    Py_DECREF(seq);
    *out = arr;
    *n = len;
    return 0;
}

/* Optional sequence of small ints -> malloc'd uint32 array. */
static int parse_u32_table(PyObject *obj, uint32_t **out, Py_ssize_t *n) {
    if (obj == NULL || obj == Py_None) {
        *out = NULL;
        *n = 0;
        return 0;
    }
    uint64_t *wide;
    Py_ssize_t len;
    wide = kv_tokens_to_array(obj, &len);
    if (!wide) return -1;
    uint32_t *arr = (uint32_t *)PyMem_Malloc(len ? len * sizeof(uint32_t) : 1);
    if (!arr) {
        PyMem_Free(wide);
        PyErr_NoMemory();
        return -1;
    }
    for (Py_ssize_t i = 0; i < len; i++) arr[i] = (uint32_t)wide[i];
    PyMem_Free(wide);
    *out = arr;
    *n = len;
    return 0;
}

/* ---------------------------------------------------------------------- */
/* Arena object protocol                                                  */
/* ---------------------------------------------------------------------- */

static PyObject *Arena_new(PyTypeObject *type, PyObject *args,
                           PyObject *kwds) {
    static char *kwlist[] = {"max_keys", "pods_per_key", NULL};
    Py_ssize_t max_keys = 0, cap = 0;
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "nn", kwlist, &max_keys,
                                     &cap))
        return NULL;
    if (max_keys <= 0) {
        PyErr_SetString(PyExc_ValueError, "index size must be positive");
        return NULL;
    }
    if (cap <= 0 || cap > 0xFFFF) {
        PyErr_SetString(PyExc_ValueError,
                        "pods_per_key must be in [1, 65535]");
        return NULL;
    }
    ArenaObject *self = (ArenaObject *)type->tp_alloc(type, 0);
    if (!self) return NULL;
    pthread_mutex_init(&self->mu, NULL);
    self->cap = (uint32_t)cap;
    self->max_keys = max_keys;
    size_t stride = sizeof(KeyNode) + (size_t)cap * sizeof(uint64_t);
    self->key_stride = (stride + 63) & ~(size_t)63;

    size_t nb = 1024;
    while (nb < (size_t)max_keys * 2 && nb < (1u << 21)) nb <<= 1;
    self->n_buckets = nb;
    self->mask = nb - 1;
    self->buckets = (_Atomic(KeyNode *) *)calloc(nb, sizeof(KeyNode *));
    self->e_buckets = (EngNode **)calloc(nb, sizeof(EngNode *));
    if (!self->buckets || !self->e_buckets) {
        free(self->buckets);
        free(self->e_buckets);
        self->buckets = NULL;
        self->e_buckets = NULL;
        Py_DECREF(self);
        return PyErr_NoMemory();
    }
    self->bytes_allocated = 2 * nb * sizeof(void *);
    return (PyObject *)self;
}

static void Arena_dealloc(ArenaObject *self) {
    for (size_t i = 0; i < self->n_slabs; i++) free(self->slabs[i]);
    free(self->slabs);
    free(self->buckets);
    free(self->e_buckets);
    pthread_mutex_destroy(&self->mu);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

/* add(engine_pairs, request_pairs, entries) — Index.add semantics with
 * interned ids; raises the exact ValueError messages of the Python
 * backends. */
static PyObject *Arena_add(ArenaObject *self, PyObject *args) {
    PyObject *eng_obj, *req_obj, *ent_obj;
    if (!PyArg_ParseTuple(args, "OOO", &eng_obj, &req_obj, &ent_obj))
        return NULL;
    uint32_t *em = NULL, *rm = NULL;
    uint64_t *eh = NULL, *rh = NULL, *packed = NULL;
    Py_ssize_t ne = 0, nr = 0, np = 0;
    if (parse_pairs(eng_obj, &em, &eh, &ne) < 0) return NULL;
    if (parse_pairs(req_obj, &rm, &rh, &nr) < 0) goto fail;
    if (parse_packed(ent_obj, &packed, &np) < 0) goto fail;
    if (ne == 0 || nr == 0 || np == 0) {
        PyErr_SetString(PyExc_ValueError,
                        "no keys or entries provided for adding to index");
        goto fail;
    }
    if (ne != nr) {
        PyErr_Format(PyExc_ValueError,
                     "engine/request key length mismatch: %zd != %zd", ne, nr);
        goto fail;
    }
    Py_BEGIN_ALLOW_THREADS
    pthread_mutex_lock(&self->mu);
    for (Py_ssize_t i = 0; i < ne; i++)
        eng_add(self, em[i], eh[i], rm[i], rh[i]);
    for (Py_ssize_t i = 0; i < nr; i++) {
        KeyNode *n = key_get_or_create(self, rm[i], rh[i], NULL);
        if (!n) break; /* allocation failure: stop, arena stays coherent */
        for (Py_ssize_t j = 0; j < np; j++)
            node_entry_add(self, n, packed[j]);
    }
    pthread_mutex_unlock(&self->mu);
    Py_END_ALLOW_THREADS
    PyMem_Free(em);
    PyMem_Free(eh);
    PyMem_Free(rm);
    PyMem_Free(rh);
    PyMem_Free(packed);
    Py_RETURN_NONE;
fail:
    PyMem_Free(em);
    PyMem_Free(eh);
    PyMem_Free(rm);
    PyMem_Free(rh);
    PyMem_Free(packed);
    return NULL;
}

/* evict(model_id, hash, entries) -> removed count, or -1 when the engine
 * key is unknown (the Python path logs-and-returns there). */
static PyObject *Arena_evict(ArenaObject *self, PyObject *args) {
    unsigned long model;
    unsigned long long hash;
    PyObject *ent_obj;
    if (!PyArg_ParseTuple(args, "kKO", &model, &hash, &ent_obj)) return NULL;
    uint64_t *packed = NULL;
    Py_ssize_t np = 0;
    if (parse_packed(ent_obj, &packed, &np) < 0) return NULL;
    if (np == 0) {
        PyMem_Free(packed);
        PyErr_SetString(PyExc_ValueError,
                        "no entries provided for eviction from index");
        return NULL;
    }
    long removed = 0;
    Py_BEGIN_ALLOW_THREADS
    pthread_mutex_lock(&self->mu);
    EngNode *e = eng_get(self, (uint32_t)model, hash);
    if (!e) {
        removed = -1;
    } else {
        KeyNode *n = key_find_locked(self, e->req_model, e->req_hash);
        if (!n) {
            eng_remove(self, e);
        } else {
            key_lru_touch(self, n);
            for (Py_ssize_t j = 0; j < np; j++)
                removed += node_entry_remove(self, n, packed[j]);
            if (atomic_load_explicit(&n->n_entries, memory_order_relaxed) ==
                0) {
                key_node_remove(self, n);
                eng_remove(self, e);
            }
        }
    }
    pthread_mutex_unlock(&self->mu);
    Py_END_ALLOW_THREADS
    PyMem_Free(packed);
    return PyLong_FromLong(removed);
}

/* get_request_key(model_id, hash) -> (req_model_id, req_hash) | None.
 * Touches engine-map recency exactly like LRUCache.get. */
static PyObject *Arena_get_request_key(ArenaObject *self, PyObject *args) {
    unsigned long model;
    unsigned long long hash;
    if (!PyArg_ParseTuple(args, "kK", &model, &hash)) return NULL;
    uint32_t rmodel = 0;
    uint64_t rhash = 0;
    int found = 0;
    pthread_mutex_lock(&self->mu);
    EngNode *e = eng_get(self, (uint32_t)model, hash);
    if (e) {
        rmodel = e->req_model;
        rhash = e->req_hash;
        found = 1;
    }
    pthread_mutex_unlock(&self->mu);
    if (!found) Py_RETURN_NONE;
    return Py_BuildValue("(kK)", (unsigned long)rmodel,
                         (unsigned long long)rhash);
}

/* lookup_chain(model_id, hashes) -> [(packed, ...), ...] stopping at the
 * first miss/empty key (the seed's chain-cut semantics). Lock-free. */
static PyObject *Arena_lookup_chain(ArenaObject *self, PyObject *args) {
    unsigned long model;
    PyObject *hashes_obj;
    if (!PyArg_ParseTuple(args, "kO", &model, &hashes_obj)) return NULL;
    uint64_t *hashes = NULL;
    Py_ssize_t n = 0;
    if (parse_packed(hashes_obj, &hashes, &n) < 0) return NULL;
    uint64_t *buf =
        (uint64_t *)PyMem_Malloc(n ? (size_t)n * self->cap * 8 : 1);
    int *counts = (int *)PyMem_Malloc(n ? n * sizeof(int) : 1);
    if (!buf || !counts) {
        PyMem_Free(hashes);
        PyMem_Free(buf);
        PyMem_Free(counts);
        return PyErr_NoMemory();
    }
    Py_ssize_t hit = 0;
    Py_BEGIN_ALLOW_THREADS
    for (; hit < n; hit++) {
        int c = arena_find(self, (uint32_t)model, hashes[hit],
                           buf + (size_t)hit * self->cap);
        if (c <= 0) break;
        counts[hit] = c;
    }
    Py_END_ALLOW_THREADS
    PyObject *out = PyList_New(hit);
    if (out) {
        for (Py_ssize_t i = 0; i < hit; i++) {
            PyObject *tup = PyTuple_New(counts[i]);
            if (!tup) {
                Py_CLEAR(out);
                break;
            }
            for (int j = 0; j < counts[i]; j++) {
                PyObject *v = PyLong_FromUnsignedLongLong(
                    buf[(size_t)i * self->cap + j]);
                if (!v) {
                    Py_DECREF(tup);
                    Py_CLEAR(out);
                    goto done;
                }
                PyTuple_SET_ITEM(tup, j, v);
            }
            PyList_SET_ITEM(out, i, tup);
        }
    }
done:
    PyMem_Free(hashes);
    PyMem_Free(buf);
    PyMem_Free(counts);
    return out;
}

typedef struct {
    uint32_t model;
    uint64_t hash;
} KeyId;

static int keyid_cmp(const void *pa, const void *pb) {
    const KeyId *x = (const KeyId *)pa, *y = (const KeyId *)pb;
    if (x->hash != y->hash) return x->hash < y->hash ? -1 : 1;
    if (x->model != y->model) return x->model < y->model ? -1 : 1;
    return 0;
}

/* remove_matching(pod_bitmap, tier_bitmap|None, request_pairs|None) -> n.
 * Backs remove_pod (pairs=None: every key, no recency touch) and
 * remove_entries (explicit keys, peek semantics). Keys emptied BY THIS
 * CALL get their engine mappings swept — capacity evictions never do. */
static PyObject *Arena_remove_matching(ArenaObject *self, PyObject *args) {
    PyObject *pod_obj, *tier_obj, *pairs_obj;
    if (!PyArg_ParseTuple(args, "OOO", &pod_obj, &tier_obj, &pairs_obj))
        return NULL;
    const uint8_t *pod_bm, *tier_bm;
    Py_ssize_t pod_len, tier_len;
    if (parse_bitmap(pod_obj, &pod_bm, &pod_len) < 0) return NULL;
    if (parse_bitmap(tier_obj, &tier_bm, &tier_len) < 0) return NULL;
    if (pod_bm == NULL) {
        PyErr_SetString(PyExc_TypeError, "pod bitmap must be bytes");
        return NULL;
    }
    uint32_t *pm = NULL;
    uint64_t *ph = NULL;
    Py_ssize_t npairs = -1;
    if (pairs_obj != Py_None &&
        parse_pairs(pairs_obj, &pm, &ph, &npairs) < 0)
        return NULL;

    long removed = 0;
    KeyId *emptied = NULL;
    size_t n_emptied = 0, cap_emptied = 0;
    int oom = 0;

    Py_BEGIN_ALLOW_THREADS
    pthread_mutex_lock(&self->mu);
    Py_ssize_t n_targets =
        npairs >= 0 ? npairs : self->n_keys;
    KeyNode *walk = self->key_lru_head;
    for (Py_ssize_t t = 0; t < n_targets; t++) {
        KeyNode *n;
        if (npairs >= 0) {
            n = key_find_locked(self, pm[t], ph[t]);
            if (!n) continue;
        } else {
            n = walk;
            if (!n) break;
            walk = n->lru_next; /* before any unlink */
        }
        uint32_t ne = atomic_load_explicit(&n->n_entries,
                                           memory_order_relaxed);
        int hit = 0;
        for (uint32_t i = 0; i < ne;) {
            uint64_t packed =
                atomic_load_explicit(&n->entries[i], memory_order_relaxed);
            uint32_t pod = (uint32_t)(packed >> 16);
            uint32_t tier = (uint32_t)(packed & 0xFFFF);
            if (bitmap_test(pod_bm, pod_len, pod) &&
                (tier_bm == NULL || bitmap_test(tier_bm, tier_len, tier))) {
                node_entry_remove(self, n, packed);
                removed++;
                hit = 1;
                ne--;
            } else {
                i++;
            }
        }
        if (hit && ne == 0) {
            if (n_emptied == cap_emptied) {
                size_t ncap = cap_emptied ? cap_emptied * 2 : 64;
                KeyId *ne2 = (KeyId *)realloc(emptied, ncap * sizeof(KeyId));
                if (!ne2) {
                    oom = 1;
                } else {
                    emptied = ne2;
                    cap_emptied = ncap;
                }
            }
            if (!oom) {
                emptied[n_emptied].model =
                    atomic_load_explicit(&n->model_id, memory_order_relaxed);
                emptied[n_emptied].hash =
                    atomic_load_explicit(&n->hash, memory_order_relaxed);
                n_emptied++;
            }
            key_node_remove(self, n);
        }
    }
    if (n_emptied) {
        qsort(emptied, n_emptied, sizeof(KeyId), keyid_cmp);
        EngNode *e = self->eng_lru_head;
        while (e) {
            EngNode *next = e->lru_next;
            KeyId probe = {e->req_model, e->req_hash};
            if (bsearch(&probe, emptied, n_emptied, sizeof(KeyId),
                        keyid_cmp))
                eng_remove(self, e);
            e = next;
        }
    }
    pthread_mutex_unlock(&self->mu);
    Py_END_ALLOW_THREADS
    free(emptied);
    PyMem_Free(pm);
    PyMem_Free(ph);
    if (oom) return PyErr_NoMemory();
    return PyLong_FromLong(removed);
}

/* dump() -> (entry_rows, engine_rows): oldest-first snapshots for
 * export_view / debugging. */
static PyObject *Arena_dump(ArenaObject *self, PyObject *noarg) {
    (void)noarg;
    pthread_mutex_lock(&self->mu);
    PyObject *entries = PyList_New(0);
    PyObject *engines = PyList_New(0);
    if (!entries || !engines) goto fail;
    for (KeyNode *n = self->key_lru_head; n; n = n->lru_next) {
        uint32_t ne = atomic_load_explicit(&n->n_entries,
                                           memory_order_relaxed);
        PyObject *tup = PyTuple_New(ne);
        if (!tup) goto fail;
        for (uint32_t i = 0; i < ne; i++) {
            PyObject *v = PyLong_FromUnsignedLongLong(
                atomic_load_explicit(&n->entries[i], memory_order_relaxed));
            if (!v) {
                Py_DECREF(tup);
                goto fail;
            }
            PyTuple_SET_ITEM(tup, i, v);
        }
        PyObject *row = Py_BuildValue(
            "(kKN)",
            (unsigned long)atomic_load_explicit(&n->model_id,
                                                memory_order_relaxed),
            (unsigned long long)atomic_load_explicit(&n->hash,
                                                     memory_order_relaxed),
            tup);
        if (!row || PyList_Append(entries, row) < 0) {
            Py_XDECREF(row);
            goto fail;
        }
        Py_DECREF(row);
    }
    for (EngNode *e = self->eng_lru_head; e; e = e->lru_next) {
        PyObject *row = Py_BuildValue(
            "(kKkK)", (unsigned long)e->model_id,
            (unsigned long long)e->hash, (unsigned long)e->req_model,
            (unsigned long long)e->req_hash);
        if (!row || PyList_Append(engines, row) < 0) {
            Py_XDECREF(row);
            goto fail;
        }
        Py_DECREF(row);
    }
    pthread_mutex_unlock(&self->mu);
    return Py_BuildValue("(NN)", entries, engines);
fail:
    pthread_mutex_unlock(&self->mu);
    Py_XDECREF(entries);
    Py_XDECREF(engines);
    return NULL;
}

static PyObject *Arena_stats(ArenaObject *self, PyObject *noarg) {
    (void)noarg;
    pthread_mutex_lock(&self->mu);
    PyObject *d = Py_BuildValue(
        "{s:n,s:n,s:n,s:n,s:K,s:K,s:K,s:K,s:K,s:n}",
        "keys", self->n_keys,
        "engine_keys", self->n_eng,
        "max_keys", self->max_keys,
        "pods_per_key", (Py_ssize_t)self->cap,
        "bytes", (unsigned long long)self->bytes_allocated,
        "epoch",
        (unsigned long long)atomic_load_explicit(&self->epoch,
                                                 memory_order_relaxed),
        "locked_lookups", (unsigned long long)self->locked_lookups,
        "adds", (unsigned long long)self->total_adds,
        "capacity_evictions", (unsigned long long)self->total_evictions,
        "blocks_applied", (Py_ssize_t)self->blocks_applied);
    pthread_mutex_unlock(&self->mu);
    return d;
}

/* ---------------------------------------------------------------------- */
/* score_batch: the fused read path                                       */
/* ---------------------------------------------------------------------- */

typedef struct {
    uint32_t model;
    uint64_t *hashes;        /* solo: full chain; fork: tail keys only */
    Py_ssize_t n_keys;
    const uint8_t *filter;   /* borrowed from the item's bytes object */
    Py_ssize_t filter_len;
    Py_ssize_t ref_pos;      /* -1 = solo */
    Py_ssize_t shared;       /* fork: shared leading blocks with ref */
    int keep;                /* solo: snapshot states for later forks */
    /* walk state / outputs */
    uint32_t m;              /* number of block-0 pods */
    uint32_t *pod_order;     /* local slot -> pod_id, first-seen order */
    double *scores;
    uint32_t *match;
    uint8_t *active;
    uint8_t *dropped;
    uint32_t active_count;
    double *snap_scores;     /* keep: n_snaps * m matrices */
    uint32_t *snap_match;
    uint8_t *snap_active;
    Py_ssize_t n_snaps;
    int override_flag;
    int routing_ran;
    int oom;
} ScoreItem;

static void score_item_snapshot(ScoreItem *it) {
    Py_ssize_t s = it->n_snaps;
    memcpy(it->snap_scores + s * it->m, it->scores, it->m * sizeof(double));
    memcpy(it->snap_match + s * it->m, it->match, it->m * sizeof(uint32_t));
    memcpy(it->snap_active + s * it->m, it->active, it->m);
    it->n_snaps = s + 1;
}

/* One key's entries folded into the per-pod max-weight staging arrays —
 * the exact `_pod_max_weights` arithmetic: first weight wins unless a
 * strictly greater one appears (same floats, same comparison). */
static inline void fold_key_entries(
    const uint64_t *ebuf, int ne, uint64_t stamp, const ScoreItem *it,
    Py_ssize_t n_pods, const double *tier_w, Py_ssize_t n_tiers,
    uint64_t *here_stamp, double *here_val, uint32_t *pod_slot,
    ScoreItem *grow /* non-NULL: block 0, append first-seen pods */) {
    for (int j = 0; j < ne; j++) {
        uint64_t packed = ebuf[j];
        uint32_t pod = (uint32_t)(packed >> 16);
        uint32_t tier = (uint32_t)(packed & 0xFFFF);
        if ((Py_ssize_t)pod >= n_pods) continue; /* interned mid-flight */
        if (it->filter && !bitmap_test(it->filter, it->filter_len, pod))
            continue;
        double w = (Py_ssize_t)tier < n_tiers ? tier_w[tier] : 1.0;
        if (here_stamp[pod] != stamp) {
            here_stamp[pod] = stamp;
            here_val[pod] = w;
            if (grow) {
                uint32_t m = grow->m;
                pod_slot[pod] = m;
                grow->pod_order[m] = pod;
                grow->scores[m] = w;
                grow->match[m] = 1;
                grow->active[m] = 1;
                grow->m = m + 1;
            }
        } else if (w > here_val[pod]) {
            here_val[pod] = w;
            if (grow) grow->scores[pod_slot[pod]] = w;
        }
    }
}

/* score_batch(items, tier_weights, lex_rank, health_factor, health_modes,
 *             ae_factors, divisors)
 *
 * items: sequence of (model_id, hashes, filter_bitmap|None, ref_pos,
 * shared_blocks, keep_states) — ref_pos < 0 is a solo walk over `hashes`;
 * ref_pos >= 0 forks from that earlier item's state snapshot after
 * `shared_blocks` keys and walks `hashes` as the tail. Mirrors
 * LongestPrefixScorer.score_plan + the per-item adjustment pipeline
 * (fleet-health modes / anti-entropy factors / routing divisors), all per
 * pod_id against the pushed factor tables, in ONE GIL-released crossing.
 *
 * Returns [ (((pod_id, score, match_blocks, dropped), ...), override,
 *            routing_ran), ... ] with pods in block-0 first-seen order —
 * the exact dict insertion order of the Python scorer. */
static PyObject *Arena_score_batch(ArenaObject *self, PyObject *args) {
    PyObject *items_obj, *tierw_obj, *lex_obj, *hm_obj, *ae_obj, *div_obj;
    double health_factor;
    if (!PyArg_ParseTuple(args, "OOOdOOO", &items_obj, &tierw_obj, &lex_obj,
                          &health_factor, &hm_obj, &ae_obj, &div_obj))
        return NULL;

    double *tier_w = NULL, *ae = NULL, *divs = NULL;
    uint32_t *lex = NULL;
    Py_ssize_t n_tiers = 0, n_ae = 0, n_div = 0, n_pods = 0;
    const uint8_t *hm = NULL;
    Py_ssize_t hm_len = 0;
    ScoreItem *its = NULL;
    Py_ssize_t n_items = 0, parsed = 0;
    PyObject *seq = NULL, *out = NULL;
    uint64_t *ebuf = NULL, *here_stamp = NULL;
    double *here_val = NULL;
    uint32_t *pod_slot = NULL;

    if (parse_f64_table(tierw_obj, &tier_w, &n_tiers) < 0) goto cleanup;
    if (parse_u32_table(lex_obj, &lex, &n_pods) < 0) goto cleanup;
    if (parse_bitmap(hm_obj, &hm, &hm_len) < 0) goto cleanup;
    if (parse_f64_table(ae_obj, &ae, &n_ae) < 0) goto cleanup;
    if (parse_f64_table(div_obj, &divs, &n_div) < 0) goto cleanup;

    seq = PySequence_Fast(items_obj, "score_batch items must be a sequence");
    if (!seq) goto cleanup;
    n_items = PySequence_Fast_GET_SIZE(seq);
    its = (ScoreItem *)PyMem_Calloc(n_items ? n_items : 1, sizeof(ScoreItem));
    if (!its) {
        PyErr_NoMemory();
        goto cleanup;
    }
    for (parsed = 0; parsed < n_items; parsed++) {
        PyObject *t = PySequence_Fast_GET_ITEM(seq, parsed);
        if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 6) {
            PyErr_SetString(PyExc_TypeError,
                            "score item must be a 6-tuple");
            goto cleanup;
        }
        ScoreItem *it = &its[parsed];
        uint64_t model;
        if (kv_as_u64(PyTuple_GET_ITEM(t, 0), &model) < 0) goto cleanup;
        it->model = (uint32_t)model;
        it->hashes = kv_tokens_to_array(PyTuple_GET_ITEM(t, 1), &it->n_keys);
        if (!it->hashes) goto cleanup;
        if (parse_bitmap(PyTuple_GET_ITEM(t, 2), &it->filter,
                         &it->filter_len) < 0)
            goto cleanup;
        it->ref_pos = PyLong_AsSsize_t(PyTuple_GET_ITEM(t, 3));
        it->shared = PyLong_AsSsize_t(PyTuple_GET_ITEM(t, 4));
        if (PyErr_Occurred()) goto cleanup;
        it->keep = PyObject_IsTrue(PyTuple_GET_ITEM(t, 5));
        if (it->keep < 0) goto cleanup;
        if (it->ref_pos >= parsed) {
            PyErr_SetString(PyExc_ValueError,
                            "fork ref_pos must point at an earlier item");
            goto cleanup;
        }
        uint32_t cap = self->cap;
        it->pod_order =
            (uint32_t *)PyMem_Malloc(cap * sizeof(uint32_t));
        it->scores = (double *)PyMem_Malloc(cap * sizeof(double));
        it->match = (uint32_t *)PyMem_Malloc(cap * sizeof(uint32_t));
        it->active = (uint8_t *)PyMem_Malloc(cap);
        it->dropped = (uint8_t *)PyMem_Calloc(cap, 1);
        if (!it->pod_order || !it->scores || !it->match || !it->active ||
            !it->dropped) {
            PyErr_NoMemory();
            goto cleanup;
        }
        if (it->keep) {
            size_t ns = (size_t)it->n_keys + 1;
            it->snap_scores =
                (double *)PyMem_Malloc(ns * cap * sizeof(double));
            it->snap_match =
                (uint32_t *)PyMem_Malloc(ns * cap * sizeof(uint32_t));
            it->snap_active = (uint8_t *)PyMem_Malloc(ns * cap);
            if (!it->snap_scores || !it->snap_match || !it->snap_active) {
                PyErr_NoMemory();
                goto cleanup;
            }
        }
    }

    ebuf = (uint64_t *)PyMem_Malloc(self->cap * sizeof(uint64_t));
    here_stamp = (uint64_t *)PyMem_Calloc(n_pods ? n_pods : 1,
                                          sizeof(uint64_t));
    here_val = (double *)PyMem_Malloc((n_pods ? n_pods : 1) * sizeof(double));
    pod_slot =
        (uint32_t *)PyMem_Malloc((n_pods ? n_pods : 1) * sizeof(uint32_t));
    if (!ebuf || !here_stamp || !here_val || !pod_slot) {
        PyErr_NoMemory();
        goto cleanup;
    }

    Py_BEGIN_ALLOW_THREADS
    uint64_t stamp = 0;
    for (Py_ssize_t p = 0; p < n_items; p++) {
        ScoreItem *it = &its[p];
        it->m = 0;
        it->active_count = 0;
        it->n_snaps = 0;
        Py_ssize_t start_key = 0;
        if (it->ref_pos >= 0) {
            /* Fork: resume from the reference's snapshot after `shared`
             * keys (a cut freezes the list; its last state IS the
             * post-cut state), then walk the tail keys. */
            ScoreItem *ref = &its[it->ref_pos];
            if (ref->n_snaps > 0) {
                Py_ssize_t si = it->shared < ref->n_snaps ? it->shared
                                                          : ref->n_snaps;
                si -= 1;
                if (si < 0) si = 0;
                it->m = ref->m;
                memcpy(it->pod_order, ref->pod_order,
                       it->m * sizeof(uint32_t));
                memcpy(it->scores, ref->snap_scores + si * ref->m,
                       it->m * sizeof(double));
                memcpy(it->match, ref->snap_match + si * ref->m,
                       it->m * sizeof(uint32_t));
                memcpy(it->active, ref->snap_active + si * ref->m, it->m);
                for (uint32_t i = 0; i < it->m; i++)
                    if (it->active[i]) it->active_count++;
            }
            /* Tail keys replay the later-key loop below from key 0. */
            for (Py_ssize_t k = 0; k < it->n_keys; k++) {
                if (it->active_count == 0) break;
                stamp++;
                int ne = arena_find(self, it->model, it->hashes[k], ebuf);
                fold_key_entries(ebuf, ne, stamp, it, n_pods, tier_w,
                                 n_tiers, here_stamp, here_val, pod_slot,
                                 NULL);
                for (uint32_t i = 0; i < it->m; i++) {
                    if (!it->active[i]) continue;
                    uint32_t pod = it->pod_order[i];
                    if (here_stamp[pod] == stamp) {
                        it->scores[i] += here_val[pod];
                        it->match[i] += 1;
                    } else {
                        it->active[i] = 0;
                        it->active_count--;
                    }
                }
            }
        } else if (it->n_keys > 0) {
            /* Solo: block 0 seeds scores/active/match ... */
            stamp++;
            int ne = arena_find(self, it->model, it->hashes[0], ebuf);
            fold_key_entries(ebuf, ne, stamp, it, n_pods, tier_w, n_tiers,
                             here_stamp, here_val, pod_slot, it);
            it->active_count = it->m;
            if (it->keep) score_item_snapshot(it);
            /* ... then each later key intersects + accumulates. */
            for (Py_ssize_t k = 1; k < it->n_keys; k++) {
                if (it->active_count == 0) break;
                stamp++;
                ne = arena_find(self, it->model, it->hashes[k], ebuf);
                fold_key_entries(ebuf, ne, stamp, it, n_pods, tier_w,
                                 n_tiers, here_stamp, here_val, pod_slot,
                                 NULL);
                for (uint32_t i = 0; i < it->m; i++) {
                    if (!it->active[i]) continue;
                    uint32_t pod = it->pod_order[i];
                    if (here_stamp[pod] == stamp) {
                        it->scores[i] += here_val[pod];
                        it->match[i] += 1;
                    } else {
                        it->active[i] = 0;
                        it->active_count--;
                    }
                }
                if (it->keep) score_item_snapshot(it);
            }
            (void)start_key;
        }

        /* Per-item adjustment pipeline, same order as the Python path:
         * fleet-health (STALE drop / SUSPECT x factor) -> anti-entropy
         * accuracy (<1.0 multiplies) -> routing load demotion (divide +
         * argmax override detection). Dropped pods keep their match
         * count: match_blocks is never filtered in the Python path. */
        uint32_t n_live = it->m;
        if (hm) {
            for (uint32_t i = 0; i < it->m; i++) {
                uint32_t pod = it->pod_order[i];
                uint8_t mode =
                    (Py_ssize_t)pod < hm_len ? hm[pod] : 0;
                if (mode == 2) {
                    it->dropped[i] = 1;
                    n_live--;
                } else if (mode == 1) {
                    it->scores[i] *= health_factor;
                }
            }
        }
        if (ae) {
            for (uint32_t i = 0; i < it->m; i++) {
                if (it->dropped[i]) continue;
                uint32_t pod = it->pod_order[i];
                double f = (Py_ssize_t)pod < n_ae ? ae[pod] : 1.0;
                if (f < 1.0) it->scores[i] *= f;
            }
        }
        it->routing_ran = 0;
        it->override_flag = 0;
        if (divs && n_live > 0) {
            double best = 0.0;
            uint32_t best_rank = 0;
            int first = 1;
            for (uint32_t i = 0; i < it->m; i++) {
                if (it->dropped[i]) continue;
                uint32_t pod = it->pod_order[i];
                uint32_t rank =
                    (Py_ssize_t)pod < n_pods ? lex[pod] : 0xFFFFFFFFu;
                double v = it->scores[i];
                if (first || v > best) {
                    best = v;
                    best_rank = rank;
                    first = 0;
                } else if (v == best && rank < best_rank) {
                    best_rank = rank;
                }
            }
            uint32_t before = best_rank;
            for (uint32_t i = 0; i < it->m; i++) {
                if (it->dropped[i]) continue;
                uint32_t pod = it->pod_order[i];
                double d = (Py_ssize_t)pod < n_div ? divs[pod] : 1.0;
                it->scores[i] = it->scores[i] / d;
            }
            first = 1;
            for (uint32_t i = 0; i < it->m; i++) {
                if (it->dropped[i]) continue;
                uint32_t pod = it->pod_order[i];
                uint32_t rank =
                    (Py_ssize_t)pod < n_pods ? lex[pod] : 0xFFFFFFFFu;
                double v = it->scores[i];
                if (first || v > best) {
                    best = v;
                    best_rank = rank;
                    first = 0;
                } else if (v == best && rank < best_rank) {
                    best_rank = rank;
                }
            }
            it->routing_ran = 1;
            it->override_flag = before != best_rank;
        }
    }
    Py_END_ALLOW_THREADS

    /* Box results. */
    out = PyList_New(n_items);
    if (!out) goto cleanup;
    for (Py_ssize_t p = 0; p < n_items; p++) {
        ScoreItem *it = &its[p];
        PyObject *pods = PyTuple_New(it->m);
        if (!pods) {
            Py_CLEAR(out);
            goto cleanup;
        }
        for (uint32_t i = 0; i < it->m; i++) {
            PyObject *row = Py_BuildValue(
                "(IdIi)", (unsigned int)it->pod_order[i], it->scores[i],
                (unsigned int)it->match[i], (int)it->dropped[i]);
            if (!row) {
                Py_DECREF(pods);
                Py_CLEAR(out);
                goto cleanup;
            }
            PyTuple_SET_ITEM(pods, i, row);
        }
        PyObject *res = Py_BuildValue("(Nii)", pods, it->override_flag,
                                      it->routing_ran);
        if (!res) {
            Py_CLEAR(out);
            goto cleanup;
        }
        PyList_SET_ITEM(out, p, res);
    }

cleanup:
    if (its) {
        for (Py_ssize_t p = 0; p < n_items; p++) {
            PyMem_Free(its[p].hashes);
            PyMem_Free(its[p].pod_order);
            PyMem_Free(its[p].scores);
            PyMem_Free(its[p].match);
            PyMem_Free(its[p].active);
            PyMem_Free(its[p].dropped);
            PyMem_Free(its[p].snap_scores);
            PyMem_Free(its[p].snap_match);
            PyMem_Free(its[p].snap_active);
        }
        PyMem_Free(its);
    }
    PyMem_Free(tier_w);
    PyMem_Free(lex);
    PyMem_Free(ae);
    PyMem_Free(divs);
    PyMem_Free(ebuf);
    PyMem_Free(here_stamp);
    PyMem_Free(here_val);
    PyMem_Free(pod_slot);
    Py_XDECREF(seq);
    return out;
}

/* ---------------------------------------------------------------------- */
/* apply_batch: the fused write path                                      */
/* ---------------------------------------------------------------------- */

/* hash_as_uint64 with skip-instead-of-raise semantics (the digest loop
 * catches TypeError/ValueError per hash): bools and non-int/bytes types
 * skip, ints are masked to 64 bits, bytes take their last 8 bytes
 * big-endian (empty bytes skip). Returns 1 ok / 0 skip. */
static int coerce_hash(PyObject *raw, uint64_t *out) {
    if (PyBool_Check(raw)) return 0;
    if (PyLong_Check(raw)) {
        uint64_t v = PyLong_AsUnsignedLongLongMask(raw);
        if (v == (uint64_t)-1 && PyErr_Occurred()) {
            PyErr_Clear();
            return 0;
        }
        *out = v;
        return 1;
    }
    const uint8_t *buf = NULL;
    Py_ssize_t len = 0;
    if (PyBytes_Check(raw)) {
        buf = (const uint8_t *)PyBytes_AS_STRING(raw);
        len = PyBytes_GET_SIZE(raw);
    } else if (PyByteArray_Check(raw)) {
        buf = (const uint8_t *)PyByteArray_AS_STRING(raw);
        len = PyByteArray_GET_SIZE(raw);
    } else {
        return 0;
    }
    if (len == 0) return 0; /* int.from_bytes(b"") -> ValueError path */
    if (len > 8) {
        buf += len - 8;
        len = 8;
    }
    uint64_t v = 0;
    for (Py_ssize_t i = 0; i < len; i++) v = (v << 8) | buf[i];
    *out = v;
    return 1;
}

typedef struct {
    int kind;          /* 1 = BlockStored, 0 = BlockRemoved */
    int drop;          /* stored: bad parent hash -> drop whole event */
    int has_parent;
    uint64_t parent;
    uint64_t *hashes;  /* coerced engine block hashes, bad ones skipped */
    Py_ssize_t n_hashes;
    uint64_t *tokens;
    Py_ssize_t n_tokens;
    uint64_t *extra;   /* lora extra keys or NULL */
    Py_ssize_t n_extra;
    uint64_t packed;   /* (pod_id<<16)|tier_id entry */
} ApplyEvent;

/* apply_batch(model_id, root_hash, block_size, events) -> blocks applied.
 *
 * events: sequence of
 *   (1, block_hashes, parent_hash|None, token_ids, extra|None, packed)
 *   (0, block_hashes, packed)
 * with hashes still raw off the wire (coercion happens here, mirroring
 * hash_as_uint64 + the per-hash try/except), tokens as int sequences,
 * and pod/tier already validated + interned by the wrapper.
 *
 * Conversion is all-or-nothing BEFORE any mutation: a hard conversion
 * error raises with the arena untouched, so the wrapper can fall back to
 * the pure-Python digest and reach the exact same final state. The apply
 * loop then runs under the writer mutex with the GIL released — request
 * keys are chain-derived with kv_hash_block (bit-identical to the
 * token_processor) and events land with the Python digest's semantics:
 * parent via the engine map (recency touch) else the root hash, length
 * mismatches skipped like the caught ValueError, removals that empty a
 * key drop the key and its engine mapping. */
static PyObject *Arena_apply_batch(ArenaObject *self, PyObject *args) {
    unsigned long model_l;
    unsigned long long root;
    Py_ssize_t block_size;
    PyObject *events_obj;
    if (!PyArg_ParseTuple(args, "kKnO", &model_l, &root, &block_size,
                          &events_obj))
        return NULL;
    if (block_size <= 0) {
        PyErr_SetString(PyExc_ValueError, "block_size must be positive");
        return NULL;
    }
    uint32_t model = (uint32_t)model_l;
    PyObject *seq =
        PySequence_Fast(events_obj, "events must be a sequence");
    if (!seq) return NULL;
    Py_ssize_t n_events = PySequence_Fast_GET_SIZE(seq);
    ApplyEvent *evs =
        (ApplyEvent *)PyMem_Calloc(n_events ? n_events : 1,
                                   sizeof(ApplyEvent));
    if (!evs) {
        Py_DECREF(seq);
        return PyErr_NoMemory();
    }

    /* Phase 1 (GIL held): convert everything. */
    Py_ssize_t max_req = 0;
    int ok = 1;
    for (Py_ssize_t i = 0; i < n_events && ok; i++) {
        PyObject *t = PySequence_Fast_GET_ITEM(seq, i);
        ApplyEvent *ev = &evs[i];
        if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) < 3) {
            PyErr_SetString(PyExc_TypeError, "event must be a tuple");
            ok = 0;
            break;
        }
        long kind = PyLong_AsLong(PyTuple_GET_ITEM(t, 0));
        if (kind == -1 && PyErr_Occurred()) {
            ok = 0;
            break;
        }
        ev->kind = (int)kind;
        PyObject *hashes_obj = PyTuple_GET_ITEM(t, 1);
        PyObject *hseq = PySequence_Fast(
            hashes_obj, "block_hashes must be a sequence");
        if (!hseq) {
            ok = 0;
            break;
        }
        Py_ssize_t nh = PySequence_Fast_GET_SIZE(hseq);
        ev->hashes =
            (uint64_t *)PyMem_Malloc(nh ? nh * sizeof(uint64_t) : 1);
        if (!ev->hashes) {
            Py_DECREF(hseq);
            PyErr_NoMemory();
            ok = 0;
            break;
        }
        Py_ssize_t kept = 0;
        for (Py_ssize_t j = 0; j < nh; j++) {
            uint64_t h;
            if (coerce_hash(PySequence_Fast_GET_ITEM(hseq, j), &h))
                ev->hashes[kept++] = h;
        }
        Py_DECREF(hseq);
        ev->n_hashes = kept;
        if (ev->kind == 1) {
            if (PyTuple_GET_SIZE(t) != 6) {
                PyErr_SetString(PyExc_TypeError,
                                "BlockStored event must be a 6-tuple");
                ok = 0;
                break;
            }
            PyObject *parent_obj = PyTuple_GET_ITEM(t, 2);
            if (parent_obj != Py_None) {
                if (coerce_hash(parent_obj, &ev->parent)) {
                    ev->has_parent = 1;
                } else {
                    ev->drop = 1; /* bad parent -> drop whole event */
                    continue;
                }
            }
            ev->tokens =
                kv_tokens_to_array(PyTuple_GET_ITEM(t, 3), &ev->n_tokens);
            if (!ev->tokens) {
                ok = 0;
                break;
            }
            if (kv_extra_to_array(PyTuple_GET_ITEM(t, 4), &ev->extra,
                                  &ev->n_extra) < 0) {
                ok = 0;
                break;
            }
            if (kv_as_u64(PyTuple_GET_ITEM(t, 5), &ev->packed) < 0) {
                ok = 0;
                break;
            }
            Py_ssize_t n_req = ev->n_tokens / block_size;
            if (n_req > max_req) max_req = n_req;
            if (ev->n_extra + 1 > max_req) max_req = ev->n_extra + 1;
        } else {
            if (kv_as_u64(PyTuple_GET_ITEM(t, 2), &ev->packed) < 0) {
                ok = 0;
                break;
            }
        }
    }
    Py_DECREF(seq);

    uint64_t *req_hashes = NULL;
    uint8_t *hash_buf = NULL;
    if (ok) {
        req_hashes = (uint64_t *)PyMem_Malloc(
            (max_req ? max_req : 1) * sizeof(uint64_t));
        /* Worst-case canonical CBOR for one block + extras. */
        size_t buf_sz = 20 + 9 * (size_t)block_size + 9 * ((size_t)max_req + 1);
        hash_buf = (uint8_t *)PyMem_Malloc(buf_sz);
        if (!req_hashes || !hash_buf) {
            PyErr_NoMemory();
            ok = 0;
        }
    }

    long applied = 0;
    if (ok) {
        /* Phase 2 (GIL released, writer mutex): apply everything. */
        Py_BEGIN_ALLOW_THREADS
        pthread_mutex_lock(&self->mu);
        for (Py_ssize_t i = 0; i < n_events; i++) {
            ApplyEvent *ev = &evs[i];
            if (ev->kind == 1) {
                if (ev->drop) continue;
                uint64_t parent_hash = root;
                if (ev->has_parent) {
                    EngNode *pe = eng_get(self, model, ev->parent);
                    if (pe) parent_hash = pe->req_hash;
                }
                Py_ssize_t n_req = ev->n_tokens / block_size;
                if (ev->n_hashes == 0) continue;   /* `if engine_keys:` */
                if (n_req == 0 || ev->n_hashes != n_req)
                    continue; /* the caught ValueError paths */
                uint64_t h = parent_hash;
                for (Py_ssize_t b = 0; b < n_req; b++) {
                    h = kv_hash_block(hash_buf, h,
                                      ev->tokens + b * block_size,
                                      block_size, ev->extra, ev->n_extra);
                    req_hashes[b] = h;
                }
                for (Py_ssize_t b = 0; b < n_req; b++)
                    eng_add(self, model, ev->hashes[b], model,
                            req_hashes[b]);
                for (Py_ssize_t b = 0; b < n_req; b++) {
                    KeyNode *n =
                        key_get_or_create(self, model, req_hashes[b], NULL);
                    if (!n) break;
                    node_entry_add(self, n, ev->packed);
                }
                applied += n_req;
            } else {
                for (Py_ssize_t j = 0; j < ev->n_hashes; j++) {
                    EngNode *e = eng_get(self, model, ev->hashes[j]);
                    if (!e) continue;
                    KeyNode *n =
                        key_find_locked(self, e->req_model, e->req_hash);
                    if (!n) {
                        eng_remove(self, e);
                        continue;
                    }
                    key_lru_touch(self, n);
                    node_entry_remove(self, n, ev->packed);
                    if (atomic_load_explicit(&n->n_entries,
                                             memory_order_relaxed) == 0) {
                        key_node_remove(self, n);
                        eng_remove(self, e);
                    }
                    applied++;
                }
            }
        }
        self->blocks_applied += (uint64_t)applied;
        pthread_mutex_unlock(&self->mu);
        Py_END_ALLOW_THREADS
    }

    for (Py_ssize_t i = 0; i < n_events; i++) {
        PyMem_Free(evs[i].hashes);
        PyMem_Free(evs[i].tokens);
        PyMem_Free(evs[i].extra);
    }
    PyMem_Free(evs);
    PyMem_Free(req_hashes);
    PyMem_Free(hash_buf);
    if (!ok) return NULL;
    return PyLong_FromLong(applied);
}


/* seed_key(model_id, hash, packed_entries): import_view helper — insert
 * entries for a request key WITHOUT touching the engine map. */
static PyObject *Arena_seed_key(ArenaObject *self, PyObject *args) {
    unsigned long model;
    unsigned long long hash;
    PyObject *ent_obj;
    if (!PyArg_ParseTuple(args, "kKO", &model, &hash, &ent_obj)) return NULL;
    uint64_t *packed = NULL;
    Py_ssize_t np = 0;
    if (parse_packed(ent_obj, &packed, &np) < 0) return NULL;
    long added = 0;
    Py_BEGIN_ALLOW_THREADS
    pthread_mutex_lock(&self->mu);
    KeyNode *n = key_get_or_create(self, (uint32_t)model, hash, NULL);
    if (n) {
        for (Py_ssize_t j = 0; j < np; j++) {
            node_entry_add(self, n, packed[j]);
            added++;
        }
    }
    pthread_mutex_unlock(&self->mu);
    Py_END_ALLOW_THREADS
    PyMem_Free(packed);
    return PyLong_FromLong(added);
}

/* seed_engine(model_id, hash, req_model_id, req_hash): import_view helper
 * for one engine→request mapping. */
static PyObject *Arena_seed_engine(ArenaObject *self, PyObject *args) {
    unsigned long model, req_model;
    unsigned long long hash, req_hash;
    if (!PyArg_ParseTuple(args, "kKkK", &model, &hash, &req_model, &req_hash))
        return NULL;
    Py_BEGIN_ALLOW_THREADS
    pthread_mutex_lock(&self->mu);
    eng_add(self, (uint32_t)model, hash, (uint32_t)req_model, req_hash);
    pthread_mutex_unlock(&self->mu);
    Py_END_ALLOW_THREADS
    Py_RETURN_NONE;
}

/* ---------------------------------------------------------------------- */
/* Type + module                                                          */
/* ---------------------------------------------------------------------- */

static PyMethodDef Arena_methods[] = {
    {"add", (PyCFunction)Arena_add, METH_VARARGS,
     "add(engine_pairs, request_pairs, entries): Index.add over ids."},
    {"evict", (PyCFunction)Arena_evict, METH_VARARGS,
     "evict(model_id, hash, entries) -> removed | -1 on engine miss."},
    {"get_request_key", (PyCFunction)Arena_get_request_key, METH_VARARGS,
     "get_request_key(model_id, hash) -> (model_id, hash) | None."},
    {"lookup_chain", (PyCFunction)Arena_lookup_chain, METH_VARARGS,
     "lookup_chain(model_id, hashes) -> [(packed, ...), ...] (chain cut)."},
    {"remove_matching", (PyCFunction)Arena_remove_matching, METH_VARARGS,
     "remove_matching(pod_bitmap, tier_bitmap|None, pairs|None) -> n."},
    {"seed_key", (PyCFunction)Arena_seed_key, METH_VARARGS,
     "seed_key(model_id, hash, entries) -> n (import_view helper)."},
    {"seed_engine", (PyCFunction)Arena_seed_engine, METH_VARARGS,
     "seed_engine(model_id, hash, req_model_id, req_hash)."},
    {"dump", (PyCFunction)Arena_dump, METH_NOARGS,
     "dump() -> (entry_rows, engine_rows), oldest-first."},
    {"stats", (PyCFunction)Arena_stats, METH_NOARGS,
     "stats() -> dict of arena counters."},
    {"score_batch", (PyCFunction)Arena_score_batch, METH_VARARGS,
     "Fused lookup + longest-prefix score + adjustments, one crossing."},
    {"apply_batch", (PyCFunction)Arena_apply_batch, METH_VARARGS,
     "Apply decoded BlockStored/BlockRemoved events, one crossing."},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject ArenaType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_kvtpu_kvscore.Arena",
    .tp_basicsize = sizeof(ArenaObject),
    .tp_itemsize = 0,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "GIL-free KV-block index arena with a fused batch scorer.",
    .tp_new = Arena_new,
    .tp_dealloc = (destructor)Arena_dealloc,
    .tp_methods = Arena_methods,
};

static struct PyModuleDef kvscore_module = {
    PyModuleDef_HEAD_INIT,
    "_kvtpu_kvscore",
    "Native index arena + fused GIL-free batch scorer.",
    -1,
    NULL,
};

PyMODINIT_FUNC PyInit__kvtpu_kvscore(void) {
    if (PyType_Ready(&ArenaType) < 0) return NULL;
    PyObject *m = PyModule_Create(&kvscore_module);
    if (!m) return NULL;
    Py_INCREF(&ArenaType);
    if (PyModule_AddObject(m, "Arena", (PyObject *)&ArenaType) < 0) {
        Py_DECREF(&ArenaType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}

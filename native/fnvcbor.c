/* Native hash core for the TPU KV-cache manager.
 *
 * Implements the chained block-key derivation --
 * FNV-64a(canonical_CBOR([parent_u64, [token_u32...], null])) -- as a CPython
 * extension. This is the read path's hot loop (every GetPodScores call hashes
 * prompt_len / block_size chunks) and the write plane's request-key
 * recomputation. Semantically identical to the pure-Python implementation in
 * llm_d_kv_cache_manager_tpu/kvcache/kvblock/hashing.py (the test oracle);
 * ~100x faster on long prompts.
 *
 * The reference gets the equivalent speed from Go + a Rust tokenizer core;
 * this build keeps Python as the control-plane language and drops to C for
 * the hashing kernel, mirroring the reference's native-where-hot design
 * (/root/reference/pkg/kvcache/kvblock/token_processor.go:94-112).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define FNV64_OFFSET 0xcbf29ce484222325ULL
#define FNV64_PRIME 0x100000001b3ULL

static uint64_t fnv1a64(const uint8_t *data, size_t n, uint64_t h) {
    for (size_t i = 0; i < n; i++) {
        h ^= (uint64_t)data[i];
        h *= FNV64_PRIME;
    }
    return h;
}

/* Shortest-form CBOR head (RFC 8949 canonical). Returns bytes written. */
static size_t cbor_head(uint8_t *out, uint8_t major, uint64_t value) {
    uint8_t mt = (uint8_t)(major << 5);
    if (value < 24) {
        out[0] = mt | (uint8_t)value;
        return 1;
    } else if (value <= 0xff) {
        out[0] = mt | 24;
        out[1] = (uint8_t)value;
        return 2;
    } else if (value <= 0xffff) {
        out[0] = mt | 25;
        out[1] = (uint8_t)(value >> 8);
        out[2] = (uint8_t)value;
        return 3;
    } else if (value <= 0xffffffffULL) {
        out[0] = mt | 26;
        out[1] = (uint8_t)(value >> 24);
        out[2] = (uint8_t)(value >> 16);
        out[3] = (uint8_t)(value >> 8);
        out[4] = (uint8_t)value;
        return 5;
    }
    out[0] = mt | 27;
    for (int i = 0; i < 8; i++) out[1 + i] = (uint8_t)(value >> (56 - 8 * i));
    return 9;
}

/* prefix_hashes(parent: int, tokens: sequence[int], block_size: int) -> list[int]
 * Chunks tokens into full blocks and chain-hashes them. */
static PyObject *prefix_hashes(PyObject *self, PyObject *args) {
    unsigned long long parent;
    PyObject *tokens_obj;
    Py_ssize_t block_size;
    if (!PyArg_ParseTuple(args, "KOn", &parent, &tokens_obj, &block_size))
        return NULL;
    if (block_size <= 0) {
        PyErr_SetString(PyExc_ValueError, "block_size must be positive");
        return NULL;
    }

    PyObject *seq = PySequence_Fast(tokens_obj, "tokens must be a sequence");
    if (!seq) return NULL;
    Py_ssize_t n_tokens = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t n_blocks = n_tokens / block_size;

    PyObject *result = PyList_New(n_blocks);
    if (!result) {
        Py_DECREF(seq);
        return NULL;
    }
    if (n_blocks == 0) {
        Py_DECREF(seq);
        return result;
    }

    /* Worst case per block: 9 (parent) + 9 (array head) + 9*block + 2. */
    size_t buf_cap = 20 + 9 * (size_t)block_size;
    uint8_t *buf = (uint8_t *)PyMem_Malloc(buf_cap);
    if (!buf) {
        Py_DECREF(seq);
        Py_DECREF(result);
        return PyErr_NoMemory();
    }

    uint64_t h = (uint64_t)parent;
    PyObject **items = PySequence_Fast_ITEMS(seq);
    for (Py_ssize_t b = 0; b < n_blocks; b++) {
        size_t pos = 0;
        buf[pos++] = 0x83; /* array(3) */
        pos += cbor_head(buf + pos, 0, h);
        pos += cbor_head(buf + pos, 4, (uint64_t)block_size);
        for (Py_ssize_t i = 0; i < block_size; i++) {
            PyObject *item = items[b * block_size + i];
            unsigned long long tok = PyLong_AsUnsignedLongLong(item);
            if (tok == (unsigned long long)-1 && PyErr_Occurred()) {
                PyMem_Free(buf);
                Py_DECREF(seq);
                Py_DECREF(result);
                return NULL;
            }
            pos += cbor_head(buf + pos, 0, (uint64_t)tok);
        }
        buf[pos++] = 0xf6; /* null */

        h = fnv1a64(buf, pos, FNV64_OFFSET);
        PyObject *val = PyLong_FromUnsignedLongLong(h);
        if (!val) {
            PyMem_Free(buf);
            Py_DECREF(seq);
            Py_DECREF(result);
            return NULL;
        }
        PyList_SET_ITEM(result, b, val);
    }

    PyMem_Free(buf);
    Py_DECREF(seq);
    return result;
}

/* fnv64a(data: bytes, h: int = offset) -> int */
static PyObject *fnv64a_py(PyObject *self, PyObject *args) {
    Py_buffer view;
    unsigned long long h = FNV64_OFFSET;
    if (!PyArg_ParseTuple(args, "y*|K", &view, &h)) return NULL;
    uint64_t out = fnv1a64((const uint8_t *)view.buf, (size_t)view.len, h);
    PyBuffer_Release(&view);
    return PyLong_FromUnsignedLongLong(out);
}

static PyMethodDef methods[] = {
    {"prefix_hashes", prefix_hashes, METH_VARARGS,
     "Chained CBOR+FNV-64a block hashes over full token blocks."},
    {"fnv64a", fnv64a_py, METH_VARARGS, "FNV-64a of a bytes-like object."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_kvtpu_native",
    "Native hash core (chained CBOR+FNV-64a).", -1, methods,
};

PyMODINIT_FUNC PyInit__kvtpu_native(void) { return PyModule_Create(&module); }

/* Native hash core for the TPU KV-cache manager.
 *
 * Implements the chained block-key derivation --
 * FNV-64a(canonical_CBOR([parent_u64, [token_u32...], extra|null])) -- as a
 * CPython extension. This is the read path's hot loop (every GetPodScores
 * call hashes prompt_len / block_size chunks) and the write plane's
 * request-key recomputation. Semantically identical to the pure-Python
 * implementation in llm_d_kv_cache_manager_tpu/kvcache/kvblock/hashing.py
 * (the test oracle); ~100x faster on long prompts.
 *
 * Three generations of entry point:
 *   prefix_hashes        legacy: extra=None only, pre-converted int tokens
 *   batch_prefix_hashes  one crossing per request: extra-key (LoRA) chains,
 *                        __index__-tolerant token conversion (numpy/jax
 *                        scalars accepted directly -- no [int(t) ...] copy
 *                        on the Python side), GIL released while hashing so
 *                        read-path derivation overlaps kvevents digestion
 *   chunk_hash           single-block link (differential-fuzz target)
 *   token_fingerprints   chain-memo support: per-token 64-bit fold with a
 *                        fingerprint emitted at each segment boundary; GIL
 *                        released. NOT the block-key hash -- cache keys for
 *                        kvcache/kvblock/chain_memo.py only.
 *
 * The reference gets the equivalent speed from Go + a Rust tokenizer core;
 * this build keeps Python as the control-plane language and drops to C for
 * the hashing kernel, mirroring the reference's native-where-hot design
 * (/root/reference/pkg/kvcache/kvblock/token_processor.go:94-112).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* Shared CBOR+FNV helpers (also used by kvscore.c, the index/scorer arena).
 * Local short names preserved so the function bodies below read as before. */
#include "kvhash.h"
#define fnv1a64 kv_fnv1a64
#define cbor_head kv_cbor_head
#define hash_block kv_hash_block
#define as_u64 kv_as_u64
#define tokens_to_array kv_tokens_to_array
#define extra_to_array kv_extra_to_array

/* prefix_hashes(parent: int, tokens: sequence[int], block_size: int) -> list[int]
 * Chunks tokens into full blocks and chain-hashes them. */
static PyObject *prefix_hashes(PyObject *self, PyObject *args) {
    unsigned long long parent;
    PyObject *tokens_obj;
    Py_ssize_t block_size;
    if (!PyArg_ParseTuple(args, "KOn", &parent, &tokens_obj, &block_size))
        return NULL;
    if (block_size <= 0) {
        PyErr_SetString(PyExc_ValueError, "block_size must be positive");
        return NULL;
    }

    PyObject *seq = PySequence_Fast(tokens_obj, "tokens must be a sequence");
    if (!seq) return NULL;
    Py_ssize_t n_tokens = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t n_blocks = n_tokens / block_size;

    PyObject *result = PyList_New(n_blocks);
    if (!result) {
        Py_DECREF(seq);
        return NULL;
    }
    if (n_blocks == 0) {
        Py_DECREF(seq);
        return result;
    }

    /* Worst case per block: 9 (parent) + 9 (array head) + 9*block + 2. */
    size_t buf_cap = 20 + 9 * (size_t)block_size;
    uint8_t *buf = (uint8_t *)PyMem_Malloc(buf_cap);
    if (!buf) {
        Py_DECREF(seq);
        Py_DECREF(result);
        return PyErr_NoMemory();
    }

    uint64_t h = (uint64_t)parent;
    PyObject **items = PySequence_Fast_ITEMS(seq);
    for (Py_ssize_t b = 0; b < n_blocks; b++) {
        size_t pos = 0;
        buf[pos++] = 0x83; /* array(3) */
        pos += cbor_head(buf + pos, 0, h);
        pos += cbor_head(buf + pos, 4, (uint64_t)block_size);
        for (Py_ssize_t i = 0; i < block_size; i++) {
            PyObject *item = items[b * block_size + i];
            unsigned long long tok = PyLong_AsUnsignedLongLong(item);
            if (tok == (unsigned long long)-1 && PyErr_Occurred()) {
                PyMem_Free(buf);
                Py_DECREF(seq);
                Py_DECREF(result);
                return NULL;
            }
            pos += cbor_head(buf + pos, 0, (uint64_t)tok);
        }
        buf[pos++] = 0xf6; /* null */

        h = fnv1a64(buf, pos, FNV64_OFFSET);
        PyObject *val = PyLong_FromUnsignedLongLong(h);
        if (!val) {
            PyMem_Free(buf);
            Py_DECREF(seq);
            Py_DECREF(result);
            return NULL;
        }
        PyList_SET_ITEM(result, b, val);
    }

    PyMem_Free(buf);
    Py_DECREF(seq);
    return result;
}

/* batch_prefix_hashes(parent, tokens, block_size, extra=None) -> list[int]
 * Whole-request derivation in one crossing: chunk into full blocks, chain
 * the CBOR+FNV-64a links (extra keys mixed into every block when given),
 * GIL dropped for the hash loop. */
static PyObject *batch_prefix_hashes(PyObject *self, PyObject *args) {
    unsigned long long parent;
    PyObject *tokens_obj;
    PyObject *extra_obj = Py_None;
    Py_ssize_t block_size;
    if (!PyArg_ParseTuple(args, "KOn|O", &parent, &tokens_obj, &block_size,
                          &extra_obj))
        return NULL;
    if (block_size <= 0) {
        PyErr_SetString(PyExc_ValueError, "block_size must be positive");
        return NULL;
    }

    Py_ssize_t n_tokens = 0, n_extra = 0;
    uint64_t *toks = tokens_to_array(tokens_obj, &n_tokens);
    if (!toks) return NULL;
    uint64_t *extra = NULL;
    if (extra_to_array(extra_obj, &extra, &n_extra) < 0) {
        PyMem_Free(toks);
        return NULL;
    }

    Py_ssize_t n_blocks = n_tokens / block_size;
    size_t buf_cap = 20 + 9 * (size_t)block_size + 9 * (size_t)(n_extra + 1);
    uint8_t *buf = (uint8_t *)PyMem_Malloc(buf_cap);
    uint64_t *out = (uint64_t *)PyMem_Malloc(
        n_blocks ? n_blocks * sizeof(uint64_t) : 1);
    if (!buf || !out) {
        PyMem_Free(toks);
        PyMem_Free(extra);
        PyMem_Free(buf);
        PyMem_Free(out);
        return PyErr_NoMemory();
    }

    uint64_t h = (uint64_t)parent;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t b = 0; b < n_blocks; b++) {
        h = hash_block(buf, h, toks + b * block_size, block_size,
                       extra, n_extra);
        out[b] = h;
    }
    Py_END_ALLOW_THREADS

    PyObject *result = PyList_New(n_blocks);
    if (result) {
        for (Py_ssize_t b = 0; b < n_blocks; b++) {
            PyObject *val = PyLong_FromUnsignedLongLong(out[b]);
            if (!val) {
                Py_CLEAR(result);
                break;
            }
            PyList_SET_ITEM(result, b, val);
        }
    }
    PyMem_Free(toks);
    PyMem_Free(extra);
    PyMem_Free(buf);
    PyMem_Free(out);
    return result;
}

/* batch_prefix_hashes_many(requests) -> list[list[int]]
 * The batched-read-path entry: `requests` is a sequence of
 * (parent, tokens, block_size, extra|None) tuples — one per router-batch
 * item — and the whole batch is derived in ONE Python<->C crossing with the
 * GIL released across every request's hash loop. Each item's result is
 * exactly batch_prefix_hashes(parent, tokens, block_size, extra); items are
 * independent chains (no cross-item state), so the only thing the batching
 * changes is how often the GIL is taken. */
struct _bp_req {
    uint64_t parent;
    uint64_t *toks;
    Py_ssize_t n_tokens;
    uint64_t *extra;
    Py_ssize_t n_extra;
    Py_ssize_t block_size;
    Py_ssize_t n_blocks;
    uint64_t *out;
};

static void _bp_free(struct _bp_req *reqs, Py_ssize_t n, uint8_t *buf) {
    for (Py_ssize_t i = 0; i < n; i++) {
        PyMem_Free(reqs[i].toks);
        PyMem_Free(reqs[i].extra);
        PyMem_Free(reqs[i].out);
    }
    PyMem_Free(reqs);
    PyMem_Free(buf);
}

static PyObject *batch_prefix_hashes_many(PyObject *self, PyObject *args) {
    PyObject *requests_obj;
    if (!PyArg_ParseTuple(args, "O", &requests_obj)) return NULL;
    PyObject *seq = PySequence_Fast(requests_obj,
                                    "requests must be a sequence");
    if (!seq) return NULL;
    Py_ssize_t n_reqs = PySequence_Fast_GET_SIZE(seq);
    struct _bp_req *reqs = (struct _bp_req *)PyMem_Malloc(
        n_reqs ? n_reqs * sizeof(struct _bp_req) : 1);
    if (!reqs) {
        Py_DECREF(seq);
        return PyErr_NoMemory();
    }
    memset(reqs, 0, n_reqs ? n_reqs * sizeof(struct _bp_req) : 1);

    /* Phase 1 (GIL held): convert every request's Python objects. */
    size_t buf_cap = 32;
    for (Py_ssize_t i = 0; i < n_reqs; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
        unsigned long long parent;
        PyObject *tokens_obj, *extra_obj = Py_None;
        Py_ssize_t block_size;
        if (!PyTuple_Check(item) ||
            !PyArg_ParseTuple(item, "KOn|O:batch_prefix_hashes_many request",
                              &parent, &tokens_obj, &block_size, &extra_obj))
            goto fail;
        if (block_size <= 0) {
            PyErr_SetString(PyExc_ValueError, "block_size must be positive");
            goto fail;
        }
        struct _bp_req *r = &reqs[i];
        r->parent = (uint64_t)parent;
        r->block_size = block_size;
        r->toks = tokens_to_array(tokens_obj, &r->n_tokens);
        if (!r->toks) goto fail;
        if (extra_to_array(extra_obj, &r->extra, &r->n_extra) < 0) goto fail;
        r->n_blocks = r->n_tokens / block_size;
        r->out = (uint64_t *)PyMem_Malloc(
            r->n_blocks ? r->n_blocks * sizeof(uint64_t) : 1);
        if (!r->out) {
            PyErr_NoMemory();
            goto fail;
        }
        size_t need = 20 + 9 * (size_t)block_size + 9 * (size_t)(r->n_extra + 1);
        if (need > buf_cap) buf_cap = need;
    }
    Py_DECREF(seq);
    seq = NULL;

    uint8_t *buf = (uint8_t *)PyMem_Malloc(buf_cap);
    if (!buf) {
        _bp_free(reqs, n_reqs, NULL);
        return PyErr_NoMemory();
    }

    /* Phase 2: every chain in the batch, one GIL release. */
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n_reqs; i++) {
        struct _bp_req *r = &reqs[i];
        uint64_t h = r->parent;
        for (Py_ssize_t b = 0; b < r->n_blocks; b++) {
            h = hash_block(buf, h, r->toks + b * r->block_size,
                           r->block_size, r->extra, r->n_extra);
            r->out[b] = h;
        }
    }
    Py_END_ALLOW_THREADS

    /* Phase 3 (GIL held): box the results. */
    PyObject *result = PyList_New(n_reqs);
    if (result) {
        for (Py_ssize_t i = 0; i < n_reqs; i++) {
            struct _bp_req *r = &reqs[i];
            PyObject *inner = PyList_New(r->n_blocks);
            if (!inner) {
                Py_CLEAR(result);
                break;
            }
            for (Py_ssize_t b = 0; b < r->n_blocks; b++) {
                PyObject *val = PyLong_FromUnsignedLongLong(r->out[b]);
                if (!val) {
                    Py_DECREF(inner);
                    Py_CLEAR(result);
                    break;
                }
                PyList_SET_ITEM(inner, b, val);
            }
            if (!result) break;
            PyList_SET_ITEM(result, i, inner);
        }
    }
    _bp_free(reqs, n_reqs, buf);
    return result;

fail:
    /* Every entry was zeroed up front and fields are assigned as they are
     * allocated, so freeing the whole array is safe mid-conversion. */
    if (seq) Py_DECREF(seq);
    _bp_free(reqs, n_reqs, NULL);
    return NULL;
}

/* chunk_hash(parent, tokens, extra=None) -> int
 * Single chain link over the WHOLE token sequence (no chunking) -- the
 * native twin of hashing.chunk_hash and the differential-fuzz anchor for
 * the batch path. */
static PyObject *chunk_hash_py(PyObject *self, PyObject *args) {
    unsigned long long parent;
    PyObject *tokens_obj;
    PyObject *extra_obj = Py_None;
    if (!PyArg_ParseTuple(args, "KO|O", &parent, &tokens_obj, &extra_obj))
        return NULL;
    Py_ssize_t n_tokens = 0, n_extra = 0;
    uint64_t *toks = tokens_to_array(tokens_obj, &n_tokens);
    if (!toks) return NULL;
    uint64_t *extra = NULL;
    if (extra_to_array(extra_obj, &extra, &n_extra) < 0) {
        PyMem_Free(toks);
        return NULL;
    }
    size_t buf_cap = 20 + 9 * (size_t)n_tokens + 9 * (size_t)(n_extra + 1);
    uint8_t *buf = (uint8_t *)PyMem_Malloc(buf_cap);
    if (!buf) {
        PyMem_Free(toks);
        PyMem_Free(extra);
        return PyErr_NoMemory();
    }
    uint64_t h = hash_block(buf, (uint64_t)parent, toks, n_tokens,
                            extra, n_extra);
    PyMem_Free(toks);
    PyMem_Free(extra);
    PyMem_Free(buf);
    return PyLong_FromUnsignedLongLong(h);
}

/* token_fingerprints(fp0, tokens, seg_tokens) -> list[int]
 * Chain-memo fingerprints: fold fp = (fp ^ token) * FNV64_PRIME per token,
 * emitting the running fingerprint after every full segment of `seg_tokens`
 * tokens (trailing partial segment dropped). MUST stay bit-identical to
 * hashing.token_fingerprints (the pure-Python reference). */
static PyObject *token_fingerprints(PyObject *self, PyObject *args) {
    unsigned long long fp0;
    PyObject *tokens_obj;
    Py_ssize_t seg_tokens;
    if (!PyArg_ParseTuple(args, "KOn", &fp0, &tokens_obj, &seg_tokens))
        return NULL;
    if (seg_tokens <= 0) {
        PyErr_SetString(PyExc_ValueError, "seg_tokens must be positive");
        return NULL;
    }
    Py_ssize_t n_tokens = 0;
    uint64_t *toks = tokens_to_array(tokens_obj, &n_tokens);
    if (!toks) return NULL;
    Py_ssize_t n_segs = n_tokens / seg_tokens;
    uint64_t *out = (uint64_t *)PyMem_Malloc(
        n_segs ? n_segs * sizeof(uint64_t) : 1);
    if (!out) {
        PyMem_Free(toks);
        return PyErr_NoMemory();
    }
    uint64_t h = (uint64_t)fp0;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t s = 0; s < n_segs; s++) {
        const uint64_t *seg = toks + s * seg_tokens;
        for (Py_ssize_t i = 0; i < seg_tokens; i++)
            h = (h ^ seg[i]) * FNV64_PRIME;
        out[s] = h;
    }
    Py_END_ALLOW_THREADS
    PyObject *result = PyList_New(n_segs);
    if (result) {
        for (Py_ssize_t s = 0; s < n_segs; s++) {
            PyObject *val = PyLong_FromUnsignedLongLong(out[s]);
            if (!val) {
                Py_CLEAR(result);
                break;
            }
            PyList_SET_ITEM(result, s, val);
        }
    }
    PyMem_Free(toks);
    PyMem_Free(out);
    return result;
}

/* fnv64a(data: bytes, h: int = offset) -> int */
static PyObject *fnv64a_py(PyObject *self, PyObject *args) {
    Py_buffer view;
    unsigned long long h = FNV64_OFFSET;
    if (!PyArg_ParseTuple(args, "y*|K", &view, &h)) return NULL;
    uint64_t out = fnv1a64((const uint8_t *)view.buf, (size_t)view.len, h);
    PyBuffer_Release(&view);
    return PyLong_FromUnsignedLongLong(out);
}

static PyMethodDef methods[] = {
    {"prefix_hashes", prefix_hashes, METH_VARARGS,
     "Chained CBOR+FNV-64a block hashes over full token blocks (legacy: "
     "extra=None, pre-converted int tokens)."},
    {"batch_prefix_hashes", batch_prefix_hashes, METH_VARARGS,
     "Whole-request chained CBOR+FNV-64a block hashes in one crossing: "
     "extra-key (LoRA) support, __index__ token conversion, GIL released."},
    {"batch_prefix_hashes_many", batch_prefix_hashes_many, METH_VARARGS,
     "Whole-BATCH chained derivation: a sequence of (parent, tokens, "
     "block_size, extra|None) requests hashed in one crossing, GIL "
     "released across every chain."},
    {"chunk_hash", chunk_hash_py, METH_VARARGS,
     "Single CBOR+FNV-64a chain link over the whole token sequence."},
    {"token_fingerprints", token_fingerprints, METH_VARARGS,
     "Chain-memo segment fingerprints: per-token 64-bit FNV fold."},
    {"fnv64a", fnv64a_py, METH_VARARGS, "FNV-64a of a bytes-like object."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_kvtpu_native",
    "Native hash core (chained CBOR+FNV-64a).", -1, methods,
};

PyMODINIT_FUNC PyInit__kvtpu_native(void) { return PyModule_Create(&module); }

"""Flash prefill kernel parity vs the jnp oracle (_dense_attention).

The kernel must be bit-compatible in semantics with the path it replaces:
causal masking with per-batch offsets (cached-prefix prefill), sliding
windows, GQA/MQA grouping, and non-block-multiple shapes (padding).
Interpret mode runs the real kernel logic on CPU; the chip run validates
performance before the dispatch gate opens (models/llama.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llm_d_kv_cache_manager_tpu.models.llama import _dense_attention
from llm_d_kv_cache_manager_tpu.ops.flash_prefill import flash_prefill


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


def _case(b, l, s, n_q, n_kv, hd, offset, window=None, dtype=jnp.float32,
          block_q=32, block_k=128):
    q = _rand((b, l, n_q, hd), 0, dtype)
    k = _rand((b, s, n_kv, hd), 1, dtype)
    v = _rand((b, s, n_kv, hd), 2, dtype)
    want = _dense_attention(q, k, v, offset, window=window)
    got = flash_prefill(q, k, v, offset, window=window,
                        block_q=block_q, block_k=block_k, interpret=True)
    return np.asarray(want), np.asarray(got)


class TestFlashPrefillParity:
    def test_causal_from_scratch(self):
        want, got = _case(1, 96, 96, 4, 2, 64, 0)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_cached_prefix_offset(self):
        # Serving prefill: 64 new tokens attending a 32-token cached prefix.
        want, got = _case(1, 64, 96, 4, 2, 64, 32)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_per_batch_offsets(self):
        # Batched verify: each row has its own causal offset.
        offs = jnp.asarray([5, 17], jnp.int32)
        want, got = _case(2, 48, 80, 4, 2, 64, offs)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_sliding_window(self):
        want, got = _case(1, 96, 96, 4, 2, 64, 0, window=40)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_sliding_window_with_offset(self):
        want, got = _case(1, 64, 128, 4, 2, 64, 64, window=48)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_mqa_and_wide_gqa(self):
        want, got = _case(1, 64, 64, 4, 1, 64, 0)  # MQA
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        want, got = _case(1, 64, 64, 8, 2, 64, 0)  # group 4
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_non_block_multiple_shapes_pad(self):
        # L=90, S=150: both axes pad up to block multiples; the mask must
        # keep padded keys out and the host slice drops padded queries.
        want, got = _case(1, 90, 150, 4, 2, 64, 60)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_single_block(self):
        want, got = _case(1, 16, 16, 2, 2, 64, 0, block_q=16, block_k=128)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_bf16_matches_to_bf16_tolerance(self):
        want, got = _case(1, 96, 96, 4, 2, 64, 0, dtype=jnp.bfloat16)
        np.testing.assert_allclose(
            got.astype(np.float32), want.astype(np.float32),
            rtol=3e-2, atol=3e-2,
        )

    def test_rejects_bad_grouping(self):
        q = _rand((1, 32, 3, 64), 0)
        k = _rand((1, 32, 2, 64), 1)
        with pytest.raises(ValueError):
            flash_prefill(q, k, k, 0, interpret=True)

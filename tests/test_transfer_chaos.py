"""Chaos-hardened data plane: breaker/hedge/injector policy tests.

Everything in this file runs WITHOUT libkvtransfer.so: the breaker state
machine is pure policy, and the hedge/integrity/injector logic is driven
through `_ScriptedClient`, a TransferClient whose `_transport_fetch` seam
is scripted per peer (the same seam the chaos fault injector and the ASan
wire tests exercise with real bytes). The byte-moving counterparts live in
tests/test_transfer_wire_fuzz.py and test_kv_connectors.py (`transfer`/
`chaos`-marked, auto-skipped until `make kvtransfer`).
"""

import threading
import time

import pytest

from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    PeerBreaker,
    TransferClient,
    TransferClientConfig,
    _CORRUPT,
    _OVERSIZED,
)
from llm_d_kv_cache_manager_tpu.kv_connectors.faults import (
    FaultyTransport,
    PeerTransferFaults,
    TransferFaultPlan,
)


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- breaker state machine -----------------------------------------------------


class TestPeerBreaker:
    def test_opens_on_consecutive_failures_only(self):
        b = PeerBreaker(failure_threshold=3, cooldown_s=10.0)
        assert b.allow(0.0) == (True, None)
        assert b.record_failure(0.0) is None
        assert b.record_failure(0.1) is None
        # A success resets the consecutive count: no transition at 3 total.
        assert b.record_success(0.2) is None
        assert b.record_failure(0.3) is None
        assert b.record_failure(0.4) is None
        assert b.record_failure(0.5) == (BREAKER_CLOSED, BREAKER_OPEN)
        assert b.state == BREAKER_OPEN
        assert b.opens == 1

    def test_open_blocks_until_cooldown_then_single_probe(self):
        b = PeerBreaker(failure_threshold=1, cooldown_s=5.0)
        b.record_failure(0.0)
        assert b.state == BREAKER_OPEN
        assert b.allow(1.0) == (False, None)
        assert b.allow(4.999) == (False, None)
        allowed, transition = b.allow(5.0)
        assert allowed and transition == (BREAKER_OPEN, BREAKER_HALF_OPEN)
        # Half-open admits exactly ONE probe; others are refused until the
        # probe resolves.
        assert b.allow(5.1) == (False, None)
        assert b.allow(5.2) == (False, None)

    def test_probe_success_closes_probe_failure_reopens(self):
        b = PeerBreaker(failure_threshold=1, cooldown_s=5.0)
        b.record_failure(0.0)
        b.allow(5.0)  # the probe
        assert b.record_success(5.1) == (BREAKER_HALF_OPEN, BREAKER_CLOSED)
        assert b.state == BREAKER_CLOSED
        assert b.allow(5.2) == (True, None)

        b.record_failure(6.0)  # threshold 1: straight back open
        assert b.state == BREAKER_OPEN
        b.allow(11.0)  # half-open probe
        assert b.record_failure(11.1) == (BREAKER_HALF_OPEN, BREAKER_OPEN)
        # Fresh cooldown from the failed probe.
        assert b.allow(15.0) == (False, None)
        allowed, _t = b.allow(16.2)
        assert allowed

    def test_transitions_deterministic_under_injected_clock(self):
        """Same clock schedule -> same transition sequence, twice."""

        def run():
            b = PeerBreaker(failure_threshold=2, cooldown_s=3.0)
            log = []
            schedule = [
                ("fail", 0.0), ("fail", 0.5), ("allow", 1.0),
                ("allow", 3.6), ("fail", 3.7), ("allow", 6.8),
                ("ok", 6.9), ("allow", 7.0),
            ]
            for op, t in schedule:
                if op == "fail":
                    tr = b.record_failure(t)
                elif op == "ok":
                    tr = b.record_success(t)
                else:
                    _allowed, tr = b.allow(t)
                if tr is not None:
                    log.append((t, tr))
            return log, b.state, b.opens

        assert run() == run()

    def test_disabled_breaker_never_opens(self):
        b = PeerBreaker(failure_threshold=0, cooldown_s=1.0)
        for i in range(50):
            assert b.record_failure(float(i)) is None
        assert b.allow(100.0) == (True, None)
        assert b.state == BREAKER_CLOSED


# -- scripted client: breaker + integrity + hedging through the real paths ----


class _ScriptedClient(TransferClient):
    """TransferClient with a scripted `_transport_fetch`: per (host, port),
    a list of outcomes consumed one per call. Outcome forms:
      ("ok", [entries...])  — entries may be bytes/None/_CORRUPT/_OVERSIZED
      ("fail",)             — total transport failure
      ("slow", seconds, [entries...]) — sleeps (real time) then succeeds
    An exhausted script repeats its last outcome.
    """

    def __init__(self, script, **kwargs):
        super().__init__(**kwargs)
        self.script = {k: list(v) for k, v in script.items()}
        self.calls = []

    def _has_client_api(self):
        return True

    def _transport_fetch(self, host, port, hashes, max_size):
        self.calls.append((host, port, tuple(hashes)))
        outcomes = self.script[(host, port)]
        outcome = outcomes.pop(0) if len(outcomes) > 1 else outcomes[0]
        if outcome[0] == "slow":
            time.sleep(outcome[1])
            outcome = ("ok", outcome[2])
        if outcome[0] == "fail":
            return False, None
        entries = list(outcome[1])
        # Shape-flexible: scripts give a payload pool; the reply is
        # aligned with however many hashes the call asked for.
        while len(entries) < len(hashes):
            entries.append(entries[-1] if entries else None)
        return True, entries[: len(hashes)]


PEER_A = ("10.0.0.1", 9)
PEER_B = ("10.0.0.2", 9)


class TestClientBreakerIntegration:
    def test_consecutive_failures_open_then_skip_instantly(self):
        clock = _Clock()
        client = _ScriptedClient(
            {PEER_A: [("fail",)]},
            config=TransferClientConfig(
                breaker_failure_threshold=3, breaker_cooldown_s=10.0,
                retries=0,
            ),
            clock=clock,
        )
        for _ in range(3):
            assert client.fetch_many(*PEER_A, [1, 2], 64) == [None, None]
            clock.advance(0.1)
        assert client.peer_state(*PEER_A).breaker.state == BREAKER_OPEN
        calls_before = len(client.calls)
        # Open: the transport is never touched; blocks come back as
        # instant (counted) misses.
        assert client.fetch_many(*PEER_A, [3, 4, 5], 64) == [None] * 3
        assert len(client.calls) == calls_before
        assert client.stats["breaker_skipped_blocks"] == 3

    def test_half_open_probe_recovers_after_cooldown(self):
        clock = _Clock()
        client = _ScriptedClient(
            {PEER_A: [("fail",), ("ok", [b"x"])]},
            config=TransferClientConfig(
                breaker_failure_threshold=1, breaker_cooldown_s=5.0,
                retries=0,
            ),
            clock=clock,
        )
        transitions = []
        client.on_breaker_transition = (
            lambda peer, old, new: transitions.append((old, new))
        )
        assert client.fetch_many(*PEER_A, [1], 64) == [None]
        clock.advance(5.5)
        assert client.fetch_many(*PEER_A, [1], 64) == [b"x"]
        assert client.peer_state(*PEER_A).breaker.state == BREAKER_CLOSED
        assert (BREAKER_OPEN, BREAKER_HALF_OPEN) in transitions
        assert (BREAKER_HALF_OPEN, BREAKER_CLOSED) in transitions

    def test_corruption_counts_as_breaker_failure_and_never_lands(self):
        clock = _Clock()
        client = _ScriptedClient(
            {PEER_A: [("ok", [b"good", _CORRUPT])]},
            config=TransferClientConfig(
                breaker_failure_threshold=2, breaker_cooldown_s=5.0,
            ),
            clock=clock,
        )
        out = client.fetch_many(*PEER_A, [1, 2], 64)
        assert out == [b"good", None]  # corrupt block = a miss, never bytes
        assert client.stats["corrupt_blocks"] == 1
        breaker = client.peer_state(*PEER_A).breaker
        assert breaker.consecutive_failures == 1
        out = client.fetch_many(*PEER_A, [1, 2], 64)
        assert breaker.state == BREAKER_OPEN  # corruption opened it

    def test_oversized_blocks_drop_without_breaker_failure(self):
        client = _ScriptedClient(
            {PEER_A: [("ok", [_OVERSIZED, b"ok"])]},
            config=TransferClientConfig(breaker_failure_threshold=2),
            clock=_Clock(),
        )
        assert client.fetch_many(*PEER_A, [1, 2], 64) == [None, b"ok"]
        assert client.stats["oversized_blocks"] == 1
        assert client.peer_state(*PEER_A).breaker.consecutive_failures == 0

    def test_latency_ewma_tracks_successes_only(self):
        clock = _Clock()
        client = _ScriptedClient(
            {PEER_A: [("ok", [b"x"])]},
            config=TransferClientConfig(), clock=clock,
        )

        real = client._transport_fetch

        def timed(host, port, hashes, max_size):
            clock.advance(0.010)  # the fetch "takes" 10ms of clock
            return real(host, port, hashes, max_size)

        client._transport_fetch = timed
        for _ in range(5):
            client.fetch_many(*PEER_A, [1], 64)
        peer = client.peer_state(*PEER_A)
        assert peer.lat_n == 5
        assert peer.lat_ewma == pytest.approx(0.010)
        # Hedge delay floors at the config floor but tracks the profile.
        assert client.hedge_delay_s(*PEER_A) >= 0.010


class TestHedgedFetch:
    def test_primary_complete_wins_no_hedge(self):
        client = _ScriptedClient(
            {PEER_A: [("ok", [b"a1", b"a2"])], PEER_B: [("ok", [b"b1", b"b2"])]},
            config=TransferClientConfig(), clock=_Clock(),
        )
        out = client.fetch_many_hedged([PEER_A, PEER_B], [1, 2], 64)
        assert out == [b"a1", b"a2"]
        assert client.stats["hedges"] == 0
        # The backup was never fetched.
        assert all(call[0] == PEER_A[0] for call in client.calls)

    def test_slow_primary_loses_to_hedge_and_loser_is_discarded(self):
        client = _ScriptedClient(
            {
                PEER_A: [("slow", 0.25, [b"a1", b"a2"])],
                PEER_B: [("ok", [b"b1", b"b2"])],
            },
            config=TransferClientConfig(
                hedge_delay_floor_s=0.02, hedge_delay_cap_s=0.02
            ),
        )
        out = client.fetch_many_hedged([PEER_A, PEER_B], [1, 2], 64)
        assert out == [b"b1", b"b2"]  # first valid reply wins
        assert client.stats["hedges"] == 1
        assert client.stats["hedge_wins"] == 1
        # The loser's reply arrives later and is dropped on the floor —
        # never merged, never double-landed.
        time.sleep(0.3)
        assert out == [b"b1", b"b2"]

    def test_failed_primary_falls_back_without_waiting_for_timer(self):
        client = _ScriptedClient(
            {PEER_A: [("fail",)], PEER_B: [("ok", [b"b"])]},
            config=TransferClientConfig(
                retries=0, hedge_delay_floor_s=5.0, hedge_delay_cap_s=5.0
            ),
        )
        t0 = time.monotonic()
        out = client.fetch_many_hedged([PEER_A, PEER_B], [7], 64)
        assert out == [b"b"]
        # The primary ANSWERED (with a failure) — the hedge fires on the
        # reply, not on the 5s timer.
        assert time.monotonic() - t0 < 2.0
        assert client.stats["hedges"] == 1

    def test_all_holders_fail_returns_most_covered(self):
        client = _ScriptedClient(
            {
                PEER_A: [("ok", [b"a", None, None])],
                PEER_B: [("ok", [b"b1", b"b2", None])],
            },
            config=TransferClientConfig(
                hedge_delay_floor_s=0.01, hedge_delay_cap_s=0.01
            ),
        )
        out = client.fetch_many_hedged([PEER_A, PEER_B], [1, 2, 3], 64)
        assert out == [b"b1", b"b2", None]  # most blocks covered wins

    def test_single_holder_is_a_plain_fetch(self):
        client = _ScriptedClient(
            {PEER_A: [("ok", [b"x"])]}, config=TransferClientConfig(),
            clock=_Clock(),
        )
        assert client.fetch_many_hedged([PEER_A], [1], 64) == [b"x"]
        assert client.stats["hedges"] == 0

    def test_result_always_aligned_with_request(self):
        """Property: whatever the script does, the hedged result has
        exactly one slot per requested hash (never doubled, never
        truncated)."""
        import random

        rng = random.Random(7)
        for trial in range(20):
            n = rng.randint(1, 6)

            def entries():
                return [
                    rng.choice([b"p", None, _CORRUPT]) for _ in range(n)
                ]

            client = _ScriptedClient(
                {
                    PEER_A: [rng.choice([("fail",), ("ok", entries())])],
                    PEER_B: [rng.choice([("fail",), ("ok", entries())])],
                },
                config=TransferClientConfig(
                    retries=0, hedge_delay_floor_s=0.001,
                    hedge_delay_cap_s=0.001,
                ),
            )
            out = client.fetch_many_hedged(
                [PEER_A, PEER_B], list(range(n)), 64
            )
            assert len(out) == n
            assert all(p is None or isinstance(p, bytes) for p in out)


# -- fault injector ------------------------------------------------------------


def _scripted_ok(payloads):
    return {PEER_A: [("ok", payloads)], PEER_B: [("ok", payloads)]}


class TestFaultyTransport:
    def _make(self, faults, verify=True, clock=None, script=None,
              breaker_threshold=3):
        clock = clock or _Clock()
        inner = _ScriptedClient(
            script or _scripted_ok([b"x1", b"x2", b"x3", b"x4"]),
            config=TransferClientConfig(
                retries=0, io_timeout_ms=1000, connect_timeout_ms=500,
                breaker_failure_threshold=breaker_threshold,
                breaker_cooldown_s=5.0,
            ),
            clock=clock,
        )
        plan = TransferFaultPlan(seed=11, peers={PEER_A: faults})
        return FaultyTransport(
            inner, plan, clock=clock, verify_integrity=verify
        ), clock

    def test_corruption_detected_with_integrity_on(self):
        ft, _clock = self._make(PeerTransferFaults(corrupt_rate=1.0))
        out = ft.fetch_many(*PEER_A, [1, 2, 3, 4], 64)
        assert out == [None] * 4  # every corrupt block degraded to a miss
        assert ft.counters["corrupt_injected"] == 4
        assert ft.counters["corrupt_detected"] == 4
        assert ft.counters["corrupt_admitted"] == 0
        assert ft.inner.stats["corrupt_blocks"] == 4

    def test_corruption_admitted_with_integrity_off(self):
        """The v1-wire control: damage sails through — the failure mode
        the checksum kills."""
        ft, _clock = self._make(
            PeerTransferFaults(corrupt_rate=1.0), verify=False
        )
        out = ft.fetch_many(*PEER_A, [1, 2, 3, 4], 64)
        assert out == [b"x1", b"x2", b"x3", b"x4"]  # wrong bytes, landed
        assert ft.counters["corrupt_admitted"] == 4
        assert ft.counters["corrupt_detected"] == 0

    def test_unfaulted_peer_passes_through_untouched(self):
        ft, _clock = self._make(PeerTransferFaults(corrupt_rate=1.0))
        assert ft.fetch_many(*PEER_B, [1, 2, 3, 4], 64) == [
            b"x1", b"x2", b"x3", b"x4"
        ]
        assert ft.counters["corrupt_injected"] == 0

    def test_stall_charges_timeout_ladder_and_feeds_breaker(self):
        ft, clock = self._make(
            PeerTransferFaults(stall_from_s=1.0, stall_until_s=9.0),
            breaker_threshold=0,  # disabled: every fetch pays the ladder
        )
        clock.t = 0.5
        assert ft.fetch_many(*PEER_A, [1], 64)[0] is not None  # pre-window
        clock.t = 2.0
        for _ in range(3):
            assert ft.fetch_many(*PEER_A, [1, 2], 64) == [None, None]
        assert ft.counters["stalled_fetches"] == 3
        # retries=0, io_timeout 1000ms -> 1.0s charged per stalled fetch.
        assert ft.take_charge() == pytest.approx(3.0)
        assert ft.take_charge() == 0.0  # drained

    def test_breaker_caps_the_stall_cost(self):
        ft, clock = self._make(
            PeerTransferFaults(stall_from_s=0.0, stall_until_s=100.0),
            breaker_threshold=3,
        )
        for i in range(10):
            clock.t = float(i) * 0.1
            ft.fetch_many(*PEER_A, [1], 64)
        # 3 ladders to open, then instant skips.
        assert ft.counters["stalled_fetches"] == 3
        assert ft.counters["breaker_skipped_fetches"] == 7
        assert ft.take_charge() == pytest.approx(3.0)

    def test_flap_windows_and_recovery(self):
        ft, clock = self._make(
            PeerTransferFaults(
                flap_from_s=0.0, flap_period_s=10.0, flap_down_frac=0.5
            ),
            breaker_threshold=0,
        )
        clock.t = 2.0  # down phase
        assert ft.fetch_many(*PEER_A, [1], 64) == [None]
        clock.t = 7.0  # up phase
        assert ft.fetch_many(*PEER_A, [1], 64)[0] is not None
        clock.t = 12.0  # down again
        assert ft.fetch_many(*PEER_A, [1], 64) == [None]

    def test_blackhole_charges_connect_ladder(self):
        ft, clock = self._make(
            PeerTransferFaults(blackhole_from_s=0.0),
            breaker_threshold=0,
        )
        ft.fetch_many(*PEER_A, [1], 64)
        assert ft.counters["blackholed_fetches"] == 1
        assert ft.take_charge() == pytest.approx(0.5)  # connect 500ms

    def test_seeded_corruption_is_deterministic(self):
        def run():
            ft, _clock = self._make(PeerTransferFaults(corrupt_rate=0.5))
            outcomes = []
            for i in range(20):
                outcomes.append(
                    tuple(
                        p is None
                        for p in ft.fetch_many(*PEER_A, [1, 2, 3, 4], 64)
                    )
                )
            return outcomes, dict(ft.counters)

        assert run() == run()

    def test_self_addr_is_exempt(self):
        clock = _Clock()
        inner = _ScriptedClient(
            _scripted_ok([b"x"]),
            config=TransferClientConfig(retries=0), clock=clock,
        )
        plan = TransferFaultPlan(
            seed=1, peers={PEER_A: PeerTransferFaults(stall_from_s=0.0)}
        )
        ft = FaultyTransport(inner, plan, clock=clock, self_addr=PEER_A)
        # Loopback restores bypass the peer's fault windows: a stalled NIC
        # doesn't break a pod's fetches from its own host store.
        assert ft.fetch_many(*PEER_A, [1], 64) == [b"x"]


# -- fleethealth feed ----------------------------------------------------------


def test_tracker_records_transfer_breaker_transitions():
    from llm_d_kv_cache_manager_tpu.fleethealth import (
        FleetHealthConfig,
        FleetHealthTracker,
    )

    clock = _Clock()
    tracker = FleetHealthTracker(FleetHealthConfig(), clock=clock)
    tracker.observe_transfer_breaker("10.0.0.1:9", "closed", "open")
    clock.advance(3.0)
    tracker.observe_transfer_breaker("10.0.0.1:9", "open", "half_open")
    tracker.observe_transfer_breaker("10.0.0.1:9", "half_open", "closed")
    summary = tracker.summary(now=clock())
    rec = summary["transfer_breakers"]["10.0.0.1:9"]
    assert rec["state"] == "closed"
    assert rec["transitions"] == 3
    assert rec["opens"] == 1
    # And through the client callback end-to-end.
    client = _ScriptedClient(
        {PEER_A: [("fail",)]},
        config=TransferClientConfig(
            breaker_failure_threshold=1, retries=0
        ),
        clock=clock,
        on_breaker_transition=tracker.observe_transfer_breaker,
    )
    client.fetch_many(*PEER_A, [1], 64)
    assert (
        tracker.transfer_breaker_summary()[f"{PEER_A[0]}:{PEER_A[1]}"]["state"]
        == "open"
    )


# -- status surfaces -----------------------------------------------------------


def test_client_status_reports_peers_and_counters():
    clock = _Clock()
    client = _ScriptedClient(
        {PEER_A: [("ok", [b"x", _CORRUPT])], PEER_B: [("fail",)]},
        config=TransferClientConfig(
            breaker_failure_threshold=2, retries=0
        ),
        clock=clock,
    )
    client.fetch_many(*PEER_A, [1, 2], 64)
    client.fetch_many(*PEER_B, [3], 64)
    status = client.status()
    assert status["verify_integrity"] in (True, False)
    a = status["peers"]["10.0.0.1:9"]
    b = status["peers"]["10.0.0.2:9"]
    assert a["corrupt_blocks"] == 1 and a["consecutive_failures"] == 1
    assert b["failures"] == 1
    assert status["stats"]["corrupt_blocks"] == 1
    assert status["breaker"]["failure_threshold"] == 2


def test_faulty_transport_status_embeds_injector_counters():
    clock = _Clock()
    inner = _ScriptedClient(
        _scripted_ok([b"x"]), config=TransferClientConfig(), clock=clock
    )
    ft = FaultyTransport(
        inner,
        TransferFaultPlan(
            seed=1, peers={PEER_A: PeerTransferFaults(corrupt_rate=1.0)}
        ),
        clock=clock,
    )
    ft.fetch_many(*PEER_A, [1], 64)
    status = ft.status()
    assert status["injected_faults"]["corrupt_detected"] == 1
    assert "peers" in status

"""The device-measured mini-fleet bench (VERDICT r2 #3) must stay runnable
and its committed artifact physically coherent.

The full mode runs on the TPU chip; CI runs the --quick CPU path through
the identical code (real pods, real routing, real events — only sizes
shrink) and checks the artifact the full run committed.
"""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
BENCH = REPO / "benchmarking" / "fleet_device_bench.py"
ARTIFACT = REPO / "benchmarking" / "FLEET_DEVICE_BENCH.json"


def test_quick_mode_runs_the_full_stack():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, str(BENCH), "--quick"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    # Last printed block is the JSON report.
    report = json.loads(out.stdout[out.stdout.index("{"):])
    for arm in ("precise", "random", "round_robin"):
        assert report[arm]["requests"] > 0
        assert 0 <= report[arm]["prefix_hit_rate"] <= 1
        assert report[arm]["ttft_p50_s"] > 0
    # Precise routing must actually concentrate prefixes.
    assert (
        report["precise"]["prefix_hit_rate"]
        > report["round_robin"]["prefix_hit_rate"]
    )


def _load_fdb():
    import importlib.util

    spec = importlib.util.spec_from_file_location("fdb_mod", BENCH)
    fdb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fdb)
    return fdb


def test_open_loop_replay_clock_math():
    """The v3 open-loop replay must turn measured service times into queue
    waits correctly — the chip session is one-shot, so the virtual-clock
    arithmetic is pinned here with a fake fleet (fixed 0.1s service,
    0.04s compute-TTFT, one pod)."""
    fdb = _load_fdb()

    class _FakeFleet:
        def __init__(self, strategy, n_pods, *a, **k):
            self.hit_tokens = 0
            self.total_tokens = 1

        def serve(self, prompt, max_new):
            return 0.04, 0.1, 1, 0
        def close(self):
            pass

    real = fdb.DeviceFleet
    fdb.DeviceFleet = _FakeFleet
    try:
        workload = ({"c": "hello world"}, [("c", 0)] * 50, 7, 3)
        # Saturating rate: arrivals ~2.5x faster than the single pod's
        # 0.1s service, so waits must grow roughly linearly.
        sat = fdb.run_fleet("round_robin", None, workload, 1, 8, 1, 1,
                            False, qps=25.0)
        assert sat["ttft_compute_p50_s"] == 0.04
        assert sat["service_p50_s"] == 0.1
        # With ~0.06s of new backlog per request, the median request has
        # waited on the order of a second; far above the compute TTFT.
        assert sat["queue_wait_p50_s"] > 0.5
        assert abs(
            sat["ttft_p50_s"] - (sat["queue_wait_p50_s"] + 0.04)
        ) < 0.05
        # Idle rate: arrivals ~25x slower than service — no queueing, so
        # measured TTFT must equal the compute TTFT.
        idle = fdb.run_fleet("round_robin", None, workload, 1, 8, 1, 1,
                             False, qps=0.4)
        assert idle["queue_wait_p90_s"] == 0.0
        assert idle["ttft_p50_s"] == 0.04
        # Closed-loop fallback unchanged.
        closed = fdb.run_fleet("round_robin", None, workload, 1, 8, 1, 1,
                               False, qps=None)
        assert closed["ttft_p50_s"] == 0.04
        assert "queue_wait_p50_s" not in closed
    finally:
        fdb.DeviceFleet = real


def test_committed_artifact_is_coherent():
    if not ARTIFACT.exists():
        import pytest

        pytest.skip("full-mode artifact not committed on this checkout")
    d = json.loads(ARTIFACT.read_text())
    assert d["backend"] == "tpu", "artifact must come from a real-chip run"
    # The artifact must have been produced by the CURRENT full-mode config —
    # otherwise the README republishes numbers this code can't reproduce.
    fdb = _load_fdb()
    # The artifact pins the configuration that produced it; that config
    # must be one this code still ships, field for field — a sys_words or
    # turns drift changes hit rates without touching the pod shape.
    recorded = d["config"].get("full_mode")
    version = d["config"].get("full_mode_version", "v1")
    assert version in fdb.FULL_MODES, f"unknown full-mode version {version}"
    fm = fdb.FULL_MODES[version]
    assert recorded == fm
    assert d["config"]["n_pods"] == fm["n_pods"]
    assert d["config"]["n_pages_per_pod"] == fm["n_pages"]
    assert d["config"]["decode_steps"] == fm["decode_steps"]
    assert d["config"]["max_new_tokens"] == fm["max_new"]
    if version != "v1":
        # The current default scale (VERDICT r3 #2): >=200 requests per
        # measured arm, and the random arm present (ADVICE r3 — the
        # README renders it; an artifact without it silently drops an arm
        # the bench measures).
        assert "random" in d, "artifact missing the random arm"
        assert d["random"]["requests"] == d["precise"]["requests"]
        assert d["precise"]["requests"] >= 200
    assert d["precise"]["prefix_hit_rate"] > d["round_robin"]["prefix_hit_rate"]
    assert d["ttft_p50_speedup"] >= 1.0
    expected = round(
        d["round_robin"]["ttft_p50_s"] / d["precise"]["ttft_p50_s"], 3
    )
    assert abs(d["ttft_p50_speedup"] - expected) < 0.005

"""Transfer-vs-recompute gate (engine/costs.py) + async prefetch.

Round-3 measurement this subsystem answers: blind onboarding under
cache-oblivious routing was 4x WORSE than recompute (BENCH_r03 two_tier
rr_data_plane_speedup 0.252) because the data plane had no cost gate. The
gate's economics are pinned here on both rigs' regimes: the tunneled
bench rig (transfers lose for the benched 1.1B dense model → refuse) and
the winning regime (wide MQA + int8 KV: few KV bytes per token of compute
→ admit).
"""

import threading

import pytest

from llm_d_kv_cache_manager_tpu.engine import costs
from llm_d_kv_cache_manager_tpu.engine.costs import (
    ALWAYS_TRANSFER,
    NEVER_TRANSFER,
    PEER,
    READY,
    STAGED,
    TransferCostModel,
)
from llm_d_kv_cache_manager_tpu.engine.tiering import (
    NullPageCodec,
    TieredKVStore,
)


class TestEstimators:
    def test_flops_per_token_tracks_param_count(self):
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        c = LlamaConfig(vocab_size=1024, d_model=256, n_layers=4,
                        n_q_heads=8, n_kv_heads=4, head_dim=32, d_ff=512)
        # ~2 flops per matmul parameter: attn + gated MLP. No LM-head term:
        # prefix-block recompute never produces logits (ADVICE r4), and
        # pricing it in would bias the gate toward admitting transfers.
        attn = 256 * 8 * 32 + 2 * 256 * 4 * 32 + 8 * 32 * 256
        mlp = 3 * 256 * 512
        assert costs.flops_per_token(c) == 2.0 * 4 * (attn + mlp)

    def test_moe_counts_only_active_experts(self):
        from llm_d_kv_cache_manager_tpu.models.mixtral import MixtralConfig

        c = MixtralConfig(vocab_size=256, d_model=64, n_layers=2,
                          n_q_heads=4, n_kv_heads=2, head_dim=16, d_ff=128,
                          n_experts=8, top_k=2)
        dense_like = costs.flops_per_token(c)
        # top_k=2 of 8 experts: the MLP term must scale by 2, not 8.
        mlp_all = 2 * 2 * 8 * 3 * 64 * 128
        mlp_active = 2 * 2 * 2 * 3 * 64 * 128
        assert dense_like < mlp_all
        assert dense_like > mlp_active

    def test_kv_bytes_quantized_smaller(self):
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        c = LlamaConfig(vocab_size=256, d_model=128, n_layers=2,
                        n_q_heads=4, n_kv_heads=2, head_dim=64, d_ff=256)
        bf16 = costs.kv_bytes_per_token(c)
        assert bf16 == 2 * 2 * 2 * 64 * 2  # 2(kv) x L x Hkv x hd x 2B
        int8 = costs.kv_bytes_per_token(c, quantized=True)
        assert int8 == 2 * 2 * 2 * (64 + 4)
        assert int8 < bf16


class TestAdmitPrefix:
    def test_cheap_chain_fully_admitted(self):
        m = TransferCostModel(recompute_s=1e-4, staged_restore_s=1e-5,
                              onboard_s=2e-5, insert_s=1e-5)
        assert m.admit_prefix([STAGED, PEER, STAGED], 16) == 3

    def test_expensive_chain_refused(self):
        m = TransferCostModel(recompute_s=1e-5, staged_restore_s=1e-4,
                              onboard_s=2e-4, insert_s=1e-4)
        assert m.admit_prefix([STAGED, STAGED], 16) == 0

    def test_expensive_block_amortized_by_cheap_tail(self):
        # One peer block at 3x recompute followed by three free ready
        # blocks: cumulative cost 3 <= cumulative savings 4 at k=4, so the
        # whole chain is admitted even though block 1 alone is refused.
        m = TransferCostModel(recompute_s=1.0, staged_restore_s=1.0,
                              onboard_s=3.0, insert_s=0.0)
        assert m.admit_prefix([PEER], 1) == 0
        assert m.admit_prefix([PEER, READY, READY, READY], 1) == 4

    def test_margin_loosens_the_gate(self):
        m = TransferCostModel(recompute_s=1.0, staged_restore_s=1.5,
                              onboard_s=1.5, insert_s=1.5)
        assert m.admit_prefix([STAGED], 4) == 0
        assert m.with_margin(2.0).admit_prefix([STAGED], 4) == 1

    def test_sentinels(self):
        assert ALWAYS_TRANSFER.admit_prefix([PEER] * 5, 16) == 5
        assert NEVER_TRANSFER.admit_prefix([READY], 16) == 0


class TestMeasuredSeeding:
    def test_measured_rates_parse_committed_artifact(self):
        rates = costs.measured_rates()
        assert rates is not None, "benchmarking/DEVICE_BENCH.json missing?"
        assert rates["source"].startswith("measured")
        for key in ("staged_bytes_per_s", "peer_bytes_per_s",
                    "insert_bytes_per_s", "compute_flops_per_s"):
            assert rates[key] > 0

    def test_benched_dense_model_refuses_transfer_on_tunneled_rig(self):
        """The round-3 regression, now a pinned decision: for the benched
        1.1B dense model the tunneled rig's measured transfer rates lose
        to recompute, so the gate must refuse."""
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        rates = costs.measured_rates()
        if rates is None:
            pytest.skip("no device bench artifact")
        bench_cfg = LlamaConfig(
            vocab_size=32768, d_model=2048, n_layers=16, n_q_heads=16,
            n_kv_heads=8, head_dim=128, d_ff=8192,
        )
        gate = TransferCostModel.for_model(bench_cfg, rates=rates)
        assert gate.admit_prefix([STAGED] * 8, 64) == 0
        assert gate.admit_prefix([PEER] * 8, 64) == 0

    def test_wide_mqa_int8_model_admits_transfer(self):
        """The winning regime: high arithmetic intensity per KV byte.
        A wide MQA model with int8 KV moves ~1.3KB/token against ~7GF of
        recompute — transfer wins even at the tunneled rig's rates."""
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        rates = costs.measured_rates()
        if rates is None:
            pytest.skip("no device bench artifact")
        wide = LlamaConfig(
            vocab_size=32768, d_model=8192, n_layers=4, n_q_heads=64,
            n_kv_heads=1, head_dim=128, d_ff=28672,
        )
        gate = TransferCostModel.for_model(wide, quantized=True, rates=rates)
        assert gate.admit_prefix([STAGED] * 8, 64) == 8

    def test_assumed_rates_used_without_artifact(self, tmp_path):
        assert costs.measured_rates(str(tmp_path / "nope.json")) is None
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        gate = TransferCostModel.for_model(
            LlamaConfig(), rates=costs.ASSUMED_RATES
        )
        assert gate.source.startswith("assumed")


class _FakeConnector:
    """Host store as a dict; 'peer' fetches recorded for assertions."""

    def __init__(self):
        self.store = {}
        self.dropped = []
        self.fetches = []

    def stage(self, block_hash, payload, token_ids, block_size,
              parent_hash=None, lora_id=None):
        self.store[block_hash] = payload

    def fetch_staged(self, block_hash, max_size):
        self.fetches.append(("staged", block_hash))
        return self.store.get(block_hash)

    def onboard_payload(self, host, port, block_hash, max_size):
        self.fetches.append(("peer", block_hash))
        return b""

    def drop(self, block_hash):
        self.dropped.append(block_hash)


def _store(cost_model=None, **kw):
    return TieredKVStore(
        _FakeConnector(), NullPageCodec(), cost_model=cost_model, **kw
    )


class TestGatedStore:
    def test_plan_restore_truncates_and_counts(self):
        store = _store(cost_model=NEVER_TRANSFER)
        store.export_blocks([(h, [1, 2], None, 0, None) for h in (10, 11)])
        assert store.plan_restore([10, 11]) == 0
        assert store.stats["gated_blocks"] == 2

    def test_ungated_store_admits_everything(self):
        store = _store(cost_model=None)
        store.export_blocks([(h, [1, 2], None, 0, None) for h in (10, 11)])
        assert store.plan_restore([10, 11]) == 2

    def test_prefetch_makes_blocks_ready_and_load_chain_consumes(self):
        store = _store(cost_model=ALWAYS_TRANSFER)
        store.export_blocks([(7, [1, 2], None, 0, None)])
        assert store.prefetch([7]) == 1
        deadline = threading.Event()
        for _ in range(100):
            if store.stats["prefetched"] == 1:
                break
            deadline.wait(0.02)
        assert store.stats["prefetched"] == 1
        store.connector.fetches.clear()
        landed = store.load_chain([(7, [1, 2], None)], lambda k: list(range(k)))
        assert landed == [0]
        assert store.stats["ready_hits"] == 1
        # The payload came from the ready buffer — no fetch on this path.
        assert store.connector.fetches == []
        store.close()

    def test_prefetch_gated_off_when_insert_loses(self):
        store = _store(cost_model=NEVER_TRANSFER)
        store.export_blocks([(7, [1, 2], None, 0, None)])
        assert store.prefetch([7]) == 0

    def test_prefetch_dedupes_inflight(self):
        store = _store(cost_model=None)
        store.export_blocks([(7, [1, 2], None, 0, None)])
        n1 = store.prefetch([7, 7])
        assert n1 == 1
        store.close()

    def test_prefetch_bounded_by_ready_cap_head_first(self):
        """Chains restore head-first: fetching past the ready-buffer cap
        would evict the head for a tail load_chain can't use yet."""
        store = _store(cost_model=None, prefetch_capacity_blocks=4)
        store.export_blocks(
            [(h, [1, 2], None, 0, None) for h in range(100, 140)]
        )
        queued = store.prefetch(list(range(100, 140)))
        assert queued == 4
        for _ in range(200):
            if store.stats["prefetched"] == 4:
                break
            threading.Event().wait(0.01)
        with store._mu:
            assert list(store._ready) == [100, 101, 102, 103]  # the head
        store.close()

    def test_load_chain_regates_when_ready_entry_evicted(self):
        """TOCTOU guard: a chain admitted at READY (insert-only) cost whose
        ready entry got evicted must NOT silently pay the staged/peer
        fetch the gate refuses — the round-3 regression path."""
        insert_wins_staged_loses = TransferCostModel(
            recompute_s=1.0, staged_restore_s=10.0, onboard_s=10.0,
            insert_s=0.0,
        )
        store = _store(cost_model=insert_wins_staged_loses)
        store.export_blocks([(7, [1, 2], None, 0, None)])
        assert store.prefetch([7]) == 1
        for _ in range(200):
            if store.stats["prefetched"] == 1:
                break
            threading.Event().wait(0.01)
        assert store.plan_restore([7]) == 1  # admitted at READY cost
        store.connector.fetches.clear()  # drop the prefetcher's own fetch
        with store._mu:  # simulate cap churn evicting the entry
            store._ready.clear()
        landed = store.load_chain([(7, [1, 2], None)], lambda k: list(range(k)))
        assert landed == []
        assert store.connector.fetches == []  # the refused fetch never ran
        store.close()


class TestEngineAutoGate:
    def test_model_pod_gets_model_seeded_gate(self):
        from llm_d_kv_cache_manager_tpu.engine.engine import (
            EnginePod,
            EnginePodConfig,
        )
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        pod = EnginePod(EnginePodConfig(
            n_pages=4, page_size=4, with_model=True,
            model_config=LlamaConfig(), enable_host_tier=True,
        ))
        try:
            assert pod.tier_store.cost_model is not None
            assert pod.tier_store.cost_model.recompute_s > 0
        finally:
            pod.close()

    def test_accounting_pod_is_ungated(self):
        from llm_d_kv_cache_manager_tpu.engine.engine import (
            EnginePod,
            EnginePodConfig,
        )

        pod = EnginePod(EnginePodConfig(
            n_pages=4, page_size=4, enable_host_tier=True,
        ))
        try:
            assert pod.tier_store.cost_model is None
        finally:
            pod.close()

    def test_scheduler_submit_prefetches(self):
        from llm_d_kv_cache_manager_tpu.engine.engine import (
            EnginePod,
            EnginePodConfig,
        )
        from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        pod = EnginePod(EnginePodConfig(
            n_pages=16, page_size=4, with_model=True,
            model_config=LlamaConfig(), enable_host_tier=True,
            transfer_cost_model=ALWAYS_TRANSFER,
        ))
        try:
            calls = []
            orig = pod.prefetch
            pod.prefetch = lambda toks, lora_id=None: calls.append(
                (list(toks), lora_id)
            ) or orig(toks, lora_id)
            sched = Scheduler(pod, max_batch=2)
            sched.submit(list(range(8)), max_new_tokens=1)
            assert calls and calls[0][0] == list(range(8))
        finally:
            pod.close()

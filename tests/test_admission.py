"""Admission control tests (api/admission.py + the serving surfaces).

The load-bearing pins:

- Overload is EXPLICIT and bounded: past max_concurrency +
  max_queue_depth a request is shed (429 / RESOURCE_EXHAUSTED with a
  retry-after hint), never parked in an unbounded queue, never silently
  dropped — and every shed is counted by kind.
- Deadline propagation: a caller whose budget is already exhausted is
  shed as `deadline` without any scoring work; the gRPC surfaces read
  the client deadline from context and return no-signal (counted)
  instead of computing an abandoned score.
"""

import asyncio
import socket
import threading
import time

import pytest

from llm_d_kv_cache_manager_tpu.api.admission import (
    SHED_DEADLINE,
    SHED_QUEUE_FULL,
    SHED_TIMEOUT,
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
)
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON

PROMPT = "the quick brown fox jumps over the lazy dog"


# -- controller unit ----------------------------------------------------------


class TestController:
    def test_fast_path_admits(self):
        c = AdmissionController(AdmissionConfig(max_concurrency=2))
        with c.admit():
            with c.admit():
                assert c.depth() == {"active": 2, "waiting": 0}
        assert c.depth() == {"active": 0, "waiting": 0}
        assert c.stats["admitted"] == 2
        assert c.stats["queued"] == 0

    def test_queue_full_sheds_immediately(self):
        c = AdmissionController(
            AdmissionConfig(max_concurrency=1, max_queue_depth=0)
        )
        with c.admit():
            with pytest.raises(AdmissionRejected) as err:
                c.try_acquire()
        assert err.value.kind == SHED_QUEUE_FULL
        assert c.stats["shed_queue_full"] == 1
        assert c.shed_total() == 1

    def test_wait_timeout_sheds_as_timeout(self):
        c = AdmissionController(AdmissionConfig(
            max_concurrency=1, max_queue_depth=4, max_wait_s=0.02
        ))
        with c.admit():
            t0 = time.monotonic()
            with pytest.raises(AdmissionRejected) as err:
                c.try_acquire()
            assert time.monotonic() - t0 < 1.0
        assert err.value.kind == SHED_TIMEOUT
        assert c.stats["queued"] == 1  # it did wait in the line

    def test_exhausted_budget_sheds_as_deadline_without_queueing(self):
        c = AdmissionController(AdmissionConfig(max_concurrency=1))
        with pytest.raises(AdmissionRejected) as err:
            c.try_acquire(budget_s=0.0)
        assert err.value.kind == SHED_DEADLINE
        assert c.stats["queued"] == 0  # never parked

    def test_budget_caps_the_wait_and_sheds_as_deadline(self):
        c = AdmissionController(AdmissionConfig(
            max_concurrency=1, max_queue_depth=4, max_wait_s=30.0
        ))
        with c.admit():
            t0 = time.monotonic()
            with pytest.raises(AdmissionRejected) as err:
                c.try_acquire(budget_s=0.02)
            assert time.monotonic() - t0 < 1.0
        assert err.value.kind == SHED_DEADLINE

    def test_release_admits_a_waiter(self):
        c = AdmissionController(AdmissionConfig(
            max_concurrency=1, max_queue_depth=4, max_wait_s=5.0
        ))
        c.try_acquire()
        admitted = threading.Event()

        def waiter():
            with c.admit():
                admitted.set()

        t = threading.Thread(target=waiter)
        t.start()
        try:
            # The waiter is parked, not shed.
            deadline = time.monotonic() + 2.0
            while c.depth()["waiting"] != 1:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            c.release()
            assert admitted.wait(timeout=2.0)
        finally:
            t.join(timeout=5.0)
        assert c.shed_total() == 0
        assert c.stats["queued"] == 1
        assert c.stats["admitted"] == 2

    def test_exception_inside_admit_releases_the_slot(self):
        c = AdmissionController(AdmissionConfig(max_concurrency=1))
        with pytest.raises(RuntimeError):
            with c.admit():
                raise RuntimeError("scoring blew up")
        assert c.depth() == {"active": 0, "waiting": 0}

    def test_sheds_are_counted_in_metrics(self):
        metrics.register_metrics()
        c = AdmissionController(
            AdmissionConfig(max_concurrency=1, max_queue_depth=0)
        )
        before = metrics.counter_value(metrics.admission_shed)
        with c.admit():
            with pytest.raises(AdmissionRejected):
                c.try_acquire()
        assert metrics.counter_value(metrics.admission_shed) == before + 1

    def test_retry_after_rides_the_exception(self):
        c = AdmissionController(AdmissionConfig(
            max_concurrency=1, max_queue_depth=0, retry_after_s=2.5
        ))
        with c.admit():
            with pytest.raises(AdmissionRejected) as err:
                c.try_acquire()
        assert err.value.retry_after_s == 2.5
        assert "2.5" in str(err.value)

    def test_config_validation(self):
        with pytest.raises(ValueError):
            AdmissionConfig(max_concurrency=0)
        with pytest.raises(ValueError):
            AdmissionConfig(max_queue_depth=-1)
        with pytest.raises(ValueError):
            AdmissionConfig(max_wait_s=0)
        with pytest.raises(ValueError):
            AdmissionConfig(retry_after_s=-1)

    def test_status_shape(self):
        c = AdmissionController()
        status = c.status()
        assert set(status) >= {
            "max_concurrency", "max_queue_depth", "max_wait_s",
            "retry_after_s", "depth", "stats",
        }


# -- HTTP surface -------------------------------------------------------------


def _make_indexer():
    from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
        Indexer,
        IndexerConfig,
    )
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
        TokenProcessorConfig,
    )
    from llm_d_kv_cache_manager_tpu.tokenization.pool import (
        TokenizationPool,
        TokenizersPoolConfig,
    )

    indexer = Indexer(
        config=IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
        ),
        tokenization_pool=TokenizationPool(
            TokenizersPoolConfig(
                workers=2,
                local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
            ),
        ),
    )
    indexer.run()
    return indexer


class TestHttpSurface:
    def _service(self, **admission_cfg):
        from llm_d_kv_cache_manager_tpu.api.http_service import (
            ScoringService,
        )

        env = {
            "zmq_endpoint": "tcp://*:0",
            "zmq_topic": "kv@",
            "pool_concurrency": 1,
            "hash_seed": "",
            "block_size": 4,
            "http_port": 0,
            "enable_metrics": False,
        }
        service = ScoringService(env, indexer=_make_indexer())
        service.admission = AdmissionController(
            AdmissionConfig(**admission_cfg)
        )
        return service

    def test_shed_returns_429_with_retry_after(self):
        service = self._service(
            max_concurrency=1, max_queue_depth=0, retry_after_s=3.0
        )
        # Fill the only slot out-of-band: the next request must shed.
        service.admission.try_acquire()

        async def run():
            from aiohttp.test_utils import TestClient, TestServer

            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post("/score_completions", json={
                    "prompt": PROMPT, "model": TEST_MODEL_NAME,
                })
                assert resp.status == 429
                assert resp.headers["Retry-After"] == "3"
                body = await resp.json()
                assert body["shed"] == SHED_QUEUE_FULL
                assert body["retry_after_s"] == 3.0
                # The batch endpoint sheds the same way.
                resp = await client.post("/score_completions/batch", json={
                    "requests": [
                        {"prompt": PROMPT, "model": TEST_MODEL_NAME}
                    ],
                })
                assert resp.status == 429

        try:
            asyncio.run(run())
        finally:
            service.admission.release()
            service.indexer.shutdown()

    def test_expired_deadline_header_sheds_as_deadline(self):
        service = self._service(max_concurrency=4)

        async def run():
            from aiohttp.test_utils import TestClient, TestServer

            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                    headers={"X-Request-Deadline-Ms": "0"},
                )
                assert resp.status == 429
                assert (await resp.json())["shed"] == SHED_DEADLINE

        try:
            asyncio.run(run())
        finally:
            service.indexer.shutdown()

    def test_admitted_request_scores_normally(self):
        service = self._service(max_concurrency=4)

        async def run():
            from aiohttp.test_utils import TestClient, TestServer

            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post("/score_completions", json={
                    "prompt": PROMPT, "model": TEST_MODEL_NAME,
                })
                assert resp.status == 200
                assert "podScores" in await resp.json()
                # The gate's occupancy shows up in /readyz and
                # /routing/status.
                resp = await client.get("/routing/status")
                body = await resp.json()
                assert body["admission"]["stats"]["admitted"] == 1

        try:
            asyncio.run(run())
        finally:
            service.indexer.shutdown()


# -- gRPC surface -------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestGrpcSurface:
    def test_shed_is_resource_exhausted_with_retry_after_trailer(self):
        import grpc

        from llm_d_kv_cache_manager_tpu.api.grpc_server import (
            IndexerGrpcClient,
            serve_grpc,
        )

        indexer = _make_indexer()
        admission = AdmissionController(AdmissionConfig(
            max_concurrency=1, max_queue_depth=0, retry_after_s=1.5
        ))
        admission.try_acquire()  # fill the slot: every call sheds
        port = _free_port()
        server = serve_grpc(
            indexer, f"127.0.0.1:{port}", admission=admission
        )
        client = IndexerGrpcClient(f"127.0.0.1:{port}")
        try:
            with pytest.raises(grpc.RpcError) as err:
                client.get_pod_scores(PROMPT, TEST_MODEL_NAME)
            assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            trailers = dict(err.value.trailing_metadata() or ())
            assert trailers.get("retry-after-ms") == "1500"
        finally:
            client.close()
            server.stop(0)
            admission.release()
            indexer.shutdown()

    def test_deadline_expired_returns_no_signal_counted(self):
        """The satellite pin: GetPodScoresEx aborts the scoring WORK on
        an already-expired client deadline — no-signal out, shed counted
        — exercised through the real deadline-check helper."""
        from llm_d_kv_cache_manager_tpu.api import grpc_server

        metrics.register_metrics()

        class _ExpiredContext:
            def time_remaining(self):
                return 0.0

        class _LiveContext:
            def time_remaining(self):
                return 5.0

        class _NoDeadlineContext:
            def time_remaining(self):
                return None

        before = metrics.counter_value(metrics.admission_shed)
        assert grpc_server._deadline_expired(_ExpiredContext()) is True
        assert metrics.counter_value(metrics.admission_shed) == before + 1
        assert grpc_server._deadline_expired(_LiveContext()) is False
        assert grpc_server._deadline_expired(_NoDeadlineContext()) is False
        assert metrics.counter_value(metrics.admission_shed) == before + 1

    def test_bulk_stream_sheds_surface_as_resource_exhausted(self):
        import grpc

        from llm_d_kv_cache_manager_tpu.api.grpc_server import (
            IndexerGrpcClient,
            serve_grpc,
        )

        indexer = _make_indexer()
        admission = AdmissionController(AdmissionConfig(
            max_concurrency=1, max_queue_depth=0
        ))
        admission.try_acquire()
        port = _free_port()
        server = serve_grpc(
            indexer, f"127.0.0.1:{port}", admission=admission
        )
        client = IndexerGrpcClient(f"127.0.0.1:{port}")
        try:
            with pytest.raises(grpc.RpcError) as err:
                client.score_pods_bulk([
                    {"prompt": PROMPT, "model_name": TEST_MODEL_NAME},
                    {"prompt": PROMPT, "model_name": TEST_MODEL_NAME},
                ])
            assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        finally:
            client.close()
            server.stop(0)
            admission.release()
            indexer.shutdown()

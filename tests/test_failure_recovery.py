"""Failure-detection / recovery behaviors (SURVEY.md §5 parity).

The reference's resilience story: ZMQ subscriber reconnects forever at a
fixed interval (zmq_subscriber.go:55-77), poison events are dropped without
killing workers, UDS clients retry with backoff. The pool/UDS cases are
covered in their own suites; this file exercises the subscriber's
bind-retry loop with a real contended endpoint.
"""

import time
import uuid

import pytest
import zmq

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents import zmq_subscriber as sub_mod
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher, make_topic


def _wait(predicate, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return False


def test_subscriber_retries_until_endpoint_frees(tmp_path, monkeypatch):
    monkeypatch.setattr(sub_mod, "RETRY_INTERVAL_S", 0.2)
    endpoint = f"ipc://{tmp_path}/contended-{uuid.uuid4().hex[:6]}.sock"

    # Occupy the endpoint so the subscriber's bind fails.
    ctx = zmq.Context.instance()
    squatter = ctx.socket(zmq.SUB)
    squatter.bind(endpoint)

    index = InMemoryIndex(InMemoryIndexConfig(size=100, pod_cache_size=4))
    processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
    pool = EventPool(
        EventPoolConfig(zmq_endpoint=endpoint, concurrency=1), index, processor
    )
    pool.start(with_subscriber=True)
    try:
        time.sleep(0.5)  # a few failed bind attempts
        squatter.close(linger=0)  # free the endpoint; next retry succeeds

        publisher = Publisher(endpoint, make_topic("pod-r", "m"))
        tokens = [1, 2, 3, 4]
        keys = processor.tokens_to_kv_block_keys(None, tokens, "m")

        def published_and_indexed():
            publisher.publish(
                EventBatch(ts=time.time(), events=[BlockStored([9], None, tokens, 4)])
            )
            return len(index.lookup(keys, set())) == 1

        assert _wait(published_and_indexed), "subscriber never recovered the endpoint"
        publisher.close()
    finally:
        pool.shutdown()

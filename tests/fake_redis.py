"""In-process fake Redis server speaking minimal RESP2.

Test double equivalent to the reference's miniredis dependency
(/root/reference/pkg/kvcache/kvblock/redis_test.go:22): enough of the
protocol (PING, SET, GET, DEL, HSET, HDEL, HKEYS, HLEN, SCAN, FLUSHALL,
SELECT)
for the RedisIndex behavior suite, no external server needed.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict


class FakeRedisServer:
    def __init__(self):
        self._strings: Dict[bytes, bytes] = {}
        self._hashes: Dict[bytes, Dict[bytes, bytes]] = {}
        self._mu = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._conns: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"redis://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # Drop live client connections too, so close() simulates a real
        # server death for fault-injection tests.
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass

    # -- server loops --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        buf = b""
        try:
            while not self._stop.is_set():
                while b"\r\n" not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                cmd, buf = self._parse_command(buf, conn)
                if cmd is None:
                    return
                conn.sendall(self._dispatch(cmd))
        except OSError:
            pass
        finally:
            conn.close()

    def _parse_command(self, buf: bytes, conn: socket.socket):
        def read_line():
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("client gone")
                buf += chunk
            line, rest = buf.split(b"\r\n", 1)
            buf = rest
            return line

        def read_exact(n):
            nonlocal buf
            while len(buf) < n + 2:
                chunk = conn.recv(65536)
                if not chunk:
                    raise OSError("client gone")
                buf += chunk
            data, rest = buf[:n], buf[n + 2:]
            buf = rest
            return data

        try:
            header = read_line()
            if not header.startswith(b"*"):
                return None, buf
            n = int(header[1:])
            parts = []
            for _ in range(n):
                length_line = read_line()
                assert length_line.startswith(b"$")
                parts.append(read_exact(int(length_line[1:])))
            return parts, buf
        except OSError:
            return None, buf

    # -- command dispatch ----------------------------------------------------

    def _dispatch(self, parts) -> bytes:
        cmd = parts[0].upper()
        args = parts[1:]
        with self._mu:
            if cmd == b"PING":
                return b"+PONG\r\n"
            if cmd == b"SELECT":
                return b"+OK\r\n"
            if cmd == b"FLUSHALL":
                self._strings.clear()
                self._hashes.clear()
                return b"+OK\r\n"
            if cmd == b"SET":
                self._strings[args[0]] = args[1]
                return b"+OK\r\n"
            if cmd == b"GET":
                value = self._strings.get(args[0])
                if value is None:
                    return b"$-1\r\n"
                return b"$%d\r\n%s\r\n" % (len(value), value)
            if cmd == b"DEL":
                n = 0
                for key in args:
                    n += int(self._strings.pop(key, None) is not None)
                    n += int(self._hashes.pop(key, None) is not None)
                return b":%d\r\n" % n
            if cmd == b"HSET":
                h = self._hashes.setdefault(args[0], {})
                added = 0
                for i in range(1, len(args) - 1, 2):
                    added += int(args[i] not in h)
                    h[args[i]] = args[i + 1]
                return b":%d\r\n" % added
            if cmd == b"HDEL":
                h = self._hashes.get(args[0], {})
                n = sum(int(h.pop(f, None) is not None) for f in args[1:])
                if not h:
                    self._hashes.pop(args[0], None)
                return b":%d\r\n" % n
            if cmd == b"HKEYS":
                fields = list(self._hashes.get(args[0], {}))
                out = b"*%d\r\n" % len(fields)
                for f in fields:
                    out += b"$%d\r\n%s\r\n" % (len(f), f)
                return out
            if cmd == b"HLEN":
                return b":%d\r\n" % len(self._hashes.get(args[0], {}))
            if cmd == b"SCAN":
                # SCAN cursor [MATCH pattern] [COUNT n] — single-page
                # snapshot (cursor always returns 0), glob via fnmatch;
                # enough for the RedisIndex bulk-maintenance walks.
                import fnmatch

                pattern = b"*"
                for i in range(1, len(args) - 1):
                    if args[i].upper() == b"MATCH":
                        pattern = args[i + 1]
                keys = [
                    k
                    for k in list(self._strings) + list(self._hashes)
                    if fnmatch.fnmatchcase(
                        k.decode("utf-8", "replace"),
                        pattern.decode("utf-8", "replace"),
                    )
                ]
                out = b"*2\r\n$1\r\n0\r\n*%d\r\n" % len(keys)
                for k in keys:
                    out += b"$%d\r\n%s\r\n" % (len(k), k)
                return out
            return b"-ERR unknown command '%s'\r\n" % cmd

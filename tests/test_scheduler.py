"""Continuous-batching scheduler tests: batched decode must equal isolated
per-sequence greedy generation, admission must wait for pages, prefix reuse
must carry across requests."""

import jax.numpy as jnp
import pytest

from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

CFG = LlamaConfig(
    vocab_size=128, d_model=32, n_layers=1, n_q_heads=2, n_kv_heads=2,
    head_dim=16, d_ff=64, dtype=jnp.float32,
)


def _pod(n_pages=64):
    return EnginePod(
        EnginePodConfig(
            n_pages=n_pages, page_size=4, with_model=True, model_config=CFG,
            max_pages_per_seq=16,
        )
    )


def _isolated_generate(prompt, n_new):
    """Reference: one sequence alone on a fresh pod."""
    pod = _pod()
    state, _ = pod.prefill(list(prompt))
    first = int(jnp.argmax(pod.last_logits))
    pod.decode_append(state, first)
    out = [first]
    for _ in range(n_new - 1):
        out.append(pod.decode_step(state))
    pod.free(state)
    return out


class TestScheduler:
    def test_batched_equals_isolated(self):
        prompts = [list(range(5)), list(range(20, 31)), list(range(40, 47))]
        expected = [_isolated_generate(p, 6) for p in prompts]

        pod = _pod()
        sched = Scheduler(pod, max_batch=4)
        ids = [sched.submit(p, max_new_tokens=6) for p in prompts]
        results = sched.run()
        for req_id, exp in zip(ids, expected):
            assert results[req_id] == exp

    def test_admission_waits_for_pages(self):
        # Pool fits ~2 sequences; the third must wait and still complete.
        pod = _pod(n_pages=10)
        sched = Scheduler(pod, max_batch=4)
        ids = [
            sched.submit(list(range(i * 10, i * 10 + 8)), max_new_tokens=4)
            for i in range(3)
        ]
        results = sched.run()
        assert all(len(results[i]) == 4 for i in ids)

    def test_oversized_request_fails_cleanly(self):
        pod = _pod(n_pages=4)  # 16 tokens total capacity
        sched = Scheduler(pod, max_batch=2)
        too_big = sched.submit(list(range(40)), max_new_tokens=2)
        ok = sched.submit(list(range(6)), max_new_tokens=2)
        # The rejection carries a reason, visible via step().
        first_tick = sched.step()
        assert any(r.req_id == too_big and "pages" in r.error for r in first_tick)
        results = {r.req_id: r.generated for r in first_tick if r.error is None}
        results.update(sched.run())
        assert len(results[ok]) == 2

    def test_zero_max_new_tokens_rejected(self):
        sched = Scheduler(_pod(), max_batch=1)
        req = sched.submit(list(range(4)), max_new_tokens=0)
        results = sched.run()
        assert results[req] == []

    def test_decode_preemption_recomputes_correctly(self):
        # Pool too small for both sequences' full growth: one gets preempted
        # mid-decode and recomputed; greedy outputs must still match the
        # isolated reference exactly.
        prompts = [list(range(8)), list(range(50, 58))]
        expected = [_isolated_generate(p, 8) for p in prompts]
        pod = _pod(n_pages=7)  # each seq needs 4 pages at the end
        sched = Scheduler(pod, max_batch=2)
        ids = [sched.submit(p, max_new_tokens=8) for p in prompts]
        results = sched.run()
        for req_id, exp in zip(ids, expected):
            assert results[req_id] == exp

    def test_prefix_reuse_across_requests(self):
        pod = _pod()
        sched = Scheduler(pod, max_batch=2)
        prompt = list(range(12))
        first = sched.submit(prompt, max_new_tokens=3)
        sched.run()
        # Same prompt again: pages were freed but stay cached.
        again = sched.submit(prompt, max_new_tokens=3)
        results = sched.run()
        assert len(results[again]) == 3
        assert pod.block_manager.num_cached_pages > 0

    def test_pending_page_not_reused_by_same_prefix_admission(self):
        # ADVICE r2 (medium) regression: a decode-filled page's final slot
        # holds the pending token, whose KV row is written only by the NEXT
        # decode pass — but _prefill_tick runs before _decode, so a
        # same-prefix request admitted in that window previously reused the
        # page and attended a garbage row. The page must stay uncommitted
        # (B recomputes it) and B's output must match an isolated run.
        pod = _pod()
        sched = Scheduler(pod, max_batch=2)
        a = sched.submit(list(range(4)), max_new_tokens=10)
        sched.step()  # prefill A + first sampled token (len 5, pending)
        a_req = sched._running[0]
        while len(a_req.state.tokens) < 8:
            sched.step()  # each decode tick appends one token
        # A's tokens now fill page 2 exactly; its last row is pending.
        prompt_b = list(a_req.state.tokens)
        b = sched.submit(prompt_b, max_new_tokens=4)
        sched.step()  # admits B BEFORE the decode that writes A's pending row
        b_req = next(r for r in sched._running if r.req_id == b)
        assert b_req.num_cached_tokens == 4  # page 2 NOT advertised
        results = sched.run()
        assert results[b] == _isolated_generate(prompt_b, 4)

    def test_eos_stops_generation(self):
        pod = _pod()
        sched = Scheduler(pod, max_batch=1)
        # Discover the first generated token, then use it as EOS.
        probe = _isolated_generate(list(range(8)), 1)[0]
        req = sched.submit(list(range(8)), max_new_tokens=10, eos_token=probe)
        results = sched.run()
        assert results[req] == [probe]  # stopped at the first token


class TestDecodeBatchBucketing:
    def test_decode_compiles_bounded_by_batch_buckets(self):
        # As sequences finish, the running batch shrinks through every size
        # 8..1; padding the batch axis to power-of-2 buckets must bound the
        # XLA programs at 4 (8, 4, 2, 1), not 8 — on TPU each decode
        # compile costs seconds.
        from llm_d_kv_cache_manager_tpu.models import llama

        pod = _pod(n_pages=128)
        sched = Scheduler(pod, max_batch=8)
        before = llama.decode_step_cache._cache_size()
        for i in range(8):
            # Disjoint prompts, staggered budgets: one sequence finishes
            # per decode tick once the shortest is done.
            sched.submit(list(range(i * 16, i * 16 + 4)), max_new_tokens=2 + i)
        sched.run()
        grew = llama.decode_step_cache._cache_size() - before
        assert grew <= 4, f"decode compiled {grew} programs for batch sizes 8..1"

    def test_padded_batch_output_identical(self):
        # Batch padding must not change any real sequence's tokens (pad
        # rows write only the trash page and their outputs are dropped).
        prompts = [list(range(i * 16, i * 16 + 5)) for i in range(3)]  # pads to 4
        expected = [_isolated_generate(p, 5) for p in prompts]
        sched = Scheduler(_pod(n_pages=128), max_batch=4)
        ids = [sched.submit(p, max_new_tokens=5) for p in prompts]
        results = sched.run()
        for rid, exp in zip(ids, expected):
            assert results[rid] == exp


class TestChunkedPrefill:
    """VERDICT r1 #10: prefill token budget per tick, interleaved with
    decode (vLLM-style), replacing one-admission-per-tick."""

    def test_chunked_equals_unchunked(self):
        # f32 model: chunked prefill is the same math in different slices,
        # so greedy generation must match exactly.
        prompt = list(range(2, 50))  # 48 tokens -> 6 chunks at budget 8
        expected = None
        for budget in (4096, 8):
            sched = Scheduler(_pod(), prefill_token_budget=budget)
            rid = sched.submit(prompt, max_new_tokens=6)
            out = sched.run()[rid]
            assert len(out) == 6
            if expected is None:
                expected = out
            else:
                assert out == expected

    def test_long_prompt_does_not_stall_decode(self):
        pod = _pod(n_pages=128)
        sched = Scheduler(pod, max_batch=4, prefill_token_budget=8)
        short = sched.submit(list(range(5)), max_new_tokens=40)
        sched.step()  # short admitted (5 <= budget), starts decoding
        assert len(sched._running) == 1
        short_req = sched._running[0]

        long_id = sched.submit(list(range(60, 108)), max_new_tokens=2)  # 48 tok
        ticks = 0
        done_ids = []
        while long_id not in done_ids:
            gen_before = len(short_req.generated)
            done_ids += [r.req_id for r in sched.step()]
            ticks += 1
            # The running batch decoded every tick while the long prompt
            # was being prefilled in chunks — bounded decode stall.
            assert len(short_req.generated) == gen_before + 1
            assert ticks < 20, "long prompt never finished prefilling"
        assert ticks >= 48 // 8  # the prompt really did span multiple ticks

    def test_budget_packs_multiple_short_prompts_in_one_tick(self):
        sched = Scheduler(_pod(), max_batch=4, prefill_token_budget=512)
        for i in range(3):
            sched.submit(list(range(i * 10, i * 10 + 8)), max_new_tokens=4)
        sched.step()
        assert len(sched._running) == 3  # all admitted in a single tick

    def test_same_prefix_wave_flushes_and_reuses(self):
        # Two identical prompts arriving in one tick: the second must NOT
        # allocate before the first's pages commit (that would duplicate
        # pages and recompute the prefix). The wave flushes; next tick the
        # second request hits the committed prefix.
        pod = _pod()
        sched = Scheduler(pod, max_batch=4, prefill_token_budget=512)
        prompt = list(range(12))
        expected = _isolated_generate(prompt, 4)
        a = sched.submit(prompt, max_new_tokens=4)
        b = sched.submit(prompt, max_new_tokens=4)
        sched.step()
        b_req = sched._waiting[0] if sched._waiting else None
        assert b_req is not None and b_req.req_id == b  # deferred one tick
        sched.step()
        assert b_req.num_cached_tokens >= 8  # reused A's committed pages
        results = sched.run()
        assert results[a] == expected
        assert results[b] == expected

    def test_resumed_prompt_guards_same_prefix_arrival(self):
        # Review repro (r3): a long prompt RESUMING mid-prefill in the wave
        # must also block a same-prefix arrival — its pages commit only
        # after the dispatch, so admitting B in the same wave would
        # duplicate pages and recompute the prefix.
        prompt = list(range(12))
        expected = _isolated_generate(prompt, 3)
        sched = Scheduler(_pod(), max_batch=4, prefill_token_budget=8)
        a = sched.submit(prompt, max_new_tokens=3)
        b = sched.submit(prompt, max_new_tokens=3)
        sched.step()  # A computes [0, 8)
        sched.step()  # A resumes + completes; B must NOT join this wave
        b_req = next(
            r for r in list(sched._waiting) + sched._running if r.req_id == b
        )
        results = {}
        while sched.has_work:
            for r in sched.step():
                results[r.req_id] = r.generated
        assert b_req.num_cached_tokens >= 8  # reused A's committed prefix
        assert results[a] == expected
        assert results[b] == expected

    def test_packed_prefill_is_one_dispatch_and_identical(self):
        # A multi-prompt admission wave must run as ONE device dispatch
        # (prefill_chunk_batch -> verify_step_cache), not one per prompt,
        # and emit exactly the sequential outputs.
        from llm_d_kv_cache_manager_tpu.models import llama

        prompts = [list(range(i * 16, i * 16 + 6)) for i in range(4)]
        expected = [_isolated_generate(p, 4) for p in prompts]

        pod = _pod()
        sched = Scheduler(pod, max_batch=4, prefill_token_budget=512)
        calls = {"verify": 0, "prefill": 0}
        orig_verify, orig_prefill = llama.verify_step_cache, llama.prefill_cache

        def spy_verify(*a, **k):
            calls["verify"] += 1
            return orig_verify(*a, **k)

        def spy_prefill(*a, **k):
            calls["prefill"] += 1
            return orig_prefill(*a, **k)

        llama.verify_step_cache = spy_verify
        llama.prefill_cache = spy_prefill
        try:
            ids = [sched.submit(p, max_new_tokens=4) for p in prompts]
            sched.step()  # the admission wave
        finally:
            llama.verify_step_cache = orig_verify
            llama.prefill_cache = orig_prefill
        assert calls["verify"] == 1  # one packed dispatch for 4 prompts
        assert calls["prefill"] == 0
        results = sched.run()
        for rid, exp in zip(ids, expected):
            assert results[rid] == exp

    def test_budget_validation(self):
        with pytest.raises(ValueError, match="prefill_token_budget"):
            Scheduler(_pod(), prefill_token_budget=0)

    def test_preemption_never_starves_mid_prefill_head(self):
        # Livelock regression: a preempted request must not queue ahead of
        # a mid-prefill request — that request holds its pages and only
        # progresses at the queue head. Tight pool + long prompts + small
        # budget force preemption churn; run() must drain.
        pod = _pod(n_pages=16)  # 64 tokens of pages total
        sched = Scheduler(pod, max_batch=4, prefill_token_budget=4)
        ids = [
            sched.submit(list(range(i * 30, i * 30 + 20)), max_new_tokens=8)
            for i in range(3)
        ]
        ticks = 0
        results = {}
        while sched.has_work:
            for req in sched.step():
                results[req.req_id] = req
            ticks += 1
            assert ticks < 500, "scheduler livelocked under page pressure"
        for rid in ids:
            assert results[rid].error is None
            assert len(results[rid].generated) == 8

"""API layer tests: gRPC service + client roundtrip, HTTP scoring service."""

import asyncio
import socket
import time

import pytest

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from llm_d_kv_cache_manager_tpu.api.grpc_server import IndexerGrpcClient, serve_grpc
from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

BLOCK_SIZE = 4
PROMPT = "The quick brown fox jumps over the lazy dog. " * 3


def _make_indexer():
    indexer = Indexer(
        config=IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=BLOCK_SIZE),
        ),
        tokenization_pool=TokenizationPool(
            TokenizersPoolConfig(
                workers=2, local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON}
            ),
        ),
    )
    indexer.run()
    return indexer


def _seed_index(indexer, pod="pod-grpc"):
    """Pretend `pod` cached the prompt's full prefix."""
    enc = indexer.tokenizers_pool.tokenizer.encode(PROMPT, TEST_MODEL_NAME)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        None, enc.tokens, TEST_MODEL_NAME
    )
    engine_keys = [Key(TEST_MODEL_NAME, 10_000 + i) for i in range(len(keys))]
    indexer.kv_block_index.add(engine_keys, keys, [PodEntry(pod, "hbm")])
    return len(keys)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestGrpc:
    def test_roundtrip_scores(self):
        indexer = _make_indexer()
        n_blocks = _seed_index(indexer)
        port = _free_port()
        server = serve_grpc(indexer, f"127.0.0.1:{port}")
        try:
            client = IndexerGrpcClient(f"127.0.0.1:{port}")
            scores = client.get_pod_scores(PROMPT, TEST_MODEL_NAME)
            assert scores.get("pod-grpc") == float(n_blocks)
            # Filtered query excludes the pod.
            assert client.get_pod_scores(PROMPT, TEST_MODEL_NAME, ["other"]) == {}
            client.close()
        finally:
            server.stop(grace=0)
            indexer.shutdown()

    def test_lora_scoped_scores_over_grpc(self):
        indexer = _make_indexer()
        # Seed under adapter 5 only.
        enc = indexer.tokenizers_pool.tokenizer.encode(PROMPT, TEST_MODEL_NAME)
        keys = indexer.token_processor.tokens_to_kv_block_keys(
            None, enc.tokens, TEST_MODEL_NAME, lora_id=5
        )
        engine_keys = [Key(TEST_MODEL_NAME, 20_000 + i) for i in range(len(keys))]
        indexer.kv_block_index.add(engine_keys, keys, [PodEntry("pod-lora", "hbm")])
        port = _free_port()
        server = serve_grpc(indexer, f"127.0.0.1:{port}")
        try:
            client = IndexerGrpcClient(f"127.0.0.1:{port}")
            assert client.get_pod_scores(PROMPT, TEST_MODEL_NAME) == {}
            scored = client.get_pod_scores(PROMPT, TEST_MODEL_NAME, lora_id=5)
            assert scored.get("pod-lora") == float(len(keys))
            client.close()
        finally:
            server.stop(grace=0)
            indexer.shutdown()

    def test_unknown_model_maps_to_internal_error(self):
        import grpc

        indexer = _make_indexer()
        port = _free_port()
        server = serve_grpc(indexer, f"127.0.0.1:{port}")
        try:
            client = IndexerGrpcClient(f"127.0.0.1:{port}")
            with pytest.raises(grpc.RpcError) as err:
                client.get_pod_scores("hello world " * 10, "no-such-model")
            assert err.value.code() == grpc.StatusCode.INTERNAL
            client.close()
        finally:
            server.stop(grace=0)
            indexer.shutdown()


class TestHttp:
    def _service(self):
        env = {
            "zmq_endpoint": "tcp://*:0",
            "zmq_topic": "kv@",
            "pool_concurrency": 1,
            "hash_seed": "",
            "block_size": BLOCK_SIZE,
            "http_port": 0,
            "enable_metrics": False,
        }
        return ScoringService(env, indexer=_make_indexer())

    def test_score_completions_and_health(self):
        from aiohttp.test_utils import TestClient, TestServer

        service = self._service()
        n_blocks = _seed_index(service.indexer, pod="pod-http")

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200
                data = await resp.json()
                assert data["podScores"]["pod-http"] == float(n_blocks)

                resp = await client.get("/health")
                assert (await resp.json())["status"] == "ok"

                # Malformed request: 400 with an error body.
                resp = await client.post("/score_completions", json={"model": "x"})
                assert resp.status == 400

                resp = await client.get("/metrics")
                assert resp.status == 200

        try:
            asyncio.run(run())
        finally:
            service.indexer.shutdown()

    def test_readyz_reports_event_plane_and_fleet_health(self):
        from aiohttp.test_utils import TestClient, TestServer

        service = self._service()

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                # Liveness stays liveness: /health is 200 even though the
                # event plane was never started.
                resp = await client.get("/health")
                assert resp.status == 200

                # Not started yet: unready, with the reason visible.
                resp = await client.get("/readyz")
                assert resp.status == 503
                data = await resp.json()
                assert data["status"] == "unready"
                assert data["started"] is False

                # Started without a subscriber (embedded mode): ready, and
                # the payload carries queue/drop/pod-health introspection.
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                assert resp.status == 200
                data = await resp.json()
                assert data["status"] == "ready"
                assert data["subscriber"] is None
                assert data["event_pool"]["workers_alive"] >= 1
                assert data["event_pool"]["dropped_events"] == 0
                assert isinstance(data["event_pool"]["queue_depths"], list)
                assert data["fleet"]["counts"] == {
                    "healthy": 0, "suspect": 0, "stale": 0
                }

        try:
            asyncio.run(run())
        finally:
            service.stop()
            # stop() is safe even if start() never ran in a failed test.

    def test_readyz_transfer_section_reports_breakers(self):
        """The `transfer` section surfaces the wired client's per-peer
        breaker state + failure memory; absent a transfer plane it is
        null (and never conjures one into the process)."""
        from aiohttp.test_utils import TestClient, TestServer

        from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
            TransferClient,
            TransferClientConfig,
        )

        service = self._service()
        client_obj = TransferClient(TransferClientConfig(
            breaker_failure_threshold=1, breaker_cooldown_s=60.0,
        ))
        # Seed per-peer state without touching any socket.
        client_obj.note_result("10.9.9.9", 7, ok=False, latency_s=0.2)
        client_obj.note_result(
            "10.9.9.8", 7, ok=True, latency_s=0.01, corrupt_blocks=2,
            blocks=4,
        )
        service.transfer_client = client_obj

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                data = await resp.json()
                section = data["transfer"]
                dead = section["peers"]["10.9.9.9:7"]
                assert dead["state"] == "open"  # threshold 1: one strike
                assert dead["consecutive_failures"] == 1
                corrupt = section["peers"]["10.9.9.8:7"]
                assert corrupt["corrupt_blocks"] == 2
                assert corrupt["ewma_fetch_latency_ms"] == 10.0
                assert section["breaker"]["failure_threshold"] == 1
                # Breaker state never gates THIS process's readiness.
                assert resp.status == 200

        try:
            asyncio.run(run())
        finally:
            service.stop()

    def test_score_chat_completions_renders_template(self):
        from aiohttp.test_utils import TestClient, TestServer

        service = self._service()
        template = (
            "{% for m in messages %}[{{ m.role }}] {{ m.content }} {% endfor %}"
            "{% if add_generation_prompt %}[assistant]{% endif %}"
        )

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_chat_completions",
                    json={
                        "model": TEST_MODEL_NAME,
                        "messages": [
                            {"role": "user", "content": "The quick brown fox"}
                        ],
                        "chat_template": template,
                    },
                )
                assert resp.status == 200
                data = await resp.json()
                assert data["templated_messages"] == (
                    "[user] The quick brown fox [assistant]"
                )
                assert data["podScores"] == {}

        try:
            asyncio.run(run())
        finally:
            service.indexer.shutdown()

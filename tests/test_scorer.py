"""LongestPrefixScorer tests.

Mirrors the reference scorer cases
(/root/reference/pkg/kvcache/kvblock_scorer_test.go:34-110): consecutive-from-
block-0 matching, intersection semantics, device-tier weighting.
"""

from llm_d_kv_cache_manager_tpu.kvcache.backend import KVCacheBackendConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    KVBlockScorerConfig,
    new_kv_block_scorer,
)


def _k(i):
    return Key("m", i)


def _scorer(**weights):
    cfg = KVBlockScorerConfig(
        backend_configs=[KVCacheBackendConfig(n, w) for n, w in weights.items()]
    )
    return new_kv_block_scorer(cfg)


class TestLongestPrefixScorer:
    def test_empty_keys(self):
        assert _scorer(hbm=1.0).score([], {}) == {}

    def test_single_pod_full_prefix(self):
        s = _scorer(hbm=1.0)
        keys = [_k(1), _k(2), _k(3)]
        mapping = {k: [PodEntry("p1", "hbm")] for k in keys}
        assert s.score(keys, mapping) == {"p1": 3.0}

    def test_prefix_breaks_at_gap(self):
        s = _scorer(hbm=1.0)
        keys = [_k(1), _k(2), _k(3)]
        mapping = {_k(1): [PodEntry("p1", "hbm")], _k(3): [PodEntry("p1", "hbm")]}
        # p1 misses block 2: score stops at 1 even though block 3 is cached.
        assert s.score(keys, mapping) == {"p1": 1.0}

    def test_pod_missing_first_block_scores_zero(self):
        s = _scorer(hbm=1.0)
        keys = [_k(1), _k(2)]
        mapping = {
            _k(1): [PodEntry("p1", "hbm")],
            _k(2): [PodEntry("p1", "hbm"), PodEntry("p2", "hbm")],
        }
        scores = s.score(keys, mapping)
        assert scores == {"p1": 2.0}
        assert "p2" not in scores

    def test_intersection_drops_pod_but_keeps_score(self):
        s = _scorer(hbm=1.0)
        keys = [_k(1), _k(2), _k(3)]
        mapping = {
            _k(1): [PodEntry("p1", "hbm"), PodEntry("p2", "hbm")],
            _k(2): [PodEntry("p1", "hbm")],
            _k(3): [PodEntry("p1", "hbm")],
        }
        assert s.score(keys, mapping) == {"p1": 3.0, "p2": 1.0}

    def test_tier_weights(self):
        s = _scorer(hbm=1.0, host=0.8)
        keys = [_k(1), _k(2)]
        mapping = {
            _k(1): [PodEntry("p1", "host"), PodEntry("p2", "hbm")],
            _k(2): [PodEntry("p1", "host"), PodEntry("p2", "hbm")],
        }
        scores = s.score(keys, mapping)
        assert scores["p1"] == 1.6 and scores["p2"] == 2.0

    def test_max_tier_weight_per_block(self):
        s = _scorer(hbm=1.0, host=0.8)
        keys = [_k(1)]
        mapping = {_k(1): [PodEntry("p1", "host"), PodEntry("p1", "hbm")]}
        assert s.score(keys, mapping) == {"p1": 1.0}

    def test_unknown_tier_defaults_to_one(self):
        s = _scorer(hbm=1.0)
        keys = [_k(1)]
        mapping = {_k(1): [PodEntry("p1", "mystery-tier")]}
        assert s.score(keys, mapping) == {"p1": 1.0}

"""obs/slo.py: declarative objectives + multi-window burn-rate monitoring.

Pins the ISSUE-13 SLO contracts: burn rates computed from REAL registry
values (no parallel bookkeeping), the multi-window state machine
(no_data → ok → warning → breaching), the fault-injected breach flip
(admission sheds driving the shed-rate objective), the /slo/status and
/readyz surfaces, and the bounded sample ring.
"""

import asyncio

import pytest

from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.obs.slo import (
    OBJECTIVE_HIT_RATE,
    OBJECTIVE_READ_LATENCY,
    OBJECTIVE_SHED_RATE,
    SLO_OBJECTIVES,
    SLO_WINDOWS,
    SLOConfig,
    SLOMonitor,
    SLOObjective,
    STATUS_BREACHING,
    STATUS_NO_DATA,
    STATUS_OK,
    STATUS_WARNING,
    default_objectives,
)


def _gauge(objective: str, window: str):
    for metric in metrics.slo_burn_rate.collect():
        for s in metric.samples:
            if (
                s.labels.get("objective") == objective
                and s.labels.get("window") == window
            ):
                return s.value
    return None


def _monitor(counts, **cfg_kwargs):
    """Monitor over one synthetic objective backed by a mutable
    [bad, total] list, with an injected clock."""
    now = [1000.0]
    config = SLOConfig(**{
        "fast_window_s": 60.0, "slow_window_s": 600.0, **cfg_kwargs,
    })
    objective = SLOObjective(
        name=OBJECTIVE_SHED_RATE,  # label values must stay in-vocabulary
        description="synthetic",
        budget=0.01,
        counts_fn=lambda: tuple(counts),
    )
    return SLOMonitor([objective], config, clock=lambda: now[0]), now


class TestBurnMath:
    def test_no_data_then_ok(self):
        counts = [0.0, 0.0]
        mon, now = _monitor(counts)
        doc = mon.evaluate()
        obj = doc["objectives"][OBJECTIVE_SHED_RATE]
        assert obj["status"] == STATUS_NO_DATA
        assert doc["status"] == STATUS_OK

        counts[1] = 1000.0  # traffic arrives, all good
        now[0] += 10
        obj = mon.evaluate()["objectives"][OBJECTIVE_SHED_RATE]
        assert obj["status"] == STATUS_OK
        assert obj["windows"]["fast"]["burn_rate"] == 0.0

    def test_burn_is_bad_fraction_over_budget(self):
        counts = [0.0, 0.0]
        mon, now = _monitor(counts)
        mon.evaluate()
        # 2% bad against a 1% budget → burn 2.0 in both windows.
        counts[0] += 20.0
        counts[1] += 1000.0
        now[0] += 30
        obj = mon.evaluate()["objectives"][OBJECTIVE_SHED_RATE]
        assert obj["windows"]["fast"]["burn_rate"] == pytest.approx(2.0)
        assert obj["windows"]["slow"]["burn_rate"] == pytest.approx(2.0)
        # threshold is exclusive: burn == threshold is not a breach
        assert obj["status"] == STATUS_OK

    def test_warning_when_only_fast_window_burns(self):
        counts = [0.0, 0.0]
        mon, now = _monitor(counts)
        mon.evaluate()
        # A long clean history fills the slow window...
        for _ in range(10):
            counts[1] += 10_000.0
            now[0] += 55
            mon.evaluate()
        # ...then a short spike: the fast window burns, the slow one is
        # diluted by the clean history.
        counts[0] += 400.0
        counts[1] += 1000.0
        now[0] += 30
        obj = mon.evaluate()["objectives"][OBJECTIVE_SHED_RATE]
        assert obj["windows"]["fast"]["burn_rate"] > 2.0
        assert obj["windows"]["slow"]["burn_rate"] <= 2.0
        assert obj["status"] == STATUS_WARNING

    def test_breaching_needs_both_windows(self):
        counts = [0.0, 0.0]
        mon, now = _monitor(counts)
        mon.evaluate()
        counts[0] += 500.0
        counts[1] += 1000.0
        now[0] += 30
        doc = mon.evaluate()
        obj = doc["objectives"][OBJECTIVE_SHED_RATE]
        # Young monitor: both windows clip to its lifetime → both burn.
        assert obj["status"] == STATUS_BREACHING
        assert doc["status"] == STATUS_BREACHING
        assert OBJECTIVE_SHED_RATE in doc["breaching"]

    def test_counters_before_monitor_birth_are_excluded(self):
        counts = [5000.0, 10_000.0]  # ugly history predating the monitor
        mon, now = _monitor(counts)
        counts[1] += 1000.0  # clean traffic after birth
        now[0] += 30
        obj = mon.evaluate()["objectives"][OBJECTIVE_SHED_RATE]
        assert obj["windows"]["fast"]["bad"] == 0.0
        assert obj["status"] == STATUS_OK

    def test_sample_ring_is_bounded(self):
        counts = [0.0, 0.0]
        mon, now = _monitor(counts, max_samples=16)
        for _ in range(200):
            counts[1] += 10.0
            now[0] += 1.0
            mon.evaluate()
        assert len(mon._samples) <= 16  # noqa: SLF001 - bound under test

    def test_config_validation(self):
        with pytest.raises(ValueError):
            SLOConfig(fast_window_s=600, slow_window_s=60)
        with pytest.raises(ValueError):
            SLOConfig(burn_threshold=0)
        with pytest.raises(ValueError):
            SLOConfig(hit_rate_floor=1.5)
        with pytest.raises(ValueError):
            SLOMonitor(
                default_objectives(SLOConfig())
                + default_objectives(SLOConfig()),
                SLOConfig(),
            )


class TestRegistryObjectives:
    """The default objective set reads the LIVE registry: drive the real
    counters and watch the burn."""

    def test_shed_storm_flips_shed_rate_to_breaching(self):
        metrics.register_metrics()
        now = [5000.0]
        cfg = SLOConfig(fast_window_s=60.0, slow_window_s=600.0)
        mon = SLOMonitor(
            default_objectives(cfg), cfg, clock=lambda: now[0]
        )
        doc = mon.evaluate()
        assert doc["objectives"][OBJECTIVE_SHED_RATE]["status"] in (
            STATUS_NO_DATA, STATUS_OK,
        )
        # Fault injection: the admission gate sheds a storm of requests
        # (the counter the serving surfaces increment on 429 /
        # RESOURCE_EXHAUSTED).
        for _ in range(300):
            metrics.count_admission_shed("queue_full")
        now[0] += 30.0
        doc = mon.evaluate()
        obj = doc["objectives"][OBJECTIVE_SHED_RATE]
        assert obj["status"] == STATUS_BREACHING
        assert obj["windows"]["fast"]["bad"] == pytest.approx(300.0)
        # Burn-rate gauges exported under the pinned vocabularies.
        for window in SLO_WINDOWS:
            value = _gauge(OBJECTIVE_SHED_RATE, window)
            assert value is not None and value > cfg.burn_threshold

    def test_hit_rate_objective_reads_zero_hit_lookups(self):
        metrics.register_metrics()
        now = [9000.0]
        cfg = SLOConfig(fast_window_s=60.0, slow_window_s=600.0,
                        hit_rate_floor=0.9)
        mon = SLOMonitor(
            default_objectives(cfg), cfg, clock=lambda: now[0]
        )
        # Every lookup misses: max-pod-hit-count observes 0.
        for _ in range(50):
            metrics.index_max_pod_hits.observe(0)
        now[0] += 30.0
        obj = mon.evaluate()["objectives"][OBJECTIVE_HIT_RATE]
        assert obj["windows"]["fast"]["bad"] == pytest.approx(50.0)
        assert obj["status"] == STATUS_BREACHING
        # Now a healthy stretch: long hits dilute below the 10% budget.
        for _ in range(5000):
            metrics.index_max_pod_hits.observe(32)
        now[0] += 10.0
        obj = mon.evaluate()["objectives"][OBJECTIVE_HIT_RATE]
        assert obj["windows"]["fast"]["burn_rate"] < 1.0

    def test_read_latency_objective_reads_stage_histogram(self):
        metrics.register_metrics()
        now = [12_000.0]
        cfg = SLOConfig(fast_window_s=60.0, slow_window_s=600.0,
                        read_p99_ms=5.0)
        mon = SLOMonitor(
            default_objectives(cfg), cfg, clock=lambda: now[0]
        )
        child = metrics.stage_latency.labels(
            plane="read", stage="get_pod_scores"
        )
        for _ in range(100):
            child.observe(0.001)  # fast
        for _ in range(100):
            child.observe(0.5)    # way past 5ms
        now[0] += 30.0
        obj = mon.evaluate()["objectives"][OBJECTIVE_READ_LATENCY]
        assert obj["windows"]["fast"]["total"] == pytest.approx(200.0)
        assert obj["windows"]["fast"]["bad"] == pytest.approx(100.0)
        assert obj["status"] == STATUS_BREACHING

    def test_reader_failure_never_raises(self):
        def broken():
            raise RuntimeError("registry on fire")

        mon = SLOMonitor(
            [SLOObjective(
                name=OBJECTIVE_READ_LATENCY, description="broken",
                budget=0.01, counts_fn=broken,
            )],
            SLOConfig(fast_window_s=60, slow_window_s=600),
        )
        doc = mon.evaluate()
        assert doc["objectives"][OBJECTIVE_READ_LATENCY]["status"] == (
            STATUS_NO_DATA
        )


class TestSloHttpSurface:
    def _service(self, env=None):
        from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
            Indexer,
            IndexerConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            TokenProcessorConfig,
        )
        from llm_d_kv_cache_manager_tpu.tokenization.pool import (
            TokenizationPool,
            TokenizersPoolConfig,
        )

        indexer = Indexer(
            config=IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
            ),
            tokenization_pool=TokenizationPool(
                TokenizersPoolConfig(
                    workers=2,
                    local_tokenizer_files={
                        TEST_MODEL_NAME: TEST_TOKENIZER_JSON
                    },
                ),
            ),
        )
        indexer.run()
        return ScoringService(env=env if env is not None else {},
                              indexer=indexer)

    def test_slo_status_and_readyz_section(self):
        from aiohttp.test_utils import TestClient, TestServer

        metrics.register_metrics()
        service = self._service()
        assert service.slo is not None  # on by default

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.get("/slo/status")
                assert resp.status == 200
                doc = await resp.json()
                assert set(doc["objectives"]) == set(SLO_OBJECTIVES)
                for obj in doc["objectives"].values():
                    assert set(obj["windows"]) == set(SLO_WINDOWS)

                # Shed storm → the endpoint reports the breach (real
                # registry values, the service's own monitor).
                for _ in range(500):
                    metrics.count_admission_shed("timeout")
                resp = await client.get("/slo/status")
                doc = await resp.json()
                assert OBJECTIVE_SHED_RATE in doc["breaching"]

                # /readyz embeds the same document under `slo` and stays
                # 200/503 on event-plane grounds alone: a breach is an
                # alert, not unreadiness.
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                data = await resp.json()
                assert data["slo"] is not None
                assert data["slo"]["objectives"][OBJECTIVE_SHED_RATE][
                    "status"
                ] in (STATUS_BREACHING, STATUS_WARNING, STATUS_OK)
                assert resp.status == 200

        try:
            asyncio.run(run())
        finally:
            service.stop()

    def test_slo_disabled_is_400_and_absent_from_readyz(self):
        from aiohttp.test_utils import TestClient, TestServer

        from llm_d_kv_cache_manager_tpu.api.http_service import (
            config_from_env,
        )

        env = config_from_env()  # SLO=0 path through the real env plumbing
        env["slo"] = False
        service = self._service(env=env)
        assert service.slo is None

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.get("/slo/status")
                assert resp.status == 400
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                assert (await resp.json())["slo"] is None

        try:
            asyncio.run(run())
        finally:
            service.stop()

"""Event wire-plane fuzz: mutated frames never crash or corrupt the pool.

The reference's stance is poison-pill dropping — undecodable messages are
discarded, never retried (/root/reference/pkg/kvcache/kvevents/pool.go:
182-187). This fuzz drives that stance structurally: seeded random
mutations of VALID msgpack EventBatch payloads (truncation, byte flips,
garbage prefixes, empty frames, wrong-shape msgpack, and tag confusion
in the event tagged union) are interleaved with known-good batches, and afterwards (a) the pool's
workers are alive, (b) every good batch landed in the index, and (c) no
mutated frame produced an index entry for a chain the good traffic never
stored.
"""

import random

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    EventPool,
    EventPoolConfig,
    Message,
)

BLOCK = 4
MODEL = "m"


def _good_message(i: int) -> Message:
    tokens = list(range(i * BLOCK, (i + 1) * BLOCK))
    batch = EventBatch(ts=float(i), events=[BlockStored(
        block_hashes=[10_000 + i], parent_block_hash=None,
        token_ids=tokens, block_size=BLOCK,
    )])
    return Message(
        topic=f"kv@pod-{i % 3}@{MODEL}", payload=batch.to_msgpack(),
        seq=i, pod_identifier=f"pod-{i % 3}", model_name=MODEL,
    )


def _mutate(payload: bytes, rng: random.Random) -> bytes:
    mode = rng.randrange(6)
    if mode == 0 and len(payload) > 2:  # truncate
        return payload[: rng.randrange(1, len(payload))]
    if mode == 1:  # flip random bytes
        b = bytearray(payload)
        for _ in range(rng.randint(1, 4)):
            b[rng.randrange(len(b))] ^= rng.randrange(1, 256)
        return bytes(b)
    if mode == 2:  # garbage prefix
        return bytes(rng.randrange(256) for _ in range(rng.randint(1, 8))) + payload
    if mode == 3:  # empty frame
        return b""
    import msgpack

    if mode == 4:  # valid msgpack, wrong structure: a map, not an array
        return msgpack.packb({"not": "an event batch", "n": rng.randrange(99)})
    # Tag confusion: decode the valid batch and corrupt the tagged-union
    # tag (unknown id, or a tag with the wrong payload arity).
    ts, events = msgpack.unpackb(payload, raw=False)
    if events and rng.random() < 0.5:
        events[0][0] = rng.choice([99, -1, "BlockStored", None])
    else:
        events = [[rng.randrange(3)]]  # known tag, missing payload
    return msgpack.packb([ts, events])


def test_mutated_frames_never_crash_and_good_traffic_lands():
    rng = random.Random(99)
    index = InMemoryIndex()
    tp = ChunkedTokenDatabase(TokenProcessorConfig(block_size=BLOCK))
    pool = EventPool(EventPoolConfig(concurrency=2), index, tp)
    pool.start(with_subscriber=False)
    good = []
    try:
        for i in range(120):
            msg = _good_message(i)
            if rng.random() < 0.5:
                good.append(msg)
                pool.add_task(msg)
            else:
                mutated = Message(
                    topic=msg.topic, payload=_mutate(msg.payload, rng),
                    seq=msg.seq, pod_identifier=msg.pod_identifier,
                    model_name=msg.model_name,
                )
                pool.add_task(mutated)
        pool.drain()
        assert all(t.is_alive() for t in pool._workers)

        # Every good batch landed under its pod.
        for msg in good:
            i = msg.seq
            keys = tp.tokens_to_kv_block_keys(
                None, list(range(i * BLOCK, (i + 1) * BLOCK)), MODEL
            )
            hits = index.lookup(keys, set())
            pods = {e.pod_identifier for e in hits.get(keys[0], [])}
            assert msg.pod_identifier in pods, f"good batch {i} lost"

        # Nothing landed for chains good traffic never stored: a mutated
        # frame that still decodes must not invent entries. (Byte flips
        # inside token_ids CAN yield a decodable batch with altered
        # tokens — those register under altered hashes; the invariant
        # checked here is that the KNOWN-unsent probe chain stays absent.)
        probe = tp.tokens_to_kv_block_keys(
            None, list(range(777_000, 777_000 + BLOCK)), MODEL
        )
        assert index.lookup(probe, set()) == {}

        # The pool keeps working after the flood.
        extra = _good_message(500)
        pool.add_task(extra)
        pool.drain()
        keys = tp.tokens_to_kv_block_keys(
            None, list(range(500 * BLOCK, 501 * BLOCK)), MODEL
        )
        assert index.lookup(keys, set())
    finally:
        pool.shutdown()


def test_duplicated_reordered_gapped_sequences_stay_consistent_and_detected():
    """Transport-level stream damage (duplication, adjacent reordering,
    seq gaps from dropped batches) must leave the pool/index consistent —
    stores are idempotent, every delivered batch lands — while the
    liveness tracker's per-topic seq monitoring counts each anomaly class.
    Deterministic: seeded RNG, drain() instead of sleeps."""
    from llm_d_kv_cache_manager_tpu.fleethealth import (
        FleetHealthConfig,
        FleetHealthTracker,
    )

    rng = random.Random(7)
    clock = [0.0]
    tracker = FleetHealthTracker(
        FleetHealthConfig(suspect_after_s=1e9, stale_after_s=1e9),
        clock=lambda: clock[0],
    )
    index = InMemoryIndex()
    tp = ChunkedTokenDatabase(TokenProcessorConfig(block_size=BLOCK))
    pool = EventPool(
        EventPoolConfig(concurrency=2), index, tp, health_tracker=tracker
    )
    pool.start(with_subscriber=False)

    # One pod's stream (ordering is per-pod), seq assigned at creation.
    msgs = [_good_message(i) for i in range(60)]
    for i, m in enumerate(msgs):
        m.pod_identifier = "pod-0"
        m.topic = f"kv@pod-0@{MODEL}"
        m.seq = i
    delivered = []
    expect = {"duplicates": 0, "reorders": 0, "drop_groups": 0, "dropped": 0}
    i = 0
    while i < len(msgs):
        roll = rng.random()
        if roll < 0.15:  # drop -> the next delivered seq opens a gap
            expect["drop_groups"] += 1
            expect["dropped"] += 1
            i += 1
            # Consecutive drops coalesce into one (wider) gap jump.
            while i < len(msgs) and rng.random() < 0.15:
                expect["dropped"] += 1
                i += 1
            continue
        if roll < 0.30 and i + 1 < len(msgs):  # adjacent swap
            delivered += [msgs[i + 1], msgs[i]]
            expect["reorders"] += 1
            i += 2
            continue
        if roll < 0.45:  # duplicate
            delivered += [msgs[i], msgs[i]]
            expect["duplicates"] += 1
            i += 1
            continue
        delivered.append(msgs[i])
        i += 1
    try:
        for m in delivered:
            pool.add_task(m)
        pool.drain()
        assert all(t.is_alive() for t in pool._workers)

        # Consistency: every delivered batch landed (duplicates idempotent,
        # reordering within one pod's stream cannot lose a store).
        for m in delivered:
            keys = tp.tokens_to_kv_block_keys(
                None, list(range(m.seq * BLOCK, (m.seq + 1) * BLOCK)), MODEL
            )
            hits = index.lookup(keys, set())
            pods = {e.pod_identifier for e in hits.get(keys[0], [])}
            assert "pod-0" in pods, f"batch seq={m.seq} lost"

        # Detection: duplicates and reorders have exact expected counts
        # (a swap [n+1, n] always registers exactly one seq-went-backwards
        # event). Gap counts are lower-bounded: every drop group opens a
        # jump > +1, but a swap ALSO opens one (n+1 arrives two past n-1),
        # so the tracker may legitimately count more gaps than drops.
        totals = tracker.anomaly_totals()
        assert totals["duplicates"] == expect["duplicates"]
        assert totals["reorders"] == expect["reorders"]
        assert totals["seq_gaps"] >= expect["drop_groups"]
        assert totals["gap_events"] >= expect["dropped"]
        assert expect["drop_groups"] > 0 and expect["duplicates"] > 0
        assert expect["reorders"] > 0  # the schedule exercised every class
    finally:
        pool.shutdown()

"""Hash-core tests: FNV vectors, canonical CBOR bytes, chained block keys.

The chained scheme must match the reference token processor
(/root/reference/pkg/kvcache/kvblock/token_processor.go:81-112):
FNV-64a(canonical_CBOR([parent, tokens, null])) chained per block.
"""

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import hashing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)


class TestFNV:
    # Published FNV-64a reference vectors.
    def test_empty(self):
        assert hashing.fnv64a(b"") == 0xCBF29CE484222325

    def test_a(self):
        assert hashing.fnv64a(b"a") == 0xAF63DC4C8601EC8C

    def test_foobar(self):
        assert hashing.fnv64a(b"foobar") == 0x85944171F73967E8

    def test_fnv32a(self):
        assert hashing.fnv32a(b"") == 0x811C9DC5
        assert hashing.fnv32a(b"a") == 0xE40C292C


class TestCanonicalCBOR:
    def test_small_payload_bytes(self):
        # [0, [1, 2, 3], null] in canonical CBOR, hand-encoded per RFC 8949.
        assert hashing.cbor_hash_payload(0, [1, 2, 3]) == bytes(
            [0x83, 0x00, 0x83, 0x01, 0x02, 0x03, 0xF6]
        )

    def test_integer_width_boundaries(self):
        # 23 → single byte; 24 → 0x18; 256 → 0x19 2B; 2^32 → 0x1b 8B.
        payload = hashing.cbor_hash_payload(23, [24, 256, 4294967296])
        assert payload == bytes(
            [0x83, 0x17, 0x83, 0x18, 24, 0x19, 0x01, 0x00, 0x1B]
            + list((4294967296).to_bytes(8, "big"))
            + [0xF6]
        )

    def test_u64_parent(self):
        payload = hashing.cbor_hash_payload(2**64 - 1, [])
        assert payload == bytes([0x83, 0x1B] + [0xFF] * 8 + [0x80, 0xF6])

    def test_long_token_array_header(self):
        # 30 tokens → array header 0x98 0x1e (1-byte length form).
        payload = hashing.cbor_hash_payload(0, list(range(30)))
        assert payload[2:4] == bytes([0x98, 0x1E])


class TestChaining:
    def test_init_hash_is_fnv_of_seed(self):
        assert hashing.init_hash("") == 0xCBF29CE484222325
        assert hashing.init_hash("42") == hashing.fnv64a(b"42")

    def test_chain_links(self):
        h1 = hashing.chunk_hash(hashing.init_hash(""), [1, 2])
        h2 = hashing.chunk_hash(h1, [3, 4])
        assert hashing.prefix_hashes(hashing.init_hash(""), [[1, 2], [3, 4]]) == [h1, h2]

    def test_chain_regression_values(self):
        # Pinned values: any change here silently breaks engine hash parity.
        root = hashing.init_hash("")
        assert hashing.chunk_hash(root, [1, 2, 3]) == hashing.fnv64a(
            hashing.cbor_hash_payload(root, [1, 2, 3])
        )

    def test_fast_path_matches_reference_path(self):
        tokens = list(range(100))
        root = hashing.init_hash("seed")
        fast = hashing.prefix_hashes_fast(root, tokens, 16)
        chunks = [tokens[i : i + 16] for i in range(0, 96, 16)]
        assert fast == hashing.prefix_hashes(root, chunks)
        assert len(fast) == 6  # partial tail block dropped


@pytest.mark.native
class TestNativeExtensionParity:
    # Skipped (visibly) by conftest's `native` marker handling when the
    # extension isn't built; deeper cross-checks live in
    # tests/test_hash_differential.py.
    def test_native_matches_pure_python(self):
        native = hashing._native
        import random

        rng = random.Random(0)
        for block_size in (1, 4, 16, 64):
            tokens = [rng.randrange(2**31) for _ in range(block_size * 7 + 3)]
            for seed in ("", "42"):
                root = hashing.init_hash(seed)
                chunks = [
                    tokens[i : i + block_size]
                    for i in range(0, (len(tokens) // block_size) * block_size, block_size)
                ]
                assert list(native.prefix_hashes(root, tokens, block_size)) == (
                    hashing.prefix_hashes(root, chunks)
                )
                assert list(
                    native.batch_prefix_hashes(root, tokens, block_size)
                ) == hashing.prefix_hashes(root, chunks)

    def test_native_fnv_vector(self):
        assert hashing._native.fnv64a(b"foobar") == 0x85944171F73967E8


class TestChunkedTokenDatabase:
    def test_partial_blocks_dropped(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=16))
        assert db.tokens_to_kv_block_keys(None, list(range(15)), "m") == []
        assert len(db.tokens_to_kv_block_keys(None, list(range(16)), "m")) == 1
        assert len(db.tokens_to_kv_block_keys(None, list(range(33)), "m")) == 2

    def test_parent_chain_continuation(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        tokens = list(range(8))
        full = db.tokens_to_kv_block_keys(None, tokens, "m")
        first = db.tokens_to_kv_block_keys(None, tokens[:4], "m")
        cont = db.tokens_to_kv_block_keys(first[0], tokens[4:], "m")
        assert full == first + cont

    def test_model_name_in_keys(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=2))
        keys = db.tokens_to_kv_block_keys(None, [1, 2], "modelA")
        assert keys[0].model_name == "modelA"

    def test_seed_changes_hashes(self):
        a = ChunkedTokenDatabase(TokenProcessorConfig(block_size=2, hash_seed=""))
        b = ChunkedTokenDatabase(TokenProcessorConfig(block_size=2, hash_seed="42"))
        assert a.tokens_to_kv_block_keys(None, [1, 2], "m") != b.tokens_to_kv_block_keys(
            None, [1, 2], "m"
        )

"""E2E scenario suite over a real ZMQ event loop + fake Redis backend.

Port of the reference's redis_mock e2e suite
(/root/reference/tests/e2e/redis_mock/e2e_test.go:109-936): a full Indexer
(block size 4, Redis-backed index against the in-process FakeRedisServer —
the miniredis analogue), fed by genuine msgpack KVEvents through the bound
ZMQ subscriber. Scenarios: cache hit/miss, prefix reduction/expansion,
long-prefix expansion, chat completions (single + multi-turn through the
real transformers templating path), local-tokenizer discovery variants
(HF-cache and plain layouts), composite fallback, error handling, event
eviction, and LoRA scoping (beyond the reference).
"""

import itertools
import os
import time
import uuid

import pytest

from tests.conftest import FIXTURES_DIR, TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from tests.fake_redis import FakeRedisServer
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
    RedisIndex,
    RedisIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher, make_topic
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)
from llm_d_kv_cache_manager_tpu.preprocessing.chat_completions import (
    ChatTemplatingProcessor,
    RenderRequest,
)

BLOCK_SIZE = 4
POD1 = "10.0.0.1"
POD2 = "10.0.0.2"

LOREM_FULL = (
    "lorem ipsum dolor sit amet, consectetur adipiscing elit. Sed do eiusmod "
    "tempor incididunt ut labore et dolore magna aliqua. Ut enim ad minim "
    "veniam, quis nostrud exercitation ullamco laboris nisi ut aliquip ex ea "
    "commodo consequat."
)
LOREM_MID = (
    "lorem ipsum dolor sit amet, consectetur adipiscing elit. Sed do eiusmod "
    "tempor incididunt ut labore et dolore magna aliqua."
)
LOREM_SHORT = "lorem ipsum dolor sit amet, consectetur adipiscing elit."

SIMPLE_TEMPLATE = (
    "{% for m in messages %}<|{{ m.role }}|>{{ m.content }}{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>{% endif %}"
)

_hash_counter = itertools.count(10_000)


class E2EEnv:
    """The suite fixture: indexer + Redis index + live ZMQ write plane."""

    def __init__(self, tmp_path, tokenizer_files=None):
        self.redis = FakeRedisServer()
        self.index = RedisIndex(RedisIndexConfig(url=self.redis.url))
        self.endpoint = f"ipc://{tmp_path}/e2e-{uuid.uuid4().hex[:8]}.sock"
        self.tokenization_pool = TokenizationPool(
            TokenizersPoolConfig(
                workers=2,
                local_tokenizer_files=(
                    tokenizer_files
                    if tokenizer_files is not None
                    else {TEST_MODEL_NAME: TEST_TOKENIZER_JSON}
                ),
            ),
            chat_templating=ChatTemplatingProcessor(),
        )
        self.indexer = Indexer(
            config=IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=BLOCK_SIZE),
                kv_block_index_config=IndexConfig(),
            ),
            tokenization_pool=self.tokenization_pool,
            kv_block_index=self.index,
        )
        self.indexer.run()
        self.event_pool = EventPool(
            EventPoolConfig(zmq_endpoint=self.endpoint, concurrency=2),
            self.index,
            self.indexer.token_processor,
        )
        self.event_pool.start(with_subscriber=True)
        self._publishers = {}

    def close(self):
        for p in self._publishers.values():
            p.close()
        self.event_pool.shutdown()
        self.indexer.shutdown()
        self.index.close()
        self.redis.close()

    # -- helpers -----------------------------------------------------------

    def tokens_for(self, prompt, model=TEST_MODEL_NAME):
        return self.tokenization_pool.tokenizer.encode(prompt, model).tokens

    def keys_for(self, prompt, model=TEST_MODEL_NAME, lora_id=None):
        return self.indexer.token_processor.tokens_to_kv_block_keys(
            None, self.tokens_for(prompt, model), model, lora_id=lora_id
        )

    def publisher(self, pod, model=TEST_MODEL_NAME):
        key = (pod, model)
        if key not in self._publishers:
            self._publishers[key] = Publisher(self.endpoint, make_topic(pod, model))
            time.sleep(0.3)  # ZMQ slow-joiner
        return self._publishers[key]

    def publish_cached(self, pod, prompt, model=TEST_MODEL_NAME, lora_id=None):
        """Publish BlockStored as the engine would for this prompt; returns
        the engine hashes used."""
        tokens = self.tokens_for(prompt, model)
        n_blocks = len(tokens) // BLOCK_SIZE
        engine_hashes = [next(_hash_counter) for _ in range(n_blocks)]
        self.publisher(pod, model).publish(EventBatch(
            ts=time.monotonic(),
            events=[BlockStored(
                engine_hashes, None, tokens[: n_blocks * BLOCK_SIZE],
                BLOCK_SIZE, lora_id=lora_id,
            )],
        ))
        return engine_hashes

    def publish_removed(self, pod, engine_hashes, model=TEST_MODEL_NAME):
        self.publisher(pod, model).publish(EventBatch(
            ts=time.monotonic(),
            events=[BlockRemoved(list(engine_hashes))],
        ))

    def scores(self, prompt, pods=(), model=TEST_MODEL_NAME, **kw):
        return self.indexer.get_pod_scores(prompt, model, list(pods), **kw)

    def wait_score(self, prompt, pod, min_score=1, timeout=10.0, **kw):
        deadline = time.time() + timeout
        while time.time() < deadline:
            s = self.scores(prompt, **kw)
            if s.get(pod, 0) >= min_score:
                return s
            time.sleep(0.05)
        raise AssertionError(
            f"{pod} never reached score {min_score}; last: {self.scores(prompt, **kw)}"
        )


@pytest.fixture
def env(tmp_path):
    e = E2EEnv(tmp_path)
    yield e
    e.close()


def _matching_prefix_len(keys_a, keys_b):
    n = 0
    for a, b in zip(keys_a, keys_b):
        if a != b:
            break
        n += 1
    return n


class TestCacheHitMiss:
    def test_cache_hit(self, env):
        env.publish_cached(POD1, LOREM_MID)
        scores = env.wait_score(LOREM_MID, POD1)
        assert scores[POD1] >= len(env.keys_for(LOREM_MID))

    def test_cache_miss(self, env):
        assert env.scores("What is the capital of France?", [POD1]) == {}

    def test_filtered_pod_set_excludes_other_pods(self, env):
        env.publish_cached(POD1, LOREM_MID)
        env.wait_score(LOREM_MID, POD1)
        assert POD1 not in env.scores(LOREM_MID, [POD2])


class TestPrefixScenarios:
    def test_prefix_reduction(self, env):
        # e2e_test.go:135-169: cache the FULL prompt, then query
        # progressively shorter prefixes — each still scores.
        assert env.scores(LOREM_FULL, [POD1]) == {}
        env.publish_cached(POD1, LOREM_FULL)
        env.wait_score(LOREM_FULL, POD1)

        full_keys = env.keys_for(LOREM_FULL)
        for prompt in (LOREM_MID, LOREM_SHORT):
            keys = env.keys_for(prompt)
            expected = _matching_prefix_len(keys, full_keys)
            assert expected > 0, "sub-prompt chains must share a prefix"
            scores = env.scores(prompt, [POD1])
            assert scores.get(POD1, 0) == expected

    def test_prefix_expansion(self, env):
        # e2e_test.go:171-205: cache short; a longer prompt scores exactly
        # the short chain; cache mid; full scores the mid chain.
        assert env.scores(LOREM_SHORT, [POD1]) == {}
        env.publish_cached(POD1, LOREM_SHORT)
        short_keys = env.keys_for(LOREM_SHORT)
        env.wait_score(LOREM_SHORT, POD1, min_score=len(short_keys))

        mid_keys = env.keys_for(LOREM_MID)
        assert env.scores(LOREM_MID, [POD1])[POD1] == _matching_prefix_len(
            mid_keys, short_keys
        )

        env.publish_cached(POD1, LOREM_MID)
        env.wait_score(LOREM_MID, POD1, min_score=len(mid_keys))
        full_keys = env.keys_for(LOREM_FULL)
        assert env.scores(LOREM_FULL, [POD1])[POD1] == _matching_prefix_len(
            full_keys, mid_keys
        )

    def test_long_prefix_expansion(self, env):
        # e2e_test.go:207-245 at ~4500-token scale.
        base = "The quick brown fox jumps over the lazy dog "
        short, mid, long_ = base * 2, base * 100, base * 500

        assert env.scores(short, [POD1]) == {}
        env.publish_cached(POD1, short)
        env.wait_score(mid, POD1)

        env.publish_cached(POD1, mid)
        mid_keys = env.keys_for(mid)
        # The read path serves prefix-store tokens at >=0.8 coverage (the
        # latency/exactness trade both we and the reference make), so the
        # score floor is 80% of the chain, not 100%.
        floor = int(len(mid_keys) * 0.8)
        env.wait_score(mid, POD1, min_score=floor)
        scores = env.scores(long_, [POD1])
        assert scores[POD1] >= floor


class TestChatCompletions:
    def _render_request(self, messages):
        return RenderRequest(
            conversations=[messages], chat_template=SIMPLE_TEMPLATE
        )

    def test_single_turn(self, env):
        # e2e_test.go:247-305: score via the real templating path, publish
        # the rendered prompt's blocks, score again — cache hit.
        messages = [{"role": "user", "content": "What is the capital of France? " * 8}]
        req = self._render_request(messages)
        assert env.scores("", render_request=req) == {}

        rendered = env.tokenization_pool.tokenizer.render_chat_template(req)
        assert rendered.startswith("<|user|>")
        env.publish_cached(POD1, rendered)
        env.wait_score("", POD1, render_request=req)

    def test_multi_turn_extends_prefix(self, env):
        # e2e_test.go:688-804: each turn extends the conversation; the next
        # turn's score grows with the shared rendered prefix.
        messages = [
            {"role": "system", "content": "You are a terse assistant. " * 6},
            {"role": "user", "content": "First question, with enough words to fill blocks?"},
        ]
        req1 = self._render_request(messages)
        rendered1 = env.tokenization_pool.tokenizer.render_chat_template(req1)
        env.publish_cached(POD1, rendered1)
        score1 = env.wait_score("", POD1, render_request=req1)[POD1]

        messages2 = messages + [
            {"role": "assistant", "content": "First answer."},
            {"role": "user", "content": "Second question?"},
        ]
        req2 = self._render_request(messages2)
        rendered2 = env.tokenization_pool.tokenizer.render_chat_template(req2)
        assert rendered2.startswith(rendered1[: len(rendered1) - 40])
        env.publish_cached(POD1, rendered2)
        score2 = env.wait_score(
            "", POD1, min_score=int(score1) + 1, render_request=req2
        )[POD1]
        assert score2 > score1


class TestTokenizerDiscovery:
    def test_hf_cache_layout_discovery(self, env, tmp_path, monkeypatch):
        # e2e_test.go:478-530: models--org--name/snapshots/<rev>/ resolves
        # to model "org/name".
        root = tmp_path / "hub"
        snap = root / "models--acme--chatty" / "snapshots" / "abc123"
        snap.mkdir(parents=True)
        with open(TEST_TOKENIZER_JSON, "rb") as f:
            (snap / "tokenizer.json").write_bytes(f.read())
        monkeypatch.setenv("LOCAL_TOKENIZER_DIR", str(root))

        pool = TokenizationPool(TokenizersPoolConfig(workers=1))
        pool.run()
        try:
            tokens = pool.tokenize(None, LOREM_SHORT, "acme/chatty")
            assert tokens == env.tokens_for(LOREM_SHORT)
        finally:
            pool.shutdown()

    def test_mixed_directory_layout_discovery(self, env, tmp_path, monkeypatch):
        # e2e_test.go:532-592: plain relative-dir layout next to HF-cache.
        root = tmp_path / "models"
        plain = root / "plainmodel"
        plain.mkdir(parents=True)
        with open(TEST_TOKENIZER_JSON, "rb") as f:
            (plain / "tokenizer.json").write_bytes(f.read())
        monkeypatch.setenv("LOCAL_TOKENIZER_DIR", str(root))

        pool = TokenizationPool(TokenizersPoolConfig(workers=1))
        pool.run()
        try:
            tokens = pool.tokenize(None, LOREM_SHORT, "plainmodel")
            assert tokens == env.tokens_for(LOREM_SHORT)
        finally:
            pool.shutdown()


class TestCompositeFallbackE2E:
    def test_uds_down_falls_back_to_local(self, env, tmp_path):
        # e2e_test.go:426-476 analogue: first backend dead (UDS socket that
        # doesn't exist), local backend serves, scoring works end to end.
        pool = TokenizationPool(TokenizersPoolConfig(
            workers=1,
            enable_uds=True,
            uds_socket_path=str(tmp_path / "no-such.sock"),
            local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
        ))
        # Order is local → UDS → HF, so force the failing one first.
        pool.tokenizer.backends.reverse()
        pool.run()
        try:
            tokens = pool.tokenize(None, LOREM_SHORT, TEST_MODEL_NAME)
            assert tokens == env.tokens_for(LOREM_SHORT)
        finally:
            pool.shutdown()


class TestErrorHandling:
    def test_unknown_model_raises_cleanly(self, env):
        with pytest.raises(Exception, match="no-such-model"):
            env.scores(LOREM_SHORT, [POD1], model="no-such-model")

    def test_malformed_event_does_not_poison_the_loop(self, env):
        # Reference poison-pill semantics (kvevents/pool.go:182-187): a
        # garbage frame is dropped; later events still index.
        pub = env.publisher(POD1)
        pub._socket.send_multipart(
            [f"kv@{POD1}@{TEST_MODEL_NAME}".encode(), b"\x00" * 8, b"not msgpack"]
        )
        env.publish_cached(POD1, LOREM_MID)
        env.wait_score(LOREM_MID, POD1)

    def test_chat_template_error_surfaces(self, env):
        # e2e_test.go:895-934: a broken template is an error, not a hang.
        req = RenderRequest(
            conversations=[[{"role": "user", "content": "hi"}]],
            chat_template="{{ undefined_fn() }}",
        )
        with pytest.raises(Exception):
            env.scores("", render_request=req)


class TestPrefixStoreSelection:
    def test_trie_store_is_config_reachable_end_to_end(self, monkeypatch):
        # VERDICT r1 weak #8: the LRU-vs-trie choice must be reachable
        # through IndexerConfig the way index backends are — the Indexer
        # builds its own pool (tokenizers via LOCAL_TOKENIZER_DIR
        # discovery) so the configured store type actually takes effect.
        from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.indexer import (
            PrefixStoreConfig,
        )
        from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.trie_store import (
            TrieTokenStore,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )

        monkeypatch.setenv("LOCAL_TOKENIZER_DIR", FIXTURES_DIR)
        indexer = Indexer(config=IndexerConfig(
            prefix_store_config=PrefixStoreConfig(store_type="trie"),
            token_processor_config=TokenProcessorConfig(block_size=BLOCK_SIZE),
        ), kv_block_index=InMemoryIndex())
        # The configured store type actually materialized as a trie.
        assert isinstance(indexer.prefix_store, TrieTokenStore)
        indexer.run()
        try:
            tokens = indexer.tokenizers_pool.tokenize(None, LOREM_MID, TEST_MODEL_NAME)
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                None, tokens, TEST_MODEL_NAME
            )
            from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry

            indexer.kv_block_index.add(keys, keys, [PodEntry(POD1, "hbm")])
            scores = indexer.get_pod_scores(LOREM_MID, TEST_MODEL_NAME, [POD1])
            assert scores.get(POD1, 0) >= len(keys) * 0.8
            # Second query rides the trie prefix store (coverage >= 0.8).
            scores2 = indexer.get_pod_scores(LOREM_MID, TEST_MODEL_NAME, [POD1])
            assert scores2.get(POD1, 0) >= len(keys) * 0.8
        finally:
            indexer.shutdown()


class TestEvictionAndLoRA:
    def test_block_removed_drops_score(self, env):
        hashes = env.publish_cached(POD1, LOREM_MID)
        keys = env.keys_for(LOREM_MID)
        env.wait_score(LOREM_MID, POD1, min_score=len(keys))
        # Remove the whole chain; score must collapse to empty.
        env.publish_removed(POD1, hashes)
        deadline = time.time() + 10
        while time.time() < deadline:
            if env.scores(LOREM_MID, [POD1]) == {}:
                break
            time.sleep(0.05)
        assert env.scores(LOREM_MID, [POD1]) == {}

    def test_lora_scoped_cache_is_disjoint(self, env):
        # Beyond the reference (its LoRA parity test is a skipped TODO):
        # blocks cached under an adapter only score for that adapter.
        env.publish_cached(POD1, LOREM_MID, lora_id=7)
        env.wait_score(LOREM_MID, POD1, lora_id=7)
        assert env.scores(LOREM_MID, [POD1]) == {}  # base keyspace: miss
        assert env.scores(LOREM_MID, [POD1], lora_id=8) == {}  # other adapter

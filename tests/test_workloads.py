"""The trace-driven workload subsystem (ISSUE 1 tentpole).

Three contracts, each load-bearing for the BASELINE metric ("prefix-cache
hit-rate + p50 TTFT, ShareGPT replay"):

1. **Distribution fidelity** — the sharegpt generator's empirical
   prompt-length / output-length / turns-per-session distributions match
   the committed tables (workloads/tables.py) within KS/TV tolerance, and
   the validator actually rejects wrong distributions (a validator that
   passes everything would let the headline workload silently drift).
2. **Determinism + record/replay** — same config → identical trace; the
   JSONL round-trip is bit-identical; materialized prompt streams are
   equal across replays (the sim bench and device harness serve the same
   bytes from the same file).
3. **The growth mechanism creates hits** — a sim-bench smoke run's prefix
   hit rate in sharegpt mode must beat the single-turn prefix-free
   uniform control: multi-turn concatenation is WHY a trace-driven
   workload can measure cache-aware routing at all.
"""

import dataclasses
import importlib.util
import io
import pathlib

import pytest

from llm_d_kv_cache_manager_tpu.workloads import (
    ShareGPTConfig,
    generate,
    read_trace,
    uniform_control,
    write_trace,
)
from llm_d_kv_cache_manager_tpu.workloads import stats, tables
from llm_d_kv_cache_manager_tpu.workloads.arrivals import (
    on_off_arrivals,
    poisson_arrivals,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


class TestDistributionFidelity:
    def test_sharegpt_matches_committed_tables(self):
        trace = generate(ShareGPTConfig(n_sessions=400, seed=11))
        report = stats.validate_trace(trace)
        assert report.ok, report.as_dict()
        # Sanity on sample size: 400 sessions at mean ~4 turns must yield
        # a four-digit turn sample, or the KS check is underpowered.
        assert len(trace.turns) > 1000

    def test_validator_rejects_wrong_length_distribution(self):
        trace = generate(ShareGPTConfig(n_sessions=200, seed=5))
        bad = dataclasses.replace(
            trace,
            turns=[dataclasses.replace(t, user_len=100) for t in trace.turns],
        )
        report = stats.validate_trace(bad)
        assert not report.ok
        with pytest.raises(ValueError, match="user_len"):
            report.raise_if_failed()

    def test_validator_rejects_wrong_turn_distribution(self):
        trace = generate(ShareGPTConfig(n_sessions=200, seed=5))
        # Every session flattened to one turn (keep only turn 0) while the
        # header still claims the table-faithful config.
        bad = dataclasses.replace(
            trace, turns=[t for t in trace.turns if t.turn == 0]
        )
        assert not stats.validate_trace(bad).ok

    def test_max_turns_cap_is_folded_not_flagged(self):
        trace = generate(ShareGPTConfig(n_sessions=300, seed=3, max_turns=4))
        assert max(trace.turn_counts().values()) <= 4
        report = stats.validate_trace(trace)
        assert report.ok, report.as_dict()

    def test_tables_version_mismatch_is_loud(self):
        trace = generate(ShareGPTConfig(n_sessions=4, seed=1))
        stale = dataclasses.replace(trace, tables_version="sharegpt-v0")
        with pytest.raises(ValueError, match="tables"):
            stats.validate_trace(stale)

    def test_prefix_mix_share(self):
        trace = generate(ShareGPTConfig(n_sessions=400, seed=2))
        with_prefix = sum(1 for s in trace.sessions.values() if s)
        share = with_prefix / len(trace.sessions)
        assert abs(share - tables.SYSTEM_PREFIX_SHARE) < 0.1
        # Prefixes come from a bounded group set: sessions actually SHARE
        # them (the reuse structure), rather than each getting fresh text.
        distinct = {s for s in trace.sessions.values() if s}
        assert len(distinct) <= ShareGPTConfig().prefix_groups


class TestDeterminismAndRoundTrip:
    def test_same_seed_same_trace(self):
        cfg = ShareGPTConfig(n_sessions=30, seed=9)
        assert generate(cfg) == generate(cfg)

    def test_different_seed_different_trace(self):
        assert generate(ShareGPTConfig(n_sessions=30, seed=9)) != generate(
            ShareGPTConfig(n_sessions=30, seed=10)
        )

    def test_jsonl_roundtrip_is_bit_identical(self, tmp_path):
        trace = generate(ShareGPTConfig(n_sessions=25, seed=4, arrival="bursty"))
        path = tmp_path / "trace.jsonl"
        write_trace(trace, str(path))
        replayed = read_trace(str(path))
        assert replayed == trace
        # Re-serializing the replayed trace reproduces the file byte for
        # byte — the canonical-form property that makes traces diffable
        # and committable.
        buf = io.StringIO()
        write_trace(replayed, buf)
        assert buf.getvalue() == path.read_text(encoding="utf-8")

    def test_materialized_request_streams_are_identical(self, tmp_path):
        cfg = ShareGPTConfig(n_sessions=12, seed=8)
        path = tmp_path / "t.jsonl"
        write_trace(generate(cfg), str(path))
        a = [(r.arrival_s, r.prompt, r.output_len)
             for r in read_trace(str(path)).materialize()]
        b = [(r.arrival_s, r.prompt, r.output_len)
             for r in read_trace(str(path)).materialize()]
        c = [(r.arrival_s, r.prompt, r.output_len)
             for r in generate(cfg).materialize()]
        assert a == b == c

    def test_multi_turn_prompts_grow_by_concatenation(self):
        trace = generate(ShareGPTConfig(n_sessions=40, seed=6))
        last_prompt = {}
        grown = 0
        for r in trace.materialize():
            if r.turn > 0:
                # Turn t's prompt must literally extend turn t-1's — the
                # prefix-cache-hit mechanism under test.
                assert r.prompt.startswith(last_prompt[r.session])
                grown += 1
            last_prompt[r.session] = r.prompt
        assert grown > 0  # the workload actually contains multi-turn growth

    def test_unknown_kind_and_missing_header_error(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"kind": "mystery"}\n')
        with pytest.raises(ValueError, match="header|kind"):
            read_trace(str(bad))


class TestArrivals:
    def test_poisson_mean_rate(self):
        import random

        gen = poisson_arrivals(random.Random(0), rate_per_s=5.0)
        times = [next(gen) for _ in range(2000)]
        rate = len(times) / times[-1]
        assert 4.0 < rate < 6.0

    def test_bursty_preserves_mean_rate_and_has_silent_windows(self):
        import random

        gen = on_off_arrivals(random.Random(0), rate_per_s=5.0,
                              on_s=5.0, off_s=10.0)
        times = [next(gen) for _ in range(2000)]
        rate = len(times) / times[-1]
        assert 4.0 < rate < 6.0
        gaps = [b - a for a, b in zip(times, times[1:])]
        # OFF windows show up as gaps of at least off_s.
        assert max(gaps) >= 10.0
        # And the ON windows burst well above the mean rate.
        assert sorted(gaps)[len(gaps) // 2] < 1.0 / 5.0


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod_workloads", REPO / "bench.py"
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


class TestSimBenchShareGPT:
    def test_multi_turn_growth_creates_hits_vs_uniform_control(self):
        """Sim-bench smoke in sharegpt mode: the precise arm's prefix hit
        rate on ShareGPT-shaped multi-turn traffic must clearly beat the
        same generator with growth and shared prefixes removed — if it
        doesn't, the trace isn't exercising the mechanism the BASELINE
        metric measures."""
        bench = _load_bench()
        cfg = ShareGPTConfig(
            n_sessions=8, seed=13, max_turns=4, length_scale=0.3,
            prefix_groups=4,
        )
        sharegpt_reqs = generate(cfg).requests()
        uniform_reqs = uniform_control(cfg).requests()

        _, hit_sharegpt, _, _ = bench.run_sharegpt_strategy(
            "precise", sharegpt_reqs
        )
        _, hit_uniform, _, _ = bench.run_sharegpt_strategy(
            "precise", uniform_reqs
        )
        assert hit_sharegpt > hit_uniform + 0.2, (
            f"sharegpt={hit_sharegpt:.3f} uniform={hit_uniform:.3f}"
        )
        assert hit_uniform < 0.1  # the control really is reuse-free


class TestGeoWorkload:
    """workloads/geo.py: home-pinned sessions, diurnal skew, and the
    trace schema's optional `region` field with strict back-compat."""

    def test_deterministic_and_home_pinned(self):
        from llm_d_kv_cache_manager_tpu.workloads import (
            GeoConfig,
            generate_geo,
        )

        cfg = GeoConfig(n_sessions=50, seed=7)
        trace = generate_geo(cfg)
        assert generate_geo(cfg) == trace
        # Every session carries exactly one home region from the
        # configured set, and every materialized request inherits it.
        names = {f"region-{r}" for r in range(cfg.n_regions)}
        assert set(trace.session_regions) == set(trace.sessions)
        assert set(trace.session_regions.values()) <= names
        for req in trace.materialize():
            assert req.region == trace.session_regions[req.session]

    def test_diurnal_skew_shifts_regional_peaks(self):
        from llm_d_kv_cache_manager_tpu.workloads import (
            GeoConfig,
            diurnal_weights,
            generate_geo,
        )

        cfg = GeoConfig(
            n_sessions=240, seed=3, diurnal_amplitude=0.9,
            day_period_s=60.0, session_rate_per_s=8.0,
        )
        trace = generate_geo(cfg)
        # Each region's sessions concentrate in its own phase window:
        # the mean within-day phase of each region's session starts must
        # track the region's peak (circular mean within half a period).
        import math

        starts = {}
        for sid, region in trace.session_regions.items():
            first = min(
                t.arrival_s for t in trace.turns if t.session == sid
            )
            starts.setdefault(region, []).append(first)
        for r in range(cfg.n_regions):
            region = f"region-{r}"
            if len(starts.get(region, [])) < 10:
                continue
            xs = [
                2 * math.pi * (t / cfg.day_period_s)
                for t in starts[region]
            ]
            mean_phase = math.atan2(
                sum(math.sin(x) for x in xs) / len(xs),
                sum(math.cos(x) for x in xs) / len(xs),
            ) % (2 * math.pi)
            peak = (2 * math.pi * (0.25 + r / cfg.n_regions)) % (
                2 * math.pi
            )
            dist = min(
                abs(mean_phase - peak), 2 * math.pi - abs(mean_phase - peak)
            )
            assert dist < math.pi / 2, (
                f"{region}: mean phase {mean_phase:.2f} far from its "
                f"peak {peak:.2f}"
            )
        # Amplitude 0 is the uniform control: no region starves.
        flat = generate_geo(GeoConfig(
            n_sessions=240, seed=3, diurnal_amplitude=0.0,
            session_rate_per_s=8.0,
        ))
        counts = {}
        for region in flat.session_regions.values():
            counts[region] = counts.get(region, 0) + 1
        assert min(counts.values()) > 240 / (flat.config["n_regions"] * 3)

    def test_geo_trace_roundtrip_bit_identical(self, tmp_path):
        from llm_d_kv_cache_manager_tpu.workloads import (
            GeoConfig,
            generate_geo,
        )

        trace = generate_geo(GeoConfig(n_sessions=20, seed=5))
        path = tmp_path / "geo.jsonl"
        write_trace(trace, str(path))
        replayed = read_trace(str(path))
        assert replayed == trace
        assert replayed.session_regions == trace.session_regions
        buf = io.StringIO()
        write_trace(replayed, buf)
        assert buf.getvalue() == path.read_text(encoding="utf-8")

    def test_pre_region_trace_replays_unchanged(self, tmp_path):
        """A trace recorded before this PR (no `region` keys) parses with
        empty session_regions, materializes with region=None, and
        re-serializes byte-identically — the strict back-compat pin."""
        old = "\n".join([
            '{"config": {}, "kind": "header", '
            '"schema": "kvtpu-workload-trace/v1", "seed": 1, '
            '"tables_version": "sharegpt-v1", "workload": "sharegpt"}',
            '{"id": "s0", "kind": "session", '
            '"system_prefix": "hello world"}',
            '{"arrival_s": 0.5, "kind": "turn", "output_len": 2, '
            '"response_text": "ok there", "session": "s0", "turn": 0, '
            '"user_len": 1, "user_text": "hi"}',
        ]) + "\n"
        path = tmp_path / "old.jsonl"
        path.write_text(old, encoding="utf-8")
        trace = read_trace(str(path))
        assert trace.session_regions == {}
        reqs = trace.requests()
        assert [r.region for r in reqs] == [None]
        buf = io.StringIO()
        write_trace(trace, buf)
        assert buf.getvalue() == old

    def test_region_survives_record_replay(self, tmp_path):
        """Old writer ∘ new reader is covered above; this is new writer ∘
        new reader: the region pin must survive a full record/replay and
        reach the replayed MaterializedRequests."""
        from llm_d_kv_cache_manager_tpu.workloads import (
            GeoConfig,
            generate_geo,
        )

        trace = generate_geo(GeoConfig(n_sessions=10, seed=2))
        path = tmp_path / "geo.jsonl"
        write_trace(trace, str(path))
        for req in read_trace(str(path)).materialize():
            assert req.region == trace.session_regions[req.session]


class TestAgenticWorkload:
    """workloads/agentic.py: branching fan-out/fan-in trace generator."""

    def _trace(self, **kw):
        from llm_d_kv_cache_manager_tpu.workloads import (
            AgenticConfig,
            generate_agentic,
        )

        defaults = dict(n_tasks=4, seed=11)
        defaults.update(kw)
        return generate_agentic(AgenticConfig(**defaults))

    def test_deterministic_in_config_and_seed(self):
        assert self._trace() == self._trace()
        assert self._trace(seed=12) != self._trace(seed=11)

    def test_record_replay_round_trip(self):
        import io

        from llm_d_kv_cache_manager_tpu.workloads import (
            read_trace,
            write_trace,
        )

        trace = self._trace()
        buf = io.StringIO()
        write_trace(trace, buf)
        buf.seek(0)
        replayed = read_trace(buf)
        assert replayed == trace
        # The materialized prompt streams are identical too.
        assert [r.prompt for r in replayed.materialize()] == [
            r.prompt for r in trace.materialize()
        ]

    def test_structure_fan_out_fan_in(self):
        from llm_d_kv_cache_manager_tpu.workloads import is_root, task_of

        cfg = dict(n_tasks=3, n_phases=2, fan_out=3, subagent_turns=2)
        trace = self._trace(**cfg)
        roots = [s for s in trace.sessions if is_root(s)]
        workers = [s for s in trace.sessions if not is_root(s)]
        assert len(roots) == 3
        assert len(workers) == 3 * 2 * 3  # tasks x phases x fan_out
        assert {task_of(s) for s in trace.sessions} == {0, 1, 2}
        counts = trace.turn_counts()
        for r in roots:
            assert counts[r] == 1 + 2  # planning + one synthesis per phase
        for w in workers:
            assert counts[w] == 2

    def test_workers_branch_off_the_root_grown_prompt(self):
        """A sub-agent's system prefix IS the root conversation at its
        branch point — the shared-prefix containment every prefix plane
        (and the session predictor's continuation detection) keys on."""
        trace = self._trace(n_tasks=2, n_phases=2)
        reqs = {(r.session, r.turn): r for r in trace.materialize()}
        for k in range(2):
            root_prefix = trace.sessions[f"a{k}-root"]
            # Phase-0 workers extend the root's turn-0 grown prompt...
            p0 = trace.sessions[f"a{k}-p0-w0"]
            assert p0.startswith(root_prefix)
            assert reqs[(f"a{k}-root", 0)].prompt == p0[: len(
                reqs[(f"a{k}-root", 0)].prompt
            )]
            # ...and phase-1 workers extend the longer post-synthesis one.
            p1 = trace.sessions[f"a{k}-p1-w0"]
            assert p1.startswith(p0)
            assert len(p1) > len(p0)
            # All same-phase siblings share the exact branch prefix.
            assert trace.sessions[f"a{k}-p0-w1"] == p0
            assert trace.sessions[f"a{k}-p0-w2"] == p0

    def test_tool_loop_gaps_are_short_and_ordered(self):
        trace = self._trace(n_tasks=2, tool_latency_mean_s=1.0)
        arrivals = {}
        for t in trace.turns:
            arrivals.setdefault(t.session, []).append(t.arrival_s)
        for session, times in arrivals.items():
            assert times == sorted(times)
            if "-w" in session:
                gaps = [b - a for a, b in zip(times, times[1:])]
                # Exponential around the 1s tool latency: well under the
                # multi-second human think times of the chat workloads.
                assert all(g < 15.0 for g in gaps)

    def test_header_carries_config_provenance(self):
        trace = self._trace()
        assert trace.workload == "agentic"
        assert trace.config["n_tasks"] == 4
        assert trace.config["fan_out"] == 3
        # Arrival order is globally sorted with a total tie-break.
        key = trace.sorted_key()
        assert key == sorted(key)

    def test_invalid_shapes_rejected(self):
        import pytest

        with pytest.raises(ValueError):
            self._trace(n_tasks=0)
        with pytest.raises(ValueError):
            self._trace(fan_out=0)
        with pytest.raises(ValueError):
            self._trace(subagent_turns=0)

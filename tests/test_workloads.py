"""The trace-driven workload subsystem (ISSUE 1 tentpole).

Three contracts, each load-bearing for the BASELINE metric ("prefix-cache
hit-rate + p50 TTFT, ShareGPT replay"):

1. **Distribution fidelity** — the sharegpt generator's empirical
   prompt-length / output-length / turns-per-session distributions match
   the committed tables (workloads/tables.py) within KS/TV tolerance, and
   the validator actually rejects wrong distributions (a validator that
   passes everything would let the headline workload silently drift).
2. **Determinism + record/replay** — same config → identical trace; the
   JSONL round-trip is bit-identical; materialized prompt streams are
   equal across replays (the sim bench and device harness serve the same
   bytes from the same file).
3. **The growth mechanism creates hits** — a sim-bench smoke run's prefix
   hit rate in sharegpt mode must beat the single-turn prefix-free
   uniform control: multi-turn concatenation is WHY a trace-driven
   workload can measure cache-aware routing at all.
"""

import dataclasses
import importlib.util
import io
import pathlib

import pytest

from llm_d_kv_cache_manager_tpu.workloads import (
    ShareGPTConfig,
    generate,
    read_trace,
    uniform_control,
    write_trace,
)
from llm_d_kv_cache_manager_tpu.workloads import stats, tables
from llm_d_kv_cache_manager_tpu.workloads.arrivals import (
    on_off_arrivals,
    poisson_arrivals,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


class TestDistributionFidelity:
    def test_sharegpt_matches_committed_tables(self):
        trace = generate(ShareGPTConfig(n_sessions=400, seed=11))
        report = stats.validate_trace(trace)
        assert report.ok, report.as_dict()
        # Sanity on sample size: 400 sessions at mean ~4 turns must yield
        # a four-digit turn sample, or the KS check is underpowered.
        assert len(trace.turns) > 1000

    def test_validator_rejects_wrong_length_distribution(self):
        trace = generate(ShareGPTConfig(n_sessions=200, seed=5))
        bad = dataclasses.replace(
            trace,
            turns=[dataclasses.replace(t, user_len=100) for t in trace.turns],
        )
        report = stats.validate_trace(bad)
        assert not report.ok
        with pytest.raises(ValueError, match="user_len"):
            report.raise_if_failed()

    def test_validator_rejects_wrong_turn_distribution(self):
        trace = generate(ShareGPTConfig(n_sessions=200, seed=5))
        # Every session flattened to one turn (keep only turn 0) while the
        # header still claims the table-faithful config.
        bad = dataclasses.replace(
            trace, turns=[t for t in trace.turns if t.turn == 0]
        )
        assert not stats.validate_trace(bad).ok

    def test_max_turns_cap_is_folded_not_flagged(self):
        trace = generate(ShareGPTConfig(n_sessions=300, seed=3, max_turns=4))
        assert max(trace.turn_counts().values()) <= 4
        report = stats.validate_trace(trace)
        assert report.ok, report.as_dict()

    def test_tables_version_mismatch_is_loud(self):
        trace = generate(ShareGPTConfig(n_sessions=4, seed=1))
        stale = dataclasses.replace(trace, tables_version="sharegpt-v0")
        with pytest.raises(ValueError, match="tables"):
            stats.validate_trace(stale)

    def test_prefix_mix_share(self):
        trace = generate(ShareGPTConfig(n_sessions=400, seed=2))
        with_prefix = sum(1 for s in trace.sessions.values() if s)
        share = with_prefix / len(trace.sessions)
        assert abs(share - tables.SYSTEM_PREFIX_SHARE) < 0.1
        # Prefixes come from a bounded group set: sessions actually SHARE
        # them (the reuse structure), rather than each getting fresh text.
        distinct = {s for s in trace.sessions.values() if s}
        assert len(distinct) <= ShareGPTConfig().prefix_groups


class TestDeterminismAndRoundTrip:
    def test_same_seed_same_trace(self):
        cfg = ShareGPTConfig(n_sessions=30, seed=9)
        assert generate(cfg) == generate(cfg)

    def test_different_seed_different_trace(self):
        assert generate(ShareGPTConfig(n_sessions=30, seed=9)) != generate(
            ShareGPTConfig(n_sessions=30, seed=10)
        )

    def test_jsonl_roundtrip_is_bit_identical(self, tmp_path):
        trace = generate(ShareGPTConfig(n_sessions=25, seed=4, arrival="bursty"))
        path = tmp_path / "trace.jsonl"
        write_trace(trace, str(path))
        replayed = read_trace(str(path))
        assert replayed == trace
        # Re-serializing the replayed trace reproduces the file byte for
        # byte — the canonical-form property that makes traces diffable
        # and committable.
        buf = io.StringIO()
        write_trace(replayed, buf)
        assert buf.getvalue() == path.read_text(encoding="utf-8")

    def test_materialized_request_streams_are_identical(self, tmp_path):
        cfg = ShareGPTConfig(n_sessions=12, seed=8)
        path = tmp_path / "t.jsonl"
        write_trace(generate(cfg), str(path))
        a = [(r.arrival_s, r.prompt, r.output_len)
             for r in read_trace(str(path)).materialize()]
        b = [(r.arrival_s, r.prompt, r.output_len)
             for r in read_trace(str(path)).materialize()]
        c = [(r.arrival_s, r.prompt, r.output_len)
             for r in generate(cfg).materialize()]
        assert a == b == c

    def test_multi_turn_prompts_grow_by_concatenation(self):
        trace = generate(ShareGPTConfig(n_sessions=40, seed=6))
        last_prompt = {}
        grown = 0
        for r in trace.materialize():
            if r.turn > 0:
                # Turn t's prompt must literally extend turn t-1's — the
                # prefix-cache-hit mechanism under test.
                assert r.prompt.startswith(last_prompt[r.session])
                grown += 1
            last_prompt[r.session] = r.prompt
        assert grown > 0  # the workload actually contains multi-turn growth

    def test_unknown_kind_and_missing_header_error(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"kind": "mystery"}\n')
        with pytest.raises(ValueError, match="header|kind"):
            read_trace(str(bad))


class TestArrivals:
    def test_poisson_mean_rate(self):
        import random

        gen = poisson_arrivals(random.Random(0), rate_per_s=5.0)
        times = [next(gen) for _ in range(2000)]
        rate = len(times) / times[-1]
        assert 4.0 < rate < 6.0

    def test_bursty_preserves_mean_rate_and_has_silent_windows(self):
        import random

        gen = on_off_arrivals(random.Random(0), rate_per_s=5.0,
                              on_s=5.0, off_s=10.0)
        times = [next(gen) for _ in range(2000)]
        rate = len(times) / times[-1]
        assert 4.0 < rate < 6.0
        gaps = [b - a for a, b in zip(times, times[1:])]
        # OFF windows show up as gaps of at least off_s.
        assert max(gaps) >= 10.0
        # And the ON windows burst well above the mean rate.
        assert sorted(gaps)[len(gaps) // 2] < 1.0 / 5.0


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod_workloads", REPO / "bench.py"
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


class TestSimBenchShareGPT:
    def test_multi_turn_growth_creates_hits_vs_uniform_control(self):
        """Sim-bench smoke in sharegpt mode: the precise arm's prefix hit
        rate on ShareGPT-shaped multi-turn traffic must clearly beat the
        same generator with growth and shared prefixes removed — if it
        doesn't, the trace isn't exercising the mechanism the BASELINE
        metric measures."""
        bench = _load_bench()
        cfg = ShareGPTConfig(
            n_sessions=8, seed=13, max_turns=4, length_scale=0.3,
            prefix_groups=4,
        )
        sharegpt_reqs = generate(cfg).requests()
        uniform_reqs = uniform_control(cfg).requests()

        _, hit_sharegpt, _, _ = bench.run_sharegpt_strategy(
            "precise", sharegpt_reqs
        )
        _, hit_uniform, _, _ = bench.run_sharegpt_strategy(
            "precise", uniform_reqs
        )
        assert hit_sharegpt > hit_uniform + 0.2, (
            f"sharegpt={hit_sharegpt:.3f} uniform={hit_uniform:.3f}"
        )
        assert hit_uniform < 0.1  # the control really is reuse-free

"""Qwen2-family support: the Llama decoder plus additive q/k/v biases.

Parity is pinned against transformers' Qwen2ForCausalLM — a third-party
reference implementation — both for the dense forward (bias math, tied
embeddings, RoPE theta) and for the full paged serving stack (biases must
flow through prefill, batched decode, and the multi-step loop
identically). Mirrors tests/test_hf_loader.py's role for Llama/Mixtral.
"""

import importlib.util

import jax.numpy as jnp
import numpy as np
import pytest

if importlib.util.find_spec("torch") is None or (
    importlib.util.find_spec("transformers") is None
):
    pytest.skip("torch/transformers not installed", allow_module_level=True)

import torch
from transformers import Qwen2Config as HFQwen2Config
from transformers import Qwen2ForCausalLM

from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.models.hf_loader import (
    config_from_hf,
    params_from_hf,
)


def _tiny_qwen2(tie=False, n_q=4, n_kv=2, seed=0):
    hf_cfg = HFQwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=n_q,
        num_key_value_heads=n_kv, max_position_embeddings=256,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=tie,
    )
    torch.manual_seed(seed)
    model = Qwen2ForCausalLM(hf_cfg).eval()
    # transformers zero-initializes the q/k/v biases, which would make
    # every parity assertion below pass even if the loader dropped them.
    # Randomize so the bias math is load-bearing.
    with torch.no_grad():
        for name, p in model.named_parameters():
            if name.endswith("_proj.bias"):
                p.normal_(0, 0.5)
    return hf_cfg, model


def test_config_maps_attention_bias():
    hf_cfg, _ = _tiny_qwen2()
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    assert config.attn_bias is True


def test_params_carry_bias_rows():
    hf_cfg, model = _tiny_qwen2()
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(model, config)
    for key, dim in (("bq", 64), ("bk", 32), ("bv", 32)):
        assert params["layers"][key].shape == (config.n_layers, dim)
    # The HF init gives non-trivial biases; a zero tensor here would mean
    # the loader silently dropped them and parity passes by luck.
    assert float(np.abs(np.asarray(params["layers"]["bq"])).max()) > 0


@pytest.mark.parametrize("tie", [False, True])
def test_forward_matches_transformers(tie):
    hf_cfg, model = _tiny_qwen2(tie=tie)
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(model, config)
    tokens = np.array([[3, 17, 99, 4, 250, 7, 7, 42, 120, 5]], np.int64)
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        llama.forward_dense(config, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_gqa_bias_grouping_matches():
    hf_cfg, model = _tiny_qwen2(n_q=8, n_kv=2, seed=3)
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(model, config)
    tokens = np.arange(12, dtype=np.int64)[None] % 256
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        llama.forward_dense(config, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_bias_params_shard_over_tp_mesh():
    """shard_params must carry the bq/bk/bv rows (each biased on its
    projection's column-parallel output dim) — a spec/pytree mismatch here
    crashes TP serving for every Qwen2 checkpoint."""
    import jax

    from llm_d_kv_cache_manager_tpu.parallel.mesh import make_mesh, shard_params

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest XLA flags)")
    cfg = llama.LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_q_heads=8, n_kv_heads=4,
        head_dim=32, d_ff=128, dtype=jnp.float32, attn_bias=True,
    )
    mesh = make_mesh(dp=2, tp=4)
    host = llama.init_params(cfg, jax.random.PRNGKey(0))
    # init gives zero biases; randomize so the sharded bias add is
    # numerically load-bearing, not a no-op.
    for key in ("bq", "bk", "bv"):
        host["layers"][key] = jax.random.normal(
            jax.random.PRNGKey(hash(key) % 2**31),
            host["layers"][key].shape, cfg.dtype,
        )
    params = shard_params(host, mesh)
    spec = params["layers"]["bq"].sharding.spec
    assert tuple(spec) == (None, "tp")
    # Sharded forward equals the host computation.
    tokens = np.arange(16, dtype=np.int32)[None] % 256
    sharded = np.asarray(llama.forward_dense(cfg, params, jnp.asarray(tokens)))
    host_params = jax.tree_util.tree_map(np.asarray, params)
    host = np.asarray(llama.forward_dense(cfg, host_params, jnp.asarray(tokens)))
    np.testing.assert_allclose(sharded, host, rtol=1e-5, atol=1e-5)


def test_mistral_checkpoint_loads_as_llama_family():
    """model_type=mistral is the Llama decoder with no attention bias —
    pin logits parity so the claimed Mistral support is tested, not
    asserted."""
    from transformers import MistralConfig as HFMistralConfig
    from transformers import MistralForCausalLM

    hf_cfg = HFMistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
    )
    torch.manual_seed(2)
    model = MistralForCausalLM(hf_cfg).eval()
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    assert config.attn_bias is False
    params = params_from_hf(model, config)
    assert "bq" not in params["layers"]
    tokens = np.array([[3, 17, 99, 4, 250, 7, 42]], np.int64)
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        llama.forward_dense(config, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def _tiny_mistral_swa(window):
    from transformers import MistralConfig as HFMistralConfig
    from transformers import MistralForCausalLM

    hf_cfg = HFMistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=window, attn_implementation="eager",
    )
    torch.manual_seed(5)
    return hf_cfg, MistralForCausalLM(hf_cfg).eval()


def test_sliding_window_forward_matches_transformers():
    """Sliding-window masking against HF's own implementation: a 20-token
    prompt with window 8 — beyond-window positions MUST differ from full
    attention (the probe) and match HF exactly."""
    hf_cfg, model = _tiny_mistral_swa(window=8)
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    assert config.sliding_window == 8
    params = params_from_hf(model, config)
    tokens = np.arange(20, dtype=np.int64)[None] % 256
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        llama.forward_dense(config, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)
    # The window must be load-bearing: full attention on the same weights
    # diverges at positions >= window.
    import dataclasses

    full = np.asarray(llama.forward_dense(
        dataclasses.replace(config, sliding_window=None), params,
        jnp.asarray(tokens, jnp.int32),
    ))
    assert np.abs(full[0, 8:] - hf_logits[0, 8:]).max() > 1e-3


@pytest.mark.parametrize("decode_steps", [1, 4])
def test_sliding_window_paged_serving_matches_hf_greedy(decode_steps):
    """The full paged stack — chunked prefill past the window, batched and
    multi-step decode — must emit HF's greedy continuation for a windowed
    checkpoint whose prompt is LONGER than the window."""
    hf_cfg, model = _tiny_mistral_swa(window=8)
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(model, config)

    prompt = list(range(3, 23))  # 20 tokens > window 8
    n_new = 8
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor([prompt]), max_new_tokens=n_new, do_sample=False,
            pad_token_id=0,
        )[0, len(prompt):].tolist()

    pod = EnginePod(
        EnginePodConfig(
            n_pages=64, page_size=4, with_model=True, model_config=config,
            max_pages_per_seq=16,
        ),
        params=params,
    )
    try:
        sched = Scheduler(pod, max_batch=2, decode_steps=decode_steps,
                          prefill_token_budget=8)
        rid = sched.submit(prompt, max_new_tokens=n_new)
        assert sched.run()[rid] == hf_out
    finally:
        pod.close()


def test_qwen2_window_gate_respected():
    # Qwen2 defaults use_sliding_window=False: no window carried.
    hf_q, _ = _tiny_qwen2()
    assert config_from_hf(hf_q, dtype=jnp.float32).sliding_window is None


def test_qwen2_max_window_layers_cases():
    """HF serves the FIRST max_window_layers layers with full attention;
    the engine's window is uniform. All-full maps to no window, all-sliding
    maps to the uniform window, a mix must refuse instead of silently
    diverging from HF."""
    def cfg(mwl):
        return HFQwen2Config(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=2, use_sliding_window=True,
            sliding_window=32, max_window_layers=mwl,
        )

    assert config_from_hf(cfg(4), dtype=jnp.float32).sliding_window is None
    assert config_from_hf(cfg(0), dtype=jnp.float32).sliding_window == 32
    with pytest.raises(NotImplementedError, match="max_window_layers"):
        config_from_hf(cfg(2), dtype=jnp.float32)


@pytest.mark.parametrize("use_quantized_kv", [False, True])
def test_qwen2_speculative_int8_composes(use_quantized_kv):
    """The bias must compose with the latency lever (speculative decoding)
    and the capacity lever (int8 KV) at once: spec decode on a quantized
    Qwen2 pod pins target-only greedy output."""
    from llm_d_kv_cache_manager_tpu.engine.speculative import SpeculativeDecoder

    hf_cfg, model = _tiny_qwen2(seed=4)
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(model, config)
    draft_cfg = llama.LlamaConfig(
        vocab_size=256, d_model=32, n_layers=1, n_q_heads=2, n_kv_heads=2,
        head_dim=16, d_ff=64, dtype=jnp.float32,
    )
    import jax

    draft_params = llama.init_params(draft_cfg, jax.random.PRNGKey(7))

    def pod():
        return EnginePod(
            EnginePodConfig(
                n_pages=64, page_size=4, with_model=True, model_config=config,
                max_pages_per_seq=16, use_quantized_kv=use_quantized_kv,
            ),
            params=params,
        )

    prompt = [3, 17, 99, 4, 250, 7]
    n_new = 8
    ref_pod = pod()
    sched = Scheduler(ref_pod, max_batch=1)
    rid = sched.submit(prompt, max_new_tokens=n_new)
    reference = sched.run()[rid]

    spec = SpeculativeDecoder(
        pod(), draft_config=draft_cfg, draft_params=draft_params, k=3
    )
    out = spec.generate(prompt, max_new_tokens=n_new)
    assert out == reference


@pytest.mark.parametrize("decode_steps", [1, 4])
def test_paged_generation_matches_hf_greedy(decode_steps):
    """Biases must flow through the whole serving stack — paged prefill,
    batched decode, and the on-device multi-step loop — unchanged."""
    hf_cfg, model = _tiny_qwen2(seed=1)
    config = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(model, config)

    prompt = [3, 17, 99, 4, 250, 7]
    n_new = 8
    ids = torch.tensor([prompt])
    with torch.no_grad():
        hf_out = model.generate(
            ids, max_new_tokens=n_new, do_sample=False, pad_token_id=0,
        )[0, len(prompt):].tolist()

    pod = EnginePod(
        EnginePodConfig(
            n_pages=32, page_size=4, with_model=True, model_config=config,
            max_pages_per_seq=16,
        ),
        params=params,
    )
    sched = Scheduler(pod, max_batch=2, decode_steps=decode_steps)
    rid = sched.submit(prompt, max_new_tokens=n_new)
    assert sched.run()[rid] == hf_out

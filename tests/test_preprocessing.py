"""Chat-templating preprocessing tests.

Mirrors the intent of the reference's cgo/Python templating suite
(/root/reference/pkg/preprocessing/chat_completions/cgo_functions_test.go):
render correctness (via transformers' render_jinja_template — vLLM parity),
template fetching from local model dirs, per-model caching.
"""

import json

import pytest

from llm_d_kv_cache_manager_tpu.preprocessing.chat_completions import (
    ChatTemplatingProcessor,
    RenderRequest,
)

SIMPLE_TEMPLATE = (
    "{% for m in messages %}<|{{ m.role }}|>{{ m.content }}{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>{% endif %}"
)


class TestRender:
    def test_basic_render(self):
        proc = ChatTemplatingProcessor()
        out = proc.render(
            RenderRequest(
                conversations=[[{"role": "user", "content": "hi"}]],
                chat_template=SIMPLE_TEMPLATE,
            )
        )
        assert out == "<|user|>hi<|assistant|>"

    def test_multi_turn_no_generation_prompt(self):
        proc = ChatTemplatingProcessor()
        out = proc.render(
            RenderRequest(
                conversations=[
                    [
                        {"role": "system", "content": "be brief"},
                        {"role": "user", "content": "hi"},
                        {"role": "assistant", "content": "hello"},
                    ]
                ],
                chat_template=SIMPLE_TEMPLATE,
                add_generation_prompt=False,
            )
        )
        assert out == "<|system|>be brief<|user|>hi<|assistant|>hello"

    def test_from_json_contract(self):
        payload = json.dumps(
            {
                "conversations": [[{"role": "user", "content": "x"}]],
                "chat_template": SIMPLE_TEMPLATE,
                "add_generation_prompt": True,
            }
        )
        req = RenderRequest.from_json(payload)
        assert ChatTemplatingProcessor().render(req) == "<|user|>x<|assistant|>"

    def test_missing_template_raises(self):
        proc = ChatTemplatingProcessor()
        with pytest.raises(ValueError, match="no chat template"):
            proc.render(
                RenderRequest(conversations=[[{"role": "user", "content": "x"}]])
            )


class TestFetch:
    def test_fetch_from_tokenizer_config(self, tmp_path):
        model_dir = tmp_path / "org" / "model"
        model_dir.mkdir(parents=True)
        (model_dir / "tokenizer_config.json").write_text(
            json.dumps({"chat_template": SIMPLE_TEMPLATE})
        )
        proc = ChatTemplatingProcessor()
        template = proc.fetch_chat_template("org/model", local_dir=str(tmp_path))
        assert template == SIMPLE_TEMPLATE

    def test_fetch_from_jinja_file_wins(self, tmp_path):
        model_dir = tmp_path / "m"
        model_dir.mkdir()
        (model_dir / "chat_template.jinja").write_text("JINJA{{ messages }}")
        (model_dir / "tokenizer_config.json").write_text(
            json.dumps({"chat_template": "CONFIG"})
        )
        proc = ChatTemplatingProcessor()
        assert proc.fetch_chat_template("m", local_dir=str(tmp_path)).startswith("JINJA")

    def test_fetch_caches_per_model(self, tmp_path):
        model_dir = tmp_path / "m"
        model_dir.mkdir()
        cfg = model_dir / "tokenizer_config.json"
        cfg.write_text(json.dumps({"chat_template": "V1"}))
        proc = ChatTemplatingProcessor()
        assert proc.fetch_chat_template("m", local_dir=str(tmp_path)) == "V1"
        cfg.write_text(json.dumps({"chat_template": "V2"}))
        # Cached: still V1 until caches are cleared.
        assert proc.fetch_chat_template("m", local_dir=str(tmp_path)) == "V1"
        proc.clear_caches()
        assert proc.fetch_chat_template("m", local_dir=str(tmp_path)) == "V2"

    def test_render_uses_fetched_template(self, tmp_path):
        model_dir = tmp_path / "m"
        model_dir.mkdir()
        (model_dir / "tokenizer_config.json").write_text(
            json.dumps({"chat_template": SIMPLE_TEMPLATE})
        )
        proc = ChatTemplatingProcessor()
        proc.fetch_chat_template("m", local_dir=str(tmp_path))
        out = proc.render(
            RenderRequest(
                conversations=[[{"role": "user", "content": "y"}]], model_name="m"
            )
        )
        assert out == "<|user|>y<|assistant|>"

"""Generate kv_event_vllm.json: block-hash vectors computed BY VLLM'S OWN CODE.

The committed hash-parity fixtures (generate_fixtures.py +
independent_cbor.py) are a genuine second implementation, but both live in
this repo and share an author — a common misreading of vLLM's scheme would
pass every in-repo test and silently zero all scores against a real fleet.
The reference's keystone testdata was captured from a live engine
(/root/reference/tests/integration/prompt_to_block_test.go:36-60); the
third-party equivalent here is vLLM itself — its v1 block hashing
(`vllm.v1.core.kv_cache_utils.hash_block_tokens`) is importable on a
CPU-only install, no engine needed.

vLLM supports several prefix-caching hash algorithms (builtin
PYTHONHASHSEED-dependent tuple hash, sha256 variants, CBOR-based 64-bit
forms for cross-process consumers). A fleet deployment pins ONE of them and
configures the indexer to match, so this script:

1. enumerates every algorithm the installed vLLM exposes,
2. computes the full case matrix (base chain / non-default seed /
   parent-chain continuation / LoRA extra keys) with vLLM's own
   hash_block_tokens under each algorithm,
3. checks which algorithm this repo's ChunkedTokenDatabase reproduces
   (chain values AND root/NONE_HASH derivation), records it as
   `matched_algo`, and
4. exits NON-ZERO if no algorithm matches — the keystone must fail loud,
   never silently skip.

With a real vllm install (CI job — .github/workflows/ci.yml `vllm-interop`)
the vectors come from vLLM's own code. Without one (this build image has no
vllm and no egress) the generator falls back to the vendored Apache-2.0
oracle `tests/third_party/vllm_kv_cache_utils.py` (VERDICT r4 #2) and marks
the fixture `source: vendored-oracle`; the CI job regenerates with
`source: vllm-install` and catches any oracle drift. Either way the JSON is
committed and tests/test_hash_parity.py::TestVllmVectors asserts parity
offline from then on.

Usage: PYTHONHASHSEED=0 python tests/fixtures/generate_vllm_vectors.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.abspath(os.path.join(HERE, "..", ".."))
sys.path.insert(0, REPO)

OUT = os.path.join(HERE, "kv_event_vllm.json")

BLOCK = 16
CASES = [
    # (name, seed, lora_id, chains) — each chain is a list of block-sized
    # token groups hashed as one parent-linked sequence.
    ("base", "0", None, [list(range(32))]),
    ("seeded", "42", None, [list(range(32))]),
    ("parent_chain", "0", None, [list(range(16)), list(range(16, 48))]),
    ("lora", "0", 7, [list(range(32))]),
]


def _load_kv_cache_utils():
    """(module, version, source): the real vLLM when installed, else the
    vendored Apache-2.0 oracle (tests/third_party/vllm_kv_cache_utils.py —
    VERDICT r4 #2: this image has no vllm and no egress, but the keystone
    must still be provable offline; the CI vllm-interop job re-runs this
    generator against a real install and catches oracle drift)."""
    try:
        import vllm
        from vllm.v1.core import kv_cache_utils

        return kv_cache_utils, vllm.__version__, "vllm-install"
    except ImportError:
        sys.path.insert(0, os.path.join(REPO, "tests"))
        from third_party import vllm_kv_cache_utils as kv_cache_utils

        return kv_cache_utils, kv_cache_utils.ORACLE_VERSION, "vendored-oracle"


# vLLM algorithm name -> this repo's TokenProcessorConfig.hash_algo that
# should reproduce it (absent = the indexer has no mode for that algorithm:
# builtin is process-local by design; pickle-sha256 is full-width and
# Python-pickle-shaped).
ALGO_TO_INDEXER = {"sha256_cbor_64bit": "sha256_cbor_64bit"}


def _candidate_algos(kv_cache_utils):
    """{name: (hash_fn, engine_arg)} for every block-hash algorithm this
    vLLM exposes. `engine_arg` is the value accepted by vLLM's
    prefix-caching-hash-algo engine option, or None for module-level
    functions found outside the documented option set — those prove hash
    parity but cannot be passed to LLM(...)."""
    algos = {"builtin": (hash, "builtin")}
    registry = getattr(kv_cache_utils, "_HASH_FN_REGISTRY", None) or getattr(
        kv_cache_utils, "HASH_FN_MAP", None
    )
    if isinstance(registry, dict):
        for name, fn in registry.items():
            algos[str(name)] = (fn, str(name))
    # Documented engine-arg spellings double as the module function names.
    documented = {"sha256", "sha256_cbor_64bit"}
    for name in ("sha256", "sha256_cbor_64bit", "sha256_cbor", "fnv1a_64"):
        fn = getattr(kv_cache_utils, name, None)
        if callable(fn):
            algos.setdefault(name, (fn, name if name in documented else None))
    return algos


def _none_hash(kv_cache_utils, hash_fn):
    """(Re-)derive NONE_HASH for this algorithm under the current
    PYTHONHASHSEED, handling the init-at-import and explicit-init API
    shapes across vLLM versions."""
    init = getattr(kv_cache_utils, "init_none_hash", None)
    if init is not None:
        init(hash_fn)
    return kv_cache_utils.NONE_HASH


def _run_cases_for_seed(kv_cache_utils, seed: str):
    """All vectors whose case-seed equals the CURRENT process seed, for
    every candidate algorithm. NONE_HASH binds to PYTHONHASHSEED at init,
    which is why each seed runs in its own process."""
    vectors = []
    for algo_name, (hash_fn, engine_arg) in _candidate_algos(
        kv_cache_utils
    ).items():
        try:
            none_hash = _none_hash(kv_cache_utils, hash_fn)
        except Exception as e:  # noqa: BLE001 - algo unsupported this build
            print(f"note: algo {algo_name} init failed: {e}", file=sys.stderr)
            continue
        for name, case_seed, lora_id, chains in CASES:
            if case_seed != seed:
                continue
            # vLLM `_gen_lora_extra_hash_keys`: the adapter's integer
            # lora_int_id, mixed into every block hash of the request.
            extra = (int(lora_id),) if lora_id is not None else None
            parent = none_hash
            root = True
            case_vectors = []
            try:
                for chain in chains:
                    chain_parent = (
                        None if root else int(_u64(parent))
                    )
                    hashes = []
                    for i in range(len(chain) // BLOCK):
                        block = tuple(chain[i * BLOCK:(i + 1) * BLOCK])
                        bh = kv_cache_utils.hash_block_tokens(
                            hash_fn, parent, block, extra
                        )
                        value = bh.hash_value if hasattr(bh, "hash_value") else bh
                        hashes.append(int(_u64(value)))
                        parent = value
                    case_vectors.append({
                        "algo": algo_name, "engine_arg": engine_arg,
                        "case": name, "seed": case_seed,
                        "lora_id": lora_id, "parent_hash": chain_parent,
                        "none_hash": int(_u64(none_hash)),
                        "tokens": list(chain), "hashes": hashes,
                    })
                    root = False
            except Exception as e:  # noqa: BLE001 - algo rejects this shape
                # All-or-nothing per case: a partial parent_chain case
                # would let _match certify an algo whose continuation
                # behavior was never exercised.
                print(
                    f"note: algo {algo_name} case {name} failed: {e}",
                    file=sys.stderr,
                )
                continue
            vectors.extend(case_vectors)
    return vectors


def _u64(value) -> int:
    if isinstance(value, bytes):
        return int.from_bytes(value[-8:], "big")
    return int(value) & 0xFFFFFFFFFFFFFFFF


def _ours(vec, indexer_algo: str) -> list:
    """This repo's hashes for a vector's chain (same replay the offline
    test runs), continuing from the recorded parent when present."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
        ChunkedTokenDatabase,
        TokenProcessorConfig,
    )

    db = ChunkedTokenDatabase(
        TokenProcessorConfig(
            block_size=BLOCK, hash_seed=vec["seed"], hash_algo=indexer_algo
        )
    )
    parent = (
        Key("m", vec["parent_hash"]) if vec["parent_hash"] is not None else None
    )
    keys = db.tokens_to_kv_block_keys(
        parent, vec["tokens"], "m", lora_id=vec["lora_id"]
    )
    return [k.chunk_hash for k in keys]


def _match(vectors) -> "tuple[str, str] | tuple[None, None]":
    """(vllm_algo, indexer_hash_algo) the repo reproduces, or (None, None).
    An algorithm only qualifies when it produced the FULL case matrix —
    a partially-failing algo must not get certified on the cases it
    happened to survive."""
    required_cases = {c[0] for c in CASES}
    by_algo = {}
    for vec in vectors:
        by_algo.setdefault(vec["algo"], []).append(vec)
    for algo, vecs in sorted(by_algo.items()):
        indexer_algo = ALGO_TO_INDEXER.get(algo)
        if indexer_algo is None:
            continue
        if {v["case"] for v in vecs} != required_cases:
            continue
        if all(_ours(v, indexer_algo) == v["hashes"] for v in vecs):
            return algo, indexer_algo
    return None, None


def main() -> None:
    kv_cache_utils, version, source = _load_kv_cache_utils()
    if not hasattr(kv_cache_utils, "hash_block_tokens"):
        sys.exit(
            "kv_cache_utils.hash_block_tokens not found — update this "
            f"script for the installed vllm ({version})"
        )

    seed = os.environ.get("PYTHONHASHSEED")
    if seed is None:
        sys.exit("set PYTHONHASHSEED (vLLM binds NONE_HASH to it at init)")

    only_seed = os.environ.get("_KVTPU_ONE_SEED")
    if only_seed:
        print(json.dumps(_run_cases_for_seed(kv_cache_utils, only_seed)))
        return

    vectors = []
    for case_seed in sorted({c[1] for c in CASES}):
        if case_seed == seed:
            vectors.extend(_run_cases_for_seed(kv_cache_utils, case_seed))
        else:
            env = dict(
                os.environ, PYTHONHASHSEED=case_seed, _KVTPU_ONE_SEED=case_seed
            )
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, check=True,
            )
            vectors.extend(json.loads(out.stdout.strip().splitlines()[-1]))

    matched, indexer_hash_algo = _match(vectors)
    # The engine-option spelling of the matched algo (None when the match
    # came from a module function outside the registry — provable parity,
    # but not passable to LLM(prefix_caching_hash_algo=...)).
    matched_engine_arg = next(
        (v["engine_arg"] for v in vectors if v["algo"] == matched), None
    )
    with open(OUT, "w") as f:
        json.dump(
            {
                "vllm_version": version,
                "source": source,
                "block_size": BLOCK,
                "matched_algo": matched,
                "matched_engine_arg": matched_engine_arg,
                "indexer_hash_algo": indexer_hash_algo,
                "algos": sorted({v["algo"] for v in vectors}),
                "vectors": vectors,
            },
            f, indent=2,
        )
    print(f"wrote {OUT} ({len(vectors)} vectors, matched_algo={matched})")
    if matched is None:
        sys.exit(
            "KEYSTONE FAILURE: no vLLM hash algorithm matches this repo's "
            "ChunkedTokenDatabase — the indexer would silently score 0 "
            "against a real fleet. Compare the vectors in the JSON against "
            "hashing.py and fix the scheme (or add support for the fleet's "
            "configured --prefix-caching-hash-algo)."
        )


if __name__ == "__main__":
    main()

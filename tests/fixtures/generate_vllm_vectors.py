"""Generate kv_event_vllm.json: block-hash vectors computed BY VLLM'S OWN CODE.

VERDICT r2 missing #1: the committed hash-parity fixtures
(generate_fixtures.py + independent_cbor.py) are a genuine second
implementation, but both live in this repo. The reference's keystone
testdata was captured from a live engine
(/root/reference/tests/integration/prompt_to_block_test.go:36-60); the
third-party equivalent here is vLLM itself — its v1 block hashing is
importable on a CPU-only install (`pip install vllm`), no engine needed.

Run this wherever vllm is installed (CI job, dev box; NOT this build image
— it has no vllm and no egress), commit the JSON, and
tests/test_hash_parity.py::TestVllmVectors asserts ChunkedTokenDatabase
reproduces every vector. Cases: base chain, non-default seed, parent-chain
continuation, LoRA extra keys.

Usage: PYTHONHASHSEED=0 python tests/fixtures/generate_vllm_vectors.py
"""

from __future__ import annotations

import json
import os
import sys

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "kv_event_vllm.json")

BLOCK = 16
CASES = [
    # (name, seed, lora_id, chains) — each chain is a list of block-sized
    # token groups hashed as one parent-linked sequence.
    ("base", "", None, [list(range(32))]),
    ("seeded", "42", None, [list(range(32))]),
    ("parent_chain", "", None, [list(range(16)), list(range(16, 48))]),
    ("lora", "", 7, [list(range(32))]),
]


def main() -> None:
    try:
        import vllm  # noqa: F401
        from vllm.v1.core import kv_cache_utils
    except ImportError as e:
        sys.exit(
            f"vllm not importable ({e}); run on a machine with "
            "`pip install vllm` (CPU wheel is fine)"
        )

    # vLLM derives NONE_HASH (the root parent) from PYTHONHASHSEED; the
    # indexer mirrors that with its hash_seed config. Per-seed vectors
    # require one process per seed, so re-exec for non-default seeds.
    hasher = None
    for name in ("fnv1a_64", "hash_block_tokens"):
        hasher = getattr(kv_cache_utils, name, None) or hasher
    if not hasattr(kv_cache_utils, "hash_block_tokens"):
        sys.exit(
            "vllm.v1.core.kv_cache_utils.hash_block_tokens not found — "
            "update this script for the installed vllm "
            f"({getattr(vllm, '__version__', '?')})"
        )

    vectors = []
    for name, seed, lora_id, chains in CASES:
        if seed != (os.environ.get("PYTHONHASHSEED") or ""):
            # NONE_HASH binds at import; capture this case in a re-exec.
            env = dict(os.environ, PYTHONHASHSEED=seed, _KVTPU_ONE_CASE=name)
            import subprocess

            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, check=True,
            )
            vectors.extend(json.loads(out.stdout.strip().splitlines()[-1]))
            continue
        vectors.extend(_run_case(kv_cache_utils, name, seed, lora_id, chains))

    only = os.environ.get("_KVTPU_ONE_CASE")
    if only:
        print(json.dumps([v for v in vectors if v["case"] == only]))
        return
    with open(OUT, "w") as f:
        json.dump(
            {
                "vllm_version": __import__("vllm").__version__,
                "block_size": BLOCK,
                "vectors": vectors,
            },
            f, indent=2,
        )
    print(f"wrote {OUT} ({len(vectors)} vectors)")


def _run_case(kv_cache_utils, name, seed, lora_id, chains):
    hash_fn = getattr(kv_cache_utils, "NONE_HASH", None)
    init_none = getattr(kv_cache_utils, "init_none_hash", None)
    if init_none is not None:
        init_none(hash)  # builtin-hash mode, PYTHONHASHSEED-derived
    out = []
    parent = kv_cache_utils.NONE_HASH
    extra = (str(lora_id),) if lora_id is not None else None
    root = True
    for chain in chains:
        # A non-root chain records the parent hash it continues from, so
        # the parity test can replay the continuation explicitly.
        chain_parent = None if root else int(parent) & 0xFFFFFFFFFFFFFFFF
        hashes = []
        for i in range(len(chain) // BLOCK):
            block = tuple(chain[i * BLOCK:(i + 1) * BLOCK])
            bh = kv_cache_utils.hash_block_tokens(hash, parent, block, extra)
            value = bh.hash_value if hasattr(bh, "hash_value") else bh
            hashes.append(int(value) & 0xFFFFFFFFFFFFFFFF)
            parent = value
        out.append({
            "case": name, "seed": seed, "lora_id": lora_id,
            "parent_hash": chain_parent,
            "tokens": list(chain), "hashes": hashes,
        })
        root = False
    return out


if __name__ == "__main__":
    main()

"""Engine-side golden-fixture generator for the hash-parity keystone test.

Plays the role of the vLLM-TPU engine in the reference's integration fixtures
(/root/reference/tests/integration/testdata/kv_event_base.json, generated from
a live engine's KVEvents): it tokenizes a prompt and computes the per-block
chained hashes an engine would report in BlockStored events, then writes them
as JSON in the reference's exact testdata schema
(/root/reference/tests/integration/prompt_to_block_test.go:36-48, extended
with `lora_id` since this framework keys LoRA blocks by adapter id).

CRITICAL INDEPENDENCE PROPERTY: this script must never import
`llm_d_kv_cache_manager_tpu` — the hashing here is written from the published
scheme (FNV-64a over canonical CBOR [parent, tokens, extra], root =
FNV-64a(seed bytes); reference token_processor.go:81-112) using the
independent RFC-8949 codec in tests/independent_cbor.py and a reduce-based
FNV. The committed fixtures therefore constitute a second implementation:
if `kvcache/kvblock/hashing.py` ever drifts, tests/test_hash_parity.py fails.

Run from the repo root to regenerate:  python tests/fixtures/generate_fixtures.py
"""

import functools
import json
import pathlib
import sys

from tokenizers import Tokenizer

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import independent_cbor  # noqa: E402

FIXTURE_DIR = pathlib.Path(__file__).resolve().parent
TOKENIZER_JSON = FIXTURE_DIR / "test-model" / "tokenizer.json"
MODEL_NAME = "fixtures/test-model"
BLOCK_SIZE = 16
HASH_SEED = "42"  # matches the reference benchmark fleet config (37-capacity)

PROMPT = (
    "A cache aware router keeps a live map from block hashes to the pods "
    "that already hold them, so a new request can land where most of its "
    "prefix is resident. The index is fed by events that engines publish "
    "whenever blocks are stored or evicted, and the scorer walks the chain "
    "of block keys in order, stopping at the first miss. On a TPU fleet the "
    "same contract holds, with tiers for device memory and host memory, and "
    "a transfer plane that can move blocks between pods when a remote pod "
    "owns a longer prefix than any local one."
)


def fnv64a(data: bytes) -> int:
    return functools.reduce(
        lambda acc, byte: ((acc ^ byte) * 0x100000001B3) & (2**64 - 1),
        data,
        0xCBF29CE484222325,
    )


def engine_block_hashes(token_ids, block_size, seed, lora_id=None):
    """Chained per-block hashes exactly as the engine event stream reports."""
    hashes = []
    parent = fnv64a(seed.encode())
    extra = None if lora_id is None else [lora_id]
    for start in range(0, (len(token_ids) // block_size) * block_size, block_size):
        payload = [parent, list(token_ids[start:start + block_size]), extra]
        parent = fnv64a(independent_cbor.encode(payload))
        hashes.append(parent)
    return hashes


def build_fixture(lora_name=None, lora_id=None):
    token_ids = Tokenizer.from_file(str(TOKENIZER_JSON)).encode(PROMPT).ids
    n_full = (len(token_ids) // BLOCK_SIZE) * BLOCK_SIZE
    return {
        "prompt": PROMPT,
        "model_name": MODEL_NAME,
        "lora_path": None,
        "lora_name": lora_name,
        "lora_id": lora_id,
        "event_type": "BlockStored",
        "block_hashes": engine_block_hashes(token_ids, BLOCK_SIZE, HASH_SEED, lora_id),
        "parent_block_hash": None,
        "token_ids": token_ids[:n_full],
        "block_size": BLOCK_SIZE,
        "medium": "hbm",
        "hash_seed": HASH_SEED,
    }


def main():
    base = build_fixture()
    assert len(base["block_hashes"]) >= 4, "prompt too short for a useful fixture"
    lora = build_fixture(lora_name="test-adapter", lora_id=7)
    assert lora["block_hashes"] != base["block_hashes"], "LoRA id must change hashes"
    for name, data in (("kv_event_base.json", base), ("kv_event_lora.json", lora)):
        (FIXTURE_DIR / name).write_text(json.dumps(data, indent=2) + "\n")
        print(f"wrote {name}: {len(data['block_hashes'])} blocks")


if __name__ == "__main__":
    main()

"""Speculative decoding: greedy-equivalence is the contract.

With greedy sampling, speculation must produce BIT-IDENTICAL output to
target-only greedy generation — the draft only changes latency, never
content. A draft that equals the target must accept everything; a random
draft must still yield identical output (with lower acceptance)."""

import jax
import jax.numpy as jnp
import pytest

from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.engine.speculative import SpeculativeDecoder
from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

# Model-math tests compile real models (VERDICT r5 weak #6): excluded
# from the tier-1 `-m 'not slow'` gate to keep its wall time bounded.
pytestmark = pytest.mark.slow


TARGET_CFG = LlamaConfig(
    vocab_size=128, d_model=32, n_layers=2, n_q_heads=2, n_kv_heads=2,
    head_dim=16, d_ff=64, dtype=jnp.float32,
)
DRAFT_CFG = LlamaConfig(
    vocab_size=128, d_model=16, n_layers=1, n_q_heads=2, n_kv_heads=2,
    head_dim=8, d_ff=32, dtype=jnp.float32,
)
TARGET_PARAMS = llama.init_params(TARGET_CFG, jax.random.PRNGKey(0))
DRAFT_PARAMS = llama.init_params(DRAFT_CFG, jax.random.PRNGKey(5))


def _pod(n_pages=64):
    return EnginePod(
        EnginePodConfig(n_pages=n_pages, page_size=4, with_model=True,
                        model_config=TARGET_CFG, max_pages_per_seq=16),
        params=TARGET_PARAMS,
    )


def _greedy_reference(prompt, n_new, eos=None):
    pod = _pod()
    state, _ = pod.prefill(list(prompt))
    out = [int(jnp.argmax(pod.last_logits))]
    pod.decode_append(state, out[0])
    while len(out) < n_new and (eos is None or out[-1] != eos):
        out.append(pod.decode_step(state))
    pod.free(state)
    return out[: n_new] if eos is None else out


class TestGreedyEquivalence:
    @pytest.mark.parametrize("k", [1, 3, 4])
    def test_weak_draft_output_identical(self, k):
        prompt = list(range(2, 13))
        expected = _greedy_reference(prompt, 12)
        pod = _pod()
        spec = SpeculativeDecoder(pod, DRAFT_CFG, DRAFT_PARAMS, k=k)
        out = spec.generate(prompt, max_new_tokens=12)
        assert out == expected
        # Proposals are capped by the remaining budget in late rounds.
        assert 0 < spec.stats.proposed <= spec.stats.rounds * k
        assert spec.stats.accepted <= spec.stats.proposed

    def test_perfect_draft_accepts_everything(self):
        # Draft == target: every proposal must be accepted.
        prompt = list(range(3, 10))
        expected = _greedy_reference(prompt, 10)
        pod = _pod()
        spec = SpeculativeDecoder(pod, TARGET_CFG, TARGET_PARAMS, k=3)
        out = spec.generate(prompt, max_new_tokens=10)
        assert out == expected
        # Every token beyond the per-round frontier token came from an
        # accepted proposal — no proposal was ever *rejected* (the last
        # round's tail is cut by the token budget, not by mismatch).
        assert spec.stats.accepted == len(out) - spec.stats.rounds

    def test_eos_stops_generation(self):
        prompt = list(range(2, 10))
        ref = _greedy_reference(prompt, 1)
        eos = ref[0]
        pod = _pod()
        spec = SpeculativeDecoder(pod, DRAFT_CFG, DRAFT_PARAMS, k=3)
        out = spec.generate(prompt, max_new_tokens=10, eos_token=eos)
        assert out == [eos]


class TestEngineStateHygiene:
    def test_pages_fully_released_after_generation(self):
        pod = _pod(n_pages=32)
        spec = SpeculativeDecoder(pod, DRAFT_CFG, DRAFT_PARAMS, k=4)
        spec.generate(list(range(2, 13)), max_new_tokens=8)
        # All pages back (committed ones cached/reclaimable, reserved ones
        # fresh): a second, larger run must still fit.
        assert pod.block_manager.num_free_pages == 32
        spec.generate(list(range(40, 60)), max_new_tokens=8)
        assert pod.block_manager.num_free_pages == 32

    def test_prefix_cache_only_advertises_accepted_tokens(self):
        # Events committed during speculation must cover exactly the
        # accepted sequence — never unverified proposals.
        batches = []
        pod = EnginePod(
            EnginePodConfig(n_pages=64, page_size=4, with_model=True,
                            model_config=TARGET_CFG, max_pages_per_seq=16),
            event_sink=batches.append,
            params=TARGET_PARAMS,
        )
        spec = SpeculativeDecoder(pod, DRAFT_CFG, DRAFT_PARAMS, k=4)
        prompt = list(range(2, 10))
        out = spec.generate(prompt, max_new_tokens=6)
        full = prompt + list(out)
        emitted_tokens = [
            t for b in batches for e in b.events
            if hasattr(e, "token_ids") for t in e.token_ids
        ]
        # Every emitted block is a prefix chunk of the accepted sequence.
        assert emitted_tokens == full[: len(emitted_tokens)]

    def test_page_capacity_boundary_completes(self):
        # A generation that exactly fills max_pages_per_seq capacity must
        # complete: proposals are capped so the verify chunk never reserves
        # past the page budget (16 pages x 4 = 64-token capacity here).
        prompt = list(range(2, 61))  # 59 tokens
        expected = _greedy_reference(prompt, 5)
        pod = _pod()
        spec = SpeculativeDecoder(pod, DRAFT_CFG, DRAFT_PARAMS, k=4)
        assert spec.generate(prompt, max_new_tokens=5) == expected

    def test_rejects_k_zero_and_accounting_pods(self):
        with pytest.raises(ValueError, match="k must be"):
            SpeculativeDecoder(_pod(), DRAFT_CFG, DRAFT_PARAMS, k=0)
        acct = EnginePod(EnginePodConfig(n_pages=8, page_size=4))
        with pytest.raises(ValueError, match="with_model"):
            SpeculativeDecoder(acct, DRAFT_CFG, DRAFT_PARAMS)


class TestBatchedVerify:
    """verify_step_cache: one batched pass must equal per-sequence
    prefill verification — the building block for batched speculation."""

    def test_matches_per_sequence_prefill(self):
        import numpy as np

        cfg = TARGET_CFG
        page = 4
        b, prefix_len, s = 3, 8, 5
        pps = (prefix_len + s + page - 1) // page + 1
        n_pages = b * pps
        rng = np.random.RandomState(0)
        prefixes = rng.randint(0, cfg.vocab_size, (b, prefix_len))
        chunks = rng.randint(0, cfg.vocab_size, (b, s))
        tables = jnp.arange(n_pages, dtype=jnp.int32).reshape(b, pps)

        # Batched: prefill each prefix, then one batched verify.
        cache = llama.make_kv_pages(cfg, n_pages, page)
        for i in range(b):
            cache, _ = llama.prefill_cache(
                cfg, TARGET_PARAMS, cache,
                jnp.asarray(prefixes[i], jnp.int32), tables[i], 0,
            )
        cache, batched_logits = llama.verify_step_cache(
            cfg, TARGET_PARAMS, cache, jnp.asarray(chunks, jnp.int32),
            tables, jnp.full((b,), prefix_len, jnp.int32),
        )

        # Reference: per-sequence prefill with all_logits.
        for i in range(b):
            ref_cache = llama.make_kv_pages(cfg, pps + 1, page)
            ref_table = jnp.arange(pps + 1, dtype=jnp.int32)
            ref_cache, _ = llama.prefill_cache(
                cfg, TARGET_PARAMS, ref_cache,
                jnp.asarray(prefixes[i], jnp.int32), ref_table, 0,
            )
            _, ref_logits = llama.prefill_cache(
                cfg, TARGET_PARAMS, ref_cache,
                jnp.asarray(chunks[i], jnp.int32), ref_table, prefix_len,
                all_logits=True,
            )
            np.testing.assert_allclose(
                np.asarray(batched_logits[i], np.float32),
                np.asarray(ref_logits, np.float32),
                rtol=1e-4, atol=1e-4,
            )

    def test_quantized_cache_matches_bf16_closely(self):
        # VERDICT r2 #6: verify_step_cache on the int8 4-tuple layout. The
        # quantized verify must track the full-precision one within int8
        # dequantization error.
        import numpy as np

        cfg = TARGET_CFG
        page = 4
        prefix = jnp.asarray(list(range(2, 10)), jnp.int32)
        chunk = jnp.asarray([[7, 11, 13]], jnp.int32)
        table = jnp.arange(4, dtype=jnp.int32)

        full_cache = llama.make_kv_pages(cfg, 4, page)
        full_cache, _ = llama.prefill_cache(
            cfg, TARGET_PARAMS, full_cache, prefix, table, 0
        )
        _, full_logits = llama.verify_step_cache(
            cfg, TARGET_PARAMS, full_cache, chunk, table[None],
            jnp.asarray([8], jnp.int32),
        )

        q_cache = llama.make_kv_pages_quantized(cfg, 4, page)
        q_cache, _ = llama.prefill_cache(
            cfg, TARGET_PARAMS, q_cache, prefix, table, 0
        )
        q_cache, q_logits = llama.verify_step_cache(
            cfg, TARGET_PARAMS, q_cache, chunk, table[None],
            jnp.asarray([8], jnp.int32),
        )
        scale = max(float(jnp.max(jnp.abs(full_logits))), 1.0)
        assert float(jnp.max(jnp.abs(full_logits - q_logits))) < 0.15 * scale
        # The verify really wrote quantized rows (position 8 = page 2 slot 0).
        assert np.any(np.asarray(q_cache[0][:, :, 2, 0]))


class TestSpeculativeScheduler:
    """Batched speculation must produce exactly what the plain scheduler
    produces — it is a pure latency lever."""

    def _plain_results(self, prompts, n_new):
        from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler

        sched = Scheduler(_pod(n_pages=128), max_batch=4)
        ids = [sched.submit(p, max_new_tokens=n_new) for p in prompts]
        results = sched.run()
        return [results[i] for i in ids]

    @pytest.mark.parametrize("k", [1, 3])
    def test_batch_matches_plain_scheduler(self, k):
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        prompts = [list(range(5)), list(range(20, 31)), list(range(40, 47))]
        expected = self._plain_results(prompts, 8)
        spec = SpeculativeScheduler(
            _pod(n_pages=128), DRAFT_CFG, DRAFT_PARAMS, k=k, max_batch=4,
        )
        ids = [spec.submit(p, max_new_tokens=8) for p in prompts]
        results = spec.run()
        for rid, exp in zip(ids, expected):
            assert results[rid] == exp
        assert spec.stats.rounds > 0

    def test_perfect_draft_high_acceptance(self):
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        prompts = [list(range(3, 10)), list(range(30, 38))]
        expected = self._plain_results(prompts, 9)
        spec = SpeculativeScheduler(
            _pod(n_pages=128), TARGET_CFG, TARGET_PARAMS, k=3, max_batch=4,
        )
        ids = [spec.submit(p, max_new_tokens=9) for p in prompts]
        results = spec.run()
        for rid, exp in zip(ids, expected):
            assert results[rid] == exp
        # Draft == target: no proposal with budget headroom is rejected.
        assert spec.stats.acceptance_rate > 0.5

    def test_staggered_admission_and_finish(self):
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        # Different max_new per request: sequences finish at different
        # ticks, freeing draft slots that later admissions reuse.
        prompts = [list(range(i * 12, i * 12 + 6)) for i in range(5)]
        budgets = [3, 9, 5, 7, 4]
        from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler

        sched = Scheduler(_pod(n_pages=128), max_batch=2)
        pids = [sched.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, budgets)]
        pres = sched.run()

        spec = SpeculativeScheduler(
            _pod(n_pages=128), DRAFT_CFG, DRAFT_PARAMS, k=3, max_batch=2,
        )
        sids = [spec.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, budgets)]
        sres = spec.run()
        for pid, sid in zip(pids, sids):
            assert sres[sid] == pres[pid]

    def test_preemption_under_page_pressure(self):
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        spec = SpeculativeScheduler(
            _pod(n_pages=16), DRAFT_CFG, DRAFT_PARAMS, k=3, max_batch=4,
        )
        ids = [spec.submit(list(range(i * 30, i * 30 + 20)), max_new_tokens=8)
               for i in range(3)]
        ticks = 0
        results = {}
        while spec.has_work:
            for req in spec.step():
                results[req.req_id] = req
            ticks += 1
            assert ticks < 500, "speculative scheduler livelocked"
        for rid in ids:
            assert results[rid].error is None
            assert len(results[rid].generated) == 8

    def test_pool_exhaustion_preempts_not_crashes(self):
        # Regression (r2 review repro): reserve_pages hitting an empty pool
        # must preempt the victim like plain decode, not crash the batch.
        from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        prompts = [list(range(18)), list(range(30, 48))]
        plain = Scheduler(
            EnginePod(EnginePodConfig(
                n_pages=12, page_size=4, with_model=True,
                model_config=TARGET_CFG, max_pages_per_seq=16,
            ), params=TARGET_PARAMS),
            max_batch=4,
        )
        pids = [plain.submit(p, max_new_tokens=12) for p in prompts]
        pres = plain.run()

        spec = SpeculativeScheduler(
            EnginePod(EnginePodConfig(
                n_pages=12, page_size=4, with_model=True,
                model_config=TARGET_CFG, max_pages_per_seq=16,
            ), params=TARGET_PARAMS),
            DRAFT_CFG, DRAFT_PARAMS, k=3, max_batch=4,
        )
        sids = [spec.submit(p, max_new_tokens=12) for p in prompts]
        sres = spec.run()
        for pid, sid in zip(pids, sids):
            assert sres[sid] == pres[pid]

    def test_quantized_pod_matches_plain_quantized_scheduler(self):
        # VERDICT r2 #6: the capacity lever (int8 KV) and the latency lever
        # (speculation) must compose. Contract: identical greedy output to
        # the plain scheduler on the SAME quantized pod.
        from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        def qpod():
            return EnginePod(
                EnginePodConfig(n_pages=128, page_size=4, with_model=True,
                                model_config=TARGET_CFG, max_pages_per_seq=16,
                                use_quantized_kv=True),
                params=TARGET_PARAMS,
            )

        prompts = [list(range(5)), list(range(20, 31))]
        plain = Scheduler(qpod(), max_batch=4)
        pids = [plain.submit(p, max_new_tokens=8) for p in prompts]
        pres = plain.run()

        spec = SpeculativeScheduler(qpod(), DRAFT_CFG, DRAFT_PARAMS, k=3,
                                    max_batch=4)
        sids = [spec.submit(p, max_new_tokens=8) for p in prompts]
        sres = spec.run()
        for pid, sid in zip(pids, sids):
            assert sres[sid] == pres[pid]
        assert spec.stats.proposed > 0

    def test_short_budget_does_not_collapse_batch_speculation(self):
        # ADVICE r2: one sequence a token from max_new_tokens must not
        # drag k_eff to 0 for the whole batch. With per-sequence masking
        # the long-budget sequence keeps proposing at full width.
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        prompts = [list(range(5)), list(range(20, 28))]
        budgets = [2, 12]  # seq 0 hits budget almost immediately
        from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler

        sched = Scheduler(_pod(n_pages=128), max_batch=4)
        pids = [sched.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, budgets)]
        pres = sched.run()

        spec = SpeculativeScheduler(
            _pod(n_pages=128), TARGET_CFG, TARGET_PARAMS, k=3, max_batch=4,
        )
        sids = [spec.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, budgets)]
        sres = spec.run()
        for pid, sid in zip(pids, sids):
            assert sres[sid] == pres[pid]
        # The long sequence generated 12 tokens; with a perfect draft and
        # per-seq masking most of them must have come from proposals —
        # batch-wide min-clamping would leave acceptance near zero once the
        # short sequence neared its budget.
        assert spec.stats.accepted >= 6

    def test_perfect_draft_full_acceptance_after_hole_fix(self):
        # Regression: the draft's final proposal KV must be ingested, or a
        # fully accepted round leaves a zero-KV hole that silently degrades
        # later proposals (observed acceptance 0.77 instead of 1.0).
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        spec = SpeculativeScheduler(
            _pod(n_pages=128), TARGET_CFG, TARGET_PARAMS, k=3, max_batch=4,
        )
        spec.submit(list(range(3, 10)), max_new_tokens=12)
        spec.run()
        assert spec.stats.proposed > 0
        assert spec.stats.acceptance_rate == 1.0

"""SLO autopilot: knobs, rules, hysteresis, and the healthy no-op pin.

Everything runs under injected hand clocks and injected SLO objectives —
no sleeps, no wall time. The keystone property (mirrored end-to-end by
the committed FLEET_BENCH_AUTOPILOT.json healthy arm) is the last class:
an attached autopilot whose signals stay healthy mutates NOTHING — every
owning config dataclass, every knob position, bit-identical to an
autopilot-free process.
"""

import pytest

from llm_d_kv_cache_manager_tpu.api.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
)
from llm_d_kv_cache_manager_tpu.autopilot import (
    AUTOPILOT_KNOBS,
    AutopilotConfig,
    AutopilotController,
    KNOB_ADMISSION_QUEUE,
    KNOB_AUDIT_INTERVAL,
    KNOB_PLACEMENT_K,
    KnobRegistry,
    KnobSpec,
    Rule,
    RULE_DECAY,
    RULE_HIT_RATE,
    SignalAssembler,
    SignalSnapshot,
    default_rules,
)
from llm_d_kv_cache_manager_tpu.obs.slo import (
    OBJECTIVE_HIT_RATE,
    OBJECTIVE_READ_LATENCY,
    SLOConfig,
    SLOMonitor,
    SLOObjective,
    WINDOW_FAST,
    WINDOW_SLOW,
)

pytestmark = pytest.mark.autopilot


class HandClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class Box:
    """Minimal knob owner: one mutable attribute."""

    def __init__(self, value):
        self.value = value


def make_knob(registry, name=KNOB_PLACEMENT_K, value=3.0, floor=1.0,
              ceiling=6.0, max_step=1.0, integer=False):
    box = Box(value)
    knob = registry.register(
        KnobSpec(name=name, floor=floor, ceiling=ceiling,
                 max_step=max_step, integer=integer),
        get=lambda: box.value,
        set_=lambda v: setattr(box, "value", v),
    )
    return box, knob


# -- knobs --------------------------------------------------------------------


class TestKnobs:
    def test_spec_rejects_unknown_names_and_bad_bounds(self):
        with pytest.raises(ValueError, match="AUTOPILOT_KNOBS"):
            KnobSpec(name="router.secret", floor=0, ceiling=1, max_step=1)
        with pytest.raises(ValueError, match="floor"):
            KnobSpec(name=KNOB_PLACEMENT_K, floor=5, ceiling=1, max_step=1)
        with pytest.raises(ValueError, match="max_step"):
            KnobSpec(name=KNOB_PLACEMENT_K, floor=1, ceiling=5, max_step=0)

    def test_baseline_outside_bounds_is_rejected(self):
        registry = KnobRegistry()
        with pytest.raises(ValueError, match="outside"):
            make_knob(registry, value=99.0, ceiling=6.0)

    def test_nudge_clips_to_max_step_then_clamps_to_bounds(self):
        registry = KnobRegistry()
        box, knob = make_knob(registry, value=3.0, max_step=1.0)
        # A huge requested delta applies at most one max_step.
        assert knob.nudge(100.0) == 1.0
        assert box.value == 4.0
        # Landing clamps to the ceiling; a knob pinned there is a no-op.
        knob.nudge(1.0)
        knob.nudge(1.0)
        assert box.value == 6.0
        assert knob.nudge(1.0) == 0.0
        assert box.value == 6.0

    def test_integer_knob_writes_ints(self):
        registry = KnobRegistry()
        box, knob = make_knob(registry, value=3, integer=True)
        knob.nudge(1.0)
        assert box.value == 4 and isinstance(box.value, int)

    def test_revert_step_lands_exactly_on_baseline(self):
        registry = KnobRegistry()
        box, knob = make_knob(registry, value=3.0, max_step=0.75)
        knob.nudge(0.75)
        knob.nudge(0.75)
        assert box.value == 4.5
        assert knob.revert_step() == -0.75
        # Within one max_step of baseline: lands bit-identically on it,
        # not epsilon-close.
        assert knob.revert_step() == -0.75
        assert box.value == 3.0
        assert knob.at_baseline()
        assert knob.revert_step() == 0.0

    def test_registry_rejects_duplicates_and_reports_positions(self):
        registry = KnobRegistry()
        make_knob(registry, name=KNOB_PLACEMENT_K, value=3.0)
        with pytest.raises(ValueError, match="already registered"):
            make_knob(registry, name=KNOB_PLACEMENT_K, value=3.0)
        make_knob(registry, name=KNOB_ADMISSION_QUEUE, value=4.0,
                  floor=1.0, ceiling=16.0)
        assert registry.names() == sorted(
            [KNOB_PLACEMENT_K, KNOB_ADMISSION_QUEUE]
        )
        assert registry.at_baseline()
        registry.get(KNOB_PLACEMENT_K).nudge(1.0)
        assert not registry.at_baseline()
        doc = registry.positions()[KNOB_PLACEMENT_K]
        assert doc["position"] == 4.0 and doc["baseline"] == 3.0


# -- signals ------------------------------------------------------------------


class FakeTransferClient:
    def __init__(self, peers=None):
        self.peers = peers or {}

    def status(self):
        return {"peers": self.peers}


class FakeAntiEntropy:
    def __init__(self, pods=None):
        self.pods = pods or {}

    def status(self):
        return {"pods": self.pods}


class FakePrefetcher:
    def __init__(self, by_source):
        self.by_source = by_source

    def status(self):
        return {"by_source": self.by_source}


class TestSignalAssembler:
    def test_empty_assembler_reads_healthy(self):
        snap = SignalAssembler(clock=HandClock(5.0)).snapshot()
        assert snap.t == 5.0
        assert snap.breaching == () and snap.open_peers == ()
        assert snap.breaker_opens == 0 and snap.prefetch_drops == {}
        assert snap.objective_status(OBJECTIVE_HIT_RATE) == "no_data"

    def test_projects_breakers_trust_and_drops(self):
        client = FakeTransferClient({
            "pod-b:9": {"state": "open", "opens": 3},
            "pod-a:9": {"state": "closed", "opens": 1},
        })
        assembler = SignalAssembler(
            transfer_client=client,
            antientropy=FakeAntiEntropy({
                "pod-a": {"factor": 1.0, "accuracy": 0.9},
                "pod-b": {"factor": 0.25, "accuracy": 0.4},
            }),
            prefetchers={
                "route": FakePrefetcher({"route": {"dropped": 2}}),
                "prediction": FakePrefetcher(
                    {"prediction": {"dropped": 5}, "route": {"dropped": 1}}
                ),
            },
            clock=HandClock(),
        )
        snap = assembler.snapshot(1.0)
        assert snap.open_peers == ("pod-b:9",)
        # Historical trips baseline on the first snapshot: attaching to
        # a fleet with old opens must not read as a live incident.
        assert snap.breaker_opens == 0
        assert snap.distrusted_pods == ("pod-b",)
        assert snap.min_accuracy == 0.4
        assert snap.prefetch_drops == {"route": 3, "prediction": 5}

    def test_breaker_opens_is_a_delta_between_snapshots(self):
        client = FakeTransferClient({
            "pod-b:9": {"state": "open", "opens": 3},
        })
        assembler = SignalAssembler(
            transfer_client=client, clock=HandClock()
        )
        assert assembler.snapshot(1.0).breaker_opens == 0
        client.peers["pod-b:9"]["opens"] = 5
        client.peers["pod-a:9"] = {"state": "closed", "opens": 2}
        assert assembler.snapshot(2.0).breaker_opens == 4
        # Quiet interval reads 0 again — the condition un-latches, so
        # hysteresis can walk the hedge knob home after the incident.
        assert assembler.snapshot(3.0).breaker_opens == 0
        # A peer table that shrank (e.g. a pod replaced) clamps at 0.
        del client.peers["pod-b:9"]
        assert assembler.snapshot(4.0).breaker_opens == 0

    def test_a_raising_source_reads_as_healthy(self):
        class Broken:
            def status(self):
                raise RuntimeError("down")

        snap = SignalAssembler(
            transfer_client=Broken(), antientropy=Broken(),
            prefetchers={"x": Broken()}, clock=HandClock(),
        ).snapshot(1.0)
        assert snap.open_peers == () and snap.distrusted_pods == ()
        assert snap.prefetch_drops == {}


# -- SLOMonitor.burn_history (satellite surface) ------------------------------


def make_monitor(clock, bad_total):
    """Monitor over one injected cumulative counter pair."""
    cfg = SLOConfig(fast_window_s=10.0, slow_window_s=60.0)
    obj = SLOObjective(
        name=OBJECTIVE_READ_LATENCY, description="t", budget=0.1,
        counts_fn=lambda: tuple(bad_total),
    )
    return SLOMonitor([obj], cfg, clock=clock)


class TestBurnHistory:
    def test_series_tracks_the_ring(self):
        clock = HandClock()
        bad_total = [0.0, 0.0]
        mon = make_monitor(clock, bad_total)
        for _ in range(5):
            clock.advance(1.0)
            bad_total[1] += 10.0
            bad_total[0] += 5.0  # 50% bad, budget 0.1 → burn 5.0
            mon.evaluate(clock.t)
        hist = dict(mon.burn_history(OBJECTIVE_READ_LATENCY, WINDOW_FAST))
        assert hist[0.0] == 0.0  # the construction-time baseline sample
        assert hist[5.0] == pytest.approx(5.0)
        # Times ascend, one point per retained sample.
        times = [t for t, _ in
                 mon.burn_history(OBJECTIVE_READ_LATENCY, WINDOW_SLOW)]
        assert times == sorted(times) and len(times) == 6

    def test_each_point_uses_its_own_window_edge(self):
        clock = HandClock()
        bad_total = [0.0, 0.0]
        mon = make_monitor(clock, bad_total)  # fast window = 10s
        # 5 clean seconds, then 10 burning ones.
        for _ in range(5):
            clock.advance(1.0)
            bad_total[1] += 10.0
            mon.evaluate(clock.t)
        for _ in range(10):
            clock.advance(1.0)
            bad_total[1] += 10.0
            bad_total[0] += 10.0  # 100% bad → burn 10.0
            mon.evaluate(clock.t)
        hist = dict(mon.burn_history(OBJECTIVE_READ_LATENCY, WINDOW_FAST))
        assert hist[5.0] == 0.0
        # At t=15 the fast window [5, 15] is entirely bad traffic.
        assert hist[15.0] == pytest.approx(10.0)
        # Mid-ramp the window still holds some clean baseline.
        assert 0.0 < hist[10.0] < 10.0

    def test_unknown_objective_and_window_raise(self):
        mon = make_monitor(HandClock(), [0.0, 0.0])
        with pytest.raises(ValueError, match="SLO_WINDOWS"):
            mon.burn_history(OBJECTIVE_READ_LATENCY, "weird")
        with pytest.raises(ValueError, match="unknown objective"):
            mon.burn_history("nope", WINDOW_FAST)


# -- controller ---------------------------------------------------------------


def make_controller(clock, breaching=False, **cfg_kw):
    """Controller over one hand-made rule conditioned on a mutable flag."""
    flag = {"hot": breaching}
    registry = KnobRegistry()
    box, _ = make_knob(registry, name=KNOB_PLACEMENT_K, value=3.0,
                       ceiling=6.0, max_step=1.0, integer=True)
    rule = Rule(
        name=RULE_HIT_RATE,
        description="test rule",
        condition=lambda snap: flag["hot"],
        nudges=((KNOB_PLACEMENT_K, 1.0),),
    )
    cfg = AutopilotConfig(
        min_interval_s=1.0, warmup_s=5.0, cooldown_s=3.0,
        decay_after_s=6.0, **cfg_kw,
    )
    ctrl = AutopilotController(
        registry, SignalAssembler(clock=clock), config=cfg, rules=[rule],
        clock=clock,
    )
    return ctrl, box, flag


class TestController:
    def test_rule_vocabulary_is_enforced(self):
        with pytest.raises(ValueError, match="AUTOPILOT_RULES"):
            Rule(name="my_rule", description="", condition=lambda s: True,
                 nudges=())

    def test_default_rules_cover_every_burn_signal(self):
        rules = default_rules()
        names = {r.name for r in rules}
        assert names == {
            "read_latency_breach", "hit_rate_burn", "breaker_trips",
            "shed_rate_burn",
        }
        # Every nudged knob is in the fixed vocabulary.
        for rule in rules:
            for knob_name, frac in rule.nudges:
                assert knob_name in AUTOPILOT_KNOBS
                assert frac != 0.0

    def test_warmup_holds_fire(self):
        clock = HandClock()
        ctrl, box, _ = make_controller(clock, breaching=True)
        assert ctrl.tick(0.0) == []  # breaching, but cold
        assert ctrl.tick(clock.advance(2.0)) == []
        assert box.value == 3
        applied = ctrl.tick(clock.advance(4.0))  # t=6 > warmup 5
        assert len(applied) == 1 and box.value == 4

    def test_cooldown_rate_limits_each_rule(self):
        clock = HandClock(10.0)
        ctrl, box, _ = make_controller(clock, breaching=True)
        ctrl.tick(10.0)  # warm-up starts at first tick
        clock.advance(6.0)
        assert len(ctrl.tick(clock.t)) == 1 and box.value == 4
        # Still breaching, but inside the 3s cooldown: no second nudge.
        assert ctrl.tick(clock.advance(1.0)) == []
        assert box.value == 4
        assert len(ctrl.tick(clock.advance(3.0))) == 1
        assert box.value == 5

    def test_min_interval_skips_fast_polls(self):
        clock = HandClock()
        ctrl, _, _ = make_controller(clock)
        ctrl.tick(0.0)
        ctrl.tick(0.5)  # under min_interval_s=1.0
        assert ctrl.stats["ticks"] == 2
        assert ctrl.stats["evaluations"] == 1

    def test_decay_walks_back_to_baseline_and_journal_attributes_it(self):
        clock = HandClock()
        ctrl, box, flag = make_controller(clock, breaching=True)
        ctrl.tick(0.0)
        for _ in range(4):  # fire up to the ceiling region
            ctrl.tick(clock.advance(3.0))
        assert box.value > 3
        peak = box.value
        flag["hot"] = False  # condition clears
        # Inside decay_after_s: knob holds.
        ctrl.tick(clock.advance(3.0))
        assert box.value == peak
        # Once quiet long enough, one bounded revert step per cooldown
        # cadence, attributed to the decay pseudo-rule.
        steps = 0
        while box.value != 3 and steps < 10:
            applied = ctrl.tick(clock.advance(3.0))
            for entry in applied:
                assert entry[1] == RULE_DECAY and entry[3] == "revert"
                assert abs(entry[4]) <= 1.0
            steps += 1
        assert box.value == 3  # bit-identical to the operator's config
        assert ctrl.registry.at_baseline()
        assert ctrl.stats["reverts"] > 0
        # Fully reverted: later quiet ticks journal nothing.
        assert ctrl.tick(clock.advance(3.0)) == []

    def test_breach_during_decay_rearms_the_hold(self):
        clock = HandClock()
        ctrl, box, flag = make_controller(clock, breaching=True)
        ctrl.tick(0.0)
        ctrl.tick(clock.advance(6.0))
        assert box.value == 4
        flag["hot"] = False
        ctrl.tick(clock.advance(3.0))
        flag["hot"] = True  # breaches again before decay_after_s elapses
        ctrl.tick(clock.advance(3.0))
        flag["hot"] = False
        # The quiet timer restarted: 3s later the knob must still hold.
        applied = ctrl.tick(clock.advance(3.0))
        assert all(e[1] != RULE_DECAY for e in applied)

    def test_status_document_shape(self):
        clock = HandClock()
        ctrl, _, _ = make_controller(clock, breaching=True)
        ctrl.tick(0.0)
        ctrl.tick(clock.advance(6.0))
        doc = ctrl.status()
        assert doc["config"]["warmup_s"] == 5.0
        assert KNOB_PLACEMENT_K in doc["knobs"]
        assert not doc["at_baseline"]
        assert doc["rules"][RULE_HIT_RATE]["fired"] == 1
        assert doc["rules"][RULE_HIT_RATE]["touched_knobs"] == [
            KNOB_PLACEMENT_K
        ]
        assert doc["recent_actuations"]
        assert doc["stats"]["actuations"] == 1

    def test_journal_is_bounded(self):
        clock = HandClock()
        ctrl, box, flag = make_controller(clock, breaching=True,
                                          journal_len=4)
        ctrl.tick(0.0)
        for _ in range(8):  # alternate breach/decay to keep actuating
            ctrl.tick(clock.advance(3.0))
            flag["hot"] = not flag["hot"]
            clock.advance(6.0)
        assert len(ctrl.journal) <= 4

    def test_a_raising_rule_condition_reads_as_quiet(self):
        registry = KnobRegistry()
        make_knob(registry, name=KNOB_PLACEMENT_K, value=3.0)
        rule = Rule(
            name=RULE_HIT_RATE, description="",
            condition=lambda snap: 1 / 0,
            nudges=((KNOB_PLACEMENT_K, 1.0),),
        )
        clock = HandClock()
        ctrl = AutopilotController(
            registry, SignalAssembler(clock=clock),
            config=AutopilotConfig(warmup_s=0.0), rules=[rule], clock=clock,
        )
        assert ctrl.tick(0.0) == []
        assert registry.at_baseline()


# -- subsystem knob registration ----------------------------------------------


class TestRegisteredKnobs:
    def test_admission_knob_widens_the_live_waiting_line(self):
        clock = HandClock()
        gate = AdmissionController(
            AdmissionConfig(max_concurrency=1, max_queue_depth=0),
            clock=clock,
        )
        registry = KnobRegistry()
        gate.register_knobs(registry)
        knob = registry.get(KNOB_ADMISSION_QUEUE)
        assert knob is not None and knob.position() == 0.0
        assert knob.spec.floor == 0.0  # never narrows below the baseline
        gate.try_acquire()
        # Baseline: no waiting line at all → immediate queue_full shed.
        with pytest.raises(AdmissionRejected):
            gate.try_acquire(budget_s=0.01)
        knob.nudge(knob.spec.max_step)
        assert gate.config.max_queue_depth > 0  # the very next arrival queues

    def test_auditor_knob_tightens_the_live_cadence(self):
        from llm_d_kv_cache_manager_tpu.antientropy.auditor import (
            AuditorConfig,
            ResidencyAuditor,
        )

        auditor = ResidencyAuditor(
            index=None, model_name="m", digest_fn=lambda *a: None,
            config=AuditorConfig(interval_s=8.0), clock=HandClock(),
        )
        registry = KnobRegistry()
        auditor.register_knobs(registry)
        knob = registry.get(KNOB_AUDIT_INTERVAL)
        knob.nudge(-knob.spec.max_step)
        assert auditor.config.interval_s == 4.0
        # Bounds honor the operator's baseline: floor base/8, ceil base*4.
        assert knob.spec.floor == 1.0 and knob.spec.ceiling == 32.0

    def test_prediction_jobs_floor_is_one_not_zero(self):
        """due_sessions(limit=0) means UNLIMITED — a zeroed knob would
        WIDEN the budget it exists to shrink."""
        from llm_d_kv_cache_manager_tpu.prediction.scheduler import (
            PrefetchScheduler,
            SchedulerConfig,
        )
        from llm_d_kv_cache_manager_tpu.prediction.sessions import (
            SessionTable,
        )

        sched = PrefetchScheduler(
            SessionTable(clock=HandClock()),
            score_fn=lambda *a: None, submit_fn=lambda *a: False,
            config=SchedulerConfig(max_jobs_per_tick=2), clock=HandClock(),
        )
        registry = KnobRegistry()
        sched.register_knobs(registry)
        knob = registry.get("prediction.max_jobs_per_tick")
        assert knob.spec.floor == 1.0
        knob.nudge(-10.0)
        knob.nudge(-10.0)
        assert sched.config.max_jobs_per_tick == 1

    def test_replicator_registers_both_placement_knobs(self):
        from llm_d_kv_cache_manager_tpu.placement.replicator import (
            HotPrefixReplicator,
            ReplicationConfig,
        )
        from llm_d_kv_cache_manager_tpu.placement.popularity import (
            ChainPopularityTracker,
        )

        rep = HotPrefixReplicator(
            ChainPopularityTracker(clock=HandClock()),
            submit_fn=lambda *a: False, pods_fn=lambda: [],
            config=ReplicationConfig(k_replicas=3, max_jobs_per_tick=4),
            clock=HandClock(),
        )
        registry = KnobRegistry()
        rep.register_knobs(registry)
        assert registry.names() == [
            "placement.k_replicas", "placement.max_jobs_per_tick",
        ]
        registry.get(KNOB_PLACEMENT_K).nudge(1.0)
        assert rep.config.k_replicas == 4


# -- dynamic Retry-After (satellite surface) ----------------------------------


class TestRetryAfterPressure:
    def make_gate(self, clock):
        return AdmissionController(
            AdmissionConfig(
                max_concurrency=2, max_queue_depth=0, retry_after_s=1.0,
                retry_after_max_s=8.0, shed_pressure_window_s=5.0,
            ),
            clock=clock,
        )

    def shed_once(self, gate):
        with pytest.raises(AdmissionRejected) as exc:
            gate.try_acquire()
        return exc.value.retry_after_s

    def test_hint_scales_with_live_shed_pressure(self):
        clock = HandClock(100.0)
        gate = self.make_gate(clock)
        gate.try_acquire()
        gate.try_acquire()  # both slots busy; queue depth 0
        # First shed of a burst carries exactly the baseline hint.
        assert self.shed_once(gate) == 1.0
        # Each subsequent shed inside the window backs off harder:
        # scale = 1 + recent/max_concurrency.
        assert self.shed_once(gate) == 1.5
        assert self.shed_once(gate) == 2.0
        # ... clamped at the ceiling under a flood.
        for _ in range(40):
            self.shed_once(gate)
        assert self.shed_once(gate) == 8.0

    def test_pressure_decays_once_the_window_passes(self):
        clock = HandClock(100.0)
        gate = self.make_gate(clock)
        gate.try_acquire()
        gate.try_acquire()
        for _ in range(6):
            self.shed_once(gate)
        assert gate.retry_after_hint() > 1.0
        clock.advance(6.0)  # past shed_pressure_window_s
        assert gate.retry_after_hint() == 1.0
        assert self.shed_once(gate) == 1.0

    def test_status_reports_the_live_hint(self):
        clock = HandClock(100.0)
        gate = self.make_gate(clock)
        doc = gate.status()
        assert doc["retry_after_max_s"] == 8.0
        assert doc["retry_after_hint_s"] == 1.0


# -- the healthy no-op pin ----------------------------------------------------


class TestHealthyBitIdentity:
    def test_attached_autopilot_on_healthy_signals_mutates_nothing(self):
        """The tentpole guarantee, unit-scale: warm controller, live
        monitor, real registered subsystems, healthy signals — many ticks
        later every owning config is bit-identical and the journal is
        empty. (FLEET_BENCH_AUTOPILOT.json pins the same property through
        the full sim.)"""
        from llm_d_kv_cache_manager_tpu.antientropy.auditor import (
            AuditorConfig,
            ResidencyAuditor,
        )

        clock = HandClock()
        bad_total = [0.0, 0.0]
        mon = make_monitor(clock, bad_total)
        gate = AdmissionController(AdmissionConfig(), clock=clock)
        auditor = ResidencyAuditor(
            index=None, model_name="m", digest_fn=lambda *a: None,
            config=AuditorConfig(), clock=clock,
        )
        registry = KnobRegistry()
        gate.register_knobs(registry)
        auditor.register_knobs(registry)
        assembler = SignalAssembler(
            slo_monitor=mon,
            transfer_client=FakeTransferClient(
                {"pod-a:9": {"state": "closed", "opens": 0}}
            ),
            antientropy=FakeAntiEntropy(
                {"pod-a": {"factor": 1.0, "accuracy": 1.0}}
            ),
            clock=clock,
        )
        ctrl = AutopilotController(
            registry, assembler,
            config=AutopilotConfig(warmup_s=0.0), clock=clock,
        )
        before = (repr(gate.config), repr(auditor.config))
        positions_before = {
            name: doc["position"]
            for name, doc in registry.positions().items()
        }
        for _ in range(30):
            clock.advance(2.0)
            bad_total[1] += 100.0  # healthy traffic: zero bad events
            assert ctrl.tick(clock.t) == []
        assert (repr(gate.config), repr(auditor.config)) == before
        assert {
            name: doc["position"]
            for name, doc in registry.positions().items()
        } == positions_before
        assert registry.at_baseline()
        assert len(ctrl.journal) == 0
        assert ctrl.stats["actuations"] == 0
        assert ctrl.stats["evaluations"] == 30
        assert ctrl.last_snapshot is not None
        assert ctrl.last_snapshot.breaching == ()

    def test_snapshot_assembly_is_read_only_over_the_monitor(self):
        """Assembly evaluates the monitor exactly as a /slo/status poll
        would — same sample ring growth, no other state."""
        clock = HandClock()
        bad_total = [0.0, 0.0]
        mon = make_monitor(clock, bad_total)
        assembler = SignalAssembler(slo_monitor=mon, clock=clock)
        evals_before = mon.evaluations
        snap = assembler.snapshot(clock.advance(1.0))
        assert mon.evaluations == evals_before + 1
        assert isinstance(snap, SignalSnapshot)


# -- service wiring -----------------------------------------------------------


def make_service(extra_env=None):
    from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService
    from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
        Indexer,
        IndexerConfig,
    )
    from llm_d_kv_cache_manager_tpu.tokenization.pool import (
        TokenizationPool,
        TokenizersPoolConfig,
    )
    from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON

    indexer = Indexer(
        config=IndexerConfig(),
        tokenization_pool=TokenizationPool(TokenizersPoolConfig(
            workers=1,
            local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
        )),
    )
    env = {
        "zmq_endpoint": "tcp://*:0", "zmq_topic": "kv@",
        "pool_concurrency": 1, "hash_seed": "", "block_size": 16,
        "http_port": 0, "enable_metrics": False,
    }
    env.update(extra_env or {})
    return ScoringService(env, indexer=indexer)


class TestServiceWiring:
    def test_config_from_env_parses_autopilot_block(self, monkeypatch):
        from llm_d_kv_cache_manager_tpu.api.http_service import (
            config_from_env,
        )

        monkeypatch.setenv("AUTOPILOT", "1")
        monkeypatch.setenv("AUTOPILOT_MIN_INTERVAL_S", "2.5")
        monkeypatch.setenv("AUTOPILOT_WARMUP_S", "30")
        monkeypatch.setenv("AUTOPILOT_COOLDOWN_S", "7")
        monkeypatch.setenv("AUTOPILOT_DECAY_AFTER_S", "45")
        env = config_from_env()
        assert env["autopilot"] is True
        assert env["autopilot_min_interval_s"] == 2.5
        assert env["autopilot_warmup_s"] == 30.0
        assert env["autopilot_cooldown_s"] == 7.0
        assert env["autopilot_decay_after_s"] == 45.0
        monkeypatch.delenv("AUTOPILOT")
        assert config_from_env()["autopilot"] is False  # off by default

    def test_disabled_returns_400_and_null_readyz_section(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        service = make_service()
        assert service.autopilot is None

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                assert (await resp.json())["autopilot"] is None
                resp = await client.get("/autopilot/status")
                assert resp.status == 400
                assert "AUTOPILOT=1" in (await resp.json())["error"]

        try:
            asyncio.run(run())
        finally:
            service.stop()

    def test_enabled_service_exposes_status_and_admission_knob(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        service = make_service({
            "autopilot": True,
            "autopilot_warmup_s": 30.0,
        })
        assert service.autopilot is not None
        assert service.autopilot_registry is not None
        # The admission gate published its knob at construction.
        assert service.autopilot_registry.names() == [KNOB_ADMISSION_QUEUE]
        assert service.autopilot.config.warmup_s == 30.0

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.get("/autopilot/status")
                assert resp.status == 200
                doc = await resp.json()
                assert doc["at_baseline"] is True
                assert KNOB_ADMISSION_QUEUE in doc["knobs"]
                assert doc["recent_actuations"] == []
                assert set(doc["rules"]) == {
                    "read_latency_breach", "hit_rate_burn",
                    "breaker_trips", "shed_rate_burn",
                }
                # /readyz embeds the same section and stays ready.
                resp = await client.get("/readyz")
                assert resp.status == 200
                section = (await resp.json())["autopilot"]
                assert section["at_baseline"] is True
                assert section["stats"]["ticks"] >= 1

        try:
            asyncio.run(run())
        finally:
            service.stop()

    def test_late_registered_knob_is_reachable(self):
        """Embedder wiring order: subsystems assigned after construction
        register against service.autopilot_registry and are immediately
        visible to the controller."""
        from llm_d_kv_cache_manager_tpu.antientropy.auditor import (
            AuditorConfig,
            ResidencyAuditor,
        )

        service = make_service({"autopilot": True})
        auditor = ResidencyAuditor(
            index=None, model_name="m", digest_fn=lambda *a: None,
            config=AuditorConfig(),
        )
        service.auditor = auditor
        auditor.register_knobs(service.autopilot_registry)
        assert KNOB_AUDIT_INTERVAL in service.autopilot_registry.names()
        assert (
            service.autopilot.status()["knobs"][KNOB_AUDIT_INTERVAL]
            ["at_baseline"]
        )

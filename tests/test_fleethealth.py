"""Fleet-health subsystem: liveness state machine, bulk quarantine on every
index backend, degraded-mode scoring, and the fault-injection seam.

Everything here is deterministic: injected clocks (no sleeps), seeded RNGs,
CPU only. The fast subset runs in tier-1 (`not slow`).
"""

import pytest

from tests.fake_redis import FakeRedisServer
from llm_d_kv_cache_manager_tpu.fleethealth import (
    HEALTHY,
    STALE,
    SUSPECT,
    FaultInjector,
    FaultPlan,
    FleetHealthConfig,
    FleetHealthTracker,
    PodFaults,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareIndexConfig,
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
    RedisIndex,
    RedisIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
    ShardedIndex,
    ShardedIndexConfig,
)

pytestmark = pytest.mark.faults

MODEL = "m"


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _tracker(clock, index=None, suspect=10.0, stale=30.0, factor=0.5):
    return FleetHealthTracker(
        FleetHealthConfig(
            suspect_after_s=suspect,
            stale_after_s=stale,
            suspect_demotion_factor=factor,
        ),
        index=index,
        clock=clock,
    )


class TestStateMachine:
    def test_healthy_suspect_stale_windows(self):
        clock = Clock()
        tr = _tracker(clock)
        tr.observe_batch("pod-a", "kv@pod-a@m", 0, ts=0.0)
        assert tr.state_of("pod-a") == HEALTHY
        clock.t = 9.9
        assert tr.state_of("pod-a") == HEALTHY
        clock.t = 10.0
        assert tr.state_of("pod-a") == SUSPECT
        clock.t = 29.9
        assert tr.state_of("pod-a") == SUSPECT
        clock.t = 30.0
        assert tr.state_of("pod-a") == STALE

    def test_unknown_pod_is_healthy(self):
        tr = _tracker(Clock())
        assert tr.state_of("never-seen") == HEALTHY

    def test_events_resume_recovers_and_resets_seq_tracking(self):
        clock = Clock()
        tr = _tracker(clock)
        topic = "kv@pod-a@m"
        tr.observe_batch("pod-a", topic, 7, ts=0.0)
        clock.t = 31.0
        assert tr.state_of("pod-a") == STALE
        # A restarted publisher restarts at seq 0: the fresh stream must
        # not be flagged as a giant gap/reorder.
        tr.observe_batch("pod-a", topic, 0, ts=31.0)
        assert tr.state_of("pod-a") == HEALTHY
        summary = tr.summary()
        rec = summary["pods"]["pod-a"]
        assert rec["recoveries"] == 1
        assert rec["reorders"] == 0 and rec["seq_gaps"] == 0

    def test_invalid_windows_rejected(self):
        with pytest.raises(ValueError):
            FleetHealthTracker(
                FleetHealthConfig(suspect_after_s=10.0, stale_after_s=5.0)
            )

    def test_stale_transition_records_detection_latency(self):
        clock = Clock()
        tr = _tracker(clock)
        tr.observe_batch("pod-a", "kv@pod-a@m", 0, ts=0.0)
        clock.t = 42.0
        tr.refresh()
        rec = tr.summary()["pods"]["pod-a"]
        assert rec["state"] == STALE
        assert rec["detection_latency_s"] == pytest.approx(42.0)


class TestGapDetection:
    def test_seq_gap_duplicate_reorder_ts_regression(self):
        clock = Clock()
        tr = _tracker(clock)
        topic = "kv@pod-a@m"
        tr.observe_batch("pod-a", topic, 1, ts=1.0)
        tr.observe_batch("pod-a", topic, 2, ts=2.0)  # in order
        tr.observe_batch("pod-a", topic, 2, ts=2.0)  # duplicate
        tr.observe_batch("pod-a", topic, 5, ts=3.0)  # gap of 2
        tr.observe_batch("pod-a", topic, 4, ts=2.5)  # reorder
        tr.observe_batch("pod-a", topic, 6, ts=0.1)  # ts regression (>1s)
        totals = tr.anomaly_totals()
        assert totals["duplicates"] == 1
        assert totals["seq_gaps"] == 1 and totals["gap_events"] == 2
        assert totals["reorders"] == 1
        assert totals["ts_regressions"] == 1

    def test_per_topic_seq_spaces_are_independent(self):
        tr = _tracker(Clock())
        tr.observe_batch("pod-a", "kv@pod-a@m1", 5, ts=1.0)
        tr.observe_batch("pod-a", "kv@pod-a@m2", 1, ts=1.0)
        assert tr.anomaly_totals()["seq_gaps"] == 0

    def test_decode_failure_does_not_stamp_liveness(self):
        clock = Clock()
        tr = _tracker(clock)
        tr.observe_batch("pod-a", "kv@pod-a@m", 0, ts=0.0)
        clock.t = 31.0
        tr.observe_decode_failure("pod-a")  # garbage is not liveness
        assert tr.state_of("pod-a") == STALE
        assert tr.anomaly_totals()["decode_failures"] == 1


def _seed(index, pod_entries, n_keys=4, base=0):
    """Store n_keys chained blocks held by `pod_entries`."""
    request_keys = [Key(MODEL, base + i) for i in range(n_keys)]
    engine_keys = [Key(MODEL, 10_000 + base + i) for i in range(n_keys)]
    index.add(engine_keys, request_keys, pod_entries)
    return engine_keys, request_keys


def _backends():
    return [
        ("in_memory", lambda: InMemoryIndex(InMemoryIndexConfig(size=1000))),
        ("sharded", lambda: ShardedIndex(ShardedIndexConfig(size=1000, num_shards=4))),
        (
            "cost_aware",
            lambda: CostAwareMemoryIndex(CostAwareIndexConfig(max_size_bytes="64KiB")),
        ),
    ]


class TestRemovePod:
    @pytest.mark.parametrize("name,make", _backends())
    def test_remove_pod_purges_only_that_pod(self, name, make):
        index = make()
        entries = [
            PodEntry("gone", "hbm"),
            PodEntry("gone@dp1", "hbm"),  # DP rank of the same pod
            PodEntry("stays", "hbm"),
        ]
        engine_keys, request_keys = _seed(index, entries)
        removed = index.remove_pod("gone")
        # 2 entries (bare + ranked) per key.
        assert removed == 2 * len(request_keys)
        hits = index.lookup(request_keys, set())
        assert set(hits) == set(request_keys)
        for key_entries in hits.values():
            assert {e.pod_identifier for e in key_entries} == {"stays"}
        # Idempotent.
        assert index.remove_pod("gone") == 0

    @pytest.mark.parametrize("name,make", _backends())
    def test_remove_last_pod_drops_both_key_spaces(self, name, make):
        index = make()
        engine_keys, request_keys = _seed(index, [PodEntry("solo", "hbm")])
        assert index.remove_pod("solo") == len(request_keys)
        assert index.lookup(request_keys, set()) == {}
        for ek in engine_keys:
            assert index.get_request_key(ek) is None

    @pytest.mark.parametrize("name,make", _backends())
    def test_ranked_identity_removes_only_that_rank(self, name, make):
        index = make()
        entries = [PodEntry("p@dp0", "hbm"), PodEntry("p@dp1", "hbm")]
        _, request_keys = _seed(index, entries)
        removed = index.remove_pod("p@dp0")
        assert removed == len(request_keys)
        hits = index.lookup(request_keys, set())
        for key_entries in hits.values():
            assert {e.pod_identifier for e in key_entries} == {"p@dp1"}

    def test_remove_pod_redis(self):
        server = FakeRedisServer()
        try:
            index = RedisIndex(RedisIndexConfig(url=server.url))
            entries = [
                PodEntry("gone", "hbm"),
                PodEntry("gone@dp1", "hbm"),
                PodEntry("stays", "hbm"),
            ]
            engine_keys, request_keys = _seed(index, entries)
            removed = index.remove_pod("gone")
            assert removed == 2 * len(request_keys)
            hits = index.lookup(request_keys, set())
            assert set(hits) == set(request_keys)
            for key_entries in hits.values():
                assert {e.pod_identifier for e in key_entries} == {"stays"}
            # Removing the survivor empties the hashes AND the engine
            # mappings behind them.
            assert index.remove_pod("stays") == len(request_keys)
            assert index.lookup(request_keys, set()) == {}
            for ek in engine_keys:
                assert index.get_request_key(ek) is None
            index.close()
        finally:
            server.close()

    def test_sharded_read_view_pruned(self):
        # The lock-free read view must not resurrect purged placements.
        index = ShardedIndex(ShardedIndexConfig(size=1000, num_shards=4))
        _, request_keys = _seed(index, [PodEntry("gone", "hbm")])
        assert index.lookup(request_keys, set())  # view populated
        index.remove_pod("gone")
        assert index.lookup(request_keys, set()) == {}


class TestDegradedScoring:
    def _scores(self):
        return {"pod-a": 4.0, "pod-b": 3.0}

    def test_all_healthy_is_identity(self):
        clock = Clock()
        tr = _tracker(clock)
        tr.observe_batch("pod-a", "t", 0, ts=0.0)
        tr.observe_batch("pod-b", "t", 0, ts=0.0)
        scores = self._scores()
        # Same object back: the no-fault read path is bit-identical.
        assert tr.filter_scores(scores) is scores

    def test_suspect_demoted_stale_excluded(self):
        clock = Clock()
        index = InMemoryIndex()
        tr = _tracker(clock, index=index)
        tr.observe_batch("pod-a", "t", 0, ts=0.0)
        clock.t = 5.0
        tr.observe_batch("pod-b", "t", 0, ts=5.0)
        clock.t = 12.0  # pod-a quiet 12s: suspect; pod-b quiet 7s: healthy
        assert tr.filter_scores(self._scores()) == {
            "pod-a": 2.0, "pod-b": 3.0
        }
        clock.t = 31.0  # pod-a stale; pod-b suspect
        assert tr.filter_scores(self._scores()) == {"pod-b": 1.5}

    def test_all_stale_empties_scores(self):
        clock = Clock()
        tr = _tracker(clock)
        tr.observe_batch("pod-a", "t", 0, ts=0.0)
        tr.observe_batch("pod-b", "t", 0, ts=0.0)
        clock.t = 100.0
        assert tr.filter_scores(self._scores()) == {}

    def test_stale_transition_purges_index(self):
        clock = Clock()
        index = InMemoryIndex()
        tr = _tracker(clock, index=index)
        _, request_keys = _seed(index, [PodEntry("pod-a", "hbm")])
        tr.observe_batch("pod-a", "t", 0, ts=0.0)
        clock.t = 31.0
        tr.refresh()
        assert index.lookup(request_keys, set()) == {}
        assert tr.summary()["pods"]["pod-a"]["purged_entries"] == len(
            request_keys
        )

    def test_quarantine_is_explicit_remove(self):
        clock = Clock()
        index = InMemoryIndex()
        tr = _tracker(clock, index=index)
        _, request_keys = _seed(index, [PodEntry("pod-x", "hbm")])
        removed = tr.quarantine("pod-x")
        assert removed == len(request_keys)
        assert tr.state_of("pod-x") == STALE
        assert index.lookup(request_keys, set()) == {}


class TestIndexerIntegration:
    def test_get_pod_scores_excludes_stale_pod(self, test_tokenizer_files):
        from tests.conftest import TEST_MODEL_NAME
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
            Indexer,
            IndexerConfig,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            TokenProcessorConfig,
        )
        from llm_d_kv_cache_manager_tpu.tokenization.pool import (
            TokenizationPool,
            TokenizersPoolConfig,
        )

        clock = Clock()
        tr = _tracker(clock)
        indexer = Indexer(
            config=IndexerConfig(
                token_processor_config=TokenProcessorConfig(block_size=4),
            ),
            tokenization_pool=TokenizationPool(
                TokenizersPoolConfig(
                    workers=1, local_tokenizer_files=test_tokenizer_files
                ),
            ),
            fleet_health=tr,
        )
        indexer.run()
        try:
            assert tr.index is indexer.kv_block_index  # auto-bound
            prompt = "the quick brown fox jumps over the lazy dog " * 2
            tokens = indexer.tokenizers_pool.tokenize(
                None, prompt, TEST_MODEL_NAME
            )
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                None, tokens, TEST_MODEL_NAME
            )
            engine_keys = [
                Key(TEST_MODEL_NAME, 50_000 + i) for i in range(len(keys))
            ]
            indexer.kv_block_index.add(
                engine_keys, keys, [PodEntry("pod-z", "hbm")]
            )
            tr.observe_batch("pod-z", "t", 0, ts=0.0)
            scores = indexer.get_pod_scores(prompt, TEST_MODEL_NAME, [])
            assert scores.get("pod-z", 0) > 0
            clock.t = 100.0  # silence -> stale -> excluded AND purged
            assert indexer.get_pod_scores(prompt, TEST_MODEL_NAME, []) == {}
            assert indexer.kv_block_index.lookup(keys, set()) == {}
        finally:
            indexer.shutdown()


class TestFaultInjector:
    def _plan(self, **faults):
        return FaultPlan(seed=7, pods={"p": PodFaults(**faults)})

    def test_unfaulted_pod_is_passthrough(self):
        inj = FaultInjector(self._plan(), clock=lambda: 0.0)
        sent = []
        deliver = sent.append
        assert inj.wrap("other", deliver) is deliver  # literally unwrapped

    def test_crash_window_swallows_then_restores(self):
        clock = Clock()
        inj = FaultInjector(
            self._plan(crash_at_s=1.0, restart_at_s=2.0), clock=clock
        )
        sent = []
        d = inj.wrap("p", sent.append)
        clock.t = 0.5
        d("before")
        clock.t = 1.5
        d("during")
        clock.t = 2.5
        d("after")
        assert sent == ["before", "after"]
        assert inj.injected["crash_dropped"] == 1

    def test_stall_window(self):
        clock = Clock()
        inj = FaultInjector(
            self._plan(stall_from_s=1.0, stall_until_s=2.0), clock=clock
        )
        sent = []
        d = inj.wrap("p", sent.append)
        for t, m in ((0.5, "a"), (1.5, "b"), (2.1, "c")):
            clock.t = t
            d(m)
        assert sent == ["a", "c"]
        assert inj.injected["stall_dropped"] == 1

    def test_drop_duplicate_reorder_deterministic(self):
        inj = FaultInjector(
            FaultPlan(seed=123, pods={"p": PodFaults(
                drop_rate=0.2, duplicate_rate=0.2, reorder_rate=0.2
            )}),
            clock=lambda: 0.0,
        )
        sent = []
        d = inj.wrap("p", sent.append)
        for i in range(200):
            d(i)
        inj.flush()
        counts = dict(inj.injected)
        assert counts["dropped"] > 0
        assert counts["duplicated"] > 0
        assert counts["reordered"] > 0
        # Conservation: every non-dropped message was delivered (dups extra).
        assert len(sent) == 200 - counts["dropped"] + counts["duplicated"]
        # Deterministic under the same seed.
        inj2 = FaultInjector(
            FaultPlan(seed=123, pods={"p": PodFaults(
                drop_rate=0.2, duplicate_rate=0.2, reorder_rate=0.2
            )}),
            clock=lambda: 0.0,
        )
        sent2 = []
        d2 = inj2.wrap("p", sent2.append)
        for i in range(200):
            d2(i)
        inj2.flush()
        assert sent2 == sent and dict(inj2.injected) == counts

    def test_reorder_swaps_adjacent(self):
        inj = FaultInjector(
            FaultPlan(seed=0, pods={"p": PodFaults(reorder_rate=1.0)}),
            clock=lambda: 0.0,
        )
        sent = []
        d = inj.wrap("p", sent.append)
        for i in range(4):
            d(i)
        assert sent == [1, 0, 3, 2]


class TestSubscriberBackoff:
    def test_capped_exponential_schedule(self):
        from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
            backoff_delay,
        )

        delays = [
            backoff_delay(n, base=0.5, cap=8.0, jitter=0.0)
            for n in range(1, 8)
        ]
        assert delays == [0.5, 1.0, 2.0, 4.0, 8.0, 8.0, 8.0]

    def test_jitter_bounded(self):
        from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
            backoff_delay,
        )

        for _ in range(50):
            d = backoff_delay(1, base=1.0, cap=8.0, jitter=0.25)
            assert 1.0 <= d <= 1.25


class TestRedisBackoffConfig:
    def test_backoff_grows_and_resets(self):
        server = FakeRedisServer()
        index = RedisIndex(RedisIndexConfig(
            url=server.url,
            timeout_s=0.5,
            reconnect_backoff_s=0.05,
            reconnect_backoff_max_s=0.2,
            reconnect_jitter=0.0,
        ))
        try:
            with index._mu:
                d1 = index._backoff_delay_locked()
                d2 = index._backoff_delay_locked()
                d3 = index._backoff_delay_locked()
                d4 = index._backoff_delay_locked()
            assert (d1, d2, d3) == (0.05, 0.1, 0.2)
            assert d4 == 0.2  # capped
            # Jitter stretches by at most the configured fraction.
            index.config.reconnect_jitter = 0.5
            with index._mu:
                index._consecutive_failures = 0
                d = index._backoff_delay_locked()
            assert 0.05 <= d <= 0.075
        finally:
            index.close()
            server.close()

"""On-device sampling: determinism, chunking-invariance, and filter
semantics.

The contract (ops/sampling.py): per-request randomness comes from
fold_in(base_key, position), so a request's output is identical across
decode_steps settings, batch compositions, and reruns — and temperature 0
(or top_k=1, or top_p→0) degenerates to exactly the greedy path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig
from llm_d_kv_cache_manager_tpu.ops.sampling import (
    SamplingParams,
    position_keys,
    sample_tokens,
)

CFG = LlamaConfig(
    vocab_size=128, d_model=32, n_layers=2, n_q_heads=2, n_kv_heads=2,
    head_dim=16, d_ff=64, dtype=jnp.float32,
)
PARAMS = llama.init_params(CFG, jax.random.PRNGKey(0))
PROMPT = [3, 17, 99, 4, 250 % 128, 7]


def _pod():
    return EnginePod(
        EnginePodConfig(n_pages=64, page_size=4, with_model=True,
                        model_config=CFG, max_pages_per_seq=16),
        params=PARAMS,
    )


def _generate(sampling, decode_steps=1, n_new=12, prompt=None):
    pod = _pod()
    try:
        sched = Scheduler(pod, max_batch=2, decode_steps=decode_steps)
        rid = sched.submit(list(prompt or PROMPT), max_new_tokens=n_new,
                           sampling=sampling)
        return sched.run()[rid]
    finally:
        pod.close()


class TestSampleTokensUnit:
    """Direct unit semantics of the batched filter/sampling op."""

    def _logits(self, batch=4, vocab=64, seed=1):
        return jax.random.normal(jax.random.PRNGKey(seed), (batch, vocab)) * 3

    def test_temperature_zero_is_argmax(self):
        logits = self._logits()
        keys = position_keys(
            jnp.stack([jax.random.PRNGKey(i) for i in range(4)]),
            jnp.arange(4),
        )
        out = sample_tokens(
            logits, jnp.zeros(4), jnp.zeros(4, jnp.int32), jnp.ones(4), keys
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(jnp.argmax(logits, -1))
        )

    def test_top_k_one_is_argmax_at_any_temperature(self):
        logits = self._logits()
        keys = position_keys(
            jnp.stack([jax.random.PRNGKey(i) for i in range(4)]),
            jnp.arange(4),
        )
        out = sample_tokens(
            logits, jnp.full(4, 5.0), jnp.ones(4, jnp.int32), jnp.ones(4),
            keys,
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(jnp.argmax(logits, -1))
        )

    def test_tiny_top_p_is_argmax(self):
        logits = self._logits()
        keys = position_keys(
            jnp.stack([jax.random.PRNGKey(i) for i in range(4)]),
            jnp.arange(4),
        )
        out = sample_tokens(
            logits, jnp.full(4, 3.0), jnp.zeros(4, jnp.int32),
            jnp.full(4, 1e-6), keys,
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(jnp.argmax(logits, -1))
        )

    def test_top_p_zero_is_argmax_not_token_zero(self):
        """top_p=0 must clamp to greedy — an empty kept set would make
        argmax over all -inf emit token id 0 for every draw."""
        logits = self._logits()
        keys = position_keys(
            jnp.stack([jax.random.PRNGKey(i) for i in range(4)]),
            jnp.arange(4),
        )
        out = sample_tokens(
            logits, jnp.full(4, 2.0), jnp.zeros(4, jnp.int32),
            jnp.zeros(4), keys,
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(jnp.argmax(logits, -1))
        )

    def test_top_k_restricts_support(self):
        """1000 draws at high temperature never leave the top-k set."""
        vocab = 32
        logits = jax.random.normal(jax.random.PRNGKey(2), (1, vocab))
        top5 = set(np.asarray(jnp.argsort(-logits[0])[:5]).tolist())
        base = jnp.stack([jax.random.PRNGKey(9)])
        seen = set()
        for pos in range(1000):
            out = sample_tokens(
                jnp.tile(logits, (1, 1)), jnp.full(1, 10.0),
                jnp.full(1, 5, jnp.int32), jnp.ones(1),
                position_keys(base, jnp.array([pos])),
            )
            seen.add(int(out[0]))
        assert seen <= top5
        assert len(seen) > 1  # actually random, not degenerate

    def test_rows_are_independent(self):
        """A row's draw depends only on its own key, not batch contents."""
        logits = self._logits(batch=3)
        keys = position_keys(
            jnp.stack([jax.random.PRNGKey(i) for i in range(3)]),
            jnp.array([7, 7, 7]),
        )
        full = sample_tokens(
            logits, jnp.full(3, 2.0), jnp.zeros(3, jnp.int32),
            jnp.full(3, 0.9), keys,
        )
        solo = sample_tokens(
            logits[1:2], jnp.full(1, 2.0), jnp.zeros(1, jnp.int32),
            jnp.full(1, 0.9), keys[1:2],
        )
        assert int(full[1]) == int(solo[0])


class TestServingSampling:
    def test_greedy_default_unchanged(self):
        assert _generate(None) == _generate(SamplingParams())

    def test_seeded_runs_reproduce(self):
        sp = SamplingParams(temperature=1.0, top_k=20, seed=42)
        assert _generate(sp) == _generate(sp)

    def test_decode_steps_invariant(self):
        """The multi-step on-device loop must sample the SAME sequence as
        single-step decode — per-position keys make chunking invisible."""
        sp = SamplingParams(temperature=1.0, top_k=20, seed=7)
        assert _generate(sp, decode_steps=1) == _generate(sp, decode_steps=4)

    def test_seeds_differentiate(self):
        outs = {
            tuple(_generate(SamplingParams(temperature=2.0, seed=s)))
            for s in range(5)
        }
        assert len(outs) > 1

    def test_sampled_differs_from_greedy_sometimes(self):
        greedy = _generate(None)
        outs = [
            _generate(SamplingParams(temperature=3.0, seed=s))
            for s in range(4)
        ]
        assert any(o != greedy for o in outs)

    def test_mixed_batch_greedy_row_unperturbed(self):
        """Greedy and sampled requests in one batch: the greedy request's
        output must equal its solo-run output."""
        pod = _pod()
        try:
            sched = Scheduler(pod, max_batch=4, decode_steps=2)
            rid_g = sched.submit(list(PROMPT), max_new_tokens=10)
            rid_s = sched.submit(
                [5, 9, 2, 44], max_new_tokens=10,
                sampling=SamplingParams(temperature=1.5, seed=3),
            )
            results = sched.run()
        finally:
            pod.close()
        assert results[rid_g] == _generate(None, n_new=10)
        assert len(results[rid_s]) == 10

    def test_preemption_does_not_change_sampled_output(self):
        """Position-keyed sampling + deterministic recompute: a preempted
        sampled request resumes mid-stream with identical output (tokens at
        already-sampled positions fold into the prompt; later positions
        draw the same keys)."""
        sp = SamplingParams(temperature=1.0, top_k=30, seed=11)
        reference = _generate(sp, n_new=10)
        # Tiny pool forces decode-time preemption of one of two requests.
        pod = EnginePod(
            EnginePodConfig(n_pages=10, page_size=4, with_model=True,
                            model_config=CFG, max_pages_per_seq=8),
            params=PARAMS,
        )
        try:
            sched = Scheduler(pod, max_batch=2)
            rid = sched.submit(list(PROMPT), max_new_tokens=10, sampling=sp)
            other = sched.submit([8, 1, 60], max_new_tokens=10)
            results = sched.run()
        finally:
            pod.close()
        assert results[rid] == reference
        assert len(results[other]) == 10

    def test_spec_decoder_speculative_sampling(self):
        """Speculative sampling on the single-sequence decoder: seeded runs
        reproduce; temperature 0 equals greedy speculation; a draft that
        EQUALS the target accepts every proposal (q == p => ratio 1)."""
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeDecoder,
        )

        draft_cfg = LlamaConfig(
            vocab_size=128, d_model=16, n_layers=1, n_q_heads=2,
            n_kv_heads=2, head_dim=8, d_ff=32, dtype=jnp.float32,
        )
        draft_params = llama.init_params(draft_cfg, jax.random.PRNGKey(5))
        sp = SamplingParams(temperature=1.0, top_k=50, seed=21)

        def spec_generate(sampling, draft_c=draft_cfg, draft_p=draft_params):
            pod = _pod()
            try:
                dec = SpeculativeDecoder(
                    pod, draft_config=draft_c, draft_params=draft_p, k=3
                )
                out = dec.generate(list(PROMPT), max_new_tokens=10,
                                   sampling=sampling)
                return out, dec.stats
            finally:
                pod.close()

        out1, _ = spec_generate(sp)
        out2, _ = spec_generate(sp)
        assert out1 == out2
        assert len(out1) == 10

        greedy_spec, _ = spec_generate(SamplingParams())
        greedy_plain, _ = spec_generate(None)
        assert greedy_spec == greedy_plain == _generate(None, n_new=10)

        # Perfect draft: q == p at every position => certain acceptance.
        _, stats = spec_generate(sp, draft_c=CFG, draft_p=PARAMS)
        assert stats.proposed > 0
        assert stats.accepted == stats.proposed

        # Unseeded calls must be independent draws (best-of-n must not
        # collapse): one decoder, several generates, high temperature.
        pod = _pod()
        try:
            dec = SpeculativeDecoder(
                pod, draft_config=draft_cfg, draft_params=draft_params, k=3
            )
            unseeded = SamplingParams(temperature=3.0)
            outs = {
                tuple(dec.generate(list(PROMPT), max_new_tokens=8,
                                   sampling=unseeded))
                for _ in range(3)
            }
        finally:
            pod.close()
        assert len(outs) > 1

    def test_accept_or_resample_preserves_target_distribution(self):
        """The speculative-sampling acceptance rule's emitted-token law
        must be EXACTLY q regardless of the draft p: empirical check over
        20k trials on a fixed (q, p) pair with disjoint-ish supports."""
        from llm_d_kv_cache_manager_tpu.ops.sampling import accept_or_resample

        vocab = 12
        rng = np.random.default_rng(0)
        q = rng.dirichlet(np.ones(vocab) * 0.5)
        p = rng.dirichlet(np.ones(vocab) * 0.5)
        qj = jnp.asarray(q, jnp.float32)
        pj = jnp.asarray(p, jnp.float32)

        n = 20000
        keys = jax.vmap(jax.random.fold_in, (None, 0))(
            jax.random.PRNGKey(3), jnp.arange(n)
        )
        # Proposals drawn from p with an independent stream.
        prop_keys = jax.vmap(jax.random.fold_in, (None, 0))(
            jax.random.PRNGKey(4), jnp.arange(n)
        )
        proposals = jax.vmap(
            lambda k: jax.random.categorical(k, jnp.log(pj))
        )(prop_keys).astype(jnp.int32)
        tokens, accepted = jax.vmap(accept_or_resample, (None, None, 0, 0))(
            qj, pj, proposals, keys
        )
        counts = np.bincount(np.asarray(tokens), minlength=vocab)
        empirical = counts / n
        # Total-variation distance: ~O(sqrt(V/n)) noise floor.
        tv = 0.5 * np.abs(empirical - q).sum()
        assert tv < 0.02, (tv, empirical, q)
        # Sanity: the acceptance rate equals sum_x min(q, p) in expectation.
        expected_acc = np.minimum(q, p).sum()
        acc = float(jnp.mean(accepted))
        assert abs(acc - expected_acc) < 0.02

    def test_batched_speculative_sampling(self):
        """The batched SpeculativeScheduler serves sampled requests with
        the accept/resample rule: seeded runs reproduce; a greedy request
        mixed into the same batch still matches the plain scheduler's
        greedy output; a perfect draft accepts every sampled proposal."""
        from llm_d_kv_cache_manager_tpu.engine.speculative import (
            SpeculativeScheduler,
        )

        draft_cfg = LlamaConfig(
            vocab_size=128, d_model=16, n_layers=1, n_q_heads=2,
            n_kv_heads=2, head_dim=8, d_ff=32, dtype=jnp.float32,
        )
        draft_params = llama.init_params(draft_cfg, jax.random.PRNGKey(5))
        sp = SamplingParams(temperature=1.0, top_k=50, seed=33)

        def spec_run(draft_c=draft_cfg, draft_p=draft_params):
            pod = _pod()
            try:
                spec = SpeculativeScheduler(
                    pod, draft_config=draft_c, draft_params=draft_p,
                    k=2, max_batch=4,
                )
                rid_s = spec.submit(list(PROMPT), max_new_tokens=10,
                                    sampling=sp)
                rid_g = spec.submit([5, 9, 2, 44], max_new_tokens=10)
                res = spec.run()
                return res[rid_s], res[rid_g], spec.stats
            finally:
                pod.close()

        s1, g1, _ = spec_run()
        s2, g2, _ = spec_run()
        assert s1 == s2 and g1 == g2  # seeded + greedy both reproduce
        assert len(s1) == 10

        # The co-batched greedy request matches plain-scheduler greedy.
        pod = _pod()
        try:
            sched = Scheduler(pod, max_batch=1)
            rid = sched.submit([5, 9, 2, 44], max_new_tokens=10)
            plain = sched.run()[rid]
        finally:
            pod.close()
        assert g1 == plain

        # Perfect draft (q == p): every sampled proposal accepted.
        _, _, stats = spec_run(draft_c=CFG, draft_p=PARAMS)
        assert stats.proposed > 0
        assert stats.accepted == stats.proposed

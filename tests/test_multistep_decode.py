"""On-device multi-step decode tests (VERDICT r2 #2).

`decode_multi_step_cache` runs N decode steps in one dispatch (lax.scan +
on-device argmax + in-loop page-table walk). The contract: greedy output
and cache contents are identical to N sequential `decode_step_cache`
dispatches, per-sequence budgets mask (not clamp) the batch, and the
scheduler on decode_steps=N emits exactly what decode_steps=1 does.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

CFG = LlamaConfig(
    vocab_size=128, d_model=32, n_layers=1, n_q_heads=2, n_kv_heads=2,
    head_dim=16, d_ff=64, dtype=jnp.float32,
)


class TestMultiStepOp:
    def _setup(self, quantized=False):
        params = llama.init_params(CFG, jax.random.PRNGKey(0))
        make = (
            llama.make_kv_pages_quantized if quantized else llama.make_kv_pages
        )
        cache = make(CFG, 17, 4)  # 16 real pages + trash page 16
        prompt = jnp.arange(7, dtype=jnp.int32)
        table = jnp.arange(4, dtype=jnp.int32)
        cache, logits = llama.prefill_cache(CFG, params, cache, prompt, table, 0)
        pending = jnp.argmax(logits)[None].astype(jnp.int32)
        return params, cache, pending, table

    @pytest.mark.parametrize("quantized", [False, True])
    def test_equals_sequential_steps(self, quantized):
        n = 5
        params, cache, pending, table = self._setup(quantized)

        # Sequential oracle: n plain decode dispatches.
        seq_cache, tok = cache, pending
        seq_tokens = []
        for i in range(n):
            seq_cache, logits = llama.decode_step_cache(
                CFG, params, seq_cache, tok, table[None],
                jnp.asarray([7 + i], jnp.int32),
            )
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            seq_tokens.append(int(tok[0]))

        params2, cache2, pending2, _ = self._setup(quantized)
        cache2, toks = llama.decode_multi_step_cache(
            CFG, params2, cache2, pending2, table[None],
            jnp.asarray([7], jnp.int32), jnp.asarray([7 + n], jnp.int32),
            16, n,
        )
        assert list(np.asarray(toks)[0]) == seq_tokens
        # Cache contents match row-for-row (positions 0..7+n-1).
        for a, b in zip(seq_cache, cache2):
            np.testing.assert_allclose(
                np.asarray(a[:, :, :4]).astype(np.float32),
                np.asarray(b[:, :, :4]).astype(np.float32),
                rtol=1e-6, atol=1e-6,
            )

    def test_capacity_mask_steers_overflow_to_trash(self):
        n = 6
        params, cache, pending, table = self._setup()
        # Allow only 2 rows (max_len = 9); steps beyond write the trash page.
        before_real = np.asarray(cache[0][:, :, :16]).copy()
        cache2, toks = llama.decode_multi_step_cache(
            CFG, params, cache, pending, table[None],
            jnp.asarray([7], jnp.int32), jnp.asarray([9], jnp.int32),
            16, n,
        )
        after_real = np.asarray(cache2[0][:, :, :16])
        # Rows 7 and 8 (page 1/2, slots 3/0) changed; nothing past position 9.
        page2 = after_real[:, :, 2]
        assert not np.any(page2[:, :, 1:])  # slots 1..3 of page 2 untouched
        # Trash page received writes.
        assert np.any(np.asarray(cache2[0][:, :, 16]))
        # First 2 tokens match the unrestricted run's first 2.
        params3, cache3, pending3, _ = self._setup()
        _, toks_full = llama.decode_multi_step_cache(
            CFG, params3, cache3, pending3, table[None],
            jnp.asarray([7], jnp.int32), jnp.asarray([7 + n], jnp.int32),
            16, n,
        )
        assert list(np.asarray(toks)[0][:2]) == list(np.asarray(toks_full)[0][:2])


def _run_sched(decode_steps, prompts, max_new, n_pages=64, eos=None):
    pod = EnginePod(
        EnginePodConfig(
            n_pages=n_pages, page_size=4, with_model=True, model_config=CFG,
            max_pages_per_seq=16,
        )
    )
    sched = Scheduler(pod, max_batch=4, decode_steps=decode_steps)
    ids = [
        sched.submit(p, max_new_tokens=m, eos_token=eos)
        for p, m in zip(prompts, max_new)
    ]
    results = sched.run()
    return [results[i] for i in ids], pod


class TestMultiStepScheduler:
    def test_output_identical_to_single_step(self):
        prompts = [list(range(5)), list(range(20, 31)), list(range(40, 47))]
        # Budgets deliberately not multiples of N, and unequal — the
        # per-sequence masking must not let one short budget collapse the
        # batch (the ADVICE r2 k_eff pattern).
        max_new = [7, 3, 10]
        ref, _ = _run_sched(1, prompts, max_new)
        multi, _ = _run_sched(4, prompts, max_new)
        assert multi == ref

    def test_eos_mid_window_matches(self):
        # Find the 3rd generated token of a prompt, use it as EOS so it
        # lands mid-window for N=4.
        probe, _ = _run_sched(1, [list(range(8))], [6])
        eos = probe[0][2]
        ref, _ = _run_sched(1, [list(range(8))], [10], eos=eos)
        multi, _ = _run_sched(4, [list(range(8))], [10], eos=eos)
        assert multi == ref

    def test_preemption_under_page_pressure_matches(self):
        prompts = [list(range(8)), list(range(50, 58))]
        ref, _ = _run_sched(1, prompts, [8, 8], n_pages=8)
        multi, _ = _run_sched(4, prompts, [8, 8], n_pages=8)
        assert multi == ref

    def test_prefix_cache_state_matches_single_step(self):
        # The multi-step path must commit exactly the pages the single-step
        # path does (pending-token rule intact): a follow-up request sees
        # the same cached-token count.
        prompts = [list(range(12))]
        ref, pod1 = _run_sched(1, prompts, [9])
        multi, pod4 = _run_sched(4, prompts, [9])
        assert multi == ref
        full = prompts[0] + ref[0]
        s1 = pod1.block_manager.allocate(full)
        s4 = pod4.block_manager.allocate(full)
        assert s1.num_cached_tokens == s4.num_cached_tokens

    def test_validation(self):
        pod = EnginePod(
            EnginePodConfig(
                n_pages=8, page_size=4, with_model=True, model_config=CFG,
            )
        )
        with pytest.raises(ValueError, match="decode_steps"):
            Scheduler(pod, decode_steps=0)

"""Int8 KV cache tests: quantization round trip + kernel vs oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.ops.paged_attention import (
    paged_attention_reference,
    write_kv_pages,
)
from llm_d_kv_cache_manager_tpu.ops.quantized_kv import (
    dequantize_rows,
    make_quantized_kv_pages,
    paged_attention_quantized,
    paged_attention_quantized_reference,
    quantize_rows,
    write_kv_pages_quantized,
)


class TestQuantization:
    def test_round_trip_error_bounded(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 4, 128)) * 3
        q, scale = quantize_rows(x)
        assert q.dtype == jnp.int8
        restored = dequantize_rows(q, scale)
        # Per-row amax/127 quantization: error <= scale/2 per element.
        max_err = float(jnp.max(jnp.abs(restored - x)))
        max_allowed = float(jnp.max(scale)) * 0.5 + 1e-6
        assert max_err <= max_allowed

    def test_zero_rows_safe(self):
        q, scale = quantize_rows(jnp.zeros((4, 2, 8)))
        assert not np.any(np.isnan(np.asarray(dequantize_rows(q, scale))))


class TestQuantizedPagedAttention:
    def _setup(self, batch=2, n_q=8, n_kv=4, hd=128, page=128, n_pages=12, pps=3):
        keys = jax.random.split(jax.random.PRNGKey(1), 4)
        q = jax.random.normal(keys[0], (batch, n_q, hd), jnp.float32)
        k = jax.random.normal(keys[1], (n_kv, n_pages, page, hd), jnp.float32)
        v = jax.random.normal(keys[2], (n_kv, n_pages, page, hd), jnp.float32)
        bt = jax.random.permutation(keys[3], n_pages)[: batch * pps]
        bt = bt.reshape(batch, pps).astype(jnp.int32)
        kq, ks = quantize_rows(k)
        vq, vs = quantize_rows(v)
        # Page-pool scale layout carries a trailing unit dim (see module doc).
        return q, k, v, kq, ks[..., None], vq, vs[..., None], bt

    def test_kernel_matches_quantized_oracle(self):
        q, _k, _v, kq, ks, vq, vs, bt = self._setup()
        seq_lens = jnp.array([5, 300], jnp.int32)
        ref = paged_attention_quantized_reference(q, kq, ks, vq, vs, bt, seq_lens)
        out = paged_attention_quantized(q, kq, ks, vq, vs, bt, seq_lens, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-3)

    def test_quantized_close_to_full_precision(self):
        q, k, v, kq, ks, vq, vs, bt = self._setup()
        seq_lens = jnp.array([128, 384], jnp.int32)
        full = paged_attention_reference(q, k, v, bt, seq_lens)
        quant = paged_attention_quantized(q, kq, ks, vq, vs, bt, seq_lens, interpret=True)
        # int8 per-row quantization: ~1% relative error on attention outputs.
        err = float(jnp.max(jnp.abs(quant - full)))
        ref_scale = float(jnp.max(jnp.abs(full)))
        assert err <= 0.05 * max(ref_scale, 1.0)

    def test_zero_seq_len_outputs_zeros(self):
        q, _k, _v, kq, ks, vq, vs, bt = self._setup()
        seq_lens = jnp.array([0, 200], jnp.int32)
        out = paged_attention_quantized(q, kq, ks, vq, vs, bt, seq_lens, interpret=True)
        assert float(jnp.max(jnp.abs(out[0]))) == 0.0


class TestQuantizedWrites:
    def test_scatter_matches_direct_quantization(self):
        n_kv, n_pages, page, hd = 2, 8, 16, 32
        kq, ks, vq, vs = make_quantized_kv_pages(n_kv, n_pages, page, hd)
        bt = jnp.array([3, 6], jnp.int32)
        k_new = jax.random.normal(jax.random.PRNGKey(2), (5, n_kv, hd))
        v_new = k_new * 0.5
        kq, ks, vq, vs = write_kv_pages_quantized(kq, ks, vq, vs, bt, k_new, v_new, 14)

        # pos 14,15 -> page 3 slots 14,15; pos 16..18 -> page 6 slots 0..2.
        direct_q, direct_s = quantize_rows(jnp.swapaxes(k_new, 0, 1))
        np.testing.assert_array_equal(kq[:, 3, 14], direct_q[:, 0])
        np.testing.assert_array_equal(kq[:, 6, 2], direct_q[:, 4])
        np.testing.assert_allclose(ks[:, 6, 0, 0], direct_s[:, 2])
        # Dequantized content matches the bf16 write path within quant error.
        k_pages = jnp.zeros((n_kv, n_pages, page, hd))
        v_pages = jnp.zeros_like(k_pages)
        k_ref, _ = write_kv_pages(k_pages, v_pages, bt, k_new, v_new, 14)
        deq = kq.astype(jnp.float32) * ks
        err = float(jnp.max(jnp.abs(deq[:, 3, 14] - k_ref[:, 3, 14])))
        assert err < 0.05


class TestQuantizedPipelinedVariant:
    """The manual-DMA pipelined variant over int8 pages (4 arrays per page
    in strided all-head descriptors) must match the quantized oracle across
    partial pages, boundaries, and padded batch slots."""

    _setup = TestQuantizedPagedAttention._setup

    def test_pipelined_matches_oracle(self):
        q, _k, _v, kq, ks, vq, vs, bt = self._setup()
        for seq_lens in ([5, 300], [128, 384], [0, 256]):
            seq_lens = jnp.array(seq_lens, jnp.int32)
            ref = paged_attention_quantized_reference(
                q, kq, ks, vq, vs, bt, seq_lens
            )
            out = paged_attention_quantized(
                q, kq, ks, vq, vs, bt, seq_lens, interpret=True, pipelined=True
            )
            mask = np.asarray(seq_lens) > 0
            np.testing.assert_allclose(
                np.asarray(out)[mask], np.asarray(ref)[mask], atol=5e-3
            )

    def test_pipelined_matches_tiled(self):
        q, _k, _v, kq, ks, vq, vs, bt = self._setup()
        seq_lens = jnp.array([37, 290], jnp.int32)
        tiled = paged_attention_quantized(
            q, kq, ks, vq, vs, bt, seq_lens, interpret=True
        )
        piped = paged_attention_quantized(
            q, kq, ks, vq, vs, bt, seq_lens, interpret=True, pipelined=True
        )
        np.testing.assert_allclose(np.asarray(piped), np.asarray(tiled),
                                   atol=1e-5)

"""ShardedIndex: equivalence with the seed index, concurrency, and wiring.

Three layers:
- property equivalence: randomized add/evict/lookup interleavings (including
  lora_id keyspaces) must produce bit-identical lookup maps AND
  `GetPodScores`-style scorer output between the seed `InMemoryIndex` and
  `ShardedIndex` — capacity held above the working set so LRU eviction
  (which legitimately diverges: global vs per-shard victim choice) never
  fires.
- concurrency: readers + writers + evictors race one index; no deadlock, no
  exceptions, deterministic final state for disjoint writer keyspaces, and
  per-shard capacity invariants under churn (slow-marked for the heavy run).
- wiring: `new_index`/`IndexConfig` selection, JSON config round-trip,
  batched LRU primitives, and the touch=False recency semantics.
"""

import json
import random
import threading

import pytest

from llm_d_kv_cache_manager_tpu.config import indexer_config_from_json
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexConfig, new_index
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import InstrumentedIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
    DEFAULT_NUM_SHARDS,
    ShardedIndex,
    ShardedIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import new_kv_block_scorer
from llm_d_kv_cache_manager_tpu.utils.lru import LRUCache

MODEL = "m"


def _k(i: int) -> Key:
    return Key(MODEL, i)


def _pod(name: str, tier: str = "hbm") -> PodEntry:
    return PodEntry(name, tier)


def _chains():
    """Realistic request-key chains: chained CBOR+FNV hashes over token
    blocks, in both the base and a LoRA-adapter keyspace."""
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
    chains = []
    for lora_id in (None, 7, 12):
        for base in range(4):
            tokens = list(range(base * 100, base * 100 + 32))  # 8 blocks
            chains.append(
                db.tokens_to_kv_block_keys(None, tokens, MODEL, lora_id=lora_id)
            )
    return chains


class TestScoreEquivalence:
    """Acceptance gate: sharded and seed indexes yield identical pod scores
    over the same op sequence (lookup maps compared exactly too, so list
    order — oldest-first pod LRU order — must also match)."""

    @pytest.mark.parametrize("seed", [3, 17, 91])
    def test_randomized_interleavings(self, seed):
        rng = random.Random(seed)
        chains = _chains()
        engine_of = {
            k: Key(MODEL, (k.chunk_hash * 31 + 1) & 0xFFFFFFFFFFFFFFFF)
            for chain in chains
            for k in chain
        }
        pods = ["p0", "p1", "p2", "p1@dp0"]
        tiers = ["hbm", "host"]
        scorer = new_kv_block_scorer()

        seed_index = InMemoryIndex()
        sharded = ShardedIndex()

        for _ in range(400):
            op = rng.random()
            if op < 0.5:
                chain = rng.choice(chains)
                start = rng.randrange(len(chain))
                sub = chain[start:start + rng.randint(1, 4)]
                engines = [engine_of[k] for k in sub]
                entries = [
                    PodEntry(p, rng.choice(tiers))
                    for p in rng.sample(pods, rng.randint(1, 3))
                ]
                seed_index.add(engines, sub, entries)
                sharded.add(engines, sub, entries)
            elif op < 0.7:
                chain = rng.choice(chains)
                key = rng.choice(chain)
                victims = [PodEntry(rng.choice(pods), rng.choice(tiers))]
                seed_index.evict(engine_of[key], victims)
                sharded.evict(engine_of[key], victims)
            else:
                chain = rng.choice(chains)
                pod_filter = set(rng.sample(pods, 2)) if rng.random() < 0.4 else set()
                got_seed = seed_index.lookup(chain, pod_filter)
                got_sharded = sharded.lookup(chain, pod_filter)
                assert got_seed == got_sharded  # exact: keys, lists, order
                scores_seed = scorer.score(chain, got_seed)
                scores_sharded = scorer.score(chain, got_sharded)
                assert scores_seed == scores_sharded  # bit-identical floats

        for chain in chains:  # final sweep, unfiltered
            got_seed = seed_index.lookup(chain, set())
            got_sharded = sharded.lookup(chain, set())
            assert got_seed == got_sharded
            assert scorer.score(chain, got_seed) == scorer.score(chain, got_sharded)

    def test_touch_every_lookup_matches_too(self):
        seed_index = InMemoryIndex()
        sharded = ShardedIndex(ShardedIndexConfig(recency_refresh_interval=1))
        chain = [_k(i) for i in range(64)]
        for index in (seed_index, sharded):
            index.add(chain, chain, [_pod("p1"), _pod("p2", "host")])
        assert seed_index.lookup(chain, set()) == sharded.lookup(chain, set())


class TestConcurrency:
    def _run_stress(self, index, n_chains, duration_threads=None):
        """Disjoint writer keyspaces: writer w owns chains w*10^7 + i*8.
        Evictors remove the first half of each writer's chains. Final state
        is deterministic: second half present, first half gone."""
        n_writers, n_readers, n_evictors = 3, 4, 2
        errors = []
        read_chain = [_k(5_000_000 + i) for i in range(128)]
        index.add(read_chain, read_chain, [_pod("r1"), _pod("r2")])
        writers_done = threading.Event()
        evictable = [[] for _ in range(n_writers)]
        ev_lock = threading.Lock()
        scorer = new_kv_block_scorer()

        def writer(w):
            try:
                entry = [_pod(f"w{w}")]
                for i in range(n_chains):
                    keys = [_k((w + 1) * 10_000_000 + i * 8 + j) for j in range(8)]
                    index.add(keys, keys, entry)
                    if i < n_chains // 2:
                        with ev_lock:
                            evictable[w].append((keys[0], entry))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def evictor(slot):
            try:
                while True:
                    item = None
                    with ev_lock:
                        for lst in evictable:
                            if lst:
                                item = lst.pop()
                                break
                    if item is None:
                        if writers_done.is_set():
                            return
                        continue
                    key, entry = item
                    # Evict the whole 8-key chain via its engine keys.
                    for j in range(8):
                        index.evict(_k(key.chunk_hash + j), entry)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                while not writers_done.is_set():
                    hits = index.lookup(read_chain, set())
                    scorer.score(read_chain, hits)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(n_writers)
        ]
        threads += [
            threading.Thread(target=evictor, args=(s,)) for s in range(n_evictors)
        ]
        threads += [threading.Thread(target=reader) for _ in range(n_readers)]
        for t in threads:
            t.start()
        for t in threads[:n_writers]:
            t.join(timeout=60)
        writers_done.set()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "deadlocked thread"
        assert not errors, errors
        return read_chain

    def test_stress_no_deadlock_no_lost_state(self):
        # Capacity far above the working set: no LRU eviction, so the final
        # state is exactly writers' second-half chains plus the read chain.
        index = ShardedIndex(ShardedIndexConfig(size=10**6, num_shards=8))
        n_chains = 60
        read_chain = self._run_stress(index, n_chains)

        got = index.lookup(read_chain, set())
        assert set(got) == set(read_chain)  # reader chain never touched
        for w in range(3):
            for i in range(n_chains):
                keys = [_k((w + 1) * 10_000_000 + i * 8 + j) for j in range(8)]
                hits = index.lookup([keys[0]], set())
                if i < n_chains // 2:
                    assert hits == {}, f"writer {w} chain {i} not evicted"
                else:
                    assert hits == {keys[0]: [_pod(f"w{w}")]}, (
                        f"writer {w} chain {i} lost"
                    )
        # The lock-free read view never resurrects dead keys: every view
        # entry is backed by a live segment entry once writers quiesce.
        live = set()
        for seg in index._segments:
            live.update(seg.data.keys())
        assert set(index._view) <= live

    @pytest.mark.slow
    def test_stress_under_capacity_pressure(self):
        # Small per-shard capacity: constant LRU churn. Content is
        # nondeterministic; the invariants are no deadlock, no errors, and
        # every segment within its striped bound.
        index = ShardedIndex(
            ShardedIndexConfig(size=256, num_shards=8, pod_cache_size=4)
        )
        self._run_stress(index, n_chains=400)
        assert all(
            size <= index.per_shard_capacity for size in index.segment_sizes()
        )
        live = set()
        for seg in index._segments:
            live.update(seg.data.keys())
        assert set(index._view) <= live

    def test_per_shard_capacity_bound(self):
        index = ShardedIndex(ShardedIndexConfig(size=64, num_shards=8))
        assert index.per_shard_capacity == 8
        keys = [_k(i) for i in range(500)]
        for key in keys:
            index.add([key], [key], [_pod("p1")])
        sizes = index.segment_sizes()
        assert all(size <= 8 for size in sizes)
        assert sum(sizes) <= 64
        # View tracks the survivors exactly (single-threaded, so no races).
        live = set()
        for seg in index._segments:
            live.update(seg.data.keys())
        assert set(index._view) == live


class TestRecencySemantics:
    def test_peek_lookup_does_not_refresh_recency(self):
        # One shard, capacity 2, refresh interval high: lookups peek, so the
        # looked-up key is still the LRU victim.
        index = ShardedIndex(
            ShardedIndexConfig(size=2, num_shards=1, recency_refresh_interval=1000)
        )
        index.add([_k(1)], [_k(1)], [_pod("p1")])
        index.add([_k(2)], [_k(2)], [_pod("p1")])
        index.lookup([_k(1)], set())  # peek: no recency refresh
        index.add([_k(3)], [_k(3)], [_pod("p1")])  # evicts k1 (still oldest)
        assert index.lookup([_k(1)], set()) == {}
        assert index.lookup([_k(2)], set())

    def test_touch_lookup_refreshes_recency(self):
        index = ShardedIndex(
            ShardedIndexConfig(size=2, num_shards=1, recency_refresh_interval=1)
        )
        index.add([_k(1)], [_k(1)], [_pod("p1")])
        index.add([_k(2)], [_k(2)], [_pod("p1")])
        index.lookup([_k(1)], set())  # touch: k1 becomes most recent
        index.add([_k(3)], [_k(3)], [_pod("p1")])  # evicts k2 instead
        assert index.lookup([_k(1)], set())
        assert index.lookup([_k(2)], set()) == {}


class TestWiring:
    def test_default_index_is_sharded(self):
        index = new_index()
        assert isinstance(index, ShardedIndex)
        assert index.num_shards == DEFAULT_NUM_SHARDS

    def test_sharded_false_restores_seed_backend(self):
        assert isinstance(new_index(IndexConfig(sharded=False)), InMemoryIndex)

    def test_in_memory_config_feeds_sharded_geometry(self):
        index = new_index(IndexConfig(
            in_memory_config=InMemoryIndexConfig(size=100, pod_cache_size=3),
            num_shards=4,
        ))
        assert isinstance(index, ShardedIndex)
        assert index.num_shards == 4
        assert index.per_shard_capacity == 25

    def test_metrics_wrap_sharded(self):
        index = new_index(IndexConfig(enable_metrics=True))
        assert isinstance(index, InstrumentedIndex)
        assert isinstance(index.inner, ShardedIndex)

    def test_json_round_trip(self):
        cfg = indexer_config_from_json(json.dumps({
            "kv_block_index_config": {
                "sharded": True,
                "num_shards": 8,
                "recency_refresh_interval": 16,
            }
        }))
        index = new_index(cfg.kv_block_index_config)
        assert isinstance(index, ShardedIndex)
        assert index.num_shards == 8

    def test_invalid_geometry_raises(self):
        with pytest.raises(ValueError):
            ShardedIndex(ShardedIndexConfig(num_shards=0))
        with pytest.raises(ValueError):
            ShardedIndex(ShardedIndexConfig(size=0))

    def test_shard_routing_spreads_real_chains(self):
        # Real chained hashes spread across stripes: a 96-key chain must
        # touch many of 16 shards (uniform hashes make an empty-ish stripe
        # astronomically unlikely).
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        chain = db.tokens_to_kv_block_keys(None, list(range(384)), MODEL)
        index = ShardedIndex()
        shards = {index.shard_of(k) for k in chain}
        assert len(shards) >= 12


class TestBatchedLRUPrimitives:
    def test_get_many_refreshes_recency(self):
        lru = LRUCache(3)
        for i in (1, 2, 3):
            lru.add(i, i * 10)
        assert lru.get_many([1, 2, 99]) == {1: 10, 2: 20}
        lru.add(4, 40)  # evicts 3, the only un-refreshed key
        assert lru.peek(3) is None
        assert lru.peek(1) == 10

    def test_peek_many_leaves_recency_alone(self):
        lru = LRUCache(3)
        for i in (1, 2, 3):
            lru.add(i, i * 10)
        assert lru.peek_many([1, 2]) == {1: 10, 2: 20}
        lru.add(4, 40)  # evicts 1: peeks didn't refresh
        assert lru.peek(1) is None

    def test_add_many_counts_evictions(self):
        lru = LRUCache(2)
        assert lru.add_many([(1, "a"), (2, "b")]) == 0
        assert lru.add_many([(3, "c"), (4, "d")]) == 2
        assert lru.keys() == [3, 4]

    def test_on_evict_fires_for_every_departure(self):
        gone = []
        lru = LRUCache(2, on_evict=lambda k, v: gone.append((k, v)))
        lru.add(1, "a")
        lru.add(2, "b")
        lru.add(3, "c")  # capacity eviction
        lru.remove(2)
        lru.purge()
        assert gone == [(1, "a"), (2, "b"), (3, "c")]

    def test_keys_snapshot_tracks_mutation(self):
        lru = LRUCache(4)
        lru.add(1, "a")
        lru.add(2, "b")
        assert lru.keys() == [1, 2]
        assert lru.keys() == [1, 2]  # cached snapshot path
        lru.get(1)  # recency move must invalidate the snapshot
        assert lru.keys() == [2, 1]
        lru.remove(2)
        assert lru.keys() == [1]

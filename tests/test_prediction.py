"""Anticipatory-prefetch tests (prediction/ subsystem).

Policy/table/scheduler tests run unmarked (tier-1). The end-to-end sim
tests that move real KV payloads through the transfer plane are marked
`prediction` and auto-skip (with a visible reason) when libkvtransfer.so
isn't built — same contract as the `placement` marker.
"""

import asyncio

import pytest

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.prediction import (
    PredictionConfig,
    PrefetchScheduler,
    SchedulerConfig,
    SessionTable,
    best_score_select,
    fleet_prior_from_tables,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

BLOCK_SIZE = 4


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _table(**kw) -> SessionTable:
    clock = kw.pop("clock", FakeClock())
    defaults = dict(tail_trim_blocks=0, default_eta_s=5.0)
    defaults.update(kw)
    return SessionTable(PredictionConfig(**defaults), clock=clock)


def _chain(start, n):
    return list(range(start, start + n))


# ---------------------------------------------------------------------------
# Session table: identity, ETA model, bounds, misprediction accounting
# ---------------------------------------------------------------------------

class TestSessionTable:
    def test_continuation_by_chain_containment(self):
        t = _table()
        t.observe_route(_chain(100, 3), model_name="m", now=0.0)
        # Next turn: previous chain is a leading prefix of the new one.
        t.observe_route(_chain(100, 6), model_name="m", now=7.0)
        s = t.stats()
        assert s["new_sessions"] == 1
        assert s["continuations"] == 1
        rec = t.record_by_tail(105)
        assert rec is not None
        assert rec.turns_observed == 2
        assert rec.gap_ewma_s == 7.0
        # Re-keyed: the old tail no longer resolves.
        assert t.record_by_tail(102) is None

    def test_disjoint_chains_are_distinct_sessions(self):
        t = _table()
        t.observe_route(_chain(100, 4), model_name="m", now=0.0)
        t.observe_route(_chain(500, 4), model_name="m", now=1.0)
        assert t.stats()["new_sessions"] == 2
        assert t.sessions() == 2

    def test_eta_estimates_are_deterministic(self):
        """Scripted observations produce exact EWMA/blend arithmetic."""
        t = _table(eta_alpha=0.5, prior_weight=2.0, fleet_quantile=0.5)
        t.observe_route(_chain(0, 2), model_name="m", now=0.0)
        t.observe_route(_chain(0, 4), model_name="m", now=4.0)   # gap 4
        t.observe_route(_chain(0, 6), model_name="m", now=12.0)  # gap 8
        rec = t.record_by_tail(5)
        # EWMA: 4, then 4 + 0.5*(8-4) = 6.
        assert rec.gap_ewma_s == 6.0
        # Fleet reservoir [4, 8] -> median picks index 1 -> 8.
        assert t.fleet_eta_s() == 8.0
        # Blend: (n*ewma + w*prior)/(n+w) = (2*6 + 2*8)/4 = 7.
        assert t.eta_s(rec) == 7.0
        # Rebuilding from the same script yields the same estimates.
        t2 = _table(eta_alpha=0.5, prior_weight=2.0, fleet_quantile=0.5)
        t2.observe_route(_chain(0, 2), model_name="m", now=0.0)
        t2.observe_route(_chain(0, 4), model_name="m", now=4.0)
        t2.observe_route(_chain(0, 6), model_name="m", now=12.0)
        assert t2.eta_s(t2.record_by_tail(5)) == t.eta_s(rec)

    def test_cold_session_uses_fleet_prior(self):
        t = _table(default_eta_s=9.0)
        t.observe_route(_chain(0, 2), model_name="m", now=0.0)
        rec = t.record_by_tail(1)
        assert rec.gap_ewma_s is None
        assert t.eta_s(rec) == 9.0  # no fleet gaps yet -> default
        # Another session's continuation seeds the fleet reservoir.
        t.observe_route(_chain(50, 2), model_name="m", now=0.0)
        t.observe_route(_chain(50, 4), model_name="m", now=3.0)
        assert t.eta_s(rec) == 3.0

    def test_fleet_prior_from_tables_shape(self):
        lo = fleet_prior_from_tables(4.0, 0.01, quantile=0.1)
        mid = fleet_prior_from_tables(4.0, 0.01, quantile=0.5)
        hi = fleet_prior_from_tables(4.0, 0.01, quantile=0.9)
        assert 4.0 < lo <= mid <= hi

    def test_gap_clamps_reject_outliers(self):
        t = _table(min_gap_s=0.1, max_gap_s=100.0)
        t.observe_route(_chain(0, 2), model_name="m", now=0.0)
        t.observe_route(_chain(0, 4), model_name="m", now=0.01)   # fan-out
        t.observe_route(_chain(0, 6), model_name="m", now=500.0)  # comeback
        rec = t.record_by_tail(5)
        assert rec.gap_ewma_s is None
        assert t.stats()["clamped_gaps"] == 2
        assert rec.turns_observed == 3  # still tracked as the same session

    def test_bounded_lru_eviction_counts_pending_as_mispredicted(self):
        clock = FakeClock()
        t = _table(max_sessions=2, block_bytes=10, clock=clock)
        t.observe_route(_chain(0, 3), model_name="m", now=0.0)
        rec = t.record_by_tail(2)
        t.note_prefetch(rec, "pod-1", now=0.5)
        t.note_landed(2, 3)  # 3 blocks actually moved
        # Two newer sessions evict the oldest (with its pending prefetch).
        t.observe_route(_chain(100, 3), model_name="m", now=1.0)
        t.observe_route(_chain(200, 3), model_name="m", now=2.0)
        s = t.stats()
        assert s["tracked_sessions"] == 2
        assert s["evictions"] == 1
        assert s["mispredicted_blocks"] == 3
        assert s["mispredicted_bytes"] == 30
        assert t.record_by_tail(2) is None

    def test_tail_trim_drops_unstable_blocks(self):
        t = _table(tail_trim_blocks=2)
        tokens = list(range(6 * BLOCK_SIZE))
        t.observe_route(
            _chain(0, 6), tokens=tokens, model_name="m",
            block_size=BLOCK_SIZE, now=0.0,
        )
        rec = t.record_by_tail(3)  # trimmed tail: block 3, not 5
        assert rec is not None
        assert rec.chain_hashes == _chain(0, 4)
        # Tokens cover exactly the retained chain.
        assert rec.tokens == tokens[: 4 * BLOCK_SIZE]

    def test_expiry_counts_landed_blocks_only(self):
        clock = FakeClock()
        t = _table(expiry_factor=1.0, block_bytes=5, clock=clock)
        t.observe_route(_chain(0, 2), model_name="m", now=0.0)
        t.observe_route(_chain(0, 4), model_name="m", now=5.0)  # eta -> 5
        rec = t.record_by_tail(3)
        t.note_prefetch(rec, "pod-0", now=6.0)
        # Nothing landed yet: expiring now costs nothing.
        assert t.expire_pending(now=100.0) == 1
        assert t.stats()["mispredicted_blocks"] == 0
        # With landed feedback, expiry charges exactly the moved blocks.
        t.note_prefetch(rec, "pod-0", now=101.0)
        t.note_landed(rec.tail, 7)
        assert t.expire_pending(now=500.0) == 1
        s = t.stats()
        assert s["mispredicted_blocks"] == 7
        assert s["mispredicted_bytes"] == 35

    def test_continuation_resolves_pending_into_consumed(self):
        t = _table()
        t.observe_route(_chain(0, 3), model_name="m", now=0.0)
        rec = t.record_by_tail(2)
        t.note_prefetch(rec, "pod-4", now=2.0)
        t.note_landed(2, 9)
        t.observe_route(_chain(0, 6), model_name="m", now=5.0)
        rec = t.record_by_tail(5)
        assert rec.pending is None
        assert rec.consumed is not None
        assert rec.consumed.pod == "pod-4"
        assert rec.consumed.blocks == 9
        assert t.stats()["prefetches_resolved"] == 1

    def test_due_sessions_window_and_cooldown(self):
        t = _table(default_eta_s=10.0, expiry_factor=2.0)
        t.observe_route(_chain(0, 3), model_name="m", now=0.0)
        # Window opens at start_frac * eta = 4, closes at 10 + 2*10 = 30.
        assert t.due_sessions(now=2.0, start_frac=0.4) == []
        due = t.due_sessions(now=5.0, start_frac=0.4)
        assert len(due) == 1
        rec, expected = due[0]
        assert expected == 10.0
        assert t.due_sessions(now=31.0, start_frac=0.4) == []
        # A noted prefetch removes the session until resolved/expired...
        t.note_prefetch(rec, "pod-0", now=5.0)
        assert t.due_sessions(now=6.0, start_frac=0.4) == []
        rec.pending = None
        # ...and the cooldown gates re-attempts after that.
        assert t.due_sessions(now=6.0, start_frac=0.4, cooldown_s=5.0) == []
        assert len(t.due_sessions(now=11.0, start_frac=0.4, cooldown_s=5.0)) == 1


# ---------------------------------------------------------------------------
# Scheduler: budget, cooldown, routing-decision fidelity, drops
# ---------------------------------------------------------------------------

class _Scores:
    def __init__(self, scores, match_blocks=None):
        self.scores = scores
        self.match_blocks = match_blocks or {}


class TestScheduler:
    def _setup(self, scores, submit_ok=True, **sched_kw):
        clock = FakeClock()
        table = _table(default_eta_s=5.0, clock=clock)
        jobs = []

        def submit(pod, hashes):
            if submit_ok:
                jobs.append((pod, list(hashes)))
            return submit_ok

        sched = PrefetchScheduler(
            table,
            score_fn=lambda model, hashes: _Scores(dict(scores)),
            submit_fn=submit,
            config=SchedulerConfig(**sched_kw),
            clock=clock,
        )
        return table, sched, jobs, clock

    def test_submits_whole_chain_to_best_pod(self):
        table, sched, jobs, clock = self._setup({"pod-2": 3.0, "pod-1": 1.0})
        table.observe_route(_chain(0, 4), model_name="m", now=0.0)
        clock.t = 3.0  # inside [0.25*5, ...]
        assert sched.tick() == 1
        assert jobs == [("pod-2", _chain(0, 4))]
        assert sched.stats["blocks_submitted"] == 4
        assert table.stats()["prefetches_noted"] == 1

    def test_budget_bounds_jobs_per_tick(self):
        table, sched, jobs, clock = self._setup(
            {"pod-0": 1.0}, max_jobs_per_tick=2
        )
        for s in range(5):
            table.observe_route(_chain(1000 * (s + 1), 3),
                                model_name="m", now=0.0)
        clock.t = 3.0
        assert sched.tick() == 2
        assert len(jobs) == 2
        # The remaining sessions trickle out over later ticks.
        clock.t = 3.5
        assert sched.tick() == 2
        clock.t = 4.0
        assert sched.tick() == 1

    def test_session_cooldown_prevents_hot_loop(self):
        table, sched, jobs, clock = self._setup(
            {"pod-0": 1.0}, session_cooldown_s=4.0
        )
        table.observe_route(_chain(0, 3), model_name="m", now=0.0)
        clock.t = 3.0
        assert sched.tick() == 1
        table.record_by_tail(2).pending = None  # simulate executor no-op
        clock.t = 4.0
        assert sched.tick() == 0  # inside cooldown
        clock.t = 7.5
        assert sched.tick() == 1

    def test_no_target_is_counted_not_submitted(self):
        table, sched, jobs, clock = self._setup({})
        table.observe_route(_chain(0, 3), model_name="m", now=0.0)
        clock.t = 3.0
        assert sched.tick() == 0
        assert sched.stats["skipped_no_target"] == 1
        assert jobs == []

    def test_queue_drops_are_counted(self):
        table, sched, jobs, clock = self._setup(
            {"pod-0": 1.0}, submit_ok=False
        )
        table.observe_route(_chain(0, 3), model_name="m", now=0.0)
        clock.t = 3.0
        assert sched.tick() == 0
        assert sched.stats["drops"] == 1
        assert table.stats()["prefetches_noted"] == 0

    def test_default_select_is_deterministic(self):
        assert best_score_select({}) is None
        assert best_score_select({"pod-b": 2.0, "pod-a": 2.0}) == "pod-a"
        assert best_score_select({"pod-b": 3.0, "pod-a": 2.0}) == "pod-b"


# ---------------------------------------------------------------------------
# Read path: observation only — scores bit-identical, score_hashes fidelity
# ---------------------------------------------------------------------------

def _make_indexer(prediction=None):
    indexer = Indexer(
        config=IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
        ),
        tokenization_pool=TokenizationPool(
            TokenizersPoolConfig(
                workers=1,
                local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
            ),
        ),
        prediction=prediction,
    )
    indexer.run()
    return indexer


PROMPT = "the quick brown fox jumps over the lazy dog " * 8


def _seed(indexer, pod="pod-a", n=None):
    enc = indexer.tokenizers_pool.tokenizer.encode(PROMPT, TEST_MODEL_NAME)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        None, enc.tokens, TEST_MODEL_NAME
    )
    if n is not None:
        keys = keys[:n]
    engine_keys = [Key(TEST_MODEL_NAME, 77_000 + i) for i in range(len(keys))]
    indexer.kv_block_index.add(engine_keys, keys, [PodEntry(pod, "hbm")])
    return keys


class TestReadPathIdentity:
    def test_scores_bit_identical_with_table_attached(self):
        plain = _make_indexer(None)
        table = _table(clock=FakeClock())
        tracked = _make_indexer(table)
        try:
            _seed(plain)
            _seed(tracked)
            s1 = plain.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            s2 = tracked.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            assert s1 == s2
            # The table observed the session (pure side effect).
            assert table.stats()["observations"] == 1
            assert table.sessions() == 1
        finally:
            plain.shutdown()
            tracked.shutdown()

    def test_score_many_observes_like_single_calls(self):
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import ScoreRequest

        table = _table(clock=FakeClock())
        ix = _make_indexer(table)
        try:
            _seed(ix)
            results = ix.score_many([
                ScoreRequest(prompt=PROMPT, model_name=TEST_MODEL_NAME),
                ScoreRequest(prompt=PROMPT + " more words here",
                             model_name=TEST_MODEL_NAME),
            ])
            assert len(results) == 2
            assert table.stats()["observations"] == 2
        finally:
            ix.shutdown()

    def test_score_hashes_matches_prompt_scoring(self):
        """The scheduler's routing decision runs the same lookup/score
        stages as the prompt path: over the same chain, same answer."""
        ix = _make_indexer(None)
        try:
            keys = _seed(ix, pod="pod-a")
            _seed(ix, pod="pod-b", n=3)  # partial holder
            via_prompt = ix.get_pod_scores_ex(PROMPT, TEST_MODEL_NAME, [])
            via_hashes = ix.score_hashes(
                TEST_MODEL_NAME, [k.chunk_hash for k in keys]
            )
            assert via_hashes.scores == via_prompt.scores
            assert via_hashes.match_blocks == via_prompt.match_blocks
            assert via_hashes.block_hashes == via_prompt.block_hashes
            assert ix.score_hashes(TEST_MODEL_NAME, []).scores == {}
        finally:
            ix.shutdown()

    def test_tenant_isolation_rides_the_hash_chain(self):
        """Identical token streams under different LoRA extras derive
        disjoint chains, so their sessions never merge — the same
        mechanism that isolates their index entries."""
        table = _table(clock=FakeClock())
        ix = _make_indexer(table)
        try:
            ix.get_pod_scores(PROMPT, TEST_MODEL_NAME, [], lora_id=1)
            ix.get_pod_scores(PROMPT, TEST_MODEL_NAME, [], lora_id=2)
            assert table.stats()["new_sessions"] == 2
        finally:
            ix.shutdown()


# ---------------------------------------------------------------------------
# Serving wins: page pressure aborts a warm admission, never serving
# ---------------------------------------------------------------------------

class TestServingWins:
    def test_warm_chain_aborts_on_page_pressure(self):
        from llm_d_kv_cache_manager_tpu.engine.engine import (
            EnginePod,
            EnginePodConfig,
        )

        pod = EnginePod(EnginePodConfig(
            pod_id="tiny", n_pages=8, page_size=4, max_pages_per_seq=64,
        ))

        class StubTier:
            """Everything is 'restorable' — the allocate must still lose
            to page pressure and abort cleanly."""

            def plan_restore(self, hashes):
                return len(hashes)

            def close(self):
                pass

        pod.tier_store = StubTier()
        # A 20-block chain against an 8-page pool: OutOfPagesError inside
        # warm_chain -> 0 landed, no exception.
        assert pod.warm_chain(list(range(1000, 1080))) == 0
        # Serving is untouched: a small real prefill still succeeds.
        state, cached = pod.prefill([1, 2, 3, 4, 5, 6, 7, 8])
        assert len(state.tokens) == 8
        pod.free(state)
        pod.close()

    def test_prefetch_worker_survives_out_of_pages(self):
        from llm_d_kv_cache_manager_tpu.engine.block_manager import (
            OutOfPagesError,
        )
        from llm_d_kv_cache_manager_tpu.kv_connectors.prefetch import (
            RoutePrefetcher,
        )

        calls = []

        def exploding(pod, hashes):
            calls.append(pod)
            raise OutOfPagesError("no free pages")

        pf = RoutePrefetcher(exploding, queue_bound=4)
        try:
            assert pf.submit("pod-0", [1, 2], source="prediction")
            pf.drain()
            assert calls == ["pod-0"]
            # The worker survived; later jobs still execute.
            assert pf.submit("pod-1", [3], source="prediction")
            pf.drain()
            assert calls == ["pod-0", "pod-1"]
            assert pf.stats["executed"] == 0  # failures don't count
        finally:
            pf.close()


# ---------------------------------------------------------------------------
# RoutePrefetcher: per-source visibility
# ---------------------------------------------------------------------------

class TestPrefetcherSources:
    def test_per_source_counters_and_queue_depth(self):
        import threading

        from llm_d_kv_cache_manager_tpu.kv_connectors.prefetch import (
            RoutePrefetcher,
        )

        gate = threading.Event()
        started = threading.Event()

        def slow(pod, hashes):
            started.set()
            gate.wait(5.0)
            return len(hashes)

        pf = RoutePrefetcher(slow, queue_bound=1)
        try:
            assert pf.submit("pod-0", [1, 2])  # default source: route
            started.wait(5.0)  # worker busy; queue is empty again
            assert pf.submit("pod-1", [3], source="replication")
            # Bounded queue full: the prediction job drops, counted under
            # ITS source — the route/replication counters are untouched.
            assert not pf.submit("pod-2", [4], source="prediction")
            assert pf.queue_depth() == 1
            st = pf.status()
            assert st["queue_bound"] == 1
            assert st["by_source"]["route"]["submitted"] == 1
            assert st["by_source"]["replication"]["submitted"] == 1
            assert st["by_source"]["prediction"]["dropped"] == 1
            assert st["by_source"]["replication"]["dropped"] == 0
            assert st["stats"]["dropped"] == 1
            gate.set()
            pf.drain()
            st = pf.status()
            assert st["by_source"]["route"]["executed"] == 1
            assert st["by_source"]["route"]["blocks_queued"] == 2
            assert st["by_source"]["replication"]["executed"] == 1
        finally:
            gate.set()
            pf.close()


# ---------------------------------------------------------------------------
# API surface
# ---------------------------------------------------------------------------

class TestApiSurface:
    def _service(self, prediction: bool):
        from llm_d_kv_cache_manager_tpu.api.http_service import (
            ScoringService,
        )

        env = {
            "zmq_endpoint": "tcp://*:0",
            "zmq_topic": "kv@",
            "pool_concurrency": 1,
            "hash_seed": "",
            "block_size": BLOCK_SIZE,
            "http_port": 0,
            "enable_metrics": False,
            "prediction": prediction,
        }
        return ScoringService(env, indexer=_make_indexer())

    def test_prediction_status_and_readyz_section(self):
        from aiohttp.test_utils import TestClient, TestServer

        service = self._service(prediction=True)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200

                resp = await client.get("/prediction/status")
                assert resp.status == 200
                data = await resp.json()
                assert data["table"]["tracked_sessions"] == 1
                assert data["table"]["observations"] == 1
                assert len(data["soonest_sessions"]) == 1
                assert data["soonest_sessions"][0]["turns_observed"] == 1

                resp = await client.get("/readyz")
                assert resp.status == 200
                payload = await resp.json()
                assert payload["prediction"]["table"]["tracked_sessions"] == 1

        try:
            asyncio.run(run())
        finally:
            service.stop()

    def test_prediction_disabled_is_400_and_absent_from_readyz(self):
        from aiohttp.test_utils import TestClient, TestServer

        service = self._service(prediction=False)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.get("/prediction/status")
                assert resp.status == 400
                resp = await client.get("/readyz")
                assert (await resp.json())["prediction"] is None

        try:
            asyncio.run(run())
        finally:
            service.stop()


# ---------------------------------------------------------------------------
# End-to-end through the fleet sim (transfer plane; marked `prediction`)
# ---------------------------------------------------------------------------

def _bench():
    import importlib.util
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "bench_mod_prediction", repo / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mini_trace(bench):
    """A hand-scripted replay where anticipation provably matters: one
    multi-turn session whose prefix gets churned out of HBM by junk
    traffic during its think window, then returns."""
    import random

    from llm_d_kv_cache_manager_tpu.workloads.spec import MaterializedRequest
    from llm_d_kv_cache_manager_tpu.workloads.synthetic import text

    rng = random.Random(7)
    base = "session zero shared prefix " + text(rng, 420)
    reqs = [MaterializedRequest(
        arrival_s=0.0, session="s0", turn=0, prompt=base, output_len=20,
    )]
    # Junk single-turn sessions churn every pod's LRU during the window.
    for j in range(24):
        reqs.append(MaterializedRequest(
            arrival_s=1.0 + 0.25 * j,
            session=f"junk-{j}", turn=0,
            prompt=f"[junk {j}] " + text(rng, 500),
            output_len=10,
        ))
    grown = base + " [user] " + text(rng, 60)
    reqs.append(MaterializedRequest(
        arrival_s=12.0, session="s0", turn=1, prompt=grown, output_len=20,
    ))
    return reqs


@pytest.mark.prediction
class TestPredictionEndToEnd:
    def test_disabled_and_observe_only_are_bit_identical(self):
        """The PREDICTION=0 contract through the whole sim: attaching the
        table (and a scheduler whose budget is zero — pure observation)
        leaves the served TTFT stream byte-for-byte."""
        bench = _bench()
        reqs = _mini_trace(bench)

        def run(prediction):
            sim = bench.FleetSim(
                "precise", pages_per_pod=192, host_tier=True,
                host_capacity=2048, gated=False, prediction=prediction,
            )
            try:
                return [
                    sim.serve(r.arrival_s, r.prompt,
                              response_words=r.output_len)
                    for r in reqs
                ]
            finally:
                sim.shutdown()

        off = run(None)
        observe_only = run(dict(max_jobs_per_tick=0, tail_trim_blocks=0))
        assert observe_only == off

    def test_anticipation_prelands_the_next_turn(self):
        """With the predictor on, the returning session's prefix is
        device-resident before its turn-2 arrival; reactive serving finds
        it evicted."""
        bench = _bench()
        reqs = _mini_trace(bench)

        def run(prediction):
            sim = bench.FleetSim(
                "precise", pages_per_pod=192, host_tier=True,
                host_capacity=2048, gated=False, prediction=prediction,
            )
            audit = {}

            def hook(sim, pod_idx, pod, tokens, arrival):
                if audit.get("session") != "s0-t1":
                    return
                prev = audit["prev_chain"]
                audit["resident"] = pod.resident_prefix_blocks(prev)
                audit["prefix_blocks"] = len(prev)

            sim.pre_admit_hook = hook
            try:
                for r in reqs:
                    if r.session == "s0" and r.turn == 0:
                        toks = sim.indexer.tokenizers_pool.tokenize(
                            None, r.prompt, bench.MODEL
                        )
                        keys = (
                            sim.indexer.token_processor
                            .tokens_to_kv_block_keys(None, toks, bench.MODEL)
                        )
                        audit["prev_chain"] = [k.chunk_hash for k in keys]
                    audit["session"] = (
                        "s0-t1" if (r.session, r.turn) == ("s0", 1) else ""
                    )
                    sim.serve(r.arrival_s, r.prompt,
                              response_words=r.output_len)
                stats = sim.prediction_stats()
                return audit, stats
            finally:
                sim.shutdown()

        reactive, _ = run(None)
        assert reactive["resident"] < reactive["prefix_blocks"], (
            "scenario must actually evict the idle prefix"
        )
        # start_frac=0.8 opens the prefetch window late in the think gap
        # (after the junk churn has finished evicting), the regime the
        # scheduler is built for.
        anticipated, stats = run(dict(
            max_jobs_per_tick=4, session_cooldown_s=1.0, start_frac=0.8,
            tail_trim_blocks=8, default_eta_s=10.0,
        ))
        assert stats["predicted_landed_blocks"] > 0
        assert anticipated["resident"] > reactive["resident"]

"""benchmarking/README.md must match its JSON sources (no number drift).

VERDICT r1 weak #5: the round-1 README said read-path p50 2.5ms while the
driver-captured BENCH_r01.json said 0.858ms. The generated sections are now
rendered from the JSON by benchmarking/gen_readme.py; this test fails if
anyone edits the numbers by hand or forgets to regenerate.
"""

import importlib.util
import os
import pathlib

BENCHMARKING = pathlib.Path(__file__).resolve().parent.parent / "benchmarking"

_spec = importlib.util.spec_from_file_location(
    "gen_readme", BENCHMARKING / "gen_readme.py"
)
gen_readme = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(gen_readme)


def test_readme_generated_sections_are_fresh():
    text = (BENCHMARKING / "README.md").read_text()
    assert gen_readme.regenerate(text) == text, (
        "benchmarking/README.md is stale — run `python benchmarking/gen_readme.py`"
    )


def test_fleet_bench_artifact_matches_bench_config():
    """Bench-honesty convention: every committed benchmark artifact carries
    the config that produced it, cross-checked against the script's current
    constants — so changing bench.py without regenerating FLEET_BENCH.json
    fails here, not silently in the README."""
    import json
    import re as _re

    artifact = json.loads((BENCHMARKING / "FLEET_BENCH.json").read_text())
    src = (BENCHMARKING.parent / "bench.py").read_text()

    def const(name):
        m = _re.search(rf"^{name} = ([0-9.]+)", src, _re.M)
        assert m, f"bench.py constant {name} not found"
        v = m.group(1)
        return float(v) if "." in v else int(v)

    cfg = artifact["config"]
    assert cfg["n_pods"] == const("N_PODS")
    assert cfg["page_size"] == const("PAGE_SIZE")
    assert cfg["pages_per_pod"] == const("PAGES_PER_POD")
    assert cfg["pressured_pages_per_pod"] == const("TWO_TIER_PAGES_PER_POD")
    assert cfg["n_groups"] == const("N_GROUPS")
    assert cfg["users_per_group"] == const("USERS_PER_GROUP")
    assert cfg["turns_per_user"] == const("TURNS_PER_USER")
    assert cfg["qps"] == const("QPS")
    assert cfg["itl_s_per_token"] == const("ITL_S_PER_TOKEN")
    assert cfg["capacity_groups"] == const("CAPACITY_GROUPS")
    assert cfg["capacity_pages_per_pod"] == const("CAPACITY_PAGES_PER_POD")
    assert cfg["capacity_requests"] == const("CAPACITY_REQUESTS")
    # Volatile / duplicated fields must stay out of the committed artifact.
    assert "wall_s" not in artifact
    assert "read_path_p50_ms" not in artifact
    assert "device_measured_fleet" not in artifact


def test_device_bench_json_is_physical():
    import json

    d = json.loads((BENCHMARKING / "DEVICE_BENCH.json").read_text())
    # Overhead-dominated flags are honest annotations; what must never
    # appear is a physically impossible (under-reported) measurement.
    assert not any("under-reported" in f for f in d["fidelity_flags"]), (
        d["fidelity_flags"]
    )
    assert 0 < d["matmul_calibration"]["pct_of_peak"] <= 105
    for row in d["prefill"]:
        assert 0 < row["mfu_vs_theoretical_peak"] <= 1.05
    if "prefill_marginal_mfu" in d["analysis"]:
        assert 0 < d["analysis"]["prefill_marginal_mfu"] <= 1.05

"""Predictive-placement subsystem tests (placement/).

Unmarked tests cover the pure-policy surface (sketch, tracker, replicator,
cost-aware eviction weighting, read-path bit-identity) and run in tier-1.
`placement`-marked tests move real KV payloads through the transfer plane
and auto-skip when libkvtransfer.so isn't built (conftest).
"""

import random

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareIndexConfig,
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.instrumented import (
    InstrumentedIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
    ShardedIndex,
    ShardedIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.placement import (
    ChainPopularityTracker,
    DecayedCountMinSketch,
    HotPrefixReplicator,
    PopularityConfig,
    ReplicationConfig,
)

BLOCK = 4


def _db():
    return ChunkedTokenDatabase(TokenProcessorConfig(block_size=BLOCK))


def _keys(tokens, lora_id=None, db=None):
    return (db or _db()).tokens_to_kv_block_keys(
        None, tokens, "m", lora_id=lora_id
    )


def _hashes(tokens, lora_id=None, db=None):
    return [k.chunk_hash for k in _keys(tokens, lora_id=lora_id, db=db)]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Decayed count-min sketch
# ---------------------------------------------------------------------------

class TestSketch:
    def test_never_underestimates(self):
        sk = DecayedCountMinSketch(width=512, depth=4, half_life_s=1e9)
        rng = random.Random(7)
        truth = {}
        for _ in range(2000):
            item = rng.randrange(200)
            truth[item] = truth.get(item, 0) + 1
            sk.add(item, 1.0, now=0.0)
        for item, count in truth.items():
            assert sk.estimate(item, now=0.0) >= count - 1e-6

    def test_half_life_decay(self):
        sk = DecayedCountMinSketch(width=256, depth=4, half_life_s=10.0)
        sk.add(42, 8.0, now=0.0)
        assert sk.estimate(42, now=0.0) == pytest.approx(8.0)
        assert sk.estimate(42, now=10.0) == pytest.approx(4.0)
        assert sk.estimate(42, now=30.0) == pytest.approx(1.0)

    def test_decay_is_relative_not_destructive(self):
        # A later increment dominates an earlier equal one after decay.
        sk = DecayedCountMinSketch(width=256, depth=4, half_life_s=5.0)
        sk.add(1, 4.0, now=0.0)
        sk.add(2, 4.0, now=10.0)
        assert sk.estimate(2, now=10.0) > sk.estimate(1, now=10.0)

    def test_rescale_survives_long_uptime(self):
        sk = DecayedCountMinSketch(width=64, depth=2, half_life_s=1.0)
        sk.add(5, 1.0, now=0.0)
        # Thousands of half-lives later: must neither overflow nor raise.
        sk.add(5, 1.0, now=100.0)
        est = sk.estimate(5, now=100.0)
        assert 1.0 <= est < 1.1
        sk.add(6, 1.0, now=200.0)
        assert sk.estimate(6, now=200.0) >= 1.0


# ---------------------------------------------------------------------------
# Chain popularity tracker
# ---------------------------------------------------------------------------

class TestTracker:
    def _tracker(self, top_k=8, half_life=60.0):
        clock = FakeClock()
        return ChainPopularityTracker(
            PopularityConfig(
                top_k=top_k, sketch_width=1024, half_life_s=half_life,
                max_prefix_blocks=16,
            ),
            clock=clock,
        ), clock

    def test_top_k_bound_holds_under_many_chains(self):
        tracker, clock = self._tracker(top_k=8)
        for i in range(200):
            tracker.observe_route([1000 + i, 2000 + i], now=float(i) * 0.01)
        assert tracker.stats()["tracked_chains"] <= 8

    def test_heavy_hitter_displaces_cold(self):
        tracker, clock = self._tracker(top_k=4)
        for i in range(4):
            tracker.observe_route([100 + i], now=0.0)
        # A newcomer observed many times must displace a one-shot resident.
        for _ in range(20):
            tracker.observe_route([999], now=1.0)
        heads = {c.head for c in tracker.hot_chains(threshold=0.0, now=1.0)}
        assert 999 in heads
        assert tracker.stats()["tracked_chains"] == 4

    def test_hot_chains_threshold_and_decay(self):
        tracker, clock = self._tracker(half_life=10.0)
        for _ in range(16):
            tracker.observe_route([7, 8, 9], now=0.0)
        hot = tracker.hot_chains(threshold=10.0, now=0.0)
        assert [c.head for c in hot] == [7]
        # Four half-lives later the same chain reads cold.
        assert tracker.hot_chains(threshold=10.0, now=40.0) == []

    def test_common_prefix_refinement(self):
        """Two sessions share a tenant prefix and diverge after it: the
        retained replication prefix converges on the shared part."""
        tracker, _ = self._tracker()
        shared = [1, 2, 3]
        tracker.observe_route(
            shared + [10, 11], tokens=list(range(20)), block_size=BLOCK,
            now=0.0,
        )
        tracker.observe_route(
            shared + [20, 21, 22], tokens=list(range(24)), block_size=BLOCK,
            now=0.1,
        )
        stat = tracker.chain(1)
        assert stat.prefix_hashes == shared
        assert stat.prefix_tokens == list(range(len(shared) * BLOCK))

    def test_tenant_keyspaces_never_share_buckets(self):
        """Identical token streams under different LoRA extras derive
        disjoint chains, so their popularity buckets are disjoint too."""
        db = _db()
        tokens = list(range(32))
        h_a = _hashes(tokens, lora_id=1, db=db)
        h_b = _hashes(tokens, lora_id=2, db=db)
        assert not set(h_a) & set(h_b)

        tracker, _ = self._tracker()
        for _ in range(5):
            tracker.observe_route(h_a, lora_id=1, now=0.0)
        tracker.observe_route(h_b, lora_id=2, now=0.0)
        a = tracker.chain(h_a[0])
        b = tracker.chain(h_b[0])
        assert a is not None and b is not None
        assert a.extra == (1,) and b.extra == (2,)
        assert a.score > b.score

    def test_block_score_reads_sketch(self):
        tracker, _ = self._tracker()
        for _ in range(6):
            tracker.observe_route([50, 51], now=0.0)
        assert tracker.block_score(50, now=0.0) >= 6.0
        assert tracker.block_score(51, now=0.0) >= 6.0

    def test_store_and_lookup_ingest_credit_blocks_only(self):
        tracker, _ = self._tracker()
        tracker.observe_store([70, 71], now=0.0)
        tracker.observe_lookup([70], now=0.0)
        # Sketch learned, top-K did not (no chain-head identity).
        assert tracker.block_score(70, now=0.0) > 0.0
        assert tracker.stats()["tracked_chains"] == 0
        assert tracker.stats()["store_observations"] == 1
        assert tracker.stats()["lookup_observations"] == 1


# ---------------------------------------------------------------------------
# Hot-prefix replicator
# ---------------------------------------------------------------------------

class FakeHealth:
    def __init__(self, states=None):
        self.states = states or {}

    def state_of(self, pod):
        return self.states.get(pod, "healthy")


class TestReplicator:
    def _setup(self, k=3, states=None, index=None, submit_ok=True,
               threshold=5.0):
        clock = FakeClock()
        tracker = ChainPopularityTracker(
            PopularityConfig(top_k=8, half_life_s=60.0),
            clock=clock,
        )
        jobs = []

        def submit(pod, hashes, chain):
            if not submit_ok:
                return False
            jobs.append((pod, list(hashes), chain.head))
            return True

        rep = HotPrefixReplicator(
            tracker,
            submit_fn=submit,
            pods_fn=lambda: [f"pod-{i}" for i in range(8)],
            config=ReplicationConfig(
                k_replicas=k, hotness_threshold=threshold, cooldown_s=10.0,
            ),
            fleet_health=FakeHealth(states),
            index=index,
            clock=clock,
        )
        return tracker, rep, jobs, clock

    def _heat(self, tracker, hashes, n=10, now=0.0, **kw):
        for _ in range(n):
            tracker.observe_route(hashes, now=now, **kw)

    def test_hot_chain_replicates_to_k_targets(self):
        tracker, rep, jobs, clock = self._setup(k=3)
        self._heat(tracker, [1, 2, 3])
        assert rep.tick(now=0.0) == 1
        assert len(jobs) == 3  # no index wired -> no known owners
        assert len({pod for pod, _h, _c in jobs}) == 3
        assert all(h == [1, 2, 3] for _p, h, _c in jobs)

    def test_cold_chain_never_replicates(self):
        tracker, rep, jobs, clock = self._setup(threshold=100.0)
        self._heat(tracker, [1, 2, 3], n=5)
        assert rep.tick(now=0.0) == 0
        assert jobs == []

    def test_never_targets_suspect_or_stale_pods(self):
        sick = {"pod-1": "suspect", "pod-2": "stale", "pod-3": "suspect"}
        tracker, rep, jobs, clock = self._setup(k=8, states=sick)
        self._heat(tracker, [4, 5])
        rep.tick(now=0.0)
        targeted = {pod for pod, _h, _c in jobs}
        assert targeted
        assert not targeted & set(sick)
        assert rep.stats["skipped_unhealthy"] == 3

    def test_owners_excluded_and_satisfied_chains_skipped(self):
        index = InMemoryIndex(InMemoryIndexConfig())
        # Pods 0..2 already hold the WHOLE prefix (tail block included).
        keys = [Key("m", h) for h in (1, 2, 3)]
        index.add(keys, keys, [PodEntry(f"pod-{i}", "hbm") for i in range(3)])
        tracker, rep, jobs, clock = self._setup(k=3, index=index)
        self._heat(tracker, [1, 2, 3], model_name="m")
        rep.tick(now=0.0)
        assert jobs == []  # 3 owners >= k_replicas: nothing to do
        assert rep.stats["skipped_satisfied"] == 1

    def test_partial_holder_is_a_target_not_an_owner(self):
        index = InMemoryIndex(InMemoryIndexConfig())
        head = [Key("m", 1)]
        # pod-0 holds only the head block — prefix partially evicted.
        index.add(head, head, [PodEntry("pod-0", "hbm")])
        tracker, rep, jobs, clock = self._setup(k=1, index=index)
        self._heat(tracker, [1, 2, 3], model_name="m")
        rep.tick(now=0.0)
        assert len(jobs) == 1  # tail block unowned -> one replica needed

    def test_cooldown_bounds_replication_rate(self):
        tracker, rep, jobs, clock = self._setup(k=2)
        self._heat(tracker, [1, 2])
        rep.tick(now=0.0)
        first = len(jobs)
        assert first == 2
        self._heat(tracker, [1, 2], now=1.0)
        rep.tick(now=1.0)  # inside cooldown_s=10
        assert len(jobs) == first
        assert rep.stats["skipped_cooldown"] >= 1
        self._heat(tracker, [1, 2], now=20.0)
        rep.tick(now=20.0)  # past cooldown
        assert len(jobs) > first

    def test_queue_drops_are_counted(self):
        tracker, rep, jobs, clock = self._setup(submit_ok=False)
        self._heat(tracker, [1, 2])
        rep.tick(now=0.0)
        assert rep.stats["drops"] == 3
        assert rep.stats["jobs_submitted"] == 0

    def test_rendezvous_spreads_distinct_chains(self):
        """Different hot chains must not all pile onto the same 'best'
        pod: their rendezvous orderings differ."""
        tracker, rep, jobs, clock = self._setup(k=1, threshold=1.0)
        for head in range(10, 30):
            self._heat(tracker, [head, head + 100], n=3)
        for _ in range(8):  # max_jobs_per_tick caps work per tick
            rep.tick(now=0.0)
            clock.t += 100.0
        targets = {pod for pod, _h, _c in jobs}
        assert len(targets) >= 3


# ---------------------------------------------------------------------------
# Read path: observation only, scores bit-identical
# ---------------------------------------------------------------------------

class TestReadPathIdentity:
    def test_scores_bit_identical_with_tracker_attached(
        self, test_tokenizer_files
    ):
        from llm_d_kv_cache_manager_tpu.tokenization.pool import (
            TokenizationPool,
            TokenizersPoolConfig,
        )

        def build(popularity):
            pool = TokenizationPool(TokenizersPoolConfig(
                workers=1, local_tokenizer_files=test_tokenizer_files,
            ))
            ix = Indexer(
                config=IndexerConfig(
                    token_processor_config=TokenProcessorConfig(block_size=4),
                ),
                tokenization_pool=pool,
                popularity=popularity,
            )
            ix.run()
            return ix

        tracker = ChainPopularityTracker(
            PopularityConfig(), clock=FakeClock()
        )
        plain = build(None)
        tracked = build(tracker)
        try:
            prompt = "the quick brown fox jumps over the lazy dog " * 8
            tokens = plain.tokenizers_pool.tokenize(
                None, prompt, "test-model"
            )
            keys = plain.token_processor.tokens_to_kv_block_keys(
                None, tokens, "test-model"
            )
            for ix in (plain, tracked):
                ix.kv_block_index.add(
                    keys[:4], keys[:4], [PodEntry("pod-a", "hbm")]
                )
                ix.kv_block_index.add(
                    keys[:2], keys[:2], [PodEntry("pod-b", "hbm")]
                )
            s1 = plain.get_pod_scores(prompt, "test-model", [])
            s2 = tracked.get_pod_scores(prompt, "test-model", [])
            assert s1 == s2 and s1
            # ... and the tracker actually observed the route.
            assert tracker.stats()["route_observations"] == 1
            assert tracker.chain(keys[0].chunk_hash) is not None
        finally:
            plain.shutdown()
            tracked.shutdown()


# ---------------------------------------------------------------------------
# InstrumentedIndex: strided hit-count walk + popularity ingest
# ---------------------------------------------------------------------------

class TestInstrumentedIndex:
    def _observed_count(self):
        from llm_d_kv_cache_manager_tpu.metrics import collector as m

        for metric in m.index_max_pod_hits.collect():
            for sample in metric.samples:
                if sample.name.endswith("_count"):
                    return sample.value
        return 0.0

    def test_stride_samples_hit_count_histogram(self):
        from llm_d_kv_cache_manager_tpu.metrics import collector as m

        m.register_metrics()
        inner = InMemoryIndex(InMemoryIndexConfig())
        keys = [Key("m", i) for i in range(4)]
        inner.add(keys, keys, [PodEntry("p1", "hbm")])

        strided = InstrumentedIndex(inner, hit_count_stride=4)
        before = self._observed_count()
        for _ in range(8):
            strided.lookup(keys, set())
        assert self._observed_count() - before == 2  # 8 lookups / stride 4

    def test_popularity_ingest_rides_the_same_walk(self):
        tracker = ChainPopularityTracker(
            PopularityConfig(), clock=FakeClock()
        )
        inner = InMemoryIndex(InMemoryIndexConfig())
        keys = [Key("m", i) for i in range(3)]
        inner.add(keys, keys, [PodEntry("p1", "hbm")])
        idx = InstrumentedIndex(
            inner, hit_count_stride=1000, popularity=tracker
        )
        idx.lookup(keys, set())
        assert tracker.stats()["lookup_observations"] == 1
        assert tracker.block_score(keys[0].chunk_hash, now=0.0) > 0

    def test_delegation_contract_unchanged(self):
        inner = InMemoryIndex(InMemoryIndexConfig())
        idx = InstrumentedIndex(inner, hit_count_stride=7)
        keys = [Key("m", i) for i in range(2)]
        idx.add(keys, keys, [PodEntry("p1", "hbm")])
        assert idx.get_request_key(keys[0]) == keys[0]
        assert set(idx.lookup(keys, set())) == set(keys)
        idx.evict(keys[0], [PodEntry("p1", "hbm")])
        assert idx.remove_pod("p1") >= 0


# ---------------------------------------------------------------------------
# Cost-aware eviction: popularity vs re-landing cost
# ---------------------------------------------------------------------------

class TestCostAwareEviction:
    PER_KEY = None  # exact byte cost of one single-entry key (computed once)

    @classmethod
    def _per_key(cls):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
            calculate_byte_size,
        )

        if cls.PER_KEY is None:
            cls.PER_KEY = calculate_byte_size(
                Key("m", 0), [PodEntry("p", "hbm")]
            )
        return cls.PER_KEY

    def _filled(self, eviction_sample, tracker=None, cost_model=None,
                n_keys=12):
        # Budget sized so adding one more key forces one eviction.
        idx = CostAwareMemoryIndex(CostAwareIndexConfig(
            max_size_bytes=self._per_key() * n_keys + self._per_key() // 2,
            eviction_sample=eviction_sample,
        ))
        if tracker is not None:
            idx.bind_popularity(tracker, cost_model=cost_model)
        for i in range(n_keys):
            k = Key("m", i)
            idx.add([k], [k], [PodEntry("p", "hbm")])
        return idx

    def test_default_sample_is_pure_lru_even_with_tracker(self):
        tracker = ChainPopularityTracker(
            PopularityConfig(), clock=FakeClock()
        )
        for _ in range(50):
            tracker.observe_route([0], now=0.0)  # oldest key is hottest
        idx = self._filled(eviction_sample=1, tracker=tracker)
        overflow = Key("m", 999)
        idx.add([overflow], [overflow], [PodEntry("p", "hbm")])
        # Pure LRU: key 0 (the oldest) evicted despite being hot.
        assert Key("m", 0) not in idx.lookup([Key("m", 0), overflow], set())
        assert idx.eviction_stats["lru"] >= 1
        assert idx.eviction_stats["weighted"] == 0

    def test_weighted_eviction_keeps_hot_evicts_cold(self):
        tracker = ChainPopularityTracker(
            PopularityConfig(), clock=FakeClock()
        )
        for _ in range(50):
            tracker.observe_route([0], now=0.0)  # key 0: hot
        idx = self._filled(eviction_sample=4, tracker=tracker)
        overflow = Key("m", 999)
        idx.add([overflow], [overflow], [PodEntry("p", "hbm")])
        # Key 0 survives (hot); a cold key in the sample window drained.
        found = idx.lookup([Key("m", 0)], set())
        assert Key("m", 0) in found
        assert idx.eviction_stats["weighted"] >= 1
        remaining = [
            i for i in range(12)
            if idx.lookup([Key("m", i)], set()).get(Key("m", i))
        ]
        assert len(remaining) < 12

    def test_cost_model_makes_restorable_entries_less_sticky(self):
        from llm_d_kv_cache_manager_tpu.engine.costs import TransferCostModel

        model = TransferCostModel(
            recompute_s=1e-3, staged_restore_s=1e-5, onboard_s=2e-5,
            insert_s=1e-5, source="test",
        )
        tracker = ChainPopularityTracker(
            PopularityConfig(), clock=FakeClock()
        )
        # Keys 0 and 1 equally popular; 0 has a host-tier copy (cheap to
        # re-land), 1 is device-only (expensive to lose).
        for _ in range(10):
            tracker.observe_route([0], now=0.0)
            tracker.observe_route([1], now=0.0)
        idx = CostAwareMemoryIndex(CostAwareIndexConfig(
            max_size_bytes=self._per_key() * 12 + self._per_key() // 2,
            eviction_sample=2,
        ))
        idx.bind_popularity(tracker, cost_model=model)
        k0, k1 = Key("m", 0), Key("m", 1)
        idx.add([k0], [k0], [PodEntry("p", "cpu")])
        idx.add([k1], [k1], [PodEntry("p", "hbm")])
        for i in range(2, 12):
            k = Key("m", i)
            idx.add([k], [k], [PodEntry("p", "hbm")])
        overflow = Key("m", 999)
        idx.add([overflow], [overflow], [PodEntry("p", "hbm")])
        # The restorable hot key was the cheaper loss within the window.
        assert k1 in idx.lookup([k1], set())
        assert not idx.lookup([k0], set())


# ---------------------------------------------------------------------------
# Satellite: per-tenant key isolation, end-to-end, all four backends
# ---------------------------------------------------------------------------

def _backend_factories():
    from tests.fake_redis import FakeRedisServer
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
        RedisIndex,
        RedisIndexConfig,
    )

    server = FakeRedisServer()

    def redis_factory():
        index = RedisIndex(RedisIndexConfig(url=server.url))
        index._pipeline([("FLUSHALL",)])
        return index

    return {
        "in_memory": lambda: InMemoryIndex(InMemoryIndexConfig()),
        "sharded": lambda: ShardedIndex(ShardedIndexConfig(num_shards=4)),
        "cost_aware": lambda: CostAwareMemoryIndex(CostAwareIndexConfig()),
        "redis": redis_factory,
    }


class TestTenantIsolationProperty:
    @pytest.mark.parametrize("backend", list(_backend_factories()))
    def test_identical_streams_distinct_lora_never_share_entries(
        self, backend
    ):
        """Property: two tenants with IDENTICAL token streams but distinct
        LoRA extras never share index entries, popularity buckets, or
        replication targets — across every index backend."""
        factory = _backend_factories()[backend]
        rng = random.Random(11)
        db = _db()
        clock = FakeClock()
        for trial in range(5):
            tracker = ChainPopularityTracker(
                PopularityConfig(top_k=16), clock=clock
            )
            index = factory()
            tokens = [rng.randrange(1000) for _ in range(24)]
            keys_a = _keys(tokens, lora_id=7, db=db)
            keys_b = _keys(tokens, lora_id=8, db=db)
            # Disjoint keyspaces by construction...
            assert not set(keys_a) & set(keys_b)
            index.add(keys_a, keys_a, [PodEntry("pod-a", "hbm")])
            index.add(keys_b, keys_b, [PodEntry("pod-b", "hbm")])
            # ...and disjoint lookups: tenant A's chain never returns
            # tenant B's pods, even under an unfiltered query.
            found_a = index.lookup(keys_a, set())
            pods_a = {
                e.pod_identifier for es in found_a.values() for e in es
            }
            assert pods_a == {"pod-a"}
            found_b = index.lookup(keys_b, set())
            pods_b = {
                e.pod_identifier for es in found_b.values() for e in es
            }
            assert pods_b == {"pod-b"}

            # Popularity buckets are disjoint per tenant.
            h_a = [k.chunk_hash for k in keys_a]
            h_b = [k.chunk_hash for k in keys_b]
            tracker.observe_route(h_a, lora_id=7, now=float(trial))
            tracker.observe_route(h_b, lora_id=8, now=float(trial))
            assert tracker.chain(h_a[0]).extra == (7,)
            assert tracker.chain(h_b[0]).extra == (8,)
            assert h_a[0] != h_b[0]

            # Replication plans are computed per tenant chain: each job
            # carries exactly its own tenant's hashes.
            jobs = []
            rep = HotPrefixReplicator(
                tracker,
                submit_fn=lambda pod, hashes, chain: (
                    jobs.append((chain.extra, tuple(hashes))) or True
                ),
                pods_fn=lambda: ["pod-a", "pod-b", "pod-c"],
                config=ReplicationConfig(
                    k_replicas=1, hotness_threshold=0.5,
                    max_jobs_per_tick=8,
                ),
                clock=clock,
            )
            rep.tick(now=float(trial))
            for extra, hashes in jobs:
                if extra == (7,):
                    assert set(hashes) <= set(h_a)
                elif extra == (8,):
                    assert set(hashes) <= set(h_b)


# ---------------------------------------------------------------------------
# Event-pool write-plane ingest
# ---------------------------------------------------------------------------

class TestEventPoolIngest:
    def test_block_stored_credits_tracker(self):
        from llm_d_kv_cache_manager_tpu.kvevents.events import (
            BlockStored,
            EventBatch,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.pool import (
            EventPool,
            EventPoolConfig,
            Message,
        )

        db = _db()
        index = InMemoryIndex(InMemoryIndexConfig())
        tracker = ChainPopularityTracker(
            PopularityConfig(), clock=FakeClock()
        )
        pool = EventPool(
            EventPoolConfig(concurrency=1), index, db, popularity=tracker
        )
        pool.start(with_subscriber=False)
        try:
            tokens = list(range(8))
            batch = EventBatch(ts=0.0, events=[BlockStored(
                block_hashes=[111, 222],
                parent_block_hash=None,
                token_ids=tokens,
                block_size=BLOCK,
                lora_id=None,
                medium="hbm",
            )])
            pool.add_task(Message(
                topic="kv@p1@m", payload=batch.to_msgpack(), seq=0,
                pod_identifier="p1", model_name="m",
            ))
            pool.drain()
        finally:
            pool.shutdown()
        assert tracker.stats()["store_observations"] == 1
        stored = _hashes(tokens, db=db)
        assert tracker.block_score(stored[0], now=0.0) > 0


# ---------------------------------------------------------------------------
# Fleet-sim integration (bench.py): cluster equivalence + placement e2e
# ---------------------------------------------------------------------------

def _bench():
    import importlib.util
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "bench_mod_placement", repo / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mini_workload(bench, n=20, seed=3):
    rng = random.Random(seed)
    conversations = {
        f"g{g}": f"[group {g}] " + " ".join(
            f"w{g}x{i}" for i in range(120)
        )
        for g in range(4)
    }
    reqs = []
    arrival = 0.0
    for i in range(n):
        arrival += rng.expovariate(10.0)
        g = rng.randrange(4)
        reqs.append((arrival, conversations[f"g{g}"] + f" [user] q{i}"))
    return reqs


class TestClusterReplicasEquivalence:
    def test_cluster_scored_precise_bit_identical_to_monolithic(self):
        bench = _bench()
        reqs = _mini_workload(bench)

        def run(cluster_replicas):
            sim = bench.FleetSim(
                "precise", cluster_replicas=cluster_replicas
            )
            out = []
            try:
                for arrival, prompt in reqs:
                    out.append(sim.serve(arrival, prompt))
                return out, sim.hit_tokens, sim.total_tokens
            finally:
                sim.shutdown()

        mono = run(1)
        clustered = run(3)
        assert mono == clustered


@pytest.mark.placement
class TestPlacementEndToEnd:
    def test_replication_lands_blocks_and_disabled_is_bit_identical(self):
        bench = _bench()
        from llm_d_kv_cache_manager_tpu.workloads import (
            MultiTenantConfig,
            generate_multitenant,
            tenant_of,
        )

        trace = generate_multitenant(MultiTenantConfig(
            n_tenants=3, n_sessions=16, seed=5, zipf_s=2.0,
            session_rate_per_s=6.0, max_turns=2, prefix_words=120,
        ))
        reqs = trace.requests()

        def run(placement):
            # gated=False: the transfer-vs-recompute gate is exercised by
            # the costs tests; with the default sim constants (measured
            # gamma > alpha) it would — correctly — refuse every
            # replication transfer and mask what THIS test pins.
            sim = bench.FleetSim(
                "precise", pages_per_pod=256, host_tier=True,
                host_capacity=512, placement=placement, gated=False,
            )
            ttfts = []
            try:
                for r in reqs:
                    ttfts.append(sim.serve(
                        r.arrival_s, r.prompt,
                        response_words=r.output_len,
                        lora_id=tenant_of(r.session),
                    ))
                return ttfts, sim.replicated_blocks, sim.placement_stats()
            finally:
                sim.shutdown()

        off, off_blocks, _ = run(None)
        assert off_blocks == 0

        # Enabled with an unreachable threshold: pure observation — the
        # served stream is bit-identical to placement-off (the PLACEMENT=0
        # contract, exercised through the whole sim).
        observe_only, blocks, _ = run(dict(hotness_threshold=1e9))
        assert blocks == 0
        assert observe_only == off

        # Enabled for real: the hot tenant's prefix replicates, blocks
        # land on target pods, nothing is dropped or mis-targeted.
        _hot, hot_blocks, stats = run(dict(
            k_replicas=2, hotness_threshold=3.0, cooldown_s=2.0,
        ))
        assert hot_blocks > 0
        assert stats["replicator"]["jobs_submitted"] > 0
        assert stats["replicator"]["skipped_unhealthy"] == 0
        assert stats["prefetcher"]["dropped"] == 0

    def test_warm_chain_restores_from_peer_and_emits_events(self):
        from llm_d_kv_cache_manager_tpu.engine.engine import (
            EnginePod,
            EnginePodConfig,
        )
        from llm_d_kv_cache_manager_tpu.engine.tiering import (
            IndexBackedPeerResolver,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            ChunkedTokenDatabase as DB,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.pool import (
            EventPool,
            EventPoolConfig,
            Message,
        )

        index = InMemoryIndex(InMemoryIndexConfig())
        db = DB(TokenProcessorConfig(block_size=16))
        pool = EventPool(EventPoolConfig(concurrency=1), index, db)
        pool.start(with_subscriber=False)

        seq = {"a": 0, "b": 0}

        def sink_for(pod_id):
            def sink(batch):
                pool.add_task(Message(
                    topic=f"kv@{pod_id}@m", payload=batch.to_msgpack(),
                    seq=seq.__setitem__(pod_id, seq[pod_id] + 1) or seq[pod_id],
                    pod_identifier=pod_id, model_name="m",
                ))
            return sink

        cfg = dict(
            model_name="m", n_pages=128, page_size=16,
            max_pages_per_seq=256, device_tier="hbm",
            enable_host_tier=True, host_capacity_blocks=256,
            transfer_cost_model=None,
        )
        pod_a = EnginePod(
            EnginePodConfig(pod_id="a", **cfg), event_sink=sink_for("a")
        )
        pod_b = EnginePod(
            EnginePodConfig(pod_id="b", **cfg), event_sink=sink_for("b")
        )
        try:
            addrs = {
                "a": pod_a.transfer_address, "b": pod_b.transfer_address,
            }
            pod_b.set_peer_resolver(IndexBackedPeerResolver(
                index, "m", addrs, "b",
            ))
            tokens = list(range(64))
            state, _ = pod_a.prefill(tokens)
            pod_a.export_sequence(state)
            pod_a.free(state)
            pool.drain()

            landed = pod_b.warm_chain(tokens)
            assert landed == 4  # 64 tokens / 16-token pages
            keys = db.tokens_to_kv_block_keys(None, tokens, "m")
            assert all(
                pod_b.block_manager.is_cached(k.chunk_hash) for k in keys
            )
            # Idempotent: a second warm is a no-op.
            assert pod_b.warm_chain(tokens) == 0
            # The landing emitted BlockStored: the index credits pod b.
            pool.drain()
            found = index.lookup(keys, set())
            pods = {
                e.pod_identifier
                for es in found.values() for e in es
            }
            assert "b" in pods
        finally:
            pod_a.close()
            pod_b.close()
            pool.shutdown()

"""obs/ tracing spine: spans, flight recorder, score explain, metrics beat.

Pins the ISSUE-6 contracts: span nesting + cross-thread propagation,
ring-buffer bounds + slow-outlier retention, disabled mode as a shared
no-op (and score-identical either way), `/debug/traces` +
`/debug/score_explain` (explain scores bit-identical to `get_pod_scores`),
the write plane's apply-delay histogram, and the stoppable metrics beat.

Plus the ISSUE-13 contracts: TraceCarrier round-trips + malformed-carrier
robustness (a broken carrier NEVER fails a request, it counts into
kvcache_trace_carrier_errors_total), scores bit-identical with tracing
on/off × carriers present/absent, ONE assembled cross-process trace for a
cluster-mode request over real gRPC with critical-path shares summing to
~100% of root wall time, and the /debug/traces filters +
/debug/critical_path surfaces."""

import random
import string
import threading
import time

import pytest

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.obs.recorder import FlightRecorder, aggregate_stages
from llm_d_kv_cache_manager_tpu.obs.spans import ObsConfig, Trace, _NOOP
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

BLOCK_SIZE = 4
PROMPT = "The quick brown fox jumps over the lazy dog. " * 3


@pytest.fixture(autouse=True)
def _obs_reset():
    """Tracing config + recorder are process-global: every test starts
    enabled with a fresh ring and leaves the shipped defaults behind."""
    obs.configure(ObsConfig(enabled=True))
    obs.get_recorder().clear()
    yield
    obs.configure(ObsConfig())
    obs.get_recorder().clear()


def _make_indexer(fleet_health=None):
    indexer = Indexer(
        config=IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=BLOCK_SIZE),
        ),
        tokenization_pool=TokenizationPool(
            TokenizersPoolConfig(
                workers=2,
                local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
            ),
        ),
        fleet_health=fleet_health,
    )
    indexer.run()
    return indexer


def _seed_index(indexer, pod="pod-a", base=10_000):
    enc = indexer.tokenizers_pool.tokenizer.encode(PROMPT, TEST_MODEL_NAME)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        None, enc.tokens, TEST_MODEL_NAME
    )
    engine_keys = [Key(TEST_MODEL_NAME, base + i) for i in range(len(keys))]
    indexer.kv_block_index.add(engine_keys, keys, [PodEntry(pod, "hbm")])
    return len(keys)


class TestSpans:
    def test_nesting_depth_and_order(self):
        rec = obs.get_recorder()
        with obs.request("read.get_pod_scores", {"model": "m"}):
            with obs.stage("read.tokenize", nested=True):
                with obs.stage("read.encode"):
                    pass
            with obs.stage("read.lookup"):
                pass
        trace = rec.recent()[-1]
        assert trace.name == "read.get_pod_scores"
        assert trace.meta == {"model": "m"}
        # Completion order (children close first), depths reconstruct the
        # tree: encode is one level under tokenize.
        assert [(s[0], s[1]) for s in trace.spans] == [
            ("read.encode", 1),
            ("read.tokenize", 0),
            ("read.lookup", 0),
        ]
        # Stage intervals nest inside the trace window.
        for _, _, t0, t1 in trace.spans:
            assert trace.t0 <= t0 <= t1 <= trace.t1
        assert trace.duration_s > 0

    def test_nested_request_degrades_to_stage(self):
        rec = obs.get_recorder()
        with obs.request("read.get_pod_scores"):
            with obs.request("transfer.load_chain"):
                pass
        traces = rec.recent()
        assert [t.name for t in traces] == ["read.get_pod_scores"]
        assert [s[0] for s in traces[0].spans] == ["transfer.load_chain"]

    def test_cross_thread_propagation(self):
        rec = obs.get_recorder()
        with obs.request("read.get_pod_scores"):
            captured = obs.current_trace()
            assert captured is not None

            def worker():
                with obs.bind(captured):
                    with obs.stage("read.encode"):
                        pass
                obs.record_into(captured, "read.tokenize_queue_wait", 1.0, 2.0)

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        names = [s[0] for s in rec.recent()[-1].spans]
        assert "read.encode" in names
        assert "read.tokenize_queue_wait" in names
        # The worker's thread-local context never leaked into this thread.
        assert obs.current_trace() is None

    def test_disabled_mode_is_shared_noop(self):
        obs.configure(ObsConfig(enabled=False))
        rec = obs.get_recorder()
        rec.clear()
        # Every API point hands back the same singleton: no allocation,
        # no trace, no recorder traffic.
        assert obs.stage("read.lookup") is _NOOP
        assert obs.request("read.get_pod_scores") is _NOOP
        assert obs.bind(None) is _NOOP
        with obs.request("read.get_pod_scores"):
            assert obs.current_trace() is None
            with obs.stage("read.lookup"):
                pass
            obs.record("read.derive", 0.0, 1.0)
        assert rec.recent() == []
        assert rec.stats()["completed_traces"] == 0

    def test_stage_without_trace_records_nothing_but_runs(self):
        rec = obs.get_recorder()
        with obs.stage("transfer.dcn_fetch"):
            pass
        assert rec.recent() == []  # no root trace, nothing submitted


class TestRecorder:
    def _trace(self, name="read.get_pod_scores", sleep=0.0):
        t = Trace(name)
        if sleep:
            time.sleep(sleep)
        t.t1 = t.t0 + max(sleep, 1e-6)
        return t

    def test_ring_bounds_and_dropped_count(self):
        rec = FlightRecorder(ObsConfig(ring_capacity=4, slow_threshold_s=9e9))
        for _ in range(10):
            rec.submit(self._trace())
        stats = rec.stats()
        assert stats["ring_occupancy"] == 4
        assert stats["completed_traces"] == 10
        assert stats["dropped_traces"] == 6
        assert len(rec.recent()) == 4
        assert rec.recent(2) == rec.recent()[-2:]

    def test_slow_reservoir_survives_ring_churn(self):
        rec = FlightRecorder(ObsConfig(
            ring_capacity=2, slow_threshold_s=0.5, reservoir_capacity=3,
        ))
        slow = []
        for i in range(5):
            t = Trace("read.get_pod_scores")
            t.t1 = t.t0 + 1.0 + i  # 1..5 s
            slow.append(t)
            rec.submit(t)
        for _ in range(50):  # fast churn rolls the ring over
            rec.submit(self._trace())
        assert all(t.name != "read.get_pod_scores" or t.duration_s < 0.5
                   for t in rec.recent()) or True
        retained = rec.slow()
        # The 3 SLOWEST outliers survive, slowest first.
        assert [round(t.duration_s) for t in retained] == [5, 4, 3]
        stats = rec.stats()
        assert stats["slow_traces_retained"] == 3

    def test_slowest_stage_recent(self):
        rec = FlightRecorder(ObsConfig(ring_capacity=8, slow_threshold_s=9e9))
        t = Trace("read.get_pod_scores")
        t.add("read.lookup", 0, t.t0, t.t0 + 0.001)
        t.add("read.score", 0, t.t0, t.t0 + 0.002)
        t.t1 = t.t0 + 0.003
        rec.submit(t)
        slowest = rec.stats()["slowest_stage_recent"]
        assert slowest["stage"] == "read.score"
        assert slowest["ms"] == pytest.approx(2.0, abs=0.1)

    def test_aggregate_stages(self):
        t1 = Trace("read.get_pod_scores")
        t1.add("read.lookup", 0, t1.t0, t1.t0 + 0.001)
        t1.t1 = t1.t0 + 0.004
        t2 = Trace("read.get_pod_scores")
        t2.add("read.lookup", 0, t2.t0, t2.t0 + 0.003)
        t2.t1 = t2.t0 + 0.004
        agg = aggregate_stages([t1, t2])
        assert agg["read.lookup"]["calls"] == 2
        assert agg["read.lookup"]["p90_us"] == pytest.approx(3000.0, rel=0.01)
        # Stage time / summed windows: 4ms / 8ms.
        assert agg["read.lookup"]["share_pct"] == pytest.approx(50.0, abs=0.5)
        # Root rows carry the whole-request durations.
        assert agg["read.get_pod_scores"]["calls"] == 2
        assert agg["read.get_pod_scores"]["share_pct"] == pytest.approx(
            100.0, abs=0.5
        )

    def test_window_stretches_to_pre_trace_spans(self):
        # A queue wait recorded from an enqueue stamp BEFORE the trace
        # opened extends the share window instead of blowing past 100%.
        t = Trace("write.digest")
        t.add("write.queue_wait", 0, t.t0 - 0.009, t.t0)
        t.t1 = t.t0 + 0.001
        agg = aggregate_stages([t])
        assert agg["write.queue_wait"]["share_pct"] == pytest.approx(
            90.0, abs=1.0
        )

    def test_reconfigure_shrinks_ring(self):
        rec = FlightRecorder(ObsConfig(ring_capacity=8, slow_threshold_s=9e9))
        for _ in range(8):
            rec.submit(self._trace())
        rec.reconfigure(ObsConfig(ring_capacity=2, slow_threshold_s=9e9))
        assert rec.stats()["ring_occupancy"] == 2


class TestReadPathTracing:
    def test_warm_read_path_trace_has_all_stages(self):
        indexer = _make_indexer()
        try:
            _seed_index(indexer)
            rec = obs.get_recorder()
            indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            rec.clear()
            indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            trace = rec.recent()[-1]
            assert trace.name == "read.get_pod_scores"
            names = {s[0] for s in trace.spans}
            assert {
                "read.tokenize_queue_wait", "read.tokenize", "read.derive",
                "read.lookup", "read.score",
            } <= names
            # tokenize nests its pool-side children one level down.
            depths = {s[0]: s[1] for s in trace.spans}
            assert depths["read.tokenize"] == 0
            assert depths["read.tokenize_queue_wait"] == 1
        finally:
            indexer.shutdown()

    def test_scores_identical_enabled_vs_disabled(self):
        indexer = _make_indexer()
        try:
            n = _seed_index(indexer)
            obs.configure(ObsConfig(enabled=True))
            enabled = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            obs.configure(ObsConfig(enabled=False))
            disabled = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            assert enabled == disabled == {"pod-a": float(n)}
        finally:
            indexer.shutdown()


class TestScoreExplain:
    def test_explain_scores_bit_identical_and_attributed(self):
        indexer = _make_indexer()
        try:
            n = _seed_index(indexer)
            plain = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            explain = indexer.explain_scores(PROMPT, TEST_MODEL_NAME, [])
            assert explain["scores"] == plain  # bit-identical
            assert explain["chosen"] == "pod-a"
            pod = explain["pods"]["pod-a"]
            assert pod["raw_score"] == pod["score"] == float(n)
            assert pod["match_blocks"] == n
            assert pod["matched_ratio"] == 1.0
            assert pod["health"] == "healthy"
            assert pod["adjustment"] == "none"
            assert explain["blocks"] == n
            assert explain["tokens"] > 0
        finally:
            indexer.shutdown()

    def test_explain_reports_chain_memo_family(self):
        # Long enough to span several prefix-store chunks — short prompts
        # never leave the memo's cold family (nothing to memoize).
        long_prompt = "The quick brown fox jumps over the lazy dog. " * 40
        indexer = _make_indexer()
        try:
            first = indexer.explain_scores(long_prompt, TEST_MODEL_NAME, [])
            second = indexer.explain_scores(long_prompt, TEST_MODEL_NAME, [])
            third = indexer.explain_scores(long_prompt, TEST_MODEL_NAME, [])
            # Cold store+memo, then the boundary chain, then the exact
            # repeat rides the whole-request probe.
            assert first["chain_memo"]["family"] == "cold"
            assert second["chain_memo"]["family"] == "boundary"
            assert third["chain_memo"]["family"] == "request"
            assert first["chain_memo"]["stats"]["native"] in (True, False)
        finally:
            indexer.shutdown()

    def test_explain_fleet_health_adjustments(self):
        from llm_d_kv_cache_manager_tpu.fleethealth import (
            FleetHealthConfig,
            FleetHealthTracker,
        )

        now = [1000.0]
        tracker = FleetHealthTracker(
            FleetHealthConfig(suspect_after_s=30.0, stale_after_s=120.0),
            clock=lambda: now[0],
        )
        indexer = _make_indexer(fleet_health=tracker)
        try:
            n = _seed_index(indexer, pod="pod-sick")
            _seed_index(indexer, pod="pod-dead", base=50_000)
            tracker.observe_batch("pod-sick", "kv@pod-sick@m", 0, now[0])
            tracker.observe_batch("pod-dead", "kv@pod-dead@m", 0, now[0])
            # pod-sick goes silent past the suspect window; pod-dead past
            # the stale window.
            now[0] += 60.0
            tracker.observe_batch("pod-sick", "kv@pod-sick@m", 1, now[0])
            now[0] += 70.0  # sick: 70s silent -> suspect; dead: 130s -> stale
            # Explain FIRST: detecting pod-dead as stale purges its index
            # entries, so only the detecting call still sees its raw score.
            explain = indexer.explain_scores(PROMPT, TEST_MODEL_NAME, [])
            plain = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            assert explain["scores"] == plain  # bit-identical under faults
            sick = explain["pods"]["pod-sick"]
            assert sick["health"] == "suspect"
            assert sick["adjustment"] == "demoted"
            assert sick["score"] == sick["raw_score"] * 0.5
            dead = explain["pods"]["pod-dead"]
            assert dead["health"] == "stale"
            assert dead["adjustment"] == "excluded"
            assert dead["score"] is None
            assert dead["raw_score"] == float(n)
            assert "pod-dead" not in explain["scores"]
            assert explain["chosen"] == "pod-sick"
        finally:
            indexer.shutdown()


class TestWritePlaneTracing:
    def _digest(self, ts: float, stride: int = 1):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            ChunkedTokenDatabase,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.events import (
            BlockStored,
            EventBatch,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.pool import (
            EventPool,
            EventPoolConfig,
            Message,
        )

        obs.configure(ObsConfig(enabled=True, write_trace_stride=stride))
        pool = EventPool(
            EventPoolConfig(concurrency=1),
            InMemoryIndex(),
            ChunkedTokenDatabase(TokenProcessorConfig(block_size=4)),
        )
        pool.start(with_subscriber=False)
        try:
            pool.add_task(Message(
                topic="kv@pod-1@m",
                payload=EventBatch(ts=ts, events=[BlockStored(
                    block_hashes=[1, 2], parent_block_hash=None,
                    token_ids=list(range(8)), block_size=4,
                )]).to_msgpack(),
                seq=0, pod_identifier="pod-1", model_name=TEST_MODEL_NAME,
            ))
            pool.drain()
        finally:
            pool.shutdown()

    def test_batch_trace_stages_and_enqueue_stamp(self):
        rec = obs.get_recorder()
        self._digest(ts=time.time())
        traces = [t for t in rec.recent() if t.name == "write.digest"]
        assert traces, "every batch traced at stride 1"
        names = {s[0] for s in traces[-1].spans}
        assert {"write.queue_wait", "write.decode", "write.index_apply"} <= names

    def test_apply_delay_histogram_observed(self):
        metrics.register_metrics()
        before = _hist_count(metrics.event_apply_delay)
        self._digest(ts=time.time() - 0.5)
        after = _hist_count(metrics.event_apply_delay)
        assert after == before + 1
        # Synthetic sim timestamps (ts≈0 epoch) fail the plausibility
        # window and must NOT pollute the staleness signal.
        self._digest(ts=5.0)
        assert _hist_count(metrics.event_apply_delay) == after


def _hist_count(h) -> float:
    total = 0.0
    for metric in h.collect():
        for s in metric.samples:
            if s.name.endswith("_count"):
                total += s.value
    return total


class TestHttpEndpoints:
    def _service(self):
        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService

        indexer = _make_indexer()
        return ScoringService(env={}, indexer=indexer)

    def test_debug_traces_and_readyz_obs(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        service = self._service()
        _seed_index(service.indexer)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200

                resp = await client.get("/debug/traces")
                assert resp.status == 200
                data = await resp.json()
                assert data["stats"]["enabled"] is True
                assert data["stats"]["completed_traces"] >= 1
                recent = data["recent"]
                assert recent[-1]["name"] == "read.get_pod_scores"
                span_names = {s["name"] for s in recent[-1]["spans"]}
                assert "read.lookup" in span_names

                resp = await client.get("/debug/traces?n=0")
                assert (await resp.json())["recent"] == []
                resp = await client.get("/debug/traces?n=bogus")
                assert resp.status == 400

                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                data = await resp.json()
                assert data["obs"]["enabled"] is True
                assert data["obs"]["ring_capacity"] >= 1
                assert "dropped_traces" in data["obs"]
                assert "slowest_stage_recent" in data["obs"]

        try:
            asyncio.run(run())
        finally:
            service.stop()

    def test_score_explain_endpoint_matches_scoring(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        service = self._service()
        n = _seed_index(service.indexer)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                scores = (await resp.json())["podScores"]

                # GET with query params.
                resp = await client.get(
                    "/debug/score_explain",
                    params={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200
                explain = await resp.json()
                assert explain["scores"] == scores  # bit-identical
                assert explain["chosen"] == "pod-a"
                assert explain["pods"]["pod-a"]["match_blocks"] == n
                assert explain["pods"]["pod-a"]["health"] == "healthy"

                # POST body form matches too.
                resp = await client.post(
                    "/debug/score_explain",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert (await resp.json())["scores"] == scores

                # Pod filter narrows the explain the same way.
                resp = await client.get(
                    "/debug/score_explain",
                    params={
                        "prompt": PROMPT, "model": TEST_MODEL_NAME,
                        "pods": "other-pod",
                    },
                )
                assert (await resp.json())["scores"] == {}

                # Missing params -> 400, bad lora -> 400.
                resp = await client.get("/debug/score_explain")
                assert resp.status == 400
                resp = await client.get(
                    "/debug/score_explain",
                    params={
                        "prompt": PROMPT, "model": TEST_MODEL_NAME,
                        "lora_id": "x",
                    },
                )
                assert resp.status == 400

        try:
            asyncio.run(run())
        finally:
            service.stop()


class TestGrpcExplain:
    def test_explain_scores_over_grpc(self):
        import socket

        from llm_d_kv_cache_manager_tpu.api.grpc_server import (
            IndexerGrpcClient,
            serve_grpc,
        )

        indexer = _make_indexer()
        n = _seed_index(indexer, pod="pod-grpc")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        server = serve_grpc(indexer, f"127.0.0.1:{port}")
        try:
            client = IndexerGrpcClient(f"127.0.0.1:{port}")
            scores = client.get_pod_scores(PROMPT, TEST_MODEL_NAME)
            explain = client.explain_scores(PROMPT, TEST_MODEL_NAME)
            assert explain["scores"] == scores  # bit-identical over the wire
            assert explain["chosen"] == "pod-grpc"
            assert explain["pods"]["pod-grpc"]["match_blocks"] == n
            client.close()
        finally:
            server.stop(grace=0)
            indexer.shutdown()


def _carrier_errors() -> float:
    metrics.register_metrics()
    return metrics.counter_value(metrics.trace_carrier_errors)


class TestTraceCarrier:
    def test_round_trip_and_w3c_interop(self):
        with obs.request("read.get_pod_scores") as trace:
            carrier = obs.current_carrier()
            tid = trace.trace_id
        assert carrier is not None and carrier.startswith("kvtpu1-")
        parsed = obs.parse_carrier(carrier)
        assert parsed.trace_id == tid
        assert parsed.span_id == tid
        # W3C traceparent from an upstream gateway joins too (low 64 bits).
        w3c = f"00-{0:016x}{tid:016x}-{'ab' * 8}-01"
        assert obs.parse_carrier(w3c).trace_id == tid

    def test_no_carrier_outside_request_or_when_off(self):
        assert obs.current_carrier() is None  # no trace open
        obs.configure(ObsConfig(enabled=True, propagate=False))
        with obs.request("read.get_pod_scores"):
            assert obs.current_carrier() is None
        obs.configure(ObsConfig(enabled=False))
        assert obs.current_carrier() is None

    def test_malformed_carriers_counted_never_raise(self):
        rng = random.Random(13)
        junk = [
            "", "garbage", "kvtpu1", "kvtpu1---", "kvtpu1-12-34-56",
            "kvtpu1-" + "z" * 16 + "-" + "0" * 16 + "-01",
            "kvtpu1-" + "0" * 16 + "-" + "0" * 16 + "-01",  # zero trace id
            "00-shortid-span-01", b"\xff\xfe binary".decode("latin1"),
            12345, b"\xff\xff\xff",
        ] + [
            "".join(rng.choices(string.printable, k=rng.randint(1, 60)))
            for _ in range(50)
        ]
        for value in junk:
            before = _carrier_errors()
            assert obs.parse_carrier(value) is None
            assert _carrier_errors() == before + 1, f"uncounted: {value!r}"
        # Absent is NOT an error — fresh local trace, silently.
        before = _carrier_errors()
        assert obs.parse_carrier(None) is None
        assert _carrier_errors() == before

    def test_adopt_links_root_to_caller_trace_id(self):
        with obs.request("read.get_pod_scores") as caller:
            carrier = obs.current_carrier()
        with obs.adopt(carrier) as adoption:
            with obs.request("read.get_pod_scores") as served:
                assert served.trace_id == caller.trace_id
                assert served.parent_id == caller.trace_id
        assert adoption.trace is served
        payload = obs.export_trace(adoption.trace)
        assert payload["trace_id"] == f"{caller.trace_id:016x}"

    def test_adopt_malformed_falls_back_to_fresh_local_trace(self):
        before = _carrier_errors()
        with obs.adopt("kvtpu1-corrupt-carrier-zz") as adoption:
            with obs.request("read.get_pod_scores") as served:
                assert served.trace_id != 0
                assert served.parent_id == 0  # fresh local root
        assert adoption.trace is None  # nothing adopted, nothing shipped
        assert _carrier_errors() == before + 1

    def test_graft_sanitizes_unknown_remote_span_names(self):
        rec = obs.get_recorder()
        payload = {
            "trace_id": "ab" * 8, "root": "read.get_pod_scores",
            "duration_us": 500.0,
            "spans": [
                ["read.lookup", 0, 10.0, 100.0],
                ["evil.pod_name_12345", 0, 120.0, 50.0],  # label-mint try
                "not-a-span",  # garbage entry: counted, skipped
            ],
        }
        before = _carrier_errors()
        with obs.request("cluster.get_pod_scores") as trace:
            t0 = time.perf_counter()
            obs.graft_remote(trace, payload, t0, t0 + 0.001)
        names = {s[0] for s in rec.recent()[-1].spans}
        assert "read.lookup" in names
        assert "other.remote_span" in names
        assert not any("evil" in n for n in names)
        assert _carrier_errors() == before + 1


class TestCriticalPath:
    def test_partition_is_exact(self):
        from llm_d_kv_cache_manager_tpu.obs.recorder import critical_path

        t = Trace("read.get_pod_scores")
        # tokenize [1,4]ms and score [3,9]ms overlap: the critical path
        # takes score back to 3ms, then tokenize's remainder [1,3]ms.
        t.spans = [
            ("read.tokenize", 0, t.t0 + 0.001, t.t0 + 0.004),
            ("read.score", 0, t.t0 + 0.003, t.t0 + 0.009),
        ]
        t.t1 = t.t0 + 0.010
        cp = critical_path(t)
        self_us = {(e["span"], e["hop"]): e["self_us"] for e in cp["entries"]}
        assert self_us[("read.score", "local")] == pytest.approx(6000, abs=1)
        assert self_us[("read.tokenize", "local")] == pytest.approx(
            2000, abs=1
        )
        assert self_us[("read.get_pod_scores", "local")] == pytest.approx(
            2000, abs=1
        )
        assert cp["share_sum_pct"] == pytest.approx(100.0, abs=0.5)

    def test_hop_attribution_under_rpc_span(self):
        from llm_d_kv_cache_manager_tpu.obs.recorder import critical_path

        t = Trace("cluster.get_pod_scores")
        t.spans = [
            ("cluster.rpc", 1, t.t0 + 0.001, t.t0 + 0.005),
            ("read.lookup", 2, t.t0 + 0.002, t.t0 + 0.004),
        ]
        t.t1 = t.t0 + 0.006
        cp = critical_path(t)
        entries = {(e["span"], e["hop"]) for e in cp["entries"]}
        assert ("read.lookup", "cluster.rpc") in entries
        assert ("cluster.rpc", "local") in entries  # wire/serialization gap
        assert cp["share_sum_pct"] == pytest.approx(100.0, abs=0.5)

    def test_aggregate_groups_by_root(self):
        from llm_d_kv_cache_manager_tpu.obs.recorder import (
            aggregate_critical_path,
        )

        traces = []
        for _ in range(3):
            t = Trace("read.get_pod_scores")
            t.spans = [("read.lookup", 0, t.t0 + 0.001, t.t0 + 0.003)]
            t.t1 = t.t0 + 0.004
            traces.append(t)
        agg = aggregate_critical_path(traces)
        doc = agg["read.get_pod_scores"]
        assert doc["traces"] == 3
        shares = {
            (e["span"], e["hop"]): e["share_pct"] for e in doc["entries"]
        }
        assert shares[("read.lookup", "local")] == pytest.approx(50.0, abs=1)
        assert sum(shares.values()) == pytest.approx(100.0, abs=0.5)


class TestDistributedClusterTrace:
    """The ISSUE-13 acceptance pin: a cluster-mode request produces ONE
    assembled trace containing replica-side stages under the caller's
    trace id, critical-path shares summing to ~100% of root wall time."""

    def _replica_indexers(self, n=2):
        from llm_d_kv_cache_manager_tpu.cluster import ReplicaPartitioner

        partitioner = ReplicaPartitioner(n)
        indexers = []
        for _ in range(n):
            idx = _make_indexer()
            indexers.append(idx)
        # Seed every replica with every pod's entries; the ownership merge
        # only takes pod P's answer from owner(P), so the merged result is
        # the monolithic answer either way.
        for idx in indexers:
            _seed_index(idx, pod="pod-a")
            _seed_index(idx, pod="pod-b", base=60_000)
        return partitioner, indexers

    def _assert_assembled(self, scorer, caller_fn, rec):
        rec.clear()
        result = caller_fn()
        assert result.scores  # the request actually scored
        assembled = [
            t for t in rec.recent() if t.name == "cluster.get_pod_scores"
        ]
        assert assembled, "no cluster root trace recorded"
        trace = assembled[-1]
        names = [s[0] for s in trace.spans]
        # Per-replica rpc hops + replica-side read stages inside them.
        assert names.count("cluster.rpc") == 2
        assert "read.lookup" in names and "read.score" in names
        assert "cluster.fanout" in names and "cluster.merge" in names
        # Replica-side roots in the ring share the caller's trace id:
        # one distributed trace, not three unrelated ones.
        replica_roots = [
            t for t in rec.recent()
            if t.name == "read.get_pod_scores"
            and t.trace_id == trace.trace_id
        ]
        assert len(replica_roots) == 2
        assert all(r.parent_id == trace.trace_id for r in replica_roots)
        # Critical-path shares sum to ~100% of root wall time, with the
        # replica hop attributed as such.
        from llm_d_kv_cache_manager_tpu.obs.recorder import critical_path

        cp = critical_path(trace)
        assert cp["share_sum_pct"] == pytest.approx(100.0, abs=1.0)
        hops = {(e["span"], e["hop"]) for e in cp["entries"]}
        assert any(hop == "cluster.rpc" for _, hop in hops)
        return trace

    def test_local_transport_assembles_one_trace(self):
        from llm_d_kv_cache_manager_tpu.cluster import (
            ClusterConfig,
            ClusterScorer,
        )
        from llm_d_kv_cache_manager_tpu.cluster.scorer import (
            LocalReplicaTransport,
        )

        partitioner, indexers = self._replica_indexers()
        scorer = ClusterScorer(
            [LocalReplicaTransport(i) for i in indexers],
            partitioner=partitioner,
            config=ClusterConfig(num_replicas=2),
        )
        try:
            self._assert_assembled(
                scorer,
                lambda: scorer.get_pod_scores_ex(PROMPT, TEST_MODEL_NAME, []),
                obs.get_recorder(),
            )
        finally:
            scorer.close()
            for idx in indexers:
                idx.shutdown()

    @pytest.mark.cluster
    def test_grpc_transport_assembles_one_trace(self):
        import socket

        from llm_d_kv_cache_manager_tpu.api.grpc_server import serve_grpc
        from llm_d_kv_cache_manager_tpu.cluster import (
            ClusterConfig,
            ClusterScorer,
        )
        from llm_d_kv_cache_manager_tpu.cluster.scorer import (
            GrpcReplicaTransport,
        )

        partitioner, indexers = self._replica_indexers()
        servers, transports = [], []
        for idx in indexers:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            servers.append(serve_grpc(idx, f"127.0.0.1:{port}"))
            transports.append(GrpcReplicaTransport(f"127.0.0.1:{port}"))
        scorer = ClusterScorer(
            transports, partitioner=partitioner,
            config=ClusterConfig(num_replicas=2),
        )
        try:
            rec = obs.get_recorder()
            trace = self._assert_assembled(
                scorer,
                lambda: scorer.get_pod_scores_ex(PROMPT, TEST_MODEL_NAME, []),
                rec,
            )
            # Bit-identity: the assembled-trace run scores exactly like a
            # propagation-off run over the same state.
            traced_scores = scorer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            obs.configure(ObsConfig(enabled=True, propagate=False))
            plain_scores = scorer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            assert traced_scores == plain_scores
            assert trace.meta.get("rpc_replicas")  # hop identity as data

            # Batched surface assembles too (bulk stream ships window
            # traces back).
            obs.configure(ObsConfig(enabled=True))
            from llm_d_kv_cache_manager_tpu.kvcache.indexer import ScoreRequest

            rec.clear()
            requests = [
                ScoreRequest(prompt=PROMPT, model_name=TEST_MODEL_NAME)
                for _ in range(3)
            ]
            results = scorer.score_many(requests)
            assert len(results) == 3 and all(r.scores for r in results)
            batch_traces = [
                t for t in rec.recent() if t.name == "cluster.score_many"
            ]
            assert batch_traces
            bnames = [s[0] for s in batch_traces[-1].spans]
            assert "cluster.rpc" in bnames
            assert "read.score_many" in bnames  # remote batch root grafted
        finally:
            scorer.close()
            for server in servers:
                server.stop(grace=0)
            for idx in indexers:
                idx.shutdown()


class TestTracesEndpointFilters:
    def test_filters_and_critical_path_endpoint(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService

        service = ScoringService(env={}, indexer=_make_indexer())
        _seed_index(service.indexer)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200

                # plane filter: the read trace is there, the write plane
                # is empty.
                resp = await client.get("/debug/traces?plane=read")
                data = await resp.json()
                assert data["recent"]
                assert all(
                    t["name"].startswith("read.") for t in data["recent"]
                )
                resp = await client.get("/debug/traces?plane=write")
                assert (await resp.json())["recent"] == []
                resp = await client.get("/debug/traces?plane=bogus")
                assert resp.status == 400

                # min_ms filter: nothing took 10 minutes.
                resp = await client.get("/debug/traces?min_ms=600000")
                data = await resp.json()
                assert data["recent"] == [] and data["slow"] == []

                # limit alias + crit attachment.
                resp = await client.get("/debug/traces?limit=1&crit=1")
                data = await resp.json()
                assert len(data["recent"]) == 1
                cp = data["recent"][0]["critical_path"]
                assert cp["share_sum_pct"] == pytest.approx(100.0, abs=1.0)

                # trace_id exact fetch round-trips through the rendered id.
                tid = data["recent"][0]["trace_id"]
                resp = await client.get(f"/debug/traces?trace_id={tid}")
                data = await resp.json()
                assert [t["trace_id"] for t in data["recent"]] == [tid]
                resp = await client.get("/debug/traces?trace_id=ffffffffffffffff")
                assert (await resp.json())["recent"] == []

                # /debug/critical_path window summary.
                resp = await client.get("/debug/critical_path")
                assert resp.status == 200
                doc = await resp.json()
                assert doc["traces"] >= 1
                root = doc["roots"]["read.get_pod_scores"]
                assert root["entries"][0]["self_us"] > 0
                resp = await client.get(
                    "/debug/critical_path?root=write.digest"
                )
                assert (await resp.json())["roots"] == {}

        try:
            asyncio.run(run())
        finally:
            service.stop()


class TestCarrierRobustnessHttp:
    """Property: no header value — valid, truncated, malformed, or binary
    garbage — changes scores or fails a request; malformed ones count."""

    def test_scores_bit_identical_and_errors_counted(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService

        service = ScoringService(env={}, indexer=_make_indexer())
        _seed_index(service.indexer)
        rng = random.Random(29)
        headers_cases = [None, "kvtpu1-0bad", "", "00-xx-yy-zz"] + [
            "".join(
                rng.choices(string.ascii_letters + string.digits + "-", k=30)
            )
            for _ in range(8)
        ]

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                baseline = (await resp.json())["podScores"]
                assert baseline
                for value in headers_cases:
                    headers = (
                        {"X-Kvtpu-Trace": value} if value is not None else {}
                    )
                    before = _carrier_errors()
                    resp = await client.post(
                        "/score_completions",
                        json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                        headers=headers,
                    )
                    assert resp.status == 200
                    assert (await resp.json())["podScores"] == baseline
                    if value is not None:
                        # every non-absent case here is malformed → counted
                        assert _carrier_errors() == before + 1
                    else:
                        assert _carrier_errors() == before

                # A VALID carrier adopts: the served root carries the
                # caller's id and still scores identically.
                with obs.request("read.get_pod_scores") as caller:
                    carrier = obs.current_carrier()
                rec = obs.get_recorder()
                rec.clear()
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                    headers={"X-Kvtpu-Trace": carrier},
                )
                assert (await resp.json())["podScores"] == baseline
                served = [
                    t for t in rec.recent()
                    if t.trace_id == caller.trace_id
                ]
                assert served, "served root did not adopt the carrier"

                # Tracing fully off: same scores again.
                obs.configure(ObsConfig(enabled=False))
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                    headers={"X-Kvtpu-Trace": carrier},
                )
                assert (await resp.json())["podScores"] == baseline

        try:
            asyncio.run(run())
        finally:
            service.stop()

    @pytest.mark.cluster
    def test_grpc_malformed_metadata_never_fails(self):
        import socket

        from llm_d_kv_cache_manager_tpu.api.grpc_server import (
            IndexerGrpcClient,
            serve_grpc,
        )

        indexer = _make_indexer()
        _seed_index(indexer, pod="pod-grpc")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        server = serve_grpc(indexer, f"127.0.0.1:{port}")
        try:
            client = IndexerGrpcClient(f"127.0.0.1:{port}")
            baseline = client.get_pod_scores_ex(PROMPT, TEST_MODEL_NAME)
            for junk in ("kvtpu1-br0ken", "x", "kvtpu1----"):
                before = _carrier_errors()
                payload = client.get_pod_scores_ex(
                    PROMPT, TEST_MODEL_NAME, carrier=junk
                )
                assert payload["scores"] == baseline["scores"]
                assert "trace" not in payload  # nothing adopted → no ship
                assert _carrier_errors() == before + 1
            client.close()
        finally:
            server.stop(grace=0)
            indexer.shutdown()


class TestMetricsBeat:
    def test_start_stop_does_not_leak_thread(self):
        metrics.register_metrics()
        before = {t.name for t in threading.enumerate()}
        assert "metrics-beat" not in before
        metrics.start_metrics_logging(interval_s=3600.0)
        assert any(
            t.name == "metrics-beat" for t in threading.enumerate()
        )
        metrics.start_metrics_logging(interval_s=3600.0)  # idempotent
        assert sum(
            1 for t in threading.enumerate() if t.name == "metrics-beat"
        ) == 1
        metrics.stop_metrics_logging()
        assert not any(
            t.name == "metrics-beat" for t in threading.enumerate()
        )
        metrics.stop_metrics_logging()  # idempotent when already stopped

    def test_beat_line_uses_public_counter_reads(self, caplog):
        import logging

        metrics.register_metrics()
        metrics.count_stream_anomaly("seq_gap")  # labeled counter
        metrics.count_transfer_failure()
        with caplog.at_level(logging.INFO, logger="kvtpu.metrics"):
            metrics.start_metrics_logging(interval_s=0.05)
            deadline = time.time() + 5.0
            while time.time() < deadline and not any(
                "metrics beat" in r.message for r in caplog.records
            ):
                time.sleep(0.01)
            metrics.stop_metrics_logging()
        beat = next(
            r.message for r in caplog.records if "metrics beat" in r.message
        )
        # The PR-3/PR-5 counters made it into the beat line, and the
        # labeled anomaly counter reads through collect() (the private
        # _value peek read 0 for labeled counters).
        assert "anomalies=" in beat
        assert "transfer_failures=" in beat
        assert "prefetch_blocks=" in beat

    def test_counter_value_sums_labeled_counters(self):
        metrics.register_metrics()
        base = metrics.counter_value(metrics.event_stream_anomalies)
        metrics.count_stream_anomaly("seq_gap")
        metrics.count_stream_anomaly("duplicate")
        assert metrics.counter_value(
            metrics.event_stream_anomalies
        ) == pytest.approx(base + 2)
        assert metrics.counter_value(None) == 0.0

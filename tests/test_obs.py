"""obs/ tracing spine: spans, flight recorder, score explain, metrics beat.

Pins the ISSUE-6 contracts: span nesting + cross-thread propagation,
ring-buffer bounds + slow-outlier retention, disabled mode as a shared
no-op (and score-identical either way), `/debug/traces` +
`/debug/score_explain` (explain scores bit-identical to `get_pod_scores`),
the write plane's apply-delay histogram, and the stoppable metrics beat.
"""

import threading
import time

import pytest

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from llm_d_kv_cache_manager_tpu import obs
from llm_d_kv_cache_manager_tpu.obs.recorder import FlightRecorder, aggregate_stages
from llm_d_kv_cache_manager_tpu.obs.spans import ObsConfig, Trace, _NOOP
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.metrics import collector as metrics
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

BLOCK_SIZE = 4
PROMPT = "The quick brown fox jumps over the lazy dog. " * 3


@pytest.fixture(autouse=True)
def _obs_reset():
    """Tracing config + recorder are process-global: every test starts
    enabled with a fresh ring and leaves the shipped defaults behind."""
    obs.configure(ObsConfig(enabled=True))
    obs.get_recorder().clear()
    yield
    obs.configure(ObsConfig())
    obs.get_recorder().clear()


def _make_indexer(fleet_health=None):
    indexer = Indexer(
        config=IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=BLOCK_SIZE),
        ),
        tokenization_pool=TokenizationPool(
            TokenizersPoolConfig(
                workers=2,
                local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
            ),
        ),
        fleet_health=fleet_health,
    )
    indexer.run()
    return indexer


def _seed_index(indexer, pod="pod-a", base=10_000):
    enc = indexer.tokenizers_pool.tokenizer.encode(PROMPT, TEST_MODEL_NAME)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        None, enc.tokens, TEST_MODEL_NAME
    )
    engine_keys = [Key(TEST_MODEL_NAME, base + i) for i in range(len(keys))]
    indexer.kv_block_index.add(engine_keys, keys, [PodEntry(pod, "hbm")])
    return len(keys)


class TestSpans:
    def test_nesting_depth_and_order(self):
        rec = obs.get_recorder()
        with obs.request("read.get_pod_scores", {"model": "m"}):
            with obs.stage("read.tokenize", nested=True):
                with obs.stage("read.encode"):
                    pass
            with obs.stage("read.lookup"):
                pass
        trace = rec.recent()[-1]
        assert trace.name == "read.get_pod_scores"
        assert trace.meta == {"model": "m"}
        # Completion order (children close first), depths reconstruct the
        # tree: encode is one level under tokenize.
        assert [(s[0], s[1]) for s in trace.spans] == [
            ("read.encode", 1),
            ("read.tokenize", 0),
            ("read.lookup", 0),
        ]
        # Stage intervals nest inside the trace window.
        for _, _, t0, t1 in trace.spans:
            assert trace.t0 <= t0 <= t1 <= trace.t1
        assert trace.duration_s > 0

    def test_nested_request_degrades_to_stage(self):
        rec = obs.get_recorder()
        with obs.request("read.get_pod_scores"):
            with obs.request("transfer.load_chain"):
                pass
        traces = rec.recent()
        assert [t.name for t in traces] == ["read.get_pod_scores"]
        assert [s[0] for s in traces[0].spans] == ["transfer.load_chain"]

    def test_cross_thread_propagation(self):
        rec = obs.get_recorder()
        with obs.request("read.get_pod_scores"):
            captured = obs.current_trace()
            assert captured is not None

            def worker():
                with obs.bind(captured):
                    with obs.stage("read.encode"):
                        pass
                obs.record_into(captured, "read.tokenize_queue_wait", 1.0, 2.0)

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        names = [s[0] for s in rec.recent()[-1].spans]
        assert "read.encode" in names
        assert "read.tokenize_queue_wait" in names
        # The worker's thread-local context never leaked into this thread.
        assert obs.current_trace() is None

    def test_disabled_mode_is_shared_noop(self):
        obs.configure(ObsConfig(enabled=False))
        rec = obs.get_recorder()
        rec.clear()
        # Every API point hands back the same singleton: no allocation,
        # no trace, no recorder traffic.
        assert obs.stage("read.lookup") is _NOOP
        assert obs.request("read.get_pod_scores") is _NOOP
        assert obs.bind(None) is _NOOP
        with obs.request("read.get_pod_scores"):
            assert obs.current_trace() is None
            with obs.stage("read.lookup"):
                pass
            obs.record("read.derive", 0.0, 1.0)
        assert rec.recent() == []
        assert rec.stats()["completed_traces"] == 0

    def test_stage_without_trace_records_nothing_but_runs(self):
        rec = obs.get_recorder()
        with obs.stage("transfer.dcn_fetch"):
            pass
        assert rec.recent() == []  # no root trace, nothing submitted


class TestRecorder:
    def _trace(self, name="read.get_pod_scores", sleep=0.0):
        t = Trace(name)
        if sleep:
            time.sleep(sleep)
        t.t1 = t.t0 + max(sleep, 1e-6)
        return t

    def test_ring_bounds_and_dropped_count(self):
        rec = FlightRecorder(ObsConfig(ring_capacity=4, slow_threshold_s=9e9))
        for _ in range(10):
            rec.submit(self._trace())
        stats = rec.stats()
        assert stats["ring_occupancy"] == 4
        assert stats["completed_traces"] == 10
        assert stats["dropped_traces"] == 6
        assert len(rec.recent()) == 4
        assert rec.recent(2) == rec.recent()[-2:]

    def test_slow_reservoir_survives_ring_churn(self):
        rec = FlightRecorder(ObsConfig(
            ring_capacity=2, slow_threshold_s=0.5, reservoir_capacity=3,
        ))
        slow = []
        for i in range(5):
            t = Trace("read.get_pod_scores")
            t.t1 = t.t0 + 1.0 + i  # 1..5 s
            slow.append(t)
            rec.submit(t)
        for _ in range(50):  # fast churn rolls the ring over
            rec.submit(self._trace())
        assert all(t.name != "read.get_pod_scores" or t.duration_s < 0.5
                   for t in rec.recent()) or True
        retained = rec.slow()
        # The 3 SLOWEST outliers survive, slowest first.
        assert [round(t.duration_s) for t in retained] == [5, 4, 3]
        stats = rec.stats()
        assert stats["slow_traces_retained"] == 3

    def test_slowest_stage_recent(self):
        rec = FlightRecorder(ObsConfig(ring_capacity=8, slow_threshold_s=9e9))
        t = Trace("read.get_pod_scores")
        t.add("read.lookup", 0, t.t0, t.t0 + 0.001)
        t.add("read.score", 0, t.t0, t.t0 + 0.002)
        t.t1 = t.t0 + 0.003
        rec.submit(t)
        slowest = rec.stats()["slowest_stage_recent"]
        assert slowest["stage"] == "read.score"
        assert slowest["ms"] == pytest.approx(2.0, abs=0.1)

    def test_aggregate_stages(self):
        t1 = Trace("read.get_pod_scores")
        t1.add("read.lookup", 0, t1.t0, t1.t0 + 0.001)
        t1.t1 = t1.t0 + 0.004
        t2 = Trace("read.get_pod_scores")
        t2.add("read.lookup", 0, t2.t0, t2.t0 + 0.003)
        t2.t1 = t2.t0 + 0.004
        agg = aggregate_stages([t1, t2])
        assert agg["read.lookup"]["calls"] == 2
        assert agg["read.lookup"]["p90_us"] == pytest.approx(3000.0, rel=0.01)
        # Stage time / summed windows: 4ms / 8ms.
        assert agg["read.lookup"]["share_pct"] == pytest.approx(50.0, abs=0.5)
        # Root rows carry the whole-request durations.
        assert agg["read.get_pod_scores"]["calls"] == 2
        assert agg["read.get_pod_scores"]["share_pct"] == pytest.approx(
            100.0, abs=0.5
        )

    def test_window_stretches_to_pre_trace_spans(self):
        # A queue wait recorded from an enqueue stamp BEFORE the trace
        # opened extends the share window instead of blowing past 100%.
        t = Trace("write.digest")
        t.add("write.queue_wait", 0, t.t0 - 0.009, t.t0)
        t.t1 = t.t0 + 0.001
        agg = aggregate_stages([t])
        assert agg["write.queue_wait"]["share_pct"] == pytest.approx(
            90.0, abs=1.0
        )

    def test_reconfigure_shrinks_ring(self):
        rec = FlightRecorder(ObsConfig(ring_capacity=8, slow_threshold_s=9e9))
        for _ in range(8):
            rec.submit(self._trace())
        rec.reconfigure(ObsConfig(ring_capacity=2, slow_threshold_s=9e9))
        assert rec.stats()["ring_occupancy"] == 2


class TestReadPathTracing:
    def test_warm_read_path_trace_has_all_stages(self):
        indexer = _make_indexer()
        try:
            _seed_index(indexer)
            rec = obs.get_recorder()
            indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            rec.clear()
            indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            trace = rec.recent()[-1]
            assert trace.name == "read.get_pod_scores"
            names = {s[0] for s in trace.spans}
            assert {
                "read.tokenize_queue_wait", "read.tokenize", "read.derive",
                "read.lookup", "read.score",
            } <= names
            # tokenize nests its pool-side children one level down.
            depths = {s[0]: s[1] for s in trace.spans}
            assert depths["read.tokenize"] == 0
            assert depths["read.tokenize_queue_wait"] == 1
        finally:
            indexer.shutdown()

    def test_scores_identical_enabled_vs_disabled(self):
        indexer = _make_indexer()
        try:
            n = _seed_index(indexer)
            obs.configure(ObsConfig(enabled=True))
            enabled = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            obs.configure(ObsConfig(enabled=False))
            disabled = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            assert enabled == disabled == {"pod-a": float(n)}
        finally:
            indexer.shutdown()


class TestScoreExplain:
    def test_explain_scores_bit_identical_and_attributed(self):
        indexer = _make_indexer()
        try:
            n = _seed_index(indexer)
            plain = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            explain = indexer.explain_scores(PROMPT, TEST_MODEL_NAME, [])
            assert explain["scores"] == plain  # bit-identical
            assert explain["chosen"] == "pod-a"
            pod = explain["pods"]["pod-a"]
            assert pod["raw_score"] == pod["score"] == float(n)
            assert pod["match_blocks"] == n
            assert pod["matched_ratio"] == 1.0
            assert pod["health"] == "healthy"
            assert pod["adjustment"] == "none"
            assert explain["blocks"] == n
            assert explain["tokens"] > 0
        finally:
            indexer.shutdown()

    def test_explain_reports_chain_memo_family(self):
        # Long enough to span several prefix-store chunks — short prompts
        # never leave the memo's cold family (nothing to memoize).
        long_prompt = "The quick brown fox jumps over the lazy dog. " * 40
        indexer = _make_indexer()
        try:
            first = indexer.explain_scores(long_prompt, TEST_MODEL_NAME, [])
            second = indexer.explain_scores(long_prompt, TEST_MODEL_NAME, [])
            third = indexer.explain_scores(long_prompt, TEST_MODEL_NAME, [])
            # Cold store+memo, then the boundary chain, then the exact
            # repeat rides the whole-request probe.
            assert first["chain_memo"]["family"] == "cold"
            assert second["chain_memo"]["family"] == "boundary"
            assert third["chain_memo"]["family"] == "request"
            assert first["chain_memo"]["stats"]["native"] in (True, False)
        finally:
            indexer.shutdown()

    def test_explain_fleet_health_adjustments(self):
        from llm_d_kv_cache_manager_tpu.fleethealth import (
            FleetHealthConfig,
            FleetHealthTracker,
        )

        now = [1000.0]
        tracker = FleetHealthTracker(
            FleetHealthConfig(suspect_after_s=30.0, stale_after_s=120.0),
            clock=lambda: now[0],
        )
        indexer = _make_indexer(fleet_health=tracker)
        try:
            n = _seed_index(indexer, pod="pod-sick")
            _seed_index(indexer, pod="pod-dead", base=50_000)
            tracker.observe_batch("pod-sick", "kv@pod-sick@m", 0, now[0])
            tracker.observe_batch("pod-dead", "kv@pod-dead@m", 0, now[0])
            # pod-sick goes silent past the suspect window; pod-dead past
            # the stale window.
            now[0] += 60.0
            tracker.observe_batch("pod-sick", "kv@pod-sick@m", 1, now[0])
            now[0] += 70.0  # sick: 70s silent -> suspect; dead: 130s -> stale
            # Explain FIRST: detecting pod-dead as stale purges its index
            # entries, so only the detecting call still sees its raw score.
            explain = indexer.explain_scores(PROMPT, TEST_MODEL_NAME, [])
            plain = indexer.get_pod_scores(PROMPT, TEST_MODEL_NAME, [])
            assert explain["scores"] == plain  # bit-identical under faults
            sick = explain["pods"]["pod-sick"]
            assert sick["health"] == "suspect"
            assert sick["adjustment"] == "demoted"
            assert sick["score"] == sick["raw_score"] * 0.5
            dead = explain["pods"]["pod-dead"]
            assert dead["health"] == "stale"
            assert dead["adjustment"] == "excluded"
            assert dead["score"] is None
            assert dead["raw_score"] == float(n)
            assert "pod-dead" not in explain["scores"]
            assert explain["chosen"] == "pod-sick"
        finally:
            indexer.shutdown()


class TestWritePlaneTracing:
    def _digest(self, ts: float, stride: int = 1):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
            InMemoryIndex,
        )
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
            ChunkedTokenDatabase,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.events import (
            BlockStored,
            EventBatch,
        )
        from llm_d_kv_cache_manager_tpu.kvevents.pool import (
            EventPool,
            EventPoolConfig,
            Message,
        )

        obs.configure(ObsConfig(enabled=True, write_trace_stride=stride))
        pool = EventPool(
            EventPoolConfig(concurrency=1),
            InMemoryIndex(),
            ChunkedTokenDatabase(TokenProcessorConfig(block_size=4)),
        )
        pool.start(with_subscriber=False)
        try:
            pool.add_task(Message(
                topic="kv@pod-1@m",
                payload=EventBatch(ts=ts, events=[BlockStored(
                    block_hashes=[1, 2], parent_block_hash=None,
                    token_ids=list(range(8)), block_size=4,
                )]).to_msgpack(),
                seq=0, pod_identifier="pod-1", model_name=TEST_MODEL_NAME,
            ))
            pool.drain()
        finally:
            pool.shutdown()

    def test_batch_trace_stages_and_enqueue_stamp(self):
        rec = obs.get_recorder()
        self._digest(ts=time.time())
        traces = [t for t in rec.recent() if t.name == "write.digest"]
        assert traces, "every batch traced at stride 1"
        names = {s[0] for s in traces[-1].spans}
        assert {"write.queue_wait", "write.decode", "write.index_apply"} <= names

    def test_apply_delay_histogram_observed(self):
        metrics.register_metrics()
        before = _hist_count(metrics.event_apply_delay)
        self._digest(ts=time.time() - 0.5)
        after = _hist_count(metrics.event_apply_delay)
        assert after == before + 1
        # Synthetic sim timestamps (ts≈0 epoch) fail the plausibility
        # window and must NOT pollute the staleness signal.
        self._digest(ts=5.0)
        assert _hist_count(metrics.event_apply_delay) == after


def _hist_count(h) -> float:
    total = 0.0
    for metric in h.collect():
        for s in metric.samples:
            if s.name.endswith("_count"):
                total += s.value
    return total


class TestHttpEndpoints:
    def _service(self):
        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService

        indexer = _make_indexer()
        return ScoringService(env={}, indexer=indexer)

    def test_debug_traces_and_readyz_obs(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        service = self._service()
        _seed_index(service.indexer)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200

                resp = await client.get("/debug/traces")
                assert resp.status == 200
                data = await resp.json()
                assert data["stats"]["enabled"] is True
                assert data["stats"]["completed_traces"] >= 1
                recent = data["recent"]
                assert recent[-1]["name"] == "read.get_pod_scores"
                span_names = {s["name"] for s in recent[-1]["spans"]}
                assert "read.lookup" in span_names

                resp = await client.get("/debug/traces?n=0")
                assert (await resp.json())["recent"] == []
                resp = await client.get("/debug/traces?n=bogus")
                assert resp.status == 400

                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                data = await resp.json()
                assert data["obs"]["enabled"] is True
                assert data["obs"]["ring_capacity"] >= 1
                assert "dropped_traces" in data["obs"]
                assert "slowest_stage_recent" in data["obs"]

        try:
            asyncio.run(run())
        finally:
            service.stop()

    def test_score_explain_endpoint_matches_scoring(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        service = self._service()
        n = _seed_index(service.indexer)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                resp = await client.post(
                    "/score_completions",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                scores = (await resp.json())["podScores"]

                # GET with query params.
                resp = await client.get(
                    "/debug/score_explain",
                    params={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200
                explain = await resp.json()
                assert explain["scores"] == scores  # bit-identical
                assert explain["chosen"] == "pod-a"
                assert explain["pods"]["pod-a"]["match_blocks"] == n
                assert explain["pods"]["pod-a"]["health"] == "healthy"

                # POST body form matches too.
                resp = await client.post(
                    "/debug/score_explain",
                    json={"prompt": PROMPT, "model": TEST_MODEL_NAME},
                )
                assert (await resp.json())["scores"] == scores

                # Pod filter narrows the explain the same way.
                resp = await client.get(
                    "/debug/score_explain",
                    params={
                        "prompt": PROMPT, "model": TEST_MODEL_NAME,
                        "pods": "other-pod",
                    },
                )
                assert (await resp.json())["scores"] == {}

                # Missing params -> 400, bad lora -> 400.
                resp = await client.get("/debug/score_explain")
                assert resp.status == 400
                resp = await client.get(
                    "/debug/score_explain",
                    params={
                        "prompt": PROMPT, "model": TEST_MODEL_NAME,
                        "lora_id": "x",
                    },
                )
                assert resp.status == 400

        try:
            asyncio.run(run())
        finally:
            service.stop()


class TestGrpcExplain:
    def test_explain_scores_over_grpc(self):
        import socket

        from llm_d_kv_cache_manager_tpu.api.grpc_server import (
            IndexerGrpcClient,
            serve_grpc,
        )

        indexer = _make_indexer()
        n = _seed_index(indexer, pod="pod-grpc")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        server = serve_grpc(indexer, f"127.0.0.1:{port}")
        try:
            client = IndexerGrpcClient(f"127.0.0.1:{port}")
            scores = client.get_pod_scores(PROMPT, TEST_MODEL_NAME)
            explain = client.explain_scores(PROMPT, TEST_MODEL_NAME)
            assert explain["scores"] == scores  # bit-identical over the wire
            assert explain["chosen"] == "pod-grpc"
            assert explain["pods"]["pod-grpc"]["match_blocks"] == n
            client.close()
        finally:
            server.stop(grace=0)
            indexer.shutdown()


class TestMetricsBeat:
    def test_start_stop_does_not_leak_thread(self):
        metrics.register_metrics()
        before = {t.name for t in threading.enumerate()}
        assert "metrics-beat" not in before
        metrics.start_metrics_logging(interval_s=3600.0)
        assert any(
            t.name == "metrics-beat" for t in threading.enumerate()
        )
        metrics.start_metrics_logging(interval_s=3600.0)  # idempotent
        assert sum(
            1 for t in threading.enumerate() if t.name == "metrics-beat"
        ) == 1
        metrics.stop_metrics_logging()
        assert not any(
            t.name == "metrics-beat" for t in threading.enumerate()
        )
        metrics.stop_metrics_logging()  # idempotent when already stopped

    def test_beat_line_uses_public_counter_reads(self, caplog):
        import logging

        metrics.register_metrics()
        metrics.count_stream_anomaly("seq_gap")  # labeled counter
        metrics.count_transfer_failure()
        with caplog.at_level(logging.INFO, logger="kvtpu.metrics"):
            metrics.start_metrics_logging(interval_s=0.05)
            deadline = time.time() + 5.0
            while time.time() < deadline and not any(
                "metrics beat" in r.message for r in caplog.records
            ):
                time.sleep(0.01)
            metrics.stop_metrics_logging()
        beat = next(
            r.message for r in caplog.records if "metrics beat" in r.message
        )
        # The PR-3/PR-5 counters made it into the beat line, and the
        # labeled anomaly counter reads through collect() (the private
        # _value peek read 0 for labeled counters).
        assert "anomalies=" in beat
        assert "transfer_failures=" in beat
        assert "prefetch_blocks=" in beat

    def test_counter_value_sums_labeled_counters(self):
        metrics.register_metrics()
        base = metrics.counter_value(metrics.event_stream_anomalies)
        metrics.count_stream_anomaly("seq_gap")
        metrics.count_stream_anomaly("duplicate")
        assert metrics.counter_value(
            metrics.event_stream_anomalies
        ) == pytest.approx(base + 2)
        assert metrics.counter_value(None) == 0.0

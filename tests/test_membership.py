"""Elastic fleet membership tests (cluster/membership.py).

The load-bearing pins:

- **Reassignment property (the PR's correctness core):** an event stream
  replayed across a live partition handoff (old owner → new owner, with
  seq floors and journal replay) yields an index bit-identical to a run
  that was NEVER reassigned — across all four index backends. The stream
  interleaves BlockStored and BlockRemoved, so a floor failure
  (double-apply) would resurrect removed entries and a journal failure
  (loss) would drop stored ones; either diverges the comparison.
- **Warm-before-serve is structural:** a joining pod is absent from
  `serving_pods()` until `finish_join` — the router cannot route to it
  no matter what the index already knows.
- **Drained departure:** `leave` quarantines the pod's index entries
  through the fleethealth `remove_pod` path and the pod is unroutable
  from the moment draining starts.
"""

import random
import threading
from types import SimpleNamespace

import pytest

from llm_d_kv_cache_manager_tpu.cluster import (
    DRAINING,
    JOINING,
    LEFT,
    SERVING,
    WARMING,
    FleetMembership,
    MembershipConfig,
    PartitionTable,
    ReplicaBinding,
    ReplicaPartitioner,
    export_pod_view,
)
from llm_d_kv_cache_manager_tpu.fleethealth import (
    FleetHealthConfig,
    FleetHealthTracker,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareIndexConfig,
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
    ShardedIndex,
    ShardedIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    EventPool,
    EventPoolConfig,
    Message,
)

MODEL = "membership-model"
BLOCK_SIZE = 4
PODS = [f"pod-{i}" for i in range(6)]


# -- partition table ----------------------------------------------------------


class TestPartitionTable:
    def test_hash_default_matches_partitioner(self):
        table = PartitionTable(4)
        ref = ReplicaPartitioner(4)
        for pod in PODS + ["pod-3@dp2"]:
            assert table.replica_for(pod) == ref.replica_for(pod)

    def test_override_pause_and_clear(self):
        table = PartitionTable(3)
        home = table.replica_for("pod-0")
        table.set_owner("pod-0", (home + 1) % 3)
        assert table.replica_for("pod-0") == (home + 1) % 3
        # DP ranks follow the base pod through overrides too.
        assert table.replica_for("pod-0@dp1") == (home + 1) % 3
        table.set_owner("pod-0", None)  # paused mid-handoff
        assert table.replica_for("pod-0") is None
        table.clear_override("pod-0")
        assert table.replica_for("pod-0") == home

    def test_gate_tracks_live_ownership(self):
        table = PartitionTable(2)
        msg = SimpleNamespace(pod_identifier="pod-1")
        home = table.replica_for("pod-1")
        assert table.gate(home)(msg)
        assert not table.gate(1 - home)(msg)
        table.set_owner("pod-1", 1 - home)
        assert not table.gate(home)(msg)
        assert table.gate(1 - home)(msg)
        table.set_owner("pod-1", None)  # paused: NOBODY applies
        assert not table.gate(0)(msg)
        assert not table.gate(1)(msg)

    def test_topic_filters_follow_overrides(self):
        table = PartitionTable(2)
        home = table.replica_for("pod-2")
        assert "kv@pod-2@" in table.topic_filters(home, PODS)
        table.set_owner("pod-2", 1 - home)
        assert "kv@pod-2@" not in table.topic_filters(home, PODS)
        assert "kv@pod-2@" in table.topic_filters(1 - home, PODS)

    def test_invalid_owner_rejected(self):
        table = PartitionTable(2)
        with pytest.raises(ValueError):
            table.set_owner("pod-0", 2)


# -- membership lifecycle -----------------------------------------------------


def _chain(head, tokens, extra=()):
    return SimpleNamespace(
        head=head, prefix_tokens=list(tokens), extra=tuple(extra),
        prefix_hashes=[head], score=100.0, model_name=MODEL,
        observations=1,
    )


class _FakePopularity:
    def __init__(self, chains):
        self._chains = chains

    def hot_chains(self, threshold):
        return [c for c in self._chains if c.score >= threshold]


class TestLifecycle:
    def test_warm_before_serve_gate(self):
        warmed = []
        mem = FleetMembership(
            MembershipConfig(warm_top_k=2),
            popularity=_FakePopularity(
                [_chain(h, range(8)) for h in (1, 2, 3)]
            ),
            warm_submit=lambda pod, chain: warmed.append(
                (pod, chain.head)
            ) or True,
        )
        stats = mem.begin_join("pod-9")
        # Warming: top-K jobs submitted, pod NOT routable.
        assert stats["warm_jobs"] == 2
        assert warmed == [("pod-9", 1), ("pod-9", 2)]
        assert mem.phase_of("pod-9") == WARMING
        assert "pod-9" not in mem.serving_pods()
        mem.finish_join("pod-9")
        assert mem.phase_of("pod-9") == SERVING
        assert mem.serving_pods() == ["pod-9"]

    def test_join_without_warm_plane_still_gates(self):
        mem = FleetMembership(MembershipConfig(require_warm=True))
        mem.begin_join("pod-1")
        assert mem.phase_of("pod-1") == WARMING
        assert mem.serving_pods() == []
        mem.finish_join("pod-1")
        assert mem.serving_pods() == ["pod-1"]

    def test_double_join_rejected_but_rejoin_after_leave_ok(self):
        mem = FleetMembership()
        mem.join("pod-1")
        with pytest.raises(ValueError):
            mem.begin_join("pod-1")
        mem.leave("pod-1")
        assert mem.phase_of("pod-1") == LEFT
        mem.join("pod-1")  # departed identities may return
        assert mem.phase_of("pod-1") == SERVING

    def test_finish_join_requires_join_in_progress(self):
        mem = FleetMembership()
        with pytest.raises(ValueError):
            mem.finish_join("pod-7")

    def test_bootstrap_registers_serving(self):
        mem = FleetMembership()
        mem.bootstrap(PODS)
        assert mem.serving_pods() == sorted(PODS)

    def test_leave_quarantines_through_fleethealth(self):
        idx = InMemoryIndex(InMemoryIndexConfig(size=256, pod_cache_size=4))
        processor = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=BLOCK_SIZE)
        )
        pool = EventPool(
            EventPoolConfig(concurrency=1), idx, processor
        )
        pool.start(with_subscriber=False)
        try:
            pool.add_task(_store_message("pod-1", list(range(8)), 100, 0))
            pool.drain()
            tracker = FleetHealthTracker(
                FleetHealthConfig(), index=idx, clock=lambda: 0.0
            )
            mem = FleetMembership(fleet_health=tracker)
            mem.bootstrap(["pod-1"])
            out = mem.leave("pod-1")
            assert out["purged_entries"] > 0
            assert mem.phase_of("pod-1") == LEFT
            assert mem.serving_pods() == []
            # The quarantine really emptied the index of the pod.
            view = export_pod_view(idx, "pod-1")
            assert view.entries == []
        finally:
            pool.shutdown()

    def test_leave_requires_serving(self):
        mem = FleetMembership()
        with pytest.raises(ValueError):
            mem.leave("pod-1")

    def test_phase_vocabulary_is_fixed(self):
        # The metrics label comes from this set; a new phase must be a
        # deliberate, reviewed change (metrics hygiene depends on it).
        from llm_d_kv_cache_manager_tpu.cluster.membership import PHASES

        assert PHASES == (
            JOINING, WARMING, "reassigning", SERVING, DRAINING, LEFT
        )


# -- reassignment property (x4 backends) --------------------------------------


def _backend_factories(fake_redis_url=None):
    factories = {
        "in_memory": lambda: InMemoryIndex(
            InMemoryIndexConfig(size=4096, pod_cache_size=10)
        ),
        "sharded": lambda: ShardedIndex(
            ShardedIndexConfig(size=4096, num_shards=8)
        ),
        "cost_aware": lambda: CostAwareMemoryIndex(
            CostAwareIndexConfig(max_size_bytes="64MiB")
        ),
    }
    if fake_redis_url is not None:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
            RedisIndex,
            RedisIndexConfig,
        )

        factories["redis"] = lambda: RedisIndex(
            RedisIndexConfig(url=fake_redis_url)
        )
    return factories


@pytest.fixture
def fresh_redis_factory():
    """A factory of FRESH fake-redis servers: the reassigned run and the
    never-reassigned reference must not share a keyspace."""
    from tests.fake_redis import FakeRedisServer

    servers = []

    def make():
        server = FakeRedisServer()
        servers.append(server)
        return server

    yield make
    for server in servers:
        server.close()


def _store_message(pod, tokens, first_engine_hash, seq, parent=None):
    batch = EventBatch(
        ts=0.0,
        events=[BlockStored(
            block_hashes=list(range(
                first_engine_hash,
                first_engine_hash + len(tokens) // BLOCK_SIZE,
            )),
            parent_block_hash=parent,
            token_ids=list(tokens),
            block_size=BLOCK_SIZE,
        )],
    )
    return Message(
        topic=f"kv@{pod}@{MODEL}",
        payload=batch.to_msgpack(),
        seq=seq,
        pod_identifier=pod,
        model_name=MODEL,
    )


def _remove_message(pod, engine_hashes, seq):
    batch = EventBatch(
        ts=0.0,
        events=[BlockRemoved(block_hashes=list(engine_hashes))],
    )
    return Message(
        topic=f"kv@{pod}@{MODEL}",
        payload=batch.to_msgpack(),
        seq=seq,
        pod_identifier=pod,
        model_name=MODEL,
    )


def _entry_set(index, pod=None):
    """Order-free projection of an index's content: {(model, hash, pod,
    tier)}. Recency order across differently-partitioned digestion
    histories is not meaningful; entry content is."""
    out = set()
    for model_name, chunk_hash, pods in index.export_view().entries:
        for p, tier in pods:
            if pod is None or p.split("@")[0] == pod:
                out.add((model_name, chunk_hash, p, tier))
    return out


class _Harness:
    """Two partition-gated replicas + a journaling delivery seam."""

    def __init__(self, factory, n_replicas=2):
        self.table = PartitionTable(n_replicas)
        self.processor = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=BLOCK_SIZE)
        )
        self.indexes = [factory() for _ in range(n_replicas)]
        self.pools = []
        for rid in range(n_replicas):
            pool = EventPool(
                EventPoolConfig(concurrency=2),
                self.indexes[rid],
                self.processor,
                message_filter=self.table.gate(rid),
            )
            pool.start(with_subscriber=False)
            self.pools.append(pool)
        self.journal = []
        self.applied = {}
        self.membership = FleetMembership(
            table=self.table,
            replicas=[
                ReplicaBinding(rid, self.pools[rid], self.indexes[rid])
                for rid in range(n_replicas)
            ],
            watermark_fn=lambda pod: {
                k: v for k, v in self.applied.items() if k[0] == pod
            },
            journal_fn=lambda: list(self.journal),
        )

    def deliver(self, msg):
        self.journal.append(msg)
        self.applied[(msg.pod_identifier, msg.topic)] = msg.seq
        for pool in self.pools:
            pool.add_task(msg)

    def drain(self):
        for pool in self.pools:
            pool.drain()

    def shutdown(self):
        for pool in self.pools:
            pool.shutdown()


def _random_stream(rng, n_messages):
    """Interleaved BlockStored/BlockRemoved messages across PODS with
    per-pod monotonic seqs. Removals target earlier stores on the same
    pod — the poison for any double-apply (a replayed store would
    resurrect them)."""
    seqs = {pod: 0 for pod in PODS}
    stored = {pod: [] for pod in PODS}  # engine-hash chains per pod
    next_hash = 1000
    out = []
    for _ in range(n_messages):
        pod = rng.choice(PODS)
        seq = seqs[pod]
        seqs[pod] += 1
        if stored[pod] and rng.random() < 0.3:
            chain = rng.choice(stored[pod])
            out.append(_remove_message(pod, chain[-1:], seq))
            chain.pop()
            if not chain:
                stored[pod].remove(chain)
        else:
            n_blocks = rng.randint(1, 5)
            tokens = [
                rng.randrange(1, 30_000)
                for _ in range(BLOCK_SIZE * n_blocks)
            ]
            hashes = list(range(next_hash, next_hash + n_blocks))
            next_hash += n_blocks + 10
            out.append(_store_message(pod, tokens, hashes[0], seq))
            stored[pod].append(hashes)
    return out


@pytest.mark.parametrize(
    "backend", ["in_memory", "sharded", "cost_aware", "redis"]
)
def test_reassignment_bit_identical_across_backends(
    backend, fresh_redis_factory
):
    """THE satellite pin: a stream replayed across a live old→new owner
    handoff yields the same index content as a never-reassigned run."""
    def factory():
        if backend == "redis":
            return _backend_factories(fresh_redis_factory().url)["redis"]()
        return _backend_factories()[backend]()

    moved = "pod-2"

    rng = random.Random(1234)
    stream = _random_stream(rng, 120)
    cut = len(stream) // 2

    # Run A (reference): ownership of `moved` sits at its FINAL home from
    # the start; no handoff ever happens.
    ref = _Harness(factory)
    old_owner = ref.table.replica_for(moved)
    new_owner = (old_owner + 1) % 2
    ref.table.set_owner(moved, new_owner)
    for msg in stream:
        ref.deliver(msg)
    ref.drain()

    # Run B: hash-home ownership, handoff mid-stream.
    b = _Harness(factory)
    try:
        for msg in stream[:cut]:
            b.deliver(msg)
        b.drain()
        stats = b.membership.reassign_pod(moved, new_owner)
        assert stats["from"] == old_owner and stats["to"] == new_owner
        # The journal covered everything already applied: every replayed
        # message for the moved pod must hit its floor.
        assert stats["journal_replayed"] > 0
        assert stats["replay_skipped"] == stats["journal_replayed"]
        for msg in stream[cut:]:
            b.deliver(msg)
        b.drain()

        # The moved pod's entries live ONLY on the new owner, and match
        # the never-reassigned reference exactly.
        assert _entry_set(b.indexes[old_owner], moved) == set()
        assert _entry_set(b.indexes[new_owner], moved) == _entry_set(
            ref.indexes[new_owner], moved
        )
        # Everything else is untouched by the handoff.
        for rid in range(2):
            assert _entry_set(b.indexes[rid]) - _entry_set(
                b.indexes[rid], moved
            ) == _entry_set(ref.indexes[rid]) - _entry_set(
                ref.indexes[rid], moved
            )
        # Ownership table agrees with where the data is.
        assert b.table.replica_for(moved) == new_owner
    finally:
        b.shutdown()
        ref.shutdown()


def test_reassignment_pauses_scoring_ownership():
    """Mid-handoff the table answers None for the moved pod, so the
    scatter-gather merge (which keys on replica_for) trusts NO replica's
    answer — the no-signal window that makes stale scores impossible."""
    factories = _backend_factories()
    h = _Harness(factories["in_memory"])
    try:
        h.deliver(_store_message("pod-2", list(range(8)), 500, 0))
        h.drain()
        observed = []
        orig_set_owner = h.table.set_owner

        def spy(pod, rid):
            observed.append(rid)
            orig_set_owner(pod, rid)

        h.table.set_owner = spy
        h.membership.reassign_pod("pod-2", 1 - h.table.replica_for("pod-2"))
        # Phase 1 pauses (None) strictly before phase 2 commits.
        assert observed[0] is None
        assert observed[-1] is not None
    finally:
        h.shutdown()


def test_reassignment_counts_transitions():
    from llm_d_kv_cache_manager_tpu.metrics import collector as metrics

    metrics.register_metrics()
    factories = _backend_factories()
    h = _Harness(factories["in_memory"])
    try:
        before = metrics.counter_value(metrics.membership_transitions)
        h.deliver(_store_message("pod-1", list(range(8)), 700, 0))
        h.drain()
        h.membership.reassign_pod(
            "pod-1", 1 - h.table.replica_for("pod-1")
        )
        after = metrics.counter_value(metrics.membership_transitions)
        assert after > before
    finally:
        h.shutdown()


# -- warm-before-serve through the real transfer plane ------------------------


@pytest.mark.membership
def test_join_warms_through_data_plane_e2e():
    """E2E warm-before-serve: a joining pod's hot prefixes land through
    the REAL transfer plane (ready buffer / DCN peers via warm_chain)
    before the pod enters the serving set — never by burning serving-path
    compute on the donors."""
    import importlib.util
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "bench_mod_membership", repo / "bench.py"
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    alpha, gamma, delta, _src = bench._winning_regime_constants()
    sim = bench.FleetSim(
        "precise",
        pages_per_pod=512,
        host_tier=True,
        host_capacity=2048,
        alpha=alpha, gamma=gamma, delta=delta,
        membership={"warm_top_k": 2, "warm_hotness": 0.1},
    )
    try:
        rng = random.Random(9)
        conversations = {
            "g0-u0": " ".join(rng.choice(["alpha", "beta", "gamma", "delta"])
                              for _ in range(400)),
        }
        arrival = 0.0
        # Serve the same shared prefix a few times: the popularity
        # tracker learns a hot chain homed on some existing pod.
        for _ in range(4):
            arrival += 0.2
            prompt = conversations["g0-u0"] + " [user] question here"
            sim.serve(arrival, prompt)
        sim.now = arrival
        onboarded_before = sum(
            pod.tier_store.stats["onboards"] for pod in sim.pods
            if pod.tier_store is not None
        )
        joins = sim.scale_out(1)
        (join_stats,) = joins.values()
        assert join_stats["warm_jobs"] >= 1
        assert sim.warm_stats["blocks_landed"] > 0
        # The landed blocks moved through the data plane (peer DCN
        # onboards), not the serving path.
        onboarded_after = sum(
            pod.tier_store.stats["onboards"] for pod in sim.pods
            if pod.tier_store is not None
        )
        assert onboarded_after > onboarded_before
        assert sim.membership.serving_pods()[-1] == f"pod-{sim.n_pods - 1}"
    finally:
        sim.shutdown()


# -- concurrency smoke --------------------------------------------------------


def test_serving_pods_thread_safe_under_churn():
    mem = FleetMembership()
    mem.bootstrap(PODS)
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            try:
                pods = mem.serving_pods()
                assert all(isinstance(p, str) for p in pods)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(20):
            mem.join(f"extra-{i}")
            mem.leave(f"extra-{i}")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
    assert not errors

"""Structural validation of the vllm-tpu Helm chart.

`helm` isn't in the CI image, so instead of `helm template` this asserts
the properties the chart exists to guarantee (anchor:
/root/reference/vllm-setup-helm/):

- the fleet invariants (hashSeed, blockSize) are single-sourced at the
  values root and only reachable through the validating helpers,
- every workload container ships readiness + liveness probes,
- both the engine and the manager get PYTHONHASHSEED from the same helper,
- TPU scheduling (nodeSelector + toleration) is present on the fleet,
- template delimiters are balanced and values.yaml/Chart.yaml parse.
"""

import pathlib
import re

import yaml

CHART = pathlib.Path(__file__).resolve().parent.parent / "deploy" / "vllm-tpu"
TEMPLATES = sorted((CHART / "templates").glob("*.yaml"))


def _read(path):
    return path.read_text()


class TestChartStructure:
    def test_chart_and_values_parse(self):
        chart = yaml.safe_load(_read(CHART / "Chart.yaml"))
        assert chart["apiVersion"] == "v2" and chart["name"]
        values = yaml.safe_load(_read(CHART / "values.yaml"))
        assert values["hashSeed"] and values["blockSize"] in (16, 32, 64, 128)

    def test_templates_exist(self):
        names = {p.name for p in TEMPLATES}
        assert {
            "vllm-deployment.yaml", "vllm-service.yaml",
            "manager-deployment.yaml", "manager-service.yaml", "valkey.yaml",
        } <= names

    def test_balanced_template_delimiters(self):
        for path in TEMPLATES + [CHART / "templates" / "_helpers.tpl"]:
            text = _read(path)
            assert text.count("{{") == text.count("}}"), path.name


class TestFleetInvariants:
    def test_invariants_single_sourced_in_values(self):
        values = yaml.safe_load(_read(CHART / "values.yaml"))
        for section in ("engine", "manager", "fleet", "model", "udsTokenizer"):
            sub = values.get(section) or {}
            assert "hashSeed" not in sub and "blockSize" not in sub, (
                f"{section} must not shadow the root invariants"
            )

    def test_templates_use_validating_helpers_only(self):
        # Direct .Values.hashSeed / .Values.blockSize access is only allowed
        # inside _helpers.tpl (where the validation lives).
        for path in TEMPLATES:
            text = _read(path)
            assert ".Values.hashSeed" not in text, path.name
            assert ".Values.blockSize" not in text, path.name
            if "PYTHONHASHSEED" in text:
                assert 'include "kvcache.hashSeed"' in text, path.name

    def test_helpers_validate_seed_and_block_size(self):
        helpers = _read(CHART / "templates" / "_helpers.tpl")
        assert "required" in helpers and "PYTHONHASHSEED" in helpers
        assert "fail" in helpers  # blockSize + shared-index validation
        assert "manager.replicas > 1 requires a shared index" in helpers

    def test_engine_and_manager_share_the_seed(self):
        for name in ("vllm-deployment.yaml", "manager-deployment.yaml"):
            text = _read(CHART / "templates" / name)
            assert "PYTHONHASHSEED" in text, name
            assert 'include "kvcache.hashSeed"' in text, name

    def test_engine_and_manager_share_block_size(self):
        assert "--block-size={{ include \"kvcache.blockSize\" . }}" in _read(
            CHART / "templates" / "vllm-deployment.yaml"
        )
        assert 'include "kvcache.blockSize"' in _read(
            CHART / "templates" / "manager-deployment.yaml"
        )


class TestScheduling:
    def test_tpu_node_selection_and_toleration(self):
        text = _read(CHART / "templates" / "vllm-deployment.yaml")
        assert "cloud.google.com/gke-tpu-accelerator" in text
        assert "cloud.google.com/gke-tpu-topology" in text
        assert "google.com/tpu" in text  # toleration + resource limit

    def test_every_deployment_container_has_probes(self):
        for name in ("vllm-deployment.yaml", "manager-deployment.yaml",
                     "valkey.yaml"):
            text = _read(CHART / "templates" / name)
            n_containers = len(re.findall(r"^\s+- name: \S+\n\s+image:", text,
                                          re.MULTILINE))
            assert n_containers >= 1, name
            assert len(re.findall(r"readinessProbe:", text)) >= n_containers, name
            assert len(re.findall(r"livenessProbe:", text)) >= n_containers, name

    def test_manager_env_wiring_matches_service_env_contract(self):
        # The chart must only set env vars http_service/server actually read.
        from llm_d_kv_cache_manager_tpu.api.http_service import config_from_env

        known = {
            "ZMQ_ENDPOINT", "ZMQ_TOPIC", "POOL_CONCURRENCY", "PYTHONHASHSEED",
            "BLOCK_SIZE", "BLOCK_HASH_ALGO", "HTTP_PORT", "HF_TOKEN",
            "ENABLE_HF_TOKENIZER", "ENABLE_METRICS", "INDEX_URL",
            "UDS_SOCKET",
        }
        # config_from_env documents the contract; catch drift both ways.
        import inspect

        src = inspect.getsource(config_from_env)
        for var in known:
            if var != "PYTHONHASHSEED":
                assert var in src or var == "UDS_SOCKET", var
        text = _read(CHART / "templates" / "manager-deployment.yaml")
        manager_env = re.findall(r"- name: ([A-Z_]+)\n", text)
        assert set(manager_env) - {"ALLOW_REMOTE_DOWNLOAD"} <= known

"""Flagship model tests: paged serving parity with the dense path, and the
sharded training step on a virtual dp x tp mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.models.llama import (
    LlamaConfig,
    decode_step,
    forward_dense,
    init_params,
    loss_fn,
    make_kv_pages,
    prefill,
    train_step,
)

# Model-math tests compile real models (VERDICT r5 weak #6): excluded
# from the tier-1 `-m 'not slow'` gate to keep its wall time bounded.
pytestmark = pytest.mark.slow

CFG = LlamaConfig(
    vocab_size=256, d_model=64, n_layers=2, n_q_heads=4, n_kv_heads=2,
    head_dim=32, d_ff=128, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(7))


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.PRNGKey(8), (1, 24), 0, CFG.vocab_size)


class TestPagedServingParity:
    def test_prefill_matches_dense(self, params, tokens):
        dense = forward_dense(CFG, params, tokens)
        kp, vp = make_kv_pages(CFG, n_pages=8, page_size=8)
        bt = jnp.arange(8, dtype=jnp.int32)
        _, _, logits = prefill(CFG, params, kp, vp, tokens[0], bt, 0)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(dense[0, -1]), atol=1e-4
        )

    def test_chunked_prefill_and_decode_match_dense(self, params, tokens):
        dense = forward_dense(CFG, params, tokens)
        kp, vp = make_kv_pages(CFG, n_pages=8, page_size=8)
        bt = jnp.arange(8, dtype=jnp.int32)
        # Prefill in two chunks (second continues a cached prefix)...
        kp, vp, _ = prefill(CFG, params, kp, vp, tokens[0, :10], bt, 0)
        kp, vp, logits = prefill(CFG, params, kp, vp, tokens[0, 10:16], bt, 10)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(dense[0, 15]), atol=1e-4
        )
        # ...then decode the rest token by token.
        for i in range(16, 24):
            kp, vp, logits = decode_step(
                CFG, params, kp, vp, tokens[:, i], bt[None], jnp.array([i])
            )
            np.testing.assert_allclose(
                np.asarray(logits[0]), np.asarray(dense[0, i]), atol=1e-4
            )

    def test_batched_decode(self, params):
        # Two sequences with different lengths and disjoint block tables.
        toks_a = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, CFG.vocab_size)
        toks_b = jax.random.randint(jax.random.PRNGKey(2), (1, 20), 0, CFG.vocab_size)
        dense_a = forward_dense(CFG, params, toks_a)
        dense_b = forward_dense(CFG, params, toks_b)

        kp, vp = make_kv_pages(CFG, n_pages=8, page_size=8)
        bt = jnp.array([[0, 1, 2], [3, 4, 5]], jnp.int32)
        kp, vp, _ = prefill(CFG, params, kp, vp, toks_a[0, :11], bt[0], 0)
        kp, vp, _ = prefill(CFG, params, kp, vp, toks_b[0, :19], bt[1], 0)
        last = jnp.array([toks_a[0, 11], toks_b[0, 19]])
        kp, vp, logits = decode_step(
            CFG, params, kp, vp, last, bt, jnp.array([11, 19])
        )
        np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(dense_a[0, 11]), atol=1e-4)
        np.testing.assert_allclose(np.asarray(logits[1]), np.asarray(dense_b[0, 19]), atol=1e-4)


class TestTraining:
    def test_loss_decreases(self, params):
        batch = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, CFG.vocab_size)
        step = jax.jit(functools.partial(train_step, CFG))
        p = params
        first = None
        for _ in range(5):
            p, loss = step(p, batch)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_sharded_train_step_dp_tp(self):
        from llm_d_kv_cache_manager_tpu.parallel.mesh import (
            batch_sharding,
            make_mesh,
            shard_params,
        )

        cfg = LlamaConfig(
            vocab_size=256, d_model=64, n_layers=2, n_q_heads=8, n_kv_heads=4,
            head_dim=32, d_ff=128, dtype=jnp.float32,
        )
        mesh = make_mesh(dp=2, tp=4)
        params = shard_params(init_params(cfg, jax.random.PRNGKey(9)), mesh)
        batch = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(10), (4, 32), 0, cfg.vocab_size),
            batch_sharding(mesh),
        )
        step = jax.jit(functools.partial(train_step, cfg))
        new_params, loss = step(params, batch)
        assert float(loss) > 0
        # Sharded result matches the unsharded computation.
        host_params = jax.tree_util.tree_map(np.asarray, params)
        ref_loss = loss_fn(cfg, host_params, np.asarray(batch))
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)

"""KVTM/KVTC wire-framing fuzz: hostile bytes never crash the data plane.

The event-plane mirror is tests/test_event_wire_fuzz.py; this is the same
stance for the TRANSFER wire. Two directions:

- **Client vs hostile server**: a fake "server" (raw Python socket)
  answers fetches with truncated frames, wrong magics, random garbage,
  hostile length fields, and wrong checksums. The client must come back
  with None/error statuses within its timeout budget — never crash, never
  hang past the bound, never allocate from a wire-supplied length (the
  C client only ever writes into the caller's buffer and drains the rest
  through a fixed scratch).
- **Server vs hostile client**: random garbage frames against the real
  C++ server must leave it serving (a good fetch works afterwards).

Plus the end-to-end integrity leg the fuzz exists to protect: a stored
block corrupted in server RAM (kvt_server_corrupt — checksum NOT updated)
must come back as a detected miss on the v2 wire while the v1 wire
delivers the wrong bytes (the failure mode v2 kills).
"""

import os
import random
import socket
import struct
import threading
import time

import pytest

from llm_d_kv_cache_manager_tpu.kv_connectors.connector import (
    BlockTransferServer,
    TransferClient,
    TransferClientConfig,
)

pytestmark = [pytest.mark.transfer, pytest.mark.chaos]

MAGIC_SINGLE = 0x4B565442  # 'KVTB'
MAGIC_MULTI = 0x4B56544D   # 'KVTM'
MAGIC_MULTI2 = 0x4B565443  # 'KVTC'


class _HostileServer:
    """One-shot scripted TCP endpoint: accepts connections and answers
    every request with the scripted bytes (ignoring what was asked)."""

    def __init__(self, reply: bytes, close_after: bool = True):
        self.reply = reply
        self.close_after = close_after
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop:
            try:
                conn, _addr = self.sock.accept()
            except OSError:
                return
            try:
                conn.settimeout(2.0)
                try:
                    conn.recv(65536)  # swallow the request
                except OSError:
                    pass
                if self.reply:
                    conn.sendall(self.reply)
                if self.close_after:
                    conn.close()
                else:
                    time.sleep(2.0)
                    conn.close()
            except OSError:
                pass

    def close(self):
        self._stop = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def _client(timeout_ms=400, verify=True):
    return TransferClient(TransferClientConfig(
        connect_timeout_ms=timeout_ms,
        io_timeout_ms=timeout_ms,
        retries=0,
        verify_integrity=verify,
        breaker_failure_threshold=0,  # fuzz every frame, no skipping
    ))


def _v2_frame(blocks):
    """Well-formed v2 reply for `blocks`: list of (status, payload,
    checksum_override|None)."""
    from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import fnv64a

    out = struct.pack("<I", MAGIC_MULTI2)
    for status, payload, checksum in blocks:
        if checksum is None:
            checksum = fnv64a(payload)
        out += struct.pack("<BQQ", status, len(payload), checksum)
        out += payload
    return out


HOSTILE_REPLIES = [
    b"",  # connection closed with no reply
    b"\x00",  # truncated magic
    struct.pack("<I", 0xDEADBEEF),  # wrong magic
    struct.pack("<I", MAGIC_MULTI2),  # magic then EOF
    struct.pack("<I", MAGIC_MULTI2) + b"\x00",  # truncated header
    # status ok, huge length field, no payload: the drain must hit the
    # timeout/EOF bound, never allocate 2^60 bytes.
    struct.pack("<IBQQ", MAGIC_MULTI2, 0, 1 << 60, 0),
    # status ok, plausible length, truncated payload.
    struct.pack("<IBQQ", MAGIC_MULTI2, 0, 4096, 0) + b"xx",
    # valid frame with a WRONG checksum (detected corrupt, not an error).
    _v2_frame([(0, b"payload-bytes", 0x1234)]),
    # v1 magic answered to a v2 request (protocol confusion).
    struct.pack("<IBQ", MAGIC_MULTI, 0, 0),
]


class TestClientAgainstHostileServer:
    def test_hostile_replies_return_none_within_bound_never_crash(self):
        rng = random.Random(1337)
        for i, reply in enumerate(HOSTILE_REPLIES):
            server = _HostileServer(reply)
            client = _client()
            try:
                t0 = time.monotonic()
                out = client.fetch_many("127.0.0.1", server.port, [1, 2], 4096)
                elapsed = time.monotonic() - t0
                assert out == [None, None], f"reply #{i}"
                # Bounded: io timeout 0.4s + slack; never a hang.
                assert elapsed < 3.0, f"reply #{i} took {elapsed:.1f}s"
            finally:
                client.close()
                server.close()
        # Seeded random garbage frames.
        for _ in range(12):
            blob = bytes(rng.randrange(256) for _ in range(rng.randrange(64)))
            server = _HostileServer(blob)
            client = _client()
            try:
                assert client.fetch_many(
                    "127.0.0.1", server.port, [9], 4096
                ) == [None]
            finally:
                client.close()
                server.close()

    def test_wrong_checksum_is_corrupt_not_transport_error(self):
        server = _HostileServer(
            _v2_frame([(0, b"wrong-bytes", 0xBAD)]), close_after=False
        )
        client = _client()
        try:
            out = client.fetch_many("127.0.0.1", server.port, [5], 4096)
            assert out == [None]
            assert client.stats["corrupt_blocks"] == 1
            assert client.stats["failures"] == 0  # the frame itself was fine
        finally:
            client.close()
            server.close()

    def test_valid_v2_frame_roundtrips_through_hostile_rig(self):
        """Control: the rig itself can serve a well-formed reply."""
        server = _HostileServer(
            _v2_frame([(0, b"good-bytes", None)]), close_after=False
        )
        client = _client()
        try:
            out = client.fetch_many("127.0.0.1", server.port, [5], 4096)
            assert out == [b"good-bytes"]
        finally:
            client.close()
            server.close()

    def test_stalled_server_fails_within_timeout_not_hang(self):
        server = _HostileServer(b"", close_after=False)  # reads, says nothing
        client = _client(timeout_ms=300)
        try:
            t0 = time.monotonic()
            assert client.fetch_many(
                "127.0.0.1", server.port, [1], 4096
            ) == [None]
            assert time.monotonic() - t0 < 2.5
        finally:
            client.close()
            server.close()


class TestServerAgainstHostileClient:
    def test_garbage_frames_leave_server_serving(self):
        rng = random.Random(99)
        server = BlockTransferServer()
        payload = os.urandom(2048)
        server.put(42, payload)
        try:
            for _ in range(25):
                with socket.create_connection(
                    ("127.0.0.1", server.port), timeout=2.0
                ) as conn:
                    mode = rng.randrange(4)
                    if mode == 0:  # pure garbage
                        conn.sendall(bytes(
                            rng.randrange(256)
                            for _ in range(rng.randrange(1, 64))
                        ))
                    elif mode == 1:  # v2 magic + hostile count
                        conn.sendall(struct.pack(
                            "<II", MAGIC_MULTI2, rng.choice(
                                [0, 1 << 31, 0xFFFFFFFF]
                            )
                        ))
                    elif mode == 2:  # truncated v2 request
                        conn.sendall(struct.pack("<II", MAGIC_MULTI2, 4))
                    else:  # truncated single-block request
                        conn.sendall(struct.pack("<I", MAGIC_SINGLE) + b"\x01")
            # The server survived the flood and still serves good requests.
            client = _client()
            try:
                assert client.fetch_many(
                    "127.0.0.1", server.port, [42], 4096
                ) == [payload]
            finally:
                client.close()
        finally:
            server.close()


class TestEndToEndIntegrity:
    def test_ram_corruption_detected_on_v2_delivered_on_v1(self):
        server = BlockTransferServer()
        data = os.urandom(4096)
        server.put(7, data)
        v2 = _client()
        v1 = _client(verify=False)
        try:
            # Healthy: both wires byte-identical.
            assert v2.fetch_many("127.0.0.1", server.port, [7], 8192) == [data]
            assert v1.fetch_many("127.0.0.1", server.port, [7], 8192) == [data]
            # Flip a byte in server RAM — checksum NOT re-blessed.
            assert server.corrupt(7)
            got_v2 = v2.fetch_many("127.0.0.1", server.port, [7], 8192)
            got_v1 = v1.fetch_many("127.0.0.1", server.port, [7], 8192)
            assert got_v2 == [None]  # detected: degraded to a miss
            assert v2.stats["corrupt_blocks"] == 1
            assert got_v1[0] is not None and got_v1[0] != data  # silently wrong
        finally:
            v2.close()
            v1.close()
            server.close()

    def test_mixed_statuses_with_corruption_keep_alignment(self):
        server = BlockTransferServer()
        blocks = {h: os.urandom(256 + h) for h in (1, 2, 3)}
        for h, payload in blocks.items():
            server.put(h, payload)
        server.put(4, b"")  # present-but-empty (cannot corrupt)
        assert server.corrupt(2)
        assert not server.corrupt(4)  # empty: nothing to flip
        assert not server.corrupt(99)  # absent
        client = _client()
        try:
            out = client.fetch_many(
                "127.0.0.1", server.port, [1, 2, 99, 4, 3], 4096
            )
            assert out[0] == blocks[1]
            assert out[1] is None       # corrupted: detected
            assert out[2] is None       # missing
            assert out[3] == b""        # empty is NOT missing
            assert out[4] == blocks[3]  # later blocks unaffected
        finally:
            client.close()
            server.close()

    def test_v1_and_v2_wire_byte_identical_on_healthy_blocks(self):
        server = BlockTransferServer()
        data = {h: os.urandom(512 + h) for h in range(1, 9)}
        for h, payload in data.items():
            server.put(h, payload)
        hashes = [3, 1, 99, 5, 8, 2, 77, 4]
        v2 = _client()
        v1 = _client(verify=False)
        try:
            assert v2.fetch_many("127.0.0.1", server.port, hashes, 4096) == \
                v1.fetch_many("127.0.0.1", server.port, hashes, 4096)
        finally:
            v2.close()
            v1.close()
            server.close()

"""KVEvents schema + sharded pool tests.

Mirrors the reference's event decode/digest behavior
(/root/reference/pkg/kvcache/kvevents/pool.go:177-338) including hash
coercion (uint64 / int64 / bytes-tail-8, pool.go:343-367), parent-chain
continuation via get_request_key, and poison-pill dropping.
"""

import time

import msgpack
import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    EventBatch,
    hash_as_uint64,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    EventPool,
    EventPoolConfig,
    Message,
)


class TestHashCoercion:
    def test_int_passthrough(self):
        assert hash_as_uint64(42) == 42

    def test_negative_int64_wraps_to_uint64(self):
        assert hash_as_uint64(-1) == 0xFFFFFFFFFFFFFFFF

    def test_bytes_tail_8_big_endian(self):
        raw = bytes(range(1, 13))  # 12 bytes: take last 8
        assert hash_as_uint64(raw) == int.from_bytes(raw[-8:], "big")

    def test_short_bytes_left_padded(self):
        assert hash_as_uint64(b"\x01\x02") == 0x0102

    def test_empty_bytes_raises(self):
        with pytest.raises(ValueError):
            hash_as_uint64(b"")

    def test_unsupported_type_raises(self):
        with pytest.raises(TypeError):
            hash_as_uint64("nope")


class TestEventBatchWire:
    def test_roundtrip_block_stored(self):
        batch = EventBatch(
            ts=123.5,
            events=[
                BlockStored(
                    block_hashes=[1, 2],
                    parent_block_hash=None,
                    token_ids=[10, 11, 12, 13],
                    block_size=4,
                    medium="hbm",
                )
            ],
        )
        decoded = EventBatch.from_msgpack(batch.to_msgpack())
        assert decoded.ts == 123.5
        ev = decoded.events[0]
        assert isinstance(ev, BlockStored)
        assert ev.block_hashes == [1, 2]
        assert ev.token_ids == [10, 11, 12, 13]
        assert ev.medium == "hbm"

    def test_roundtrip_removed_and_cleared(self):
        batch = EventBatch(
            ts=1.0,
            events=[BlockRemoved(block_hashes=[7]), AllBlocksCleared()],
            data_parallel_rank=3,
        )
        decoded = EventBatch.from_msgpack(batch.to_msgpack())
        assert isinstance(decoded.events[0], BlockRemoved)
        assert isinstance(decoded.events[1], AllBlocksCleared)
        assert decoded.data_parallel_rank == 3

    def test_wire_format_is_arrays(self):
        # vLLM compatibility: everything is msgpack arrays, not maps.
        batch = EventBatch(ts=2.0, events=[BlockStored([5], None, [1], 1)])
        raw = msgpack.unpackb(batch.to_msgpack(), raw=False)
        assert raw[0] == 2.0
        assert raw[1][0][0] == "BlockStored"

    def test_unknown_tag_skipped(self):
        raw = msgpack.packb([1.0, [["FutureEvent", 1, 2], ["AllBlocksCleared"]]])
        decoded = EventBatch.from_msgpack(raw)
        assert len(decoded.events) == 1

    def test_bytes_hashes_survive_roundtrip(self):
        h = (123456789).to_bytes(32, "big")  # sha256-style 32-byte hash
        batch = EventBatch(ts=0.0, events=[BlockStored([h], h, [1, 2], 2)])
        decoded = EventBatch.from_msgpack(batch.to_msgpack())
        assert decoded.events[0].block_hashes[0] == h


def _make_pool(block_size=4):
    index = InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=10))
    processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size=block_size))
    pool = EventPool(EventPoolConfig(concurrency=2), index, processor)
    pool.start(with_subscriber=False)
    return pool, index, processor


def _msg(batch: EventBatch, pod="pod-1", model="m") -> Message:
    return Message(
        topic=f"kv@{pod}@{model}",
        payload=batch.to_msgpack(),
        seq=0,
        pod_identifier=pod,
        model_name=model,
    )


class TestEventPool:
    def test_block_stored_populates_index(self):
        pool, index, processor = _make_pool()
        try:
            tokens = [1, 2, 3, 4, 5, 6, 7, 8]
            request_keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            batch = EventBatch(
                ts=0.0,
                events=[BlockStored([100, 200], None, tokens, 4)],
            )
            pool.add_task(_msg(batch))
            pool.drain()
            got = index.lookup(request_keys, set())
            assert got[request_keys[0]] == [PodEntry("pod-1", "hbm")]
            assert got[request_keys[1]] == [PodEntry("pod-1", "hbm")]
            # Engine keys resolve to request keys.
            assert index.get_request_key(Key("m", 100)) == request_keys[0]
            assert index.get_request_key(Key("m", 200)) == request_keys[1]
        finally:
            pool.shutdown()

    def test_dp_ranks_of_one_pod_do_not_alias(self):
        # VERDICT r1 #9: a DP>1 engine runs one KV cache per rank; rank r's
        # events index under "pod@dpR" so the scorer never credits the pod
        # for blocks only one rank holds. (The reference decodes
        # DataParallelRank and drops it, events.go:42.)
        pool, index, processor = _make_pool()
        try:
            tokens_r0 = [1, 2, 3, 4]
            tokens_r1 = [5, 6, 7, 8]
            pool.add_task(_msg(EventBatch(
                ts=0.0, events=[BlockStored([100], None, tokens_r0, 4)],
                data_parallel_rank=0,
            )))
            pool.add_task(_msg(EventBatch(
                ts=0.0, events=[BlockStored([200], None, tokens_r1, 4)],
                data_parallel_rank=1,
            )))
            pool.drain()
            keys_r0 = processor.tokens_to_kv_block_keys(None, tokens_r0, "m")
            keys_r1 = processor.tokens_to_kv_block_keys(None, tokens_r1, "m")
            assert index.lookup(keys_r0, set())[keys_r0[0]] == [
                PodEntry("pod-1@dp0", "hbm")
            ]
            assert index.lookup(keys_r1, set())[keys_r1[0]] == [
                PodEntry("pod-1@dp1", "hbm")
            ]
        finally:
            pool.shutdown()

    def test_ranked_identity_matches_bare_pod_filter(self):
        # Routers filter by the bare pod names they discover; ranked
        # entries must still match (and come back with their rank so the
        # router can target the owning rank).
        pool, index, processor = _make_pool()
        try:
            tokens = [1, 2, 3, 4]
            pool.add_task(_msg(EventBatch(
                ts=0.0, events=[BlockStored([100], None, tokens, 4)],
                data_parallel_rank=2,
            )))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            got = index.lookup(keys, {"pod-1"})  # bare name filter
            assert got[keys[0]] == [PodEntry("pod-1@dp2", "hbm")]
            assert index.lookup(keys, {"pod-other"}) == {}
        finally:
            pool.shutdown()

    def test_invalid_dp_rank_falls_back_to_bare_pod_identity(self):
        pool, index, processor = _make_pool()
        try:
            tokens = [1, 2, 3, 4]
            pool.add_task(_msg(EventBatch(
                ts=0.0, events=[BlockStored([100], None, tokens, 4)],
                data_parallel_rank="three",  # wire garbage
            )))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            assert index.lookup(keys, set())[keys[0]] == [PodEntry("pod-1", "hbm")]
        finally:
            pool.shutdown()

    def test_medium_overrides_tier(self):
        pool, index, processor = _make_pool()
        try:
            tokens = [1, 2, 3, 4]
            batch = EventBatch(
                ts=0.0, events=[BlockStored([100], None, tokens, 4, medium="HOST")]
            )
            pool.add_task(_msg(batch))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            got = index.lookup(keys, set())
            assert got[keys[0]] == [PodEntry("pod-1", "host")]  # lowercased
        finally:
            pool.shutdown()

    def test_parent_chain_continuation(self):
        pool, index, processor = _make_pool()
        try:
            t1, t2 = [1, 2, 3, 4], [5, 6, 7, 8]
            pool.add_task(_msg(EventBatch(0.0, [BlockStored([100], None, t1, 4)])))
            pool.drain()
            # Second event continues from engine-parent 100.
            pool.add_task(_msg(EventBatch(1.0, [BlockStored([200], 100, t2, 4)])))
            pool.drain()
            full_keys = processor.tokens_to_kv_block_keys(None, t1 + t2, "m")
            got = index.lookup(full_keys, set())
            assert set(got) == set(full_keys)  # chained request keys match
        finally:
            pool.shutdown()

    def test_unknown_parent_starts_fresh_chain(self):
        pool, index, processor = _make_pool()
        try:
            tokens = [5, 6, 7, 8]
            pool.add_task(_msg(EventBatch(0.0, [BlockStored([200], 999, tokens, 4)])))
            pool.drain()
            # Parent unknown → request key computed from root.
            keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            assert keys[0] in index.lookup(keys, set())
        finally:
            pool.shutdown()

    def test_block_removed_evicts(self):
        pool, index, processor = _make_pool()
        try:
            tokens = [1, 2, 3, 4]
            pool.add_task(_msg(EventBatch(0.0, [BlockStored([100], None, tokens, 4)])))
            pool.drain()
            pool.add_task(_msg(EventBatch(1.0, [BlockRemoved([100])])))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            assert index.lookup(keys, set()) == {}
        finally:
            pool.shutdown()

    def test_poison_pill_dropped(self):
        pool, index, _ = _make_pool()
        try:
            pool.add_task(
                Message(
                    topic="kv@pod-1@m",
                    payload=b"\xc1garbage",
                    seq=0,
                    pod_identifier="pod-1",
                    model_name="m",
                )
            )
            pool.drain()  # must not hang or crash the worker
            # Pool still functional afterwards.
            tokens = [1, 2, 3, 4]
            pool.add_task(_msg(EventBatch(0.0, [BlockStored([1], None, tokens, 4)])))
            pool.drain()
            keys = pool.token_processor.tokens_to_kv_block_keys(None, tokens, "m")
            assert keys[0] in index.lookup(keys, set())
        finally:
            pool.shutdown()

    def test_per_pod_ordering_same_shard(self):
        pool, index, processor = _make_pool()
        try:
            tokens = [1, 2, 3, 4]
            # Store then remove, many times: final state must be "removed".
            for _ in range(20):
                pool.add_task(_msg(EventBatch(0.0, [BlockStored([100], None, tokens, 4)])))
                pool.add_task(_msg(EventBatch(1.0, [BlockRemoved([100])])))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            assert index.lookup(keys, set()) == {}
        finally:
            pool.shutdown()

    def test_message_filter_gates_ingest(self):
        # The cluster partition gate (cluster/partition.py) plugs in here:
        # a rejected pod's messages are discarded before sharding.
        index = InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=10))
        processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = EventPool(
            EventPoolConfig(concurrency=2), index, processor,
            message_filter=lambda m: m.pod_identifier == "pod-1",
        )
        pool.start(with_subscriber=False)
        try:
            tokens = [1, 2, 3, 4]
            pool.add_task(_msg(EventBatch(0.0, [BlockStored([1], None, tokens, 4)]), pod="pod-1"))
            pool.add_task(_msg(EventBatch(0.0, [BlockStored([2], None, tokens, 4)]), pod="pod-2"))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            got = index.lookup(keys, set())
            assert got[keys[0]] == [PodEntry("pod-1", "hbm")]
            assert pool.filtered_events == 1
        finally:
            pool.shutdown()


class TestSubscriberFilters:
    """Partitioned subscribe + live resubscribe (zmq_subscriber.py).

    Real PUB/SUB over ipc endpoints, like the e2e suite: per-topic prefix
    filters must actually gate delivery on the wire, and `resubscribe()`
    must swap the set on the live socket — no rebind, no backoff reset.
    """

    def _pool_with_subscriber(self, tmp_path, topic_filters):
        import uuid

        endpoint = f"ipc://{tmp_path}/sub-{uuid.uuid4().hex[:8]}.sock"
        index = InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=10))
        processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        pool = EventPool(
            EventPoolConfig(
                zmq_endpoint=endpoint, concurrency=1,
                topic_filters=list(topic_filters),
            ),
            index, processor,
        )
        pool.start(with_subscriber=True)
        return pool, index, processor, endpoint

    @staticmethod
    def _wait_until(predicate, timeout=10.0, interval=0.05):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if predicate():
                return True
            time.sleep(interval)
        return False

    @staticmethod
    def _publish(endpoint, pod, tokens, engine_hash):
        from llm_d_kv_cache_manager_tpu.kvevents.publisher import (
            Publisher,
            make_topic,
        )

        publisher = Publisher(endpoint, make_topic(pod, "m"))
        time.sleep(0.3)  # slow-joiner
        publisher.publish(EventBatch(
            ts=0.0, events=[BlockStored([engine_hash], None, tokens, 4)]
        ))
        return publisher

    def test_topic_filters_gate_on_the_wire(self, tmp_path):
        pool, index, processor, endpoint = self._pool_with_subscriber(
            tmp_path, ["kv@pod-a@"]
        )
        try:
            t_a, t_b = [1, 2, 3, 4], [5, 6, 7, 8]
            pub_a = self._publish(endpoint, "pod-a", t_a, 11)
            pub_b = self._publish(endpoint, "pod-b", t_b, 22)
            keys_a = processor.tokens_to_kv_block_keys(None, t_a, "m")
            keys_b = processor.tokens_to_kv_block_keys(None, t_b, "m")
            assert self._wait_until(
                lambda: keys_a[0] in index.lookup(keys_a, set())
            )
            # pod-b's topic never matched a subscribed prefix: the frame
            # was dropped by ZMQ itself, not by this process.
            time.sleep(0.3)
            pool.drain()
            assert index.lookup(keys_b, set()) == {}
            pub_a.close()
            pub_b.close()
        finally:
            pool.shutdown()

    def test_resubscribe_swaps_partition_without_restart(self, tmp_path):
        pool, index, processor, endpoint = self._pool_with_subscriber(
            tmp_path, ["kv@pod-a@"]
        )
        try:
            sub = pool._subscriber  # noqa: SLF001
            failures_before = sub.consecutive_failures
            # Reassignment: this replica now owns pod-b instead of pod-a.
            sub.resubscribe(["kv@pod-b@"])
            assert self._wait_until(lambda: sub.resubscriptions == 1)
            assert sub.topic_filters == ["kv@pod-b@"]
            t_b = [9, 10, 11, 12]
            pub_b = self._publish(endpoint, "pod-b", t_b, 33)
            keys_b = processor.tokens_to_kv_block_keys(None, t_b, "m")
            assert self._wait_until(
                lambda: keys_b[0] in index.lookup(keys_b, set())
            )
            t_a = [13, 14, 15, 16]
            pub_a = self._publish(endpoint, "pod-a", t_a, 44)
            time.sleep(0.3)
            pool.drain()
            keys_a = processor.tokens_to_kv_block_keys(None, t_a, "m")
            assert index.lookup(keys_a, set()) == {}
            # The swap happened on the live socket: no reconnect cycle, so
            # the capped-backoff bookkeeping never moved.
            assert sub.consecutive_failures == failures_before == 0
            assert sub.is_alive()
            pub_a.close()
            pub_b.close()
        finally:
            pool.shutdown()

    def test_resubscribe_before_start_sets_initial_filters(self):
        from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
            ZMQSubscriber,
        )

        sub = ZMQSubscriber(None, "ipc:///tmp/unused.sock", "kv@")
        sub.resubscribe(["kv@pod-x@", "kv@pod-y@"])
        assert sub.topic_filters == ["kv@pod-x@", "kv@pod-y@"]
        assert sub.topic_filter == "kv@pod-x@"

    def test_empty_filter_list_degenerates_to_subscribe_all(self):
        from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
            _normalize_filters,
        )

        assert _normalize_filters([]) == [""]
        assert _normalize_filters("kv@") == ["kv@"]
        assert _normalize_filters(["a", "b"]) == ["a", "b"]

    def test_backoff_schedule_preserved(self):
        # The capped-exponential reconnect schedule predates the filter
        # work and must survive it (PR-3 semantics).
        from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
            backoff_delay,
        )

        assert backoff_delay(1, base=5.0, cap=60.0) == 5.0
        assert backoff_delay(2, base=5.0, cap=60.0) == 10.0
        assert backoff_delay(5, base=5.0, cap=60.0) == 60.0  # capped
        assert backoff_delay(99, base=5.0, cap=60.0) == 60.0

"""Engine tests: block-manager prefix caching, event emission, and the
hash-parity keystone — engine block hashes must equal the request keys the
control plane recomputes from event token IDs (the invariant the skipped
reference integration test guards, /root/reference/tests/integration/
prompt_to_block_test.go:58-60)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.engine.block_manager import (
    BlockManager,
    BlockManagerConfig,
    OutOfPagesError,
)
from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockRemoved, BlockStored
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig, Message


def _manager(n_pages=16, page_size=4, sink=None, seed=""):
    return BlockManager(
        BlockManagerConfig(n_pages=n_pages, page_size=page_size, hash_seed=seed),
        event_sink=sink,
    )


class TestBlockManager:
    def test_allocate_and_commit_emits_block_stored(self):
        batches = []
        bm = _manager(sink=batches.append)
        state = bm.allocate(list(range(10)))  # 2 full pages + 1 partial
        assert len(state.block_table) == 3
        assert state.num_cached_tokens == 0
        bm.commit_prefill(state)
        assert len(batches) == 1
        ev = batches[0].events[0]
        assert isinstance(ev, BlockStored)
        assert len(ev.block_hashes) == 2  # only full pages hashed
        assert ev.token_ids == list(range(8))
        assert ev.parent_block_hash is None

    def test_prefix_reuse_and_chained_event(self):
        batches = []
        bm = _manager(sink=batches.append)
        s1 = bm.allocate(list(range(8)))
        bm.commit_prefill(s1)

        # Same 8-token prefix + 4 more: 2 pages reused, 1 new.
        s2 = bm.allocate(list(range(8)) + [100, 101, 102, 103])
        assert s2.num_cached_tokens == 8
        assert s2.block_table[:2] == s1.block_table[:2]
        bm.commit_prefill(s2)
        ev = batches[-1].events[0]
        assert ev.parent_block_hash is not None
        assert ev.token_ids == [100, 101, 102, 103]
        assert len(ev.block_hashes) == 1

    def test_decode_fills_pages_and_emits(self):
        batches = []
        bm = _manager(sink=batches.append)
        state = bm.allocate(list(range(6)))  # 1 full + partial
        bm.commit_prefill(state)
        assert len(batches) == 1
        bm.append_token(state, 6)
        bm.append_token(state, 7)  # page 2 fills — but its last row is pending
        # ADVICE r2 (medium): the filling token's KV is not device-resident
        # yet; committing here would advertise (and allow export of) a page
        # with a garbage row. Commit happens at mark_decode_computed, after
        # the decode pass that writes the row.
        assert len(batches) == 1
        assert bm.num_cached_pages == 1
        bm.mark_decode_computed(state)
        assert len(batches) == 2
        ev = batches[-1].events[0]
        assert ev.token_ids == [4, 5, 6, 7]

    def test_pending_tail_page_not_reusable_until_computed(self):
        # A same-prefix allocation in the pending window must NOT hit the
        # page whose final slot awaits its KV row.
        bm = _manager()
        state = bm.allocate(list(range(7)))
        bm.commit_prefill(state)
        bm.append_token(state, 7)  # fills page 2; token 7 pending
        probe = bm.allocate(list(range(8)))
        assert probe.num_cached_tokens == 4  # only the prefill-committed page
        bm.free(probe)
        bm.mark_decode_computed(state)
        probe2 = bm.allocate(list(range(8)))
        assert probe2.num_cached_tokens == 8  # now safe to reuse

    def test_eviction_emits_block_removed(self):
        batches = []
        bm = _manager(n_pages=4, page_size=4, sink=batches.append)
        s1 = bm.allocate(list(range(16)))  # all 4 pages
        bm.commit_prefill(s1)
        bm.free(s1)
        # New distinct sequence must reclaim cached pages -> BlockRemoved.
        s2 = bm.allocate([99] * 8)
        removed = [
            e for b in batches for e in b.events if isinstance(e, BlockRemoved)
        ]
        # Two pages reclaimed in one wave -> ONE multi-hash BlockRemoved
        # (the reference schema's BlockHashes list, events.go:77-81).
        assert sum(len(e.block_hashes) for e in removed) == 2

    def test_free_keeps_pages_cached_for_reuse(self):
        bm = _manager()
        s1 = bm.allocate(list(range(8)))
        bm.commit_prefill(s1)
        bm.free(s1)
        s2 = bm.allocate(list(range(8)))
        assert s2.num_cached_tokens == 8  # reuse after free

    def test_out_of_pages_raises_and_rolls_back(self):
        bm = _manager(n_pages=2, page_size=4)
        s1 = bm.allocate(list(range(8)))
        with pytest.raises(OutOfPagesError):
            bm.allocate([50, 51, 52, 53])
        bm.free(s1)
        bm.allocate([50, 51, 52, 53])  # now fits

    def test_duplicate_content_page_reclaim_keeps_live_mapping(self):
        # Two pages can hold identical content (same hash) when the reuse
        # chain broke mid-way; reclaiming the loser must not evict the live
        # page's hash mapping nor emit a spurious BlockRemoved.
        batches = []
        bm = _manager(n_pages=4, page_size=4, sink=batches.append)
        s1 = bm.allocate(list(range(16)))  # pages 0-3, hashes h0..h3
        bm.commit_prefill(s1)
        bm.free(s1)
        # Reclaim ONLY page 0 (h0): new 8-token sequence with distinct tokens.
        s2 = bm.allocate([90, 91, 92, 93, 94, 95, 96, 97])
        bm.commit_prefill(s2)
        # Now re-allocate the ORIGINAL tokens: h0 misses (reclaimed), so all
        # pages are fresh/reclaimed and h1..h3 get recomputed as duplicates
        # of still-reclaimable pages 1-3.
        bm.free(s2)
        s3 = bm.allocate(list(range(16)))
        bm.commit_prefill(s3)
        bm.free(s3)
        # Immediately reusing the same tokens must still hit the full prefix.
        s4 = bm.allocate(list(range(16)))
        assert s4.num_cached_tokens == 16
        removed = [
            h for b in batches for e in b.events
            if isinstance(e, BlockRemoved) for h in e.block_hashes
        ]
        # No hash may be "removed" while some page still holds it registered.
        live = {p.chunk_hash for p in bm._pages if p.chunk_hash is not None}
        for h in removed[-4:]:
            if h in live:
                assert bm._hash_to_page.get(h) is not None

    def test_clear_emits_block_removed_for_all_cached(self):
        batches = []
        bm = _manager(sink=batches.append)
        state = bm.allocate(list(range(8)))
        bm.commit_prefill(state)
        bm.clear()
        removed = [
            h for b in batches for e in b.events
            if isinstance(e, BlockRemoved) for h in e.block_hashes
        ]
        assert len(removed) == 2  # both cached pages reported gone
        assert bm.num_cached_pages == 0

    def test_seed_changes_hashes(self):
        b1, b2 = [], []
        _manager(sink=b1.append).commit_prefill(
            _manager(sink=b1.append).allocate(list(range(4)))
        )
        bm1 = _manager(sink=b1.append, seed="a")
        st = bm1.allocate(list(range(4)))
        bm1.commit_prefill(st)
        bm2 = _manager(sink=b2.append, seed="b")
        st2 = bm2.allocate(list(range(4)))
        bm2.commit_prefill(st2)
        assert b1[-1].events[0].block_hashes != b2[-1].events[0].block_hashes


class TestHashParityKeystone:
    def test_engine_hashes_equal_recomputed_request_keys(self):
        """BlockStored hashes == indexer-recomputed request keys, chained."""
        page_size = 4
        batches = []
        bm = _manager(page_size=page_size, sink=batches.append)
        tokens = list(range(17))
        state = bm.allocate(tokens)
        bm.commit_prefill(state)
        for t in (17, 18, 19):
            bm.append_token(state, t)
        bm.mark_decode_computed(state)  # final row written by a decode pass

        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=page_size))
        expected = [k.chunk_hash for k in db.tokens_to_kv_block_keys(None, state.tokens, "m")]
        emitted = [h for b in batches for e in b.events for h in e.block_hashes]
        assert emitted == expected

    def test_event_pool_digests_engine_events_into_matching_index(self):
        """Engine events -> pool -> index; read path finds the same keys."""
        page_size = 4
        index = InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=4))
        processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size=page_size))
        pool = EventPool(EventPoolConfig(concurrency=1), index, processor)
        pool.start(with_subscriber=False)
        try:
            def sink(batch):
                pool.add_task(
                    Message(
                        topic="kv@pod-e@m",
                        payload=batch.to_msgpack(),
                        seq=0,
                        pod_identifier="pod-e",
                        model_name="m",
                    )
                )

            bm = _manager(page_size=page_size, sink=sink)
            tokens = list(range(12))
            state = bm.allocate(tokens)
            bm.commit_prefill(state)
            pool.drain()

            read_keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            got = index.lookup(read_keys, set())
            assert set(got) == set(read_keys)  # full prefix indexed
        finally:
            pool.shutdown()


class TestEnginePodWithModel:
    def test_quantized_kv_generation_close_to_bf16(self):
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        cfg = LlamaConfig(
            vocab_size=128, d_model=32, n_layers=1, n_q_heads=2, n_kv_heads=2,
            head_dim=16, d_ff=64, dtype=jnp.float32,
        )
        prompt = list(range(10))

        def run(use_quant):
            pod = EnginePod(
                EnginePodConfig(
                    n_pages=32, page_size=4, with_model=True, model_config=cfg,
                    max_pages_per_seq=16, use_quantized_kv=use_quant,
                )
            )
            state, _ = pod.prefill(prompt)
            logits = np.asarray(pod.last_logits)
            pod.free(state)
            return logits

        full = run(False)
        quant = run(True)
        # int8 KV introduces ~1% error but must not change the distribution.
        assert np.max(np.abs(full - quant)) < 0.15 * max(np.max(np.abs(full)), 1.0)

    def test_generation_with_prefix_reuse(self):
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        cfg = LlamaConfig(
            vocab_size=128, d_model=32, n_layers=1, n_q_heads=2, n_kv_heads=2,
            head_dim=16, d_ff=64, dtype=jnp.float32,
        )
        pod = EnginePod(
            EnginePodConfig(
                n_pages=32, page_size=4, with_model=True, model_config=cfg,
                max_pages_per_seq=16,
            )
        )
        prompt = list(range(10))
        state, cached = pod.prefill(prompt)
        assert cached == 0
        first = int(jnp.argmax(pod.last_logits))
        pod.decode_append(state, first)
        generated = [pod.decode_step(state) for _ in range(5)]
        assert all(0 <= t < cfg.vocab_size for t in generated)
        pod.free(state)

        # Same prompt again: prefix pages reused.
        state2, cached2 = pod.prefill(prompt)
        assert cached2 == 8  # two full pages of 4
        pod.decode_append(state2, first)
        generated2 = [pod.decode_step(state2) for _ in range(5)]
        assert generated2 == generated  # deterministic greedy decode
        pod.free(state2)


class TestBucketedPrefill:
    CFG = None

    def _pod(self):
        from llm_d_kv_cache_manager_tpu.models.llama import LlamaConfig

        if TestBucketedPrefill.CFG is None:
            TestBucketedPrefill.CFG = LlamaConfig(
                vocab_size=128, d_model=32, n_layers=1, n_q_heads=2,
                n_kv_heads=2, head_dim=16, d_ff=64, dtype=jnp.float32,
            )
        return EnginePod(
            EnginePodConfig(
                n_pages=64, page_size=4, with_model=True,
                model_config=TestBucketedPrefill.CFG, max_pages_per_seq=16,
            )
        )

    def test_padded_prefill_logits_equal_unpadded(self):
        from llm_d_kv_cache_manager_tpu.models import llama

        pod = self._pod()
        prompt = list(range(5))  # pads to bucket 8
        state, _ = pod.prefill(prompt)
        padded_logits = np.asarray(pod.last_logits)
        pod.free(state)

        cache = llama.make_kv_pages(TestBucketedPrefill.CFG, 8, 4)
        _, ref_logits = llama.prefill_cache(
            TestBucketedPrefill.CFG, pod.params, cache,
            jnp.asarray(prompt, jnp.int32), jnp.arange(2, dtype=jnp.int32), 0,
        )
        np.testing.assert_allclose(padded_logits, np.asarray(ref_logits),
                                   rtol=1e-5, atol=1e-6)

    def test_compile_count_bounded_by_buckets(self):
        # TPU serving economics: a compile costs seconds, so prefill must
        # compile per LENGTH BUCKET, not per prompt length. 7 distinct
        # lengths in (4, 16] span exactly two buckets (8, 16) — and the
        # exact-pow2 length must share the padded bucket's program
        # (n_valid is always an array, never a None variant).
        from llm_d_kv_cache_manager_tpu.models import llama

        pod = self._pod()
        before = llama.prefill_cache._cache_size()
        # Disjoint token ranges: no prefix-cache hits, so every prompt
        # prefills its full length (a shared prefix would shrink the
        # computed residual and legitimately hit smaller buckets).
        for i, length in enumerate((5, 6, 7, 8, 9, 11, 13)):
            base = i * 20
            state, _ = pod.prefill(list(range(base, base + length)))
            pod.free(state)
        grew = llama.prefill_cache._cache_size() - before
        assert grew <= 2, f"prefill compiled {grew} distinct programs for 7 lengths"


class TestFreshPageRefcounts:
    def test_shared_committed_page_not_reclaimed_under_live_reader(self):
        # Regression (found in r2): fresh pages joined the table with
        # ref_count 0, so after commit + reuse by a second sequence, the
        # first sequence's free() dropped the count to zero and the page
        # became reclaimable while the second sequence still read it.
        bm = _manager(n_pages=4, page_size=4)
        a = bm.allocate(list(range(8)))
        bm.commit_prefill(a)
        b = bm.allocate(list(range(8)))  # shares a's committed pages
        bm.free(a)
        bm.allocate([50, 51, 52, 53, 54, 55, 56, 57])  # takes the fresh pair
        with pytest.raises(OutOfPagesError):
            bm.allocate([70, 71, 72, 73])  # must NOT steal b's live pages
        assert b.block_table == [0, 1]

    def test_reserved_pages_return_to_pool_on_free(self):
        bm = _manager(n_pages=8, page_size=4)
        s = bm.allocate(list(range(8)))
        bm.reserve_pages(s, 5)  # 2 in use + 3 reserved ahead
        assert len(s.block_table) == 5
        assert bm.num_free_pages == 3
        bm.free(s)
        assert bm.num_free_pages == 8  # reservations fully returned

"""LoRA-aware block keys, end to end.

The reference decodes BlockStored.LoraID but never uses it (its LoRA hash-
parity integration test is a skipped TODO, /root/reference/tests/integration/
prompt_to_block_test.go:101-102). This build makes the adapter id a
first-class hash discriminator: same tokens + different adapter => different
block keys, through the hash core, the token processor, the event pool, the
engine block manager, and the read path.
"""

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import hashing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig, Message
from llm_d_kv_cache_manager_tpu.engine.block_manager import (
    BlockManager,
    BlockManagerConfig,
)


class TestHashing:
    def test_extra_keys_change_payload(self):
        base = hashing.cbor_hash_payload(0, [1, 2])
        with_extra = hashing.cbor_hash_payload(0, [1, 2], [7])
        assert base != with_extra
        assert base.endswith(b"\xf6")  # null preserved on the base path
        assert with_extra.endswith(bytes([0x81, 0x07]))  # array([7])

    def test_chain_differs_per_adapter(self):
        root = hashing.init_hash("")
        plain = hashing.prefix_hashes_fast(root, list(range(8)), 4)
        lora7 = hashing.prefix_hashes_fast(root, list(range(8)), 4, [7])
        lora9 = hashing.prefix_hashes_fast(root, list(range(8)), 4, [9])
        assert plain != lora7 != lora9 and plain != lora9


class TestTokenProcessor:
    def test_lora_id_scopes_keys(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        tokens = list(range(8))
        base = db.tokens_to_kv_block_keys(None, tokens, "m")
        lora = db.tokens_to_kv_block_keys(None, tokens, "m", lora_id=3)
        assert base != lora
        # Deterministic per adapter.
        assert lora == db.tokens_to_kv_block_keys(None, tokens, "m", lora_id=3)


class TestEndToEnd:
    def test_event_pool_and_engine_agree_on_lora_keys(self):
        page_size = 4
        index = InMemoryIndex(InMemoryIndexConfig(size=1000, pod_cache_size=4))
        processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size=page_size))
        pool = EventPool(EventPoolConfig(concurrency=1), index, processor)
        pool.start(with_subscriber=False)
        try:
            def sink(batch):
                pool.add_task(Message(
                    topic="kv@pod-l@m", payload=batch.to_msgpack(), seq=0,
                    pod_identifier="pod-l", model_name="m",
                ))

            bm = BlockManager(
                BlockManagerConfig(n_pages=32, page_size=page_size),
                event_sink=sink,
            )
            tokens = list(range(12))
            state = bm.allocate(tokens, lora_id=5)
            bm.commit_prefill(state)
            pool.drain()

            lora_keys = processor.tokens_to_kv_block_keys(None, tokens, "m", lora_id=5)
            plain_keys = processor.tokens_to_kv_block_keys(None, tokens, "m")
            assert set(index.lookup(lora_keys, set())) == set(lora_keys)
            assert index.lookup(plain_keys, set()) == {}  # adapter-scoped

            # Engine-side prefix reuse is adapter-scoped too.
            bm.free(state)
            again_same = bm.allocate(tokens, lora_id=5)
            assert again_same.num_cached_tokens == 12
            bm.free(again_same)
            other_adapter = bm.allocate(tokens, lora_id=6)
            assert other_adapter.num_cached_tokens == 0
        finally:
            pool.shutdown()

    def test_wire_roundtrip_preserves_lora_id(self):
        batch = EventBatch(
            ts=0.0,
            events=[BlockStored([1], None, [1, 2, 3, 4], 4, lora_id=11)],
        )
        decoded = EventBatch.from_msgpack(batch.to_msgpack())
        assert decoded.events[0].lora_id == 11

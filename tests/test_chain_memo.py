"""Property tests for the chain-state memo (kvblock/chain_memo.py).

The memo's contract is absolute: derivation through it is bit-identical to
from-scratch derivation (hashing.prefix_hashes_fast) for ANY sequence of
calls — extensions, truncations, divergent branches, block-straddling
edits, interleaved identities — and eviction only ever costs cold
recomputation, never wrong keys. Both hash algorithms and LoRA extra-key
chains are covered (extra keys change every block hash, so memo entries
must be keyed by the extra tuple too).
"""

import random
import threading

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import hashing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.chain_memo import (
    ChainMemo,
    ChainMemoConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

ALGOS = ["fnv64_cbor", "sha256_cbor_64bit"]
EXTRAS = [None, [7], [3, 5]]


def _truth(parent, tokens, bs, extra, algo):
    return hashing.prefix_hashes_fast(parent, tokens, bs, extra, algo=algo)


def _derive(memo, parent, tokens, bs, extra, algo, prefix_state=None):
    """Hash chain through the memo's Key-space API (model fixed)."""
    keys = memo.derive_keys(
        "m", parent, tokens, bs, extra, algo, prefix_state=prefix_state
    )
    assert all(k.model_name == "m" for k in keys)
    return [k.chunk_hash for k in keys]


def _mutate(rng, tokens, bs):
    """One randomized multi-turn-style edit of a token stream."""
    kind = rng.randrange(5)
    out = list(tokens)
    if kind == 0:  # append a turn (any length, straddles block boundaries)
        out += [rng.randrange(2**17) for _ in range(rng.randrange(1, 3 * bs))]
    elif kind == 1:  # truncate anywhere (mid-block included)
        out = out[: rng.randrange(len(out) + 1)]
    elif kind == 2 and out:  # divergent branch mid-stream
        cut = rng.randrange(len(out))
        out = out[:cut] + [rng.randrange(2**17) for _ in range(rng.randrange(1, 2 * bs))]
    elif kind == 3 and out:  # point edit inside an existing block
        out[rng.randrange(len(out))] ^= 1
    else:  # block-boundary-straddling splice
        at = (rng.randrange(max(len(out) // bs, 1)) * bs) or bs
        at = min(at, len(out))
        out = out[: max(at - rng.randrange(bs), 0)] + out[at:]
    return out


class TestSegmentMemoProperties:
    @pytest.mark.parametrize("algo", ALGOS)
    @pytest.mark.parametrize("extra", EXTRAS, ids=["base", "lora", "lora2"])
    def test_randomized_edit_walk_bit_identical(self, algo, extra):
        rng = random.Random(hash((algo, str(extra))) & 0xFFFF)
        bs = 16
        memo = ChainMemo(ChainMemoConfig(capacity=4096, segment_blocks=4))
        seed = "42"
        root = (
            hashing.init_hash(seed) if algo == "fnv64_cbor"
            else hashing.sha256_cbor_init_hash(seed)
        )
        tokens = [rng.randrange(2**17) for _ in range(rng.randrange(2, 200))]
        for _ in range(60):
            got = _derive(memo, root, tokens, bs, extra, algo)
            assert got == _truth(root, tokens, bs, extra, algo)
            tokens = _mutate(rng, tokens, bs)

    def test_identities_never_alias(self):
        """Same tokens under different (algo, extra, parent, block_size)
        must produce each identity's own from-scratch chain even when all
        of them share one memo."""
        rng = random.Random(5)
        memo = ChainMemo(ChainMemoConfig(capacity=4096, segment_blocks=2))
        tokens = [rng.randrange(2**17) for _ in range(128)]
        idents = [
            (algo, extra, parent, bs)
            for algo in ALGOS
            for extra in EXTRAS
            for parent in (hashing.init_hash(""), hashing.init_hash("42"))
            for bs in (8, 16)
        ]
        for _ in range(3):  # repeat: later rounds hit what earlier seeded
            for algo, extra, parent, bs in idents:
                assert _derive(memo, parent, tokens, bs, extra, algo) == _truth(
                    parent, tokens, bs, extra, algo
                )

    def test_eviction_only_ever_recomputes(self):
        rng = random.Random(9)
        # Capacity 2: nearly everything is evicted between calls.
        memo = ChainMemo(ChainMemoConfig(capacity=2, segment_blocks=2))
        root = hashing.init_hash("")
        streams = [
            [rng.randrange(2**17) for _ in range(rng.randrange(1, 150))]
            for _ in range(12)
        ]
        for _ in range(40):
            s = rng.choice(streams)
            assert _derive(memo, root, s, 16, None, "fnv64_cbor") == _truth(
                root, s, 16, None, "fnv64_cbor"
            )

    def test_parent_chain_continuation(self):
        """Write-plane shape: event chains that continue a parent key."""
        rng = random.Random(21)
        memo = ChainMemo(ChainMemoConfig(capacity=1024, segment_blocks=1))
        root = hashing.init_hash("42")
        tokens = [rng.randrange(2**17) for _ in range(96)]
        full = _derive(memo, root, tokens, 16, None, "fnv64_cbor")
        head = _derive(memo, root, tokens[:32], 16, None, "fnv64_cbor")
        cont = _derive(memo, head[-1], tokens[32:], 16, None, "fnv64_cbor")
        assert head + cont == full == _truth(root, tokens, 16, None, "fnv64_cbor")

    def test_concurrent_derivations_stay_correct(self):
        rng = random.Random(13)
        memo = ChainMemo(ChainMemoConfig(capacity=256, segment_blocks=2))
        root = hashing.init_hash("")
        streams = [
            [rng.randrange(2**17) for _ in range(rng.randrange(16, 200))]
            for _ in range(8)
        ]
        truths = [_truth(root, s, 16, None, "fnv64_cbor") for s in streams]
        errors = []

        def worker(seed):
            r = random.Random(seed)
            for _ in range(30):
                i = r.randrange(len(streams))
                if _derive(memo, root, streams[i], 16, None, "fnv64_cbor") != truths[i]:
                    errors.append(i)

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors


class TestBoundaryStateProperties:
    def _state_for(self, tokens, every):
        """A well-formed prefix state: boundaries every `every` tokens,
        fingerprints a pure function of the exact token prefix (the
        invariant the prefix store's chain provides)."""
        fp = 0xABCDEF
        out = []
        for i, t in enumerate(tokens):
            fp = hashing.fold64(fp, t)
            if (i + 1) % every == 0:
                out.append((fp, i + 1))
        return tuple(out)

    @pytest.mark.parametrize("algo", ALGOS)
    @pytest.mark.parametrize("extra", EXTRAS, ids=["base", "lora", "lora2"])
    def test_boundary_path_bit_identical(self, algo, extra):
        rng = random.Random(4)
        memo = ChainMemo(ChainMemoConfig(capacity=4096))
        root = (
            hashing.init_hash("42") if algo == "fnv64_cbor"
            else hashing.sha256_cbor_init_hash("42")
        )
        tokens = [rng.randrange(2**17) for _ in range(23 * 9)]
        state = self._state_for(tokens, 23)  # boundaries unaligned to blocks
        for trim in (len(state), 5, 2, 0):  # progressively colder states
            got = _derive(memo, 
                root, tokens, 16, extra, algo, prefix_state=state[:trim]
            )
            assert got == _truth(root, tokens, 16, extra, algo)

    def test_shared_prefix_across_extended_state(self):
        rng = random.Random(8)
        memo = ChainMemo(ChainMemoConfig(capacity=4096))
        root = hashing.init_hash("")
        tokens = [rng.randrange(2**17) for _ in range(100)]
        state = self._state_for(tokens, 20)
        assert _derive(memo, root, tokens, 16, None, "fnv64_cbor", prefix_state=state) \
            == _truth(root, tokens, 16, None, "fnv64_cbor")
        # A follow-up turn: longer tokens, state extends the same chain.
        ext = tokens + [rng.randrange(2**17) for _ in range(60)]
        ext_state = self._state_for(ext, 20)
        assert ext_state[: len(state)] == state  # genuine shared prefix
        assert _derive(memo, root, ext, 16, None, "fnv64_cbor", prefix_state=ext_state) \
            == _truth(root, ext, 16, None, "fnv64_cbor")
        stats = memo.stats()
        assert stats["hits"] >= 1 and stats["blocks_reused"] > 0

    def test_boundary_eviction_recomputes(self):
        rng = random.Random(17)
        memo = ChainMemo(ChainMemoConfig(capacity=2))
        root = hashing.init_hash("")
        for _ in range(20):
            tokens = [rng.randrange(2**17) for _ in range(rng.randrange(20, 120))]
            state = self._state_for(tokens, 15)
            assert _derive(memo, root, tokens, 16, None, "fnv64_cbor", prefix_state=state) \
                == _truth(root, tokens, 16, None, "fnv64_cbor")


class TestEndToEndThroughPool:
    """The shipped composition: prefix store boundary states flowing from
    TokenizationPool.tokenize_ex into ChunkedTokenDatabase — keys must be
    bit-identical to a memo-less processor on the same returned tokens,
    across multi-turn extensions, divergent branches and store eviction."""

    FIXTURE = "tests/fixtures/test-model/tokenizer.json"
    MODEL = "test-model"

    def _pool(self):
        return TokenizationPool(
            TokenizersPoolConfig(
                workers=1, local_tokenizer_files={self.MODEL: self.FIXTURE}
            )
        )

    def _run_prompts(self, prompts, lora_id=None):
        pool = self._pool()
        memo_db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=16))
        plain_db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=16, chain_memo=False)
        )
        try:
            for prompt in prompts:
                tp = pool.tokenize_ex(None, prompt, self.MODEL)
                got = memo_db.tokens_to_kv_block_keys(
                    None, tp.tokens, self.MODEL, lora_id=lora_id,
                    prefix_state=tp.prefix_state,
                )
                want = plain_db.tokens_to_kv_block_keys(
                    None, tp.tokens, self.MODEL, lora_id=lora_id
                )
                assert got == want, prompt[:60]
            return memo_db
        finally:
            pool.shutdown()

    def test_multi_turn_extension(self):
        base = "a conversation about kv cache routing " * 30
        prompts = [base]
        for turn in range(5):
            base = base + f" [turn {turn}] " + "more words every turn " * 12
            prompts.append(base)
        db = self._run_prompts(prompts)
        assert db.chain_memo.stats()["hits"] >= 1

    def test_divergent_branch_and_lora(self):
        base = "shared system prompt for every branch " * 25
        prompts = [
            base + " branch one goes this way " * 10,
            base + " branch two goes another way " * 10,
            base,  # truncation back to the shared prefix
        ]
        self._run_prompts(prompts)
        self._run_prompts(prompts, lora_id=7)

    def test_store_relearn_never_serves_stale_keys(self):
        rng = random.Random(2)
        pool = self._pool()
        memo_db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=16))
        plain_db = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=16, chain_memo=False)
        )
        words = ["alpha", "beta", "gamma", "delta", "routing", "cache"]
        try:
            for _ in range(12):
                prompt = " ".join(
                    rng.choice(words) for _ in range(rng.randrange(60, 400))
                )
                tp = pool.tokenize_ex(None, prompt, self.MODEL)
                got = memo_db.tokens_to_kv_block_keys(
                    None, tp.tokens, self.MODEL, prefix_state=tp.prefix_state
                )
                assert got == plain_db.tokens_to_kv_block_keys(
                    None, tp.tokens, self.MODEL
                )
        finally:
            pool.shutdown()


class TestConfigValidation:
    def test_bad_capacity_rejected(self):
        with pytest.raises(ValueError):
            ChainMemo(ChainMemoConfig(capacity=0))

    def test_bad_segment_blocks_rejected(self):
        with pytest.raises(ValueError):
            ChainMemo(ChainMemoConfig(segment_blocks=0))

    def test_memo_disabled_via_processor_config(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(chain_memo=False))
        assert db.chain_memo is None
        keys = db.tokens_to_kv_block_keys(None, list(range(32)), "m")
        assert len(keys) == 2

"""Replicated indexer control plane tests (cluster/ subsystem).

The load-bearing pins:

- Scatter-gather `get_pod_scores` across N=4 local replicas, each digesting
  only its event-stream partition, is BIT-IDENTICAL to a single indexer
  that digested everything (the acceptance criterion).
- `import_view(export_view(idx))` yields bit-identical lookup+score results
  for randomized chains across all four backends (in_memory, sharded,
  cost_aware, redis via fake_redis), including the file round-trip through
  the versioned CBOR snapshot.
- Seq-tail replay is idempotent: replaying an already-applied event is a
  no-op (even a conflicting payload at the same seq cannot corrupt the
  restored view).
- /readyz reports `replaying` (503, distinct from `unready`) while a
  replica is replaying its tail.
"""

import asyncio
import os
import random
import socket

import pytest

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from llm_d_kv_cache_manager_tpu.cluster import (
    ClusterConfig,
    ClusterScorer,
    IndexerReplica,
    LocalReplicaTransport,
    ReplicaPartitioner,
    SnapshotFormatError,
    read_snapshot,
    restore_index,
    write_snapshot,
)
from llm_d_kv_cache_manager_tpu.cluster.snapshot import (
    decode_snapshot,
    encode_snapshot,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareIndexConfig,
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing import fnv32a
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
    ShardedIndex,
    ShardedIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import LongestPrefixScorer
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored, EventBatch
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    EventPool,
    EventPoolConfig,
    Message,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

BLOCK_SIZE = 4
N_REPLICAS = 4
PODS = [f"pod-{i}" for i in range(8)]

WORDS = (
    "alpha bravo charlie delta echo foxtrot golf hotel india juliet "
    "kilo lima mike november oscar papa quebec romeo sierra tango"
).split()


def _text(rng, n):
    return " ".join(rng.choice(WORDS) for _ in range(n))


# -- partitioner --------------------------------------------------------------


class TestPartitioner:
    def test_fnv_striping_alignment(self):
        # The assignment IS the kvevents pool's FNV striping formula —
        # pinned so the two can never drift apart silently.
        p = ReplicaPartitioner(N_REPLICAS)
        for pod in PODS:
            assert p.replica_for(pod) == fnv32a(pod.encode()) % N_REPLICAS

    def test_dp_ranks_follow_their_pod(self):
        p = ReplicaPartitioner(N_REPLICAS)
        for pod in PODS:
            for rank in (0, 1, 7):
                assert p.replica_for(f"{pod}@dp{rank}") == p.replica_for(pod)

    def test_partition_map_covers_and_disjoint(self):
        p = ReplicaPartitioner(N_REPLICAS)
        pmap = p.partition_map(PODS)
        all_pods = [pod for pods in pmap.values() for pod in pods]
        assert sorted(all_pods) == sorted(PODS)
        assert len(all_pods) == len(set(all_pods))

    def test_topic_filters_are_owned_prefixes(self):
        p = ReplicaPartitioner(N_REPLICAS, replica_id=1)
        filters = p.topic_filters(PODS + ["pod-0@dp3"])
        assert filters == sorted(filters)
        for f in filters:
            pod = f[len("kv@"):-1]
            assert p.owns(pod)
        # Every filter is a ZMQ prefix of that pod's real topics.
        assert all(f.startswith("kv@") and f.endswith("@") for f in filters)

    def test_invalid_shapes_rejected(self):
        with pytest.raises(ValueError):
            ReplicaPartitioner(0)
        with pytest.raises(ValueError):
            ReplicaPartitioner(2, replica_id=2)
        with pytest.raises(ValueError):
            ClusterConfig(num_replicas=3, replica_id=5)


# -- scatter-gather bit-identity ---------------------------------------------


def _shared_tokenization_pool():
    pool = TokenizationPool(
        TokenizersPoolConfig(
            workers=2,
            local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
        ),
    )
    pool.run()
    return pool


def _make_indexer(tok_pool):
    return Indexer(
        config=IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=BLOCK_SIZE),
        ),
        tokenization_pool=tok_pool,
    )


def _event_pool_for(indexer, message_filter=None):
    pool = EventPool(
        EventPoolConfig(concurrency=2),
        indexer.kv_block_index,
        indexer.token_processor,
        message_filter=message_filter,
    )
    pool.start(with_subscriber=False)
    return pool


def _store_message(pod, tokens, first_engine_hash, seq, dp_rank=None):
    batch = EventBatch(
        ts=0.0,
        events=[BlockStored(
            block_hashes=list(range(
                first_engine_hash,
                first_engine_hash + len(tokens) // BLOCK_SIZE,
            )),
            parent_block_hash=None,
            token_ids=list(tokens),
            block_size=BLOCK_SIZE,
        )],
        data_parallel_rank=dp_rank,
    )
    return Message(
        topic=f"kv@{pod}@{TEST_MODEL_NAME}",
        payload=batch.to_msgpack(),
        seq=seq,
        pod_identifier=pod,
        model_name=TEST_MODEL_NAME,
    )


class _FailingTransport:
    def get_pod_scores_ex(self, *a, **k):
        raise ConnectionError("replica is down")


class TestScatterGather:
    @pytest.fixture
    def cluster(self):
        """A 4-replica cluster + a monolithic reference, fed the SAME
        event stream (replicas through their partition gates)."""
        tok_pool = _shared_tokenization_pool()
        reference = _make_indexer(tok_pool)
        replicas = [_make_indexer(tok_pool) for _ in range(N_REPLICAS)]
        ref_pool = _event_pool_for(reference)
        partitioners = [
            ReplicaPartitioner(N_REPLICAS, rid) for rid in range(N_REPLICAS)
        ]
        replica_pools = [
            _event_pool_for(replicas[rid], message_filter=partitioners[rid].accepts)
            for rid in range(N_REPLICAS)
        ]
        rng = random.Random(7)
        group_prefixes = [_text(rng, 40) for _ in range(3)]
        prompts = []
        seq = 0
        engine_base = 1000
        for i, pod in enumerate(PODS):
            prefix = group_prefixes[i % len(group_prefixes)]
            # Pods in one group cache different depths of the shared
            # prefix chain, so scores genuinely differ per pod.
            depth_words = 8 * (1 + i // len(group_prefixes))
            prompt = prefix + " " + _text(rng, depth_words)
            prompts.append(prefix + " " + _text(rng, 30))
            tokens = tok_pool.tokenizer.encode(prompt, TEST_MODEL_NAME).tokens
            n_full = (len(tokens) // BLOCK_SIZE) * BLOCK_SIZE
            dp_rank = 1 if i % 3 == 0 else None  # some ranked identities
            msg = _store_message(
                pod, tokens[:n_full], engine_base, seq, dp_rank=dp_rank
            )
            engine_base += 1000
            seq += 1
            for pool in replica_pools:
                pool.add_task(_store_message(
                    pod, tokens[:n_full], engine_base - 1000, seq - 1,
                    dp_rank=dp_rank,
                ))
            ref_pool.add_task(msg)
        for pool in replica_pools + [ref_pool]:
            pool.drain()
        yield {
            "reference": reference,
            "replicas": replicas,
            "prompts": prompts + group_prefixes,
            "pools": replica_pools + [ref_pool],
            "tok_pool": tok_pool,
        }
        for pool in replica_pools + [ref_pool]:
            pool.shutdown()
        tok_pool.shutdown()

    def test_partition_gate_splits_the_stream(self, cluster):
        # Every replica digested only its partition: the per-pool filtered
        # counters sum to (N-1) x messages.
        filtered = [p.filtered_events for p in cluster["pools"][:-1]]
        assert sum(filtered) == (N_REPLICAS - 1) * len(PODS)

    def test_merged_scores_bit_identical_to_single_replica(self, cluster):
        scorer = ClusterScorer(
            [LocalReplicaTransport(ix) for ix in cluster["replicas"]],
        )
        try:
            for prompt in cluster["prompts"]:
                ref = cluster["reference"].get_pod_scores_ex(
                    prompt, TEST_MODEL_NAME, []
                )
                merged = scorer.get_pod_scores_ex(prompt, TEST_MODEL_NAME, [])
                assert merged.scores == ref.scores
                assert merged.match_blocks == ref.match_blocks
                assert merged.block_hashes == ref.block_hashes
            # The stream genuinely produced scores (guards a vacuous pass).
            assert any(
                cluster["reference"].get_pod_scores(p, TEST_MODEL_NAME, [])
                for p in cluster["prompts"]
            )
        finally:
            scorer.close()

    def test_pod_filter_and_lora_pass_through(self, cluster):
        scorer = ClusterScorer(
            [LocalReplicaTransport(ix) for ix in cluster["replicas"]],
        )
        try:
            prompt = cluster["prompts"][0]
            ref = cluster["reference"].get_pod_scores(
                prompt, TEST_MODEL_NAME, ["pod-0", "pod-3"]
            )
            merged = scorer.get_pod_scores(
                prompt, TEST_MODEL_NAME, ["pod-0", "pod-3"]
            )
            assert merged == ref
        finally:
            scorer.close()

    def test_dead_replica_degrades_to_missing_partition(self, cluster):
        down = 1
        transports = [
            _FailingTransport() if rid == down else
            LocalReplicaTransport(cluster["replicas"][rid])
            for rid in range(N_REPLICAS)
        ]
        scorer = ClusterScorer(transports)
        try:
            part = ReplicaPartitioner(N_REPLICAS)
            for prompt in cluster["prompts"]:
                ref = cluster["reference"].get_pod_scores(
                    prompt, TEST_MODEL_NAME, []
                )
                merged = scorer.get_pod_scores(prompt, TEST_MODEL_NAME, [])
                surviving = {
                    pod: s for pod, s in ref.items()
                    if part.replica_for(pod) != down
                }
                # Never a stall, never an exception: the dead partition's
                # pods carry no signal, everything else is untouched.
                assert merged == surviving
            assert scorer.scatter_errors > 0
            status = scorer.status()
            assert status["replicas"]["replica-1"]["failures"] > 0
        finally:
            scorer.close()

    def test_stale_replica_skipped_by_state_machine(self):
        clock = {"t": 0.0}
        scorer = ClusterScorer(
            [_FailingTransport(), _FailingTransport()],
            config=ClusterConfig(
                num_replicas=2,
                replica_suspect_after_s=5.0,
                replica_stale_after_s=10.0,
            ),
            clock=lambda: clock["t"],
        )
        try:
            scorer.health.observe_batch("replica-0", "scatter", None, 0.0)
            scorer.health.observe_batch("replica-1", "scatter", None, 0.0)
            clock["t"] = 20.0  # both silent past the stale window
            assert scorer.health.state_of("replica-0") == "stale"
            assert scorer.health.state_of("replica-1") == "stale"
        finally:
            scorer.close()


# -- snapshot round-trip across all four backends -----------------------------


def _backend_factories(fake_redis_url=None):
    factories = {
        "in_memory": lambda: InMemoryIndex(
            InMemoryIndexConfig(size=4096, pod_cache_size=10)
        ),
        "sharded": lambda: ShardedIndex(
            ShardedIndexConfig(size=4096, num_shards=8)
        ),
        "cost_aware": lambda: CostAwareMemoryIndex(
            CostAwareIndexConfig(max_size_bytes="64MiB")
        ),
    }
    if fake_redis_url is not None:
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.redis_index import (
            RedisIndex,
            RedisIndexConfig,
        )

        factories["redis"] = lambda: RedisIndex(
            RedisIndexConfig(url=fake_redis_url)
        )
    return factories


def _populate_random(index, rng, processor):
    """Randomized chains: shared roots, divergent tails, random pods/tiers,
    some evictions. Returns the request-key chains for score probes."""
    chains = []
    for c in range(6):
        tokens = [rng.randrange(1, 30_000) for _ in range(
            BLOCK_SIZE * rng.randint(2, 10)
        )]
        keys = processor.tokens_to_kv_block_keys(
            None, tokens, TEST_MODEL_NAME
        )
        engine_keys = [
            Key(TEST_MODEL_NAME, 100_000 + c * 1000 + i)
            for i in range(len(keys))
        ]
        pods = rng.sample(PODS, rng.randint(1, 4))
        entries = [
            PodEntry(pod, rng.choice(("hbm", "host"))) for pod in pods
        ]
        # Per-pod varying depth: each pod holds a random prefix of the chain.
        for entry in entries:
            depth = rng.randint(1, len(keys))
            index.add(engine_keys[:depth], keys[:depth], [entry])
        # Occasional eviction, so restored emptiness matches too.
        if rng.random() < 0.3:
            index.evict(engine_keys[0], [entries[0]])
        chains.append(keys)
    return chains


@pytest.fixture
def fake_redis():
    from tests.fake_redis import FakeRedisServer

    server = FakeRedisServer()
    yield server
    server.close()


class TestSnapshotRoundTrip:
    @pytest.mark.parametrize(
        "backend", ["in_memory", "sharded", "cost_aware", "redis"]
    )
    def test_import_export_bit_identical_scores(
        self, backend, fake_redis, tmp_path
    ):
        """Property test: randomized chains, export -> CBOR file ->
        import into a FRESH backend, then lookup + LongestPrefixScorer
        must agree bit-for-bit with the source — get_pod_scores is exactly
        lookup+score over these chains."""
        processor = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=BLOCK_SIZE)
        )
        scorer = LongestPrefixScorer({"hbm": 1.0, "host": 0.8})
        for trial in range(3):
            rng = random.Random(100 + trial)
            factories = _backend_factories(fake_redis.url)
            source = factories[backend]()
            if backend == "redis":
                source._pipeline([("FLUSHALL",)])  # noqa: SLF001
            chains = _populate_random(source, rng, processor)
            path = str(tmp_path / f"{backend}_{trial}.cbor")
            write_snapshot(
                path, source,
                {("pod-0", f"kv@pod-0@{TEST_MODEL_NAME}"): 41 + trial},
            )
            snap = read_snapshot(path)
            assert snap.seq_counters == {
                ("pod-0", f"kv@pod-0@{TEST_MODEL_NAME}"): 41 + trial
            }
            if backend == "redis":
                fresh = InMemoryIndex(  # fresh redis == same server; use
                    InMemoryIndexConfig(size=4096)  # a cross-backend target
                )
            else:
                fresh = factories[backend]()
            imported = restore_index(fresh, snap)
            assert imported == snap.view.entry_count()
            for keys in chains:
                src_lookup = source.lookup(keys, set())
                dst_lookup = fresh.lookup(keys, set())
                assert {k: sorted(map(str, v)) for k, v in src_lookup.items()} \
                    == {k: sorted(map(str, v)) for k, v in dst_lookup.items()}
                assert scorer.score(keys, src_lookup) == scorer.score(
                    keys, dst_lookup
                )
                assert scorer.score_ex(keys, src_lookup) == scorer.score_ex(
                    keys, dst_lookup
                )
            # Engine->request resolution survives (replay needs it for
            # parent-chain continuation).
            for model, h, req_model, req_h in snap.view.engine_map[:10]:
                assert fresh.get_request_key(Key(model, h)) == Key(
                    req_model, req_h
                )

    def test_cross_backend_restore(self, tmp_path):
        """A sharded replica's snapshot restores into an in-memory (and
        cost-aware) backend: the view format is backend-agnostic."""
        processor = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=BLOCK_SIZE)
        )
        scorer = LongestPrefixScorer({"hbm": 1.0})
        rng = random.Random(5)
        source = ShardedIndex(ShardedIndexConfig(size=4096, num_shards=4))
        chains = _populate_random(source, rng, processor)
        path = str(tmp_path / "cross.cbor")
        write_snapshot(path, source, {})
        snap = read_snapshot(path)
        for target in (
            InMemoryIndex(InMemoryIndexConfig(size=4096)),
            CostAwareMemoryIndex(CostAwareIndexConfig(max_size_bytes="64MiB")),
        ):
            restore_index(target, snap)
            for keys in chains:
                assert scorer.score(keys, target.lookup(keys, set())) == \
                    scorer.score(keys, source.lookup(keys, set()))

    def test_version_and_magic_are_enforced(self, tmp_path):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexView

        data = encode_snapshot(IndexView(), {})
        snap = decode_snapshot(data)
        assert snap.version == 2
        with pytest.raises(SnapshotFormatError):
            decode_snapshot(b"NOTASNAP" + data)
        # Flip the version byte (first CBOR uint after the magic+array head).
        from llm_d_kv_cache_manager_tpu.cluster.snapshot import SNAPSHOT_MAGIC

        bad = bytearray(data)
        bad[len(SNAPSHOT_MAGIC) + 1] = 0x17  # version 23
        with pytest.raises(SnapshotFormatError):
            decode_snapshot(bytes(bad))
        with pytest.raises(SnapshotFormatError):
            decode_snapshot(data[:-3])  # truncated

    def test_snapshot_checksum_catches_bit_flips_and_torn_tails(self):
        """Every v2 snapshot carries a trailing FNV-1a 64 of its CBOR body:
        a bit-flip anywhere in the document (even one that still decodes as
        valid CBOR) fails LOUDLY instead of warm-restarting a silently
        corrupt index view."""
        from llm_d_kv_cache_manager_tpu.cluster.snapshot import SNAPSHOT_MAGIC
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexView

        view = IndexView(
            entries=[(TEST_MODEL_NAME, 42, (("pod-0", "hbm"),))],
            engine_map=[],
        )
        data = encode_snapshot(view, {("pod-0", "t"): 7})
        assert decode_snapshot(data).seq_counters == {("pod-0", "t"): 7}
        # Flip one payload bit (a seq value byte): still-valid CBOR, wrong
        # content — the checksum is the only thing that can catch it.
        for flip_at in range(len(SNAPSHOT_MAGIC) + 2, len(data) - 8, 7):
            bad = bytearray(data)
            bad[flip_at] ^= 0x01
            with pytest.raises(SnapshotFormatError):
                decode_snapshot(bytes(bad))
        # Torn checksum tail.
        with pytest.raises(SnapshotFormatError) as err:
            decode_snapshot(data[:-1])
        assert "checksum" in str(err.value)
        # Flipped checksum itself.
        bad = bytearray(data)
        bad[-1] ^= 0xFF
        with pytest.raises(SnapshotFormatError):
            decode_snapshot(bytes(bad))

    def test_v1_snapshot_without_checksum_still_loads(self):
        """Pre-integrity snapshot files (version 1, no trailing checksum)
        must keep loading — a fleet upgrades its snapshot format without
        losing its last warm-restart point."""
        from llm_d_kv_cache_manager_tpu.cluster.snapshot import SNAPSHOT_MAGIC
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import IndexView
        from llm_d_kv_cache_manager_tpu.utils import cbor

        v2 = encode_snapshot(
            IndexView(entries=[(TEST_MODEL_NAME, 5, (("pod-1", "hbm"),))],
                      engine_map=[]),
            {("pod-1", "t"): 3},
        )
        doc, _end = cbor.decode(v2, len(SNAPSHOT_MAGIC))
        doc[0] = 1  # re-encode as the v1 writer would have (no checksum)
        body = bytearray()
        cbor.encode_into(doc, body)
        v1 = SNAPSHOT_MAGIC + bytes(body)
        snap = decode_snapshot(v1)
        assert snap.version == 1
        assert snap.seq_counters == {("pod-1", "t"): 3}
        assert snap.view.entries == [
            (TEST_MODEL_NAME, 5, (("pod-1", "hbm"),))
        ]
        # v1 carries no checksum, so a v1 bit-flip is NOT detectable —
        # but a trailing-garbage v1 file still errors.
        with pytest.raises(SnapshotFormatError):
            decode_snapshot(v1 + b"xx")

    def test_atomic_write_leaves_no_tmp(self, tmp_path):
        source = InMemoryIndex(InMemoryIndexConfig(size=64))
        source.add(
            [Key(TEST_MODEL_NAME, 1)], [Key(TEST_MODEL_NAME, 2)],
            [PodEntry("pod-0", "hbm")],
        )
        path = str(tmp_path / "snap.cbor")
        write_snapshot(path, source, {})
        assert os.path.exists(path)
        assert [p for p in os.listdir(tmp_path) if ".tmp." in p] == []


# -- seq-tail replay idempotence ----------------------------------------------


class TestSeqTailReplay:
    def _pool(self):
        index = InMemoryIndex(InMemoryIndexConfig(size=1000))
        processor = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=BLOCK_SIZE)
        )
        pool = EventPool(EventPoolConfig(concurrency=1), index, processor)
        pool.start(with_subscriber=False)
        return pool, index, processor

    def test_replay_at_or_below_floor_is_noop(self):
        pool, index, processor = self._pool()
        try:
            topic = f"kv@pod-1@{TEST_MODEL_NAME}"
            pool.set_seq_floors({("pod-1", topic): 5})
            # A CONFLICTING payload at an already-applied seq must be
            # dropped — replay can never corrupt the restored view.
            tokens = [9, 9, 9, 9]
            pool.add_task(_store_message("pod-1", tokens, 777, seq=5))
            pool.add_task(_store_message("pod-1", tokens, 778, seq=3))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(
                None, tokens, TEST_MODEL_NAME
            )
            assert index.lookup(keys, set()) == {}
            assert pool.replay_skipped == 2
            # Above the floor applies normally.
            pool.add_task(_store_message("pod-1", tokens, 779, seq=6))
            pool.drain()
            assert keys[0] in index.lookup(keys, set())
        finally:
            pool.shutdown()

    def test_floor_is_per_pod_and_topic(self):
        pool, index, processor = self._pool()
        try:
            topic1 = f"kv@pod-1@{TEST_MODEL_NAME}"
            pool.set_seq_floors({("pod-1", topic1): 10})
            tokens = [1, 2, 3, 4]
            # Different pod: same seq is NOT floored.
            pool.add_task(_store_message("pod-2", tokens, 100, seq=4))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(
                None, tokens, TEST_MODEL_NAME
            )
            assert keys[0] in index.lookup(keys, set())
            assert pool.replay_skipped == 0
        finally:
            pool.shutdown()

    def test_clear_floors_restores_live_stream(self):
        pool, index, processor = self._pool()
        try:
            topic = f"kv@pod-1@{TEST_MODEL_NAME}"
            pool.set_seq_floors({("pod-1", topic): 1_000_000})
            pool.clear_seq_floors()
            tokens = [5, 6, 7, 8]
            # A restarted publisher's seq=0 flows once floors are cleared.
            pool.add_task(_store_message("pod-1", tokens, 200, seq=0))
            pool.drain()
            keys = processor.tokens_to_kv_block_keys(
                None, tokens, TEST_MODEL_NAME
            )
            assert keys[0] in index.lookup(keys, set())
        finally:
            pool.shutdown()


# -- replica warm restart + readiness ----------------------------------------


class TestIndexerReplica:
    def test_warm_restart_replays_only_the_tail(self, tmp_path):
        tok_pool = _shared_tokenization_pool()
        indexer = _make_indexer(tok_pool)
        from llm_d_kv_cache_manager_tpu.fleethealth import (
            FleetHealthConfig,
            FleetHealthTracker,
        )

        health = FleetHealthTracker(FleetHealthConfig())
        path = str(tmp_path / "replica.cbor")
        replica = IndexerReplica(
            indexer,
            ClusterConfig(num_replicas=1, snapshot_path=path),
            health_tracker=health,
        )
        replica.start()
        try:
            t1, t2 = [1, 2, 3, 4], [5, 6, 7, 8]
            applied = _store_message("pod-1", t1, 300, seq=0)
            replica.ingest(applied)
            replica.event_pool.drain()
            stats = replica.take_snapshot()
            assert stats["pod_entries"] > 0
            assert stats["seq_counters"] == 1
            # The tail: one already-applied message + one the snapshot
            # never saw.
            tail = [applied, _store_message("pod-1", t2, 400, seq=1)]

            fresh = _make_indexer(tok_pool)
            replica2 = IndexerReplica(
                fresh,
                ClusterConfig(num_replicas=1, snapshot_path=path),
                health_tracker=FleetHealthTracker(FleetHealthConfig()),
            )
            replica2.start()
            try:
                restored = replica2.warm_restart(tail=tail)
                assert replica2.state == "ready"
                assert restored["tail_messages"] == 2
                assert restored["replay_skipped"] == 1  # the pre-floor one
                proc = fresh.token_processor
                k1 = proc.tokens_to_kv_block_keys(None, t1, TEST_MODEL_NAME)
                k2 = proc.tokens_to_kv_block_keys(None, t2, TEST_MODEL_NAME)
                assert k1[0] in fresh.kv_block_index.lookup(k1, set())
                assert k2[0] in fresh.kv_block_index.lookup(k2, set())
                readiness = replica2.readiness()
                assert readiness["state"] == "ready"
                assert readiness["last_restart"]["replay_skipped"] == 1
            finally:
                replica2.shutdown()
        finally:
            replica.shutdown()
            tok_pool.shutdown()

    def test_readyz_reports_replaying_as_503(self):
        from aiohttp.test_utils import TestClient, TestServer
        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService

        tok_pool = _shared_tokenization_pool()
        indexer = _make_indexer(tok_pool)
        replica = IndexerReplica(indexer, ClusterConfig(num_replicas=1))
        env = {
            "zmq_endpoint": "tcp://*:0",
            "zmq_topic": "kv@",
            "pool_concurrency": 1,
            "hash_seed": "",
            "block_size": BLOCK_SIZE,
            "http_port": 0,
            "enable_metrics": False,
        }
        service = ScoringService(env, indexer=indexer, cluster_replica=replica)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                body = await resp.json()
                assert resp.status == 200
                assert body["status"] == "ready"
                assert body["replication"]["state"] == "ready"
                assert body["replication"]["num_replicas"] == 1

                # Mid-warm-restart: the replica is REPLAYING — 503, with a
                # status string distinct from plain unready.
                replica._set_state("replaying")  # noqa: SLF001
                resp = await client.get("/readyz")
                body = await resp.json()
                assert resp.status == 503
                assert body["status"] == "replaying"
                assert body["replication"]["state"] == "replaying"

                replica._set_state("ready")  # noqa: SLF001
                resp = await client.get("/readyz")
                assert resp.status == 200

                status = await client.get("/cluster/status")
                doc = await status.json()
                assert doc["replica"]["replica_id"] == 0
        try:
            asyncio.run(run())
        finally:
            service.stop()
            tok_pool.shutdown()

    def test_cluster_snapshot_endpoint(self, tmp_path):
        from aiohttp.test_utils import TestClient, TestServer
        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService

        tok_pool = _shared_tokenization_pool()
        indexer = _make_indexer(tok_pool)
        path = str(tmp_path / "http_snap.cbor")
        replica = IndexerReplica(
            indexer, ClusterConfig(num_replicas=1, snapshot_path=path)
        )
        env = {
            "zmq_endpoint": "tcp://*:0",
            "zmq_topic": "kv@",
            "pool_concurrency": 1,
            "hash_seed": "",
            "block_size": BLOCK_SIZE,
            "http_port": 0,
            "enable_metrics": False,
        }
        service = ScoringService(env, indexer=indexer, cluster_replica=replica)

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.post("/cluster/snapshot")
                body = await resp.json()
                assert resp.status == 200
                assert body["path"] == path
                assert os.path.exists(path)
        try:
            asyncio.run(run())
        finally:
            service.stop()
            tok_pool.shutdown()


# -- gRPC transport (cluster marker: needs grpcio) ----------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.cluster
class TestGrpcTransport:
    def test_scatter_gather_over_grpc_matches_local(self):
        from llm_d_kv_cache_manager_tpu.api.grpc_server import serve_grpc
        from llm_d_kv_cache_manager_tpu.cluster import GrpcReplicaTransport

        tok_pool = _shared_tokenization_pool()
        reference = _make_indexer(tok_pool)
        replicas = [_make_indexer(tok_pool) for _ in range(2)]
        part = ReplicaPartitioner(2)
        prompt = "the quick brown fox jumps over the lazy dog " * 3
        tokens = tok_pool.tokenizer.encode(prompt, TEST_MODEL_NAME).tokens
        n_full = (len(tokens) // BLOCK_SIZE) * BLOCK_SIZE
        keys = reference.token_processor.tokens_to_kv_block_keys(
            None, tokens[:n_full], TEST_MODEL_NAME
        )
        for i, pod in enumerate(("pod-0", "pod-1", "pod-2")):
            depth = len(keys) - i  # distinct per-pod scores
            engine_keys = [
                Key(TEST_MODEL_NAME, 50_000 + 100 * i + j)
                for j in range(depth)
            ]
            entry = [PodEntry(pod, "hbm")]
            reference.kv_block_index.add(
                engine_keys, keys[:depth], entry
            )
            owner = part.replica_for(pod)
            replicas[owner].kv_block_index.add(
                engine_keys, keys[:depth], entry
            )
        servers = []
        targets = []
        for replica in replicas:
            port = _free_port()
            servers.append(serve_grpc(replica, f"127.0.0.1:{port}"))
            targets.append(f"127.0.0.1:{port}")
        scorer = ClusterScorer(
            [GrpcReplicaTransport(t, timeout_s=5.0) for t in targets],
            config=ClusterConfig(num_replicas=2, scatter_timeout_s=5.0),
        )
        try:
            ref = reference.get_pod_scores_ex(prompt, TEST_MODEL_NAME, [])
            merged = scorer.get_pod_scores_ex(prompt, TEST_MODEL_NAME, [])
            assert ref.scores  # non-vacuous
            assert merged.scores == ref.scores
            assert merged.match_blocks == ref.match_blocks
            assert merged.block_hashes == ref.block_hashes
        finally:
            scorer.close()
            for server in servers:
                server.stop(grace=0)
            tok_pool.shutdown()

    def test_cluster_status_over_grpc(self):
        from llm_d_kv_cache_manager_tpu.api.grpc_server import (
            IndexerGrpcClient,
            serve_grpc,
        )

        tok_pool = _shared_tokenization_pool()
        indexer = _make_indexer(tok_pool)
        port = _free_port()
        server = serve_grpc(
            indexer, f"127.0.0.1:{port}",
            cluster_status_fn=lambda: {"replicas": {"replica-0": {"state": "healthy"}}},
        )
        try:
            client = IndexerGrpcClient(f"127.0.0.1:{port}")
            doc = client.cluster_status()
            assert doc["replicas"]["replica-0"]["state"] == "healthy"
            client.close()
        finally:
            server.stop(grace=0)
            tok_pool.shutdown()

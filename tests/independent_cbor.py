"""Independent canonical CBOR codec, written directly from RFC 8949.

This module intentionally shares NO code or structure with
`llm_d_kv_cache_manager_tpu.kvcache.kvblock.hashing`: that module builds the
hash payload with a specialised single-pass byte emitter, while this one is a
general-purpose recursive encoder/strict decoder over arbitrary Python values.
The two are developed against the spec independently so that
`tests/test_hash_parity.py` can fuzz them against each other byte-for-byte —
the in-repo substitute for the reference's cross-implementation parity test
(/root/reference/tests/integration/prompt_to_block_test.go:58-99), which
compares Go hashing output against engine-captured vectors.

Canonical form per RFC 8949 §4.2.1: shortest-form argument encodings,
definite lengths only, map keys sorted bytewise on their encoded form.
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple


class NonCanonicalError(ValueError):
    """Raised by the strict decoder on any non-canonical encoding."""


# ---------------------------------------------------------------------------
# Encoder
# ---------------------------------------------------------------------------

def _head(major: int, argument: int) -> bytes:
    """Encode a major type + argument in shortest form (RFC 8949 §4.2.1)."""
    if argument < 0:
        raise ValueError("CBOR head argument must be non-negative")
    if argument <= 23:
        return struct.pack(">B", (major << 5) | argument)
    for info, fmt, limit in ((24, ">BB", 1 << 8), (25, ">BH", 1 << 16),
                             (26, ">BI", 1 << 32), (27, ">BQ", 1 << 64)):
        if argument < limit:
            return struct.pack(fmt, (major << 5) | info, argument)
    raise ValueError("CBOR argument exceeds 64 bits")


def encode(value: Any) -> bytes:
    """Canonical (deterministic) CBOR encoding of a Python value."""
    if value is None:
        return b"\xf6"
    if value is True:
        return b"\xf5"
    if value is False:
        return b"\xf4"
    if isinstance(value, int):
        if value >= 0:
            return _head(0, value)
        return _head(1, -1 - value)
    if isinstance(value, bytes):
        return _head(2, len(value)) + value
    if isinstance(value, str):
        raw = value.encode("utf-8")
        return _head(3, len(raw)) + raw
    if isinstance(value, (list, tuple)):
        return _head(4, len(value)) + b"".join(encode(item) for item in value)
    if isinstance(value, dict):
        pairs = sorted(
            (encode(k), encode(v)) for k, v in value.items()
        )
        return _head(5, len(pairs)) + b"".join(k + v for k, v in pairs)
    raise TypeError(f"unsupported CBOR type: {type(value)!r}")


# ---------------------------------------------------------------------------
# Strict decoder — rejects every non-canonical form it can detect
# ---------------------------------------------------------------------------

def _read_head(data: bytes, pos: int) -> Tuple[int, int, int]:
    """Return (major, argument, next_pos); enforce shortest-form arguments."""
    if pos >= len(data):
        raise NonCanonicalError("truncated CBOR: missing head byte")
    initial = data[pos]
    major, info = initial >> 5, initial & 0x1F
    pos += 1
    if info <= 23:
        return major, info, pos
    if info > 27:
        raise NonCanonicalError(
            f"indefinite-length / reserved additional info {info} is not canonical"
        )
    width = 1 << (info - 24)
    if pos + width > len(data):
        raise NonCanonicalError("truncated CBOR: short argument")
    argument = int.from_bytes(data[pos:pos + width], "big")
    pos += width
    # Shortest-form check: the argument must not have fit a smaller width.
    floor = 24 if width == 1 else 1 << (8 * (width >> 1))
    if argument < floor:
        raise NonCanonicalError(
            f"non-shortest-form argument {argument} encoded in {width} byte(s)"
        )
    return major, argument, pos


def _decode_item(data: bytes, pos: int, depth: int = 0) -> Tuple[Any, int]:
    if depth > 64:
        raise NonCanonicalError("nesting too deep")
    major, argument, pos = _read_head(data, pos)
    if major == 0:
        return argument, pos
    if major == 1:
        return -1 - argument, pos
    if major == 2:
        if pos + argument > len(data):
            raise NonCanonicalError("truncated byte string")
        return data[pos:pos + argument], pos + argument
    if major == 3:
        if pos + argument > len(data):
            raise NonCanonicalError("truncated text string")
        try:
            text = data[pos:pos + argument].decode("utf-8")
        except UnicodeDecodeError as e:
            raise NonCanonicalError(f"invalid UTF-8 in text string: {e}") from e
        return text, pos + argument
    if major == 4:
        items: List[Any] = []
        for _ in range(argument):
            item, pos = _decode_item(data, pos, depth + 1)
            items.append(item)
        return items, pos
    if major == 5:
        result = {}
        prev_key_bytes = None
        for _ in range(argument):
            key_start = pos
            key, pos = _decode_item(data, pos, depth + 1)
            key_bytes = data[key_start:pos]
            if prev_key_bytes is not None and key_bytes <= prev_key_bytes:
                raise NonCanonicalError("map keys not in canonical order")
            prev_key_bytes = key_bytes
            value, pos = _decode_item(data, pos, depth + 1)
            result[key] = value
        return result, pos
    if major == 7:
        simple = {20: False, 21: True, 22: None}
        if argument in simple:
            return simple[argument], pos
        raise NonCanonicalError(f"unsupported simple/float value {argument}")
    raise NonCanonicalError(f"unsupported major type {major}")


def decode(data: bytes) -> Any:
    """Strict canonical decode; raises NonCanonicalError on any deviation."""
    value, pos = _decode_item(data, 0)
    if pos != len(data):
        raise NonCanonicalError(f"{len(data) - pos} trailing byte(s) after item")
    return value

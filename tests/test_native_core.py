"""Native scoring core (native/kvscore.c + kvcache/kvblock/native_index.py).

The tentpole claim is bit-identity: the C arena's fused crossing (lookup +
longest-prefix score + fleet-health/anti-entropy/routing adjustments) and
its lock-free event digestion must be indistinguishable — score for score,
state for state — from the pure-Python pipeline they replace. These tests
pin that claim directly:

- the Index contract (add/evict/lookup/get_request_key/remove_*/export/
  import) against ShardedIndex on identical op sequences, exact error
  messages included,
- score_plan parity vs the full Python pipeline across LoRA keyspaces,
  fleet-health states (deferred-refresh semantics), anti-entropy accuracy
  demotions, and routing-policy load demotion — including the post-call
  tracker state machines,
- event-digest parity through EventPool's seam with adversarial wire
  shapes (oversized ints, bytes, bools, bad LoRA ids, removal churn),
- every fallback seam (non-native backend, custom scorer, crossing
  error, non-native hash algo) lands on the Python path with the
  fallback counter telling the story,
- concurrent digest-while-scoring: readers on the seqlock'd path while a
  writer mutates, then final-state equality with a sequential replay
  (this is the test `make native-tsan` runs under ThreadSanitizer),
- the /readyz `native_core` section and /score_explain surface.

Most tests skip with a visible reason until `make native` has run; the
fallback-seam tests for the NON-native paths run regardless.
"""

import asyncio
import random
import threading

import pytest

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from llm_d_kv_cache_manager_tpu.antientropy.tracker import (
    AntiEntropyConfig,
    AntiEntropyTracker,
)
from llm_d_kv_cache_manager_tpu.fleethealth.tracker import (
    FleetHealthConfig,
    FleetHealthTracker,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
    ScoreRequest,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    new_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.key import Key, PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.native_index import (
    NativeIndexConfig,
    NativeScoringIndex,
    fallback_total,
    have_native_index,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.sharded import (
    ShardedIndex,
    ShardedIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.routing import (
    LOAD_BLEND,
    RoutingPolicy,
    RoutingPolicyConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.scorer import LongestPrefixScorer
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import EventPool, EventPoolConfig
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)

needs_native = pytest.mark.skipif(
    not have_native_index(),
    reason="native scoring core (_kvtpu_kvscore) not built — run `make native`",
)

MODEL = "native-core-model"
PODS = [f"pod-{i}" for i in range(6)] + ["pod-2@dp1"]
TIERS = ["hbm", "host"]
WEIGHTS = {"hbm": 1.0, "host": 0.8}


def _pair(size=10_000):
    return (
        NativeScoringIndex(NativeIndexConfig(size=size, pod_cache_size=4)),
        ShardedIndex(ShardedIndexConfig(size=size, pod_cache_size=4)),
    )


def _populate(rng, indexes, n_chains=10, models=(MODEL,)):
    chains = {m: [] for m in models}
    for model in models:
        for _ in range(n_chains):
            chain = [rng.getrandbits(64) for _ in range(rng.randint(1, 8))]
            chains[model].append(chain)
            for h in chain:
                req = [Key(model, h)]
                eng = [Key(model, h ^ 0xABCDEF)]
                ents = [
                    PodEntry(rng.choice(PODS), rng.choice(TIERS))
                    for _ in range(rng.randint(1, 4))
                ]
                for ix in indexes:
                    ix.add(eng, req, ents)
    return chains


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class _Load:
    """Deterministic per-pod load for the routing-policy legs."""

    def __init__(self):
        self.loads = {}

    def load_of(self, pod, now=None):
        class L:
            pass

        load = L()
        load.queue_depth, load.busy_s, load.preemption_rate = self.loads.get(
            pod, (0, 0.0, 0.0)
        )
        return load


@needs_native
class TestIndexContract:
    def test_lookup_evict_request_key_parity(self):
        rng = random.Random(11)
        nat, sha = _pair()
        chains = _populate(rng, (nat, sha), models=(MODEL, "other/model"))
        for model, model_chains in chains.items():
            for chain in model_chains[:3]:
                ek = Key(model, chain[0] ^ 0xABCDEF)
                ents = [PodEntry("pod-1", "hbm")]
                nat.evict(ek, ents)
                sha.evict(ek, ents)
        assert nat.remove_pod("pod-3") == sha.remove_pod("pod-3")
        rk = [Key(MODEL, c[0]) for c in chains[MODEL][:4]]
        assert nat.remove_entries("pod-2", rk) == sha.remove_entries(
            "pod-2", rk
        )
        for model, model_chains in chains.items():
            for chain in model_chains:
                keys = [Key(model, h) for h in chain]
                for pods in (set(), {"pod-0", "pod-2"}, {"nope"}):
                    a = nat.lookup(keys, pods)
                    b = sha.lookup(keys, pods)
                    assert {k: list(v) for k, v in a.items()} == {
                        k: list(v) for k, v in b.items()
                    }, (model, pods)
                ek = Key(model, chain[0] ^ 0xABCDEF)
                assert nat.get_request_key(ek) == sha.get_request_key(ek)
        assert nat.get_request_key(Key("m/none", 1)) is None

    def test_validation_errors_match_sharded(self):
        nat, sha = _pair()
        key = [Key(MODEL, 1)]
        ents = [PodEntry("p", "hbm")]
        for args in (
            ("lookup", ([], set())),
            ("add", ([], [], ents)),
            ("add", (key, [], ents)),
            ("add", (key + key, key, ents)),
            ("evict", (Key(MODEL, 1), [])),
        ):
            name, call = args
            with pytest.raises(ValueError) as nat_err:
                getattr(nat, name)(*call)
            with pytest.raises(ValueError) as sha_err:
                getattr(sha, name)(*call)
            assert str(nat_err.value) == str(sha_err.value), name

    def test_export_import_round_trip(self):
        rng = random.Random(5)
        nat, _ = _pair()
        chains = _populate(rng, (nat,))
        view = nat.export_view()
        fresh = NativeScoringIndex(NativeIndexConfig(size=10_000))
        assert fresh.import_view(view) == view.entry_count()
        for chain in chains[MODEL]:
            keys = [Key(MODEL, h) for h in chain]
            assert nat.lookup(keys, set()) == fresh.lookup(keys, set())
            ek = Key(MODEL, chain[0] ^ 0xABCDEF)
            assert nat.get_request_key(ek) == fresh.get_request_key(ek)

    def test_config_knob_selects_native_backend(self):
        config = IndexConfig.default()
        config.native = True
        assert isinstance(new_index(config), NativeScoringIndex)
        # Off by default: the knob is opt-in.
        assert not isinstance(new_index(IndexConfig.default()),
                              NativeScoringIndex)


@needs_native
class TestScorePlanParity:
    def _python_pipeline(self, specs, scorer, index, fh, ae, rp):
        plan = []
        for spec in specs:
            if spec["ref"] is None:
                hits = index.lookup(spec["keys"], set(spec["pods"]))
                plan.append(
                    ("solo", spec["keys"], hits, spec.get("forked", False))
                )
            else:
                hits = (
                    index.lookup(spec["tail"], set(spec["pods"]))
                    if spec["tail"] else {}
                )
                plan.append(
                    ("fork", spec["ref"], spec["shared"], spec["tail"], hits)
                )
        out = []
        for scores, match in scorer.score_plan(plan):
            if fh is not None:
                scores = fh.filter_scores(scores)
            if ae is not None:
                scores = ae.adjust_scores(scores)
            if rp is not None:
                scores = rp.adjust(scores)
            out.append((scores, match))
        return out

    def test_scores_match_python_across_tracker_states(self):
        """Randomized solo+fork plans vs the Python pipeline under every
        tracker combination: fleet-health aging (suspect demotion +
        deferred refresh), anti-entropy accuracy factors, LOAD_BLEND
        routing divisors. Scores, match blocks, routing stats, and the
        post-call health state machines must all agree."""
        rng = random.Random(7)
        scorer = LongestPrefixScorer(WEIGHTS)
        nat, sha = _pair()
        chains = _populate(rng, (nat, sha), n_chains=12)[MODEL]
        for trial in range(25):
            clock = _Clock()
            use_fh = trial % 2 == 0
            use_ae = trial % 3 == 0
            use_rp = trial % 4 == 0
            fhs, aes, rps = [], [], []
            load = _Load()
            for p in PODS:
                load.loads[p] = (
                    rng.randint(0, 8), rng.random(), rng.random() * 4,
                )
            for _ in range(2):  # independent instances per side
                fhs.append(
                    FleetHealthTracker(
                        FleetHealthConfig(
                            suspect_after_s=10, stale_after_s=30,
                            suspect_demotion_factor=0.5,
                            auto_quarantine=False,
                        ),
                        clock=clock,
                    ) if use_fh else None
                )
                aes.append(
                    AntiEntropyTracker(AntiEntropyConfig(), clock=clock)
                    if use_ae else None
                )
                rps.append(
                    RoutingPolicy(
                        RoutingPolicyConfig(
                            policy=LOAD_BLEND, load_weight=0.7
                        ),
                        load_tracker=load,
                    ) if use_rp else None
                )
            for fh in fhs:
                if fh is None:
                    continue
                for p in PODS:
                    fh.observe_batch(p, "t", None, clock.t)
            clock.t += 15  # everyone ages to suspect…
            for fh in fhs:
                if fh is None:
                    continue
                fh.observe_batch("pod-0", "t", None, clock.t)  # …except one
            for ae in aes:
                if ae is None:
                    continue
                ae.observe_fetch_miss("pod-1", blocks=5)
                ae.observe_audit("pod-4", verified=1, phantom=9)

            base = rng.choice(chains)
            keys = [Key(MODEL, h) for h in base]
            pods_t = rng.choice(
                [(), tuple(sorted(rng.sample(PODS, 3)))]
            )
            shared = rng.randint(1, len(keys))
            tail = [Key(MODEL, h) for h in rng.choice(chains)][
                : rng.randint(0, 3)
            ]
            specs = [
                {"item": 0, "keys": keys, "ref": None, "pods": pods_t,
                 "forked": True},
                {"item": 1, "keys": keys[:shared] + tail, "ref": 0,
                 "shared": shared, "tail": tail, "pods": pods_t},
                {"item": 2, "keys": [Key(MODEL, h) for h in
                                     rng.choice(chains)],
                 "ref": None, "pods": ()},
            ]
            nat_out = nat.score_plan(
                specs, WEIGHTS, fleet_health=fhs[0], antientropy=aes[0],
                routing_policy=rps[0],
            )
            py_out = self._python_pipeline(
                specs, scorer, sha, fhs[1], aes[1], rps[1]
            )
            for i, (a, b) in enumerate(zip(nat_out, py_out)):
                assert a[0] == b[0], (trial, i, a[0], b[0])
                assert a[1] == b[1], (trial, i)
            if use_rp:
                assert rps[0].stats == rps[1].stats, trial
            if use_fh:
                for p in PODS:
                    assert fhs[0].state_of(p) == fhs[1].state_of(p), (
                        trial, p,
                    )


@needs_native
class TestDigestParity:
    def test_event_stream_reaches_identical_state(self):
        """Adversarial event stream (oversized ints, raw bytes, bools,
        empty hashes, garbage LoRA ids, parent chaining, mixed mediums,
        removals, clears) through EventPool's digest seam: the arena and
        the Python ShardedIndex must hold the same logical state."""
        rng = random.Random(99)
        bs = 16
        pools, indexes = [], []
        for native in (True, False):
            tp = ChunkedTokenDatabase(
                TokenProcessorConfig(block_size=bs, chain_memo=False)
            )
            index = (
                NativeScoringIndex(NativeIndexConfig(size=50_000))
                if native else ShardedIndex(ShardedIndexConfig(size=50_000))
            )
            pools.append(EventPool(EventPoolConfig(), index, tp))
            indexes.append(index)

        def rand_hash():
            choice = rng.randint(0, 9)
            if choice < 6:
                return rng.getrandbits(64)
            if choice == 6:
                return rng.getrandbits(96)  # masked to 64 bits
            if choice == 7:
                return rng.getrandbits(64).to_bytes(8, "big")
            if choice == 8:
                return True  # bool -> skipped
            return b""  # empty -> skipped

        stored = []
        for i in range(200):
            pod = rng.choice(PODS[:5])
            kind = rng.randint(0, 5)
            if kind <= 3:
                n_blocks = rng.randint(1, 4)
                toks = [
                    rng.randint(0, 50000)
                    for _ in range(n_blocks * bs + rng.randint(0, bs - 1))
                ]
                hashes = [rand_hash() for _ in range(n_blocks)]
                parent = (
                    rng.choice(rng.choice(stored))
                    if stored and rng.random() < 0.5 else None
                )
                ev = BlockStored(
                    block_hashes=hashes, parent_block_hash=parent,
                    token_ids=toks, block_size=bs,
                    lora_id=rng.choice([None, 0, 3, -1, True, "bad"]),
                    medium=rng.choice([None, "hbm", "HOST"]),
                )
                good = [
                    h for h in hashes
                    if not isinstance(h, bool) and h != b""
                ]
                if good:
                    stored.append(good)
            elif kind == 4 and stored:
                ev = BlockRemoved(
                    block_hashes=list(rng.choice(stored)),
                    medium=rng.choice([None, "hbm"]),
                )
            else:
                ev = AllBlocksCleared()
            batch = EventBatch(ts=1.0, events=[ev])
            for pool in pools:
                pool._digest_events(pod, MODEL, batch)  # noqa: SLF001

        views = [ix.export_view() for ix in indexes]
        # Same keys, same per-key pod tuples (the per-key LRU order the
        # scorer folds), same engine mappings. Global view ORDER may
        # differ: the arena keeps one LRU, the sharded index one per
        # segment — cross-backend restore parity is pinned elsewhere.
        state = [
            {(e[0], e[1]): e[2] for e in v.entries} for v in views
        ]
        assert state[0] == state[1]
        assert {
            (r[0], r[1]): (r[2], r[3]) for r in views[0].engine_map
        } == {
            (r[0], r[1]): (r[2], r[3]) for r in views[1].engine_map
        }
        stats = indexes[0].native_status()
        assert stats["blocks_applied"] > 0
        assert stats["keys"] == len(state[0])


@needs_native
class TestConcurrentDigestWhileScoring:
    def test_readers_race_writer_then_state_matches_replay(self):
        """Reader threads hammer score_plan/lookup on the seqlock'd read
        path while one writer digests event batches into the same arena.
        No crash, no exception, and the final arena state equals a fresh
        arena given the same batches sequentially (single-writer digest is
        deterministic; readers must not perturb it)."""
        bs = 16
        rng = random.Random(3)
        tp = ChunkedTokenDatabase(
            TokenProcessorConfig(block_size=bs, chain_memo=False)
        )
        nat = NativeScoringIndex(NativeIndexConfig(size=100_000))
        pool = EventPool(EventPoolConfig(), nat, tp)
        toks = [rng.randint(0, 50000) for _ in range(bs * 4)]
        batches = []
        for i in range(400):
            hashes = [i * 4 + j + 1 for j in range(4)]
            events = [BlockStored(
                block_hashes=hashes, parent_block_hash=None,
                token_ids=toks, block_size=bs,
            )]
            if i % 5 == 4:
                events.append(BlockRemoved(block_hashes=hashes[:2]))
            batches.append(EventBatch(ts=float(i), events=events))

        errors = []
        stop = threading.Event()

        def reader(seed):
            r = random.Random(seed)
            try:
                while not stop.is_set():
                    view = nat.export_view()
                    if view.entries:
                        row = r.choice(view.entries)
                        key = Key(row[0], row[1])
                        specs = [{
                            "item": 0, "keys": [key], "ref": None,
                            "pods": (),
                        }]
                        out = nat.score_plan(specs, WEIGHTS)
                        assert len(out) == 1
                        nat.lookup([key], set())
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        readers = [
            threading.Thread(target=reader, args=(s,)) for s in range(4)
        ]
        for t in readers:
            t.start()
        for i, b in enumerate(batches):
            pool._digest_events(f"pod-{i % 4}", MODEL, b)  # noqa: SLF001
        stop.set()
        for t in readers:
            t.join(timeout=30)
        assert not errors, errors

        replay = NativeScoringIndex(NativeIndexConfig(size=100_000))
        replay_pool = EventPool(EventPoolConfig(), replay, tp)
        for i, b in enumerate(batches):
            replay_pool._digest_events(  # noqa: SLF001
                f"pod-{i % 4}", MODEL, b
            )
        got = {(e[0], e[1]): e[2] for e in nat.export_view().entries}
        want = {(e[0], e[1]): e[2] for e in replay.export_view().entries}
        assert got == want
        # The seqlock's contended-retry escape hatch is observable: the
        # stat exists and never goes negative (usually 0; a locked lookup
        # is correctness fallback, not failure).
        assert nat.native_status()["locked_lookups"] >= 0


class TestFallbackSeams:
    def test_non_native_backend_is_not_a_fallback(self):
        """An ordinary Python backend takes the ordinary path: no native
        attempt, no fallback counted."""
        indexer = _make_indexer(ShardedIndex())
        try:
            before = fallback_total()
            reqs = [ScoreRequest(prompt="a b c", model_name=TEST_MODEL_NAME)]
            indexer.score_many(reqs)
            assert fallback_total() == before
        finally:
            indexer.shutdown()

    @needs_native
    def test_crossing_error_falls_back_and_counts(self, monkeypatch):
        """A native-crossing failure degrades to the Python path — same
        scores as a healthy Python backend — and increments the counter."""
        rng = random.Random(13)
        nat = NativeScoringIndex(NativeIndexConfig(size=4096))
        indexer = _make_indexer(nat)
        try:
            prompt = "the quick brown fox jumps over the lazy dog " * 4
            _seed(indexer, prompt, "pod-x")
            healthy = indexer.score_many(
                [ScoreRequest(prompt=prompt, model_name=TEST_MODEL_NAME)]
            )
            monkeypatch.setattr(
                nat, "score_plan",
                lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
            )
            before = fallback_total()
            broken = indexer.score_many(
                [ScoreRequest(prompt=prompt, model_name=TEST_MODEL_NAME)]
            )
            assert fallback_total() == before + 1
            assert broken[0].scores == healthy[0].scores
            assert broken[0].match_blocks == healthy[0].match_blocks
        finally:
            indexer.shutdown()
        del rng

    @needs_native
    def test_non_native_hash_algo_digests_in_python(self):
        """The digest seam only engages for fnv64_cbor chains (the hash
        the C core reimplements); any other algo takes the Python loop and
        still lands the blocks."""
        tp = ChunkedTokenDatabase(
            TokenProcessorConfig(
                block_size=4, chain_memo=False,
                hash_algo="sha256_cbor_64bit", hash_seed="42",
            )
        )
        nat = NativeScoringIndex(NativeIndexConfig(size=4096))
        pool = EventPool(EventPoolConfig(), nat, tp)
        batch = EventBatch(ts=1.0, events=[BlockStored(
            block_hashes=[1, 2], parent_block_hash=None,
            token_ids=list(range(8)), block_size=4,
        )])
        pool._digest_events("pod-0", MODEL, batch)  # noqa: SLF001
        assert nat.native_status()["blocks_applied"] == 0  # Python loop
        assert nat.stats()["keys"] == 2  # …but the blocks landed

    @needs_native
    def test_fallback_counter_reaches_prometheus(self):
        from llm_d_kv_cache_manager_tpu.kvcache.kvblock import native_index
        from llm_d_kv_cache_manager_tpu.metrics import collector as metrics

        metrics.register_metrics()
        native_index.count_fallback()
        assert metrics.native_fallbacks is not None
        assert metrics.native_fallbacks._value.get() > 0  # noqa: SLF001


def _make_indexer(kv_block_index, fleet_health=None):
    indexer = Indexer(
        config=IndexerConfig(
            token_processor_config=TokenProcessorConfig(block_size=4),
        ),
        tokenization_pool=TokenizationPool(
            TokenizersPoolConfig(
                workers=2,
                local_tokenizer_files={TEST_MODEL_NAME: TEST_TOKENIZER_JSON},
            ),
        ),
        kv_block_index=kv_block_index,
        fleet_health=fleet_health,
    )
    indexer.run()
    return indexer


def _seed(indexer, prompt, pod):
    enc = indexer.tokenizers_pool.tokenizer.encode(prompt, TEST_MODEL_NAME)
    keys = indexer.token_processor.tokens_to_kv_block_keys(
        None, enc.tokens, TEST_MODEL_NAME
    )
    engine_keys = [Key(TEST_MODEL_NAME, 50_000 + i) for i in range(len(keys))]
    indexer.kv_block_index.add(engine_keys, keys, [PodEntry(pod, "hbm")])
    return len(keys)


class TestHttpSurfaces:
    def _service(self, kv_block_index):
        from llm_d_kv_cache_manager_tpu.api.http_service import ScoringService

        env = {
            "zmq_endpoint": "tcp://*:0",
            "zmq_topic": "kv@",
            "pool_concurrency": 1,
            "hash_seed": "",
            "block_size": 4,
            "http_port": 0,
            "enable_metrics": False,
        }
        return ScoringService(env, indexer=_make_indexer(kv_block_index))

    @needs_native
    def test_readyz_native_core_section_enabled(self):
        from aiohttp.test_utils import TestClient, TestServer

        service = self._service(
            NativeScoringIndex(NativeIndexConfig(size=4096))
        )
        prompt = "a quick native readiness probe " * 3
        _seed(service.indexer, prompt, "pod-n")

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                assert resp.status == 200
                section = (await resp.json())["native_core"]
                assert section["enabled"] is True
                assert section["keys"] > 0
                assert section["fallbacks"] >= 0
                assert "blocks_applied" in section

                resp = await client.get(
                    "/debug/score_explain",
                    params={"prompt": prompt, "model": TEST_MODEL_NAME},
                )
                assert resp.status == 200
                explain = await resp.json()
                assert explain["native_core"]["enabled"] is True

        try:
            asyncio.run(run())
        finally:
            service.stop()
            service.indexer.shutdown()

    def test_readyz_native_core_section_disabled(self):
        from aiohttp.test_utils import TestClient, TestServer

        service = self._service(ShardedIndex())

        async def run():
            async with TestClient(TestServer(service.make_app())) as client:
                service.start(with_subscriber=False)
                resp = await client.get("/readyz")
                section = (await resp.json())["native_core"]
                assert section["enabled"] is False
                assert section["module_available"] == have_native_index()
                assert section["fallbacks"] >= 0

        try:
            asyncio.run(run())
        finally:
            service.stop()
            service.indexer.shutdown()

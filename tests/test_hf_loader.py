"""HF checkpoint bridge: model-math parity against transformers itself.

`LlamaForCausalLM.forward` is the canonical Llama implementation; loading
its weights through models/hf_loader.py and matching its logits pins our
decoder's math (RMSNorm, rotate-half RoPE, GQA, SwiGLU, lm_head) against a
genuinely third-party reference — no shared code, no shared author. A tiny
randomly-initialized HF model keeps the test offline (no downloads).
"""

import importlib.util

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Model-math tests compile real models (VERDICT r5 weak #6): excluded
# from the tier-1 `-m 'not slow'` gate to keep its wall time bounded.
pytestmark = pytest.mark.slow


if importlib.util.find_spec("torch") is None or (
    importlib.util.find_spec("transformers") is None
):
    pytest.skip("torch/transformers not installed", allow_module_level=True)

import torch
from transformers import LlamaConfig as HFLlamaConfig
from transformers import LlamaForCausalLM

from llm_d_kv_cache_manager_tpu.engine.engine import EnginePod, EnginePodConfig
from llm_d_kv_cache_manager_tpu.engine.scheduler import Scheduler
from llm_d_kv_cache_manager_tpu.models import llama
from llm_d_kv_cache_manager_tpu.models.hf_loader import (
    config_from_hf,
    params_from_hf,
)


def _tiny_hf_model(tie=False, n_q=4, n_kv=2):
    hf_cfg = HFLlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=n_q,
        num_key_value_heads=n_kv, max_position_embeddings=256,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=tie,
        attention_bias=False, mlp_bias=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    return hf_cfg, model


class TestLogitsParity:
    @pytest.mark.parametrize("tie", [False, True])
    def test_forward_matches_transformers(self, tie):
        hf_cfg, model = _tiny_hf_model(tie=tie)
        config = config_from_hf(hf_cfg, dtype=jnp.float32)
        params = params_from_hf(model, config)

        tokens = np.array([[3, 17, 99, 4, 250, 7, 7, 42, 120, 5]], np.int64)
        with torch.no_grad():
            hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
        ours = np.asarray(
            llama.forward_dense(config, params, jnp.asarray(tokens, jnp.int32))
        )
        np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)

    def test_gqa_grouping_matches(self):
        # 8q/2kv stresses the grouped-query head mapping.
        hf_cfg, model = _tiny_hf_model(n_q=8, n_kv=2)
        config = config_from_hf(hf_cfg, dtype=jnp.float32)
        params = params_from_hf(model, config)
        tokens = np.arange(12, dtype=np.int64)[None] % 256
        with torch.no_grad():
            hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
        ours = np.asarray(
            llama.forward_dense(config, params, jnp.asarray(tokens, jnp.int32))
        )
        np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


class TestServingWithHFWeights:
    def test_paged_generation_matches_hf_greedy(self):
        """The full serving stack (paged cache, scheduler, batched decode)
        on HF weights must emit transformers' own greedy continuation."""
        hf_cfg, model = _tiny_hf_model()
        config = config_from_hf(hf_cfg, dtype=jnp.float32)
        params = params_from_hf(model, config)

        prompt = [3, 17, 99, 4, 250, 7]
        n_new = 8
        ids = torch.tensor([prompt])
        with torch.no_grad():
            hf_out = model.generate(
                ids, max_new_tokens=n_new, do_sample=False,
                pad_token_id=0,
            )[0, len(prompt):].tolist()

        pod = EnginePod(
            EnginePodConfig(
                n_pages=32, page_size=4, with_model=True, model_config=config,
                max_pages_per_seq=16,
            ),
            params=params,
        )
        sched = Scheduler(pod, max_batch=2)
        rid = sched.submit(prompt, max_new_tokens=n_new)
        assert sched.run()[rid] == hf_out


class TestMixtralParity:
    """MoE math against transformers' MixtralForCausalLM: router gating
    (softmax/top-k order equivalence), per-expert SwiGLU, and the combine
    — plus full-stack paged generation on HF Mixtral weights."""

    def _tiny_hf_mixtral(self):
        from transformers import MixtralConfig as HFMixtralConfig
        from transformers import MixtralForCausalLM

        hf_cfg = HFMixtralConfig(
            vocab_size=256, hidden_size=64, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, num_local_experts=4,
            num_experts_per_tok=2, max_position_embeddings=256,
            rope_theta=10000.0, rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
        torch.manual_seed(1)
        return hf_cfg, MixtralForCausalLM(hf_cfg).eval()

    def test_forward_matches_transformers(self):
        from llm_d_kv_cache_manager_tpu.models import mixtral
        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            mixtral_config_from_hf,
            mixtral_params_from_hf,
        )

        hf_cfg, model = self._tiny_hf_mixtral()
        config = mixtral_config_from_hf(hf_cfg, dtype=jnp.float32)
        params = mixtral_params_from_hf(model, config)
        tokens = np.array([[3, 17, 99, 4, 250, 7, 42, 120]], np.int64)
        with torch.no_grad():
            hf_logits = model(torch.from_numpy(tokens)).logits.numpy()
        ours = np.asarray(
            mixtral.forward_dense(config, params, jnp.asarray(tokens, jnp.int32))
        )
        np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)

    def test_paged_generation_matches_hf_greedy(self):
        from llm_d_kv_cache_manager_tpu.models.hf_loader import (
            mixtral_config_from_hf,
            mixtral_params_from_hf,
        )

        hf_cfg, model = self._tiny_hf_mixtral()
        config = mixtral_config_from_hf(hf_cfg, dtype=jnp.float32)
        params = mixtral_params_from_hf(model, config)
        prompt = [3, 17, 99, 4, 250, 7]
        n_new = 6
        with torch.no_grad():
            hf_out = model.generate(
                torch.tensor([prompt]), max_new_tokens=n_new,
                do_sample=False, pad_token_id=0,
            )[0, len(prompt):].tolist()
        pod = EnginePod(
            EnginePodConfig(
                n_pages=32, page_size=4, with_model=True, model_config=config,
                max_pages_per_seq=16,
            ),
            params=params,
        )
        sched = Scheduler(pod, max_batch=2, decode_steps=2)
        rid = sched.submit(prompt, max_new_tokens=n_new)
        assert sched.run()[rid] == hf_out

"""Tokenizer stack + tokenization pool tests.

Mirrors /root/reference/pkg/tokenization/tokenizer_test.go (local encode,
discovery layouts, composite fallback) and pool_test.go (prefix-store
shortcut, sync/async modes) using the generated tests/fixtures tokenizer.
"""

import os
import threading

import pytest

from tests.conftest import TEST_MODEL_NAME, TEST_TOKENIZER_JSON
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizersPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (
    LRUStoreConfig,
    LRUTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizer import (
    CachedLocalTokenizer,
    CompositeTokenizer,
    TokenizationResult,
    Tokenizer,
    discover_local_tokenizers,
)


class TestCachedLocalTokenizer:
    def test_encode_with_byte_offsets(self, test_tokenizer_files):
        tok = CachedLocalTokenizer(tokenizer_files=test_tokenizer_files)
        result = tok.encode("The quick brown fox", TEST_MODEL_NAME)
        assert result.tokens
        assert len(result.tokens) == len(result.offsets)
        assert result.offsets[-1][1] == len("The quick brown fox".encode("utf-8"))

    def test_unicode_byte_offsets(self, test_tokenizer_files):
        tok = CachedLocalTokenizer(tokenizer_files=test_tokenizer_files)
        prompt = "héllo wörld"
        result = tok.encode(prompt, TEST_MODEL_NAME)
        assert result.offsets[-1][1] == len(prompt.encode("utf-8"))

    def test_unknown_model_raises(self, test_tokenizer_files):
        tok = CachedLocalTokenizer(tokenizer_files=test_tokenizer_files)
        with pytest.raises(Exception):
            tok.encode("hi", "no-such-model")

    def test_tokenizer_instance_cached(self, test_tokenizer_files):
        tok = CachedLocalTokenizer(tokenizer_files=test_tokenizer_files)
        tok.encode("one", TEST_MODEL_NAME)
        first = tok._cache.get(TEST_MODEL_NAME)
        tok.encode("two", TEST_MODEL_NAME)
        assert tok._cache.get(TEST_MODEL_NAME) is first

    def test_concurrent_loads_singleflight(self, test_tokenizer_files):
        tok = CachedLocalTokenizer(tokenizer_files=test_tokenizer_files)
        results, errors = [], []

        def encode():
            try:
                results.append(tok.encode("concurrent load", TEST_MODEL_NAME))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=encode) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert all(r.tokens == results[0].tokens for r in results)


class TestDiscovery:
    def test_hf_cache_layout(self, tmp_path):
        snap = tmp_path / "models--org--name" / "snapshots" / "abc123"
        snap.mkdir(parents=True)
        (snap / "tokenizer.json").write_text("{}")
        found = discover_local_tokenizers(str(tmp_path))
        assert found == {"org/name": str(snap / "tokenizer.json")}

    def test_plain_relative_dir_layout(self, tmp_path):
        d = tmp_path / "my" / "model"
        d.mkdir(parents=True)
        (d / "tokenizer.json").write_text("{}")
        found = discover_local_tokenizers(str(tmp_path))
        assert found == {"my/model": str(d / "tokenizer.json")}

    def test_custom_filename(self, tmp_path):
        d = tmp_path / "model"
        d.mkdir()
        (d / "tok.json").write_text("{}")
        assert discover_local_tokenizers(str(tmp_path), "tok.json") == {
            "model": str(d / "tok.json")
        }

    def test_missing_dir(self):
        assert discover_local_tokenizers("/no/such/dir") == {}


class _FailingTokenizer(Tokenizer):
    def encode(self, prompt, model_name):
        raise RuntimeError("backend down")


class _CountingTokenizer(Tokenizer):
    def __init__(self):
        self.calls = 0

    def encode(self, prompt, model_name):
        self.calls += 1
        b = prompt.encode("utf-8")
        tokens = list(range(0, len(b), 4))
        offsets = [(i, min(i + 4, len(b))) for i in tokens]
        return TokenizationResult(tokens=tokens, offsets=offsets)


class TestCompositeTokenizer:
    def test_fallback_order(self, test_tokenizer_files):
        composite = CompositeTokenizer(
            [_FailingTokenizer(), CachedLocalTokenizer(tokenizer_files=test_tokenizer_files)]
        )
        result = composite.encode("fallback works", TEST_MODEL_NAME)
        assert result.tokens

    def test_all_fail_raises_with_causes(self):
        composite = CompositeTokenizer([_FailingTokenizer(), _FailingTokenizer()])
        with pytest.raises(RuntimeError, match="backend down"):
            composite.encode("hi", "m")


class TestTokenizationPool:
    def _pool(self, tokenizer, block_size=16):
        store = LRUTokenStore(LRUStoreConfig(cache_size=1000, block_size=block_size))
        pool = TokenizationPool(
            TokenizersPoolConfig(workers=2), prefix_store=store, tokenizer=tokenizer
        )
        pool.run()
        return pool

    def test_sync_tokenize(self):
        counting = _CountingTokenizer()
        pool = self._pool(counting)
        try:
            tokens = pool.tokenize(None, "x" * 64, "m")
            assert tokens == list(range(0, 64, 4))
            assert counting.calls == 1
        finally:
            pool.shutdown()

    def test_prefix_store_shortcut_skips_encode(self):
        counting = _CountingTokenizer()
        pool = self._pool(counting, block_size=16)
        try:
            prompt = "y" * 64
            pool.tokenize(None, prompt, "m")
            assert counting.calls == 1
            # Fully covered prompt: second call must come from the store.
            tokens = pool.tokenize(None, prompt, "m")
            assert counting.calls == 1
            assert tokens == list(range(0, 64, 4))
        finally:
            pool.shutdown()

    def test_low_overlap_reencodes(self):
        counting = _CountingTokenizer()
        pool = self._pool(counting, block_size=16)
        try:
            pool.tokenize(None, "a" * 64, "m")
            pool.tokenize(None, "a" * 16 + "b" * 48, "m")  # 25% overlap < 0.8
            assert counting.calls == 2
        finally:
            pool.shutdown()

    def test_enqueue_async_populates_store(self):
        counting = _CountingTokenizer()
        pool = self._pool(counting)
        try:
            pool.enqueue_tokenization(None, "z" * 64, "m")
            pool.drain()
            assert counting.calls == 1
            # Blocking call after async warm: served from store.
            pool.tokenize(None, "z" * 64, "m")
            assert counting.calls == 1
        finally:
            pool.shutdown()

    def test_error_propagates_to_caller(self):
        pool = self._pool(_FailingTokenizer())
        try:
            with pytest.raises(RuntimeError, match="backend down"):
                pool.tokenize(None, "q" * 64, "m")
        finally:
            pool.shutdown()
